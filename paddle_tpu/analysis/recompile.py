"""Recompile-hazard pass: statically enumerate the program set a
serving call site can produce.

``serving_prefill_chunk`` takes ``prefix_pages`` as a STATIC argument
— the gathered-prefix width is a shape — so every distinct value XLA
sees is one more compile, and compiles land *inside the serving tick*
(a multi-second stall per novel prefix length, the compile-storm
failure mode the r8 attach quantum exists to prevent). Whether the
quantum actually bounds the set is a function of pure host-side
geometry: page size, slot budget, prompt buckets, attach quantum and
chunk size. This pass enumerates the reachable set exactly and proves
(or refutes) the ≤``limit``-programs-per-bucket invariant *before* any
traffic runs.

Reachability model (mirrors ``ServingEngine`` dispatch exactly):

* the engine calls the chunk program with width ``tb`` = the prefill
  chunk (when chunking is on) or the suffix bucket (prefix-hit path),
  and ``prefix_pages`` = (attached cached pages) + (chunks already
  written) · (chunk pages);
* attached pages are multiples of ``attach_quantum`` capped by the
  match cap ``floor((n-1)/ps)`` (one suffix token always remains);
* chunk starts are page-aligned multiples of the chunk size past the
  attach point; every start must leave ≥ 1 prompt token.

The compiled-program key is ``(tb, prefix_pages)``; the invariant is
``|{prefix_pages}| ≤ limit`` per width bucket. Prefill/decode program
counts (one per prompt bucket, one decode shape) are reported as INFO
so the CLI shows the whole compile inventory.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set

from .framework import (Finding, GraphTarget, LintPass, Severity,
                        register_pass)

__all__ = ["ServingGeometry", "enumerate_chunk_programs",
           "RecompileHazardPass"]


@dataclass
class ServingGeometry:
    """The host-side facts that determine the serving program set."""
    page_size: int
    pages_per_slot: int
    buckets: List[int]          # prompt-length buckets (sorted)
    attach_quantum: int = 1     # 0/None = prefix cache off
    prefill_chunk: Optional[int] = None

    @staticmethod
    def of_engine(engine) -> "ServingGeometry":
        """Extract the geometry from a live ``ServingEngine``."""
        return ServingGeometry(
            page_size=engine.pool.page_size,
            pages_per_slot=engine.scheduler.pages_per_slot,
            buckets=list(engine._buckets),
            attach_quantum=(engine.prefix_cache.attach_quantum
                            if engine.prefix_cache is not None else 0),
            prefill_chunk=engine._chunk)


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def enumerate_chunk_programs(geom: ServingGeometry) -> Dict[int,
                                                            Set[int]]:
    """Exact reachable ``{chunk_width: {prefix_pages}}`` under the
    engine's dispatch rules. Empty when no code path can ever call the
    chunk program (no cache and no chunking)."""
    ps = geom.page_size
    q = geom.attach_quantum
    chunk = geom.prefill_chunk
    max_prompt = geom.buckets[-1]
    out: Dict[int, Set[int]] = {}
    if not q and chunk is None:
        return out

    def add(width: int, pp: int) -> None:
        out.setdefault(int(width), set()).add(int(pp))

    c_pages = chunk // ps if chunk is not None else None
    for n in range(1, max_prompt + 1):
        cap = (n - 1) // ps                      # match cap: >=1 suffix tok
        attaches = [0]
        if q:
            attaches = list(range(0, (cap // q) * q + 1, q))
        for a in attaches:
            suffix = n - a * ps
            if chunk is None:
                if a == 0:
                    continue    # whole-prompt prefill program, not chunk
                add(_bucket(suffix, geom.buckets), a)
                continue
            if suffix <= chunk:
                add(chunk, a)   # single suffix chunk at width `chunk`
                continue
            # parked: one chunk per tick at page-aligned starts
            start_pages = a
            done = 0
            while done < suffix:
                add(chunk, start_pages)
                take = min(suffix - done, chunk)
                done += take
                start_pages += c_pages
    return out


@register_pass
class RecompileHazardPass(LintPass):
    """Runs on targets whose ``meta['geometry']`` is a
    :class:`ServingGeometry` (the CLI attaches the flagship engines');
    jaxpr-free — the hazard is host-side dispatch, not graph content."""

    name = "recompile-hazard"

    def __init__(self, limit: int = 16):
        self.limit = int(limit)

    def run(self, target: GraphTarget) -> List[Finding]:
        geom = target.meta.get("geometry")
        if geom is None:
            return []
        findings: List[Finding] = []
        programs = enumerate_chunk_programs(geom)
        total = sum(len(v) for v in programs.values())
        for width in sorted(programs):
            vals = programs[width]
            if len(vals) > self.limit:
                lo, hi = min(vals), max(vals)
                findings.append(self.finding(
                    target,
                    f"chunk-prefill width {width} reaches "
                    f"{len(vals)} distinct static prefix_pages values "
                    f"(range {lo}..{hi}) > limit {self.limit}: each is "
                    f"one XLA compile inside the serving tick — raise "
                    f"attach_quantum/prefill_chunk or shrink the "
                    f"prompt budget"))
        findings.append(self.finding(
            target,
            f"program inventory: {len(geom.buckets)} prefill buckets, "
            f"{total} chunk programs over {len(programs)} width(s), "
            f"1 decode shape — proven bound "
            f"{max((len(v) for v in programs.values()), default=0)} "
            f"prefix_pages/bucket (limit {self.limit})",
            severity=Severity.INFO))
        return findings

"""Recompile-hazard pass: statically enumerate the program set a
serving call site can produce.

Two reachability models live here, matching the two engine designs:

**Ragged one-program tick (r12+, ``geom.ragged``).** The engine's only
step functions are ``serving_tick`` (decode tokens + prompt spans as
one program; geometry rides in device arrays) and
``serving_tick_block`` (the fused decode block). The compiled-program
key is the packed token width, and the reachable set is fixed by
construction: mixed widths run the tail/no-tail tick pair, width
``S`` exactly ONE program (the fused block — since r16 sampling rides
it as data and the single-step sampling tick is gone).
``enumerate_tick_programs`` enumerates that set
so the invariant — ≤ 2 programs per width bucket — is *proven* from
engine dispatch, not asserted, and any future dispatch change that
silently multiplies the set fails the pass (and warns at engine
construction) before traffic does.

**Legacy bucketed dispatch (``ragged=False``).** The pre-r12
``serving_prefill_chunk`` took ``prefix_pages`` as a STATIC argument —
the gathered-prefix width was a shape — so every distinct value XLA
saw was one more compile landing *inside the serving tick* (a multi-
second stall per novel prefix length). ``enumerate_chunk_programs``
walks that dispatch exactly (attach quanta on the chunk grid, page-
aligned chunk starts, ≥ 1 suffix token) and proves or refutes the
≤ ``limit``-programs-per-bucket invariant. It is retained both as the
model for the still-exported bucketed step fns (offline callers,
benches A/B-ing against the old path) and as the regression oracle the
tests seed hazards through.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set

from .framework import (Finding, GraphTarget, LintPass, Severity,
                        register_pass)

__all__ = ["ServingGeometry", "enumerate_chunk_programs",
           "enumerate_tick_programs", "program_inventory",
           "tick_budget", "tick_width_grid", "RecompileHazardPass"]


@dataclass
class ServingGeometry:
    """The host-side facts that determine the serving program set."""
    page_size: int
    pages_per_slot: int
    buckets: List[int]          # prompt-length buckets (sorted)
    attach_quantum: int = 1     # 0/None = prefix cache off
    prefill_chunk: Optional[int] = None
    # ragged one-program-tick engine (r12+): program widths are
    # S / S+budget and the set below is reachable
    ragged: bool = False
    max_batch: int = 0
    decode_block: int = 1
    # speculative decoding (r15): draft-length cap; > 0 routes every
    # span-carrying tick through the ONE verify program per width
    spec_k: int = 0

    @staticmethod
    def of_engine(engine) -> "ServingGeometry":
        """Extract the geometry from a live ``ServingEngine``."""
        return ServingGeometry(
            page_size=engine.pool.page_size,
            pages_per_slot=engine.scheduler.pages_per_slot,
            buckets=list(engine._buckets),
            attach_quantum=(engine.prefix_cache.attach_quantum
                            if engine.prefix_cache is not None else 0),
            prefill_chunk=engine._chunk,
            ragged=True,
            max_batch=engine.scheduler.max_batch,
            decode_block=engine._decode_block,
            spec_k=engine._spec_k)


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def tick_budget(geom: ServingGeometry) -> int:
    """The ragged engine's per-tick prefill token budget: the
    prefill_chunk when set, else a whole max-length suffix (the same
    arithmetic as ``ServingEngine.__init__``)."""
    return (int(geom.prefill_chunk) if geom.prefill_chunk is not None
            else int(geom.buckets[-1]))


def tick_width_grid(geom: ServingGeometry) -> List[int]:
    """The engine's packed-width grid (the same arithmetic as
    ``ServingEngine.__init__`` — pinned against a live engine by
    test): prompt buckets capped at the prefill budget, plus the
    budget itself; a speculative geometry adds the all-slots-drafting
    width ``S*(1+spec_k)`` and the combined worst case on top, so
    every reachable span-token total (prefill spans + draft spans)
    snaps to a small static set."""
    budget = tick_budget(geom)
    grid = {min(int(b), budget) for b in geom.buckets} | {budget}
    if geom.spec_k:
        spec_max = int(geom.max_batch) * (1 + int(geom.spec_k))
        grid |= {spec_max, budget + spec_max}
    return sorted(grid)


def enumerate_tick_programs(geom: ServingGeometry) -> Dict[int,
                                                           Set[str]]:
    """Exact reachable ``{packed_width: {program}}`` under the ragged
    engine's dispatch (``ServingEngine._decode_tick``). Since r16
    SAMPLING is per-slot DATA to the fused in-graph sampler
    (temperature/top-k/top-p/keys ride the tick meta), so temperature
    never selects a program:

    * ticks with pending prefill spans run ``serving_tick`` at packed
      width ``max_batch + w`` where ``w`` is the smallest entry of the
      width grid (prompt buckets capped at the budget, plus the budget
      itself) covering the tick's span tokens — span count, span
      offsets, prefix size and cache lengths are all device data.
      Each width compiles with the fused decode tail
      (``decode_tail = decode_block-1``; sampling slots ride it via
      the fused sampler) plus, when ``decode_block > 1``, the
      tail-less variant for ticks where NO slot is tail-live (pure
      mid-prefill ticks): at most two compiles per width;
    * pure-decode ticks — greedy, sampling or mixed — run the fused
      ``serving_tick_block`` at width ``max_batch``. The pre-r16
      width-S single-step sampling ``serving_tick[decode]`` program
      is GONE from the inventory.

    A SPECULATIVE geometry (``spec_k > 0``) changes the mixed widths,
    not the bound: every tick carrying spans or drafts — prefill-only
    ticks included — runs the ONE ``spec_k``-static verify program for
    its width (speculation replaces the fused decode tail there, so
    the tail variant is unreachable), and the width grid grows the two
    speculative entries (``tick_width_grid``). Width ``max_batch``
    keeps the fused block alone — a slot degraded by the acceptance
    policy, like a sampling slot, is a data state, not a new program.

    Nothing else is reachable, whatever the traffic: the bound is
    1-2 programs per width bucket by construction.
    """
    S = int(geom.max_batch)
    k = int(geom.decode_block)
    grid = tick_width_grid(geom)
    if geom.spec_k:
        mixed: Set[str] = {f"serving_tick[verify,spec_k="
                           f"{int(geom.spec_k)}]"}
    else:
        mixed = {f"serving_tick[mixed,tail={k - 1}]"}
        if k > 1:
            # reachable only on ticks with zero tail-live slots (all
            # spans mid-prefill): the engine drops the tail there
            # rather than run k-1 all-dead steps
            mixed.add("serving_tick[mixed,tail=0]")
    out: Dict[int, Set[str]] = {S + w: set(mixed) for w in grid}
    out[S] = {f"serving_tick_block[k={k}]"}
    return out


def program_inventory(geom: ServingGeometry) -> Dict[str, object]:
    """The one schema for "what programs may this engine compile":
    ``{programs_per_bucket, total, widths: {str(width): [program]}}``.
    Shared by ``graph_lint --json`` (``serving_programs`` and the
    ``observability`` block), the engine-ctor warning, and the runtime
    recompile sentinel (observability/sentinel.py) — the static proof
    and the runtime alarm carry the SAME inventory, so a CI consumer
    and a production postmortem can be diffed field for field."""
    programs = enumerate_tick_programs(geom)
    return {
        "programs_per_bucket": max(
            (len(v) for v in programs.values()), default=0),
        "total": sum(len(v) for v in programs.values()),
        "widths": {str(w): sorted(v)
                   for w, v in sorted(programs.items())},
    }


def enumerate_chunk_programs(geom: ServingGeometry) -> Dict[int,
                                                            Set[int]]:
    """Exact reachable ``{chunk_width: {prefix_pages}}`` under the
    LEGACY bucketed dispatch rules (see module docstring). Empty when
    no code path can ever call the chunk program (no cache and no
    chunking)."""
    ps = geom.page_size
    q = geom.attach_quantum
    chunk = geom.prefill_chunk
    max_prompt = geom.buckets[-1]
    out: Dict[int, Set[int]] = {}
    if not q and chunk is None:
        return out

    def add(width: int, pp: int) -> None:
        out.setdefault(int(width), set()).add(int(pp))

    c_pages = chunk // ps if chunk is not None else None
    for n in range(1, max_prompt + 1):
        cap = (n - 1) // ps                      # match cap: >=1 suffix tok
        attaches = [0]
        if q:
            attaches = list(range(0, (cap // q) * q + 1, q))
        for a in attaches:
            suffix = n - a * ps
            if chunk is None:
                if a == 0:
                    continue    # whole-prompt prefill program, not chunk
                add(_bucket(suffix, geom.buckets), a)
                continue
            if suffix <= chunk:
                add(chunk, a)   # single suffix chunk at width `chunk`
                continue
            # parked: one chunk per tick at page-aligned starts
            start_pages = a
            done = 0
            while done < suffix:
                add(chunk, start_pages)
                take = min(suffix - done, chunk)
                done += take
                start_pages += c_pages
    return out


@register_pass
class RecompileHazardPass(LintPass):
    """Runs on targets whose ``meta['geometry']`` is a
    :class:`ServingGeometry` (the CLI attaches the flagship engines');
    jaxpr-free — the hazard is host-side dispatch, not graph content.

    Ragged geometries are held to ``ragged_limit`` (the one-program-
    tick invariant: ≤ 2 per width bucket); legacy bucketed geometries
    to ``limit`` (≤ 16 static prefix_pages per chunk width)."""

    name = "recompile-hazard"

    def __init__(self, limit: int = 16, ragged_limit: int = 2):
        self.limit = int(limit)
        self.ragged_limit = int(ragged_limit)

    def _run_ragged(self, target, geom) -> List[Finding]:
        findings: List[Finding] = []
        programs = enumerate_tick_programs(geom)
        for width in sorted(programs):
            progs = programs[width]
            if len(progs) > self.ragged_limit:
                findings.append(self.finding(
                    target,
                    f"tick width {width} reaches {len(progs)} distinct "
                    f"programs ({sorted(progs)}) > limit "
                    f"{self.ragged_limit}: each is an XLA compile "
                    f"inside the serving tick — the one-program-tick "
                    f"dispatch regressed"))
        worst = max((len(v) for v in programs.values()), default=0)
        inventory = {w: sorted(v) for w, v in sorted(programs.items())}
        findings.append(self.finding(
            target,
            f"program inventory (ragged tick): {inventory} — proven "
            f"bound {worst} programs/bucket (limit {self.ragged_limit})",
            severity=Severity.INFO))
        return findings

    def run(self, target: GraphTarget) -> List[Finding]:
        geom = target.meta.get("geometry")
        if geom is None:
            return []
        if geom.ragged:
            return self._run_ragged(target, geom)
        findings: List[Finding] = []
        programs = enumerate_chunk_programs(geom)
        total = sum(len(v) for v in programs.values())
        for width in sorted(programs):
            vals = programs[width]
            if len(vals) > self.limit:
                findings.append(self.finding(
                    target,
                    f"chunk-prefill width {width} reaches "
                    f"{len(vals)} distinct static prefix_pages values "
                    f"({sorted(vals)}) > limit {self.limit}: each is "
                    f"one XLA compile inside the serving tick — raise "
                    f"attach_quantum/prefill_chunk or shrink the "
                    f"prompt budget"))
        findings.append(self.finding(
            target,
            f"program inventory: {len(geom.buckets)} prefill buckets, "
            f"{total} chunk programs over {len(programs)} width(s), "
            f"1 decode shape — proven bound "
            f"{max((len(v) for v in programs.values()), default=0)} "
            f"prefix_pages/bucket (limit {self.limit})",
            severity=Severity.INFO))
        return findings

"""Static HBM peak estimator: a liveness walk over the traced step.

The question every geometry decision ultimately asks — "does this step
fit?" — is answerable before any compile: the jaxpr is a schedule of
buffer births (equation outputs) and deaths (last uses), so walking it
in order while summing live bytes gives the per-program-point resident
set, and its maximum is the static peak. The model mirrors how XLA's
buffer assignment actually behaves:

* non-donated inputs stay resident for the whole program (argument
  buffers are caller-owned and never freed);
* donated inputs die at their last use (XLA reuses them as outputs —
  the donation audit proves the aliasing is real);
* equation outputs live from their defining equation to their last
  consumer; program outputs live to the end;
* control-flow bodies (scan/while/pjit/remat/custom_vjp) contribute
  their own INTERNAL peak on top of the operands live outside — a
  scan's stacked residuals are its equation outputs, its body
  intermediates are transient inside one trip;
* per-device bytes divide by the declared PartitionSpec's shard factor
  where one is known (program inputs from ``meta['in_specs']``,
  ``with_sharding_constraint`` sites in the graph); unannotated
  intermediates inherit the factor of their largest input — GSPMD may
  shard them further, so the estimate is an upper bound, which is the
  safe direction for a fits-in-HBM question.

Accuracy is pinned by test against the compiled module's own
accounting (``compiled.memory_analysis()`` / ``cost_analysis()``):
within ±10% on the flagship llama train step (f32 on the CPU mesh —
bf16 graphs compiled ON CPU get f32-normalized buffers XLA itself
inflates ~2x, a backend artifact, not an estimator one; see
docs/ANALYSIS.md for the measured table).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..core.graph_trace import sub_jaxprs as _sub_jaxprs
from .framework import (GraphTarget, LintPass, Severity,
                        aval_nbytes as _nbytes, register_pass)
from .sharding_lint import spec_shard_factor

__all__ = ["HbmEstimate", "estimate_hbm_peak", "HbmPeakPass",
           "xla_cost_analysis", "xla_peak_bytes"]


@dataclass
class HbmEstimate:
    """Per-device peak estimate + the live set at the peak instant."""
    peak_bytes: int
    #: (bytes, label) largest-first at the peak program point
    top: List[Tuple[int, str]] = field(default_factory=list)
    args_bytes: int = 0          # resident non-donated + donated inputs
    graph: str = ""

    def __str__(self) -> str:
        lines = [f"{self.graph}: est. peak {self.peak_bytes / 2**20:.2f}"
                 f" MiB/device (inputs {self.args_bytes / 2**20:.2f}"
                 f" MiB)"]
        for b, label in self.top:
            lines.append(f"  {b / 2**20:8.2f} MiB  {label}")
        return "\n".join(lines)


def _internal_peak(jaxpr) -> int:
    """Peak bytes of values CREATED inside ``jaxpr`` (its invars alias
    buffers that the caller already accounts for)."""
    from jax._src import core as jax_core
    last: Dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for a in eqn.invars:
            if not isinstance(a, jax_core.Literal):
                last[a] = i
    outset = {o for o in jaxpr.outvars
              if not isinstance(o, jax_core.Literal)}
    n_eqns = len(jaxpr.eqns)
    for o in outset:
        last[o] = n_eqns
    live = peak = 0
    created: Dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        out_b = sum(_nbytes(o.aval) for o in eqn.outvars)
        sub_pk = max([_internal_peak(sj) for _, sj in _sub_jaxprs(eqn)]
                     + [0])
        peak = max(peak, live + out_b + sub_pk)
        for o in eqn.outvars:
            created[o] = _nbytes(o.aval)
        live += out_b
        for v in list(created):
            if last.get(v, -1) <= i and v not in outset:
                live -= created.pop(v)
    return peak


def estimate_hbm_peak(target: GraphTarget, top_k: int = 8
                      ) -> HbmEstimate:
    """Liveness-walk ``target.jaxpr`` and return the per-device peak
    estimate with its top-k live contributors."""
    from jax._src import core as jax_core
    closed = target.jaxpr
    jaxpr = closed.jaxpr
    # make_jaxpr over a jitted fn wraps everything in one pjit: inline
    # through single-equation wrappers whose arity matches
    while (len(jaxpr.eqns) == 1 and _sub_jaxprs(jaxpr.eqns[0])
           and len(_sub_jaxprs(jaxpr.eqns[0])[0][1].invars)
           == len(jaxpr.invars)):
        jaxpr = _sub_jaxprs(jaxpr.eqns[0])[0][1]

    mesh_axes = dict(target.meta.get("mesh_axes", {}))
    specs = target.meta.get("in_specs")
    labels = target.meta.get("invar_labels",
                             [f"arg{i}" for i in range(len(jaxpr.invars))])
    donated = target.meta.get("donated_invars",
                              [False] * len(jaxpr.invars))

    factor: Dict[Any, int] = {}
    bytes_of: Dict[Any, int] = {}
    label_of: Dict[Any, str] = {}

    for i, v in enumerate(jaxpr.invars):
        f = (spec_shard_factor(specs[i], mesh_axes)
             if specs is not None and i < len(specs) else 1)
        factor[v] = max(f, 1)
        bytes_of[v] = _nbytes(v.aval) // factor[v]
        label_of[v] = labels[i] if i < len(labels) else f"arg{i}"
    for v in jaxpr.constvars:
        factor[v] = 1
        bytes_of[v] = _nbytes(v.aval)
        label_of[v] = "const"

    last: Dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for a in eqn.invars:
            if not isinstance(a, jax_core.Literal):
                last[a] = i
    outset = {o for o in jaxpr.outvars
              if not isinstance(o, jax_core.Literal)}
    n_eqns = len(jaxpr.eqns)
    for o in outset:
        last[o] = n_eqns

    args_bytes = sum(bytes_of[v] for v in jaxpr.invars)
    live: Dict[Any, int] = {v: bytes_of[v]
                            for v in (*jaxpr.invars, *jaxpr.constvars)}
    live_total = sum(live.values())
    peak, peak_live, peak_extra = live_total, dict(live), 0
    don = {v for v, d in zip(jaxpr.invars, donated) if d}

    for i, eqn in enumerate(jaxpr.eqns):
        prim = eqn.primitive.name
        # propagate shard factors: constraint sites are exact, other
        # outputs inherit the largest input's factor (upper bound)
        if prim == "sharding_constraint":
            sh = eqn.params.get("sharding")
            f_out = (spec_shard_factor(sh.spec, mesh_axes)
                     if getattr(sh, "spec", None) is not None else 1)
        else:
            in_fs = [factor.get(a, 1) for a in eqn.invars
                     if not isinstance(a, jax_core.Literal)]
            big = max(((_nbytes(a.aval), factor.get(a, 1))
                       for a in eqn.invars
                       if not isinstance(a, jax_core.Literal)),
                      default=(0, 1))
            f_out = big[1] if big[0] else (min(in_fs) if in_fs else 1)
        out_b = 0
        for o in eqn.outvars:
            factor[o] = max(f_out, 1)
            bytes_of[o] = _nbytes(o.aval) // factor[o]
            label_of[o] = f"{prim} -> {getattr(o, 'aval', '?')}"
            out_b += bytes_of[o]
        sub_pk = max([_internal_peak(sj) for _, sj in _sub_jaxprs(eqn)]
                     + [0]) // max(f_out, 1)
        if live_total + out_b + sub_pk > peak:
            peak = live_total + out_b + sub_pk
            peak_live = dict(live)
            for o in eqn.outvars:
                peak_live[o] = bytes_of[o]
            peak_extra = sub_pk
        for o in eqn.outvars:
            live[o] = bytes_of[o]
            live_total += bytes_of[o]
        for v in list(live):
            if last.get(v, -1) > i or v in outset:
                continue
            if v in jaxpr.invars and v not in don:
                continue  # caller-owned buffer: resident to the end
            live_total -= live.pop(v)

    top = sorted(((b, label_of.get(v, "?")) for v, b in
                  peak_live.items()), key=lambda t: -t[0])[:top_k]
    if peak_extra:
        top = [(peak_extra, "loop-body transient peak")] + top
        top = top[:top_k]
    return HbmEstimate(peak_bytes=peak, top=top, args_bytes=args_bytes,
                       graph=target.name)


@register_pass
class HbmPeakPass(LintPass):
    """Report the per-device static peak for every target that declares
    input specs, and fail targets that declare a byte budget
    (``meta['hbm_budget_bytes']``) the estimate exceeds. The estimate
    is also collected on the pass instance (``self.reports``) so the
    CLI can emit the full table in ``--json``."""

    name = "hbm-peak"

    def __init__(self, top_k: int = 6):
        self.top_k = int(top_k)
        self.reports: Dict[str, HbmEstimate] = {}

    def run(self, target: GraphTarget):
        if target.meta.get("in_specs") is None:
            return []
        est = estimate_hbm_peak(target, top_k=self.top_k)
        self.reports[target.name] = est
        findings = [self.finding(
            target,
            f"estimated per-device peak {est.peak_bytes / 2**20:.2f} "
            f"MiB (top: "
            + "; ".join(f"{b / 2**20:.2f} MiB {lbl}"
                        for b, lbl in est.top[:3]) + ")",
            severity=Severity.INFO)]
        budget = target.meta.get("hbm_budget_bytes")
        if budget is not None and est.peak_bytes > int(budget):
            findings.append(self.finding(
                target,
                f"estimated peak {est.peak_bytes / 2**20:.2f} MiB "
                f"exceeds the declared per-device budget "
                f"{int(budget) / 2**20:.2f} MiB — the step does not "
                f"fit the geometry it claims to run on"))
        return findings


def xla_cost_analysis(compiled) -> Dict[str, float]:
    """``compiled.cost_analysis()`` normalized across jax versions: the
    current one returns a LIST with one properties-dict per partition,
    older ones return the dict directly. Always returns a (possibly
    empty) plain dict for the addressable partition, so callers can
    ``.get("flops")`` without version branches — the one shared helper
    for every cost_analysis consumer (this module's accuracy pin,
    tools/resnet_bench.py, tools/decode_profile.py, the 1F1B
    schedule-efficiency test)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def xla_peak_bytes(compiled) -> Optional[int]:
    """XLA's own per-device peak for a compiled step: argument buffers
    + temp heap + non-aliased outputs (``memory_analysis()``, the same
    introspection family as ``cost_analysis()``). None when the backend
    does not expose it."""
    try:
        ma = compiled.memory_analysis()
        return int(ma.argument_size_in_bytes + ma.temp_size_in_bytes
                   + ma.output_size_in_bytes - ma.alias_size_in_bytes)
    except Exception:
        return None

"""Paged-KV invariant checker: a race-detector-style model of
PagePool + prefix-cache trie + scheduler state.

The serving stack's correctness rests on host-side bookkeeping that no
jitted program can check for itself: page ownership, trie refcounts,
dead-slot table rows. A single slipped refcount aliases two requests
onto one physical page and decode silently cross-contaminates their
KV — tokens still stream, nothing crashes (the *Ragged Paged
Attention* paper's mis-maintained-page-table failure class). This
module re-derives every invariant from first principles against the
live state and reports each violation:

* **partition** — every non-trash page is in exactly ONE of: the pool
  free list, some live request's private pages, or the prefix-cache
  trie. No page in two places; no allocated page owned by nobody
  (leak).
* **refcounts** — each trie node's ``refs`` equals the number of live
  requests whose attached chain contains it; a page shared by two
  slots' table rows MUST be a cached node with refs ≥ 2 (the
  "no double-attach without a matching trie refcount" rule).
* **table rows** — a live slot's row is position-major: each attached
  trie node's page sits at its chain-depth position, every remaining
  non-trash position in order is a private page of the request,
  TRASH-padded; its length fits the row's capacity; entries are in
  pool range.
* **parked slots** — a request mid chunked-prefill is a DEAD slot: the
  scheduler row must be all-TRASH with length 0 (a single real entry
  there and the TPU pallas page loop reads a row the scheduler thinks
  is dead), while the stashed real row must stay consistent with the
  request's pages.
* **trie shape** — parent/child links are mutually consistent and
  node pages are distinct (a duplicated page id inside the trie is the
  refcount bug one step before it becomes visible).
* **defrag closure** — a ``defrag_plan`` must be closed over every
  live reference source: scheduler rows, request page lists, PARKED
  stashed rows, and cached trie pages. A source the plan misses keeps
  pointing at a page whose KV just moved.

Everything is host-side dict/array walking — O(pages + slots·row) per
audit — so the per-tick debug mode (``ServingEngine(
check_invariants=True)``) stays well under the 10% tick budget
(measured in docs/ANALYSIS.md).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

import numpy as np

__all__ = ["Violation", "KVInvariantError", "audit_serving_state",
           "audit_defrag_plan", "audit_engine"]


@dataclass
class Violation:
    code: str        # stable machine-readable id, e.g. "page-aliased"
    message: str

    def __str__(self) -> str:
        return f"[{self.code}] {self.message}"


class KVInvariantError(AssertionError):
    """Raised by ``assert_ok`` paths; carries the full violation list.
    ``context`` (optional) names the engine state the audit ran
    against — e.g. the serving geometry — so a violation report from a
    dead engine is actionable without reproducing the run."""

    def __init__(self, violations: List[Violation],
                 context: str = ""):
        self.violations = violations
        self.context = context
        msg = ("paged-KV invariant violation(s):\n  " +
               "\n  ".join(str(v) for v in violations))
        if context:
            msg += f"\n  [{context}]"
        super().__init__(msg)


def _row_list(row) -> List[int]:
    """Table row as a plain python int list (one C-level conversion —
    the audit runs per tick, so per-element ``int()`` casts are real
    overhead)."""
    return row.tolist() if isinstance(row, np.ndarray) \
        else [int(p) for p in row]


def _nz(row) -> List[int]:
    """Non-trash entries of a table row, in position order."""
    return [p for p in _row_list(row) if p != 0]


def _chain_depth(nd) -> int:
    """1-based chain depth of a trie node (token pages covered)."""
    d = 0
    while nd is not None and nd.parent is not None:
        d += 1
        nd = nd.parent
    return d


def audit_serving_state(pool, scheduler=None, prefix_cache=None,
                        prefill_queue=None, extra_refs=None,
                        extra_pages=None) -> List[Violation]:
    """Full audit of one serving stack's host-side state. Callers must
    hold whatever lock serializes mutation (the engine's tick lock);
    the checker only reads. ``prefill_queue=None`` means "unknown" —
    the parked-but-not-queued liveness check is skipped.

    ``extra_refs`` (``{id(node): count}``) are trie refcounts held by
    something OTHER than a live request's attached chain — the chunked
    migration protocol pins exported chains and adopt graft points for
    a transfer's lifetime; without declaring them the refcount-drift
    check would fire on every in-flight transfer. ``extra_pages``
    (``{page_id: label}``) are allocated pages owned by a pending
    chunked adopt — scattered into but not yet grafted into the trie —
    which the partition check must count as owned, not leaked."""
    v: List[Violation] = []
    extra_refs = extra_refs or {}
    total = pool.total_pages
    trash = pool.TRASH

    # ---- pool internal consistency ----------------------------------
    free_list = list(pool._free)
    free = set(free_list)
    if len(free) != len(free_list):
        v.append(Violation("pool-free-dup",
                           "pool free list contains duplicate ids"))
    if free != pool._free_set:
        v.append(Violation(
            "pool-free-desync",
            f"free list ({len(free_list)} ids) and membership set "
            f"({len(pool._free_set)}) disagree"))
    bad = [p for p in free if not 0 < p < total]
    if trash in free or bad:
        v.append(Violation(
            "pool-free-range",
            f"free list holds trash/out-of-range ids: "
            f"{sorted(bad) + ([trash] if trash in free else [])}"))

    # ---- ownership maps ---------------------------------------------
    # owner labels are (kind, ident) tuples, stringified only on a
    # violation: this path runs every engine tick and eager f-strings
    # per page were the measured hot spot
    owners: Dict[int, List] = {}

    def own(page: int, kind: str, ident) -> None:
        if page == trash:
            return
        owners.setdefault(int(page), []).append((kind, ident))

    def who_str(who) -> str:
        return ", ".join(f"{k}:{i}" for k, i in who)

    cached_nodes = []
    if prefix_cache is not None:
        cached_nodes = prefix_cache.nodes()
        for nd in cached_nodes:
            own(nd.page, "cache-node", nd.page)

    live_reqs = []
    if scheduler is not None:
        live_reqs = scheduler.occupied()
        for slot, req in live_reqs:
            for p in req.pages:
                own(p, "req-private", req.id)

    if extra_pages:
        for page, label in extra_pages.items():
            own(int(page), "pending-adopt", label)

    for page, who in owners.items():
        if not 0 < page < total:
            v.append(Violation(
                "page-range", f"page {page} (owned by {who_str(who)}) "
                f"is out of pool range 1..{total - 1}"))
            continue
        if len(who) > 1:
            v.append(Violation(
                "page-aliased",
                f"page {page} owned {len(who)}x: {who_str(who)} — two "
                f"owners will free/overwrite each other's KV"))
        if page in free:
            v.append(Violation(
                "page-free-owned",
                f"page {page} owned by {who_str(who)} is ALSO on the "
                f"free list — the next alloc() aliases it"))
    used = total - 1 - len(free)
    if used != len(owners):
        v.append(Violation(
            "page-leak",
            f"pool reports {used} allocated pages but only "
            f"{len(owners)} are owned by live requests or the prefix "
            f"cache — {used - len(owners)} leaked (or over-owned)"))

    # ---- trie shape + refcounts -------------------------------------
    if prefix_cache is not None:
        seen_pages: Dict[int, int] = {}
        for nd in cached_nodes:
            seen_pages[nd.page] = seen_pages.get(nd.page, 0) + 1
            parent = nd.parent
            if parent is None or parent.children.get(nd.toks) is not nd:
                v.append(Violation(
                    "trie-links",
                    f"cache node for page {nd.page} is not its "
                    f"parent's child under its own key"))
            if nd.refs < 0:
                v.append(Violation(
                    "refcount-negative",
                    f"cache node page {nd.page} has refs={nd.refs}"))
        for page, cnt in seen_pages.items():
            if cnt > 1:
                v.append(Violation(
                    "trie-page-dup",
                    f"page {page} appears in {cnt} trie nodes"))

        expected: Dict[int, int] = {}
        for slot, req in live_reqs:
            for nd in req.prefix_nodes:
                expected[id(nd)] = expected.get(id(nd), 0) + 1
        by_id = {id(nd): nd for nd in cached_nodes}
        for nd in cached_nodes:
            want = expected.get(id(nd), 0) + int(extra_refs.get(id(nd),
                                                                0))
            if nd.refs != want:
                v.append(Violation(
                    "refcount-drift",
                    f"cache node page {nd.page} has refs={nd.refs} "
                    f"but {want} live request(s) attach it"))
        for nid, cnt in expected.items():
            if nid not in by_id:
                v.append(Violation(
                    "attach-evicted",
                    "a live request attaches a node no longer in the "
                    "trie (evicted while pinned)"))

    # ---- table rows / parked slots ----------------------------------
    if scheduler is not None:
        tables = scheduler.tables
        lengths = scheduler.lengths
        ps = pool.page_size
        parked_ids = ({id(r) for _, r in prefill_queue}
                      if prefill_queue is not None else None)
        lengths_l = _row_list(lengths)
        row_users: Dict[int, int] = {}
        for slot, req in live_reqs:
            parked = req.table_row is not None
            if parked:
                if not req.prefilling:
                    v.append(Violation(
                        "parked-not-prefilling",
                        f"slot {slot} stashes a real row but request "
                        f"{req.id} is not mid-prefill"))
                sched_row = _nz(tables[slot])
                if sched_row:
                    v.append(Violation(
                        "parked-row-live",
                        f"parked slot {slot} scheduler row is not "
                        f"all-TRASH (entries {sched_row}) — the shared "
                        f"decode program will read/write real pages "
                        f"of a mid-prefill request"))
                if lengths_l[slot] != 0:
                    v.append(Violation(
                        "parked-length",
                        f"parked slot {slot} has length "
                        f"{lengths_l[slot]} != 0 — the pallas page "
                        f"loop walks ceil(len/block) entries of a "
                        f"dead row"))
                row_ints = _row_list(req.table_row)
            else:
                row_ints = _row_list(tables[slot])
            if row_ints and not (0 <= min(row_ints)
                                 and max(row_ints) < total):
                v.append(Violation(
                    "row-range",
                    f"slot {slot} row has out-of-range page ids"))
            # chain nodes live at their chain-depth positions (token
            # order); every remaining non-trash position, in order, is
            # a private page. This stays true through insert()'s
            # adoption (adopted/duplicate pages interleave in token
            # order — the row is position-major, never list-order).
            chain_pos = {}
            for nd in req.prefix_nodes:
                chain_pos[_chain_depth(nd) - 1] = int(nd.page)
            bad_chain = [
                (j, page, row_ints[j] if j < len(row_ints) else None)
                for j, page in chain_pos.items()
                if j >= len(row_ints) or row_ints[j] != page]
            if bad_chain:
                v.append(Violation(
                    "row-chain-mismatch",
                    f"slot {slot}: attached chain pages not at their "
                    f"chain positions: {sorted(bad_chain)} "
                    f"(pos, want, got)"))
            if chain_pos:
                got = [p for p in row_ints if p != 0]
                private_got = [p for j, p in enumerate(row_ints)
                               if p != 0 and j not in chain_pos]
            else:
                got = private_got = [p for p in row_ints if p != 0]
            private_want = [int(p) for p in req.pages]
            if private_got != private_want:
                v.append(Violation(
                    "row-mismatch",
                    f"slot {slot} private row pages {private_got} != "
                    f"request's page list {private_want}"))
            # the row must FUND the tokens the scheduler thinks exist
            n_tok = lengths_l[slot]
            if n_tok > len(got) * ps:
                v.append(Violation(
                    "length-overflow",
                    f"slot {slot} length {n_tok} exceeds row capacity "
                    f"{len(got)} pages x {ps}"))
            if parked and parked_ids is not None \
                    and id(req) not in parked_ids:
                v.append(Violation(
                    "parked-not-queued",
                    f"slot {slot} is parked but not in the prefill "
                    f"queue — its prefill will never advance"))
            # cross-slot sharing tally (reuses this slot's row walk;
            # set() so a duplicated entry within one row counts once)
            for p in set(got):
                row_users[p] = row_users.get(p, 0) + 1

        # cross-slot sharing must be trie-backed with refs >= count
        cached_by_page = ({nd.page: nd for nd in cached_nodes}
                          if prefix_cache is not None else {})
        for page, cnt in row_users.items():
            if cnt < 2:
                continue
            nd = cached_by_page.get(page)
            if nd is None:
                v.append(Violation(
                    "share-uncached",
                    f"page {page} sits in {cnt} live slots' rows but "
                    f"is not a prefix-cache node — a private page got "
                    f"double-attached"))
            elif nd.refs < cnt:
                v.append(Violation(
                    "share-underref",
                    f"page {page} sits in {cnt} live slots' rows but "
                    f"its trie refcount is {nd.refs} — retirement "
                    f"will free KV another slot still reads"))
    return v


def audit_defrag_plan(plan: Dict[int, int], pool, scheduler=None,
                      prefix_cache=None) -> List[Violation]:
    """Check a ``PagePool.defrag_plan()`` is applicable AND closed over
    every live reference source (table rows, request page lists,
    parked stashed rows, cached trie pages) BEFORE anything is
    rewritten."""
    v: List[Violation] = []
    total = pool.total_pages
    free = set(pool.free_page_ids)
    used = set(range(1, total)) - free
    for old, new in plan.items():
        if not (0 < old < total and 0 < new < total):
            v.append(Violation(
                "defrag-range", f"plan entry {old}->{new} out of range"))
        if old not in used:
            v.append(Violation(
                "defrag-stale-src",
                f"plan moves page {old} which is not allocated — the "
                f"plan is stale (recompute after alloc/free)"))
    dests = set(plan.values())
    if dests & (used - set(plan)):
        v.append(Violation(
            "defrag-dest-live",
            f"plan destinations {sorted(dests & (used - set(plan)))} "
            f"hold live KV not being moved — the gather overwrites it"))

    # closure: every page id any live structure references must survive
    # the remap (be a non-source, or be remapped)
    referenced: Dict[int, str] = {}
    if scheduler is not None:
        for slot, req in scheduler.occupied():
            for p in req.pages:
                referenced[int(p)] = f"req{req.id}.pages"
            row = scheduler.effective_row(slot)
            for p in _nz(row):
                referenced.setdefault(int(p), f"slot{slot}.row")
    if prefix_cache is not None:
        for nd in prefix_cache.nodes():
            referenced.setdefault(int(nd.page), f"cache@{nd.page}")
    for page, src in sorted(referenced.items()):
        if page in free:
            v.append(Violation(
                "defrag-ref-freed",
                f"{src} references page {page} which is on the free "
                f"list"))
    # a plan is CLOSED when no referenced page is a move *destination*
    # of some other page unless it is itself moved away first — the
    # gather formulation handles ordering, so the real hazard is a
    # referenced page that the plan treats as free space
    for page, src in sorted(referenced.items()):
        if page in dests and page not in plan:
            v.append(Violation(
                "defrag-clobber",
                f"plan writes page {page} still referenced by {src} "
                f"without moving it first"))
    return v


def audit_engine(engine) -> List[Violation]:
    """Standalone audit of a live ``ServingEngine`` (grabs the tick
    lock so the state it reads is a consistent snapshot)."""
    with engine._tick_lock:
        extra_refs, extra_pages = engine._audit_extras()
        return audit_serving_state(
            engine.pool, engine.scheduler, engine.prefix_cache,
            prefill_queue=tuple(engine._prefill_q),
            extra_refs=extra_refs, extra_pages=extra_pages)

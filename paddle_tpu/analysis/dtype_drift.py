"""Dtype-drift lint pass: silent bf16→f32 upcasts in compute.

The silent-wrongness class this hunts: a weight or constant left in
f32 while the model's declared compute dtype is bf16. JAX's type
promotion then silently upcasts the bf16 side and the whole downstream
chain — matmuls included — runs in f32: numerically *different* from
the bf16 program that was benchmarked (and 2x the weight stream the
int8/bf16 decode budgets assume), with no error anywhere. At the jaxpr
level promotion is explicit (``convert_element_type`` equations), so
the drift is statically visible.

Three rules, each anchored to a concrete failure:

* **wide-dot** (error): a ``dot_general``/``conv`` computing in f32+
  where an operand's value *originates* from the declared narrow dtype
  (reached the dot through casts/elementwise ops). Deliberate f32
  islands — softmax stats, rms-norm accumulation, rope angles — are
  elementwise/reduction math and never trip this; only a GEMM pulled
  up to f32 does. That is exactly the f32-weight-in-bf16-model bug.
* **const-pollution** (error): a non-scalar f32 constant (a baked-in
  table or weight captured by closure) forcing a bf16 operand's upcast
  in a binary op. Scalar literals (eps, mask values) are exempt — f32
  scalars against bf16 arrays are JAX's weak-type norm.
* **f64-anywhere** (error): any float64 value in the graph. On TPU
  f64 is always an accident (x64 leaks through np arithmetic).

Origin tracking is per-jaxpr and flows through ``convert_element_type``
and elementwise ops: ``origin(v)`` is the set of float dtypes the value
passed through. Sub-jaxprs (scan bodies — the serving hot loops) are
analysed with origins seeded from their invars' own dtypes, which is
where the weights enter; this keeps the analysis linear and local
while still catching every in-loop drift.
"""
from __future__ import annotations

from typing import Dict, List, Set

import numpy as np

from ..core.graph_trace import sub_jaxprs
from .framework import (Finding, GraphTarget, LintPass, Severity,
                        register_pass)

__all__ = ["DtypeDriftPass"]

# GEMM-class primitives: where an upcast changes the compute budget
_DOT_PRIMS = {"dot_general", "conv_general_dilated"}

# primitives that PRODUCE a value of a new dtype by design: their
# output's origin is reset to its own dtype (an f32 iota is a genuine
# f32 source, not drift from some narrow input)
_SOURCE_PRIMS = {"iota", "rng_bit_generator", "random_seed",
                 "random_bits"}


def _is_float(dt) -> bool:
    import jax.numpy as jnp
    try:
        # jnp.issubdtype, not np: the extended float dtypes (bfloat16,
        # f8 variants) register as numpy kind 'V' and np.issubdtype
        # calls them non-floating
        # issubdtype is a metadata predicate (already a Python bool) —
        # no bool() wrapper, which source_lint PT003 would read as a
        # device-array coercion
        return jnp.issubdtype(np.dtype(dt), jnp.floating)
    except TypeError:
        return False


def _width(dt) -> int:
    return np.dtype(dt).itemsize


@register_pass
class DtypeDriftPass(LintPass):
    name = "dtype-drift"

    def __init__(self, max_const_elems_exempt: int = 1):
        # constants with <= this many elements never count as pollution
        # (scalar eps / mask literals are idiomatic f32 weak types)
        self.max_const_elems_exempt = int(max_const_elems_exempt)

    # ------------------------------------------------------------------
    def run(self, target: GraphTarget) -> List[Finding]:
        narrow = target.compute_dtype
        if narrow is None or not _is_float(narrow) or _width(narrow) >= 4:
            # f32 models have no narrower dtype to drift FROM; only the
            # f64 rule applies
            narrow = None
        closed = target.jaxpr
        findings: List[Finding] = []
        self._walk(target, closed.jaxpr, narrow, (), findings)
        return findings

    # ------------------------------------------------------------------
    def _walk(self, target, jaxpr, narrow, path, findings):
        # origin[id(var)] = set of float dtype names the value has
        # lived in; const_ids = vars that ARE baked-in constants (or
        # pure elementwise functions of one)
        origin: Dict[int, Set[str]] = {}
        const_ids: Set[int] = set()

        def seed(v, is_const=False):
            dt = getattr(v.aval, "dtype", None)
            if dt is not None and _is_float(dt):
                origin[id(v)] = {np.dtype(dt).name}
            if is_const:
                const_ids.add(id(v))

        for v in jaxpr.invars:
            seed(v)
        for v in jaxpr.constvars:
            seed(v, is_const=True)

        narrow_name = np.dtype(narrow).name if narrow is not None else None

        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            in_orig: Set[str] = set()
            any_const_in = False
            for a in eqn.invars:
                if hasattr(a, "aval") and not hasattr(a, "val"):
                    in_orig |= origin.get(id(a), set())
                    if id(a) in const_ids:
                        any_const_in = True

            # ---- f64 rule -------------------------------------------
            for o in eqn.outvars:
                dt = getattr(o.aval, "dtype", None)
                if (dt is not None and _is_float(dt)
                        and np.dtype(dt) == np.float64):
                    findings.append(self.finding(
                        target,
                        f"float64 value produced by `{prim}` — f64 on "
                        f"TPU is always drift (np x64 leak)",
                        path=path))
                    break

            # ---- wide-dot rule --------------------------------------
            if (narrow_name is not None and prim in _DOT_PRIMS
                    and eqn.outvars):
                out_dt = getattr(eqn.outvars[0].aval, "dtype", None)
                if (out_dt is not None and _is_float(out_dt)
                        and _width(out_dt) > _width(narrow)
                        and narrow_name in in_orig):
                    # declared f32 islands (e.g. the MoE router GEMM,
                    # fp32-by-design for stable softmax) are suppressed
                    # via target.meta['wide_dot_ok'](lhs_aval, rhs_aval)
                    # — suppression is per-shape and auditable, never a
                    # blanket rule relaxation
                    avals = [a.aval for a in eqn.invars
                             if hasattr(a, "aval")]
                    allow = target.meta.get("wide_dot_ok")
                    shapes = " x ".join(
                        str(list(a.shape)) for a in avals[:2])
                    if (allow is not None and len(avals) >= 2
                            and allow(avals[0], avals[1])):
                        findings.append(self.finding(
                            target,
                            f"declared f32 island: `{prim}` ({shapes}) "
                            f"runs in {np.dtype(out_dt).name} by "
                            f"design", severity=Severity.INFO,
                            path=path))
                    else:
                        findings.append(self.finding(
                            target,
                            f"`{prim}` ({shapes}) computes in "
                            f"{np.dtype(out_dt).name} on "
                            f"{narrow_name}-origin data — a silent "
                            f"upcast widened GEMM compute (check for "
                            f"f32 weights/constants in the "
                            f"{narrow_name} model)", path=path))

            # ---- const-pollution rule -------------------------------
            if (narrow_name is not None and len(eqn.invars) >= 2
                    and prim not in _DOT_PRIMS and any_const_in
                    and narrow_name in in_orig):
                for a in eqn.invars:
                    if id(a) not in const_ids:
                        continue
                    dt = getattr(a.aval, "dtype", None)
                    if (dt is None or not _is_float(dt)
                            or _width(dt) <= _width(narrow)):
                        continue
                    size = int(np.prod(getattr(a.aval, "shape", ()) or
                                       (1,)))
                    if size <= self.max_const_elems_exempt:
                        continue
                    findings.append(self.finding(
                        target,
                        f"{np.dtype(dt).name} constant "
                        f"({size} elems) meets {narrow_name} compute "
                        f"in `{prim}` — the constant should be cast "
                        f"to {narrow_name} at build time",
                        path=path))

            # ---- propagate origins ----------------------------------
            if prim in _SOURCE_PRIMS:
                out_orig: Set[str] = set()
            elif prim == "convert_element_type":
                out_orig = set(in_orig)     # casts carry provenance
            else:
                out_orig = set(in_orig)
            for o in eqn.outvars:
                dt = getattr(o.aval, "dtype", None)
                if dt is not None and _is_float(dt):
                    cur = set(out_orig)
                    cur.add(np.dtype(dt).name)
                    origin[id(o)] = cur
                    if any_const_in and all(
                            (id(a) in const_ids or hasattr(a, "val"))
                            for a in eqn.invars):
                        # pure function of constants stays a constant
                        const_ids.add(id(o))

            # ---- recurse into sub-jaxprs ----------------------------
            for label, sub in sub_jaxprs(eqn):
                self._walk(target, sub, narrow,
                           path + ((prim, label),), findings)

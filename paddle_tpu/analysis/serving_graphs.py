"""Flagship serving-graph targets for the lint passes.

One place that knows how to hand each flagship program to the
analysers: abstract-trace (``jax.make_jaxpr`` over ShapeDtypeStructs —
nothing allocates, nothing compiles) the serving step functions of a
model module exactly as the engine jits them, tagged with the
call-site facts the passes need (compute dtype, donated pool outputs,
slot/step counts, engine geometry for the recompile pass, pp stage
grouping for the collective pass).

The geometries here are the FLAGSHIP shapes — the ones the engine
tests and serving_bench drive on the CPU mesh — shrunk to tiny model
dims (linting is structural; hidden size changes nothing a pass looks
at, while tracing a 4-layer model keeps the CLI under a second).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .framework import GraphTarget, trace_graph
from .recompile import ServingGeometry

__all__ = ["engine_geometry", "serving_targets", "pp_stage_targets",
           "rewrite_targets", "ragged_walk_model", "FLAGSHIP_MODELS"]

FLAGSHIP_MODELS = ("llama", "qwen2_moe")


def ragged_walk_model(*, kv_len: int, page_size: int, head_dim: int,
                      num_kv_heads: int, num_heads: int,
                      num_layers: int, dtype_bytes: int = 2,
                      kv_tile_pages: int = 0) -> Dict[str, Any]:
    """Analytic flops/bytes of ONE slot's decode-step KV walk through
    the ragged kernel (ops/pallas/ragged_paged_attention.py), for the
    one-shot and tiled formulations alike — the model decode_profile's
    long-context ceiling prices the tiled walk with.

    Both walks stream each live page exactly once per (slot, kv-head),
    so HBM bytes are identical — ``2 · L · ceil(kv_len/ps) · ps · Dh``
    per kv head — and the tiled walk's only cost deltas are (a) the
    flash-combine flops (one extra exp/mul pair per score — noise next
    to the dots) and (b) a second in-flight DMA buffer. What changes
    is VMEM residency: one-shot pins the whole table's scratch, tiled
    pins O(tile) (``vmem_scratch_bytes``) — which is the quantity that
    caps context length on-chip, not bandwidth."""
    from ..ops.pallas.ragged_paged_attention import (
        ONE_SHOT_VMEM_BUDGET, vmem_scratch_bytes)
    pages = -(-int(kv_len) // int(page_size))
    kv_bytes = (2 * num_layers * num_kv_heads * pages * page_size
                * head_dim * dtype_bytes)
    # decode q_len=1: scores + weighted sum, 2 dots of [1, Dh] x
    # [Dh/., kv] per head
    flops = 2 * 2 * num_layers * num_heads * int(kv_len) * head_dim
    one_shot = vmem_scratch_bytes(pages, page_size, head_dim,
                                  jnp_dtype_of(dtype_bytes))
    tiled = (vmem_scratch_bytes(pages, page_size, head_dim,
                                jnp_dtype_of(dtype_bytes),
                                kv_tile_pages=kv_tile_pages)
             if kv_tile_pages else None)
    return {
        "kv_len": int(kv_len), "pages": pages,
        "kv_bytes_per_step": kv_bytes, "attn_flops_per_step": flops,
        "vmem_scratch_bytes_oneshot": one_shot,
        "oneshot_fits_vmem": one_shot <= ONE_SHOT_VMEM_BUDGET,
        "vmem_scratch_bytes_tiled": tiled,
    }


def jnp_dtype_of(dtype_bytes: int):
    """bytes-per-element -> the matching pool dtype (the walk model's
    inputs are geometry numbers, not arrays)."""
    import jax.numpy as jnp
    return {1: jnp.int8, 2: jnp.bfloat16, 4: jnp.float32}[int(dtype_bytes)]


def engine_geometry(*, page_size: int, max_prompt_len: int,
                    max_new_tokens_cap: int,
                    prefill_chunk: Optional[int] = None,
                    prompt_buckets=None,
                    prefix_cache: bool = True,
                    max_batch: int = 8,
                    decode_block: int = 1,
                    spec_k: int = 0) -> ServingGeometry:
    """The ``ServingGeometry`` a ``ServingEngine(**same_kwargs)`` would
    run — the same arithmetic as the engine ctor, computable without
    building pools or starting workers (tests pin the two against each
    other so this cannot drift). The r12 engine is RAGGED: prefix
    attach is exact (quantum 1 — attach size is device data, not a
    compile shape) and the program set is keyed by packed token width
    (``enumerate_tick_programs``)."""
    from ..serving.engine import _default_buckets
    buckets = sorted(set(int(b) for b in (
        prompt_buckets or _default_buckets(max_prompt_len))))
    pages_per_slot = -(-(buckets[-1] + max_new_tokens_cap - 1)
                       // page_size)
    return ServingGeometry(
        page_size=page_size, pages_per_slot=pages_per_slot,
        buckets=buckets,
        attach_quantum=1 if prefix_cache else 0,
        prefill_chunk=prefill_chunk,
        ragged=True, max_batch=int(max_batch),
        decode_block=int(decode_block), spec_k=int(spec_k))


def _get_model(name: str):
    if name == "llama":
        from ..models import llama as mod
        cfg = mod.LlamaConfig.tiny(use_flash_attention=False, remat=False)
    elif name == "qwen2_moe":
        from ..models import qwen2_moe as mod
        cfg = mod.Qwen2MoeConfig.tiny(use_flash_attention=False,
                                      remat=False)
    else:
        raise ValueError(f"unknown flagship model {name!r}; "
                         f"one of {FLAGSHIP_MODELS}")
    return mod, cfg


def serving_targets(model: str = "llama", *, slots: int = 4,
                    page_size: int = 4, max_prompt_len: int = 16,
                    max_new_tokens_cap: int = 16,
                    prefill_chunk: int = 8,
                    decode_block: int = 4,
                    spec_k: int = 3) -> List[GraphTarget]:
    """GraphTargets for one model's flagship serving programs — the
    r12 one-program-tick set as r16 reshaped it: ``serving_tick`` at
    the mixed packed width, ``serving_tick_block`` (the fused decode
    path — since r16 the ONLY pure-decode program: sampling slots ride
    it through the fused in-graph sampler, whose per-slot
    temperature/top-k/top-p/key/produced state is traced here exactly
    as the engine passes it, and the width-S single-step sampling tick
    no longer exists) and ``generate_paged`` (the offline batched
    decode), plus the engine geometry riding the block target for the
    recompile-hazard pass — and, since r15, the speculative VERIFY
    tick (``serving_tick[verify]`` at the all-slots-drafting width,
    spec_k static, draft/acceptance geometry as device data) carrying
    the SPECULATIVE engine geometry, so the recompile pass statically
    proves the draft/verify program set keeps the
    ≤2-programs-per-width-bucket invariant too."""
    import jax
    import jax.numpy as jnp

    mod, cfg = _get_model(model)
    geom = engine_geometry(
        page_size=page_size, max_prompt_len=max_prompt_len,
        max_new_tokens_cap=max_new_tokens_cap,
        prefill_chunk=prefill_chunk, max_batch=slots,
        decode_block=decode_block)
    pps = geom.pages_per_slot
    total_pages = slots * pps + 1
    meta: Dict[str, Any] = {}
    if model == "qwen2_moe":
        # the router GEMM is fp32 BY DESIGN (stable softmax over expert
        # logits — see qwen2_moe.init_params): declare the island so
        # the dtype-drift pass pins every OTHER wide dot. The predicate
        # is shape-tight: only a projection onto the expert dim passes.
        n_e = cfg.num_experts
        meta["wide_dot_ok"] = (
            lambda lhs, rhs: rhs.shape and rhs.shape[-1] == n_e)

    params = mod.abstract_params(cfg)
    pools = jax.eval_shape(
        lambda: mod.init_serving_pages(cfg, total_pages, page_size))
    kp, vp = pools["k_pages"], pools["v_pages"]
    sds = jax.ShapeDtypeStruct
    i32 = jnp.int32

    targets: List[GraphTarget] = []

    def sampling_meta():
        # the fused in-graph sampler's per-slot DATA (r16): the engine
        # passes these with every tick, so the linted graphs carry the
        # sampling head exactly as production compiles it
        return {"temp": sds((slots,), jnp.float32),
                "top_p": sds((slots,), jnp.float32),
                "top_k": sds((slots,), i32),
                "key": sds((slots, 2), jnp.uint32),
                "produced": sds((slots,), i32)}

    def tick_meta(T):
        return {"tok_slot": sds((T,), i32), "tok_pos": sds((T,), i32),
                "tok_page": sds((T,), i32), "tok_off": sds((T,), i32),
                "tok_qoff": sds((T,), i32), "q_len": sds((slots,), i32),
                "kv_len": sds((slots,), i32), "last": sds((slots,), i32),
                "tables": sds((slots, pps), i32), **sampling_meta()}

    # --- the ragged tick at its mixed width ---------------------------
    # widths mirror enumerate_tick_programs: S+budget (mixed ticks);
    # the pre-r16 width-S single-step sampling tick is GONE — sampling
    # rides the fused block below as data. The mixed tick carries
    # prefill, which legitimately returns one [S, V] logits row set per
    # prompt completion — in_decode_loop stays False so the host-pull
    # budget (whose hot-path guard is the block program below) does not
    # charge it per step; the engine pulls only the [S(,1+tail)] i32
    # token block whoever samples.
    from .recompile import tick_budget
    budget = tick_budget(geom)
    T = slots + budget
    targets.append(trace_graph(
        f"{model}.serving_tick[mixed]",
        mod.serving_tick,
        (params, sds((T,), i32), tick_meta(T), kp, vp),
        static_kwargs=dict(cfg=cfg, tq=budget, attn_impl="dense"),
        compute_dtype=cfg.dtype, slots=slots,
        donated_outputs=(2, 3), meta=dict(meta)))

    # --- the speculative verify tick (r15): drafted slots as ragged
    # spans + in-graph longest-prefix acceptance. Traced at the
    # all-slots-drafting width; the SPECULATIVE engine geometry rides
    # this target, so graph_lint proves the draft/verify program set
    # stays within the per-bucket bound (emitted as
    # serving_programs_spec in --json)
    spec_geom = engine_geometry(
        page_size=page_size, max_prompt_len=max_prompt_len,
        max_new_tokens_cap=max_new_tokens_cap,
        prefill_chunk=prefill_chunk, max_batch=slots,
        decode_block=decode_block, spec_k=spec_k)
    Tv = slots + slots * (1 + spec_k)
    ver_meta = dict(
        tick_meta(Tv),
        ver_idx=sds((slots, 1 + spec_k), i32),
        draft_tok=sds((slots, spec_k), i32),
        draft_len=sds((slots,), i32),
        tail_live=jax.ShapeDtypeStruct((slots,), jnp.bool_))
    targets.append(trace_graph(
        f"{model}.serving_tick[verify,spec_k={spec_k}]",
        mod.serving_tick,
        (params, sds((Tv,), i32), ver_meta, kp, vp),
        static_kwargs=dict(cfg=cfg, tq=slots * (1 + spec_k),
                           spec_k=spec_k, attn_impl="dense"),
        compute_dtype=cfg.dtype, slots=slots,
        donated_outputs=(3, 4), meta=dict(meta, geometry=spec_geom)))

    # --- fused decode block: the per-tick hot program (greedy AND
    # sampling slots since r16 — the sampling state is a traced arg,
    # exactly as the engine passes it) ---------------------------------
    def _block_with_sampling(p, tok, lens, tabs, kp_, vp_, samp):
        return mod.serving_tick_block(p, tok, lens, tabs, kp_, vp_,
                                      cfg=cfg, num_steps=decode_block,
                                      attn_impl="dense", sampling=samp)

    targets.append(trace_graph(
        f"{model}.serving_tick_block[k={decode_block}]",
        _block_with_sampling,
        (params, sds((slots,), i32), sds((slots,), i32),
         sds((slots, pps), i32), kp, vp, sampling_meta()),
        compute_dtype=cfg.dtype, slots=slots,
        steps_per_call=decode_block, in_decode_loop=True,
        # outputs (toks, k_pages, v_pages): the engine donates + rebinds
        # the pools, so only toks crosses to the host
        donated_outputs=(1, 2),
        meta=dict(meta, geometry=geom)))

    # --- offline batched decode: generate_paged ----------------------
    if hasattr(mod, "generate_paged"):
        B, T0, mnt = slots, max_prompt_len, max_new_tokens_cap
        targets.append(trace_graph(
            f"{model}.generate_paged[B={B}]",
            mod.generate_paged,
            (params, sds((B, T0), i32), sds((B,), i32)),
            static_kwargs=dict(cfg=cfg, max_new_tokens=mnt,
                               page_size=page_size, attn_impl="dense"),
            compute_dtype=cfg.dtype, slots=B, steps_per_call=mnt,
            in_decode_loop=True, meta=dict(meta)))
    return targets


def rewrite_targets(models=("llama",), *, slots: int = 4,
                    page_size: int = 4, max_prompt_len: int = 16,
                    max_new_tokens_cap: int = 16, decode_block: int = 4,
                    serving_pool: Optional[List[GraphTarget]] = None
                    ) -> List[GraphTarget]:
    """Flagship targets for the REWRITE suite (graph_lint --suite
    rewrite): per model, the fused decode block and the cold prefill
    chunk — both traced with the fused norm/rope kernels OFF (the
    default off-TPU), so the jnp rmsnorm formulation the
    ``fused-rmsnorm`` substitution targets is really present — plus,
    for llama, the int8 decode step traced with the UNFUSED
    dequantize-then-matmul idiom (``PADDLE_TPU_INT8_IMPL=unfused``),
    the seeded graph the ``int8-epilogue-fuse`` pass must fire on.

    Each target's ``meta['expect_rewrites']`` names the rewrites that
    MUST fire there — the suite errors if one does not, so the
    patterns cannot silently rot as the model code evolves.

    ``serving_pool``: already-traced serving targets (the lint suite's
    — same default geometry) to select from instead of re-tracing
    them, so ``graph_lint --suite all`` traces each flagship program
    once."""
    import os

    import jax
    import jax.numpy as jnp

    targets: List[GraphTarget] = []
    for m in models:
        pool = (serving_pool if serving_pool is not None
                else serving_targets(
                    m, slots=slots, page_size=page_size,
                    max_prompt_len=max_prompt_len,
                    max_new_tokens_cap=max_new_tokens_cap,
                    decode_block=decode_block))
        for t in pool:
            if not t.name.startswith(m + "."):
                continue
            if ("serving_tick_block" in t.name
                    or "serving_tick[mixed]" in t.name):
                # the tail (final norm → last-row gather → lm_head →
                # f32 cast) belongs to decode-tail-fuse; the per-layer
                # norms still fall through to the plain substitution
                t.meta["expect_rewrites"] = ("fused-rmsnorm",
                                             "decode-tail-fuse")
                targets.append(t)

    # --- int8: the un-fused dequant-matmul decode step (llama is the
    # int8 flagship — skipped when the caller excluded llama) ---------
    if "llama" not in models:
        return targets
    from ..quantization.decode import quantize_for_decode
    mod, cfg = _get_model("llama")
    geom = engine_geometry(
        page_size=page_size, max_prompt_len=max_prompt_len,
        max_new_tokens_cap=max_new_tokens_cap)
    pps = geom.pages_per_slot
    total_pages = slots * pps + 1
    qparams = jax.eval_shape(lambda: quantize_for_decode(
        mod.init_params(cfg, jax.random.PRNGKey(0)), cfg))
    pools = jax.eval_shape(
        lambda: mod.init_serving_pages(cfg, total_pages, page_size))
    sds, i32 = jax.ShapeDtypeStruct, jnp.int32
    prev = os.environ.get("PADDLE_TPU_INT8_IMPL")
    os.environ["PADDLE_TPU_INT8_IMPL"] = "unfused"
    try:
        t = trace_graph(
            "llama.serving_decode_step[int8-unfused]",
            mod.serving_decode_step,
            (qparams, sds((slots,), i32), sds((slots,), i32),
             sds((slots, pps), i32), pools["k_pages"],
             pools["v_pages"]),
            static_kwargs=dict(cfg=cfg, attn_impl="dense"),
            compute_dtype=cfg.dtype, slots=slots, in_decode_loop=True,
            donated_outputs=(1, 2))
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_INT8_IMPL", None)
        else:
            os.environ["PADDLE_TPU_INT8_IMPL"] = prev
    t.meta["expect_rewrites"] = ("int8-epilogue-fuse", "fused-rmsnorm")
    targets.append(t)
    return targets


def pp_stage_targets(num_stages: int = 2, virtual_chunks: int = 2,
                     seq_len: int = 8, batch: int = 2
                     ) -> List[GraphTarget]:
    """One GraphTarget per pipeline stage chunk of the flagship llama
    pp path (the round-robin VPP partition feeding
    ``pipeline_train_1f1b``), grouped for the collective-consistency
    pass: every chunk program must issue the identical collective
    sequence or the lockstep schedule deadlocks/corrupts."""
    import jax
    import jax.numpy as jnp

    from ..models import llama as L
    from ..parallel.pipeline_1f1b import split_chunks_round_robin

    cfg = L.LlamaConfig.tiny(use_flash_attention=False, remat=False,
                             pp_stages=num_stages,
                             vpp_chunks=virtual_chunks)
    params = L.abstract_params(cfg)
    VS = num_stages * virtual_chunks
    x = jax.ShapeDtypeStruct((batch, seq_len, cfg.hidden_size),
                             cfg.dtype)

    def stage_fn(chunk_params, xm):
        return L._scan_layers(chunk_params, xm, cfg, None,
                              remat=False)

    targets = []
    for k in range(VS):
        # each stage traces ITS OWN chunk slice (abstract-indexed out
        # of the real round-robin split) — so a future heterogeneous
        # partition, or any chunk-dependent program difference, shows
        # up as a genuinely different jaxpr rather than the check
        # comparing VS copies of one trace against itself
        chunk_k = jax.eval_shape(
            lambda p, k=k: jax.tree_util.tree_map(
                lambda c: c[k],
                split_chunks_round_robin(
                    p, cfg.num_hidden_layers, num_stages,
                    virtual_chunks)),
            params["layers"])
        targets.append(trace_graph(
            f"llama.pp_stage_chunk[{k}/{VS}]", stage_fn, (chunk_k, x),
            compute_dtype=cfg.dtype,
            meta={"stage_group": f"llama.pp[{num_stages}x"
                                 f"{virtual_chunks}]",
                  "stage_count": VS}))
    return targets

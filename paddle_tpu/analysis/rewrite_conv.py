"""The ResNet conv rewrite passes: profile-justified jaxpr rewrites.

Reference capability: the deploy-time IR passes PaddlePaddle applies to
every CNN (``conv_bn_fuse_pass``, ``conv_elementwise_add_act_fuse``,
the cuDNN/oneDNN layout-transfer passes in paddle/fluid/framework/ir/).
The per-op profile (``tools/resnet_bench.py --profile``) shows where
ResNet-50's step goes — conv regions plus three full activation
round-trips of BN/relu/residual traffic per block — and these passes
delete exactly that, as registered :class:`RewritePass`es under pinned
exactness contracts:

* ``conv-bn-fold`` — inference ``conv → batch_norm → relu?`` becomes
  ONE fused NHWC conv+bias+act (``ops/fused/conv_epilogue.py``): the
  BN affine folds into the conv weights per output channel, so the BN
  stats never touch the activation and the epilogue never re-reads it.
  Fires only on inference graphs: in training the conv output escapes
  into the batch-stat reduces, and the matcher's exclusivity rule
  rejects the site (folding a data-dependent mean into weights would
  be wrong — the no-fire is structural, not special-cased).
* ``stem-space-to-depth`` — the 7×7/stride-2/pad-3 stem conv becomes a
  dense 4×4/stride-1 conv on the space-to-depth input (3 → 12
  channels): same taps, same products, associated per 2×2 phase.
  TPU-wise this turns the one conv whose input channel count (3) stalls
  the 128-lane MXU into a dense well-shaped one.
* ``conv-nhwc-layout`` — any remaining NCHW conv is rewritten to the
  TPU-native NHWC layout with explicit border transposes (XLA cancels
  back-to-back pairs between consecutive rewritten convs, so interior
  transposes vanish after fusion).

Priorities (see :func:`framework.default_rewrites`): fold (20) beats
space-to-depth (30) beats layout (40) — the fold's pattern CONTAINS a
stem/layout-rewritable conv and routes the stem shape through the same
space-to-depth transform internally, so the narrower passes only pick
up convs the fold could not take (training graphs).
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .framework import ExactnessContract, RewritePass, register_rewrite
from .patterns import In, Lit, Op

__all__ = ["ConvBnFoldPass", "StemSpaceToDepthPass",
           "ConvNhwcLayoutPass", "resnet_rewrite_targets"]

#: lax's NCHW/OIHW ConvDimensionNumbers: every spec is the identity
_NCHW_SPECS = ((0, 1, 2, 3), (0, 1, 2, 3), (0, 1, 2, 3))


def _is_nchw(dn, eqn) -> bool:
    return (tuple(dn.lhs_spec), tuple(dn.rhs_spec),
            tuple(dn.out_spec)) == _NCHW_SPECS


def _is_relu_call(cj, eqn) -> bool:
    """``jax.nn.relu`` traces to ``custom_jvp_call`` whose call_jaxpr
    is a single pjit named "relu" (or, flattened, a single max) — match
    on that structure, not on the opaque primitive alone."""
    inner = getattr(cj, "jaxpr", cj)
    if len(inner.eqns) != 1:
        return False
    e = inner.eqns[0]
    if e.primitive.name == "max":
        return True
    return e.primitive.name == "pjit" and e.params.get("name") == "relu"


def _stat4(new_sizes, eqn) -> bool:
    """BN stat/affine broadcast shape [1, C, 1, 1] — a channel-axis-1
    reshape. A wrong-axis BN (channels-last stats reshape to
    [1,1,1,C]) must NOT fold into an NCHW conv's output channels."""
    return (len(new_sizes) == 4 and new_sizes[0] == 1
            and new_sizes[2] == 1 and new_sizes[3] == 1)


def _conv_eqn_of(match, jaxpr):
    for i in sorted(match.eqn_idxs):
        if jaxpr.eqns[i].primitive.name == "conv_general_dilated":
            return jaxpr.eqns[i]
    return None


def _stash_conv(match, eqn) -> bool:
    """Common conv-eqn legality + param stash: 2-D spatial, no input
    dilation (transposed convs keep their own lowering), no batch
    groups, default accum dtype. The precision request is stashed (as
    None or a pair of Precision names — strings, so statics stay
    serializable) and re-emitted by the replacement: the test suite
    runs under ``jax_default_matmul_precision=highest`` and a pass
    that refused non-default precision would never fire there."""
    p = eqn.params
    strides = tuple(p["window_strides"])
    if len(strides) != 2 or tuple(p["lhs_dilation"]) != (1, 1):
        return False
    if p["batch_group_count"] != 1:
        return False
    if p.get("preferred_element_type") is not None:
        return False
    prec = p.get("precision")
    if prec is None:
        match.statics["precision"] = None
    else:
        pair = prec if isinstance(prec, tuple) else (prec, prec)
        names = tuple(getattr(q, "name", None) for q in pair)
        if any(n is None for n in names):
            return False
        match.statics["precision"] = names
    match.statics["strides"] = strides
    match.statics["padding"] = tuple(tuple(x) for x in p["padding"])
    match.statics["dilation"] = tuple(p["rhs_dilation"])
    match.statics["groups"] = int(p["feature_group_count"])
    return True


@register_rewrite
class ConvBnFoldPass(RewritePass):
    """conv → BN(infer) → relu?  ⇒  one NHWC conv+bias+act with the BN
    folded into the weights (``s = γ·rsqrt(var+eps)``, ``w' = w·s``,
    ``bias = β − mean·s``).

    Contract: the fold moves the per-channel scale across the conv
    reduction — a genuine reassociation, so it pins a tolerance, not
    ulp. The verifier seeds BN statistics adversarially (variance from
    0.5·randn: negative values NaN both sides identically, near-zero
    positives blow ``rsqrt`` up to ~1e3), which amplifies the
    reassociation drift far beyond realistic running-stat inputs:
    measured across all 20 r18 sites × 2 seeds, finite max_abs 4.4e-4 /
    max_rel 3.3e-2, NaN positions identical. Pinned at rtol 5e-2 /
    atol 1e-3 against that adversarial measurement; with real BN stats
    (positive O(1) variance) the drift is ~1e-6 relative.
    """

    name = "conv-bn-fold"
    contract = ExactnessContract(rtol=5e-2, atol=1e-3)
    arg_names = ("x", "w", "gamma", "beta", "mean", "var")
    priority = 20

    def patterns(self):
        conv = Op("conv_general_dilated", In("x"), In("w"),
                  params={"dimension_numbers": _is_nchw})
        mr = Op("reshape", In("mean", ndim=1),
                params={"new_sizes": _stat4})
        vr = Op("reshape", In("var", ndim=1),
                params={"new_sizes": _stat4})
        rstd = Op("rsqrt", Op("add", vr, Lit("eps")))
        y = Op("mul", Op("sub", conv, mr), rstd, commute=True)
        y = Op("mul", y, Op("reshape", In("gamma", ndim=1),
                            params={"new_sizes": _stat4}), commute=True)
        bn = Op("add", y, Op("reshape", In("beta", ndim=1),
                             params={"new_sizes": _stat4}), commute=True)
        relu = Op("custom_jvp_call", bn,
                  params={"call_jaxpr": _is_relu_call})
        return [relu, bn]

    def validate(self, match, jaxpr) -> bool:
        eqn = _conv_eqn_of(match, jaxpr)
        if eqn is None or not _stash_conv(match, eqn):
            return False
        w = match.bindings["w"].aval
        c = w.shape[0]                       # OIHW output channels
        for name in ("gamma", "beta", "mean", "var"):
            if tuple(match.bindings[name].aval.shape) != (c,):
                return False
        match.statics["relu"] = (
            jaxpr.eqns[match.anchor_idx].primitive.name
            == "custom_jvp_call")
        return True

    def build(self, statics: Dict[str, Any]):
        from ..ops.fused.conv_epilogue import (conv_bn_act_nchw,
                                               fused_impl)
        eps = float(statics["eps"])
        kw = dict(strides=statics["strides"], padding=statics["padding"],
                  dilation=statics["dilation"], groups=statics["groups"],
                  relu=statics["relu"], impl=fused_impl(),
                  precision=statics["precision"])
        return lambda x, w, gamma, beta, mean, var: conv_bn_act_nchw(
            x, w, gamma, beta, mean, var, eps=eps, **kw)


@register_rewrite
class StemSpaceToDepthPass(RewritePass):
    """The 7×7/stride-2/pad-3 stem conv over 3 input channels ⇒ a dense
    4×4/stride-1 conv over the space-to-depth (12-channel) input —
    ``ops/fused/conv_epilogue.stem_s2d_conv_nchw``, the exact same taps
    regrouped by 2×2 phase.

    Contract: phase regrouping reorders the 147-term per-pixel
    reduction (and adds exact zeros from the tap padding), so ulp does
    not apply; pinned at rtol 5e-2 / atol 2e-2 — wide enough to stay
    honest for the bf16 AMP training graphs this pass fires on (bf16
    eps ≈ 8e-3/term; suite-measured max_rel 1.95e-2 sat within 2.4% of
    a 2e-2 pin), measured f32 drift is ~1e-7.
    """

    name = "stem-space-to-depth"
    contract = ExactnessContract(rtol=5e-2, atol=2e-2)
    arg_names = ("x", "w")
    priority = 30

    def patterns(self):
        return [Op("conv_general_dilated", In("x"), In("w"),
                   params={"dimension_numbers": _is_nchw,
                           "window_strides": (2, 2),
                           "padding": ((3, 3), (3, 3)),
                           "feature_group_count": 1})]

    def validate(self, match, jaxpr) -> bool:
        eqn = _conv_eqn_of(match, jaxpr)
        if eqn is None or not _stash_conv(match, eqn):
            return False
        x = match.bindings["x"].aval
        w = match.bindings["w"].aval
        if match.statics["dilation"] != (1, 1):
            return False
        # the STEM shape, nothing else: Cin=3, 7x7 taps, even image
        return (tuple(w.shape[1:]) == (3, 7, 7) and len(x.shape) == 4
                and x.shape[2] % 2 == 0 and x.shape[3] % 2 == 0)

    def build(self, statics: Dict[str, Any]):
        from ..ops.fused.conv_epilogue import stem_s2d_conv_nchw
        precision = statics["precision"]
        return lambda x, w: stem_s2d_conv_nchw(x, w, precision=precision)


@register_rewrite
class ConvNhwcLayoutPass(RewritePass):
    """Any remaining NCHW conv ⇒ transpose → NHWC conv → transpose
    (the TPU-native conv layout; border transposes between consecutive
    rewritten convs cancel in XLA's fusion).

    Contract: identical taps, but the conv's internal reduction walks a
    different memory order and XLA may associate it differently per
    layout — pinned at rtol 5e-2 / atol 2e-2 for the same bf16-honesty
    reason as the stem pass (f32 measures ~1e-7).
    """

    name = "conv-nhwc-layout"
    contract = ExactnessContract(rtol=5e-2, atol=2e-2)
    arg_names = ("x", "w")
    priority = 40

    def patterns(self):
        return [Op("conv_general_dilated", In("x"), In("w"),
                   params={"dimension_numbers": _is_nchw})]

    def validate(self, match, jaxpr) -> bool:
        eqn = _conv_eqn_of(match, jaxpr)
        return eqn is not None and _stash_conv(match, eqn)

    def build(self, statics: Dict[str, Any]):
        import jax.numpy as jnp
        from jax import lax

        from ..ops.fused.conv_epilogue import decode_precision
        strides, padding = statics["strides"], statics["padding"]
        dilation, groups = statics["dilation"], statics["groups"]
        precision = decode_precision(statics["precision"])

        def fn(x, w):
            y = lax.conv_general_dilated(
                jnp.transpose(x, (0, 2, 3, 1)),
                jnp.transpose(w, (2, 3, 1, 0)),
                window_strides=strides, padding=padding,
                rhs_dilation=dilation,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=groups, precision=precision)
            return jnp.transpose(y, (0, 3, 1, 2))
        return fn


# ---------------------------------------------------------------------------
# rewrite-suite targets (graph_lint --suite rewrite)
# ---------------------------------------------------------------------------

def resnet_rewrite_targets(depth: int = 18, image: int = 64,
                           batch: int = 2):
    """The two ResNet targets the rewrite suite traces: the inference
    graph (every conv+BN folds; ``expect_rewrites`` makes
    didn't-fire an error) and the train-mode forward (BN-train's
    escaping conv outputs block the fold structurally; the stem
    space-to-depth and the layout pass cover the convs instead).
    Small depth/image — firing is shape-independent beyond the stem's
    even-image constraint, and the suite eval-verifies every site."""
    import paddle_tpu as pt
    from ..autograd import tape as _tape
    from ..core.tensor import Tensor
    from ..models.resnet import ResNet
    from ..static.nn import _bind
    from .framework import trace_graph

    pt.seed(0)
    model = ResNet(depth=depth, num_classes=10)
    params = model.parameters()
    bufs = list(model.buffers())
    parrs = [p._data for p in params]
    barrs = [b._data for b in bufs]
    x = np.zeros((batch, 3, image, image), np.float32)

    def fwd(parrs, barrs, x):
        with _bind(params, parrs), _bind(bufs, barrs), _tape.no_grad():
            return model(Tensor(x)).data

    model.eval()
    infer = trace_graph(
        f"resnet{depth}.infer_fwd", fwd, (parrs, barrs, x),
        meta={"expect_rewrites": ("conv-bn-fold",)})
    model.train()
    train = trace_graph(
        f"resnet{depth}.train_fwd", fwd, (parrs, barrs, x),
        meta={"expect_rewrites": ("stem-space-to-depth",
                                  "conv-nhwc-layout")})
    model.eval()
    return [infer, train]

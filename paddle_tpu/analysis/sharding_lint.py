"""SPMD sharding lint: the declared layout must be the intended one.

The silent-wrongness class here never crashes: a large weight whose
PartitionSpec quietly degenerated to replicated costs an all-gather's
worth of HBM on every device; an optimizer moment that missed its ZeRO
dp dim pays ``dp``-times the memory the stage was supposed to save; a
spec written against an axis the mesh does not have (``"mp"`` vs
``"tp"`` — the Engine and the functional llama stack use different
names) shards NOTHING while reading as if it did. All three are
host-side facts of the traced step plus its declared input specs
(``analysis/training_graphs.py`` tags every flat invar with the spec
``train_state_specs`` places it by), so they are statically checkable
with zero compiles.

Rules, each anchored to a concrete failure:

* **unknown-axis** (error): a declared spec (or a traced
  ``with_sharding_constraint`` site) names a mesh axis that does not
  exist or has degree 1 while the tensor is large — the spec is
  decorative, the array is actually replicated.
* **replicated-large** (error): an input tensor ≥ ``replicated_bytes``
  whose spec shards over no axis with degree > 1. Small tensors
  replicate by design (the planner's ``min_shard_size`` logic); big
  ones replicating silently is the all-gather-blowup bug.
* **zero-uncovered** (error): on a target declaring
  ``meta['zero_stage'] >= 1``, an optimizer-state leaf that
  ``zero_spec`` COULD dp-shard but whose declared spec carries no dp
  axis. Unshardable leaves (scalars, no dp-divisible free dim) are
  exempt — ``zero_spec`` returning None is the documented contract.

``audit_engine_plan`` is the Engine-side companion: it re-derives the
mpu usage hints for every parameter the auto-parallel Engine planned
and flags plan entries that contradict them (the hint path losing to
the dim-order heuristic is exactly the mesh-axis-mismatch bug class).
"""
from __future__ import annotations

from typing import List

from ..core.graph_trace import iter_jaxpr_eqns
from .framework import (Finding, GraphTarget, LintPass, Severity,
                        aval_nbytes as _nbytes, register_pass)

__all__ = ["ShardingLintPass", "audit_engine_plan", "spec_shard_factor"]


def _spec_axes(spec):
    """Flat mesh-axis names a PartitionSpec references."""
    axes = []
    for entry in tuple(spec):
        if entry is None:
            continue
        for ax in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            axes.append(ax)
    return axes


def spec_shard_factor(spec, mesh_axes) -> int:
    """How many ways ``spec`` splits an array on a mesh with
    ``mesh_axes`` (axis name -> size); 1 = fully replicated."""
    f = 1
    for ax in _spec_axes(spec):
        f *= int(mesh_axes.get(ax, 1))
    return f


@register_pass
class ShardingLintPass(LintPass):
    name = "sharding-lint"

    def __init__(self, replicated_bytes: int = 4 << 20):
        #: tensors at least this large must shard over SOME real axis
        self.replicated_bytes = int(replicated_bytes)

    def run(self, target: GraphTarget) -> List[Finding]:
        specs = target.meta.get("in_specs")
        if specs is None:
            return []  # serving targets carry no declared spec tree
        mesh_axes = dict(target.meta.get("mesh_axes", {}))
        labels = target.meta.get("invar_labels",
                                 [f"arg{i}" for i in range(len(specs))])
        classes = target.meta.get("invar_classes", ["?"] * len(specs))
        invars = target.jaxpr.jaxpr.invars
        findings: List[Finding] = []
        nontrivial = any(v > 1 for v in mesh_axes.values())

        for i, (v, spec) in enumerate(zip(invars, specs)):
            bytes_ = _nbytes(v.aval)
            bad_axes = [ax for ax in _spec_axes(spec)
                        if ax not in mesh_axes]
            if bad_axes:
                findings.append(self.finding(
                    target,
                    f"{labels[i]}: spec {tuple(spec)} names mesh "
                    f"axes {bad_axes} that do not exist on this mesh "
                    f"{mesh_axes} — the spec is decorative and the "
                    f"array is fully replicated"))
                continue
            if (nontrivial and bytes_ >= self.replicated_bytes
                    and spec_shard_factor(spec, mesh_axes) == 1
                    and classes[i] in ("param", "opt")):
                findings.append(self.finding(
                    target,
                    f"{labels[i]} ({bytes_ / 2**20:.1f} MiB, "
                    f"{classes[i]}) materializes fully replicated on "
                    f"every device (spec {tuple(spec)}) — an "
                    f"all-gather's worth of HBM per device; shard it "
                    f"or raise the planner's threshold deliberately"))

        # ---- zero coverage ------------------------------------------
        if int(target.meta.get("zero_stage", 0)) >= 1 \
                and mesh_axes.get("dp", 1) > 1:
            from ..distributed.sharding import zero_spec
            for i, (v, spec) in enumerate(zip(invars, specs)):
                if classes[i] != "opt":
                    continue
                shape = getattr(v.aval, "shape", ())
                if not shape:
                    continue  # scalars (step counts) replicate by design
                if "dp" in _spec_axes(spec):
                    continue
                if zero_spec(spec, shape, mesh_axes["dp"]) is None:
                    continue  # genuinely unshardable: documented exempt
                findings.append(self.finding(
                    target,
                    f"{labels[i]}: optimizer-state leaf "
                    f"{tuple(shape)} is zero_spec-shardable but its "
                    f"declared spec {tuple(spec)} carries no dp axis — "
                    f"ZeRO stage {target.meta['zero_stage']} pays "
                    f"{mesh_axes['dp']}x the memory it claims to save"))

        # ---- traced constraint sites --------------------------------
        for path, eqn in iter_jaxpr_eqns(target.jaxpr):
            if eqn.primitive.name != "sharding_constraint":
                continue
            sh = eqn.params.get("sharding")
            spec = getattr(sh, "spec", None)
            if spec is None:
                continue
            missing = [ax for ax in _spec_axes(spec)
                       if ax not in mesh_axes]
            if missing and mesh_axes:
                findings.append(self.finding(
                    target,
                    f"with_sharding_constraint names mesh axes "
                    f"{missing} absent from the target mesh "
                    f"{mesh_axes}", path=path))
        return findings


def audit_engine_plan(engine) -> List[Finding]:
    """Mesh-axis-mismatch audit of a prepared auto-parallel Engine: for
    every parameter owned by an mpu layer type, the plan entry must
    equal the usage hint the layer type declares (``Engine._mpu_hint``)
    — the planner's dim-order heuristic winning over an explicit
    Column/Row/Vocab declaration is a silent wrong-axis layout. Returns
    findings (empty = clean)."""
    engine.prepare()
    findings: List[Finding] = []
    if engine.strategy.mp_degree <= 1:
        return findings
    owners = engine._param_owners()
    name_of = {id(p): n for n, p in engine.model.named_parameters()}
    for name, p in engine.model.named_parameters():
        owner = owners.get(id(p))
        if owner is None:
            continue
        hint = engine._mpu_hint(p, owner)
        if hint is None:
            continue
        planned = engine.plan.get(name)
        if planned is None or tuple(planned) != tuple(hint):
            findings.append(Finding(
                pass_name="sharding-lint", severity=Severity.ERROR,
                graph=f"engine.plan[{name_of.get(id(p), name)}]",
                message=f"planned spec "
                        f"{tuple(planned) if planned is not None else None}"
                        f" contradicts the {type(owner).__name__} usage "
                        f"hint {tuple(hint)} — the mpu declaration must "
                        f"win over the size heuristic"))
    return findings

"""Subgraph pattern DSL for the jaxpr rewrite passes.

A pattern is a small dataflow tree written from the anchor (the last
equation of the idiom — the one whose output the rest of the graph
consumes) back toward its inputs:

    ``Op("mul", In("x"), Op("rsqrt", ...), commute=True)``

Matching walks BACKWARD from candidate anchor equations through the
producing equations at the *same jaxpr level* (``lax.scan`` bodies are
their own level — the rewriter recurses into control flow separately),
binding:

* ``In("name")``  — a pattern input: any value (var or literal) feeding
  the idiom from outside. Re-using a name (or the same node instance)
  at two operand positions requires the SAME value at both — how
  ``mul(x, x)`` expresses "the square of one thing".
* ``Lit("name")`` — a scalar ``jax.core.Literal`` operand, captured as
  a Python number (static to the replacement: eps, axis sizes).
* ``Op(prims, *operands, params=..., commute=...)`` — an equation whose
  primitive is in ``prims``; ``params`` entries are exact values or
  ``callable(value, eqn) -> bool`` predicates.
* ``Opt(prims, inner)`` / ``Via(prims, inner)`` — zero-or-one / zero-or-
  more single-input pass-through equations (convert/broadcast/reshape
  wrappers), so one pattern covers the f32 and bf16 spellings of an
  idiom.

A successful match yields the bound values plus the full matched
equation set; the matcher then enforces **exclusivity** — every matched
intermediate is consumed only inside the match — because the rewrite
deletes those equations, and a value someone else reads must keep its
producer. Overlapping candidates resolve largest-first (the bf16
variant of an idiom strictly contains its f32 core).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from jax._src import core as jax_core

from ..core.graph_trace import producer_map, var_use_sites

__all__ = ["In", "Lit", "Op", "Opt", "Via", "Match", "match_jaxpr"]


def _prims(p) -> Tuple[str, ...]:
    return (p,) if isinstance(p, str) else tuple(p)


class Pat:
    """Base pattern node."""
    capture: Optional[str] = None


@dataclass
class In(Pat):
    """A value feeding the pattern from outside (captured by name)."""
    name: str
    dtype: Any = None          # required numpy dtype kind/name, if any
    ndim: Optional[int] = None

    def ok(self, aval) -> bool:
        import numpy as np
        if self.dtype is not None:
            dt = getattr(aval, "dtype", None)
            if dt is None or np.dtype(dt) != np.dtype(self.dtype):
                return False
        if self.ndim is not None:
            if len(getattr(aval, "shape", ())) != self.ndim:
                return False
        return True


@dataclass
class Lit(Pat):
    """A scalar literal operand, captured as a Python number."""
    name: Optional[str] = None
    value: Any = None           # required exact value, if given


@dataclass
class Op(Pat):
    prims: Any
    operands: Tuple[Pat, ...]
    params: Optional[Dict[str, Any]] = None
    commute: bool = False
    capture: Optional[str] = None

    def __init__(self, prims, *operands, params=None, commute=False,
                 capture=None):
        self.prims = _prims(prims)
        self.operands = tuple(operands)
        self.params = params
        self.commute = commute
        self.capture = capture


@dataclass
class Opt(Pat):
    """Zero-or-ONE single-input wrapper equation around ``inner``."""
    prims: Any
    inner: Pat
    capture: Optional[str] = None

    def __post_init__(self):
        self.prims = _prims(self.prims)


@dataclass
class Via(Pat):
    """Zero-or-MORE single-input wrapper equations around ``inner``."""
    prims: Any
    inner: Pat
    capture: Optional[str] = None

    def __post_init__(self):
        self.prims = _prims(self.prims)


@dataclass
class Match:
    """One accepted occurrence of a pattern inside one jaxpr level."""
    anchor_idx: int
    eqn_idxs: frozenset               # all matched equations (anchor incl.)
    bindings: Dict[str, Any]          # In/Op captures -> Var | Literal
    statics: Dict[str, Any]           # Lit captures -> Python number
    out_vars: Tuple                   # the anchor equation's outvars
    pattern: Pat = None
    meta: Dict[str, Any] = field(default_factory=dict)

    @property
    def n_eqns(self) -> int:
        return len(self.eqn_idxs)


class _State:
    """Copy-on-branch match state (patterns are tiny; copies are cheap)."""

    __slots__ = ("bindings", "statics", "eqns", "nodes")

    def __init__(self, bindings=None, statics=None, eqns=None, nodes=None):
        self.bindings = dict(bindings or {})
        self.statics = dict(statics or {})
        self.eqns = set(eqns or ())
        self.nodes = dict(nodes or {})   # id(Pat) -> atom (instance reuse)

    def fork(self) -> "_State":
        return _State(self.bindings, self.statics, self.eqns, self.nodes)


def _same_atom(a, b) -> bool:
    if isinstance(a, jax_core.Literal) or isinstance(b, jax_core.Literal):
        return (isinstance(a, jax_core.Literal)
                and isinstance(b, jax_core.Literal)
                and type(a.val) is type(b.val) and bool(a.val == b.val))
    return a is b


def _bind(st: _State, name: Optional[str], atom) -> bool:
    if name is None:
        return True
    if name in st.bindings:
        return _same_atom(st.bindings[name], atom)
    st.bindings[name] = atom
    return True


def _params_ok(pat: Op, eqn) -> bool:
    if not pat.params:
        return True
    for k, want in pat.params.items():
        if k not in eqn.params:
            return False
        got = eqn.params[k]
        if callable(want):
            try:
                if not want(got, eqn):
                    return False
            except Exception:
                return False
        elif got != want:
            return False
    return True


def _match_node(pat: Pat, atom, producers, st: _State) -> Optional[_State]:
    """Try to match ``pat`` against ``atom`` (Var or Literal); returns
    the extended state or None."""
    prev = st.nodes.get(id(pat))
    if prev is not None:
        return st if _same_atom(prev, atom) else None

    if isinstance(pat, In):
        aval = getattr(atom, "aval", None)
        if isinstance(atom, jax_core.Literal):
            aval = jax_core.get_aval(atom.val)
        if not pat.ok(aval):
            return None
        if not _bind(st, pat.name, atom):
            return None
        st.nodes[id(pat)] = atom
        return st

    if isinstance(pat, Lit):
        if not isinstance(atom, jax_core.Literal):
            return None
        import numpy as np
        val = atom.val
        if np.ndim(val) != 0:
            return None
        val = val.item() if hasattr(val, "item") else val
        if pat.value is not None and val != pat.value:
            return None
        if pat.name is not None:
            if pat.name in st.statics and st.statics[pat.name] != val:
                return None
            st.statics[pat.name] = val
        st.nodes[id(pat)] = atom
        return st

    if isinstance(pat, (Opt, Via)):
        cur, walk = atom, st.fork()
        hops = 0
        max_hops = 1 if isinstance(pat, Opt) else 16
        while True:
            got = _match_node(pat.inner, cur, producers, walk.fork())
            if got is not None:
                if not _bind(got, pat.capture, atom):
                    return None
                got.nodes[id(pat)] = atom
                return got
            if hops >= max_hops:
                return None
            if isinstance(cur, jax_core.Literal):
                return None       # literals have no producer to walk
            prod = producers.get(cur)
            if prod is None:
                return None
            i, eqn = prod
            if (eqn.primitive.name not in pat.prims
                    or len(eqn.invars) != 1 or len(eqn.outvars) != 1):
                return None
            walk.eqns.add(i)
            cur = eqn.invars[0]
            hops += 1

    if isinstance(pat, Op):
        if isinstance(atom, jax_core.Literal):
            return None           # an Op's output is never a literal
        prod = producers.get(atom)
        if prod is None:
            return None
        i, eqn = prod
        if eqn.primitive.name not in pat.prims:
            return None
        if len(eqn.invars) != len(pat.operands):
            return None
        if not _params_ok(pat, eqn):
            return None
        orders = [pat.operands]
        if pat.commute and len(pat.operands) == 2:
            orders.append((pat.operands[1], pat.operands[0]))
        for order in orders:
            nxt = st.fork()
            nxt.eqns.add(i)
            ok = True
            for sub, arg in zip(order, eqn.invars):
                got = _match_node(sub, arg, producers, nxt)
                if got is None:
                    ok = False
                    break
                nxt = got
            if ok:
                if not _bind(nxt, pat.capture, atom):
                    continue
                nxt.nodes[id(pat)] = atom
                return nxt
        return None

    raise TypeError(f"unknown pattern node {type(pat).__name__}")


def _exclusive(m: Match, jaxpr, producers, uses) -> bool:
    """Every matched intermediate (output of a matched non-anchor eqn)
    must be consumed ONLY by matched eqns and must not be a jaxpr
    output — the rewrite deletes its producer."""
    for idx in m.eqn_idxs:
        if idx == m.anchor_idx:
            continue
        eqn = jaxpr.eqns[idx]
        for o in eqn.outvars:
            for site in uses.get(o, ()):
                if site == -1 or site not in m.eqn_idxs:
                    return False
    return True


def match_jaxpr(jaxpr, patterns: Sequence[Pat],
                validate: Optional[Callable[[Match, Any], bool]] = None
                ) -> List[Match]:
    """All non-overlapping, exclusive occurrences of ``patterns``
    (anchor variants of ONE idiom) at the top level of ``jaxpr``.
    Candidates are resolved largest-first so a wrapper variant beats
    its own core; ``validate(match, jaxpr)`` is the rule's cross-
    binding check (shape arithmetic the DSL cannot express)."""
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    producers = producer_map(jaxpr)
    uses = var_use_sites(jaxpr)
    candidates: List[Match] = []
    anchor_prims = set()
    for p in patterns:
        if not isinstance(p, Op):
            raise TypeError("a pattern's anchor must be an Op")
        anchor_prims |= set(p.prims)
    for i, eqn in enumerate(jaxpr.eqns):
        if eqn.primitive.name not in anchor_prims:
            continue
        if len(eqn.outvars) != 1:
            continue
        for p in patterns:
            st = _match_node(p, eqn.outvars[0], producers, _State())
            if st is None:
                continue
            m = Match(anchor_idx=i, eqn_idxs=frozenset(st.eqns),
                      bindings=st.bindings, statics=st.statics,
                      out_vars=tuple(eqn.outvars), pattern=p)
            if not _exclusive(m, jaxpr, producers, uses):
                continue
            if validate is not None and not validate(m, jaxpr):
                continue
            candidates.append(m)
            break   # first variant that fully matches this anchor wins
    # overlap resolution: larger matches first, then program order
    candidates.sort(key=lambda m: (-m.n_eqns, m.anchor_idx))
    taken: set = set()
    out: List[Match] = []
    for m in candidates:
        if m.eqn_idxs & taken:
            continue
        taken |= m.eqn_idxs
        out.append(m)
    out.sort(key=lambda m: m.anchor_idx)
    return out

"""Verified jaxpr rewrite passes: the analysis subsystem as optimizer.

PRs 4-5 taught the passes to *see* every flagship graph; this module
lets them *rewrite*. The shape of the thing:

* a :class:`~paddle_tpu.analysis.framework.RewritePass` declares a
  subgraph pattern (``analysis/patterns.py`` DSL), a replacement
  callable (a real Python function — a Pallas kernel entry point, a
  fused op), and an :class:`ExactnessContract`;
* :func:`rewrite_jaxpr` matches every registered pattern across a
  traced ``ClosedJaxpr`` — including inside ``lax.scan`` / ``pjit`` /
  ``cond`` / ``while`` bodies, rebuilt 1:1 via
  ``core.graph_trace.bind_rewritten`` — and returns a **re-jittable,
  re-differentiable callable**: a custom interpreter that executes the
  original equations except where a match fires, where it calls the
  replacement instead (CODA-style epilogue fusion / KForge-style
  kernel substitution, PAPERS.md arxiv 2605.19269 / 2606.02963);
* :func:`verify_rewrite` runs original-vs-rewritten on concrete seeded
  inputs and enforces the contract — bitwise for reassociation-free
  kernel substitutions, pinned tolerance otherwise — before a rewrite
  is allowed to ship (``tools/graph_lint.py --suite rewrite`` is the
  gate).

Concrete rewrites registered here:

* ``int8-epilogue-fuse`` — the dequantize-then-matmul idiom
  (``convert(int8 q) * scale -> dot_general``) becomes the fused
  dequant-in-matmul (``ops/fused/int8_matmul.int8_weight_matmul``:
  scale applied post-matmul, O(out) not O(in*out); routes to the
  authored Pallas int8*bf16 kernel when ``PADDLE_TPU_INT8_IMPL=pallas``).
* ``fused-rmsnorm`` — the jnp rms_norm formulation becomes the
  ``ops/pallas/fused_norm_rope.fused_rms_norm`` kernel (one HBM pass;
  same reductions in the same association, so nothing reassociates —
  but compiler clustering (FMA contraction and reduction tiling inside
  the compiled kernel body vs the eager eqn chain) rounds each of the
  square-sum/rsqrt/mul steps slightly differently. Measured worst case
  across a 420-config sweep (bf16+f32, widths 16-1024, input scales
  0.01-100): 4 units in the last place, so the contract is ``ulp<=4``).
"""
from __future__ import annotations

import functools
from collections import Counter
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.graph_trace import (bind_rewritten, eval_eqn, iter_jaxpr_eqns,
                                sub_jaxprs)
from .framework import (ExactnessContract, Finding, GraphTarget,
                        RewritePass, Severity, default_rewrites,
                        register_rewrite)
from .patterns import In, Lit, Match, Op, Opt, Via, match_jaxpr

__all__ = ["RewriteResult", "VerifyOutcome", "rewrite_jaxpr",
           "rewrite_target", "rewrite_callable", "verify_rewrite",
           "count_matches", "run_rewrite_suite",
           "Int8EpilogueFusePass", "FusedRmsNormPass",
           "DecodeTailFusePass"]

_CONVERT = "convert_element_type"
#: jaxpr-carrying primitives whose bodies the rewriter can rebuild;
#: anything else (custom_vjp bodies, shard_map, pallas_call) is opaque
#: — matches inside it neither fire nor count.
_REBUILDABLE = frozenset({"scan", "pjit", "closed_call", "core_call",
                          "cond", "while", "remat2", "checkpoint"})


def _closed(jaxpr):
    from jax._src import core as jax_core
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        return jaxpr
    return jax_core.ClosedJaxpr(jaxpr, ())


# ---------------------------------------------------------------------------
# the rewriting interpreter
# ---------------------------------------------------------------------------

class _Rewriter:
    """Matches per jaxpr level (cached) + the evaluating interpreter."""

    def __init__(self, rules: Sequence[RewritePass]):
        self.rules = list(rules)
        self._matches: Dict[int, List[Tuple[RewritePass, Match]]] = {}
        self._deep: Dict[int, bool] = {}
        self._keep: List[Any] = []   # id()-stability for cached jaxprs

    # -- matching ----------------------------------------------------
    def matches_for(self, jaxpr) -> List[Tuple[RewritePass, Match]]:
        key = id(jaxpr)
        hit = self._matches.get(key)
        if hit is not None:
            return hit
        self._keep.append(jaxpr)
        out: List[Tuple[RewritePass, Match]] = []
        taken: set = set()
        for rule in self.rules:
            ms = match_jaxpr(
                jaxpr, rule.patterns(),
                validate=lambda m, j, r=rule: (
                    r.validate(m, j) and _replacement_fits(r, m)))
            for m in ms:
                if m.eqn_idxs & taken:
                    continue
                taken |= m.eqn_idxs
                out.append((rule, m))
        self._matches[key] = out
        return out

    def deep(self, jaxpr) -> bool:
        """Any match at this level or inside a rebuildable body?"""
        key = id(jaxpr)
        hit = self._deep.get(key)
        if hit is not None:
            return hit
        self._deep[key] = False   # cycle guard (jaxprs are acyclic)
        found = bool(self.matches_for(jaxpr))
        if not found:
            for eqn in jaxpr.eqns:
                if eqn.primitive.name not in _REBUILDABLE:
                    continue
                for _, sub in sub_jaxprs(eqn):
                    if self.deep(sub):
                        found = True
                        break
                if found:
                    break
        self._deep[key] = found
        return found

    def count(self, jaxpr) -> Counter:
        """Static fire counts: matched sites at this level plus inside
        every rebuildable body (each textual site counts once, however
        many loop trips execute it)."""
        c: Counter = Counter()
        for rule, _ in self.matches_for(jaxpr):
            c[rule.name] += 1
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in _REBUILDABLE:
                for _, sub in sub_jaxprs(eqn):
                    c.update(self.count(sub))
        return c

    def sites(self, jaxpr):
        """Yield ``(level_jaxpr, rule, match)`` for every matched site
        at every rebuildable level — the unit local verification runs
        on."""
        for rule, m in self.matches_for(jaxpr):
            yield jaxpr, rule, m
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in _REBUILDABLE:
                for _, sub in sub_jaxprs(eqn):
                    yield from self.sites(sub)

    # -- evaluation --------------------------------------------------
    def run(self, closed, *args) -> List[Any]:
        from jax._src import core as jax_core
        closed = _closed(closed)
        jaxpr = closed.jaxpr
        if len(args) != len(jaxpr.invars):
            raise TypeError(
                f"rewritten program takes {len(jaxpr.invars)} flat "
                f"args, got {len(args)}")
        env: Dict[Any, Any] = {}

        def read(a):
            return a.val if isinstance(a, jax_core.Literal) else env[a]

        for v, c in zip(jaxpr.constvars, closed.consts):
            env[v] = c
        for v, a in zip(jaxpr.invars, args):
            env[v] = a

        level = self.matches_for(jaxpr)
        anchors = {m.anchor_idx: (rule, m) for rule, m in level}
        skip: set = set()
        for _, m in level:
            skip |= m.eqn_idxs - {m.anchor_idx}

        for i, eqn in enumerate(jaxpr.eqns):
            if i in skip:
                continue
            if i in anchors:
                rule, m = anchors[i]
                fn = rule.build(m.statics)
                vals = [read(m.bindings[n]) for n in rule.arg_names]
                out = fn(*vals)
                outs = (list(out) if isinstance(out, (tuple, list))
                        else [out])
                for v, val in zip(m.out_vars, outs):
                    env[v] = val
                continue
            invals = [read(a) for a in eqn.invars]
            subs = sub_jaxprs(eqn)
            if subs and any(self.deep(s) for _, s in subs):
                try:
                    outs = bind_rewritten(eqn, self.run, invals)
                except NotImplementedError:
                    outs = eval_eqn(eqn, invals)   # opaque body
            else:
                outs = eval_eqn(eqn, invals)
            for v, val in zip(eqn.outvars, outs):
                env[v] = val
        return [read(v) for v in jaxpr.outvars]


def _replacement_fits(rule: RewritePass, m: Match) -> bool:
    """The replacement must produce exactly the anchor's aval (shape
    AND dtype) — a match whose substitute would change the graph's
    types is not a match."""
    import jax
    from jax._src import core as jax_core
    try:
        args = []
        for n in rule.arg_names:
            atom = m.bindings[n]
            if isinstance(atom, jax_core.Literal):
                args.append(atom.val)
            else:
                args.append(jax.ShapeDtypeStruct(atom.aval.shape,
                                                 atom.aval.dtype))
        out = jax.eval_shape(rule.build(m.statics), *args)
        outs = jax.tree_util.tree_leaves(out)
        if len(outs) != len(m.out_vars):
            return False
        for o, v in zip(outs, m.out_vars):
            if (tuple(o.shape) != tuple(v.aval.shape)
                    or np.dtype(o.dtype) != np.dtype(v.aval.dtype)):
                return False
        return True
    except Exception:
        return False


def count_matches(jaxpr, rules: Optional[Sequence[RewritePass]] = None
                  ) -> Dict[str, int]:
    """Static per-rule match counts over ``jaxpr`` (rebuildable bodies
    included) — the idempotence probe: re-counting on a rewritten
    retrace must give zero."""
    rules = list(rules) if rules is not None else default_rewrites()
    rw = _Rewriter(rules)
    return dict(rw.count(_closed(jaxpr).jaxpr))


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

@dataclass
class RewriteResult:
    """A rewritten program plus everything the suite reports on it."""
    name: str
    closed: Any                           # the original ClosedJaxpr
    fn_flat: Callable                     # flat-args -> flat-outputs
    fired: Dict[str, int]                 # rule name -> matched sites
    eqns_before: int
    eqns_after: Optional[int] = None      # after retrace (None if skipped)
    residual: Optional[Dict[str, int]] = None   # matches on the retrace
    rewritten_closed: Any = None

    @property
    def idempotent(self) -> Optional[bool]:
        if self.residual is None:
            return None
        return not any(self.residual.values())


def rewrite_jaxpr(closed, rules: Optional[Sequence[RewritePass]] = None,
                  name: str = "graph", retrace: bool = False
                  ) -> RewriteResult:
    """Apply ``rules`` (default: every registered rewrite) to a traced
    ``ClosedJaxpr``. The result's ``fn_flat`` takes the jaxpr's flat
    invars and is re-jittable and re-differentiable — replacements are
    real Python functions (custom_vjp kernels keep their gradients).

    ``retrace=True`` re-traces the rewritten callable abstractly to
    report after-rewrite equation counts and the idempotence residual
    (matches still present — must be zero).
    """
    import jax
    closed = _closed(closed)
    rules = list(rules) if rules is not None else default_rewrites()
    rw = _Rewriter(rules)
    fired = dict(rw.count(closed.jaxpr))
    fn_flat = functools.partial(rw.run, closed)
    res = RewriteResult(
        name=name, closed=closed, fn_flat=fn_flat, fired=fired,
        eqns_before=sum(1 for _ in iter_jaxpr_eqns(closed)))
    if retrace:
        if any(fired.values()):
            avals = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                     for v in closed.jaxpr.invars]
            new_closed = jax.make_jaxpr(fn_flat)(*avals)
            res.rewritten_closed = new_closed
            res.eqns_after = sum(1 for _ in iter_jaxpr_eqns(new_closed))
            res.residual = count_matches(new_closed, rules)
        else:
            res.rewritten_closed = closed
            res.eqns_after = res.eqns_before
            res.residual = {}
    return res


def rewrite_target(target: GraphTarget,
                   rules: Optional[Sequence[RewritePass]] = None,
                   retrace: bool = True) -> RewriteResult:
    """:func:`rewrite_jaxpr` over a lint :class:`GraphTarget`."""
    return rewrite_jaxpr(target.jaxpr, rules, name=target.name,
                         retrace=retrace)


def rewrite_callable(fn: Callable,
                     rules: Optional[Sequence[str]] = None) -> Callable:
    """Wrap ``fn`` so every call traces it, applies the rewrites, and
    runs the rewritten program. Composes with ``jax.jit`` (the wrapper
    re-traces per jit trace — compile-time cost only) and with
    ``jax.grad`` (replacements carry their own VJPs). Keyword args are
    treated as static (closed over at trace time), matching how the
    serving engine partials its step functions."""
    rule_objs = None if rules is None else default_rewrites(tuple(rules))

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        import jax
        closed, out_shape = jax.make_jaxpr(
            lambda *a: fn(*a, **kwargs), return_shape=True)(*args)
        res = rewrite_jaxpr(closed, rule_objs)
        leaves = jax.tree_util.tree_leaves(args)
        out_flat = res.fn_flat(*leaves)
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(out_shape), out_flat)

    return wrapped


# ---------------------------------------------------------------------------
# verification: the exactness gate
# ---------------------------------------------------------------------------

@dataclass
class VerifyOutcome:
    ok: bool
    mode: str                   # "bitwise" | "rtol=.. atol=.." | "no-op"
    max_abs: float = 0.0
    max_rel: float = 0.0
    sites: int = 0              # locally verified match sites
    detail: str = ""


def _seed_value(aval, rng):
    """One seeded concrete value for an abstract value: small ints for
    integer avals (valid as tokens/lengths/page ids — XLA clamps
    gathers, and both sides see identical inputs), scaled normals for
    floats."""
    import jax.numpy as jnp
    sh = tuple(aval.shape)
    dt = aval.dtype
    if jnp.issubdtype(dt, jnp.integer):
        lo, hi = (-3, 4) if np.dtype(dt).itemsize == 1 else (0, 4)
        return jnp.asarray(rng.randint(lo, hi, size=sh), dt)
    if jnp.issubdtype(dt, jnp.bool_):
        return jnp.zeros(sh, bool)
    return jnp.asarray(
        rng.standard_normal(sh) * 0.5, jnp.float32).astype(dt)


def concrete_inputs(closed, seed: int = 0) -> List[Any]:
    """Seeded concrete values for a jaxpr's flat invars."""
    rng = np.random.RandomState(seed)
    return [_seed_value(v.aval, rng)
            for v in _closed(closed).jaxpr.invars]


def _ulp_distance(an: np.ndarray, bn: np.ndarray) -> int:
    """Max units-in-last-place distance between two same-dtype float
    arrays (IEEE lexicographic-ordering trick: bit patterns map to a
    monotonic integer line; +0 and -0 coincide). NaNs must coincide
    positionally; any mismatched NaN is an infinite distance."""
    nan_a, nan_b = np.isnan(an), np.isnan(bn)
    if (nan_a != nan_b).any():
        return np.iinfo(np.int64).max
    # all arithmetic stays in the UNSIGNED view dtype (modular), so the
    # mapping is exact for 8-byte floats too — int64 intermediates
    # would wrap at `1 << 63` and scramble the float64 ordering
    u = np.dtype(f"u{an.dtype.itemsize}")
    ai, bi = an.view(u), bn.view(u)
    sign = np.array(1, u) << np.array(8 * an.dtype.itemsize - 1, u)
    zero = np.array(0, u)
    ao = np.where(ai < sign, sign + ai, zero - ai)
    bo = np.where(bi < sign, sign + bi, zero - bi)
    d = np.where(ao >= bo, ao - bo, bo - ao)
    d = np.where(nan_a, zero, d)
    return int(d.max()) if d.size else 0


def _compare(contract: ExactnessContract, ref, got, label: str
             ) -> VerifyOutcome:
    """Compare two flat output lists under a contract."""
    if len(ref) != len(got):
        return VerifyOutcome(False, contract.describe(),
                             detail=f"{label}: output arity changed")
    max_abs = max_rel = 0.0
    for k, (a, b) in enumerate(zip(ref, got)):
        an, bn = np.asarray(a), np.asarray(b)
        if an.shape != bn.shape or an.dtype != bn.dtype:
            return VerifyOutcome(
                False, contract.describe(),
                detail=f"{label}: output {k} aval changed: "
                       f"{an.dtype}{an.shape} vs {bn.dtype}{bn.shape}")
        exact_kind = an.dtype.kind in "iub"
        if contract.bitwise or exact_kind:
            if an.tobytes() != bn.tobytes():
                af = an.astype(np.float64) if not exact_kind else an
                bf = bn.astype(np.float64) if not exact_kind else bn
                d = float(np.max(np.abs(af - bf)))
                return VerifyOutcome(
                    False, contract.describe(), max_abs=d,
                    detail=f"{label}: output {k} not bitwise-equal "
                           f"(max abs diff {d:.3e})")
        elif contract.ulp:
            d = _ulp_distance(an, bn)
            if d > contract.ulp:
                return VerifyOutcome(
                    False, contract.describe(),
                    max_abs=float(np.max(np.abs(
                        an.astype(np.float64) - bn.astype(np.float64)))),
                    detail=f"{label}: output {k} is {d} ulp from the "
                           f"original (contract allows {contract.ulp})")
        else:
            af = an.astype(np.float64)
            bf = bn.astype(np.float64)
            # Diffs over the jointly-finite positions only: a NaN (from
            # e.g. rsqrt of an adversarially-seeded negative variance)
            # would poison max() and report 0.0 for a failing site.
            fin = np.isfinite(af) & np.isfinite(bf)
            diff = np.abs(af[fin] - bf[fin])
            denom = np.maximum(np.abs(af[fin]), 1e-30)
            max_abs = max(max_abs, float(diff.max()) if diff.size
                          else 0.0)
            max_rel = max(max_rel, float((diff / denom).max())
                          if diff.size else 0.0)
            if not np.allclose(af, bf, rtol=contract.rtol,
                               atol=contract.atol, equal_nan=True):
                why = ("NaN/inf positions diverge"
                       if bool((np.isnan(af) != np.isnan(bf)).any()
                               or (np.isinf(af) != np.isinf(bf)).any())
                       else "outside tolerance")
                return VerifyOutcome(
                    False, contract.describe(), max_abs=max_abs,
                    max_rel=max_rel,
                    detail=f"{label}: output {k} {why}")
    return VerifyOutcome(True, contract.describe(), max_abs=max_abs,
                         max_rel=max_rel)


def verify_site(jaxpr, rule: RewritePass, m: Match,
                seeds: Sequence[int] = (0, 1)) -> VerifyOutcome:
    """Verify ONE matched site locally: evaluate the matched subgraph
    (original equations) vs the rule's replacement on seeded concrete
    values of the subgraph's own inputs, under the rule's contract.

    This is where a tolerance contract is *meaningful*: it bounds the
    error of the replaced computation itself. (A whole-graph tolerance
    check would instead measure how a downstream transformer amplifies
    a one-ulp weight difference — unbounded and graph-dependent, so the
    suite never does that; whole-graph equivalence is only asserted
    bitwise, when every firing rule is bitwise.)"""
    from jax._src import core as jax_core
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    idxs = sorted(m.eqn_idxs)
    produced = {o for i in idxs for o in jaxpr.eqns[i].outvars}
    # external inputs of the subgraph = vars read by matched eqns but
    # produced outside the match (named In captures among them)
    external: List[Any] = []
    for i in idxs:
        for a in jaxpr.eqns[i].invars:
            if (not isinstance(a, jax_core.Literal)
                    and a not in produced and a not in external):
                external.append(a)
    outcome = None
    for seed in seeds:
        rng = np.random.RandomState(seed)
        env: Dict[Any, Any] = {v: _seed_value(v.aval, rng)
                               for v in external}

        def read(a):
            return (a.val if isinstance(a, jax_core.Literal)
                    else env[a])

        for i in idxs:
            eqn = jaxpr.eqns[i]
            outs = eval_eqn(eqn, [read(a) for a in eqn.invars])
            for v, val in zip(eqn.outvars, outs):
                env[v] = val
        ref = [env[v] for v in m.out_vars]
        args = [read(m.bindings[n]) for n in rule.arg_names]
        got = rule.build(m.statics)(*args)
        got = list(got) if isinstance(got, (tuple, list)) else [got]
        outcome = _compare(rule.contract, ref, got,
                           f"{rule.name}@eqn{m.anchor_idx} seed {seed}")
        if not outcome.ok:
            return outcome
    return outcome if outcome is not None else VerifyOutcome(
        True, rule.contract.describe())


def _effective_contract(fired: Dict[str, int],
                        rules: Sequence[RewritePass]) -> ExactnessContract:
    """The loosest contract among the rules that fired: outputs are
    bitwise only if EVERY firing rewrite is bitwise; a tolerance
    (rtol/atol) rule dominates a ulp rule dominates bitwise."""
    by_name = {r.name: r for r in rules}
    rtol = atol = 0.0
    ulp = 0
    bitwise = True
    for name, n in fired.items():
        if not n:
            continue
        c = by_name[name].contract
        if not c.bitwise:
            bitwise = False
            ulp = max(ulp, c.ulp)
            rtol = max(rtol, c.rtol)
            atol = max(atol, c.atol)
    if rtol or atol:
        ulp = 0
    return ExactnessContract(bitwise=bitwise, ulp=ulp, rtol=rtol,
                             atol=atol)


def verify_rewrite(res: RewriteResult,
                   rules: Optional[Sequence[RewritePass]] = None,
                   seeds: Sequence[int] = (0, 1),
                   jit: bool = True) -> VerifyOutcome:
    """Enforce the exactness contracts of every rewrite that fired:

    1. **Per-site, always** — every matched subgraph is evaluated
       original-vs-replacement in isolation on seeded concrete values
       of its own inputs (:func:`verify_site`), under the owning rule's
       contract. A tolerance contract bounds THIS — the error of the
       replaced computation — not the whole program, through which a
       downstream transformer amplifies one-ulp differences without
       bound.
    2. **Whole-graph, when every firing rule is bitwise** — original vs
       rewritten program on seeded whole-graph inputs, byte-identical
       outputs required. ``jit=True`` compiles both sides, which also
       proves the rewritten callable is re-jittable.
    """
    import jax
    from jax._src import core as jax_core
    rules = list(rules) if rules is not None else default_rewrites()
    if not any(res.fired.values()):
        return VerifyOutcome(ok=True, mode="no-op",
                             detail="no rewrite fired")
    contract = _effective_contract(res.fired, rules)
    # 1. local: every matched site, under its own rule's contract
    rw = _Rewriter(rules)
    n_sites = 0
    max_abs = max_rel = 0.0
    for level, rule, m in rw.sites(res.closed.jaxpr):
        out = verify_site(level, rule, m, seeds)
        n_sites += 1
        max_abs = max(max_abs, out.max_abs)
        max_rel = max(max_rel, out.max_rel)
        if not out.ok:
            out.sites = n_sites
            return out
    # 2. global: only meaningful when the composition is bitwise
    if contract.bitwise:
        base = jax_core.jaxpr_as_fun(res.closed)
        new = res.fn_flat
        if jit:
            base, new = jax.jit(base), jax.jit(new)
        for seed in seeds:
            ins = concrete_inputs(res.closed, seed)
            out = _compare(contract, base(*ins), new(*ins),
                           f"whole-graph seed {seed}")
            if not out.ok:
                out.sites = n_sites
                return out
    return VerifyOutcome(True, contract.describe(), max_abs=max_abs,
                         max_rel=max_rel, sites=n_sites,
                         detail=f"{n_sites} sites verified locally"
                                + (", whole graph bitwise"
                                   if contract.bitwise else ""))


# ---------------------------------------------------------------------------
# concrete rewrites
# ---------------------------------------------------------------------------

def _is_matmul_dims(dn, eqn) -> bool:
    """dot_general contracting (last lhs dim, first rhs dim), no batch
    dims — the ``x @ w`` shape every projection in the repo uses."""
    try:
        (lc, rc), (lb, rb) = dn
        lhs_ndim = len(eqn.invars[0].aval.shape)
        return (tuple(lb) == () and tuple(rb) == ()
                and tuple(rc) == (0,) and tuple(lc) == (lhs_ndim - 1,))
    except Exception:
        return False


@register_rewrite
class Int8EpilogueFusePass(RewritePass):
    """Fuse dequantize-then-matmul into dequant-IN-matmul.

    The unfused idiom materialises the dense weight —
    ``w = (q.astype(f32) * scale).astype(dtype); x @ w`` — paying
    O(in*out) dequant traffic per call. The fused form computes
    ``(x @ q.astype(dtype)) * scale``: int8 values are exact in bf16,
    the per-output-channel scale moves across the contraction, and the
    epilogue costs O(out). Moving the scale reassociates the rounding,
    so the contract is a pinned tolerance, not bitwise."""

    name = "int8-epilogue-fuse"
    contract = ExactnessContract(bitwise=False, rtol=0.05, atol=0.1)
    arg_names = ("x", "q", "scale")

    def patterns(self):
        qf = Op(_CONVERT, In("q", dtype=np.int8))
        sb = Via((_CONVERT, "broadcast_in_dim", "reshape"),
                 In("scale", ndim=1), capture="scale_b")
        w = Via((_CONVERT,), Op("mul", qf, sb, commute=True))
        return [Op("dot_general", In("x"), w,
                   params={"dimension_numbers": _is_matmul_dims})]

    def validate(self, match, jaxpr) -> bool:
        q = match.bindings["q"]
        scale = match.bindings["scale"]
        qsh = tuple(q.aval.shape)
        if len(qsh) != 2:
            return False
        if tuple(scale.aval.shape) != (qsh[1],):
            return False
        # the scale must broadcast over the INPUT dim (per-output-
        # channel): the mul's scale-side operand (``scale_b`` — the
        # broadcast/reshape chain's outer value) has `out` as its
        # trailing dim and only 1s before it. A per-input-channel
        # scale ([in, 1]) is a different quantization scheme — the
        # epilogue cannot represent it, so it must NOT fire.
        sb = match.bindings.get("scale_b")
        if sb is not None and hasattr(sb, "aval"):
            sh = tuple(sb.aval.shape)
            if sh and (sh[-1] != qsh[1]
                       or any(d != 1 for d in sh[:-1])):
                return False
        return True

    def build(self, statics):
        from ..ops.fused.int8_matmul import fused_impl, int8_weight_matmul
        impl = fused_impl()
        return lambda x, q, scale: int8_weight_matmul(x, q, scale,
                                                      impl=impl)


def _last_axis(axes, eqn) -> bool:
    ndim = len(eqn.invars[0].aval.shape)
    return tuple(axes) == (ndim - 1,)


def _rms_core_pattern():
    """The jnp rms_norm idiom (models.llama.rms_norm and the
    functional layer path trace to the same eqn chain), ending at the
    pre-output-convert weight multiply. Shared by ``fused-rmsnorm``
    (which anchors here / on the trailing convert) and by
    ``decode-tail-fuse`` (which swallows it inside the serving tail)."""
    xf = Opt(_CONVERT, In("x"))
    mean = Op("div",
              Via(("broadcast_in_dim", "reshape"),
                  Op("reduce_sum", Op("mul", xf, xf),
                     params={"axes": _last_axis})),
              Lit("denom"))
    rstd = Op("rsqrt", Op("add", mean, Lit("eps")))
    y = Op("mul", xf, Via(("broadcast_in_dim", "reshape"), rstd),
           commute=True)
    wb = Via((_CONVERT, "broadcast_in_dim", "reshape"), In("w", ndim=1))
    return Op("mul", y, wb, commute=True)


@register_rewrite
class FusedRmsNormPass(RewritePass):
    """Substitute the fused Pallas rms_norm kernel for the jnp
    formulation (KForge-style kernel substitution against a kernel the
    repo already trusts — tests/test_pallas_kernels.py). The kernel
    performs the same reductions in the same association in f32; only
    compiler clustering (FMA contraction, reduction tiling across the
    fused kernel body vs the eager eqn chain) can round differently.
    The compounded drift through the square-sum -> rsqrt -> two-mul
    chain measures at most 4 units in the last place of the output
    dtype (420-config sweep: bf16+f32, widths 16-1024, input scales
    0.01-100; flagship shapes measure 2), so the contract pins
    ``ulp<=4``."""

    name = "fused-rmsnorm"
    contract = ExactnessContract(ulp=4)
    arg_names = ("x", "w")

    def patterns(self):
        core = _rms_core_pattern()
        return [Op(_CONVERT, core), core]

    def validate(self, match, jaxpr) -> bool:
        x = match.bindings["x"]
        w = match.bindings["w"]
        xsh = tuple(x.aval.shape)
        if not xsh or tuple(w.aval.shape) != (xsh[-1],):
            return False
        # the mean's denominator must be the normalised axis size —
        # a mean over anything else is not an rmsnorm
        if match.statics.get("denom") != xsh[-1]:
            return False
        # the kernel tiles rows in VMEM: rows must exist
        return int(np.prod(xsh[:-1], dtype=np.int64)) >= 1

    def build(self, statics):
        from ..ops.pallas.fused_norm_rope import fused_rms_norm
        eps = float(statics["eps"])
        return lambda x, w: fused_rms_norm(x, w, eps)


def _is_row_gather(dn, eqn) -> bool:
    """``x[idx]`` on a 2-D operand: one whole row per index."""
    return (tuple(dn.offset_dims) == (1,)
            and tuple(dn.collapsed_slice_dims) == (0,)
            and tuple(dn.start_index_map) == (0,))


@register_rewrite
class DecodeTailFusePass(RewritePass):
    """Fuse the serving decode tail — final rms_norm over the packed
    ``[T, D]`` stream, negative-wrapping last-row gather, lm_head
    matmul, f32 cast — into ``ops/fused/decode_tail.fused_decode_tail``,
    which hoists the gather ABOVE the norm (rms is row-local, so the
    reorder is exact per surviving row and the ``T−S`` dead rows are
    never normalised or written back) and runs the norm through the
    Pallas ``fused_rms_norm`` kernel.

    The pattern swallows the whole fused-rmsnorm core, so this pass
    must outrank it (priority 10 < 100): the tail's norm belongs to
    this match, while every per-layer norm still falls through to the
    plain substitution.

    Contract: the gather reorder is exact, and the substitution
    mirrors the matched dot's compute dtype (the AMP graphs cast the
    normed f32 rows DOWN to ``head.dtype`` before the matmul — an
    early version computed the dot in f32 and measured 2e-2 of
    phantom "drift" that was really extra precision). With dtypes
    mirrored the serving suite's seeded sites measure 0.0 drift; the
    rtol 1e-3 / atol 1e-3 pin is headroom for the kernel-vs-eager
    norm difference (≤4 ulp) amplified through the [D]-long dot.
    """

    name = "decode-tail-fuse"
    contract = ExactnessContract(rtol=1e-3, atol=1e-3)
    arg_names = ("x", "w", "idx", "head")
    priority = 10

    def patterns(self):
        normed = Opt(_CONVERT, _rms_core_pattern())
        idx = In("idx")
        wrapped = Op("select_n",
                     Op("lt", idx, Lit(value=0)),
                     idx,
                     Op("add", idx, Lit("nrows")))
        bidx = Via(("broadcast_in_dim", "reshape", _CONVERT), wrapped)
        rows = Op("gather", normed, bidx,
                  params={"dimension_numbers": _is_row_gather})
        mm = Op("dot_general", rows, In("head"),
                params={"dimension_numbers": _is_matmul_dims})
        return [Op(_CONVERT, mm), mm]

    def validate(self, match, jaxpr) -> bool:
        x = match.bindings["x"].aval
        w = match.bindings["w"].aval
        idx = match.bindings["idx"].aval
        head = match.bindings["head"].aval
        if len(x.shape) != 2 or tuple(w.shape) != (x.shape[-1],):
            return False
        if match.statics.get("denom") != x.shape[-1]:
            return False
        # the wrap's added constant must be THIS stream's row count
        if match.statics.get("nrows") != x.shape[0]:
            return False
        if len(idx.shape) != 1 or not np.issubdtype(idx.dtype,
                                                    np.integer):
            return False
        if len(head.shape) != 2 or head.shape[0] != x.shape[-1]:
            return False
        gather = next(jaxpr.eqns[i] for i in sorted(match.eqn_idxs)
                      if jaxpr.eqns[i].primitive.name == "gather")
        if tuple(gather.params["slice_sizes"]) != (1, x.shape[-1]):
            return False
        # the anchor may or may not carry the final f32 convert; the
        # replacement must reproduce the matched output dtype exactly
        match.statics["out_dtype"] = str(match.out_vars[0].aval.dtype)
        return True

    def build(self, statics):
        import jax.numpy as jnp
        from ..ops.fused.decode_tail import fused_decode_tail
        eps = float(statics["eps"])
        out_dtype = jnp.dtype(statics["out_dtype"])
        return lambda x, w, idx, head: fused_decode_tail(
            x, w, idx, head, eps=eps, out_dtype=out_dtype)


# ---------------------------------------------------------------------------
# the rewrite suite (graph_lint --suite rewrite)
# ---------------------------------------------------------------------------

def run_rewrite_suite(models=("llama",), verify: bool = True,
                      rules: Optional[Sequence[RewritePass]] = None,
                      targets: Optional[Sequence[GraphTarget]] = None,
                      serving_pool: Optional[Sequence[GraphTarget]] = None):
    """Rewrite + verify every flagship rewrite target (or explicit
    ``targets``). Returns ``(findings, table)`` where ``findings`` are
    framework Findings (ERROR when an expected rewrite did not fire,
    the rewriter is not idempotent, or a contract is violated) and
    ``table`` is the ``--json`` payload: per graph, which rewrites
    fired with before/after eqn counts and the verifier verdict."""
    rules = list(rules) if rules is not None else default_rewrites()
    if targets is None:
        from .rewrite_conv import resnet_rewrite_targets
        from .serving_graphs import rewrite_targets
        targets = rewrite_targets(models, serving_pool=(
            list(serving_pool) if serving_pool is not None else None))
        targets = list(targets) + resnet_rewrite_targets()
    findings: List[Finding] = []
    table: List[Dict[str, Any]] = []
    for target in targets:
        res = rewrite_target(target, rules)
        expect = set(target.meta.get("expect_rewrites", ()))
        fired = {k for k, v in res.fired.items() if v}
        row: Dict[str, Any] = {
            "graph": target.name, "fired": dict(res.fired),
            "eqns_before": res.eqns_before, "eqns_after": res.eqns_after,
            "idempotent": res.idempotent,
        }
        for missing in sorted(expect - fired):
            findings.append(Finding(
                pass_name="rewrite-suite", severity=Severity.ERROR,
                graph=target.name,
                message=f"expected rewrite {missing!r} did not fire "
                        f"(fired: {sorted(fired) or 'none'})"))
        if res.idempotent is False:
            findings.append(Finding(
                pass_name="rewrite-suite", severity=Severity.ERROR,
                graph=target.name,
                message=f"rewriter is not idempotent: re-running on the "
                        f"rewritten graph still matches {res.residual}"))
        if verify:
            out = verify_rewrite(res, rules)
            row["verify"] = {"ok": out.ok, "contract": out.mode,
                             "max_abs": out.max_abs,
                             "max_rel": out.max_rel}
            if not out.ok:
                findings.append(Finding(
                    pass_name="rewrite-suite", severity=Severity.ERROR,
                    graph=target.name,
                    message=f"exactness contract ({out.mode}) violated: "
                            f"{out.detail}"))
        findings.append(Finding(
            pass_name="rewrite-suite", severity=Severity.INFO,
            graph=target.name,
            message=f"fired {dict(res.fired)}, eqns "
                    f"{res.eqns_before}->{res.eqns_after}"
                    + (f", verified {row['verify']['contract']}"
                       if verify and "verify" in row else "")))
        table.append(row)
    return findings, table


# registers the ResNet conv passes (conv-bn-fold, stem-space-to-depth,
# conv-nhwc-layout) alongside the passes defined above — one import
# site, so building rules from REWRITE_REGISTRY always sees all of them
from . import rewrite_conv as _rewrite_conv  # noqa: E402,F401

"""Concurrency analysis for the threaded serving stack.

Three cooperating layers (ISSUE 19):

1. **Static guarded-by lint** (CC001): per-class AST pass over
   ``paddle_tpu/serving/`` that discovers every ``threading.Lock`` /
   ``RLock`` attribute (seen through the :func:`~paddle_tpu.serving.
   locktrace.wrap_lock` construction hook), computes which ``self.*``
   attributes are accessed under ``with self._lock`` vs. outside it,
   and errors on accesses reachable from a thread-entry function
   (``Thread(target=...)``, RPC pump callbacks, public API methods)
   that bypass the inferred owning lock. Justified lock-free reads are
   sanctioned per-line (``# noqa: CC001(reason)``) or per-attribute
   (class-level ``_CC_LOCK_FREE_READS = {"attr": "reason"}`` — reads
   only; writes still flag).
2. **Static lock-order analysis** (CC003): the acquisition graph —
   which lock ROLES (``"ServingEngine._tick_lock"``) are taken while
   which are held, across classes via ``self.attr = KnownClass(...)``
   attribute types — with cycles (and plain-``Lock`` re-acquisition)
   reported as deadlocks. The runtime twin lives in
   ``paddle_tpu/serving/locktrace.py`` (:class:`LockTracer`).
3. **Deterministic interleaving fuzzer**: seeded schedule
   perturbation replaying the fleet drain / crash / migration
   protocols against the REAL fleet/router/replica code with a
   stdlib fake engine, asserting exactly-once / zero-drop / bitwise
   invariants under every seed (:func:`fuzz_fleet_scenario`).

Every rule is mutation-tested: :func:`mutate_remove_with` deletes a
real lock acquisition on a COPY of the source, and the tests assert
the static pass and the fuzzer both catch it.

Scope and honest limits (also in docs/ANALYSIS.md): the guarded-by
pass is per-class (``self.*`` state only — cross-object accesses like
``rep.engine.x`` are the callee class's problem), module-level and
function-local locks (``transport._spawn_lock``, worker relay ``reg``)
are out of scope, and ``threading.Condition`` attributes are treated
as thread-safe primitives rather than locks (their mutex cannot be
wrapped or modelled without tracking ``wait()`` release semantics).

Module-level imports are stdlib-only; the fuzz harness imports the
serving fleet lazily inside the function.
"""
from __future__ import annotations

import ast
import os
import re
import threading
import time
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

__all__ = [
    "RULES", "analyze_source", "analyze_sources", "analyze_tree",
    "check_tree", "mutate_remove_with",
    "DEMO_COUNTER_SRC", "DEMO_ORDER_SRC",
    "run_counter_demo", "run_order_demo", "fuzz_fleet_scenario",
]

RULES = {
    "CC001": "lock-free access to a lock-guarded attribute",
    "CC002": "threading.Thread(...) must pass name= and daemon= "
             "(enforced by source_lint)",
    "CC003": "lock acquisition-order cycle",
    "CC004": "CC-series noqa without a justification",
}

_NOQA_CC = re.compile(r"#\s*noqa:\s*(CC\d{3})\s*(?:\(([^)]*)\))?")
_LOCK_CTORS = {"Lock", "RLock"}
# Thread-safe primitives: attributes built from these ctors are never
# guarded-by candidates (they synchronize themselves).
_SAFE_CTORS = {"Event", "Condition", "Semaphore", "BoundedSemaphore",
               "Barrier", "local", "Queue", "SimpleQueue", "LifoQueue",
               "PriorityQueue", "count"}
# self.ATTR.m(...) with m here counts as a WRITE of ATTR.
_MUTATORS = {"append", "appendleft", "add", "extend", "insert", "pop",
             "popleft", "popitem", "remove", "discard", "clear",
             "update", "setdefault", "sort", "reverse"}


# ===================================================================
# data model
# ===================================================================

@dataclass
class _Access:
    attr: str
    kind: str               # "read" | "write"
    line: int
    held: FrozenSet[str]    # lexically held lock attrs at the access


@dataclass
class _MethodInfo:
    name: str
    entry: bool = False     # thread entry / stored callback
    public: bool = False
    accesses: List[_Access] = field(default_factory=list)
    # (callee_method, lexical_held, line)
    self_calls: List[Tuple[str, FrozenSet[str], int]] = \
        field(default_factory=list)
    # (self_attr, method, lexical_held, line)
    attr_calls: List[Tuple[str, str, FrozenSet[str], int]] = \
        field(default_factory=list)
    # (lock_attr, lexical_held_before, line)
    acquires: List[Tuple[str, FrozenSet[str], int]] = \
        field(default_factory=list)


@dataclass
class _ClassInfo:
    name: str
    path: str
    line: int
    method_names: Set[str] = field(default_factory=set)
    locks: Dict[str, str] = field(default_factory=dict)    # attr->kind
    safe: Set[str] = field(default_factory=set)
    attr_ctor: Dict[str, str] = field(default_factory=dict)
    lock_free_reads: Dict[str, str] = field(default_factory=dict)
    # method -> (lock_attr, reason): caller-must-hold contracts the
    # entry detector cannot see (e.g. a callback the callee only
    # fires while holding the lock)
    requires: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    entries: Set[str] = field(default_factory=set)
    methods: Dict[str, _MethodInfo] = field(default_factory=dict)


def _self_attr(node) -> Optional[str]:
    """``self.X`` -> ``"X"`` (one level only), else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _call_names(value: ast.AST) -> List[str]:
    out = []
    for n in ast.walk(value):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute):
                out.append(f.attr)
            elif isinstance(f, ast.Name):
                out.append(f.id)
    return out


# ===================================================================
# per-method scanner
# ===================================================================

class _Scan:
    """Recursive statement walker carrying the lexically-held lock
    set. One instance per (real or synthetic) method."""

    def __init__(self, ci: _ClassInfo, mname: str, record: bool):
        self.ci = ci
        self.mi = ci.methods[mname]
        self.record = record
        # shared across a real method and its nested synthetics so a
        # later ``Thread(target=_go)`` resolves the local fn name
        self.entry_locals: Set[str] = set()
        self.local_fns: Dict[str, str] = {}

    def run(self, body: List[ast.stmt]) -> None:
        for s in body:
            self.stmt(s, frozenset())
        for nm in self.entry_locals:
            syn = self.local_fns.get(nm)
            if syn and syn in self.ci.methods:
                self.ci.methods[syn].entry = True

    # ----------------------------------------------------- statements
    def stmt(self, node: ast.stmt, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new = set(held)
            for item in node.items:
                lk = _self_attr(item.context_expr)
                if lk is not None and lk in self.ci.locks:
                    if self.record:
                        self.mi.acquires.append(
                            (lk, frozenset(held),
                             item.context_expr.lineno))
                    new.add(lk)
                else:
                    self.expr(item.context_expr, held)
                if item.optional_vars is not None:
                    self._write(item.optional_vars, held)
            for s in node.body:
                self.stmt(s, frozenset(new))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            syn = f"{self.mi.name}.<{node.name}>"
            self.ci.methods[syn] = _MethodInfo(name=syn)
            sub = _Scan(self.ci, syn, True)
            sub.entry_locals = self.entry_locals
            sub.local_fns = self.local_fns
            self.local_fns[node.name] = syn
            for s in node.body:
                sub.stmt(s, frozenset())
            for d in node.decorator_list:
                self.expr(d, held)
        elif isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Lambda) and any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in node.targets):
                # lambda stored on an object: deferred callback — runs
                # on some other thread with NO locks held
                self._synthetic_lambda(node.value, entry=True)
            else:
                self.expr(node.value, held)
            for t in node.targets:
                self._write(t, held)
        elif isinstance(node, ast.AugAssign):
            self.expr(node.value, held)
            self._write(node.target, held, also_read=True)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self.expr(node.value, held)
                self._write(node.target, held)
        else:
            self._generic(node, held)

    def _generic(self, node: ast.AST, held: FrozenSet[str]) -> None:
        for ch in ast.iter_child_nodes(node):
            self._dispatch(ch, held)

    def _dispatch(self, ch: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(ch, ast.stmt):
            self.stmt(ch, held)
        elif isinstance(ch, ast.expr):
            self.expr(ch, held)
        else:   # ExceptHandler, comprehension, keyword, withitem, ...
            self._generic(ch, held)

    # ---------------------------------------------------- expressions
    def expr(self, node: Optional[ast.AST],
             held: FrozenSet[str]) -> None:
        if node is None:
            return
        if isinstance(node, ast.Call):
            self._call(node, held)
            return
        if isinstance(node, ast.Attribute):
            x = _self_attr(node)
            if x is not None:
                if x in self.ci.method_names:
                    # bare ``self.m`` reference: callback entry AND a
                    # potential call site with the current held set
                    self.ci.entries.add(x)
                    if self.record:
                        self.mi.self_calls.append(
                            (x, frozenset(held), node.lineno))
                else:
                    kind = "write" if isinstance(node.ctx, ast.Store) \
                        else "read"
                    self._access(x, kind, node.lineno, held)
                return
            self.expr(node.value, held)
            return
        if isinstance(node, ast.Lambda):
            # inline lambda (sort key, map fn): assume immediate call
            # under the current held set; STORED lambdas are routed to
            # _synthetic_lambda by the Assign/keyword handlers
            self.expr(node.body, held)
            return
        self._generic(node, held)

    def _call(self, node: ast.Call, held: FrozenSet[str]) -> None:
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if fname == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    t = _self_attr(kw.value)
                    if t is not None and t in self.ci.method_names:
                        self.ci.entries.add(t)
                    elif isinstance(kw.value, ast.Name):
                        self.entry_locals.add(kw.value.id)
        handled_func = False
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self":
                if f.attr in self.ci.method_names:
                    if self.record:
                        self.mi.self_calls.append(
                            (f.attr, frozenset(held), node.lineno))
                else:
                    # calling a stored callback / data attribute
                    self._access(f.attr, "read", node.lineno, held)
                handled_func = True
            else:
                a = _self_attr(base)
                if a is not None:
                    if a in self.ci.locks or a in self.ci.safe:
                        pass    # self._cond.notify() / queue.put(...)
                    else:
                        kind = "write" if f.attr in _MUTATORS \
                            else "read"
                        self._access(a, kind, node.lineno, held)
                        if self.record:
                            self.mi.attr_calls.append(
                                (a, f.attr, frozenset(held),
                                 node.lineno))
                    handled_func = True
        if not handled_func:
            self.expr(f, held)
        for arg in node.args:
            self.expr(arg, held)
        for kw in node.keywords:
            if isinstance(kw.value, ast.Lambda) and kw.arg and (
                    kw.arg == "target" or kw.arg.startswith("on_")):
                self._synthetic_lambda(kw.value, entry=True)
            else:
                self.expr(kw.value, held)

    def _synthetic_lambda(self, lam: ast.Lambda, entry: bool) -> None:
        syn = f"{self.mi.name}.<lambda@{lam.lineno}>"
        while syn in self.ci.methods:
            syn += "'"
        self.ci.methods[syn] = _MethodInfo(name=syn, entry=entry)
        sub = _Scan(self.ci, syn, True)
        sub.entry_locals = self.entry_locals
        sub.local_fns = self.local_fns
        sub.expr(lam.body, frozenset())

    # ------------------------------------------------------- accesses
    def _access(self, x: str, kind: str, line: int,
                held: FrozenSet[str]) -> None:
        if not self.record:
            return
        if x in self.ci.locks or x in self.ci.safe or \
                x in self.ci.method_names:
            return
        self.mi.accesses.append(_Access(x, kind, line, frozenset(held)))

    def _root_self_attr(self, node: ast.AST,
                        held: FrozenSet[str]) -> Optional[str]:
        """Root attr of ``self.X[...].y`` chains; scans subscript
        indices as reads along the way."""
        prev: Optional[ast.Attribute] = None
        cur = node
        while True:
            if isinstance(cur, ast.Subscript):
                self.expr(cur.slice, held)
                cur = cur.value
            elif isinstance(cur, ast.Attribute):
                prev = cur
                cur = cur.value
            else:
                break
        if isinstance(cur, ast.Name) and cur.id == "self" and \
                prev is not None:
            return prev.attr
        return None

    def _write(self, t: ast.AST, held: FrozenSet[str],
               also_read: bool = False) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._write(e, held, also_read)
            return
        if isinstance(t, ast.Starred):
            self._write(t.value, held, also_read)
            return
        if isinstance(t, (ast.Attribute, ast.Subscript)):
            x = self._root_self_attr(t, held)
            if x is not None:
                if also_read:
                    self._access(x, "read", t.lineno, held)
                self._access(x, "write", t.lineno, held)
            else:
                # non-self target (obj.attr = .., d[k] = ..): reads
                if isinstance(t, ast.Subscript):
                    self.expr(t.value, held)
                    self.expr(t.slice, held)
                else:
                    self.expr(t.value, held)
        # bare Name targets are locals: ignored


# ===================================================================
# per-class scan
# ===================================================================

def _scan_class(node: ast.ClassDef, path: str) -> _ClassInfo:
    ci = _ClassInfo(name=node.name, path=path, line=node.lineno)
    # pass 1: method names, lock/safe/typed attrs, declarations
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ci.method_names.add(item.name)
        elif isinstance(item, ast.Assign):
            for t in item.targets:
                if isinstance(t, ast.Name) and \
                        t.id == "_CC_LOCK_FREE_READS" and \
                        isinstance(item.value, ast.Dict):
                    for k, v in zip(item.value.keys,
                                    item.value.values):
                        if isinstance(k, ast.Constant) and \
                                isinstance(v, ast.Constant):
                            ci.lock_free_reads[str(k.value)] = \
                                str(v.value)
                elif isinstance(t, ast.Name) and \
                        t.id == "_CC_REQUIRES" and \
                        isinstance(item.value, ast.Dict):
                    for k, v in zip(item.value.keys,
                                    item.value.values):
                        if isinstance(k, ast.Constant) and \
                                isinstance(v, (ast.List, ast.Tuple)) \
                                and len(v.elts) == 2 and all(
                                    isinstance(e, ast.Constant)
                                    for e in v.elts):
                            ci.requires[str(k.value)] = (
                                str(v.elts[0].value),
                                str(v.elts[1].value))
    for n in ast.walk(node):
        if not isinstance(n, ast.Assign):
            continue
        names = None
        for t in n.targets:
            x = _self_attr(t)
            if x is None:
                continue
            if names is None:
                names = _call_names(n.value)
            if any(c in _LOCK_CTORS for c in names):
                ci.locks[x] = "RLock" if "RLock" in names else "Lock"
            elif any(c in _SAFE_CTORS for c in names):
                ci.safe.add(x)
            elif isinstance(n.value, ast.Call):
                f = n.value.func
                ci.attr_ctor[x] = f.attr if isinstance(
                    f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else "")
    # pass 2: scan each direct method
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mi = ci.methods.setdefault(item.name, _MethodInfo(item.name))
        mi.public = not item.name.startswith("_")
        # __init__ runs single-threaded before the object escapes:
        # its DIRECT accesses are exempt; nested fns (worker loops
        # spawned from __init__) are still scanned fully.
        sc = _Scan(ci, item.name, record=(item.name != "__init__"))
        sc.run(item.body)
    for e in ci.entries:
        if e in ci.methods:
            ci.methods[e].entry = True
    return ci


# ===================================================================
# whole-tree analysis
# ===================================================================

def _inherited(ci: _ClassInfo) -> Dict[str, Optional[FrozenSet[str]]]:
    """Per-method inherited-held set: the intersection over all call
    sites of (caller_inherited | site_held). Entry + public methods
    are roots pinned at the empty set (any thread may call them with
    nothing held). ``None`` = unreachable from any root."""
    pinned = {m: frozenset({lk}) for m, (lk, _r) in
              ci.requires.items() if lk in ci.locks}
    roots = {m for m, mi in ci.methods.items()
             if (mi.entry or mi.public) and m not in pinned}
    inh: Dict[str, Optional[FrozenSet[str]]] = {
        m: (frozenset() if m in roots else None) for m in ci.methods}
    inh.update(pinned)
    changed = True
    while changed:
        changed = False
        for mname, mi in ci.methods.items():
            cur = inh[mname]
            if cur is None:
                continue
            for callee, held, _ln in mi.self_calls:
                if callee not in inh or callee in roots \
                        or callee in pinned:
                    continue
                eff = cur | held
                old = inh[callee]
                new = eff if old is None else (old & eff)
                if new != old:
                    inh[callee] = new
                    changed = True
    return inh


def _guards(ci: _ClassInfo,
            inh: Dict[str, Optional[FrozenSet[str]]]
            ) -> Dict[str, str]:
    """attr -> inferred owning lock. Candidate iff the attr is ever
    written (outside __init__) AND some access — read or write — runs
    with a lock held (so deleting the lock from the one writer still
    leaves a locked READ pinning the guard: mutation-testable)."""
    written = set()
    cnt: Dict[str, Counter] = {}
    for mname, mi in ci.methods.items():
        base = inh.get(mname) or frozenset()
        for acc in mi.accesses:
            if acc.kind == "write":
                written.add(acc.attr)
            eff = (acc.held | base) & set(ci.locks)
            if eff:
                c = cnt.setdefault(acc.attr, Counter())
                for lk in eff:
                    c[lk] += 1
    return {a: cnt[a].most_common(1)[0][0]
            for a in written if a in cnt}


def _sccs(nodes, adj):
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstk: Set[str] = set()
    stk: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strong(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stk.append(v)
        onstk.add(v)
        for w in adj.get(v, ()):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in onstk:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stk.pop()
                onstk.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(comp)

    for v in sorted(nodes):
        if v not in index:
            strong(v)
    return out


def analyze_sources(items: List[Tuple[str, str]]) -> dict:
    """Run the full static suite over ``[(path, source), ...]``.
    Returns the suite dict (see :func:`check_tree`)."""
    classes: List[_ClassInfo] = []
    noqa: Dict[str, Dict[int, List[Tuple[str, str]]]] = {}
    for path, src in items:
        tree = ast.parse(src, filename=path)
        for ln, line in enumerate(src.splitlines(), 1):
            for m in _NOQA_CC.finditer(line):
                noqa.setdefault(path, {}).setdefault(ln, []).append(
                    (m.group(1), (m.group(2) or "").strip()))
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                classes.append(_scan_class(node, path))

    findings: Set[Tuple[str, str, int, str]] = set()
    inh_by_class: Dict[str, Dict[str, Optional[FrozenSet[str]]]] = {}

    # ---- CC001 guarded-by ------------------------------------------
    for ci in classes:
        if not ci.locks:
            continue
        inh = inh_by_class[ci.name] = _inherited(ci)
        guards = _guards(ci, inh)
        for mname, mi in ci.methods.items():
            base = inh.get(mname)
            if base is None:        # not reachable from any entry
                continue
            for acc in mi.accesses:
                g = guards.get(acc.attr)
                if g is None or g in (acc.held | base):
                    continue
                if acc.kind == "read" and \
                        acc.attr in ci.lock_free_reads:
                    continue
                findings.add((
                    "CC001", ci.path, acc.line,
                    f"lock-free {acc.kind} of {ci.name}.{acc.attr} "
                    f"in {mname}() (guarded by {ci.name}.{g})"))

    # ---- CC003 lock order ------------------------------------------
    registry = {ci.name: ci for ci in classes}
    lock_kind = {f"{ci.name}.{a}": k
                 for ci in classes for a, k in ci.locks.items()}
    attr_types = {ci.name: {a: c for a, c in ci.attr_ctor.items()
                            if c in registry}
                  for ci in classes}
    acq: Dict[Tuple[str, str], Set[str]] = {}
    for ci in classes:
        for mname, mi in ci.methods.items():
            acq[(ci.name, mname)] = {
                f"{ci.name}.{lk}" for lk, _h, _ln in mi.acquires}
    changed = True
    while changed:
        changed = False
        for ci in classes:
            for mname, mi in ci.methods.items():
                cur = acq[(ci.name, mname)]
                n0 = len(cur)
                for callee, _h, _ln in mi.self_calls:
                    cur |= acq.get((ci.name, callee), set())
                for a, meth, _h, _ln in mi.attr_calls:
                    tcls = attr_types.get(ci.name, {}).get(a)
                    if tcls is not None:
                        cur |= acq.get((tcls, meth), set())
                if len(cur) != n0:
                    changed = True

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def _edge(a: str, b: str, path: str, ln: int) -> None:
        if a == b and lock_kind.get(a) == "RLock":
            return              # RLock re-entry is legal
        edges.setdefault((a, b), (path, ln))

    for ci in classes:
        inh = inh_by_class.get(ci.name, {})
        for mname, mi in ci.methods.items():
            base = inh.get(mname) or frozenset()
            for lk, held, ln in mi.acquires:
                for h in held | base:
                    _edge(f"{ci.name}.{h}", f"{ci.name}.{lk}",
                          ci.path, ln)
            for callee, held, ln in mi.self_calls:
                eff = held | base
                if not eff:
                    continue
                for r in acq.get((ci.name, callee), ()):
                    for h in eff:
                        _edge(f"{ci.name}.{h}", r, ci.path, ln)
            for a, meth, held, ln in mi.attr_calls:
                eff = held | base
                if not eff:
                    continue
                tcls = attr_types.get(ci.name, {}).get(a)
                if tcls is None:
                    continue
                for r in acq.get((tcls, meth), ()):
                    for h in eff:
                        _edge(f"{ci.name}.{h}", r, ci.path, ln)

    adj: Dict[str, Set[str]] = {}
    nodes: Set[str] = set()
    for (a, b) in edges:
        nodes.add(a)
        nodes.add(b)
        adj.setdefault(a, set()).add(b)
    cycles = [sorted(c) for c in _sccs(nodes, adj) if len(c) > 1]
    cycles += [[v] for v in sorted(nodes) if (v, v) in edges]
    for cyc in cycles:
        first = min((a, b) for (a, b) in edges
                    if a in cyc and b in cyc)
        path, ln = edges[first]
        findings.add((
            "CC003", path, ln,
            "lock-order cycle: " + " -> ".join(cyc + [cyc[0]])))

    # ---- noqa discipline -------------------------------------------
    suppressed: List[dict] = []
    kept: List[dict] = []
    for rule, path, ln, msg in sorted(findings):
        codes = dict(noqa.get(path, {}).get(ln, []))
        if rule in codes:
            suppressed.append({"rule": rule, "path": path,
                               "line": ln, "message": msg,
                               "reason": codes[rule]})
        else:
            kept.append({"rule": rule, "path": path, "line": ln,
                         "message": msg})
    for path, per_line in sorted(noqa.items()):
        for ln, ents in sorted(per_line.items()):
            for code, reason in ents:
                if not reason:
                    kept.append({
                        "rule": "CC004", "path": path, "line": ln,
                        "message": f"noqa: {code} lacks a "
                                   f"justification (use # noqa: "
                                   f"{code}(reason))"})
    kept.sort(key=lambda f: (f["path"], f["line"], f["rule"]))
    by_rule = Counter(f["rule"] for f in kept)
    lfr = [{"class": ci.name, "path": ci.path, "attr": a,
            "reason": r}
           for ci in classes
           for a, r in sorted(ci.lock_free_reads.items())]
    reqs = [{"class": ci.name, "path": ci.path, "method": m,
             "lock": lk, "reason": r}
            for ci in classes
            for m, (lk, r) in sorted(ci.requires.items())]
    return {
        "files": len(items),
        "classes": sorted(ci.name for ci in classes if ci.locks),
        "locks": dict(sorted(lock_kind.items())),
        "findings": kept,
        "by_rule": {r: by_rule.get(r, 0) for r in RULES},
        "suppressed": suppressed,
        "lock_free_reads": lfr,
        "requires": reqs,
        "lock_order": {
            "edges": [[a, b, p, ln]
                      for (a, b), (p, ln) in sorted(edges.items())],
            "cycles": cycles,
        },
        "errors": len(kept),
    }


def analyze_source(src: str, path: str = "<src>") -> dict:
    return analyze_sources([(path, src)])


def _serving_root() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "serving")


def analyze_tree(root: Optional[str] = None) -> dict:
    root = root or _serving_root()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    items = []
    for dirpath, _dirs, files in sorted(os.walk(root)):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            with open(p, "r", encoding="utf-8") as fh:
                items.append((os.path.relpath(p, repo), fh.read()))
    return analyze_sources(items)


def check_tree(root: Optional[str] = None) -> dict:
    """The ``graph_lint --suite concurrency`` entry point: static
    guarded-by + lock-order over ``paddle_tpu/serving/``."""
    return analyze_tree(root)


# ===================================================================
# mutation helper
# ===================================================================

def mutate_remove_with(src: str, method: Optional[str] = None,
                       nth: int = 0) -> str:
    """Return ``src`` with the ``nth`` ``with self.<attr>:`` block
    (inside ``method``, or anywhere when None) replaced by its bare
    body — the seeded race for mutation tests."""
    tree = ast.parse(src)
    state = {"i": 0, "done": False}

    def lockish(w: ast.With) -> bool:
        return len(w.items) == 1 and \
            _self_attr(w.items[0].context_expr) is not None

    class _T(ast.NodeTransformer):
        def __init__(self):
            self.depth = 0

        def visit_FunctionDef(self, node):
            hit = (node.name == method)
            if hit:
                self.depth += 1
            self.generic_visit(node)
            if hit:
                self.depth -= 1
            return node

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_With(self, node):
            self.generic_visit(node)
            if state["done"] or (method is not None and
                                 self.depth == 0):
                return node
            if not lockish(node):
                return node
            if state["i"] == nth:
                state["done"] = True
                return node.body
            state["i"] += 1
            return node

    tree = _T().visit(tree)
    if not state["done"]:
        raise ValueError(
            f"no with-block #{nth} found in method={method!r}")
    ast.fix_missing_locations(tree)
    return ast.unparse(tree)


# ===================================================================
# demo protocols (mutation-test substrate)
# ===================================================================

DEMO_COUNTER_SRC = '''\
import threading

from paddle_tpu.serving.locktrace import fuzz_point, wrap_lock


class DemoCounter:
    """Known-good locked counter. Mutation tests remove add()'s lock:
    the surviving locked accesses in reset()/total() keep _value
    guarded, so the static pass flags the unlocked read-modify-write,
    and the fuzz window between read and write loses updates."""

    def __init__(self):
        self._lock = wrap_lock(threading.Lock(), "DemoCounter._lock")
        self._value = 0

    def add(self, n):
        with self._lock:
            v = self._value
            fuzz_point("demo.counter.window")
            self._value = v + n

    def reset(self):
        with self._lock:
            old = self._value
            self._value = 0
        return old

    def total(self):
        with self._lock:
            return self._value
'''

DEMO_ORDER_SRC = '''\
import threading

from paddle_tpu.serving.locktrace import wrap_lock


class DemoPair:
    """Seeded lock-order inversion: ab() takes _a then _b, ba() takes
    them in the opposite order — the classic two-thread deadlock."""

    def __init__(self):
        self._a = wrap_lock(threading.Lock(), "DemoPair._a")
        self._b = wrap_lock(threading.Lock(), "DemoPair._b")
        self.hits = 0

    def ab(self):
        with self._a:
            with self._b:
                self.hits += 1

    def ba(self):
        with self._b:
            with self._a:
                self.hits += 1
'''


def run_counter_demo(src: str, seed: int, threads: int = 2,
                     iters: int = 120) -> dict:
    """Execute (possibly mutated) DEMO_COUNTER_SRC under the seeded
    schedule fuzzer: N threads hammer add(1); returns
    ``{"expected", "got", "ok"}``. The unmutated source is ok for
    EVERY seed; the removed-lock mutant loses updates."""
    from ..serving import locktrace

    locktrace.enable(fuzzer=locktrace.ScheduleFuzzer(seed))
    try:
        ns: dict = {}
        exec(compile(src, "<demo_counter>", "exec"), ns)
        c = ns["DemoCounter"]()

        def _hammer():
            for _ in range(iters):
                c.add(1)

        ts = [threading.Thread(target=_hammer, name=f"demo-add-{i}",
                               daemon=True) for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30.0)
        got = int(c.total())
        want = threads * iters
        return {"expected": want, "got": got, "ok": got == want}
    finally:
        locktrace.disable()


def run_order_demo(src: str) -> dict:
    """Execute DEMO_ORDER_SRC under the LockTracer and drive both
    acquisition orders SEQUENTIALLY on one thread — the inversion is
    detected from the two-direction edge set, so no second thread
    (and no actual deadlock risk) is needed. Returns the tracer
    report; ``report["inversions"]`` is non-empty for DemoPair."""
    from ..serving import locktrace

    tr = locktrace.enable()
    try:
        ns: dict = {}
        exec(compile(src, "<demo_order>", "exec"), ns)
        p = ns["DemoPair"]()
        p.ab()
        p.ba()
        return tr.report()
    finally:
        locktrace.disable()


# ===================================================================
# fleet protocol fuzzing (real fleet/router/replica, fake engine)
# ===================================================================

def _expected_tokens(prompt, n: int) -> List[int]:
    base = int(sum(int(x) for x in prompt)) % 9973
    return [(base * 31 + i * 7) % 1021 for i in range(int(n))]


def _chain_fp(prompt) -> int:
    fp = 1469598103934665603
    for x in prompt:
        fp = ((fp ^ int(x)) * 1099511628211) % (1 << 64)
    return fp


class _Shim:
    pass


class _FakeEngine:
    """Stdlib-only ServingEngine stand-in satisfying the full
    Replica/router-facing surface (inject/close/snapshot/gauges/
    chain export-adopt/on_chain_complete), so the schedule fuzzer
    drives the REAL fleet/router/replica protocol code without jax:
    tokens are a pure function of the prompt (bitwise-checkable), the
    close() modes mirror the engine contract (hand_back returns the
    untaken queue; drain serves it; neither touches in-flight work),
    and crash() reproduces the fail-fast contract (queued requests
    errored immediately, nothing handed back)."""

    def __init__(self, name: str = "eng"):
        self.name = name
        self._cv = threading.Condition()
        self._q: List = []                  # queued, not yet taken
        self._closing = False
        self._dead: Optional[BaseException] = None
        self._busy = 0                      # taken, not yet finished
        self.served: List[int] = []         # request ids finished HERE
        self.chains: Dict[int, List[int]] = {}
        self.counters = {k: 0 for k in (
            "submitted", "admitted", "completed", "handed_back",
            "tokens_out", "prefix_hits", "prefix_misses")}
        self.on_chain_complete = None
        self.metrics = None
        self.sentinel = None
        self.postmortem_path = None
        self.flight = _Shim()
        self.flight.ticks = lambda: []
        self.scheduler = _Shim()
        self.scheduler.max_batch = 4
        self.pool = _Shim()
        self.pool.page_size = 8
        self._t = threading.Thread(target=self._loop,
                                   name=f"fake-engine-{name}",
                                   daemon=True)
        self._t.start()

    # ------------------------------------------------------- surface ----
    @property
    def alive(self) -> bool:
        return self._dead is None and self._t.is_alive()

    def warm_programs(self) -> None:
        pass

    def arm_sentinel(self) -> None:
        pass

    def affinity_summary(self, max_depth: int = 2) -> dict:
        return {}

    def gauges(self) -> dict:
        with self._cv:
            return {"queued": len(self._q),
                    "occupancy": self._busy / 4.0}

    def snapshot(self) -> dict:
        with self._cv:
            return {"counters": dict(self.counters),
                    "gauges": {"queued": len(self._q),
                               "occupancy": self._busy / 4.0}}

    def inject(self, req) -> bool:
        from ..serving import locktrace
        locktrace.fuzz_point("fake.inject")
        with self._cv:
            if self._closing or self._dead is not None:
                return False
            self._q.append(req)
            self.counters["submitted"] += 1
            self.counters["admitted"] += 1
            self._cv.notify_all()
        return True

    def close(self, drain: bool = True,
              hand_back: bool = False) -> List:
        handed: List = []
        with self._cv:
            self._closing = True
            if self._dead is None:
                if hand_back:
                    handed = list(self._q)
                    self._q.clear()
                    self.counters["handed_back"] += len(handed)
                elif not drain:
                    for r in self._q:
                        r.error = RuntimeError(
                            f"engine {self.name}: cancelled at close")
                        r.finish("cancelled")
                    self._q.clear()
            self._cv.notify_all()
        self._t.join(timeout=30.0)
        return handed

    def crash(self) -> None:
        with self._cv:
            self._dead = RuntimeError("injected crash")
            self._cv.notify_all()

    def export_chain(self, fp: int, max_depth: int = 64):
        from ..serving import locktrace
        with self._cv:
            if self._dead is not None:
                raise RuntimeError(f"engine {self.name} is dead")
            toks = self.chains.get(int(fp))
        locktrace.fuzz_point("fake.export")
        if toks is None:
            return None
        return {"fp": int(fp), "tokens": list(toks)}

    def adopt_chain(self, blob: dict) -> dict:
        from ..serving import locktrace
        locktrace.fuzz_point("fake.adopt")
        with self._cv:
            if self._dead is not None:
                raise RuntimeError(f"engine {self.name} is dead")
            self.chains[int(blob["fp"])] = list(blob["tokens"])
        return {"fp": int(blob["fp"]),
                "pages": len(blob["tokens"])}

    # -------------------------------------------------------- worker ----
    def _loop(self) -> None:
        while True:
            req = None
            with self._cv:
                while not self._q and not self._closing \
                        and self._dead is None:
                    self._cv.wait(0.02)
                if self._dead is not None:
                    # fail-fast contract: error the queue, hand back
                    # nothing (suspect state must not be retried
                    # silently)
                    for r in self._q:
                        r.error = RuntimeError(
                            f"engine {self.name} died: {self._dead}")
                        r.finish("cancelled")
                    self._q.clear()
                    return
                if self._q:
                    req = self._q.pop(0)
                    self._busy += 1
                elif self._closing:
                    return
            if req is None:
                continue
            try:
                self._serve(req)
            finally:
                with self._cv:
                    self._busy -= 1
                    self._cv.notify_all()

    def _serve(self, req) -> None:
        from ..serving import locktrace
        toks = _expected_tokens(req.prompt, req.max_new_tokens)
        for i, t in enumerate(toks):
            locktrace.fuzz_point("fake.token")
            if i == 0:
                req.first_token_t = time.monotonic()
            req.tokens.append(int(t))
            req.stream.put(int(t))
        fp = _chain_fp(req.prompt)
        hook = self.on_chain_complete
        with self._cv:
            self.served.append(req.id)
            self.chains[fp] = list(toks)
            self.counters["completed"] += 1
            self.counters["tokens_out"] += len(toks)
        req.finish("completed")
        if hook is not None:
            hook(req, {"fp": fp, "fps": [fp]})


def fuzz_fleet_scenario(seed: int, scenario: str = "drain",
                        requests: int = 12,
                        max_new_tokens: int = 4) -> dict:
    """Replay one fleet protocol under seeded schedule perturbation
    against the REAL ServingFleet/FleetRouter/Replica code.

    scenario:
      * ``drain``   — graceful leave concurrent with submits: the
        handed-back queue re-dispatches to survivors exactly once.
      * ``crash``   — SIGKILL-shaped engine death + reap concurrent
        with submits: fail-fast errors, survivors unaffected.
      * ``migrate`` — prefill/decode roles + auto-migration: chain
        handoff runs on the fleet's background thread while decode
        traffic flows; ODD seeds crash a decode replica mid-run.

    Invariants asserted every run: every accepted request's handle
    RESOLVES (zero drops), completed handles match the expected
    tokens bitwise, no request id is served twice (exactly-once), no
    re-dispatch failures on drain, migration bookkeeping drains, and
    the LockTracer observes zero order inversions. Returns a result
    dict with ``ok``/``failures`` (reproduce with the same seed).
    """
    if scenario not in ("drain", "crash", "migrate"):
        raise ValueError(f"unknown scenario {scenario!r}")
    from ..serving import locktrace
    from ..serving.fleet.fleet import ServingFleet

    engines: List[_FakeEngine] = []

    def factory():
        e = _FakeEngine(name=f"e{len(engines)}")
        engines.append(e)
        return e

    tr = locktrace.enable(fuzzer=locktrace.ScheduleFuzzer(seed))
    failures: List[str] = []
    try:
        roles = (["prefill", "decode", "decode"]
                 if scenario == "migrate" else None)
        fleet = ServingFleet(factory, replicas=3, roles=roles,
                             policy="least_loaded",
                             prefill_len_ratio=0.1, warm=False)
        prompts = [[(seed + 3 * j) % 97 + 1, (7 * j) % 89 + 1,
                    j + 1, 5] for j in range(requests)]
        results: List = [None] * requests
        try:
            def _submitter(lo: int, hi: int) -> None:
                for j in range(lo, hi):
                    locktrace.fuzz_point("fuzz.submit")
                    try:
                        results[j] = fleet.submit(
                            prompts[j], max_new_tokens)
                    except RuntimeError as e:
                        results[j] = e

            ts = [threading.Thread(target=_submitter,
                                   args=(0, requests // 2),
                                   name="fuzz-submit-0", daemon=True),
                  threading.Thread(target=_submitter,
                                   args=(requests // 2, requests),
                                   name="fuzz-submit-1", daemon=True)]
            for t in ts:
                t.start()
            # the disturbance runs CONCURRENTLY with the submitters
            if scenario == "drain":
                fleet.drain("r1")
            elif scenario == "crash":
                engines[1].crash()
                fleet.reap()
            elif scenario == "migrate" and seed % 2 == 1:
                locktrace.fuzz_point("fuzz.crash-decode")
                engines[2].crash()
            for t in ts:
                t.join(timeout=30.0)
            if scenario == "migrate" and seed % 2 == 1:
                fleet.reap()
            # let background migration threads settle
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                with fleet._lock:
                    busy = len(fleet._migrating)
                if not busy:
                    break
                time.sleep(0.002)
            else:
                failures.append("migration bookkeeping never drained")

            completed = 0
            for j, r in enumerate(results):
                if r is None:
                    failures.append(f"req {j}: submitter never ran")
                elif isinstance(r, Exception):
                    failures.append(f"req {j}: rejected: {r}")
                else:
                    try:
                        toks = r.result(timeout=30.0)
                    except TimeoutError:
                        failures.append(f"req {j}: DROPPED (handle "
                                        f"never resolved)")
                        continue
                    except Exception:
                        if scenario in ("crash", "migrate"):
                            continue    # fail-fast errors are the
                            # contract when an engine dies mid-run
                        failures.append(f"req {j}: unexpected error")
                        continue
                    exp = _expected_tokens(prompts[j], max_new_tokens)
                    if [int(x) for x in toks] != exp:
                        failures.append(
                            f"req {j}: tokens diverge: {list(toks)} "
                            f"!= {exp}")
                    completed += 1

            served: List[int] = []
            for e in engines:
                served += e.served
            if len(served) != len(set(served)):
                failures.append("a request was served on two engines")
            if scenario == "drain":
                if fleet.router.counters.get("redispatch_failed", 0):
                    failures.append("drain hand-back re-dispatch "
                                    "failed")
                if completed != requests:
                    failures.append(
                        f"drain dropped work: {completed}/{requests} "
                        f"completed")
            if scenario == "migrate":
                src = engines[0]
                for e in engines[1:]:
                    for fp, toks in e.chains.items():
                        if fp in src.chains and \
                                toks != src.chains[fp]:
                            failures.append(
                                f"migrated chain {fp} diverges")
                if seed % 2 == 0 and \
                        fleet.counters["migrations"] == 0:
                    failures.append("no chain migrated on a healthy "
                                    "decode pool")
            inv = tr.inversions
            if inv:
                failures.append(f"lock-order inversion: {inv}")
            counters = dict(fleet.counters)
        finally:
            fleet.close()
        return {"ok": not failures, "seed": seed,
                "scenario": scenario, "failures": failures,
                "completed": completed, "served": len(served),
                "fleet": counters, "report": tr.report()}
    finally:
        locktrace.disable()

"""Flagship training-graph targets for the lint passes.

The training mirror of ``serving_graphs.py``: abstract-trace the llama
auto-parallel train step (``models/llama.py make_train_step`` — model
fwd + bwd + adamw as ONE program) exactly as a trainer would jit it,
at the flagship parallel geometries, and tag each target with the
call-site facts the training passes need: the declared per-leaf
PartitionSpecs (``train_state_specs`` — the same tree ``init_fn``
places by, so the lint sees the real layout), which flat inputs the
step donates (``donate_argnums=(0,)``: the whole state), what each
input IS (param / optimizer state / batch data), the mesh axis sizes,
and for the 1F1B geometry the schedule's expected scan trip count.

Everything here is ``jax.eval_shape`` + ``jax.make_jaxpr`` over
ShapeDtypeStructs — nothing allocates, nothing compiles; linting all
geometries costs a few seconds of tracing on one CPU core. Model dims
are the tiny config: the passes are structural and per-leaf, so hidden
size changes nothing they look at, while keeping the CLI fast.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .framework import GraphTarget

__all__ = ["TRAIN_GEOMETRIES", "training_targets", "train_step_target",
           "build_train_target", "train_stage_targets",
           "flagship_train_objects", "schedule_inventory"]

#: name -> mesh degrees + schedule knobs. The acceptance geometries:
#: plain dp, dp x mp(tp), pp (lockstep 1F1B + interleaved VPP),
#: dp-zero-sharded optimizer state, the rank-asymmetric schedules
#: (pipeline_async: classic per-rank 1F1B at pp=4, ZB-H1 W-deferral at
#: pp=2 with M NOT divisible by pp — the ragged-microbatch case), and
#: the COMPOSED async geometries (r19: dp and tp inside the shard_map
#: stage body — manual in-body collectives, dp grad psum in the f32
#: carry) so sharding-lint / donation-audit / hbm-peak /
#: collective-consistency all walk the composed programs under
#: ``graph_lint --ci``.
TRAIN_GEOMETRIES: Dict[str, Dict] = {
    "dp":      dict(dp=2, tp=1, pp=1, vpp=1, microbatches=1,
                    zero_stage=0),
    "dp_mp":   dict(dp=2, tp=2, pp=1, vpp=1, microbatches=1,
                    zero_stage=0),
    "pp_1f1b": dict(dp=1, tp=1, pp=2, vpp=2, microbatches=4,
                    zero_stage=0),
    "pp2_zb":  dict(dp=1, tp=1, pp=2, vpp=1, microbatches=5,
                    zero_stage=0, schedule="zb"),
    "pp4_async": dict(dp=1, tp=1, pp=4, vpp=1, microbatches=8,
                      zero_stage=0, schedule="1f1b_async"),
    "pp2_dp2_zb": dict(dp=2, tp=1, pp=2, vpp=1, microbatches=4,
                       zero_stage=0, schedule="zb"),
    "pp2_tp2_async": dict(dp=1, tp=2, pp=2, vpp=1, microbatches=4,
                          zero_stage=0, schedule="1f1b_async"),
    "zero1":   dict(dp=4, tp=2, pp=1, vpp=1, microbatches=1,
                    zero_stage=1),
}

from ..parallel.pipeline_async import PP_SCHEDULES

#: cfg.pp_schedule -> the schedule-model name schedule_ticks /
#: schedule_efficiency speak (the legacy traced form is "lockstep");
#: derived from the one exported mapping so it cannot drift from the
#: executor dispatch in models/llama.py
PP_SCHEDULE_MODEL = {k: model for k, (model, _) in PP_SCHEDULES.items()}


def _train_cfg(g: Dict, dtype=None):
    from ..models import llama as L
    kw = dict(use_flash_attention=False, remat=False,
              pp_stages=g["pp"], vpp_chunks=g["vpp"],
              num_microbatches=g["microbatches"])
    if g["pp"] > 1:
        kw["pp_schedule"] = g.get("schedule", "1f1b")
    if dtype is not None:
        kw["dtype"] = dtype
    return L.LlamaConfig.tiny(**kw)


def _abstract_state(cfg, mesh, optimizer, zero_stage):
    import jax
    from ..models import llama as L
    _, init_fn = L.make_train_step(cfg, mesh, optimizer=optimizer,
                                   zero_stage=zero_stage)
    return jax.eval_shape(lambda: init_fn(jax.random.PRNGKey(0)))


def _flat_call_site(state, batch, state_specs, batch_specs):
    """(labels, classes, specs, donated) aligned with the traced step's
    flat invars — the order ``jax.make_jaxpr`` flattens (state, batch)."""
    import jax
    from jax.sharding import PartitionSpec as P
    paths, _ = jax.tree_util.tree_flatten_with_path((state, batch))
    flat_specs, _ = jax.tree_util.tree_flatten(
        (state_specs, batch_specs),
        is_leaf=lambda x: isinstance(x, P))
    if len(paths) != len(flat_specs):
        raise AssertionError(
            f"spec tree ({len(flat_specs)} leaves) does not match the "
            f"state/batch tree ({len(paths)} leaves)")
    labels, classes, donated = [], [], []
    for path, _leaf in paths:
        label = jax.tree_util.keystr(path)
        labels.append(label)
        # path[0] selects state (index 0) vs batch (index 1)
        in_state = getattr(path[0], "idx", None) == 0
        if not in_state:
            cls = "data"
        else:
            key = getattr(path[1], "key", None) if len(path) > 1 else None
            cls = {"params": "param", "opt": "opt"}.get(key, "counter")
        classes.append(cls)
        donated.append(bool(in_state))  # donate_argnums=(0,): the state
    return labels, classes, flat_specs, donated


def train_step_target(geometry: str = "dp", *,
                      batch_size: Optional[int] = None,
                      seq_len: int = 8, dtype=None,
                      hbm_budget_bytes: Optional[int] = None
                      ) -> GraphTarget:
    """One flagship geometry's train-step GraphTarget (abstract, zero
    compiles)."""
    return build_train_target(
        TRAIN_GEOMETRIES[geometry], geometry,
        batch_size=batch_size, seq_len=seq_len, dtype=dtype,
        hbm_budget_bytes=hbm_budget_bytes)


def build_train_target(g: Dict, geometry: str, *,
                       batch_size: Optional[int] = None,
                       seq_len: int = 8, dtype=None, cfg=None,
                       hbm_budget_bytes: Optional[int] = None
                       ) -> GraphTarget:
    """Trace ``make_train_step`` at an ARBITRARY geometry dict (same
    keys as ``TRAIN_GEOMETRIES`` entries) — the builder behind
    :func:`train_step_target`, exported separately so the auto-parallel
    planner (analysis/planner.py) can price and verify search points
    that are not in the flagship set. ``cfg`` overrides the tiny model
    config (the planner passes the user's model)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..models import llama as L
    from ..parallel.mesh import init_hybrid_mesh
    from ..parallel.pipeline_1f1b import schedule_ticks

    if cfg is None:
        cfg = _train_cfg(g, dtype)
    hm = init_hybrid_mesh(dp=g["dp"], pp=g["pp"], tp=g["tp"],
                          set_global=False)
    mesh = hm.mesh
    optimizer = L.default_train_optimizer()
    step_fn, _ = L.make_train_step(cfg, mesh, optimizer=optimizer,
                                   zero_stage=g["zero_stage"])
    state = _abstract_state(cfg, mesh, optimizer, g["zero_stage"])
    state_specs = L.train_state_specs(cfg, mesh, optimizer,
                                      g["zero_stage"])
    if batch_size is None:
        # default: the smallest batch >= 4 whose per-microbatch rows
        # split evenly over dp (the composed async shard_map REQUIRES
        # even row splits; pp2_zb runs M=5 — the M-not-divisible-by-pp
        # case — so a fixed 4 wouldn't divide either)
        M, dp = g["microbatches"], g["dp"]
        batch_size = M * dp * max(1, -(-4 // (M * dp)))
    elif batch_size % (g["microbatches"] * g["dp"]):
        raise ValueError(
            f"batch_size={batch_size} does not split into geometry "
            f"{geometry!r}'s {g['microbatches']} microbatches of "
            f"dp={g['dp']}-divisible rows (the composed async "
            f"shard_map requires even row splits)")
    sds = jax.ShapeDtypeStruct
    batch = {"tokens": sds((batch_size, seq_len), jnp.int32),
             "labels": sds((batch_size, seq_len), jnp.int32)}
    dp_spec = P("dp", None) if g["dp"] > 1 else P()
    batch_specs = {"tokens": dp_spec, "labels": dp_spec}

    closed = jax.make_jaxpr(lambda s, b: step_fn(s, b))(state, batch)
    labels, classes, specs, donated = _flat_call_site(
        state, batch, state_specs, batch_specs)
    if len(closed.jaxpr.invars) != len(labels):
        raise AssertionError(
            f"traced step has {len(closed.jaxpr.invars)} invars but the "
            f"call-site tree has {len(labels)} leaves — the flat "
            f"alignment the passes rely on broke")
    meta = dict(
        in_specs=specs, donated_invars=donated, invar_labels=labels,
        invar_classes=classes, mesh_axes=dict(mesh.shape),
        zero_stage=g["zero_stage"], train_geometry=geometry,
    )
    if g["pp"] > 1:
        meta["pp_schedule"] = cfg.pp_schedule
        meta["expected_scan_trips"] = schedule_ticks(
            g["pp"], g["microbatches"], g["vpp"],
            schedule=PP_SCHEDULE_MODEL[cfg.pp_schedule])
    if hbm_budget_bytes is not None:
        meta["hbm_budget_bytes"] = int(hbm_budget_bytes)
    return GraphTarget(
        name=f"llama.train_step[{geometry}]", jaxpr=closed,
        compute_dtype=cfg.dtype, meta=meta)


def training_targets(geometries=None, **kw) -> List[GraphTarget]:
    """GraphTargets for every flagship training geometry plus the 1F1B
    stage-chunk group."""
    out = [train_step_target(gname, **kw)
           for gname in (geometries or TRAIN_GEOMETRIES)]
    out += train_stage_targets()
    return out


def train_stage_targets(num_stages: int = 2, virtual_chunks: int = 2,
                        seq_len: int = 8, batch: int = 2
                        ) -> List[GraphTarget]:
    """One fwd+bwd GraphTarget per 1F1B stage chunk (the per-slot
    program ``pipeline_train_1f1b`` vmaps every tick), grouped for the
    collective-consistency pass in loop-signature mode: under GSPMD the
    chunks carry no explicit collectives, but their layer-scan trip
    counts are the lockstep work contract — a chunk scanning a
    different layer count (heterogeneous partition, a bad round-robin
    edit) desynchronizes the schedule exactly like a diverging
    collective."""
    import jax

    from ..models import llama as L
    from ..parallel.pipeline_1f1b import split_chunks_round_robin

    cfg = L.LlamaConfig.tiny(use_flash_attention=False, remat=False,
                             pp_stages=num_stages,
                             vpp_chunks=virtual_chunks,
                             pp_schedule="1f1b")
    params = L.abstract_params(cfg)
    VS = num_stages * virtual_chunks
    x = jax.ShapeDtypeStruct((batch, seq_len, cfg.hidden_size),
                             cfg.dtype)

    def chunk_fwd_bwd(chunk_params, xm):
        y, pull = jax.vjp(
            lambda p, h: L._scan_layers(p, h, cfg, None, remat=False),
            chunk_params, xm)
        return pull(y)  # grads wrt (chunk_params, xm)

    targets = []
    for k in range(VS):
        chunk_k = jax.eval_shape(
            lambda p, k=k: jax.tree_util.tree_map(
                lambda c: c[k],
                split_chunks_round_robin(
                    p, cfg.num_hidden_layers, num_stages,
                    virtual_chunks)),
            params["layers"])
        closed = jax.make_jaxpr(chunk_fwd_bwd)(chunk_k, x)
        targets.append(GraphTarget(
            name=f"llama.train_stage_chunk[{k}/{VS}]", jaxpr=closed,
            compute_dtype=cfg.dtype,
            meta={"stage_group": f"llama.train_pp[{num_stages}x"
                                 f"{virtual_chunks}]",
                  "stage_count": VS,
                  "signature_include_loops": True}))
    return targets


def flagship_train_objects(dtype=None, batch_size: int = 4,
                           seq_len: int = 8, zero_stage: int = 0):
    """(target, step_fn, state, batch) for the single-device flagship
    llama train step with CONCRETE arrays — the estimator-accuracy
    harness: tests compile ``step_fn`` once and compare the target's
    static estimate against XLA's own accounting. f32 by default: bf16
    modules compiled on the CPU backend get float-normalized (f32)
    buffers, an XLA-CPU artifact that would skew the comparison."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..models import llama as L
    from ..parallel.mesh import init_hybrid_mesh

    cfg = L.LlamaConfig.tiny(use_flash_attention=False, remat=False,
                             dtype=dtype or jnp.float32)
    hm = init_hybrid_mesh(dp=1, pp=1, tp=1, set_global=False)
    optimizer = L.default_train_optimizer()
    step_fn, init_fn = L.make_train_step(cfg, hm.mesh,
                                         optimizer=optimizer,
                                         zero_stage=zero_stage)
    state = init_fn(jax.random.PRNGKey(0))
    batch = {"tokens": jnp.zeros((batch_size, seq_len), jnp.int32),
             "labels": jnp.zeros((batch_size, seq_len), jnp.int32)}
    state_specs = L.train_state_specs(cfg, hm.mesh, optimizer,
                                      zero_stage)
    closed = jax.make_jaxpr(lambda s, b: step_fn(s, b))(state, batch)
    labels, classes, specs, donated = _flat_call_site(
        state, batch, state_specs,
        {"tokens": P(), "labels": P()})
    target = GraphTarget(
        name="llama.train_step[flagship-1dev]", jaxpr=closed,
        compute_dtype=cfg.dtype,
        meta=dict(in_specs=specs, donated_invars=donated,
                  invar_labels=labels, invar_classes=classes,
                  mesh_axes=dict(hm.mesh.shape), zero_stage=zero_stage,
                  train_geometry="flagship-1dev"))
    return target, step_fn, state, batch


def schedule_inventory(geometries=None) -> Dict:
    """The pipeline-schedule trip/phase inventory for every pp>1
    flagship geometry — the training-schedule counterpart of the
    serving ``program_inventory``: one diffable dict that
    ``graph_lint --json`` emits as ``pipeline_schedules`` so CI
    consumers can pin the schedule shape (tick counts, per-op-kind
    rank-tick counts, modeled efficiency) field for field.

    Pure host arithmetic (the validated schedule builder / closed
    forms) — no tracing, no compiles.
    """
    from ..parallel.pipeline_1f1b import (schedule_efficiency,
                                          schedule_ticks)
    out: Dict = {"schema": "paddle_tpu.schedule_inventory/1",
                 "geometries": {}}
    for name in (geometries or TRAIN_GEOMETRIES):
        g = TRAIN_GEOMETRIES[name]
        if g["pp"] <= 1:
            continue
        cfg_sched = g.get("schedule", "1f1b")
        model = PP_SCHEDULE_MODEL[cfg_sched]
        S, M, V = g["pp"], g["microbatches"], g["vpp"]
        ticks = schedule_ticks(S, M, V, schedule=model)
        entry = {
            "pp": S, "vpp": V, "microbatches": M,
            "pp_schedule": cfg_sched, "schedule_model": model,
            "ticks": ticks,
            "efficiency": round(schedule_efficiency(
                S, M, V, schedule=model), 6),
        }
        if model == "lockstep":
            # every tick runs all S*V slots; useful slot-ticks = M per
            # slot — the masked fill/drain is the phase inventory
            entry["phases"] = {
                "slots": S * V, "useful_slot_ticks": M * S * V,
                "masked_slot_ticks": (ticks - M) * S * V}
        else:
            from ..parallel.pipeline_async import build_schedule
            sched = build_schedule(S, M, V, model)
            entry["phases"] = sched.op_counts()
            entry["saved_ring_depth"] = {"acts": sched.depth_x,
                                         "cotangents": sched.depth_c,
                                         "residuals": sched.depth_r}
        out["geometries"][name] = entry
    return out

"""paddle.geometric namespace (reference: python/paddle/geometric/ —
message passing send_u_recv/send_ue_recv, segment ops, sampling).

TPU-native: segment reductions are jax.ops.segment_* (XLA scatter), the
natural fit — no CSR kernels needed.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


def _u(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


def segment_sum(data, segment_ids, name=None):
    d, s = _u(data), _u(segment_ids).astype(jnp.int32)
    n = int(s.max()) + 1 if s.size else 0
    return Tensor(jax.ops.segment_sum(d, s, num_segments=n))


def segment_mean(data, segment_ids, name=None):
    d, s = _u(data), _u(segment_ids).astype(jnp.int32)
    n = int(s.max()) + 1 if s.size else 0
    tot = jax.ops.segment_sum(d, s, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones_like(d), s, num_segments=n)
    return Tensor(tot / jnp.maximum(cnt, 1))


def segment_max(data, segment_ids, name=None):
    d, s = _u(data), _u(segment_ids).astype(jnp.int32)
    n = int(s.max()) + 1 if s.size else 0
    return Tensor(jax.ops.segment_max(d, s, num_segments=n))


def segment_min(data, segment_ids, name=None):
    d, s = _u(data), _u(segment_ids).astype(jnp.int32)
    n = int(s.max()) + 1 if s.size else 0
    return Tensor(jax.ops.segment_min(d, s, num_segments=n))


_POOLS = {"sum": jax.ops.segment_sum, "mean": None,
          "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size: Optional[int] = None, name=None):
    """Graph message passing: gather x[src] then segment-reduce onto dst
    (geometric/message_passing/send_recv.py)."""
    xd = _u(x)
    src = _u(src_index).astype(jnp.int32)
    dst = _u(dst_index).astype(jnp.int32)
    n = int(out_size) if out_size is not None else xd.shape[0]
    msgs = xd[src]
    if reduce_op == "mean":
        tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],) + (1,) *
                                           (msgs.ndim - 1)), dst,
                                  num_segments=n)
        return Tensor(tot / jnp.maximum(cnt, 1))
    fn = _POOLS[reduce_op]
    return Tensor(fn(msgs, dst, num_segments=n))


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size: Optional[int] = None,
                 name=None):
    """Node+edge messages: combine x[src] with edge features y, reduce."""
    xd = _u(x)
    yd = _u(y)
    src = _u(src_index).astype(jnp.int32)
    msgs = xd[src]
    if message_op == "add":
        msgs = msgs + yd
    elif message_op == "mul":
        msgs = msgs * yd
    else:
        raise ValueError(f"unknown message_op {message_op}")
    return send_u_recv(Tensor(msgs),
                       jnp.arange(msgs.shape[0]), dst_index,
                       reduce_op=reduce_op,
                       out_size=out_size if out_size is not None
                       else xd.shape[0])


def _host_rng():
    """Host-side RNG derived from the framework generator so sampling
    follows paddle_tpu.seed() (reproducible GNN pipelines)."""
    import jax
    from ..core.generator import next_key
    seed = int(jax.random.randint(next_key(), (), 0, 2 ** 31 - 1))
    return np.random.RandomState(seed)


def send_uv(x, y, src_index, dst_index, message_op: str = "add", name=None):
    """Per-edge message from both endpoints (reference
    geometric/message_passing/send_recv.py send_uv): out[e] =
    op(x[src[e]], y[dst[e]]) — one gather per side, no scatter."""
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    xd, yd = _u(x), _u(y)
    s = _u(src_index).astype(jnp.int32)
    d = _u(dst_index).astype(jnp.int32)
    a, b = xd[s], yd[d]
    if message_op == "add":
        out = a + b
    elif message_op == "sub":
        out = a - b
    elif message_op == "mul":
        out = a * b
    elif message_op == "div":
        out = a / b
    else:
        raise ValueError(f"unknown message_op {message_op!r}")
    return Tensor(out)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling over a CSC graph (reference
    geometric/sampling/neighbors.py over graph_sample_neighbors kernels).
    Host-side: sampling drives the NEXT batch's gather — it is data
    pipeline work, not accelerator compute (same split as the
    reference, whose kernel runs on CPU for the DataLoader path)."""
    import numpy as np
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    rows = np.asarray(_u(row))
    cptr = np.asarray(_u(colptr))
    nodes = np.atleast_1d(np.asarray(_u(input_nodes)))
    rng = _host_rng()
    out_neighbors, out_count, out_eids = [], [], []
    for n in nodes:
        lo, hi = int(cptr[n]), int(cptr[n + 1])
        neigh = rows[lo:hi]
        eid = np.arange(lo, hi)
        if sample_size > 0 and len(neigh) > sample_size:
            pick = rng.choice(len(neigh), sample_size, replace=False)
            neigh, eid = neigh[pick], eid[pick]
        out_neighbors.append(neigh)
        out_eids.append(eid)
        out_count.append(len(neigh))
    neighbors = Tensor(jnp.asarray(
        np.concatenate(out_neighbors) if out_neighbors else
        np.zeros((0,), rows.dtype)))
    counts = Tensor(jnp.asarray(np.asarray(out_count, np.int32)))
    if return_eids:
        return neighbors, counts, Tensor(jnp.asarray(
            np.concatenate(out_eids).astype(np.int64)))
    return neighbors, counts


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-proportional variant of sample_neighbors."""
    import numpy as np
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    rows = np.asarray(_u(row))
    cptr = np.asarray(_u(colptr))
    w = np.asarray(_u(edge_weight), np.float64)
    nodes = np.atleast_1d(np.asarray(_u(input_nodes)))
    rng = _host_rng()
    out_neighbors, out_count, out_eids = [], [], []
    for n in nodes:
        lo, hi = int(cptr[n]), int(cptr[n + 1])
        neigh = rows[lo:hi]
        eid = np.arange(lo, hi)
        if sample_size > 0 and len(neigh) > sample_size:
            p = w[lo:hi] / max(w[lo:hi].sum(), 1e-12)
            pick = rng.choice(len(neigh), sample_size, replace=False, p=p)
            neigh, eid = neigh[pick], eid[pick]
        out_neighbors.append(neigh)
        out_eids.append(eid)
        out_count.append(len(neigh))
    neighbors = Tensor(jnp.asarray(
        np.concatenate(out_neighbors) if out_neighbors else
        np.zeros((0,), rows.dtype)))
    counts = Tensor(jnp.asarray(np.asarray(out_count, np.int32)))
    if return_eids:
        return neighbors, counts, Tensor(jnp.asarray(
            np.concatenate(out_eids).astype(np.int64)))
    return neighbors, counts


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (reference
    geometric/reindex.py): x's nodes get 0..len(x)-1, unseen neighbor
    ids get fresh ids in first-appearance order."""
    import numpy as np
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    xs = np.asarray(_u(x))
    nb = np.asarray(_u(neighbors))
    mapping = {int(v): i for i, v in enumerate(xs)}
    out = np.empty(len(nb), np.int64)
    nodes = list(xs)
    for i, v in enumerate(nb):
        v = int(v)
        if v not in mapping:
            mapping[v] = len(mapping)
            nodes.append(v)
        out[i] = mapping[v]
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray(np.asarray(nodes, xs.dtype))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are per-edge-type lists
    sharing one id space."""
    import numpy as np
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    xs = np.asarray(_u(x))
    mapping = {int(v): i for i, v in enumerate(xs)}
    nodes = list(xs)
    outs = []
    for nb in neighbors:
        nbv = np.asarray(_u(nb))
        out = np.empty(len(nbv), np.int64)
        for i, v in enumerate(nbv):
            v = int(v)
            if v not in mapping:
                mapping[v] = len(mapping)
                nodes.append(v)
            out[i] = mapping[v]
        outs.append(Tensor(jnp.asarray(out)))
    return outs, Tensor(jnp.asarray(np.asarray(nodes, xs.dtype)))

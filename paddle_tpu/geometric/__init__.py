"""paddle.geometric namespace (reference: python/paddle/geometric/ —
message passing send_u_recv/send_ue_recv, segment ops, sampling).

TPU-native: segment reductions are jax.ops.segment_* (XLA scatter), the
natural fit — no CSR kernels needed.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _u(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


def segment_sum(data, segment_ids, name=None):
    d, s = _u(data), _u(segment_ids).astype(jnp.int32)
    n = int(s.max()) + 1 if s.size else 0
    return Tensor(jax.ops.segment_sum(d, s, num_segments=n))


def segment_mean(data, segment_ids, name=None):
    d, s = _u(data), _u(segment_ids).astype(jnp.int32)
    n = int(s.max()) + 1 if s.size else 0
    tot = jax.ops.segment_sum(d, s, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones_like(d), s, num_segments=n)
    return Tensor(tot / jnp.maximum(cnt, 1))


def segment_max(data, segment_ids, name=None):
    d, s = _u(data), _u(segment_ids).astype(jnp.int32)
    n = int(s.max()) + 1 if s.size else 0
    return Tensor(jax.ops.segment_max(d, s, num_segments=n))


def segment_min(data, segment_ids, name=None):
    d, s = _u(data), _u(segment_ids).astype(jnp.int32)
    n = int(s.max()) + 1 if s.size else 0
    return Tensor(jax.ops.segment_min(d, s, num_segments=n))


_POOLS = {"sum": jax.ops.segment_sum, "mean": None,
          "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size: Optional[int] = None, name=None):
    """Graph message passing: gather x[src] then segment-reduce onto dst
    (geometric/message_passing/send_recv.py)."""
    xd = _u(x)
    src = _u(src_index).astype(jnp.int32)
    dst = _u(dst_index).astype(jnp.int32)
    n = int(out_size) if out_size is not None else xd.shape[0]
    msgs = xd[src]
    if reduce_op == "mean":
        tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],) + (1,) *
                                           (msgs.ndim - 1)), dst,
                                  num_segments=n)
        return Tensor(tot / jnp.maximum(cnt, 1))
    fn = _POOLS[reduce_op]
    return Tensor(fn(msgs, dst, num_segments=n))


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size: Optional[int] = None,
                 name=None):
    """Node+edge messages: combine x[src] with edge features y, reduce."""
    xd = _u(x)
    yd = _u(y)
    src = _u(src_index).astype(jnp.int32)
    msgs = xd[src]
    if message_op == "add":
        msgs = msgs + yd
    elif message_op == "mul":
        msgs = msgs * yd
    else:
        raise ValueError(f"unknown message_op {message_op}")
    return send_u_recv(Tensor(msgs),
                       jnp.arange(msgs.shape[0]), dst_index,
                       reduce_op=reduce_op,
                       out_size=out_size if out_size is not None
                       else xd.shape[0])

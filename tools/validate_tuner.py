"""Validate the auto-tuner's memory/cost models against reality.

Reference capability: the auto-tuner prunes candidate configs by a
memory model before measuring survivors
(python/paddle/distributed/auto_tuner/memory_cost_model.py); a model
that is badly wrong prunes good configs or launches OOM ones. This tool
scores OUR models (distributed/auto_tuner.py estimate_memory /
estimate_step_cost) against the compiler's memory analysis and measured
step time for single-chip llama configs, and prints one JSON line per
config. Results are recorded in docs/PERF.md.

Run on the real chip: python tools/validate_tuner.py
"""
import gc
import json
import time

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.parallel import init_hybrid_mesh
from paddle_tpu.distributed.auto_tuner import (Candidate, ModelDesc,
                                               estimate_memory,
                                               estimate_step_cost)

CONFIGS = [
    # D, L, F, H, KV, B
    (4096, 6, 16384, 32, 8, 5),
    (2560, 16, 10240, 20, 4, 8),
    (2048, 24, 8192, 16, 4, 8),
    (1024, 8, 4096, 8, 8, 8),
]


def slope_ms(step, state, batch, ns=(2, 6)):
    def run_n(n, st):
        l = None
        for _ in range(n):
            st, l = step(st, batch)
        return st, float(l)

    state, _ = run_n(2, state)
    t = []
    for n in ns:
        t0 = time.perf_counter()
        state, _ = run_n(n, state)
        t.append(time.perf_counter() - t0)
    return (t[1] - t[0]) / (ns[1] - ns[0]) * 1e3


def main():
    for D, Ln, F, H, KV, B in CONFIGS:
        cfg = L.LlamaConfig(
            vocab_size=32000, hidden_size=D, intermediate_size=F,
            num_hidden_layers=Ln, num_attention_heads=H,
            num_key_value_heads=KV, max_position_embeddings=2048,
            dtype=jnp.bfloat16, remat=True, use_flash_attention=True)
        m = ModelDesc(hidden=D, layers=Ln, ffn=F, vocab=32000, heads=H,
                      kv_heads=KV, seq_len=2048, global_batch=B)
        c = Candidate(dp=1, tp=1, pp=1, zero=1, microbatches=1)
        est_mem = estimate_memory(m, c)
        est_ms = estimate_step_cost(m, c) * 1e3

        hm = init_hybrid_mesh(dp=1, pp=1, tp=1, set_global=False)
        with hm.mesh:
            step, init = L.make_train_step(cfg, hm.mesh)
            state = init(jax.random.PRNGKey(0))
            batch = L.make_batch(cfg, batch_size=B, seq_len=2048,
                                 mesh=hm.mesh)
            compiled = jax.jit(step.__wrapped__, donate_argnums=(0,)
                               ).lower(state, batch).compile()
            ma = compiled.memory_analysis()
            # peak live HBM ~ resident args + XLA temp (outputs alias
            # the donated args)
            real_mem = (ma.argument_size_in_bytes + ma.temp_size_in_bytes)
            ms = slope_ms(step, state, batch)
            del state, compiled, step
        gc.collect()
        print(json.dumps({
            "config": f"D{D} L{Ln} F{F} B{B}",
            "est_mem_gb": round(est_mem / 1e9, 2),
            "real_mem_gb": round(real_mem / 1e9, 2),
            "mem_err_pct": round(100 * (est_mem - real_mem) / real_mem, 1),
            "est_step_ms": round(est_ms, 1),
            "real_step_ms": round(ms, 1),
            "cost_err_pct": round(100 * (est_ms - ms) / ms, 1),
        }), flush=True)


if __name__ == "__main__":
    main()

"""Per-op profile of the single-stream decode step + int8 A/B.

Decode is weight-bandwidth-bound: one greedy step must stream every
projection weight once, so the hard ceiling is

    steps/s <= HBM_bandwidth / bytes_per_step

(bytes_per_step = quantization.decode.decode_weight_bytes + the KV
cache read + the activation noise). This tool measures where the step's
time actually goes — the PERF.md decode counterpart of the train-side
device-op breakdown:

  * whole-step rate by slope timing (chained generate of N0 vs N1
    tokens, prefill and sync cancel in the difference);
  * the step TAIL in isolation — final_norm + lm_head + argmax sample
    on a captured hidden state (jitted alone);
  * embed lookup in isolation;
  * layer body = step − tail − embed (the scan over blocks, including
    the per-layer KV append + cached attention);
  * compiled-program cost_analysis (XLA's own flops / bytes-accessed
    estimate) for the f32-accounting cross-check;
  * the analytic bytes/step + ceiling at a given HBM bandwidth, and the
    fraction of that ceiling the measured rate achieves.

Runs the bf16/f32 params and (``int8`` flag) the weight-only-quantized
params through the SAME harness, printing both and the uplift.

``rewrites`` adds the verified-rewrite A/B (analysis/rewrite.py): the
single int8 decode step traced with the naive dequantize-then-matmul
idiom (``PADDLE_TPU_INT8_IMPL=unfused``) is measured three ways —
as-is, through the ``int8-epilogue-fuse`` rewrite (fires at jit-trace
time; the fused-rmsnorm substitution is excluded off-TPU, where its
Pallas kernel would run in interpret mode and the emulation cost would
swamp the signal), and against the hand-fused path — emitting per
variant the XLA bytes/flops per step and measured step time, plus the
rewrite deltas. This is the acceptance A/B for the optimizer passes:
the rewritten graph must beat the unfused baseline and land at (or
within noise of) the hand fusion it reproduces.

``ragged`` adds the r12 serving-tick A/B: one serving-batch decode
step (S=8 slots on a paged pool) measured through the one-program
ragged tick (``serving_tick_block`` at num_steps=1) and the legacy
``serving_decode_step`` it replaced — fresh function object per
variant (the r11 trace-cache lesson) — reporting XLA flops/bytes per
step and the slope-timed ratio.

``trace=out.json`` records one observability span per measured section
(per-variant whole-step / tail / embed slope chains, the rewrite and
ragged A/B arms) and exports them as Perfetto-loadable Chrome-trace
JSON — the same exporter ``serving_bench --trace`` uses, so a profile
session and a serving run read in the same UI.

Every variant's JSON additionally carries ``spec_ceiling`` — the
acceptance-rate-parameterized SPECULATIVE decode ceiling (expected
tok/s as a function of draft length k, per-token acceptance alpha and
relative draft cost — ``spec_draft_cost=``, default 0 for the
host-side n-gram self-drafter): decode's bandwidth ceiling is per
target LAUNCH, and a verify span emits ``1 + E[accepted]`` tokens per
launch, so the PERF.md speculative projections are computed here, not
hand-derived. The measured counterpart is ``serving_bench --modes
spec_ab``.

Usage:
  python tools/decode_profile.py [flagship|deep|mid|tiny] [int8] [json]
      [rewrites] [ragged] [trace=out.json] [bw=819e9] [steps=64]
      [spec_draft_cost=0.0]

``flagship`` is the 1.72B bench model (TPU-sized; expect minutes per
chain on CPU); ``mid`` (0.17B) profiles the same shape story at
CPU-friendly cost. Default: mid off-TPU, flagship on TPU.
"""
import contextlib
import json
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.models import llama as L
from paddle_tpu.quantization.decode import (decode_weight_bytes,
                                            quantize_for_decode)

# module-level so the measured helpers can annotate their sections
# without threading a tracer through every signature; None = no-op
_TRACER = None


def _span(name, **args):
    if _TRACER is None:
        return contextlib.nullcontext()
    return _TRACER.span(name, track="decode_profile", **args)


PRESETS = {
    # bench.py flagship: the 1.72B decode whose 176.7 tok/s (BENCH_r05)
    # this tool exists to explain
    "flagship": dict(vocab_size=32000, hidden_size=4096,
                     intermediate_size=16384, num_hidden_layers=6,
                     num_attention_heads=32, num_key_value_heads=8),
    "deep": dict(vocab_size=32000, hidden_size=2560,
                 intermediate_size=10240, num_hidden_layers=16,
                 num_attention_heads=20, num_key_value_heads=4),
    "mid": dict(vocab_size=8192, hidden_size=1024,
                intermediate_size=4096, num_hidden_layers=8,
                num_attention_heads=8, num_key_value_heads=4),
    "tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=4, num_attention_heads=4,
                 num_key_value_heads=2),
}


def slope(run_n, n0, n1, repeats=2):
    """Per-iteration seconds: min-per-chain, then difference (the bench.py
    convention — min of the difference would pair a slowed short chain
    with a fast long one and understate dt)."""
    run_n(2)  # compile + warmup
    t_short = t_long = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        run_n(n0)
        t_short = min(t_short, time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_n(n1)
        t_long = min(t_long, time.perf_counter() - t0)
    return (t_long - t_short) / (n1 - n0)


def speculative_ceiling(ceiling_tok_s, ks=(1, 2, 3, 4, 6, 8),
                        alphas=(0.3, 0.5, 0.7, 0.8, 0.9),
                        draft_cost: float = 0.0):
    """Acceptance-rate-parameterized speculative decode ceiling.

    Decode is weight-bandwidth-bound: the ceiling is per target-model
    LAUNCH (one launch streams every weight once, whether it scores 1
    token or a k+1-token verify span — the extra span rows are compute,
    which decode has slack of). Speculation therefore multiplies the
    per-launch ceiling by expected emitted tokens per launch:

        E[accepted | k, alpha] = alpha (1 - alpha^k) / (1 - alpha)
        tok/s(k, alpha)       = ceiling * (1 + E) / (1 + k*draft_cost)

    with iid per-token draft acceptance probability ``alpha`` and
    ``draft_cost`` = the cost of ONE draft token relative to a target
    launch (0 for the host-side n-gram self-drafter; a draft MODEL
    pays roughly its size ratio). Emitted in the JSON output so the
    PERF.md projections are computed, not hand-derived; the measured
    counterpart of (1 + E) is serving_bench spec_ab's
    ``launch_reduction``."""
    table = {}
    for k in ks:
        row = {}
        for a in alphas:
            e = float(k) if a >= 1.0 else a * (1 - a ** k) / (1 - a)
            row[f"alpha={a}"] = {
                "tok_s": round(ceiling_tok_s * (1 + e)
                               / (1 + k * draft_cost), 1),
                "launches_per_token": round(1 / (1 + e), 4),
                "expected_accepted": round(e, 3)}
        table[f"k={k}"] = row
    return {"draft_cost_per_token": draft_cost,
            "model": "iid per-token acceptance; E[acc]="
                     "a(1-a^k)/(1-a); verify span streams the same "
                     "weights as one decode step",
            "table": table}


def long_context_ceiling(cfg, bw, weight_bytes,
                         kv_lens=(4096, 16384, 65536, 102400),
                         page_size=16):
    """The r16 long-context extension of the same bandwidth ceiling:
    price the decode step at context lengths the ONE-SHOT ragged
    kernel cannot even hold — its K+V VMEM scratch grows with the
    page table, so past the knee only the TILED flash-combine walk
    runs on-chip. Both walks stream each live page exactly once per
    (slot, kv-head) (analysis/serving_graphs.ragged_walk_model), so
    the bytes term — and therefore the tok/s ceiling — is the same;
    what the table shows is the ceiling the tiled walk UNLOCKS
    (oneshot_fits_vmem goes False) and the O(tile) scratch it pays
    for it. The measured counterpart is the kernel_bench
    ``--ragged-sweep`` A/B on the chip."""
    from paddle_tpu.analysis.serving_graphs import ragged_walk_model
    from paddle_tpu.ops.pallas.ragged_paged_attention import (
        default_kv_tile_pages)
    dtype_bytes = jnp.dtype(cfg.dtype).itemsize
    rows = {}
    for n in kv_lens:
        pages = -(-int(n) // page_size)
        tile = default_kv_tile_pages(pages, page_size, cfg.head_dim,
                                     cfg.dtype)
        m = ragged_walk_model(
            kv_len=n, page_size=page_size, head_dim=cfg.head_dim,
            num_kv_heads=cfg.num_key_value_heads,
            num_heads=cfg.num_attention_heads,
            num_layers=cfg.num_hidden_layers,
            dtype_bytes=dtype_bytes, kv_tile_pages=tile)
        total = weight_bytes + m["kv_bytes_per_step"]
        rows[f"kv={n}"] = {
            "kv_bytes_per_step": m["kv_bytes_per_step"],
            "bw_ceiling_tok_per_s": round(bw / total, 1),
            "oneshot_fits_vmem": m["oneshot_fits_vmem"],
            "vmem_scratch_bytes_oneshot":
                m["vmem_scratch_bytes_oneshot"],
            "kv_tile_pages": tile,
            "vmem_scratch_bytes_tiled": m["vmem_scratch_bytes_tiled"],
            "walk": "tiled" if tile else "oneshot",
        }
    return {"page_size": page_size,
            "model": "ceiling = bw / (weight_bytes + kv_bytes); both "
                     "walks stream each live page once, so the tiled "
                     "walk changes VMEM residency (O(tile) scratch), "
                     "not the bytes term — it UNLOCKS the long rows",
            "table": rows}


def kv_bytes_per_step(cfg, seq_len, dtype_bytes=None):
    """K+V read traffic of one cached-attention step at cache length
    ``seq_len`` (the write is one token — noise)."""
    if dtype_bytes is None:
        dtype_bytes = jnp.dtype(cfg.dtype).itemsize
    return (2 * cfg.num_hidden_layers * seq_len * cfg.num_key_value_heads
            * cfg.head_dim * dtype_bytes)


def profile(params, cfg, steps, prompt_len=32):
    """Measured seconds per decode step, split step/tail/embed."""
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, prompt_len), 0,
                                cfg.vocab_size, dtype=jnp.int32)

    n0 = max(steps // 4, 2)
    n1 = max(steps, n0 + 4)  # slope needs n1 > n0 (steps<=2 otherwise
    #                          divides by zero in the difference)
    gens = {n: jax.jit(lambda p, t, n=n: L.generate(p, t, cfg,
                                                    max_new_tokens=n))
            for n in (2, n0, n1)}

    def run_gen(n):
        out = gens[n](params, prompt)
        int(out[0, -1])  # host read: the only reliable sync everywhere

    with _span("step_slope"):
        step_s = slope(run_gen, n0, n1)

    # tail: final_norm + lm_head + greedy sample, jitted alone on a
    # captured hidden state (chained via a data dependency so the chain
    # cannot be executed in parallel)
    h = jnp.zeros((1, cfg.hidden_size), cfg.dtype) + 0.1

    def tail_n(p, h, n):
        def body(carry, _):
            hh = L.rms_norm(carry, p["final_norm"], cfg.rms_norm_eps)
            logits = L._mm(hh, p["lm_head"]).astype(jnp.float32)
            tok = jnp.argmax(logits, axis=-1)
            # feed the token back so steps serialize
            return carry + tok.astype(carry.dtype)[:, None] * 1e-9, tok
        _, toks = jax.lax.scan(body, h, None, length=n)
        return toks

    # scan length must be static: one jit per chain length
    tails = {n: jax.jit(lambda p, h, n=n: tail_n(p, h, n))
             for n in (2, n0, n1)}

    def run_tail(n):
        int(np.asarray(tails[n](params, h))[-1, 0])

    with _span("tail_slope"):
        tail_s = slope(run_tail, n0, n1)

    # embed lookup in isolation (chained through an index dependency)
    def embed_n(p, n):
        def body(tok, _):
            row = p["embed"][tok]
            nxt = (tok + jnp.int32(1) +
                   (row.sum() * 0).astype(jnp.int32)) % cfg.vocab_size
            return nxt, row.sum()
        _, s = jax.lax.scan(body, jnp.int32(0), None, length=n)
        return s

    embeds = {n: jax.jit(lambda p, n=n: embed_n(p, n))
              for n in (2, n0, n1)}

    def run_embed(n):
        float(np.asarray(embeds[n](params))[-1])

    with _span("embed_slope"):
        embed_s = slope(run_embed, n0, n1)

    # XLA's own accounting of ONE decode step (prefilled cache, T=1)
    cost = {}
    try:
        cache = L.init_kv_cache(cfg, 1, prompt_len + steps)
        _, cache = jax.jit(
            lambda p, t, c: L.forward_with_cache(p, t, c, 0, cfg)
        )(params, prompt, cache)
        tok = jnp.zeros((1, 1), jnp.int32)
        lowered = jax.jit(
            lambda p, t, c: L.forward_with_cache(p, t, c,
                                                 jnp.int32(prompt_len),
                                                 cfg)
        ).lower(params, tok, cache)
        from paddle_tpu.analysis.hbm import xla_cost_analysis
        ca = xla_cost_analysis(lowered.compile())
        if ca:
            cost = {"xla_flops": float(ca.get("flops", -1)),
                    "xla_bytes_accessed": float(ca.get("bytes accessed",
                                                       -1))}
    except Exception as e:  # cost_analysis is best-effort per backend
        cost = {"xla_cost_error": str(e)[:120]}

    return {
        "step_ms": step_s * 1e3,
        "tail_ms": tail_s * 1e3,          # final_norm + lm_head + sample
        "embed_ms": embed_s * 1e3,
        "layers_ms": max(step_s - tail_s - embed_s, 0.0) * 1e3,
        "tok_per_s": 1.0 / step_s,
        **cost,
    }


def rewrite_ab(params, cfg, steps, prompt_len=32):
    """The verified-rewrite A/B (docstring above): one int8 decode step
    (``forward_with_cache`` at T=1 on a prefilled cache) traced with the
    naive dequantize-then-matmul idiom, measured three ways — as-is,
    through the rewrite passes, and against the hand-fused path. Each
    variant reports XLA bytes-accessed of the compiled step and the
    slope-timed ms/step; the deltas at the end are the acceptance
    numbers for the optimizer passes."""
    from paddle_tpu.analysis.hbm import xla_cost_analysis
    from paddle_tpu.analysis.rewrite import count_matches, rewrite_callable

    qparams = quantize_for_decode(params, cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, prompt_len), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    cache0 = L.init_kv_cache(cfg, 1, prompt_len + 2)
    _, cache0 = jax.jit(
        lambda p, t, c: L.forward_with_cache(p, t, c, 0, cfg)
    )(qparams, prompt, cache0)
    pos = jnp.int32(prompt_len)
    tok0 = jnp.zeros((1, 1), jnp.int32)

    def make_step():
        # a FRESH function object per variant: jax caches traces keyed
        # on the function's identity, so reusing one `step` across
        # variants would hand every impl the first variant's jaxpr and
        # the PADDLE_TPU_INT8_IMPL switch would silently not happen
        # (measured: identical flops across impls without this)
        def step(p, tok, c):
            logits, c2 = L.forward_with_cache(p, tok, c, pos, cfg)
            # greedy sample in-graph so chained calls serialize on data
            return (jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None],
                    c2)
        return step

    n0 = max(steps // 4, 2)
    n1 = max(steps, n0 + 4)

    # the A/B isolates the int8-epilogue rewrite: off-TPU the
    # fused-rmsnorm substitution would route through the Pallas kernel
    # in INTERPRET mode, polluting the step time with emulation cost
    # that says nothing about the rewrite (the rmsnorm contract is
    # verified separately by graph_lint --suite rewrite)
    rules = ("int8-epilogue-fuse",)

    def measure(impl, wrap=None):
        prev = os.environ.get("PADDLE_TPU_INT8_IMPL")
        os.environ["PADDLE_TPU_INT8_IMPL"] = impl
        try:
            step = make_step()
            fn = wrap(step, rules=rules) if wrap is not None else step
            jitted = jax.jit(fn)
            # compile (and, for the rewritten variant, pattern-match)
            # while the impl env var is in force — the idiom is chosen
            # at trace time
            lowered = jitted.lower(qparams, tok0, cache0)
            ca = xla_cost_analysis(lowered.compile())
            fired = None
            if wrap is not None:
                from paddle_tpu.analysis.framework import default_rewrites
                fired = dict(count_matches(
                    jax.make_jaxpr(step)(qparams, tok0, cache0),
                    rules=default_rewrites(rules)))

            def run(n):
                t, c = tok0, cache0
                for _ in range(n):
                    t, c = jitted(qparams, t, c)
                int(np.asarray(t)[0, 0])

            with _span(f"rewrite_ab.{impl}" + (
                    ".rewritten" if wrap is not None else "")):
                ms = slope(run, n0, n1) * 1e3
        finally:
            if prev is None:
                os.environ.pop("PADDLE_TPU_INT8_IMPL", None)
            else:
                os.environ["PADDLE_TPU_INT8_IMPL"] = prev
        row = {"step_ms": round(ms, 4),
               "xla_bytes_accessed": float(ca.get("bytes accessed", -1)),
               "xla_flops": float(ca.get("flops", -1))}
        if fired is not None:
            row["fired"] = fired
        return row

    ab = {
        "unfused": measure("unfused"),
        "rewritten": measure("unfused", wrap=rewrite_callable),
        "hand_fused": measure("jnp"),
    }
    ub, rb = (ab["unfused"]["xla_bytes_accessed"],
              ab["rewritten"]["xla_bytes_accessed"])
    hb = ab["hand_fused"]["xla_bytes_accessed"]
    if ub > 0 and rb > 0:
        ab["bytes_cut_vs_unfused"] = round(ub / rb, 4)
        ab["bytes_vs_hand_fused"] = round(rb / hb, 4) if hb > 0 else None
    uf, rf = ab["unfused"]["xla_flops"], ab["rewritten"]["xla_flops"]
    if uf > 0 and rf > 0:
        ab["flops_cut_vs_unfused"] = round(uf / rf, 4)
    ab["speedup_vs_unfused"] = round(
        ab["unfused"]["step_ms"] / ab["rewritten"]["step_ms"], 4)
    ab["time_vs_hand_fused"] = round(
        ab["rewritten"]["step_ms"] / ab["hand_fused"]["step_ms"], 4)
    return ab


def ragged_step_ab(params, cfg, steps, S=8, ctx=48, page_size=16):
    """The ragged-tick decode A/B (ISSUE r12): one serving-batch decode
    step measured two ways on identical state — the r12 one-program
    tick (``serving_tick_block`` at num_steps=1, in-graph argmax) and
    the legacy ``serving_decode_step`` it replaced. Each variant gets a
    FRESH function object (the r11 trace-cache lesson: jax keys traces
    on function identity, and a shared wrapper would hand the second
    variant the first one's jaxpr), is lowered for XLA's own
    flops/bytes accounting, then slope-timed on a chained greedy run.
    Neither variant donates the pools, so both pay the same copy —
    the RATIOS are the signal, not the absolute ms."""
    from paddle_tpu.analysis.hbm import xla_cost_analysis

    pps = -(-(ctx + steps + 8) // page_size)
    pools = L.init_serving_pages(cfg, S * pps + 1, page_size)
    kp0, vp0 = pools["k_pages"], pools["v_pages"]
    tables = jnp.asarray(
        1 + np.arange(S * pps, dtype=np.int32).reshape(S, pps))
    tok0 = jnp.zeros((S,), jnp.int32)
    len0 = jnp.full((S,), ctx, jnp.int32)

    def make_ragged():
        def step(p, tok, lengths, kp, vp):
            toks, kp, vp = L.serving_tick_block(
                p, tok, lengths, tables, kp, vp, cfg, num_steps=1)
            return toks[:, 0], lengths + 1, kp, vp
        return step

    def make_bucketed():
        def step(p, tok, lengths, kp, vp):
            logits, kp, vp = L.serving_decode_step(
                p, tok, lengths, tables, kp, vp, cfg)
            return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                    lengths + 1, kp, vp)
        return step

    n0 = max(steps // 4, 2)
    n1 = max(steps, n0 + 4)

    def measure(mk):
        jitted = jax.jit(mk())
        lowered = jitted.lower(params, tok0, len0, kp0, vp0)
        ca = xla_cost_analysis(lowered.compile())

        def run(n):
            tok, lens, kp, vp = tok0, len0, kp0, vp0
            for _ in range(n):
                tok, lens, kp, vp = jitted(params, tok, lens, kp, vp)
            int(np.asarray(tok)[0])

        with _span(f"ragged_ab.{mk.__name__}"):
            ms = slope(run, n0, n1) * 1e3
        return {"step_ms": round(ms, 4),
                "xla_flops": float(ca.get("flops", -1)),
                "xla_bytes_accessed": float(ca.get("bytes accessed", -1))}

    ab = {"slots": S, "ctx": ctx,
          "ragged": measure(make_ragged),
          "bucketed": measure(make_bucketed)}
    rb, bb = (ab["ragged"]["xla_bytes_accessed"],
              ab["bucketed"]["xla_bytes_accessed"])
    rf, bf = ab["ragged"]["xla_flops"], ab["bucketed"]["xla_flops"]
    if rb > 0 and bb > 0:
        ab["bytes_vs_bucketed"] = round(rb / bb, 4)
    if rf > 0 and bf > 0:
        ab["flops_vs_bucketed"] = round(rf / bf, 4)
    ab["time_vs_bucketed"] = round(
        ab["ragged"]["step_ms"] / ab["bucketed"]["step_ms"], 4)
    return ab


def main():
    flags = set(sys.argv[1:])
    preset = next((f for f in flags if f in PRESETS), None)
    if preset is None:
        preset = "flagship" if jax.default_backend() == "tpu" else "mid"
    bw = next((float(f.split("=")[1]) for f in flags
               if f.startswith("bw=")), 819e9)  # v5e HBM
    steps = next((int(f.split("=")[1]) for f in flags
                  if f.startswith("steps=")), 64)
    spec_draft_cost = next((float(f.split("=")[1]) for f in flags
                            if f.startswith("spec_draft_cost=")), 0.0)
    trace_path = next((f.split("=", 1)[1] for f in flags
                       if f.startswith("trace=")), None)
    if trace_path:
        global _TRACER
        from paddle_tpu.observability import SpanTracer
        _TRACER = SpanTracer()
    on_tpu = jax.default_backend() == "tpu"
    cfg = L.LlamaConfig(
        max_position_embeddings=4096,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        remat=False, use_flash_attention="pallas" if on_tpu else False,
        **PRESETS[preset])

    params = L.init_params(cfg, jax.random.PRNGKey(0))
    variants = [("fp", params)]
    if "noint8" not in flags:
        variants.append(("int8", quantize_for_decode(params, cfg)))

    out = {"preset": preset, "backend": jax.default_backend(),
           "hbm_bw_gbs": bw / 1e9, "steps": steps}
    seq = 32 + steps // 2  # mean cache length over the run
    for tag, p in variants:
        with _span(f"profile.{tag}"):
            prof = profile(p, cfg, steps)
        wbytes = decode_weight_bytes(p)
        tbytes = wbytes + kv_bytes_per_step(cfg, seq)
        ceiling = bw / tbytes
        prof.update({
            "weight_bytes_per_step": wbytes,
            "kv_bytes_per_step": kv_bytes_per_step(cfg, seq),
            "bw_ceiling_tok_per_s": ceiling,
            "ceiling_fraction": prof["tok_per_s"] / ceiling,
        })
        out[tag] = {k: (round(v, 4) if isinstance(v, float) else v)
                    for k, v in prof.items()}
        # the speculative extension of the same ceiling: per-LAUNCH
        # bandwidth bound x expected emitted tokens per verify launch
        out[tag]["spec_ceiling"] = speculative_ceiling(
            ceiling, draft_cost=spec_draft_cost)
        # the long-context extension (r16): the ceiling at 4k..100k
        # context, with the VMEM story — which rows only the tiled
        # flash-combine walk can serve on-chip
        out[tag]["long_context_ceiling"] = long_context_ceiling(
            cfg, bw, wbytes)
    if "fp" in out and "int8" in out:
        out["int8_speedup"] = round(
            out["int8"]["tok_per_s"] / out["fp"]["tok_per_s"], 4)
    if "rewrites" in flags:
        with _span("rewrite_ab"):
            out["rewrite_ab"] = rewrite_ab(params, cfg, steps)
    if "ragged" in flags:
        with _span("ragged_step_ab"):
            out["ragged_step_ab"] = ragged_step_ab(params, cfg, steps)
    if trace_path:
        out["trace"] = _TRACER.export(trace_path)

    if "json" in flags:
        print(json.dumps(out))
        return
    print(f"# decode profile — {preset} ({out['backend']}), "
          f"bw={bw/1e9:.0f} GB/s")
    hdr = ("variant | step ms | layers | tail(norm+head+sample) | embed "
           "| tok/s | bytes/step | ceiling tok/s | achieved")
    print(hdr)
    for tag, _ in variants:
        r = out[tag]
        print(f"{tag:5s} | {r['step_ms']:8.3f} | {r['layers_ms']:7.3f} | "
              f"{r['tail_ms']:7.3f} | {r['embed_ms']:6.3f} | "
              f"{r['tok_per_s']:8.1f} | {r['weight_bytes_per_step']:>11,} |"
              f" {r['bw_ceiling_tok_per_s']:8.1f} | "
              f"{r['ceiling_fraction']:.3f}")
    if "int8_speedup" in out:
        print(f"int8 speedup: {out['int8_speedup']}x")
    sc = out[variants[0][0]]["spec_ceiling"]
    print(f"\n# speculative ceiling ({variants[0][0]}, draft cost "
          f"{sc['draft_cost_per_token']}/token): expected tok/s at "
          f"acceptance alpha")
    alphas = list(next(iter(sc["table"].values())).keys())
    print("k | " + " | ".join(a.split("=")[1] for a in alphas))
    for krow, row in sc["table"].items():
        print(krow.split("=")[1] + " | "
              + " | ".join(f"{row[a]['tok_s']:.0f}" for a in alphas))
    lc = out[variants[0][0]]["long_context_ceiling"]
    print(f"\n# long-context ceiling ({variants[0][0]}, page_size "
          f"{lc['page_size']}): the rows the tiled KV walk unlocks")
    print("kv_len | ceiling tok/s | one-shot fits VMEM | walk | "
          "scratch bytes")
    for krow, row in lc["table"].items():
        print(f"{krow.split('=')[1]:>6s} | "
              f"{row['bw_ceiling_tok_per_s']:13.1f} | "
              f"{str(row['oneshot_fits_vmem']):>18s} | "
              f"{row['walk']:6s} | "
              f"{row['vmem_scratch_bytes_tiled'] or row['vmem_scratch_bytes_oneshot']:>12,}")
    if "ragged_step_ab" in out:
        ab = out["ragged_step_ab"]
        print(f"\n# ragged tick A/B (serving decode step, "
              f"S={ab['slots']}, ctx={ab['ctx']})")
        print("variant    | step ms  | XLA flops/step | XLA bytes/step")
        for tag in ("ragged", "bucketed"):
            r = ab[tag]
            print(f"{tag:10s} | {r['step_ms']:8.3f} | "
                  f"{r['xla_flops']:>14,.0f} | "
                  f"{r['xla_bytes_accessed']:>14,.0f}")
        print(f"ragged vs bucketed: flops "
              f"{ab.get('flops_vs_bucketed')}x, bytes "
              f"{ab.get('bytes_vs_bucketed')}x, time "
              f"{ab['time_vs_bucketed']}x")
    if "rewrite_ab" in out:
        ab = out["rewrite_ab"]
        print("\n# rewrite A/B (int8 decode step, unfused idiom)")
        print("variant    | step ms  | XLA bytes/step | rewrites fired")
        for tag in ("unfused", "rewritten", "hand_fused"):
            r = ab[tag]
            print(f"{tag:10s} | {r['step_ms']:8.3f} | "
                  f"{r['xla_bytes_accessed']:>14,.0f} | "
                  f"{r.get('fired', '')}")
        print(f"bytes cut vs unfused: {ab.get('bytes_cut_vs_unfused')}x; "
              f"flops cut vs unfused: {ab.get('flops_cut_vs_unfused')}x; "
              f"bytes vs hand-fused: {ab.get('bytes_vs_hand_fused')}x; "
              f"speedup vs unfused: {ab['speedup_vs_unfused']}x; "
              f"time vs hand-fused: {ab['time_vs_hand_fused']}x")


if __name__ == "__main__":
    main()

"""Per-op profile of the single-stream decode step + int8 A/B.

Decode is weight-bandwidth-bound: one greedy step must stream every
projection weight once, so the hard ceiling is

    steps/s <= HBM_bandwidth / bytes_per_step

(bytes_per_step = quantization.decode.decode_weight_bytes + the KV
cache read + the activation noise). This tool measures where the step's
time actually goes — the PERF.md decode counterpart of the train-side
device-op breakdown:

  * whole-step rate by slope timing (chained generate of N0 vs N1
    tokens, prefill and sync cancel in the difference);
  * the step TAIL in isolation — final_norm + lm_head + argmax sample
    on a captured hidden state (jitted alone);
  * embed lookup in isolation;
  * layer body = step − tail − embed (the scan over blocks, including
    the per-layer KV append + cached attention);
  * compiled-program cost_analysis (XLA's own flops / bytes-accessed
    estimate) for the f32-accounting cross-check;
  * the analytic bytes/step + ceiling at a given HBM bandwidth, and the
    fraction of that ceiling the measured rate achieves.

Runs the bf16/f32 params and (``int8`` flag) the weight-only-quantized
params through the SAME harness, printing both and the uplift.

Usage:
  python tools/decode_profile.py [flagship|deep|mid|tiny] [int8] [json]
      [bw=819e9] [steps=64]

``flagship`` is the 1.72B bench model (TPU-sized; expect minutes per
chain on CPU); ``mid`` (0.17B) profiles the same shape story at
CPU-friendly cost. Default: mid off-TPU, flagship on TPU.
"""
import json
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.models import llama as L
from paddle_tpu.quantization.decode import (decode_weight_bytes,
                                            quantize_for_decode)

PRESETS = {
    # bench.py flagship: the 1.72B decode whose 176.7 tok/s (BENCH_r05)
    # this tool exists to explain
    "flagship": dict(vocab_size=32000, hidden_size=4096,
                     intermediate_size=16384, num_hidden_layers=6,
                     num_attention_heads=32, num_key_value_heads=8),
    "deep": dict(vocab_size=32000, hidden_size=2560,
                 intermediate_size=10240, num_hidden_layers=16,
                 num_attention_heads=20, num_key_value_heads=4),
    "mid": dict(vocab_size=8192, hidden_size=1024,
                intermediate_size=4096, num_hidden_layers=8,
                num_attention_heads=8, num_key_value_heads=4),
    "tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=4, num_attention_heads=4,
                 num_key_value_heads=2),
}


def slope(run_n, n0, n1, repeats=2):
    """Per-iteration seconds: min-per-chain, then difference (the bench.py
    convention — min of the difference would pair a slowed short chain
    with a fast long one and understate dt)."""
    run_n(2)  # compile + warmup
    t_short = t_long = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        run_n(n0)
        t_short = min(t_short, time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_n(n1)
        t_long = min(t_long, time.perf_counter() - t0)
    return (t_long - t_short) / (n1 - n0)


def kv_bytes_per_step(cfg, seq_len, dtype_bytes=None):
    """K+V read traffic of one cached-attention step at cache length
    ``seq_len`` (the write is one token — noise)."""
    if dtype_bytes is None:
        dtype_bytes = jnp.dtype(cfg.dtype).itemsize
    return (2 * cfg.num_hidden_layers * seq_len * cfg.num_key_value_heads
            * cfg.head_dim * dtype_bytes)


def profile(params, cfg, steps, prompt_len=32):
    """Measured seconds per decode step, split step/tail/embed."""
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, prompt_len), 0,
                                cfg.vocab_size, dtype=jnp.int32)

    n0 = max(steps // 4, 2)
    n1 = max(steps, n0 + 4)  # slope needs n1 > n0 (steps<=2 otherwise
    #                          divides by zero in the difference)
    gens = {n: jax.jit(lambda p, t, n=n: L.generate(p, t, cfg,
                                                    max_new_tokens=n))
            for n in (2, n0, n1)}

    def run_gen(n):
        out = gens[n](params, prompt)
        int(out[0, -1])  # host read: the only reliable sync everywhere

    step_s = slope(run_gen, n0, n1)

    # tail: final_norm + lm_head + greedy sample, jitted alone on a
    # captured hidden state (chained via a data dependency so the chain
    # cannot be executed in parallel)
    h = jnp.zeros((1, cfg.hidden_size), cfg.dtype) + 0.1

    def tail_n(p, h, n):
        def body(carry, _):
            hh = L.rms_norm(carry, p["final_norm"], cfg.rms_norm_eps)
            logits = L._mm(hh, p["lm_head"]).astype(jnp.float32)
            tok = jnp.argmax(logits, axis=-1)
            # feed the token back so steps serialize
            return carry + tok.astype(carry.dtype)[:, None] * 1e-9, tok
        _, toks = jax.lax.scan(body, h, None, length=n)
        return toks

    # scan length must be static: one jit per chain length
    tails = {n: jax.jit(lambda p, h, n=n: tail_n(p, h, n))
             for n in (2, n0, n1)}

    def run_tail(n):
        int(np.asarray(tails[n](params, h))[-1, 0])

    tail_s = slope(run_tail, n0, n1)

    # embed lookup in isolation (chained through an index dependency)
    def embed_n(p, n):
        def body(tok, _):
            row = p["embed"][tok]
            nxt = (tok + jnp.int32(1) +
                   (row.sum() * 0).astype(jnp.int32)) % cfg.vocab_size
            return nxt, row.sum()
        _, s = jax.lax.scan(body, jnp.int32(0), None, length=n)
        return s

    embeds = {n: jax.jit(lambda p, n=n: embed_n(p, n))
              for n in (2, n0, n1)}

    def run_embed(n):
        float(np.asarray(embeds[n](params))[-1])

    embed_s = slope(run_embed, n0, n1)

    # XLA's own accounting of ONE decode step (prefilled cache, T=1)
    cost = {}
    try:
        cache = L.init_kv_cache(cfg, 1, prompt_len + steps)
        _, cache = jax.jit(
            lambda p, t, c: L.forward_with_cache(p, t, c, 0, cfg)
        )(params, prompt, cache)
        tok = jnp.zeros((1, 1), jnp.int32)
        lowered = jax.jit(
            lambda p, t, c: L.forward_with_cache(p, t, c,
                                                 jnp.int32(prompt_len),
                                                 cfg)
        ).lower(params, tok, cache)
        ca = lowered.compile().cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        if ca:
            cost = {"xla_flops": float(ca.get("flops", -1)),
                    "xla_bytes_accessed": float(ca.get("bytes accessed",
                                                       -1))}
    except Exception as e:  # cost_analysis is best-effort per backend
        cost = {"xla_cost_error": str(e)[:120]}

    return {
        "step_ms": step_s * 1e3,
        "tail_ms": tail_s * 1e3,          # final_norm + lm_head + sample
        "embed_ms": embed_s * 1e3,
        "layers_ms": max(step_s - tail_s - embed_s, 0.0) * 1e3,
        "tok_per_s": 1.0 / step_s,
        **cost,
    }


def main():
    flags = set(sys.argv[1:])
    preset = next((f for f in flags if f in PRESETS), None)
    if preset is None:
        preset = "flagship" if jax.default_backend() == "tpu" else "mid"
    bw = next((float(f.split("=")[1]) for f in flags
               if f.startswith("bw=")), 819e9)  # v5e HBM
    steps = next((int(f.split("=")[1]) for f in flags
                  if f.startswith("steps=")), 64)
    on_tpu = jax.default_backend() == "tpu"
    cfg = L.LlamaConfig(
        max_position_embeddings=4096,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        remat=False, use_flash_attention="pallas" if on_tpu else False,
        **PRESETS[preset])

    params = L.init_params(cfg, jax.random.PRNGKey(0))
    variants = [("fp", params)]
    if "noint8" not in flags:
        variants.append(("int8", quantize_for_decode(params, cfg)))

    out = {"preset": preset, "backend": jax.default_backend(),
           "hbm_bw_gbs": bw / 1e9, "steps": steps}
    seq = 32 + steps // 2  # mean cache length over the run
    for tag, p in variants:
        prof = profile(p, cfg, steps)
        wbytes = decode_weight_bytes(p)
        tbytes = wbytes + kv_bytes_per_step(cfg, seq)
        ceiling = bw / tbytes
        prof.update({
            "weight_bytes_per_step": wbytes,
            "kv_bytes_per_step": kv_bytes_per_step(cfg, seq),
            "bw_ceiling_tok_per_s": ceiling,
            "ceiling_fraction": prof["tok_per_s"] / ceiling,
        })
        out[tag] = {k: (round(v, 4) if isinstance(v, float) else v)
                    for k, v in prof.items()}
    if "fp" in out and "int8" in out:
        out["int8_speedup"] = round(
            out["int8"]["tok_per_s"] / out["fp"]["tok_per_s"], 4)

    if "json" in flags:
        print(json.dumps(out))
        return
    print(f"# decode profile — {preset} ({out['backend']}), "
          f"bw={bw/1e9:.0f} GB/s")
    hdr = ("variant | step ms | layers | tail(norm+head+sample) | embed "
           "| tok/s | bytes/step | ceiling tok/s | achieved")
    print(hdr)
    for tag, _ in variants:
        r = out[tag]
        print(f"{tag:5s} | {r['step_ms']:8.3f} | {r['layers_ms']:7.3f} | "
              f"{r['tail_ms']:7.3f} | {r['embed_ms']:6.3f} | "
              f"{r['tok_per_s']:8.1f} | {r['weight_bytes_per_step']:>11,} |"
              f" {r['bw_ceiling_tok_per_s']:8.1f} | "
              f"{r['ceiling_fraction']:.3f}")
    if "int8_speedup" in out:
        print(f"int8 speedup: {out['int8_speedup']}x")


if __name__ == "__main__":
    main()

"""Microbench: authored Pallas kernels vs XLA-fused baselines, on TPU.

Run: python tools/kernel_bench.py   (needs the real chip)

Methodology: per-call DEVICE time from a jax.profiler trace (sum of
jit_* device events / iterations). Wall-clock through the tunnelled
runtime carries ~70 ms/call dispatch overhead that would swamp
sub-millisecond kernels; device time is what the hardware actually
spends. Results recorded in docs/PERF.md.

``--ragged-sweep`` (r16) runs the tiled-vs-one-shot ragged
paged-attention A/B instead: a sweep over (pages_per_slot, page_size,
kv_tile_pages) geometries, ONE JSON LINE PER CONFIG on stdout (and
``--out=path`` as JSONL), each carrying a ``vmem_scratch_bytes``
column computed from the kernels' actual scratch shapes — the
evidence that tiled scratch is O(tile) while one-shot scratch grows
with the table. Per geometry the fastest variant is then recorded
through ``ops.autotune`` (key ``("ragged_kv_walk", ...)``) — the
first entry of the KForge-style autotune loop (PAPERS.md
2606.02963): block shapes searched against the bench harness, cache
picks the winner per geometry. On TPU it times device events; off
TPU it still runs end-to-end in interpreter mode (wall-clock,
``timing_honest: false`` — the smoke path; the overdue on-chip round,
ROADMAP item 3, reruns it unmodified for real numbers).

``--block-sweep`` (r23) is the flywheel's write side for the other
swept kernels: per geometry it times every candidate block shape for
``fused_rms_norm`` (row tile), the conv-epilogue matmul (tm/tn/tk),
and the dropless-MoE grouped matmul (tile_m/tile_n), one JSON row per
candidate (``tiling_source: "explicit"``), records the fastest into
the persistent winner store when ``$PADDLE_TPU_AUTOTUNE_DIR`` is set
(``ops.autotune.record`` — the geometry kwargs here match each entry
point's ``lookup`` byte-for-byte), then emits a resolution row showing
what a default call now resolves to (``tiling_source: "swept"`` vs
``"default"``). The ragged sweep records its winner the same way.

Every sweep row additionally carries the static kernel-audit verdict
(``audit: "ok" | "failed:<rule>"`` — analysis/kernel_audit.py run on
that exact geometry+tiling, no compile), and the record path runs
with ``audit=True``: a measured winner that fails KA001/KA002 is
REFUSED admission to the store — the row keeps its timing but gains
an ``audit_failed`` marker and the resolution row shows what actually
resolves without it. Fast-but-unsound never enters the flywheel.
"""
import functools
import glob
import gzip
import json
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _audit_verdict(kind, geom, config):
    """Static kernel-audit verdict for one sweep row: ``"ok"`` or
    ``"failed:<rule>"`` (KA001/KA002 gate rules), ``None`` when the
    auditor cannot run here. Pure jaxpr inspection — no compile, so
    annotating every candidate costs milliseconds."""
    try:
        from paddle_tpu.analysis import kernel_audit as ka
        v = ka.audit_config(kind, geom, config)
    except Exception:
        return None
    return "ok" if v["ok"] else "failed:" + ",".join(v["rules"])


def devtime(f, args, tag, n=5):
    y = f(*args)
    jax.block_until_ready(y)
    with jax.profiler.trace(f"/tmp/kb_{tag}"):
        for _ in range(n):
            y = f(*args)
        np.asarray(jax.tree_util.tree_leaves(y)[0].ravel()[0])
    tr = json.load(gzip.open(sorted(glob.glob(
        f"/tmp/kb_{tag}/plugins/profile/*/vm.trace.json.gz"))[-1]))
    pids = {e["pid"]: e["args"].get("name", "")
            for e in tr["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    tot = sum(e.get("dur", 0) for e in tr["traceEvents"]
              if e.get("ph") == "X"
              and "tpu" in pids.get(e.get("pid"), "").lower()
              and e["name"].startswith("jit_"))
    return tot / n / 1e3


def bench_moe():
    from paddle_tpu.ops.pallas.grouped_matmul import moe_mlp_dropless
    S, D, F, E, topk = 8192, 2048, 5632, 8, 2
    ks = jax.random.split(jax.random.PRNGKey(0), 6)
    dt = jnp.bfloat16
    x = jax.random.normal(ks[0], (S, D), dt)
    wg = jax.random.normal(ks[1], (E, D, F), dt) * 0.02
    wu = jax.random.normal(ks[2], (E, D, F), dt) * 0.02
    wd = jax.random.normal(ks[3], (E, F, D), dt) * 0.02
    logits = jax.random.normal(ks[4], (S, E), jnp.float32)
    cw, eids = jax.lax.top_k(jax.nn.softmax(logits), topk)
    cw = cw.astype(dt)
    C = topk * S // E

    # NOTE: everything is a jit ARGUMENT — closed-over device arrays
    # become compile-time constants and XLA's constant folding of the
    # routing cumsums hangs the compile for minutes
    fd = jax.jit(lambda x, eids, cw, wg, wu, wd: moe_mlp_dropless(
        x, eids, cw, wg, wu, wd, tile_m=256, tile_n=512))

    def einsum_moe(x, eids, cw, wg, wu, wd):
        # GShard capacity-1.0 dense dispatch (the incubate/moe
        # formulation): drops overflow tokens; dispatch/combine einsums
        # cost 2*S*E*C*D extra FLOPs and an [S*k, E, C] slot one-hot
        disp = jax.nn.one_hot(eids, E, dtype=dt)
        pos = jnp.cumsum(disp.reshape(S * topk, E), axis=0) - 1.0
        slot_id = jnp.where(disp.reshape(S * topk, E) > 0, pos, -1.0)
        slot = (jax.nn.one_hot(slot_id.astype(jnp.int32), C, dtype=dt)
                * disp.reshape(S * topk, E)[..., None])
        slc = (slot.reshape(S, topk, E, C) * cw[:, :, None, None]).sum(1)
        sl = slot.reshape(S, topk, E, C).sum(1)
        xe = jnp.einsum("sec,sd->ecd", sl, x)
        h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, wg)) * \
            jnp.einsum("ecd,edf->ecf", xe, wu)
        ye = jnp.einsum("ecf,efd->ecd", h, wd)
        return jnp.einsum("sec,ecd->sd", slc, ye)

    fe = jax.jit(einsum_moe)
    args = (x, eids, cw, wg, wu, wd)
    td = devtime(fd, args, "moe_drop")
    te = devtime(fe, args, "moe_ein")
    fl = 2 * 3 * S * topk * D * F
    print(f"moe S={S} D={D} F={F} E={E} top{topk} (device time):")
    print(f"  dropless gmm : {td:7.2f} ms  {fl/td/1e9:6.0f} TFLOP/s  "
          f"(0 tokens dropped)")
    print(f"  einsum (XLA) : {te:7.2f} ms  (capacity 1.0: overflow "
          f"tokens dropped; slot one-hot is 2*(S*k)^2 bytes = "
          f"{2*(S*topk)**2/2**30:.1f} GiB here, 8.6 GiB at top-8 — "
          f"the dropless glue stays O(S*k*E) int32)")
    print(f"  ratio        : {te/td:.2f}x")


def bench_rope():
    from paddle_tpu.ops.pallas.fused_norm_rope import fused_rope
    from paddle_tpu.models.llama import rope as xla_rope
    B, T, H, Hkv, Dh = 4, 2048, 32, 8, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (B, T, H, Dh),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, T, Hkv, Dh),
                          jnp.bfloat16)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))
    tf = devtime(jax.jit(
        lambda q, k: fused_rope(q, k, pos, 500000.0, 256)), (q, k), "ropef")
    tx = devtime(jax.jit(
        lambda q, k: xla_rope(q, k, pos, 500000.0, Dh)), (q, k), "ropex")
    by = (q.size + k.size) * 2 * 2 / 1e9
    print(f"rope B={B} T={T} H={H}/{Hkv} Dh={Dh} (device time):")
    print(f"  fused pallas : {tf:7.3f} ms  {by/tf*1e3:6.0f} GB/s")
    print(f"  xla          : {tx:7.3f} ms  {by/tx*1e3:6.0f} GB/s")
    print(f"  speedup      : {tx/tf:.2f}x")


def bench_rms():
    from paddle_tpu.ops.pallas.fused_norm_rope import fused_rms_norm
    from paddle_tpu.models.llama import rms_norm as xla_rms
    N, D = 16384, 4096
    x = jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.bfloat16)
    w = jnp.ones((D,), jnp.bfloat16)
    tf = devtime(jax.jit(lambda x: fused_rms_norm(x, w, 1e-5)), (x,),
                 "rmsf")
    tx = devtime(jax.jit(lambda x: xla_rms(x, w, 1e-5)), (x,), "rmsx")
    by = x.size * 2 * 2 / 1e9
    print(f"rms_norm N={N} D={D} (device time):")
    print(f"  fused pallas : {tf:7.3f} ms  {by/tf*1e3:6.0f} GB/s")
    print(f"  xla          : {tx:7.3f} ms  {by/tx*1e3:6.0f} GB/s")
    print(f"  speedup      : {tx/tf:.2f}x")


def _walltime(f, args, n=3):
    """best-of wall-clock ms/call (the off-TPU fallback — honest
    enough for interpret-mode smoke, not for perf claims)."""
    y = f(*args)
    jax.block_until_ready(y)
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def ragged_tiling_sweep(out=None, iters=3):
    """Tiled-vs-one-shot ragged paged-attention A/B (module
    docstring). Returns the list of per-config result dicts."""
    from paddle_tpu.ops import autotune as at
    from paddle_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention, vmem_scratch_bytes)
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        dt = jnp.bfloat16
        S, H, Hkv, Dh = 8, 32, 8, 128
        # pps x page_size spans the knee: 2k tokens (one-shot
        # territory) to 100k (tiled-only)
        geoms = [(128, 16), (512, 16), (2048, 16), (6250, 16)]
        tiles = (0, 8, 16, 32, 64)
    else:
        dt = jnp.float32
        S, H, Hkv, Dh = 2, 4, 2, 8
        geoms = [(8, 4), (32, 4)]
        tiles = (0, 2, 4, 8)
    rng = np.random.RandomState(0)
    results = []
    for pps, ps in geoms:
        P = S * pps + 1
        kv_len = pps * ps
        q = jnp.asarray(rng.randn(S, 1, H, Dh), dt)       # decode spans
        kp = jnp.asarray(rng.randn(Hkv, P, ps, Dh), dt)
        vp = jnp.asarray(rng.randn(Hkv, P, ps, Dh), dt)
        ql = jnp.ones((S,), jnp.int32)
        kl = jnp.full((S,), kv_len, jnp.int32)
        tabs = jnp.asarray(
            1 + np.arange(S * pps, dtype=np.int32).reshape(S, pps))
        args = (q, kp, vp, ql, kl, tabs)

        def make(tile):
            return jax.jit(functools.partial(
                ragged_paged_attention, impl="pallas",
                kv_tile_pages=tile))

        ageom = dict(pages_per_slot=pps, page_size=ps, head_dim=Dh,
                     dtype=str(jnp.dtype(dt)))
        cands, rows = [], []
        for tile in tiles:
            if tile > pps:
                continue
            scratch = vmem_scratch_bytes(pps, ps, Dh, dt,
                                         kv_tile_pages=tile)
            row = {
                "bench": "ragged_kv_walk", "pps": pps, "page_size": ps,
                "kv_len": kv_len, "slots": S, "heads": H,
                "kv_heads": Hkv, "head_dim": Dh, "dtype": str(jnp.dtype(dt)),
                "kv_tile_pages": tile,
                "walk": "tiled" if tile else "oneshot",
                "vmem_scratch_bytes": scratch,
                "timing_honest": on_tpu,
                "audit": _audit_verdict("ragged_paged_attention", ageom,
                                        {"kv_tile_pages": tile}),
            }
            # the one-shot variant past the VMEM knee cannot even
            # compile on the chip — that IS the result (the row the
            # tiled walk exists for), not a reason to abort the sweep
            if on_tpu and tile == 0 and scratch > 12 * 2 ** 20:
                rows.append(dict(row, ms=None,
                                 skipped="oneshot scratch exceeds VMEM"))
                continue
            fn = make(tile)
            try:
                if on_tpu:
                    ms = devtime(fn, args, f"rg_{pps}_{ps}_{tile}",
                                 n=iters)
                else:
                    ms = _walltime(fn, args, n=iters)
            except Exception as e:   # compile/scratch failure = a row
                rows.append(dict(row, ms=None, error=str(e)[:200]))
                continue
            rows.append(dict(row, ms=round(ms, 4)))
            cands.append((len(rows) - 1, fn))
        # the KForge-style loop's first entry: cache the measured
        # winner per geometry so a runtime dispatcher can pick it
        # (skipped/failed variants never become candidates)
        if cands:
            key = ("ragged_kv_walk", pps, ps, Dh, Hkv,
                   str(jnp.dtype(dt)))
            at.autotune(key, [f for _, f in cands], args,
                        iters=max(iters, 2))
            win_row = cands[at.cache_info()[0][key]][0]
            for i, row in enumerate(rows):
                row["autotune_winner"] = bool(i == win_row)
                row["tiling_source"] = "explicit"
            # persist the winner under the EXACT geometry key the
            # entry point's lookup uses — audit-gated: a measured
            # winner failing KA001/KA002 is refused and emits an
            # audit_failed row instead — then report what a
            # kv_tile_pages=None call now resolves to
            winner_cfg = {"kv_tile_pages":
                          rows[win_row]["kv_tile_pages"]}
            if at.store_dir():
                try:
                    at.record("ragged_paged_attention", winner_cfg,
                              audit=True, **ageom)
                except at.AutotuneAuditError as e:
                    rows.append({"bench": "ragged_kv_walk", **ageom,
                                 **winner_cfg,
                                 "audit_failed": str(e)[:200]})
            win = at.lookup("ragged_paged_attention", **ageom)
            rows.append({"bench": "ragged_kv_walk", "resolution": True,
                         **ageom, **(win or {}),
                         "tiling_source": "swept" if win else "default"})
        results.extend(rows)
    for row in results:
        print(json.dumps(row))
    if out:
        with open(out, "w") as f:
            for row in results:
                f.write(json.dumps(row) + "\n")
    return results


def block_sweep(out=None, iters=3):
    """Block-shape sweeps for the swept Pallas entry points (module
    docstring): time every candidate, record the winner per geometry
    into the persistent store, emit a resolution row. Returns the list
    of result dicts."""
    from paddle_tpu.ops import autotune as at
    from paddle_tpu.ops.pallas.conv_epilogue import matmul_bias_act
    from paddle_tpu.ops.pallas.fused_norm_rope import fused_rms_norm
    from paddle_tpu.ops.pallas.grouped_matmul import moe_mlp_dropless

    on_tpu = jax.default_backend() == "tpu"
    persist = at.store_dir() is not None
    rng = np.random.RandomState(0)
    results = []

    def timed(fn, args, tag):
        try:
            if on_tpu:
                return devtime(fn, args, tag, n=iters), None
            return _walltime(fn, args, n=max(iters, 2)), None
        except Exception as e:     # a failing candidate is a row, not an abort
            return None, str(e)[:200]

    def finish(kind, geom, cand_rows, winner_blocks):
        """Mark the winner among ``cand_rows``, persist it, then report
        what a tiles-unspecified call now resolves to. The resolution
        row is the flywheel's read-side receipt: ``swept`` only if the
        store actually answers for this geometry."""
        timed_rows = [r for r in cand_rows if r.get("ms") is not None]
        best = min(timed_rows, key=lambda r: r["ms"]) if timed_rows \
            else None
        for r in cand_rows:
            r["tiling_source"] = "explicit"
            r["timing_honest"] = on_tpu
            r["autotune_winner"] = r is best
            r["audit"] = _audit_verdict(kind, geom, winner_blocks(r))
        results.extend(cand_rows)
        if best is not None and persist:
            # audit-gated admission: fastest-but-unsound is refused
            # (the flywheel would otherwise replay the violation on
            # every future default call at this geometry)
            try:
                at.record(kind, winner_blocks(best), audit=True, **geom)
            except at.AutotuneAuditError as e:
                results.append({"bench": kind, **geom,
                                **winner_blocks(best),
                                "audit_failed": str(e)[:200]})
        win = at.lookup(kind, **geom)
        results.append({"bench": kind, "resolution": True, **geom,
                        **(win or {}),
                        "tiling_source": "swept" if win else "default"})

    # --- fused_rms_norm: row-tile sweep --------------------------------
    if on_tpu:
        rms_geoms = [(16384, 4096, jnp.bfloat16)]
        rms_tiles = (32, 64, 128, 256)
    else:
        rms_geoms = [(64, 32, jnp.float32)]
        rms_tiles = (2, 4, 8, 16)
    for n, d, dt in rms_geoms:
        x = jnp.asarray(rng.randn(n, d), dt)
        w = jnp.asarray(1.0 + 0.1 * rng.randn(d), dt)
        geom = dict(rows=n, d=d, dtype=str(jnp.dtype(dt)))
        cand = []
        for t in rms_tiles:
            if n % t:
                continue
            fn = jax.jit(functools.partial(fused_rms_norm, eps=1e-5,
                                           tile_n=t))
            ms, err = timed(fn, (x, w), f"rms_{n}_{d}_{t}")
            cand.append({"bench": "fused_rms_norm", **geom, "tile_n": t,
                         "ms": None if ms is None else round(ms, 4),
                         **({"error": err} if err else {})})
        finish("fused_rms_norm", geom, cand,
               lambda best: {"tile_n": best["tile_n"]})

    # --- conv-epilogue matmul: tm/tn/tk sweep --------------------------
    if on_tpu:
        ce_geoms = [(12544, 256, 512, jnp.bfloat16)]
        ce_tiles = [(128, 128, 256), (128, 256, 256), (256, 128, 512),
                    (256, 256, 512)]
    else:
        ce_geoms = [(64, 32, 128, jnp.float32)]
        ce_tiles = [(8, 128, 8), (16, 128, 16), (32, 128, 32),
                    (64, 128, 32)]
    for M, K, N, dt in ce_geoms:
        x2 = jnp.asarray(rng.randn(M, K), dt)
        wmat = jnp.asarray(0.05 * rng.randn(K, N), dt)
        bias = jnp.asarray(rng.randn(N), jnp.float32)
        geom = dict(M=M, K=K, N=N, dtype=str(jnp.dtype(dt)))
        sub = 16 if jnp.dtype(dt) == jnp.bfloat16 else 8
        cand = []
        for tm, tn, tk in ce_tiles:
            # a tiling the kernel would reject silently falls back to
            # jnp — that's not a candidate, it's a measurement of the
            # wrong thing
            if (M % tm or N % tn or K % tk or N % 128 or tk % sub
                    or tm % sub):
                continue
            fn = jax.jit(functools.partial(matmul_bias_act, relu=True,
                                           tiles=(tm, tn, tk)))
            ms, err = timed(fn, (x2, wmat, bias),
                            f"ce_{M}_{tm}_{tn}_{tk}")
            cand.append({"bench": "conv_epilogue", **geom,
                         "tm": tm, "tn": tn, "tk": tk,
                         "ms": None if ms is None else round(ms, 4),
                         **({"error": err} if err else {})})
        finish("conv_epilogue", geom, cand,
               lambda best: {"tm": best["tm"], "tn": best["tn"],
                             "tk": best["tk"]})

    # --- dropless-MoE grouped matmul: tile_m/tile_n sweep --------------
    if on_tpu:
        gm_geoms = [(8192, 2048, 5632, 8, 2, jnp.bfloat16)]
        gm_tiles = [(128, 128), (256, 256), (256, 512), (512, 256)]
    else:
        gm_geoms = [(32, 16, 32, 4, 2, jnp.float32)]
        gm_tiles = [(8, 16), (16, 16), (16, 32)]
    for S, D, F, E, k, dt in gm_geoms:
        ks = jax.random.split(jax.random.PRNGKey(0), 5)
        x = jax.random.normal(ks[0], (S, D), dt)
        wg = jax.random.normal(ks[1], (E, D, F), dt) * 0.02
        wu = jax.random.normal(ks[2], (E, D, F), dt) * 0.02
        wd = jax.random.normal(ks[3], (E, F, D), dt) * 0.02
        logits = jax.random.normal(ks[4], (S, E), jnp.float32)
        cw, eids = jax.lax.top_k(jax.nn.softmax(logits), k)
        cw = cw.astype(dt)
        args = (x, eids, cw, wg, wu, wd)
        geom = dict(S=S, D=D, F=F, E=E, k=k, dtype=str(jnp.dtype(dt)))
        cand = []
        for tm, tn in gm_tiles:
            # everything a jit ARGUMENT (see bench_moe) but the tiles
            # partial-bound so they stay concrete Python ints
            fn = jax.jit(functools.partial(
                lambda x, e, c, g, u, d2, tm, tn: moe_mlp_dropless(
                    x, e, c, g, u, d2, tile_m=tm, tile_n=tn),
                tm=tm, tn=tn))
            ms, err = timed(fn, args, f"gm_{S}_{tm}_{tn}")
            cand.append({"bench": "grouped_matmul", **geom,
                         "tile_m": tm, "tile_n": tn,
                         "ms": None if ms is None else round(ms, 4),
                         **({"error": err} if err else {})})
        finish("grouped_matmul", geom, cand,
               lambda best: {"tile_m": best["tile_m"],
                             "tile_n": best["tile_n"]})

    for row in results:
        print(json.dumps(row))
    if out:
        with open(out, "w") as f:
            for row in results:
                f.write(json.dumps(row) + "\n")
    return results


if __name__ == "__main__":
    if "--block-sweep" in sys.argv or "--ragged-sweep" in sys.argv:
        path = next((a.split("=", 1)[1] for a in sys.argv
                     if a.startswith("--out=")), None)
        if "--block-sweep" in sys.argv:
            block_sweep(out=path)
        else:
            ragged_tiling_sweep(out=path)
    else:
        assert jax.default_backend() == "tpu", "run on the TPU chip"
        bench_moe()
        bench_rope()
        bench_rms()

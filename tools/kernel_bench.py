"""Microbench: authored Pallas kernels vs XLA-fused baselines, on TPU.

Run: python tools/kernel_bench.py   (needs the real chip)

Methodology: per-call DEVICE time from a jax.profiler trace (sum of
jit_* device events / iterations). Wall-clock through the tunnelled
runtime carries ~70 ms/call dispatch overhead that would swamp
sub-millisecond kernels; device time is what the hardware actually
spends. Results recorded in docs/PERF.md.
"""
import glob
import gzip
import json
import time

import numpy as np
import jax
import jax.numpy as jnp


def devtime(f, args, tag, n=5):
    y = f(*args)
    jax.block_until_ready(y)
    with jax.profiler.trace(f"/tmp/kb_{tag}"):
        for _ in range(n):
            y = f(*args)
        np.asarray(jax.tree_util.tree_leaves(y)[0].ravel()[0])
    tr = json.load(gzip.open(sorted(glob.glob(
        f"/tmp/kb_{tag}/plugins/profile/*/vm.trace.json.gz"))[-1]))
    pids = {e["pid"]: e["args"].get("name", "")
            for e in tr["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    tot = sum(e.get("dur", 0) for e in tr["traceEvents"]
              if e.get("ph") == "X"
              and "tpu" in pids.get(e.get("pid"), "").lower()
              and e["name"].startswith("jit_"))
    return tot / n / 1e3


def bench_moe():
    from paddle_tpu.ops.pallas.grouped_matmul import moe_mlp_dropless
    S, D, F, E, topk = 8192, 2048, 5632, 8, 2
    ks = jax.random.split(jax.random.PRNGKey(0), 6)
    dt = jnp.bfloat16
    x = jax.random.normal(ks[0], (S, D), dt)
    wg = jax.random.normal(ks[1], (E, D, F), dt) * 0.02
    wu = jax.random.normal(ks[2], (E, D, F), dt) * 0.02
    wd = jax.random.normal(ks[3], (E, F, D), dt) * 0.02
    logits = jax.random.normal(ks[4], (S, E), jnp.float32)
    cw, eids = jax.lax.top_k(jax.nn.softmax(logits), topk)
    cw = cw.astype(dt)
    C = topk * S // E

    # NOTE: everything is a jit ARGUMENT — closed-over device arrays
    # become compile-time constants and XLA's constant folding of the
    # routing cumsums hangs the compile for minutes
    fd = jax.jit(lambda x, eids, cw, wg, wu, wd: moe_mlp_dropless(
        x, eids, cw, wg, wu, wd, tile_m=256, tile_n=512))

    def einsum_moe(x, eids, cw, wg, wu, wd):
        # GShard capacity-1.0 dense dispatch (the incubate/moe
        # formulation): drops overflow tokens; dispatch/combine einsums
        # cost 2*S*E*C*D extra FLOPs and an [S*k, E, C] slot one-hot
        disp = jax.nn.one_hot(eids, E, dtype=dt)
        pos = jnp.cumsum(disp.reshape(S * topk, E), axis=0) - 1.0
        slot_id = jnp.where(disp.reshape(S * topk, E) > 0, pos, -1.0)
        slot = (jax.nn.one_hot(slot_id.astype(jnp.int32), C, dtype=dt)
                * disp.reshape(S * topk, E)[..., None])
        slc = (slot.reshape(S, topk, E, C) * cw[:, :, None, None]).sum(1)
        sl = slot.reshape(S, topk, E, C).sum(1)
        xe = jnp.einsum("sec,sd->ecd", sl, x)
        h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, wg)) * \
            jnp.einsum("ecd,edf->ecf", xe, wu)
        ye = jnp.einsum("ecf,efd->ecd", h, wd)
        return jnp.einsum("sec,ecd->sd", slc, ye)

    fe = jax.jit(einsum_moe)
    args = (x, eids, cw, wg, wu, wd)
    td = devtime(fd, args, "moe_drop")
    te = devtime(fe, args, "moe_ein")
    fl = 2 * 3 * S * topk * D * F
    print(f"moe S={S} D={D} F={F} E={E} top{topk} (device time):")
    print(f"  dropless gmm : {td:7.2f} ms  {fl/td/1e9:6.0f} TFLOP/s  "
          f"(0 tokens dropped)")
    print(f"  einsum (XLA) : {te:7.2f} ms  (capacity 1.0: overflow "
          f"tokens dropped; slot one-hot is 2*(S*k)^2 bytes = "
          f"{2*(S*topk)**2/2**30:.1f} GiB here, 8.6 GiB at top-8 — "
          f"the dropless glue stays O(S*k*E) int32)")
    print(f"  ratio        : {te/td:.2f}x")


def bench_rope():
    from paddle_tpu.ops.pallas.fused_norm_rope import fused_rope
    from paddle_tpu.models.llama import rope as xla_rope
    B, T, H, Hkv, Dh = 4, 2048, 32, 8, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (B, T, H, Dh),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, T, Hkv, Dh),
                          jnp.bfloat16)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))
    tf = devtime(jax.jit(
        lambda q, k: fused_rope(q, k, pos, 500000.0, 256)), (q, k), "ropef")
    tx = devtime(jax.jit(
        lambda q, k: xla_rope(q, k, pos, 500000.0, Dh)), (q, k), "ropex")
    by = (q.size + k.size) * 2 * 2 / 1e9
    print(f"rope B={B} T={T} H={H}/{Hkv} Dh={Dh} (device time):")
    print(f"  fused pallas : {tf:7.3f} ms  {by/tf*1e3:6.0f} GB/s")
    print(f"  xla          : {tx:7.3f} ms  {by/tx*1e3:6.0f} GB/s")
    print(f"  speedup      : {tx/tf:.2f}x")


def bench_rms():
    from paddle_tpu.ops.pallas.fused_norm_rope import fused_rms_norm
    from paddle_tpu.models.llama import rms_norm as xla_rms
    N, D = 16384, 4096
    x = jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.bfloat16)
    w = jnp.ones((D,), jnp.bfloat16)
    tf = devtime(jax.jit(lambda x: fused_rms_norm(x, w, 1e-5)), (x,),
                 "rmsf")
    tx = devtime(jax.jit(lambda x: xla_rms(x, w, 1e-5)), (x,), "rmsx")
    by = x.size * 2 * 2 / 1e9
    print(f"rms_norm N={N} D={D} (device time):")
    print(f"  fused pallas : {tf:7.3f} ms  {by/tf*1e3:6.0f} GB/s")
    print(f"  xla          : {tx:7.3f} ms  {by/tx*1e3:6.0f} GB/s")
    print(f"  speedup      : {tx/tf:.2f}x")


if __name__ == "__main__":
    assert jax.default_backend() == "tpu", "run on the TPU chip"
    bench_moe()
    bench_rope()
    bench_rms()

"""Microbench: authored Pallas kernels vs XLA-fused baselines, on TPU.

Run: python tools/kernel_bench.py   (needs the real chip)

Methodology: per-call DEVICE time from a jax.profiler trace (sum of
jit_* device events / iterations). Wall-clock through the tunnelled
runtime carries ~70 ms/call dispatch overhead that would swamp
sub-millisecond kernels; device time is what the hardware actually
spends. Results recorded in docs/PERF.md.

``--ragged-sweep`` (r16) runs the tiled-vs-one-shot ragged
paged-attention A/B instead: a sweep over (pages_per_slot, page_size,
kv_tile_pages) geometries, ONE JSON LINE PER CONFIG on stdout (and
``--out=path`` as JSONL), each carrying a ``vmem_scratch_bytes``
column computed from the kernels' actual scratch shapes — the
evidence that tiled scratch is O(tile) while one-shot scratch grows
with the table. Per geometry the fastest variant is then recorded
through ``ops.autotune`` (key ``("ragged_kv_walk", ...)``) — the
first entry of the KForge-style autotune loop (PAPERS.md
2606.02963): block shapes searched against the bench harness, cache
picks the winner per geometry. On TPU it times device events; off
TPU it still runs end-to-end in interpreter mode (wall-clock,
``timing_honest: false`` — the smoke path; the overdue on-chip round,
ROADMAP item 3, reruns it unmodified for real numbers).
"""
import functools
import glob
import gzip
import json
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def devtime(f, args, tag, n=5):
    y = f(*args)
    jax.block_until_ready(y)
    with jax.profiler.trace(f"/tmp/kb_{tag}"):
        for _ in range(n):
            y = f(*args)
        np.asarray(jax.tree_util.tree_leaves(y)[0].ravel()[0])
    tr = json.load(gzip.open(sorted(glob.glob(
        f"/tmp/kb_{tag}/plugins/profile/*/vm.trace.json.gz"))[-1]))
    pids = {e["pid"]: e["args"].get("name", "")
            for e in tr["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    tot = sum(e.get("dur", 0) for e in tr["traceEvents"]
              if e.get("ph") == "X"
              and "tpu" in pids.get(e.get("pid"), "").lower()
              and e["name"].startswith("jit_"))
    return tot / n / 1e3


def bench_moe():
    from paddle_tpu.ops.pallas.grouped_matmul import moe_mlp_dropless
    S, D, F, E, topk = 8192, 2048, 5632, 8, 2
    ks = jax.random.split(jax.random.PRNGKey(0), 6)
    dt = jnp.bfloat16
    x = jax.random.normal(ks[0], (S, D), dt)
    wg = jax.random.normal(ks[1], (E, D, F), dt) * 0.02
    wu = jax.random.normal(ks[2], (E, D, F), dt) * 0.02
    wd = jax.random.normal(ks[3], (E, F, D), dt) * 0.02
    logits = jax.random.normal(ks[4], (S, E), jnp.float32)
    cw, eids = jax.lax.top_k(jax.nn.softmax(logits), topk)
    cw = cw.astype(dt)
    C = topk * S // E

    # NOTE: everything is a jit ARGUMENT — closed-over device arrays
    # become compile-time constants and XLA's constant folding of the
    # routing cumsums hangs the compile for minutes
    fd = jax.jit(lambda x, eids, cw, wg, wu, wd: moe_mlp_dropless(
        x, eids, cw, wg, wu, wd, tile_m=256, tile_n=512))

    def einsum_moe(x, eids, cw, wg, wu, wd):
        # GShard capacity-1.0 dense dispatch (the incubate/moe
        # formulation): drops overflow tokens; dispatch/combine einsums
        # cost 2*S*E*C*D extra FLOPs and an [S*k, E, C] slot one-hot
        disp = jax.nn.one_hot(eids, E, dtype=dt)
        pos = jnp.cumsum(disp.reshape(S * topk, E), axis=0) - 1.0
        slot_id = jnp.where(disp.reshape(S * topk, E) > 0, pos, -1.0)
        slot = (jax.nn.one_hot(slot_id.astype(jnp.int32), C, dtype=dt)
                * disp.reshape(S * topk, E)[..., None])
        slc = (slot.reshape(S, topk, E, C) * cw[:, :, None, None]).sum(1)
        sl = slot.reshape(S, topk, E, C).sum(1)
        xe = jnp.einsum("sec,sd->ecd", sl, x)
        h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, wg)) * \
            jnp.einsum("ecd,edf->ecf", xe, wu)
        ye = jnp.einsum("ecf,efd->ecd", h, wd)
        return jnp.einsum("sec,ecd->sd", slc, ye)

    fe = jax.jit(einsum_moe)
    args = (x, eids, cw, wg, wu, wd)
    td = devtime(fd, args, "moe_drop")
    te = devtime(fe, args, "moe_ein")
    fl = 2 * 3 * S * topk * D * F
    print(f"moe S={S} D={D} F={F} E={E} top{topk} (device time):")
    print(f"  dropless gmm : {td:7.2f} ms  {fl/td/1e9:6.0f} TFLOP/s  "
          f"(0 tokens dropped)")
    print(f"  einsum (XLA) : {te:7.2f} ms  (capacity 1.0: overflow "
          f"tokens dropped; slot one-hot is 2*(S*k)^2 bytes = "
          f"{2*(S*topk)**2/2**30:.1f} GiB here, 8.6 GiB at top-8 — "
          f"the dropless glue stays O(S*k*E) int32)")
    print(f"  ratio        : {te/td:.2f}x")


def bench_rope():
    from paddle_tpu.ops.pallas.fused_norm_rope import fused_rope
    from paddle_tpu.models.llama import rope as xla_rope
    B, T, H, Hkv, Dh = 4, 2048, 32, 8, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (B, T, H, Dh),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, T, Hkv, Dh),
                          jnp.bfloat16)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))
    tf = devtime(jax.jit(
        lambda q, k: fused_rope(q, k, pos, 500000.0, 256)), (q, k), "ropef")
    tx = devtime(jax.jit(
        lambda q, k: xla_rope(q, k, pos, 500000.0, Dh)), (q, k), "ropex")
    by = (q.size + k.size) * 2 * 2 / 1e9
    print(f"rope B={B} T={T} H={H}/{Hkv} Dh={Dh} (device time):")
    print(f"  fused pallas : {tf:7.3f} ms  {by/tf*1e3:6.0f} GB/s")
    print(f"  xla          : {tx:7.3f} ms  {by/tx*1e3:6.0f} GB/s")
    print(f"  speedup      : {tx/tf:.2f}x")


def bench_rms():
    from paddle_tpu.ops.pallas.fused_norm_rope import fused_rms_norm
    from paddle_tpu.models.llama import rms_norm as xla_rms
    N, D = 16384, 4096
    x = jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.bfloat16)
    w = jnp.ones((D,), jnp.bfloat16)
    tf = devtime(jax.jit(lambda x: fused_rms_norm(x, w, 1e-5)), (x,),
                 "rmsf")
    tx = devtime(jax.jit(lambda x: xla_rms(x, w, 1e-5)), (x,), "rmsx")
    by = x.size * 2 * 2 / 1e9
    print(f"rms_norm N={N} D={D} (device time):")
    print(f"  fused pallas : {tf:7.3f} ms  {by/tf*1e3:6.0f} GB/s")
    print(f"  xla          : {tx:7.3f} ms  {by/tx*1e3:6.0f} GB/s")
    print(f"  speedup      : {tx/tf:.2f}x")


def _walltime(f, args, n=3):
    """best-of wall-clock ms/call (the off-TPU fallback — honest
    enough for interpret-mode smoke, not for perf claims)."""
    y = f(*args)
    jax.block_until_ready(y)
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def ragged_tiling_sweep(out=None, iters=3):
    """Tiled-vs-one-shot ragged paged-attention A/B (module
    docstring). Returns the list of per-config result dicts."""
    from paddle_tpu.ops import autotune as at
    from paddle_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention, vmem_scratch_bytes)
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        dt = jnp.bfloat16
        S, H, Hkv, Dh = 8, 32, 8, 128
        # pps x page_size spans the knee: 2k tokens (one-shot
        # territory) to 100k (tiled-only)
        geoms = [(128, 16), (512, 16), (2048, 16), (6250, 16)]
        tiles = (0, 8, 16, 32, 64)
    else:
        dt = jnp.float32
        S, H, Hkv, Dh = 2, 4, 2, 8
        geoms = [(8, 4), (32, 4)]
        tiles = (0, 2, 4, 8)
    rng = np.random.RandomState(0)
    results = []
    for pps, ps in geoms:
        P = S * pps + 1
        kv_len = pps * ps
        q = jnp.asarray(rng.randn(S, 1, H, Dh), dt)       # decode spans
        kp = jnp.asarray(rng.randn(Hkv, P, ps, Dh), dt)
        vp = jnp.asarray(rng.randn(Hkv, P, ps, Dh), dt)
        ql = jnp.ones((S,), jnp.int32)
        kl = jnp.full((S,), kv_len, jnp.int32)
        tabs = jnp.asarray(
            1 + np.arange(S * pps, dtype=np.int32).reshape(S, pps))
        args = (q, kp, vp, ql, kl, tabs)

        def make(tile):
            return jax.jit(functools.partial(
                ragged_paged_attention, impl="pallas",
                kv_tile_pages=tile))

        cands, rows = [], []
        for tile in tiles:
            if tile > pps:
                continue
            scratch = vmem_scratch_bytes(pps, ps, Dh, dt,
                                         kv_tile_pages=tile)
            row = {
                "bench": "ragged_kv_walk", "pps": pps, "page_size": ps,
                "kv_len": kv_len, "slots": S, "heads": H,
                "kv_heads": Hkv, "head_dim": Dh, "dtype": str(jnp.dtype(dt)),
                "kv_tile_pages": tile,
                "walk": "tiled" if tile else "oneshot",
                "vmem_scratch_bytes": scratch,
                "timing_honest": on_tpu,
            }
            # the one-shot variant past the VMEM knee cannot even
            # compile on the chip — that IS the result (the row the
            # tiled walk exists for), not a reason to abort the sweep
            if on_tpu and tile == 0 and scratch > 12 * 2 ** 20:
                rows.append(dict(row, ms=None,
                                 skipped="oneshot scratch exceeds VMEM"))
                continue
            fn = make(tile)
            try:
                if on_tpu:
                    ms = devtime(fn, args, f"rg_{pps}_{ps}_{tile}",
                                 n=iters)
                else:
                    ms = _walltime(fn, args, n=iters)
            except Exception as e:   # compile/scratch failure = a row
                rows.append(dict(row, ms=None, error=str(e)[:200]))
                continue
            rows.append(dict(row, ms=round(ms, 4)))
            cands.append((len(rows) - 1, fn))
        # the KForge-style loop's first entry: cache the measured
        # winner per geometry so a runtime dispatcher can pick it
        # (skipped/failed variants never become candidates)
        if cands:
            key = ("ragged_kv_walk", pps, ps, Dh, Hkv,
                   str(jnp.dtype(dt)))
            at.autotune(key, [f for _, f in cands], args,
                        iters=max(iters, 2))
            win_row = cands[at.cache_info()[0][key]][0]
            for i, row in enumerate(rows):
                row["autotune_winner"] = bool(i == win_row)
        results.extend(rows)
    for row in results:
        print(json.dumps(row))
    if out:
        with open(out, "w") as f:
            for row in results:
                f.write(json.dumps(row) + "\n")
    return results


if __name__ == "__main__":
    if "--ragged-sweep" in sys.argv:
        path = next((a.split("=", 1)[1] for a in sys.argv
                     if a.startswith("--out=")), None)
        ragged_tiling_sweep(out=path)
    else:
        assert jax.default_backend() == "tpu", "run on the TPU chip"
        bench_moe()
        bench_rope()
        bench_rms()

"""Graph lint CLI: run the static-analysis passes over the flagship
serving graphs.

The pre-merge check (with ruff — see pyproject.toml):

    JAX_PLATFORMS=cpu python tools/graph_lint.py --ci

runs, in a few seconds and with zero XLA compiles:

  * the jaxpr lint passes (dtype-drift, host-sync,
    collective-consistency) over the flagship llama + qwen2_moe
    serving programs (`serving_prefill_chunk` at the extreme static
    prefix_pages values, the fused `serving_decode_block` tick,
    `generate_paged`) and the llama pp stage chunks;
  * the recompile-hazard pass over the flagship engine geometry —
    statically proving the ≤16-programs-per-bucket chunk-prefill
    invariant;
  * (--ci) the AST source lint over paddle_tpu/ + tools/
    (analysis/source_lint.py), plus `ruff check` when the binary is
    installed (the container image does not ship it; the AST subset
    always runs so the gate can never silently no-op).

Exit status: non-zero on any ERROR finding. `--json` emits a
machine-readable report; `--verbose` includes INFO findings (program
inventories, declared f32 islands).
"""
import argparse
import json
import os
import shutil
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_passes(limit: int):
    from paddle_tpu.analysis import (CollectiveConsistencyPass,
                                     DtypeDriftPass, HostSyncPass,
                                     RecompileHazardPass)
    return [DtypeDriftPass(), HostSyncPass(),
            RecompileHazardPass(limit=limit),
            CollectiveConsistencyPass()]


def run_graph_passes(models, limit):
    from paddle_tpu.analysis import (pp_stage_targets, run_passes,
                                     serving_targets)
    targets = []
    for m in models:
        targets += serving_targets(m)
    targets += pp_stage_targets()
    return run_passes(build_passes(limit), targets)


def run_ruff(root):
    """ruff check, when available. Returns (ran, ok, output)."""
    exe = shutil.which("ruff")
    if exe is None:
        return False, True, "ruff not installed (AST lint still ran)"
    proc = subprocess.run([exe, "check", "."], cwd=root,
                          capture_output=True, text=True)
    return True, proc.returncode == 0, proc.stdout + proc.stderr


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--models", nargs="+",
                    default=["llama", "qwen2_moe"],
                    help="flagship models to lint")
    ap.add_argument("--limit", type=int, default=16,
                    help="recompile-hazard programs-per-bucket bound")
    ap.add_argument("--ci", action="store_true",
                    help="also run the source lint (+ruff if installed)"
                         " — the pre-merge configuration")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--verbose", action="store_true",
                    help="include INFO findings")
    args = ap.parse_args(argv)

    # lint runs must not grab the TPU tunnel: tracing is platform-free
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    report = run_graph_passes(args.models, args.limit)
    ok = report.ok
    out = {"graph": report.to_dict()}

    if args.ci:
        from paddle_tpu.analysis.source_lint import lint_tree
        root = os.path.join(os.path.dirname(__file__), "..")
        src = lint_tree(root)
        out["source"] = [
            {"file": p, "rule": r, "line": ln, "message": m}
            for p, r, ln, m in src]
        ok = ok and not src
        ruff_ran, ruff_ok, ruff_out = run_ruff(root)
        out["ruff"] = {"ran": ruff_ran, "ok": ruff_ok}
        if not ruff_ok:
            out["ruff"]["output"] = ruff_out[-4000:]
        ok = ok and ruff_ok

    if args.json:
        print(json.dumps(out, indent=2))
    else:
        from paddle_tpu.analysis import Severity
        shown = 0
        for f in report.findings:
            if f.severity == Severity.INFO and not args.verbose:
                continue
            print(f)
            shown += 1
        if args.ci:
            for item in out.get("source", []):
                print(f"[error] source-lint @ {item['file']}:"
                      f"{item['line']}: {item['rule']} "
                      f"{item['message']}")
            r = out["ruff"]
            print(f"ruff: {'ok' if r['ok'] else 'FAILED'}"
                  f"{'' if r['ran'] else ' (not installed)'}")
            if not r["ok"]:
                print(out["ruff"].get("output", ""))
        print(f"graph lint: {report.summary()} -> "
              f"{'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Graph lint CLI: run the static-analysis passes over the flagship
serving AND training graphs.

The pre-merge check (with ruff — see pyproject.toml):

    JAX_PLATFORMS=cpu python tools/graph_lint.py --ci

runs, in seconds and with zero XLA compiles:

  * the jaxpr lint passes (dtype-drift, host-sync,
    collective-consistency) over the flagship llama + qwen2_moe
    serving programs (the r12 one-program tick as r16 reshaped it:
    `serving_tick` at the mixed width, the fused `serving_tick_block`
    with the in-graph sampling state traced as data — the width-S
    single-step sampling program no longer exists — and
    `generate_paged`) and the llama pp stage chunks;
  * the recompile-hazard pass over the flagship engine geometry —
    statically proving the ≤2-programs-per-packed-width one-program-
    tick invariant (`--json` carries the inventory as
    `serving_programs`);
  * the TRAINING passes (sharding-lint, donation-audit, hbm-peak,
    collective-consistency trip counts) over the llama auto-parallel
    train step at the dp / dp×mp / pp-1F1B / zero1 geometries, the
    rank-asymmetric pipeline schedules (pp2_zb W-deferral, pp4_async
    per-rank 1F1B — `--json` carries their trip/phase inventory as
    `pipeline_schedules`), plus the 1F1B stage-chunk group
    (analysis/training_graphs.py);
  * the REWRITE suite (analysis/rewrite.py): every registered rewrite
    pass applied to its flagship targets — the jnp-rmsnorm serving
    graphs and the unfused-int8 decode step — with each expected
    rewrite required to fire, the rewriter required to be idempotent,
    and every fired site verified against its exactness contract
    (bitwise / pinned tolerance) on concrete seeded inputs;
  * the CONCURRENCY suite (analysis/concurrency.py, also under
    --ci): the static guarded-by lint + lock-order cycle analysis
    over every threading.Lock/RLock in paddle_tpu/serving/ — `--json`
    carries the lock inventory, the acquisition-order graph
    (`concurrency.lock_order.edges`), per-rule counts and the
    suppression/annotation inventories; any unsuppressed finding or
    order cycle fails the run (static passes only here — the runtime
    LockTracer and the schedule fuzzer run in the test suite and
    under `serving_bench --check-invariants`);
  * the KERNELS suite (analysis/kernel_audit.py, also under --ci):
    the static Pallas kernel auditor — per registered kernel geometry
    (plus every swept winner in the autotune store) it proves the
    VMEM footprint fits the per-core budget (KA001), every index_map
    stays in bounds and the output tiling covers exactly (KA002),
    every async-copy start has a matching wait ordered before any
    read (KA003), and reduction carries over bf16/int8 inputs are f32
    (KA004); `--json` carries the per-launch VMEM table
    (`kernels.vmem`), per-rule finding counts and per-rule evaluation
    counts (the non-vacuity proof), the suppression inventory, and
    stale-waiver list — any finding, error, or stale waiver fails the
    run;
  * (--ci) the AST source lint over paddle_tpu/ + tools/
    (analysis/source_lint.py), plus `ruff check` when the binary is
    installed (the container image does not ship it; the AST subset
    always runs so the gate can never silently no-op);
  * (--planner) the auto-parallel planner smoke (analysis/planner.py:
    tiny config, 2x2 mesh): a non-empty ranked plan whose winner
    passes trace-verification under the planner contract, emitted as
    the `planner` section of `--json`.

Exit status: non-zero on any ERROR finding. `--json` emits a
machine-readable report including the per-geometry HBM peak estimates;
`--verbose` includes INFO findings (program inventories, declared f32
islands, donation inventories, HBM tops).
"""
import argparse
import json
import os
import shutil
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_passes(limit: int):
    from paddle_tpu.analysis import default_passes
    return default_passes(**{"recompile-hazard": {"limit": limit}})


def run_graph_passes(models, limit, suite="all"):
    from paddle_tpu.analysis import (pp_stage_targets, run_passes,
                                     serving_targets, training_targets)
    targets = []
    serving_pool = []
    if suite in ("all", "serving"):
        for m in models:
            serving_pool += serving_targets(m)
        targets += serving_pool
        targets += pp_stage_targets()
    if suite in ("all", "training"):
        targets += training_targets()
    passes = build_passes(limit)
    report = run_passes(passes, targets)
    hbm = next((p for p in passes if p.name == "hbm-peak"), None)
    return report, (hbm.reports if hbm is not None else {}), serving_pool


def run_ruff(root):
    """ruff check, when available. Returns (ran, ok, output)."""
    exe = shutil.which("ruff")
    if exe is None:
        return False, True, "ruff not installed (AST lint still ran)"
    proc = subprocess.run([exe, "check", "."], cwd=root,
                          capture_output=True, text=True)
    return True, proc.returncode == 0, proc.stdout + proc.stderr


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--models", nargs="+",
                    default=["llama", "qwen2_moe"],
                    help="flagship models to lint (serving suite)")
    ap.add_argument("--limit", type=int, default=16,
                    help="recompile-hazard programs-per-bucket bound")
    ap.add_argument("--suite",
                    choices=["all", "serving", "training", "rewrite",
                             "concurrency", "kernels"],
                    default="all")
    ap.add_argument("--ci", action="store_true",
                    help="also run the source lint (+ruff if installed)"
                         " — the pre-merge configuration")
    ap.add_argument("--planner", action="store_true",
                    help="also run the auto-parallel planner smoke "
                         "(tiny config, 2x2 mesh) and emit the ranked "
                         "plan + winner verification as a `planner` "
                         "section (~20s)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--verbose", action="store_true",
                    help="include INFO findings")
    args = ap.parse_args(argv)

    # lint runs must not grab the TPU tunnel, and the training
    # geometries need the virtual 8-device CPU mesh (tracing only —
    # nothing executes on the fake devices)
    from paddle_tpu.testing import force_host_cpu_devices
    force_host_cpu_devices(8)

    t0 = time.time()
    report, hbm, serving_pool = run_graph_passes(
        args.models, args.limit, args.suite)
    rw_table = None
    if args.suite in ("all", "rewrite"):
        from paddle_tpu.analysis.rewrite import run_rewrite_suite
        # reuse the lint suite's already-traced serving targets (same
        # geometry) so --suite all traces each flagship program once
        rw_findings, rw_table = run_rewrite_suite(
            models=args.models,
            serving_pool=serving_pool or None)
        report.findings.extend(rw_findings)
        report.ran.extend(
            ("rewrite-suite", row["graph"]) for row in rw_table)
    ok = report.ok
    out = {"graph": report.to_dict()}
    if args.suite in ("all", "serving"):
        # the serving-suite program-set proof, machine-readable: the
        # exact tick-program inventory the recompile-hazard pass
        # enumerated for the flagship engine geometry (--ci consumers
        # gate on programs_per_bucket <= 2)
        from paddle_tpu.analysis.recompile import program_inventory
        geoms = [t.meta["geometry"] for t in serving_pool
                 if t.meta.get("geometry") is not None
                 and getattr(t.meta["geometry"], "ragged", False)]
        geom = next((g for g in geoms if not g.spec_k), None)
        if geom is not None:
            inventory = program_inventory(geom)
            out["serving_programs"] = inventory
            # the runtime-observability contract: the recompile
            # sentinel (observability/sentinel.py) reports this SAME
            # inventory dict as `expected_programs` at runtime, so the
            # static (CI) and runtime (postmortem / sentinel report)
            # views of "what may ever compile" are one schema a
            # consumer can diff field for field
            from paddle_tpu.observability import (COMPILE_EVENT,
                                                  RECOMPILES_METRIC)
            out["observability"] = {
                "sentinel": {
                    "expected_programs": inventory,
                    "compile_event": COMPILE_EVENT,
                    "metric": RECOMPILES_METRIC,
                    "schema": "paddle_tpu.program_inventory/1",
                }}
        # the speculative engine's inventory (ISSUE r15): the same
        # schema over the draft/verify tick programs — the static
        # proof that speculation keeps ≤2 programs per width bucket
        spec_geom = next((g for g in geoms if g.spec_k), None)
        if spec_geom is not None:
            out["serving_programs_spec"] = program_inventory(spec_geom)
    if args.suite in ("all", "training"):
        # the training-schedule counterpart of serving_programs: the
        # pipeline schedules' expected trip/phase inventory (tick
        # counts, per-op-kind rank-ticks, modeled efficiency) — one
        # diffable schema next to the serving program inventory, and
        # the same numbers the collective-consistency pass pins via
        # expected_scan_trips on the traced train steps
        from paddle_tpu.analysis.training_graphs import (
            schedule_inventory)
        out["pipeline_schedules"] = schedule_inventory()
    if rw_table is not None:
        out["rewrite"] = rw_table
    if args.planner:
        # the auto-parallel planner as a CI section: the ONE shared
        # smoke space (planner.SMOKE_KNOBS — the same knobs
        # `tools/auto_parallel.py --smoke` plans) must produce a
        # non-empty ranked plan whose winner trace-verifies under the
        # planner contract — prediction-vs-trace deltas ride the same
        # Finding JSON schema as every other pass
        from paddle_tpu.analysis.planner import (SMOKE_KNOBS,
                                                 plan_auto_parallel)
        from paddle_tpu.models import llama as L
        kn = dict(SMOKE_KNOBS)
        plan = plan_auto_parallel(
            L.LlamaConfig.tiny(), kn.pop("devices"), **kn)
        out["planner"] = plan
        ok = ok and bool(plan["plans"]) and bool(
            plan.get("verification", {}).get("ok"))
    out["hbm"] = [
        {"graph": name, "peak_bytes": est.peak_bytes,
         "input_bytes": est.args_bytes,
         "top": [{"bytes": b, "value": lbl} for b, lbl in est.top]}
        for name, est in sorted(hbm.items())]

    if args.suite in ("all", "concurrency") or args.ci:
        # the static half of the concurrency analysis (guarded-by,
        # lock-order cycles, noqa discipline) over paddle_tpu/serving/
        # — pure AST, no tracing, well under the --ci 10s budget
        from paddle_tpu.analysis.concurrency import check_tree
        cres = check_tree()
        out["concurrency"] = {
            "by_rule": cres["by_rule"],
            "findings": cres["findings"],
            "suppressed": cres["suppressed"],
            "lock_free_reads": cres["lock_free_reads"],
            "requires": cres["requires"],
            "locks": cres["locks"],
            "lock_order": cres["lock_order"],
            "errors": cres["errors"],
        }
        ok = ok and not cres["findings"] and not cres["errors"]

    if args.suite in ("all", "kernels") or args.ci:
        # the Pallas kernel auditor (analysis/kernel_audit.py): static
        # VMEM/grid/DMA/accumulator proofs over every registered kernel
        # geometry plus every swept winner in the autotune store — jaxpr
        # inspection only, no Mosaic compiles, well inside the --ci
        # budget. `--json` carries the per-launch VMEM table and the
        # per-rule finding/evaluation counts; rule_evals being all
        # non-zero is the non-vacuity proof (a rule that evaluated
        # nothing proves nothing)
        from paddle_tpu.analysis.kernel_audit import run_kernel_audit
        kres = run_kernel_audit()
        out["kernels"] = kres
        ok = ok and kres["ok"]

    if args.ci:
        from paddle_tpu.analysis.source_lint import lint_tree
        root = os.path.join(os.path.dirname(__file__), "..")
        src = lint_tree(root)
        out["source"] = [
            {"file": p, "rule": r, "line": ln, "message": m}
            for p, r, ln, m in src]
        ok = ok and not src
        ruff_ran, ruff_ok, ruff_out = run_ruff(root)
        out["ruff"] = {"ran": ruff_ran, "ok": ruff_ok}
        if not ruff_ok:
            out["ruff"]["output"] = ruff_out[-4000:]
        ok = ok and ruff_ok
    out["seconds"] = round(time.time() - t0, 2)

    if args.json:
        print(json.dumps(out, indent=2))
    else:
        from paddle_tpu.analysis import Severity
        for f in report.findings:
            if f.severity == Severity.INFO and not args.verbose:
                continue
            print(f)
        if args.verbose:
            for name, est in sorted(hbm.items()):
                print(est)
        if "concurrency" in out:
            c = out["concurrency"]
            for item in c["findings"]:
                print(f"[error] {item['rule']} @ {item['path']}:"
                      f"{item['line']}: {item['message']}")
            lo = c["lock_order"]
            print(f"concurrency: {len(c['locks'])} locks, "
                  f"{len(lo['edges'])} order edges, "
                  f"{len(lo['cycles'])} cycles, "
                  f"{sum(c['by_rule'].values())} findings "
                  f"({len(c['suppressed'])} suppressed)")
        if "kernels" in out:
            k = out["kernels"]
            for item in k["findings"]:
                print(f"[error] {item['pass']} @ {item['graph']}: "
                      f"{item['message']}")
            for msg in k["errors"]:
                print(f"[error] kernel-audit: {msg}")
            for w in k["stale_waivers"]:
                print(f"[error] kernel-audit stale waiver: "
                      f"{w['kernel']} {w['rule']} {w['match']!r}")
            peak = max((row["total_bytes"] for row in k["vmem"]),
                       default=0)
            print(f"kernel audit: {len(k['kernels'])} kernels, "
                  f"{k['launches']} launches, peak VMEM "
                  f"{peak / 2**20:.2f} MiB, "
                  f"{sum(k['by_rule'].values())} findings "
                  f"({len(k['suppressed'])} suppressed)")
        if args.ci:
            for item in out.get("source", []):
                print(f"[error] source-lint @ {item['file']}:"
                      f"{item['line']}: {item['rule']} "
                      f"{item['message']}")
            r = out["ruff"]
            print(f"ruff: {'ok' if r['ok'] else 'FAILED'}"
                  f"{'' if r['ran'] else ' (not installed)'}")
            if not r["ok"]:
                print(out["ruff"].get("output", ""))
        if args.planner:
            pl = out["planner"]
            win = pl["winner"]["label"] if pl["winner"] else "<none>"
            ver = pl.get("verification", {}).get("ok")
            print(f"planner: {pl['legal']} legal plans, winner {win} "
                  f"verification {'OK' if ver else 'FAIL'}")
        print(f"graph lint: {report.summary()} in {out['seconds']}s -> "
              f"{'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

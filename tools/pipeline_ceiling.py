"""Measure the lockstep 1F1B pipeline ceiling at north-star scale.

VERDICT r4 #9: put a number on what the lockstep traced schedule costs
at pp∈{2,4,8} × M∈{8,16,32} vs the reference's interleaved-1F1B
analytic bubble. The measurement is structural (the r4-established
method): trace the ACTUAL train step on the CPU mesh and read the
schedule scan's trip count out of the jaxpr — every tick executes all
slots, so measured efficiency = M / ticks. The reference comparison is
the interleaved-1F1B bubble fraction (S-1)/(V*M + S - 1)
(pipeline_parallel.py forward_backward_pipeline, VPP chunks V).

Run: python tools/pipeline_ceiling.py   (prints a markdown table)
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402


def _scan_lengths(jaxpr, out):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            out.add(int(eqn.params["length"]))
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                _scan_lengths(inner, out)
            if isinstance(v, (list, tuple)):
                for w in v:
                    inner = getattr(w, "jaxpr", None)
                    if inner is not None:
                        _scan_lengths(inner, out)
    return out


def measure(S, M):
    from paddle_tpu.models import llama as L
    from paddle_tpu.parallel import init_hybrid_mesh

    cfg = L.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=8, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=128,
        dtype=jnp.float32, use_flash_attention=False, remat=False,
        pp_stages=S, pp_schedule="1f1b", num_microbatches=M)
    hm = init_hybrid_mesh(dp=1, pp=S, tp=1, set_global=False)
    with hm.mesh:
        step, init = L.make_train_step(cfg, hm.mesh)
        state = init(jax.random.PRNGKey(0))
        batch = L.make_batch(cfg, batch_size=M * 2, seq_len=16,
                             mesh=hm.mesh)
        jaxpr = jax.make_jaxpr(step.__wrapped__)(state, batch)
    lengths = _scan_lengths(jaxpr.jaxpr, set())
    # the schedule scan is the longest scan in the program (layer scans
    # run layers/S <= 4 steps at these configs); report what is actually
    # traced, flagging divergence from the analytic count rather than
    # refusing to measure it
    ticks = max(lengths)
    expect = M + 2 * S - 1
    if ticks != expect:
        print(f"NOTE: pp={S} M={M}: traced schedule runs {ticks} ticks, "
              f"analytic model says {expect}", flush=True)
    return ticks


def main():
    print("| pp | M | measured ticks | lockstep eff M/ticks | "
          "ref 1F1B eff (V=1) | ref interleaved eff (V=2) |")
    print("|---|---|---|---|---|---|")
    for S in (2, 4, 8):
        for M in (8, 16, 32):
            ticks = measure(S, M)
            lockstep = M / ticks
            ref1 = 1 - (S - 1) / (M + S - 1)
            refv = 1 - (S - 1) / (2 * M + S - 1)
            print(f"| {S} | {M} | {ticks} | {lockstep:.3f} | "
                  f"{ref1:.3f} | {refv:.3f} |")


if __name__ == "__main__":
    main()

"""Measure pipeline schedule efficiency at north-star scale — A/B over
the lockstep scan, rank-asymmetric 1F1B, and ZB-style W-deferral.

The measurement is structural (the r4-established method): trace the
ACTUAL train step on the CPU mesh and read the schedule scan's trip
count out of the jaxpr — for the rank-asymmetric schedules the scan
lives inside the shard_map body, which the shared jaxpr walker
(analysis/collectives.scan_trip_counts) sees through. Per schedule the
efficiency those ticks imply:

  * lockstep  — every tick runs all S slots fwd+bwd (masked fill/drain
                included), so efficiency = M / ticks;
  * 1f1b      — rank-asymmetric half-step ticks (one F or one full
                backward per rank), useful = 2·V·M per rank, so
                efficiency = 2·V·M / ticks (= the reference per-rank
                1F1B bubble 1 - (S-1)/(VM+S-1) when the builder hits
                its bound — asserted);
  * zb        — F / input-grad B / deferred weight-grad W unit ticks,
                useful = 3·M per rank, efficiency = 3·M / ticks.

Reference comparison columns: the interleaved-1F1B analytic bubble
(pipeline_parallel.py forward_backward_pipeline, VPP chunks V).

Composed geometries (r19): ``--dp``/``--tp`` run the SAME tick-count
A/B with data/tensor parallelism composed into the async schedules'
shard_map (the op-table scan is along pp only, so tick counts — and
therefore the efficiency columns — must be IDENTICAL to the dp=tp=1
run at every geometry; the measured table in docs/PERF.md r19 pins
that parity). dp·tp·pp must fit the 8 virtual host devices.

Run:  python tools/pipeline_ceiling.py
      python tools/pipeline_ceiling.py --schedule lockstep 1f1b zb \
          --json out.json
      python tools/pipeline_ceiling.py --schedule zb --pp 2 --dp 2
"""
import argparse
import json
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

#: CLI schedule name -> (cfg.pp_schedule, useful rank-ticks factor x M
#: — 1 lockstep fwd+bwd tick, 2 half-step F/B ticks, 3 F/B/W unit
#: ticks). The model name comes from the one exported
#: parallel.pipeline_async.PP_SCHEDULES mapping, so this tool cannot
#: drift from the executor dispatch.
SCHEDULES = {
    "lockstep": ("1f1b", 1),
    "1f1b": ("1f1b_async", 2),
    "zb": ("zb", 3),
}


def measure(S, M, schedule, dp=1, tp=1):
    """Trace the real train step, return (ticks, efficiency)."""
    from paddle_tpu.analysis.collectives import scan_trip_counts
    from paddle_tpu.models import llama as L
    from paddle_tpu.parallel import init_hybrid_mesh
    from paddle_tpu.parallel.pipeline_1f1b import schedule_ticks
    from paddle_tpu.parallel.pipeline_async import PP_SCHEDULES

    pp_schedule, factor = SCHEDULES[schedule]
    model = PP_SCHEDULES[pp_schedule][0]
    cfg = L.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=8, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=128,
        dtype=jnp.float32, use_flash_attention=False, remat=False,
        pp_stages=S, pp_schedule=pp_schedule, num_microbatches=M)
    hm = init_hybrid_mesh(dp=dp, pp=S, tp=tp, set_global=False)
    with hm.mesh:
        step, init = L.make_train_step(cfg, hm.mesh)
        state = init(jax.random.PRNGKey(0))
        batch = L.make_batch(cfg, batch_size=M * 2 * dp, seq_len=16,
                             mesh=hm.mesh)
        jaxpr = jax.make_jaxpr(step.__wrapped__)(state, batch)
    # exclude the per-stage layer scans (trip count <= layers) so an
    # analytic tick count that happens to collide with one can never
    # mask a schedule/model desync; at tiny M the schedule scan itself
    # can run <= layers ticks, so fall back to the unfiltered set
    # rather than measuring nothing
    all_lengths = set(scan_trip_counts(jaxpr))
    lengths = {n for n in all_lengths if n > cfg.num_hidden_layers}
    if not lengths:
        lengths = all_lengths
    expect = schedule_ticks(S, M, 1, schedule=model)
    if expect in lengths:
        ticks = expect
    else:
        # report what is actually traced, flagging divergence from the
        # analytic count rather than refusing to measure it
        ticks = max(lengths)
        print(f"NOTE: pp={S} M={M} {schedule}: traced schedule runs "
              f"{ticks} ticks, analytic model says {expect}",
              flush=True)
    return ticks, factor * M / ticks


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--schedule", nargs="+",
                    choices=sorted(SCHEDULES), default=["lockstep",
                                                        "1f1b", "zb"])
    ap.add_argument("--pp", nargs="+", type=int, default=[2, 4, 8])
    ap.add_argument("--mb", nargs="+", type=int, default=[8, 16, 32])
    ap.add_argument("--dp", type=int, default=1,
                    help="data-parallel degree composed into the "
                         "schedules (r19); batch rows shard over it")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree composed into the "
                         "stage bodies (r19, manual collectives)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the efficiency table as JSON")
    args = ap.parse_args(argv)

    rows = []
    cols = " | ".join(f"{s} eff" for s in args.schedule)
    geo = (f" (dp={args.dp} tp={args.tp})"
           if args.dp > 1 or args.tp > 1 else "")
    print(f"| pp | M | {cols} | ref 1F1B eff (V=1) | "
          f"ref interleaved eff (V=2) |{geo}")
    print("|---|---|" + "---|" * (len(args.schedule) + 2))
    for S in args.pp:
        for M in args.mb:
            effs = {}
            for sched in args.schedule:
                ticks, eff = measure(S, M, sched, dp=args.dp,
                                     tp=args.tp)
                effs[sched] = {"ticks": ticks, "efficiency": round(eff,
                                                                   4)}
            ref1 = 1 - (S - 1) / (M + S - 1)
            refv = 1 - (S - 1) / (2 * M + S - 1)
            rows.append({"pp": S, "microbatches": M,
                         "dp": args.dp, "tp": args.tp,
                         "schedules": effs,
                         "ref_1f1b_eff": round(ref1, 4),
                         "ref_interleaved_v2_eff": round(refv, 4)})
            cells = " | ".join(
                f"{effs[s]['efficiency']:.3f} ({effs[s]['ticks']}t)"
                for s in args.schedule)
            print(f"| {S} | {M} | {cells} | {ref1:.3f} | {refv:.3f} |")
    out = {"schema": "paddle_tpu.pipeline_ceiling/2", "rows": rows}
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Eager-dispatch microbenchmark: µs per op with and without the vjp
cache (FLAGS_eager_vjp_cache). Run on any backend; numbers in
docs/PERF.md come from the CPU host (the overhead being measured is
host-side Python/tracing, not device compute).
"""
import time

import numpy as np


def bench(label, n=300):
    import paddle_tpu as pt

    x = pt.to_tensor(np.random.randn(64, 64).astype("float32"),
                     stop_gradient=False)
    w = pt.to_tensor(np.random.randn(64, 64).astype("float32"),
                     stop_gradient=False)

    def chain():
        y = pt.matmul(x, w)
        y = pt.nn.functional.relu(y)
        y = y + x
        y = y * 0.5
        return y.sum()

    chain()  # warm caches (1st occurrence registers keys,
    chain()  # 2nd occurrence builds the jitted entries)
    t0 = time.perf_counter()
    for _ in range(n):
        chain()
    fwd_us = (time.perf_counter() - t0) / n / 5 * 1e6

    loss = chain()
    loss.backward()
    t0 = time.perf_counter()
    for _ in range(n):
        x.clear_grad()
        w.clear_grad()
        loss = chain()
        loss.backward()
    fb_us = (time.perf_counter() - t0) / n / 5 * 1e6
    print(f"{label}: fwd {fwd_us:7.1f} us/op   fwd+bwd {fb_us:7.1f} us/op")
    return fwd_us, fb_us


def main():
    import paddle_tpu as pt

    pt.set_flags({"FLAGS_eager_vjp_cache": False})
    off = bench("vjp cache OFF")
    pt.set_flags({"FLAGS_eager_vjp_cache": True})
    on = bench("vjp cache ON ")
    print(f"speedup: fwd {off[0]/on[0]:.2f}x   fwd+bwd {off[1]/on[1]:.2f}x")


if __name__ == "__main__":
    main()

"""Traffic-replay serving benchmark: sequential vs DynamicBatcher vs
the continuous-batching ServingEngine.

Replays one synthetic mixed-length request trace (Poisson arrivals,
mixed prompt lengths, mixed max_new_tokens) through three serving
strategies over the SAME model params:

  (a) sequential    — one `generate_paged` per request, in arrival
                      order (no batching at all);
  (b) batcher       — `inference.DynamicBatcher` whole-request ragged
                      batching: mixed-length prompts coalesce into one
                      paged decode, but every batch runs the GLOBAL
                      max_new_tokens and a request's tokens only
                      surface when the whole batch finishes;
  (c) engine        — `serving.ServingEngine` continuous batching:
                      per-step admission/retirement over the shared
                      page pool, tokens streamed as decoded.

Reported per mode: wall_s, useful tok/s (only each request's OWN
requested tokens count), time-to-first-token p50/p99 (ms), and mean
batch occupancy where defined. Acceptance (ISSUE r6): (c) beats (b) on
aggregate tok/s AND p99 TTFT on the CPU mesh.

    JAX_PLATFORMS=cpu python tools/serving_bench.py --requests 32
"""
import argparse
import json
import os
import sys
import threading
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_trace(n, rate, max_prompt, mnt_choices, seed):
    """[(arrival_s, prompt int32[?], max_new_tokens)] sorted by arrival.
    mnt_choices is a SMALL set so every mode compiles a bounded number
    of programs."""
    rng = np.random.RandomState(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
    trace = []
    for t in arrivals:
        plen = int(rng.randint(2, max_prompt + 1))
        prompt = rng.randint(0, 256, (plen,)).astype(np.int32)
        trace.append((float(t), prompt, int(rng.choice(mnt_choices))))
    return trace


def _bucket(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _pctl(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else float("nan")


def _report(name, wall, useful, ttfts, occupancy=None):
    out = {"mode": name, "wall_s": round(wall, 3),
           "useful_tokens": int(useful),
           "tok_s": round(useful / wall, 1),
           "ttft_p50_ms": round(_pctl(ttfts, 50) * 1e3, 1),
           "ttft_p99_ms": round(_pctl(ttfts, 99) * 1e3, 1)}
    if occupancy is not None:
        out["occupancy_mean"] = round(occupancy, 3)
    return out


class Bench:
    def __init__(self, args):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama as L
        self.jnp = jnp
        self.L = L
        self.args = args
        self.cfg = L.LlamaConfig(
            vocab_size=256, hidden_size=args.hidden,
            intermediate_size=2 * args.hidden,
            num_hidden_layers=args.layers,
            num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=args.max_prompt + max(args.mnt_choices),
            dtype=jnp.float32, use_flash_attention=False, remat=False)
        self.params = L.init_params(self.cfg, jax.random.PRNGKey(0))
        # the ENGINE's bucket policy, so every mode pads to the same
        # shapes as the engine under test (no silent drift)
        from paddle_tpu.serving.engine import _default_buckets
        self.buckets = _default_buckets(args.max_prompt)
        self.mnt_cap = max(args.mnt_choices)
        # one jitted ragged generate per (B, Tb, mnt): shared by (a)/(b)
        self._gen = jax.jit(
            partial(L.generate_paged, cfg=self.cfg, page_size=args.page_size),
            static_argnames=("max_new_tokens",))

    def _pad(self, prompts):
        lens = [len(p) for p in prompts]
        tb = _bucket(max(lens), self.buckets)
        out = np.zeros((len(prompts), tb), np.int32)
        for i, p in enumerate(prompts):
            out[i, :len(p)] = p
        return out, np.asarray(lens, np.int32)

    # ------------------------------------------------------------ modes ----
    def run_sequential(self, trace):
        jnp = self.jnp
        t0 = time.perf_counter()
        useful, ttfts = 0, []
        for arrival, prompt, mnt in trace:
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            padded, lens = self._pad([prompt])
            out = self._gen(self.params, jnp.asarray(padded),
                            jnp.asarray(lens), max_new_tokens=mnt)
            np.asarray(out)  # block
            ttfts.append(time.perf_counter() - t0 - arrival)
            useful += mnt
        return _report("sequential", time.perf_counter() - t0, useful,
                       ttfts)

    def run_batcher(self, trace):
        """Whole-request ragged batching: the r5 serving shape. Every
        batch decodes the GLOBAL mnt cap (the batcher cannot retire rows
        early), and a request's TTFT is its whole batch's completion."""
        from paddle_tpu.inference import DynamicBatcher
        jnp = self.jnp
        cap = self.mnt_cap

        def fn(batch, lengths):
            out = self._gen(self.params, jnp.asarray(batch),
                            jnp.asarray(lengths), max_new_tokens=cap)
            return np.asarray(out)

        bat = DynamicBatcher(fn, max_batch_size=self.args.max_batch,
                             max_delay_ms=self.args.batch_delay_ms,
                             seq_buckets=self.buckets)
        t0 = time.perf_counter()
        done_t, lock = {}, threading.Lock()
        futs = []
        for i, (arrival, prompt, mnt) in enumerate(trace):
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            fut = bat.submit(prompt)

            def _mark(f, i=i):
                with lock:
                    done_t[i] = time.perf_counter()
            fut.add_done_callback(_mark)
            futs.append(fut)
        for f in futs:
            f.result()
        wall = time.perf_counter() - t0
        bat.close()
        useful = sum(mnt for _, _, mnt in trace)
        ttfts = [done_t[i] - t0 - trace[i][0] for i in range(len(trace))]
        return _report("batcher", wall, useful, ttfts)

    def run_engine(self, trace):
        from paddle_tpu.serving import ServingEngine
        a = self.args
        eng = ServingEngine(
            self.params, self.cfg, max_batch=a.max_batch,
            page_size=a.page_size, max_prompt_len=a.max_prompt,
            max_new_tokens_cap=self.mnt_cap,
            prompt_buckets=self.buckets,
            decode_block_size=a.decode_block)
        t0 = time.perf_counter()
        handles = []
        for arrival, prompt, mnt in trace:
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            handles.append(eng.submit(prompt, mnt))
        outs = [h.result(timeout=600) for h in handles]
        wall = time.perf_counter() - t0
        snap = eng.stats()
        eng.close()
        useful = sum(len(o) for o in outs)
        ttfts = [h.ttft_s for h in handles]
        occ = snap["histograms"]["batch_occupancy"]["mean"]
        return _report("engine", wall, useful, ttfts, occupancy=occ)

    def warmup(self, modes):
        """Compile the selected modes' program shapes outside the timed
        runs (only theirs — the full grid is seconds of XLA compiles)."""
        warm = [(0.0, np.arange(1, 1 + ln, dtype=np.int32) % 200, mnt)
                for ln in self.buckets for mnt in self.args.mnt_choices]
        if "sequential" in modes:
            self.run_sequential(warm)
        if "batcher" in modes:
            # warm the (batch-bucket, seq-bucket) grid at the cap
            jnp = self.jnp
            bb = 1
            while True:
                for tb in self.buckets:
                    padded = np.ones((bb, tb), np.int32)
                    lens = np.full((bb,), tb, np.int32)
                    np.asarray(self._gen(self.params, jnp.asarray(padded),
                                         jnp.asarray(lens),
                                         max_new_tokens=self.mnt_cap))
                if bb >= self.args.max_batch:
                    break
                bb = min(bb * 2, self.args.max_batch)
        if "engine" in modes:
            # one prefill per prompt bucket + the decode step
            self.run_engine([(0.0, np.ones((b,), np.int32), 2)
                             for b in self.buckets])


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=96)
    ap.add_argument("--rate", type=float, default=500.0,
                    help="arrival rate, requests/sec (keep the system "
                         "LOADED: an underloaded trace measures the "
                         "arrival window, not serving capacity)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--max-prompt", type=int, default=32)
    ap.add_argument("--mnt-choices", type=int, nargs="+",
                    default=[4, 8, 16, 48])
    ap.add_argument("--batch-delay-ms", type=float, default=4.0)
    ap.add_argument("--decode-block", type=int, default=4,
                    help="fused greedy decode steps per engine tick")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--modes", nargs="+",
                    default=["sequential", "batcher", "engine"])
    args = ap.parse_args(argv)

    bench = Bench(args)
    trace = build_trace(args.requests, args.rate, args.max_prompt,
                        args.mnt_choices, args.seed)
    bench.warmup(args.modes)
    results = {}
    for mode in args.modes:
        results[mode] = getattr(bench, f"run_{mode}")(list(trace))
        print(json.dumps(results[mode]), flush=True)
    if "engine" in results and "batcher" in results:
        verdict = {
            "engine_beats_batcher_tok_s":
                results["engine"]["tok_s"] > results["batcher"]["tok_s"],
            "engine_beats_batcher_ttft_p99":
                results["engine"]["ttft_p99_ms"]
                < results["batcher"]["ttft_p99_ms"],
        }
        print(json.dumps(verdict), flush=True)
        results["verdict"] = verdict
    return results


if __name__ == "__main__":
    main(sys.argv[1:])

"""Traffic-replay serving benchmark: sequential vs DynamicBatcher vs
the continuous-batching ServingEngine.

Replays one synthetic mixed-length request trace (Poisson arrivals,
mixed prompt lengths, mixed max_new_tokens) through three serving
strategies over the SAME model params:

  (a) sequential    — one `generate_paged` per request, in arrival
                      order (no batching at all);
  (b) batcher       — `inference.DynamicBatcher` whole-request ragged
                      batching: mixed-length prompts coalesce into one
                      paged decode, but every batch runs the GLOBAL
                      max_new_tokens and a request's tokens only
                      surface when the whole batch finishes;
  (c) engine        — `serving.ServingEngine` continuous batching:
                      per-step admission/retirement over the shared
                      page pool, tokens streamed as decoded.

Reported per mode: wall_s, useful tok/s (only each request's OWN
requested tokens count), time-to-first-token p50/p99 (ms), and mean
batch occupancy where defined. Acceptance (ISSUE r6): (c) beats (b) on
aggregate tok/s AND p99 TTFT on the CPU mesh.

``--shared-prefix N`` prepends one fixed N-token header to every prompt
(the common-system-prompt workload the r8 prefix cache targets) and adds
prefix-cache counters to the engine row. The ``prefix_ab`` mode emits
the ISSUE r8 acceptance numbers directly: cold-vs-warm TTFT on one
shared prefix, pages saved, and the max decode stall an in-flight stream
feels while a max-length prompt is admitted — chunked vs unchunked
prefill.

``--speculative`` serves the engine mode with self-drafting (n-gram)
speculative decoding (``--spec-k`` caps drafts); the ``spec_ab`` mode
emits the ISSUE r15 acceptance numbers: target-model launches per
emitted token, speculative vs plain greedy, on a repetitive
single-stream workload — with outputs asserted bitwise-equal across
the arms.

``--replicas N`` (the ``fleet`` mode) drives the serving FLEET
(paddle_tpu/serving/fleet/): N engine replicas behind the
prefix-affinity router, a multi-turn multi-session shared-prefix
workload A/B'd against forced round-robin (the hit-rate claim), a
flood 1-vs-N scaling arm, and a kill-one-replica scenario
(drain-on-failure: queued hand-back + re-dispatch, zero drops, clean
survivor sentinels). ``--arrival seed:K`` pins a replayable arrival
schedule (inter-arrival + length draws) independent of content.

    JAX_PLATFORMS=cpu python tools/serving_bench.py --requests 32
    JAX_PLATFORMS=cpu python tools/serving_bench.py \
        --shared-prefix 24 --modes engine prefix_ab
    JAX_PLATFORMS=cpu python tools/serving_bench.py \
        --replicas 4 --arrival seed:1
"""
import argparse
import json
import os
import sys
import threading
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


class ArrivalSpec:
    """Replayable HEAVY-TAILED schedule (``--arrival lognormal:K[:s]``
    / ``pareto:K[:a]``): inter-arrival gaps, prompt lengths and output
    lengths all draw from the heavy-tailed law instead of the uniform/
    exponential defaults — the production traffic shape (a few huge
    prompts/outputs among many small ones) that convoy/admission
    policies must be measured under. Same replay contract as
    ``seed:K``: the spec string alone reproduces the schedule bitwise,
    whatever ``--seed`` says about content.

    Gaps keep MEAN ``1/rate`` so ``--rate`` means the same offered
    load across laws: lognormal uses ``mu = ln(1/rate) - sigma^2/2``;
    Pareto (Lomax) scales by ``(alpha-1)/rate`` and needs
    ``alpha > 1`` for the mean to exist. Lengths map a mean-1 draw of
    the same law onto ``[lo, hi]`` (mass near ``lo``, rare spikes
    capped at ``hi``); output lengths pick from the sorted
    ``--mnt-choices`` by the same draw (small outputs common, the big
    choice rare)."""

    def __init__(self, kind, seed, param=None):
        if kind not in ("lognormal", "pareto"):
            raise ValueError(f"unknown arrival law {kind!r}")
        self.kind = kind
        self.seed = int(seed)
        self.param = 1.5 if param is None else float(param)
        if kind == "pareto" and self.param <= 1.0:
            raise ValueError("pareto alpha must be > 1 (finite mean), "
                             f"got {self.param}")
        if kind == "lognormal" and self.param <= 0.0:
            raise ValueError("lognormal sigma must be > 0, "
                             f"got {self.param}")

    def __repr__(self):
        return f"ArrivalSpec({self.kind}:{self.seed}:{self.param})"

    def gaps(self, sched, rate, n):
        """n inter-arrival gaps with mean 1/rate."""
        if self.kind == "lognormal":
            s = self.param
            mu = np.log(1.0 / rate) - 0.5 * s * s
            return sched.lognormal(mu, s, n)
        a = self.param
        return sched.pareto(a, n) * (a - 1.0) / rate

    def _unit(self, sched):
        """One mean-1 draw of the law (shared by lengths + mnt)."""
        return float(self.gaps(sched, 1.0, 1)[0])

    def length(self, sched, lo, hi):
        """Heavy-tailed int length in [lo, hi]."""
        lo, hi = int(lo), int(hi)
        if hi <= lo:
            return lo
        # mean-1 draw scaled so the typical draw sits in the lower
        # third of the span; the tail hits hi and is capped there
        d = self._unit(sched) * (hi - lo) / 3.0
        return lo + min(int(d), hi - lo)

    def pick(self, sched, choices):
        """Heavy-tailed pick over sorted choices (small ones common)."""
        cs = sorted(int(c) for c in choices)
        i = int(self._unit(sched) * len(cs) / 2.0)
        return cs[min(i, len(cs) - 1)]


def parse_arrival(spec):
    """``--arrival`` spec -> schedule-RNG seed, :class:`ArrivalSpec`,
    or None (legacy: the schedule rides the content seed).

    * ``seed:K`` — dedicated, replayable arrival schedule (ROADMAP
      item 5's first slice): the SAME ``seed:K`` reproduces identical
      inter-arrival gaps, prompt lengths and mnt draws whatever
      ``--seed`` says, so fleet A/Bs and the kill-replica scenario
      replay bit-identical schedules while varying content.
    * ``lognormal:K[:sigma]`` / ``pareto:K[:alpha]`` — same replay
      contract with HEAVY-TAILED gaps + lengths (:class:`ArrivalSpec`;
      defaults sigma=1.5, alpha=1.5)."""
    if spec is None:
        return None
    if isinstance(spec, str):
        if spec.startswith("seed:"):
            return int(spec.split(":", 1)[1])
        parts = spec.split(":")
        if parts[0] in ("lognormal", "pareto") and len(parts) in (2, 3):
            return ArrivalSpec(parts[0], int(parts[1]),
                               float(parts[2]) if len(parts) == 3
                               else None)
    raise ValueError(f"--arrival must be 'seed:K', 'lognormal:K[:s]' "
                     f"or 'pareto:K[:a]', got {spec!r}")


def build_trace(n, rate, max_prompt, mnt_choices, seed, shared_prefix=0,
                arrival=None):
    """[(arrival_s, prompt int32[?], max_new_tokens)] sorted by arrival.
    mnt_choices is a SMALL set so every mode compiles a bounded number
    of programs. shared_prefix > 0 prepends one fixed token header to
    EVERY prompt (the common-system-prompt serving shape the prefix
    cache exists for). ``arrival`` (see :func:`parse_arrival`) splits
    the SCHEDULE draws (inter-arrival gaps, prompt lengths, mnt
    choices) onto their own seeded RNG, leaving ``seed`` to govern
    content only."""
    rng = np.random.RandomState(seed)
    heavy = isinstance(arrival, ArrivalSpec)
    sched = rng if arrival is None else np.random.RandomState(
        arrival.seed if heavy else arrival)
    arrivals = np.cumsum(arrival.gaps(sched, rate, n) if heavy
                         else sched.exponential(1.0 / rate, n))
    header = (rng.randint(0, 256, (shared_prefix,)).astype(np.int32)
              if shared_prefix else None)
    lo = min(shared_prefix + 2, max_prompt)
    trace = []
    for t in arrivals:
        plen = (arrival.length(sched, max(lo, 2), max_prompt) if heavy
                else int(sched.randint(max(lo, 2), max_prompt + 1)))
        prompt = rng.randint(0, 256, (plen,)).astype(np.int32)
        if header is not None:
            prompt[:shared_prefix] = header
        mnt = (arrival.pick(sched, mnt_choices) if heavy
               else int(sched.choice(mnt_choices)))
        trace.append((float(t), prompt, mnt))
    return trace


def build_session_trace(groups, group_size, rate, header_tokens,
                        tail_lo, tail_hi, mnt_choices, seed,
                        arrival=None):
    """Multi-session shared-prefix workload for the FLEET modes: ``groups``
    sessions, each with its own fixed ``header_tokens``-token header
    (system prompt), ``group_size`` requests per session with random
    tails, arrival order interleaved across sessions by the schedule
    RNG. Returns ``[(arrival_s, group_id, prompt, mnt)]``. This is the
    workload where routing decides the hit rate: affinity keeps each
    session's header on ONE replica (~1 cold prefill per session);
    round-robin scatters it over N cold tries."""
    rng = np.random.RandomState(seed)
    heavy = isinstance(arrival, ArrivalSpec)
    sched = rng if arrival is None else np.random.RandomState(
        arrival.seed if heavy else arrival)
    headers = [rng.randint(0, 256, (header_tokens,)).astype(np.int32)
               for _ in range(groups)]
    order = np.repeat(np.arange(groups), group_size)
    sched.shuffle(order)
    arrivals = np.cumsum(arrival.gaps(sched, rate, order.size) if heavy
                         else sched.exponential(1.0 / rate, order.size))
    trace = []
    for t, g in zip(arrivals, order):
        tlen = (arrival.length(sched, tail_lo, tail_hi) if heavy
                else int(sched.randint(tail_lo, tail_hi + 1)))
        tail = rng.randint(0, 256, (tlen,)).astype(np.int32)
        prompt = np.concatenate([headers[int(g)], tail])
        mnt = (arrival.pick(sched, mnt_choices) if heavy
               else int(sched.choice(mnt_choices)))
        trace.append((float(t), int(g), prompt, mnt))
    return trace


def _bucket(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _pctl(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else float("nan")


def _report(name, wall, useful, ttfts, occupancy=None):
    out = {"mode": name, "wall_s": round(wall, 3),
           "useful_tokens": int(useful),
           "tok_s": round(useful / wall, 1),
           "ttft_p50_ms": round(_pctl(ttfts, 50) * 1e3, 1),
           "ttft_p99_ms": round(_pctl(ttfts, 99) * 1e3, 1)}
    if occupancy is not None:
        out["occupancy_mean"] = round(occupancy, 3)
    return out


class Bench:
    def __init__(self, args):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama as L
        self.jnp = jnp
        self.L = L
        self.args = args
        self.cfg = L.LlamaConfig(
            vocab_size=256, hidden_size=args.hidden,
            intermediate_size=2 * args.hidden,
            num_hidden_layers=args.layers,
            num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=args.max_prompt + max(args.mnt_choices),
            dtype=jnp.float32, use_flash_attention=False, remat=False)
        self.params = L.init_params(self.cfg, jax.random.PRNGKey(0))
        # the ENGINE's bucket policy, so every mode pads to the same
        # shapes as the engine under test (no silent drift)
        from paddle_tpu.serving.engine import _default_buckets
        self.buckets = _default_buckets(args.max_prompt)
        self.mnt_cap = max(args.mnt_choices)
        # one jitted ragged generate per (B, Tb, mnt): shared by (a)/(b)
        self._gen = jax.jit(
            partial(L.generate_paged, cfg=self.cfg, page_size=args.page_size),
            static_argnames=("max_new_tokens",))

    def _pad(self, prompts):
        lens = [len(p) for p in prompts]
        tb = _bucket(max(lens), self.buckets)
        out = np.zeros((len(prompts), tb), np.int32)
        for i, p in enumerate(prompts):
            out[i, :len(p)] = p
        return out, np.asarray(lens, np.int32)

    # ------------------------------------------------------------ modes ----
    def run_sequential(self, trace):
        jnp = self.jnp
        t0 = time.perf_counter()
        useful, ttfts = 0, []
        for arrival, prompt, mnt in trace:
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            padded, lens = self._pad([prompt])
            out = self._gen(self.params, jnp.asarray(padded),
                            jnp.asarray(lens), max_new_tokens=mnt)
            np.asarray(out)  # block
            ttfts.append(time.perf_counter() - t0 - arrival)
            useful += mnt
        return _report("sequential", time.perf_counter() - t0, useful,
                       ttfts)

    def run_batcher(self, trace):
        """Whole-request ragged batching: the r5 serving shape. Every
        batch decodes the GLOBAL mnt cap (the batcher cannot retire rows
        early), and a request's TTFT is its whole batch's completion."""
        from paddle_tpu.inference import DynamicBatcher
        jnp = self.jnp
        cap = self.mnt_cap

        def fn(batch, lengths):
            out = self._gen(self.params, jnp.asarray(batch),
                            jnp.asarray(lengths), max_new_tokens=cap)
            return np.asarray(out)

        bat = DynamicBatcher(fn, max_batch_size=self.args.max_batch,
                             max_delay_ms=self.args.batch_delay_ms,
                             seq_buckets=self.buckets)
        t0 = time.perf_counter()
        done_t, lock = {}, threading.Lock()
        futs = []
        for i, (arrival, prompt, mnt) in enumerate(trace):
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            fut = bat.submit(prompt)

            def _mark(f, i=i):
                with lock:
                    done_t[i] = time.perf_counter()
            fut.add_done_callback(_mark)
            futs.append(fut)
        for f in futs:
            f.result()
        wall = time.perf_counter() - t0
        bat.close()
        useful = sum(mnt for _, _, mnt in trace)
        ttfts = [done_t[i] - t0 - trace[i][0] for i in range(len(trace))]
        return _report("batcher", wall, useful, ttfts)

    def _mk_engine(self, **over):
        from paddle_tpu.serving import ServingEngine
        a = self.args
        kw = dict(max_batch=a.max_batch, page_size=a.page_size,
                  max_prompt_len=a.max_prompt,
                  max_new_tokens_cap=self.mnt_cap,
                  prompt_buckets=self.buckets,
                  decode_block_size=a.decode_block,
                  prefix_cache=not a.no_prefix_cache,
                  prefill_chunk=a.prefill_chunk or None,
                  admission_window=a.admission_window,
                  cold_tier_bytes=getattr(a, "cold_tier", 0),
                  rewrites=getattr(a, "rewrites", False),
                  # None = env default; True = per-tick paged-KV
                  # invariant checking (violations raise inside the
                  # tick -> every handle errors -> main exits non-zero)
                  check_invariants=a.check_invariants or None)
        if a.speculative:
            kw.update(speculative="ngram", spec_k=a.spec_k)
        kw.update(over)
        return ServingEngine(self.params, self.cfg, **kw)

    def run_engine(self, trace):
        a = self.args
        # explicit flags must win over fleet-wide env defaults: --trace
        # with PADDLE_TPU_SERVING_TRACE=0 would export zero spans, and
        # --check-invariants with PADDLE_TPU_SERVING_SENTINEL=0 would
        # silently skip the sentinel gate it documents
        over = {}
        if a.trace:
            over["trace"] = True
        if a.check_invariants:
            over["recompile_sentinel"] = True
        eng = self._mk_engine(**over)
        if a.speculative:
            # the verify program's reachable widths depend on per-tick
            # draft counts — traffic cannot be trusted to cover them,
            # so compile the whole static inventory deterministically
            eng.warm_programs()
        # warmup (bench.warmup) already compiled every width-grid entry
        # and the fused block; from here any compile is a warmed-run
        # regression the sentinel must name
        eng.arm_sentinel()
        # --sample-frac: that fraction of requests submit with
        # temperature/top-p sampling (fused in-graph sampler, r16) —
        # deterministic per bench seed. Sampling is DATA to the tick,
        # so the armed sentinel doubles as the proof that sampled
        # traffic compiles NOTHING beyond the warmed inventory.
        sampled = (np.random.RandomState(a.seed).rand(len(trace))
                   < a.sample_frac)
        t0 = time.perf_counter()
        handles = []
        for i, (arrival, prompt, mnt) in enumerate(trace):
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            kw = (dict(temperature=a.temperature, top_p=0.95, seed=i)
                  if sampled[i] else {})
            handles.append(eng.submit(prompt, mnt, **kw))
        outs = [h.result(timeout=600) for h in handles]
        wall = time.perf_counter() - t0
        snap = eng.stats()
        sentinel = (eng.sentinel.report() if eng.sentinel is not None
                    else None)
        if a.trace:
            eng.export_trace(a.trace)
        if a.check_invariants:
            # final standalone audit on top of the per-tick checks —
            # the post-drain state (page leaks) is only visible here
            violations = eng.audit()
            if violations:
                eng.close()
                raise SystemExit(
                    "serving_bench --check-invariants: "
                    + "; ".join(str(v) for v in violations))
            # --check-invariants also gates on a CLEAN recompile
            # sentinel: a post-warmup compile means the static
            # program-set proof and the runtime program set diverged
            if sentinel is not None and not sentinel["clean"]:
                eng.close()
                raise SystemExit(
                    "serving_bench --check-invariants: recompile "
                    f"sentinel tripped — "
                    f"{sentinel['post_warmup_compiles']} post-warmup "
                    f"XLA compile(s): "
                    + "; ".join(
                        f"during={e['during']} "
                        f"({e['compile_s'] * 1e3:.0f} ms)"
                        for e in sentinel["events"]
                        if e["phase"] == "post_warmup"))
        eng.close()
        useful = sum(len(o) for o in outs)
        ttfts = [h.ttft_s for h in handles]
        occ = snap["histograms"]["batch_occupancy"]["mean"]
        out = _report("engine", wall, useful, ttfts, occupancy=occ)
        c = snap["counters"]
        if a.shared_prefix and not a.no_prefix_cache:
            denom = max(c["prefix_hits"] + c["prefix_misses"], 1)
            out["prefix_hit_rate"] = round(c["prefix_hits"] / denom, 3)
            out["prefix_hit_tokens"] = int(c["prefix_hit_tokens"])
            out["prefix_pages_saved"] = int(c["prefix_pages_saved"])
            out["prefix_hit_tokens_per_sec"] = round(
                c["prefix_hit_tokens"] / wall, 1)
        st = snap["histograms"]["decode_stall_s"]
        if st["count"]:
            out["decode_stall_max_ms"] = round(st["max"] * 1e3, 1)
        if a.speculative:
            out["spec"] = {
                "spec_ticks": int(c["spec_ticks"]),
                "draft_tokens": int(c["draft_tokens"]),
                "draft_accepted": int(c["draft_accepted"]),
                "acceptance": round(
                    c["draft_accepted"] / max(c["draft_tokens"], 1), 3),
                "launches_per_token": round(
                    c["decode_steps"] / max(c["tokens_out"], 1), 3)}
        if sentinel is not None:
            out["sentinel"] = {
                "clean": sentinel["clean"],
                "post_warmup_compiles":
                    sentinel["post_warmup_compiles"]}
        if a.trace:
            out["trace"] = a.trace
        return out

    def run_trace_overhead(self, trace, reps=6):
        """Measured cost of span tracing (ISSUE r13 acceptance): the
        same unpaced flood replayed through engines that differ ONLY
        in ``trace=`` — interleaved traced/untraced repeats so
        co-tenant CPU drift hits both arms, best-of-``reps`` per arm,
        per-tick wall = replay wall / engine ticks. The slow test pins
        ``overhead_ratio`` ≤ 1.03 (docs/OBSERVABILITY.md). Invariant
        checking and the sentinel are OFF in both arms (their host
        work would mask the tracer's)."""
        kw = dict(check_invariants=False, recompile_sentinel=False)
        # pay every compile before either timed arm
        eng = self._mk_engine(trace=False, **kw)
        rng = np.random.RandomState(self.args.seed + 4)
        for b in self.buckets:
            p = rng.randint(0, 256, (b,)).astype(np.int32)
            eng.submit(p, self.mnt_cap).result(timeout=600)
        eng.close()

        def replay_once(traced):
            eng = self._mk_engine(trace=traced, **kw)
            t0 = time.perf_counter()
            handles = [eng.submit(prompt, mnt)
                       for _, prompt, mnt in trace]
            for h in handles:
                h.result(timeout=600)
            wall = time.perf_counter() - t0
            ticks = eng._tick_no
            spans = len(eng.tracer.spans()) + eng.tracer.dropped
            eng.close()
            return wall / max(ticks, 1), spans

        per_tick = {True: [], False: []}
        spans_traced = 0
        for _ in range(reps):
            for traced in (True, False):
                t, n = replay_once(traced)
                per_tick[traced].append(t)
                if traced:
                    spans_traced = max(spans_traced, n)
        t_on, t_off = min(per_tick[True]), min(per_tick[False])
        return {"mode": "trace_overhead",
                "tick_ms_traced": round(t_on * 1e3, 4),
                "tick_ms_untraced": round(t_off * 1e3, 4),
                "overhead_ratio": round(t_on / t_off, 4),
                "spans_recorded": int(spans_traced),
                "reps": reps,
                "within_3pct": bool(t_on / t_off <= 1.03)}

    # -------------------------------------------- prefix / chunk A-Bs ----
    def _ab_geometry(self):
        """The A-B runs at prompt lengths where prefill COST (not fixed
        dispatch overhead) dominates — at the default tiny trace shapes
        a whole prefill costs ~2 ms against ~1 ms of per-call overhead
        and both effects drown. 128+ tokens puts prefill well clear of
        the noise floor on the CPU mesh."""
        from paddle_tpu.serving.engine import _default_buckets
        a = self.args
        ab_len = max(a.max_prompt, 256)
        if a.shared_prefix:
            # honor the user's shared FRACTION (their --shared-prefix is
            # sized for the --max-prompt trace), rescaled to ab_len — a
            # 24-of-256-token share would measure nothing
            shared = int(ab_len * a.shared_prefix / a.max_prompt)
        else:
            shared = 7 * ab_len // 8
        shared = min(shared, ab_len - 4)
        chunk = a.prefill_chunk or max(
            (ab_len // 8) // a.page_size, 1) * a.page_size
        return ab_len, shared, chunk, _default_buckets(ab_len)

    def run_prefix_ab(self, trace=None):
        """Controlled cold-vs-warm TTFT on one shared prefix, plus the
        max decode stall an in-flight stream feels while a max-length
        prompt is admitted — chunked vs unchunked. Emitted as one JSON
        row; the ISSUE r8 acceptance numbers."""
        a = self.args
        rng = np.random.RandomState(a.seed + 1)
        ab_len, shared, chunk, buckets = self._ab_geometry()
        header = rng.randint(0, 256, (shared,)).astype(np.int32)
        tail = ab_len - shared

        def mk_prompt():
            return np.concatenate(
                [header, rng.randint(0, 256, (tail,)).astype(np.int32)])

        mnt = min(self.mnt_cap, 8)
        eng = self._mk_engine(max_prompt_len=ab_len,
                              prompt_buckets=buckets)
        # compile the COLD-path shapes outside the timed submissions,
        # with token values that cannot seed the measured prefix chain
        warm_p = (mk_prompt() + 1) % 256
        eng.submit(warm_p, mnt).result(timeout=600)
        # compile the WARM-path shape (suffix bucket x attached-page
        # count) too: a second throwaway-header request hits the first
        # one's chain with exactly the measured geometry
        eng.submit(((mk_prompt() + 1) % 256), mnt).result(timeout=600)
        # median of 3 cold/warm PAIRS, each on a fresh header (cold
        # prefill time swings 2x with co-tenant CPU load; one sample
        # proves nothing)
        colds, warms = [], []
        for i in range(3):
            header[:] = rng.randint(0, 256, (shared,))
            h_cold = eng.submit(mk_prompt(), mnt)
            h_cold.result(timeout=600)
            h_warm = eng.submit(mk_prompt(), mnt)
            h_warm.result(timeout=600)
            colds.append(h_cold.ttft_s)
            warms.append(h_warm.ttft_s)
        snap = eng.stats()
        eng.close()
        c = snap["counters"]
        cold_s = float(np.median(colds))
        warm_s = float(np.median(warms))

        out = {
            "mode": "prefix_ab",
            "shared_prefix_tokens": int(shared),
            "ttft_cold_ms": round(cold_s * 1e3, 1),
            "ttft_warm_ms": round(warm_s * 1e3, 1),
            "warm_ttft_speedup": round(cold_s / max(warm_s, 1e-9), 2),
            "prefix_hit_tokens": int(c["prefix_hit_tokens"]),
            "prefix_pages_saved": int(c["prefix_pages_saved"]),
            "stall_unchunked_ms": self._admission_stall(None),
            "stall_chunked_ms": self._admission_stall(chunk),
        }
        out["prefill_chunk_tokens"] = int(chunk)
        out["stall_reduced"] = (out["stall_chunked_ms"]
                                < out["stall_unchunked_ms"])
        return out

    def _admission_stall(self, chunk):
        """Max inter-token gap (ms) an in-flight VICTIM stream feels
        while a max-length intruder is admitted mid-stream — the
        latency a user actually observes. Pre-r12 the admission's
        prefill ran as a separate program BETWEEN decode ticks (the
        engine's ``decode_stall_s`` histogram measured it directly);
        the ragged one-program tick folds prefill INTO the tick, so
        the between-tick gap is structurally ~0 and the felt latency
        is the tick's own duration: chunking bounds it by capping the
        per-tick prefill token budget (= the packed program width).
        Median of 3 fresh-engine repeats (any single gap swings with
        co-tenant CPU load)."""
        rng = np.random.RandomState(self.args.seed + 2)
        ab_len, _, _, buckets = self._ab_geometry()
        mnt = min(self.mnt_cap, 24)
        victim_p = rng.randint(0, 256, (2,)).astype(np.int32)
        intruder_p = rng.randint(0, 256, (ab_len,)).astype(np.int32)
        stalls = []
        for _ in range(3):
            eng = self._mk_engine(prefill_chunk=chunk,
                                  prefix_cache=False, max_batch=2,
                                  max_prompt_len=ab_len,
                                  prompt_buckets=buckets,
                                  decode_block_size=1)
            # compile victim decode + intruder prefill shapes (the jit
            # cache is shared across engines, so only the first repeat
            # can ever pay a compile)
            eng.submit(intruder_p, 2).result(timeout=600)
            h = eng.submit(victim_p, mnt)
            it = iter(h)
            next(it)
            next(it)                   # victim is mid-decode
            h2 = eng.submit(intruder_p, 2)
            gap, last = 0.0, time.perf_counter()
            for _tok in it:            # live timestamps: tick + stall
                now = time.perf_counter()
                gap = max(gap, now - last)
                last = now
            h.result(timeout=600)
            h2.result(timeout=600)
            eng.close()
            stalls.append(gap)
        return round(float(np.median(stalls)) * 1e3, 1)

    # ------------------------------------------------- ragged vs bucketed --
    def run_ragged_ab(self, trace):
        """ISSUE r12 acceptance A/B: the one-program ragged tick vs the
        legacy bucketed path (whole-prompt ``serving_prefill`` per
        prompt bucket run BETWEEN ``serving_decode_block`` ticks — the
        pre-r12 program structure, replayed synchronously over the same
        Scheduler/PagePool). Three measurements in one JSON row:

        * ``program_set`` — the STATIC program-set sizes both dispatch
          models reach at this geometry under EXACT prefix attach
          (attach_quantum=1, what the ragged tick gives for free), from
          the recompile-hazard pass's two enumerations. Deterministic:
          this is the structural claim, provable without running;
        * per-arm replay stats over the same trace — tok/s, TTFT
          p50/p99, measured per-decode-step latency, max between-tick
          stall, and the MEASURED compile count (fresh jit objects per
          arm, per the r11 trace-cache lesson);
        * ``tick_latency_*`` — a controlled chained pure-decode A/B on
          matched state (same slots, lengths, tables, pools, fused
          block size): the parity number the slow test pins.
        """
        from paddle_tpu.analysis.recompile import (
            enumerate_chunk_programs, enumerate_tick_programs)
        from paddle_tpu.analysis.serving_graphs import engine_geometry
        a = self.args
        geom = engine_geometry(
            page_size=a.page_size, max_prompt_len=a.max_prompt,
            max_new_tokens_cap=self.mnt_cap,
            prefill_chunk=a.prefill_chunk or None,
            prompt_buckets=self.buckets, prefix_cache=True,
            max_batch=a.max_batch, decode_block=a.decode_block)
        tick_progs = enumerate_tick_programs(geom)
        chunk_progs = enumerate_chunk_programs(geom)
        ragged_set = sum(len(v) for v in tick_progs.values())
        # + one whole-prompt prefill per bucket + the fused decode block
        bucketed_set = (sum(len(v) for v in chunk_progs.values())
                        + len(self.buckets) + 1)
        ragged_worst = max((len(v) for v in tick_progs.values()),
                           default=0)
        bucketed_worst = max((len(v) for v in chunk_progs.values()),
                             default=0)
        rag = self._replay_ragged(trace)
        buck = self._replay_bucketed(trace)
        t_rag = self._tick_chain("ragged")
        t_buck = self._tick_chain("bucketed")
        ratio = t_rag / t_buck if t_buck > 0 else float("nan")
        out = {
            "mode": "ragged_ab",
            "program_set": {"ragged": int(ragged_set),
                            "bucketed": int(bucketed_set),
                            "ragged_worst_per_bucket": int(ragged_worst),
                            "bucketed_worst_per_bucket":
                                int(bucketed_worst)},
            "ragged": rag,
            "bucketed": buck,
            "tick_latency_ragged_ms": round(t_rag * 1e3, 3),
            "tick_latency_bucketed_ms": round(t_buck * 1e3, 3),
            "tick_latency_ratio": round(ratio, 3),
            # the documented parity band (docs/PERF.md, pinned <=1.10
            # by test_ragged_ab_acceptance)
            "tick_parity": bool(ratio <= 1.10),
        }
        return out

    def _replay_ragged(self, trace):
        """The real engine over the trace, twice: a warm pass to pay
        (and then count) the compiles, then a paced pass for the
        latency/throughput stats. Fresh jit objects via a cleared step-
        fn cache, so ``_cache_size`` counts THIS geometry's programs.

        The warm pass submits SEQUENTIALLY, one bucket-length prompt at
        the mnt cap per width-grid entry: each request runs alone, so
        it exercises both its mixed-tick width AND the pure-decode
        fused block (a flooded warm pass never reaches pure decode —
        spans are always pending — and the block would then compile in
        the middle of the measured pass).

        Invariant checking stays OFF unless --check-invariants was
        passed: the suite-wide env default would add per-tick audit
        host work to the engine arm that the bucketed sim never pays,
        skewing the A/B."""
        from paddle_tpu.serving import engine as _em
        _em._JIT_CACHE.clear()
        check = self.args.check_invariants or False
        eng = self._mk_engine(check_invariants=check)
        rng = np.random.RandomState(self.args.seed + 3)
        for b in self.buckets:
            p = rng.randint(0, 256, (b,)).astype(np.int32)
            eng.submit(p, self.mnt_cap).result(timeout=600)
        eng.close()
        # warm fns via the step-fn cache
        eng = self._mk_engine(check_invariants=check)
        t0 = time.perf_counter()
        handles = []
        for arrival, prompt, mnt in trace:
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            handles.append(eng.submit(prompt, mnt))
        outs = [h.result(timeout=600) for h in handles]
        wall = time.perf_counter() - t0
        snap = eng.stats()
        compiles = (eng._tick_jit._cache_size()
                    + eng._block_jit._cache_size())
        eng.close()
        useful = sum(len(o) for o in outs)
        ttfts = [h.ttft_s for h in handles]
        out = _report("ragged", wall, useful, ttfts)
        out["decode_step_p50_ms"] = round(
            snap["histograms"]["decode_step_s"]["p50"] * 1e3, 3)
        st = snap["histograms"]["decode_stall_s"]
        out["stall_max_ms"] = round(st["max"] * 1e3, 1) if st["count"] \
            else 0.0
        out["compiles"] = int(compiles)
        return out

    def _replay_bucketed(self, trace):
        """The pre-r12 program structure, replayed synchronously: on
        admission, ONE whole-prompt prefill program (right-padded to
        its prompt bucket — one compile per bucket) runs between decode
        ticks; decode is the fused ``serving_decode_block``. Same
        Scheduler/PagePool, same admission policy, greedy only."""
        import jax
        from paddle_tpu.inference.paged_kv import PagePool
        from paddle_tpu.serving.scheduler import (COMPLETED, Request,
                                                  Scheduler)
        jnp, Lm, a = self.jnp, self.L, self.args
        k = a.decode_block
        max_bucket = self.buckets[-1]
        ps = a.page_size
        pps = -(-(max_bucket + self.mnt_cap - 1) // ps)
        prefill = jax.jit(partial(Lm.serving_prefill, cfg=self.cfg),
                          donate_argnums=(4, 5))
        block = jax.jit(partial(Lm.serving_decode_block, cfg=self.cfg),
                        donate_argnums=(4, 5),
                        static_argnames=("num_steps",))

        def replay(paced):
            pool = PagePool(total_pages=a.max_batch * pps + 1,
                            page_size=ps)
            sched = Scheduler(max_batch=a.max_batch, pages_per_slot=pps,
                              pool=pool, max_prompt_len=max_bucket)
            pools = Lm.init_serving_pages(self.cfg, pool.total_pages, ps)
            kp, vp = pools["k_pages"], pools["v_pages"]
            cur = np.zeros((a.max_batch,), np.int32)
            produced = np.zeros((a.max_batch,), np.int64)
            arrival_of, ttfts, steps = {}, [], []
            useful = 0
            stall_max, last_tick_end = 0.0, None
            i = 0
            t0 = time.perf_counter()
            while True:
                now = time.perf_counter() - t0
                while i < len(trace) and (trace[i][0] <= now
                                          or not paced):
                    arr, prompt, mnt = trace[i]
                    req = Request(prompt, mnt)
                    sched.submit(req)
                    arrival_of[id(req)] = arr
                    i += 1
                for slot, req in sched.admit():
                    n = req.prompt.size
                    tb = _bucket(n, self.buckets)
                    padded = np.zeros((1, tb), np.int32)
                    padded[0, :n] = req.prompt
                    logits, kp, vp = prefill(
                        self.params, jnp.asarray(padded), jnp.int32(n),
                        jnp.asarray(sched.tables[slot]), kp, vp)
                    tok = int(np.argmax(np.asarray(logits)))
                    sched.lengths[slot] = n
                    cur[slot] = tok
                    produced[slot] = 1
                    useful += 1
                    ttfts.append(time.perf_counter() - t0
                                 - arrival_of[id(req)])
                    if produced[slot] >= req.max_new_tokens:
                        sched.retire(slot, COMPLETED)
                        produced[slot] = 0
                live = sched.live()
                if live:
                    td0 = time.perf_counter()
                    toks, kp, vp = block(
                        self.params, jnp.asarray(cur),
                        jnp.asarray(sched.lengths),
                        jnp.asarray(sched.tables), kp, vp, num_steps=k)
                    toks = np.asarray(toks)
                    td1 = time.perf_counter()
                    steps.append((td1 - td0) / k)
                    if last_tick_end is not None:
                        stall_max = max(stall_max, td0 - last_tick_end)
                    last_tick_end = td1
                    for slot, req in live:
                        sched.lengths[slot] += k
                        for j in range(k):
                            cur[slot] = int(toks[slot, j])
                            produced[slot] += 1
                            useful += 1
                            if produced[slot] >= req.max_new_tokens:
                                sched.retire(slot, COMPLETED)
                                produced[slot] = 0
                                break
                    continue
                if i >= len(trace) and not sched.queued():
                    break
                if paced and i < len(trace):
                    nxt = trace[i][0] - (time.perf_counter() - t0)
                    if nxt > 0:
                        time.sleep(min(nxt, 0.05))
            return (time.perf_counter() - t0, useful, ttfts, steps,
                    stall_max)

        replay(paced=False)                      # pay the compiles
        wall, useful, ttfts, steps, stall = replay(paced=True)
        out = _report("bucketed", wall, useful, ttfts)
        out["decode_step_p50_ms"] = round(
            float(np.median(steps)) * 1e3, 3) if steps else float("nan")
        out["stall_max_ms"] = round(stall * 1e3, 1)
        out["compiles"] = int(prefill._cache_size()
                              + block._cache_size())
        return out

    # ------------------------------------------------- speculative A/B ----
    def run_spec_ab(self, trace=None):
        """ISSUE r15 acceptance A/B: speculative vs plain greedy decode
        on a self-drafting repetitive workload, single stream (the
        motivating perf number — docs/PERF.md decode section). The
        MEASURED win is structural and CPU-visible: target-model
        LAUNCHES per emitted token (``decode_steps / tokens_out`` —
        each launch streams every weight once, so on-chip this ratio
        IS the bandwidth-ceiling uplift; the wall-time A/B rides the
        next TPU round). Both arms replay the same requests; spec
        outputs are asserted bitwise-equal to the plain arm's.

        The workload: tiled 4-token-pattern prompts (fixed seeds —
        greedy decode of the bench model settles into repetitive
        attractors the n-gram drafter locks onto; deterministic, so
        the slow test pins the measured ratio and acceptance)."""
        a = self.args
        k = a.spec_k
        mnt = a.spec_mnt
        pats = []
        for s in (2, 5, 2, 5):
            rng = np.random.RandomState(s)
            pats.append(np.tile(
                rng.randint(0, 256, (4,)).astype(np.int32), 6)[:24])
        kw = dict(max_batch=1, page_size=8, max_prompt_len=32,
                  max_new_tokens_cap=mnt, prompt_buckets=[32],
                  decode_block_size=1, prefix_cache=False,
                  prefill_chunk=None, admission_window=0,
                  check_invariants=a.check_invariants or False)

        def run(spec):
            over = dict(kw)
            if spec:
                over.update(speculative="ngram", spec_k=k)
            else:
                over.update(speculative=None)
            eng = self._mk_engine(**over)
            eng.warm_programs()
            # one throwaway request pays any remaining host-side cache
            # warmup outside the measured pass
            eng.submit((pats[0] + 1) % 256, 4).result(timeout=600)
            if a.check_invariants:
                eng.arm_sentinel()
            base = eng.stats()["counters"]
            t0 = time.perf_counter()
            outs = [eng.submit(p, mnt).result(timeout=600)
                    for p in pats]
            wall = time.perf_counter() - t0
            c = eng.stats()["counters"]
            sentinel = (eng.sentinel.report()
                        if a.check_invariants and eng.sentinel is not None
                        else None)
            if a.check_invariants:
                violations = eng.audit()
                if violations:
                    eng.close()
                    raise SystemExit("spec_ab --check-invariants: "
                                     + "; ".join(map(str, violations)))
            eng.close()
            launches = c["decode_steps"] - base["decode_steps"]
            tokens = c["tokens_out"] - base["tokens_out"]
            row = {"wall_s": round(wall, 3),
                   "tok_s": round(tokens / wall, 1),
                   "target_launches": int(launches),
                   "tokens": int(tokens),
                   "launches_per_token": round(launches / tokens, 4)}
            if spec:
                dt = c["draft_tokens"] - base["draft_tokens"]
                da = c["draft_accepted"] - base["draft_accepted"]
                row.update(
                    spec_ticks=int(c["spec_ticks"] - base["spec_ticks"]),
                    draft_tokens=int(dt), draft_accepted=int(da),
                    acceptance=round(da / max(dt, 1), 4))
            if sentinel is not None:
                row["sentinel_clean"] = bool(sentinel["clean"])
                if not sentinel["clean"]:
                    raise SystemExit(
                        "spec_ab --check-invariants: recompile sentinel "
                        f"tripped — {sentinel['post_warmup_compiles']} "
                        "post-warmup compile(s)")
            return row, outs

        plain, outs_p = run(False)
        spec, outs_s = run(True)
        exact = all(np.array_equal(x, y)
                    for x, y in zip(outs_p, outs_s))
        ratio = (plain["launches_per_token"]
                 / max(spec["launches_per_token"], 1e-9))
        return {
            "mode": "spec_ab", "spec_k": int(k),
            "requests": len(pats), "mnt": int(mnt),
            "plain": plain, "spec": spec,
            "acceptance": spec["acceptance"],
            "launch_reduction": round(ratio, 3),
            "bitwise_equal": bool(exact),
            # the ISSUE r15 acceptance bar, pinned by the slow test
            "meets_bar": bool(ratio >= 1.8
                              and spec["acceptance"] >= 0.7
                              and exact),
        }

    # ------------------------------------------------------- fleet mode ----
    def _session_trace(self):
        a = self.args
        header = a.fleet_header or max(2 * a.page_size, 16)
        header = min(header, a.max_prompt - 6)
        tail_lo, tail_hi = 4, max(5, a.max_prompt - header)
        mnts = [m for m in a.mnt_choices if m <= 16] or \
            [min(a.mnt_choices)]
        return build_session_trace(
            a.fleet_groups, a.fleet_group_size, a.rate, header,
            tail_lo, tail_hi, mnts, a.seed,
            arrival=parse_arrival(a.arrival)), header

    def _proc_spec(self):
        """WorkerSpec mirroring this bench's cfg + engine geometry —
        every spawned worker re-derives the SAME weights
        (params_seed=0 == the parent's PRNGKey(0)), so proc and
        in-process arms decode identical streams and A/B cleanly."""
        from paddle_tpu.serving.engine import _default_buckets
        from paddle_tpu.serving.fleet.proc import WorkerSpec
        a = self.args
        cfg_kw = dict(
            vocab_size=256, hidden_size=a.hidden,
            intermediate_size=2 * a.hidden,
            num_hidden_layers=a.layers,
            num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=a.max_prompt + max(a.mnt_choices),
            dtype="float32", use_flash_attention=False, remat=False)
        engine_kw = dict(max_batch=a.max_batch, page_size=a.page_size,
                         max_prompt_len=a.max_prompt,
                         max_new_tokens_cap=self.mnt_cap,
                         prompt_buckets=_default_buckets(a.max_prompt),
                         decode_block_size=a.decode_block,
                         prefix_cache=not a.no_prefix_cache,
                         prefill_chunk=a.prefill_chunk or None,
                         admission_window=a.admission_window,
                         check_invariants=a.check_invariants or None)
        return WorkerSpec(cfg_kw=cfg_kw, params_seed=0,
                          engine_kw=engine_kw, warm=True)

    def _fleet_run(self, n, policy, strace, *, paced=True,
                   sequential=True, kill_at=None, proc=False):
        """One fleet arm over ``[(arrival, group, prompt, mnt)]``.

        ``sequential=True`` replays each group as a MULTI-TURN session
        (one thread per session; turn k+1 submits only after turn k's
        reply completed — the traffic shape whose prefix re-hits the
        router must keep warm). ``sequential=False, paced=False`` is
        the flood: every request submitted up front, wall = pure
        service time (the tok/s scaling arm). ``kill_at=i`` runs the
        kill-one-replica scenario: after the i-th accepted submission
        the first serving replica is killed (drain-on-failure:
        admission stops, in-flight finish, queued hand back +
        re-dispatch) while submission continues — the zero-drop claim
        is checked on EVERY handle, the killed replica's accepted
        requests included."""
        from collections import defaultdict

        from paddle_tpu.serving.fleet import SERVING, ServingFleet
        if proc:
            from paddle_tpu.serving.fleet.proc import ProcServingFleet
            fleet = ProcServingFleet(self._proc_spec(), replicas=n,
                                     policy=policy)
        else:
            fleet = ServingFleet(lambda: self._mk_engine(), replicas=n,
                                 policy=policy)
        fleet.arm_sentinels()
        nreq = len(strace)
        handles = [None] * nreq
        state = {"submitted": 0, "kill_started": False, "kill": None}
        klock = threading.Lock()
        t0 = time.perf_counter()

        def _maybe_kill():
            with klock:
                if (kill_at is None or state["kill_started"]
                        or state["submitted"] < kill_at):
                    return
                state["kill_started"] = True
            victim = min(fleet.replicas(SERVING), key=lambda r: r.name)
            handed = fleet.kill(victim.name)
            with klock:
                state["kill"] = {"killed": victim.name,
                                 "at_request": int(kill_at),
                                 "handed_back": len(handed)}

        def _one(idx, arrival, prompt, mnt, wait_done):
            if paced:
                now = time.perf_counter() - t0
                if now < arrival:
                    time.sleep(arrival - now)
            try:
                handles[idx] = fleet.submit(prompt, mnt)
            except BaseException:
                return                  # counted as a drop below
            with klock:
                state["submitted"] += 1
            _maybe_kill()
            if wait_done:
                try:
                    handles[idx].result(timeout=600)
                except BaseException:
                    pass                # judged in the collect pass

        if sequential:
            sessions = defaultdict(list)
            for idx, (arr, g, prompt, mnt) in enumerate(strace):
                sessions[g].append((idx, arr, prompt, mnt))

            def _run_session(items):
                for idx, arr, prompt, mnt in items:
                    _one(idx, arr, prompt, mnt, wait_done=True)

            threads = [threading.Thread(target=_run_session,
                                        args=(items,), daemon=True)
                       for items in sessions.values()]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        else:
            for idx, (arr, g, prompt, mnt) in enumerate(strace):
                _one(idx, arr, prompt, mnt, wait_done=False)
        drops, useful, ttfts = 0, 0, []
        for h in handles:
            if h is None:
                drops += 1
                continue
            try:
                out = h.result(timeout=600)
            except BaseException:
                drops += 1
                continue
            if h.status != "completed":
                drops += 1
                continue
            useful += len(out)
            if h.ttft_s is not None:
                ttfts.append(h.ttft_s)
        wall = time.perf_counter() - t0
        kill_info = state["kill"]
        snap = fleet.snapshot()
        sentinels = {rep.name: rep.sentinel_report()
                     for rep in fleet.replicas()}
        fleet.close()
        agg = {k: 0 for k in ("completed", "tokens_out", "prefix_hits",
                              "prefix_misses", "handed_back")}
        per_replica = {}
        for name, rh in snap["replicas"].items():
            c = rh.get("counters")
            if not c:
                continue
            for k in agg:
                agg[k] += c.get(k, 0)
            denom = max(c["prefix_hits"] + c["prefix_misses"], 1)
            per_replica[name] = {
                "state": rh["state"], "role": rh["role"],
                "completed": int(c["completed"]),
                "tokens_out": int(c["tokens_out"]),
                "prefix_hit_rate": round(c["prefix_hits"] / denom, 3)}
        denom = max(agg["prefix_hits"] + agg["prefix_misses"], 1)
        row = _report(f"fleet[{policy}]x{n}", wall, useful, ttfts)
        row.update(
            replicas=n, policy=policy,
            prefix_hit_rate=round(agg["prefix_hits"] / denom, 3),
            drops=int(drops), completed=int(agg["completed"]),
            per_replica=per_replica,
            router=dict(snap["router"]), generation=snap["generation"])
        if kill_info is not None:
            survivors_clean = all(
                s is None or s["clean"] for name, s in sentinels.items()
                if name != kill_info["killed"])
            kill_info.update(
                redispatched=snap["router"]["redispatched"],
                redispatch_failed=snap["router"]["redispatch_failed"],
                drops=int(drops),
                zero_drops=bool(drops == 0),
                sentinel_clean_survivors=bool(survivors_clean))
            row["kill"] = kill_info
        return row

    def run_fleet(self, trace):
        """ISSUE r18 acceptance mode (``--replicas N``). Arms, one
        JSON row:

        * **sessions** — the multi-session shared-prefix workload
          (multi-turn: turn k+1 follows turn k's reply) under
          prefix-affinity routing vs forced round-robin, plus a
          single-replica baseline. This is where the hit rate lives:
          affinity keeps each session's header chain on one replica
          (~1 cold prefill per session); round-robin scatters it cold.
        * **flood** — the plain mixed trace, all requests submitted up
          front, 1 vs N replicas: aggregate tok/s scaling
          (``speedup_vs_single``). On the shared-CPU mesh this
          measures in-process contention more than fleet capacity
          (docs/SERVING.md "Fleet" discusses the measured ceiling);
          the N-process multi-host number is the real target.
        * **kill** (unless ``--no-kill``) — kill-one-replica during
          the flood: drain-on-failure, queued hand-back +
          re-dispatch, submission continuing throughout; reports
          zero-drop status and survivor sentinel cleanliness.
        """
        a = self.args
        n = max(a.replicas, 2)
        proc = bool(getattr(a, "proc", False))
        strace, header = self._session_trace()
        single_s = self._fleet_run(1, "affinity", strace, proc=proc)
        aff = self._fleet_run(n, "affinity", strace, proc=proc)
        rr = self._fleet_run(n, "round_robin", strace, proc=proc)
        ftrace = [(arr, 0, p, mnt) for arr, p, mnt in trace]
        flood_1 = self._fleet_run(1, "affinity", ftrace, paced=False,
                                  sequential=False, proc=proc)
        flood_n = self._fleet_run(n, "affinity", ftrace, paced=False,
                                  sequential=False, proc=proc)
        out = {
            "mode": "fleet", "proc": proc, "replicas": n,
            "workload": {
                "groups": a.fleet_groups,
                "group_size": a.fleet_group_size,
                "header_tokens": int(header),
                "session_requests": len(strace),
                "flood_requests": len(ftrace),
                "arrival": a.arrival or f"seed:{a.seed} (legacy)"},
            "sessions": {"single": single_s, "affinity": aff,
                         "round_robin": rr},
            "flood": {"single": flood_1, "fleet": flood_n},
            "speedup_vs_single": round(
                flood_n["tok_s"] / max(flood_1["tok_s"], 1e-9), 2),
            "hit_rate_affinity": aff["prefix_hit_rate"],
            "hit_rate_round_robin": rr["prefix_hit_rate"],
            "affinity_beats_round_robin": bool(
                aff["prefix_hit_rate"] > rr["prefix_hit_rate"]),
            "hit_rate_target_met": bool(
                aff["prefix_hit_rate"] >= 0.90),
        }
        if not a.no_kill:
            kill_at = max(1, int(0.4 * len(ftrace)))
            kill_row = self._fleet_run(n, "affinity", ftrace,
                                       paced=False, sequential=False,
                                       kill_at=kill_at, proc=proc)
            out["kill"] = kill_row["kill"]
            out["kill"]["completed"] = kill_row["completed"]
        return out

    def run_migration_ab(self, trace=None):
        """Router-driven KV-migration A/B (ISSUE r17): the SAME
        multi-turn session workload (heavy-tailed lognormal arrivals
        by default) served by

        * **disaggregated_migrate** — a 3-proc fleet split 1 prefill
          + 2 decode with the automatic handoff policy ON: a
          session's header chain prefills on the prefill worker, the
          chain-completion event triggers a chunked transfer to the
          rendezvous-chosen decode worker, and the session's
          decode-heavy turns route there warm
          (``router.routed_migrated``);
        * **monolithic** — the same 3 workers untagged (no pools, no
          migration): the control arm.

        Reports per-arm tok/s + TTFT, follow-up-turn (turn >= 2) TTFT,
        migration/router counters, decode-side prefix hit rate, and
        each worker's max inter-tick stall from its flight recorder —
        the overlap evidence: chunked transfer must not open tick gaps
        beyond one chunk's gather/scatter."""
        from collections import defaultdict

        from paddle_tpu.serving.fleet.proc import ProcServingFleet
        a = self.args
        arrival = parse_arrival(a.arrival or f"lognormal:{a.seed}")
        header = a.fleet_header or max(2 * a.page_size, 16)
        header = min(header, a.max_prompt - 6)
        mnt_lo, mnt_hi = min(a.mnt_choices), max(a.mnt_choices)
        strace = build_session_trace(
            a.fleet_groups, a.fleet_group_size, a.rate, header,
            4, max(5, a.max_prompt - header), [mnt_lo], a.seed,
            arrival=arrival)
        # the handoff workload: each session opens with one expensive
        # header prefill (small mnt -> prefill-classed on the split
        # fleet), then decode-heavy follow-up turns (large mnt ->
        # decode-classed). prefill_len_ratio is computed from the
        # trace so the split is exact for any geometry: turn-0
        # requests satisfy plen >= r*mnt_lo, follow-ups plen < r*mnt_hi
        turns = defaultdict(int)
        shaped = []
        for t, g, p, _ in strace:
            k = turns[g]
            turns[g] += 1
            shaped.append((t, g, p, mnt_lo if k == 0 else mnt_hi))
        strace = shaped
        plens = [len(p) for _, _, p, _ in strace]
        ratio = (max(plens) + 1) / mnt_hi
        if ratio > min(plens) / mnt_lo:
            ratio = 1.0             # degenerate mnt choices: best effort

        def run(roles, label):
            fleet = ProcServingFleet(
                self._proc_spec(), replicas=3, roles=roles,
                prefill_len_ratio=ratio)
            sessions = defaultdict(list)
            for idx, (arr, g, prompt, mnt) in enumerate(strace):
                sessions[g].append((idx, arr, prompt, mnt))
            results = [None] * len(strace)
            t0 = time.perf_counter()

            def _session(items):
                for turn, (idx, arr, prompt, mnt) in enumerate(items):
                    now = time.perf_counter() - t0
                    if now < arr:
                        time.sleep(arr - now)
                    try:
                        h = fleet.submit(prompt, mnt)
                        out = h.result(timeout=600)
                    except BaseException:
                        continue
                    results[idx] = (turn, h.ttft_s, len(out))
            ths = [threading.Thread(target=_session, args=(items,),
                                    daemon=True)
                   for items in sessions.values()]
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            wall = time.perf_counter() - t0
            # max inter-tick stall per worker: gap between one tick's
            # end (t_mono_s + dur_s) and the next tick's start — what
            # a chunked transfer must keep bounded
            stalls = {}
            for rep in fleet.replicas():
                try:
                    ticks = rep.flight_ticks()
                except Exception:
                    continue
                gaps = [ticks[i + 1]["t_mono_s"]
                        - (ticks[i]["t_mono_s"] + ticks[i]["dur_s"])
                        for i in range(len(ticks) - 1)]
                stalls[rep.name] = round(max(gaps), 4) if gaps else 0.0
            snap = fleet.snapshot()
            fleet.close()
            done = [r for r in results if r is not None]
            useful = sum(r[2] for r in done)
            ttfts = [r[1] for r in done if r[1] is not None]
            follow = [r[1] for r in done
                      if r[0] >= 1 and r[1] is not None]
            decode_hits = decode_total = 0
            for name, rh in snap["replicas"].items():
                c = rh.get("counters")
                if c and rh.get("role") == "decode":
                    decode_hits += c.get("prefix_hits", 0)
                    decode_total += (c.get("prefix_hits", 0)
                                     + c.get("prefix_misses", 0))
            row = _report(f"migration[{label}]", wall, useful, ttfts)
            row.update(
                arm=label, drops=int(len(strace) - len(done)),
                followup_ttft_p50_ms=round(_pctl(follow, 50) * 1e3, 1),
                followup_ttft_p99_ms=round(_pctl(follow, 99) * 1e3, 1),
                migrations=snap["fleet"]["migrations"],
                migration_failed=snap["fleet"]["migration_failed"],
                routed_migrated=snap["router"].get("routed_migrated", 0),
                decode_prefix_hit_rate=round(
                    decode_hits / max(decode_total, 1), 3),
                max_tick_stall_s=stalls)
            return row

        dis = run(["prefill", "decode", "decode"],
                  "disaggregated_migrate")
        mono = run(None, "monolithic")
        return {
            "mode": "migration_ab",
            "workload": {"groups": a.fleet_groups,
                         "group_size": a.fleet_group_size,
                         "header_tokens": int(header),
                         "requests": len(strace),
                         "arrival": a.arrival or f"lognormal:{a.seed}"},
            "disaggregated_migrate": dis, "monolithic": mono,
            "migrations_happened": bool(dis["migrations"] > 0),
            "zero_drops_both": bool(dis["drops"] == 0
                                    and mono["drops"] == 0),
        }

    def run_cold_tier(self, trace=None):
        """Host-memory cold-tier A/B (ISSUE r17): one engine, device
        page budget deliberately too small for the working set of
        session header chains, revisited over two rounds:

        * **cold_tier on** — evicted chains spill to host RAM; a
          round-2 revisit re-adopts the pages (``cold_hits``) instead
          of recomputing prefill;
        * **cold_tier off** — the control: a round-2 revisit
          re-prefills from scratch.

        Outputs must be BITWISE identical between arms (the cold tier
        stores the bytes the device computed); the win is round-2
        TTFT. Reports per-arm revisit TTFT, cold counters and the
        cold-tier gauges."""
        a = self.args
        groups = max(4, a.fleet_groups)
        header = a.fleet_header or max(2 * a.page_size, 16)
        header = min(header, a.max_prompt - 6)
        mnt = min(m for m in a.mnt_choices)
        rng = np.random.RandomState(a.seed)
        headers = [rng.randint(0, 256, (header,)).astype(np.int32)
                   for _ in range(groups)]
        tails = [rng.randint(0, 256, (4,)).astype(np.int32)
                 for _ in range(groups)]
        prompts = [np.concatenate([h, t])
                   for h, t in zip(headers, tails)]
        # pool sized for ONE in-flight request + ~1 cached chain: by
        # the time a session's header is revisited its chain has been
        # evicted (admission matches the trie BEFORE evicting, so a
        # roomier pool would let revisits stay warm and the control
        # arm would never re-prefill)
        pages_per_slot = -(-(_bucket(a.max_prompt, self.buckets)
                             + self.mnt_cap - 1) // a.page_size)
        chain_pages = header // a.page_size
        total_pages = pages_per_slot + chain_pages + 2
        cold_bytes = int(getattr(a, "cold_tier", 0)) or (64 << 20)
        wrng = np.random.RandomState(a.seed + 17)
        warm_prompts = [wrng.randint(0, 256, (header + 4,))
                        .astype(np.int32) for _ in range(3)]

        def run(tier_bytes):
            eng = self._mk_engine(max_batch=1,
                                  total_pages=total_pages,
                                  cold_tier_bytes=tier_bytes)
            # unmeasured warm lap: compile prefill/decode (+ the
            # rewarm gather/scatter when the tier is on — submit A,
            # evict it via B, revisit A) so the measured revisits
            # compare steady-state costs, not XLA compiles
            for p in (*warm_prompts, warm_prompts[0]):
                eng.submit(p, mnt).result(timeout=600)
            c0 = eng.snapshot()["counters"]
            outs, ttfts = {}, []
            t0 = time.perf_counter()
            for rnd in range(2):
                for g in range(groups):
                    h = eng.submit(prompts[g], mnt)
                    outs[(rnd, g)] = list(h.result(timeout=600))
                    if rnd == 1 and h.ttft_s is not None:
                        ttfts.append(h.ttft_s)
            wall = time.perf_counter() - t0
            snap = eng.snapshot()
            eng.close()
            c = {k: int(v - c0.get(k, 0))
                 for k, v in snap["counters"].items()}
            row = {
                "wall_s": round(wall, 3),
                "revisit_ttft_p50_ms": round(
                    _pctl(ttfts, 50) * 1e3, 2),
                "revisit_ttft_mean_ms": round(
                    float(np.mean(ttfts)) * 1e3, 2),
                "cold_hits": c.get("cold_hits", 0),
                "cold_hit_pages": c.get("cold_hit_pages", 0),
                "cold_spills": c.get("cold_spills", 0),
                "prefix_hits": c.get("prefix_hits", 0),
                "cold_tier": snap["gauges"].get("cold_tier"),
            }
            hist = snap.get("histograms", {}).get("cold_adopt_s")
            if hist:
                row["cold_adopt_s"] = hist
            return row, outs

        off, outs_off = run(0)
        on, outs_on = run(cold_bytes)
        bitwise = all(outs_on[k] == outs_off[k] for k in outs_on)
        return {
            "mode": "cold_tier",
            "workload": {"groups": groups, "header_tokens": int(header),
                         "mnt": int(mnt), "rounds": 2,
                         "total_pages": int(total_pages),
                         "cold_tier_bytes": int(cold_bytes)},
            "cold_tier_on": on, "cold_tier_off": off,
            "bitwise_equal": bool(bitwise),
            "rehit_beats_cold_prefill": bool(
                on["revisit_ttft_p50_ms"] < off["revisit_ttft_p50_ms"]),
        }

    def _tick_chain(self, kind, ctx=24, iters=12, reps=3):
        """Controlled pure-decode tick latency on matched state: all
        slots live at cache length ``ctx``, ``iters`` chained fused
        blocks (donated pools, token fed back so calls serialize),
        fresh jit fn per arm. Returns median per-step seconds."""
        import jax
        jnp, Lm, a = self.jnp, self.L, self.args
        S, k, ps = a.max_batch, a.decode_block, a.page_size
        pps = -(-(self.buckets[-1] + self.mnt_cap - 1) // ps)
        fn = {"ragged": Lm.serving_tick_block,
              "bucketed": Lm.serving_decode_block}[kind]
        jitted = jax.jit(partial(fn, cfg=self.cfg), donate_argnums=(4, 5),
                         static_argnames=("num_steps",))
        tables = jnp.asarray(
            1 + np.arange(S * pps, dtype=np.int32).reshape(S, pps))
        best = float("inf")
        for _ in range(reps):
            pools = Lm.init_serving_pages(self.cfg, S * pps + 1, ps)
            kp, vp = pools["k_pages"], pools["v_pages"]
            tok = jnp.zeros((S,), jnp.int32)
            lengths = jnp.full((S,), ctx, jnp.int32)
            # compile outside the timed chain
            toks, kp, vp = jitted(self.params, tok, lengths, tables, kp,
                                  vp, num_steps=k)
            tok = toks[:, -1]
            lengths = lengths + k
            t0 = time.perf_counter()
            for _ in range(iters):
                toks, kp, vp = jitted(self.params, tok, lengths, tables,
                                      kp, vp, num_steps=k)
                tok = toks[:, -1]
                lengths = lengths + k
            np.asarray(tok)
            best = min(best, (time.perf_counter() - t0) / (iters * k))
        return best

    def warmup(self, modes):
        """Compile the selected modes' program shapes outside the timed
        runs (only theirs — the full grid is seconds of XLA compiles)."""
        warm = [(0.0, np.arange(1, 1 + ln, dtype=np.int32) % 200, mnt)
                for ln in self.buckets for mnt in self.args.mnt_choices]
        if "sequential" in modes:
            self.run_sequential(warm)
        if "batcher" in modes:
            # warm the (batch-bucket, seq-bucket) grid at the cap
            jnp = self.jnp
            bb = 1
            while True:
                for tb in self.buckets:
                    padded = np.ones((bb, tb), np.int32)
                    lens = np.full((bb,), tb, np.int32)
                    np.asarray(self._gen(self.params, jnp.asarray(padded),
                                         jnp.asarray(lens),
                                         max_new_tokens=self.mnt_cap))
                if bb >= self.args.max_batch:
                    break
                bb = min(bb * 2, self.args.max_batch)
        if "engine" in modes:
            # one request per prompt bucket at the mnt cap, submitted
            # SEQUENTIALLY so each runs alone: covers every mixed tick
            # width AND the pure-decode fused block (an mnt below the
            # fused tail never reaches pure decode, leaving the block
            # program to compile inside the measured run). Distinct
            # random prompts — shared prefixes would attach and shrink
            # the span below the width being warmed.
            rng = np.random.RandomState(self.args.seed + 3)
            eng = self._mk_engine()
            for b in self.buckets:
                p = rng.randint(0, 256, (b,)).astype(np.int32)
                eng.submit(p, self.mnt_cap).result(timeout=600)
            eng.close()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=96)
    ap.add_argument("--rate", type=float, default=500.0,
                    help="arrival rate, requests/sec (keep the system "
                         "LOADED: an underloaded trace measures the "
                         "arrival window, not serving capacity)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--max-prompt", type=int, default=32)
    ap.add_argument("--mnt-choices", type=int, nargs="+",
                    default=[4, 8, 16, 48])
    ap.add_argument("--batch-delay-ms", type=float, default=4.0)
    ap.add_argument("--decode-block", type=int, default=4,
                    help="fused greedy decode steps per engine tick")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--shared-prefix", type=int, default=0,
                    help="prepend one fixed N-token header to every "
                         "prompt (the common-system-prompt workload); "
                         "also enables the prefix_ab mode's default "
                         "prefix length and the engine row's "
                         "prefix-cache counters")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="engine prefill chunk tokens (multiple of "
                         "--page-size; 0 = whole-suffix prefill)")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable cross-request KV prefix reuse")
    ap.add_argument("--admission-window", type=int, default=0,
                    help="queued requests allowed to overtake a "
                         "non-fitting head (0 = strict FIFO)")
    ap.add_argument("--sample-frac", type=float, default=0.0,
                    help="fraction of engine-mode requests submitted "
                         "with temperature/top-p sampling (r16 fused "
                         "sampler: rides the same programs — the "
                         "sentinel gate proves it)")
    ap.add_argument("--temperature", type=float, default=0.8,
                    help="temperature for --sample-frac requests")
    ap.add_argument("--speculative", action="store_true",
                    help="serve the engine mode with self-drafting "
                         "(n-gram) speculative decoding")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="speculative draft-length cap (the one "
                         "static knob; per-tick k is adaptive)")
    ap.add_argument("--spec-mnt", type=int, default=160,
                    help="spec_ab mode: tokens generated per request "
                         "(long enough that the repetitive attractor "
                         "dominates)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="serving-fleet size for the fleet mode; "
                         "passing N>1 selects the fleet mode when "
                         "--modes was not given")
    ap.add_argument("--arrival", default=None,
                    help="seeded replayable arrival schedule. "
                         "'seed:K': gaps/lengths/mnt from "
                         "RandomState(K), independent of --seed "
                         "(content) — the same spec replays the "
                         "identical schedule. 'lognormal:K[:sigma]' / "
                         "'pareto:K[:alpha]': same replay contract "
                         "with HEAVY-TAILED gaps + prompt/output "
                         "lengths (defaults sigma=1.5, alpha=1.5)")
    ap.add_argument("--proc", action="store_true",
                    help="fleet mode: run replicas as worker "
                         "PROCESSES (serving.fleet.proc) instead of "
                         "in-process engines — same JSON schema, so "
                         "the two are directly A/B-able")
    ap.add_argument("--fleet-groups", type=int, default=8,
                    help="fleet mode: distinct shared-prefix sessions "
                         "(each gets its own system-prompt header)")
    ap.add_argument("--fleet-group-size", type=int, default=12,
                    help="fleet mode: requests per session")
    ap.add_argument("--fleet-header", type=int, default=0,
                    help="fleet mode: session header tokens "
                         "(0 = max(2 pages, 16))")
    ap.add_argument("--no-kill", action="store_true",
                    help="fleet mode: skip the kill-one-replica "
                         "scenario")
    ap.add_argument("--rewrites", action="store_true",
                    help="route engine step functions through the "
                         "verified rewrite passes (decode-tail fuse + "
                         "fused rmsnorm); greedy outputs are pinned "
                         "bitwise-identical, so --check-invariants "
                         "and the recompile sentinel apply unchanged")
    ap.add_argument("--check-invariants", action="store_true",
                    help="run the paged-KV invariant checker "
                         "(analysis/kv_invariants.py) after every "
                         "engine tick + a final audit, require a "
                         "clean recompile sentinel (any post-warmup "
                         "XLA compile exits non-zero), AND enable the "
                         "runtime LockTracer (serving/locktrace.py): "
                         "an observed lock-order inversion also exits "
                         "non-zero; the acquisition graph + wait/hold "
                         "stats land in the results as `lock_trace`")
    ap.add_argument("--trace", metavar="OUT.json", default=None,
                    help="export the engine run's span timeline as "
                         "Perfetto-loadable Chrome-trace JSON (one "
                         "track per engine phase + per slot)")
    ap.add_argument("--cold-tier", type=int, default=0,
                    help="host-memory cold-chain tier byte budget "
                         "(engine mode: passed straight to the "
                         "engine's cold_tier_bytes=; cold_tier mode: "
                         "the ON arm's budget, 0 = 64 MiB default)")
    ap.add_argument("--modes", nargs="+", default=None,
                    help="any of: sequential batcher engine prefix_ab "
                         "ragged_ab trace_overhead spec_ab fleet "
                         "migration_ab cold_tier "
                         "(default: sequential batcher engine, or "
                         "fleet when --replicas > 1)")
    args = ap.parse_args(argv)
    if args.modes is None:
        args.modes = (["fleet"] if args.replicas > 1
                      else ["sequential", "batcher", "engine"])
    if (args.shared_prefix and args.shared_prefix >= args.max_prompt
            and any(m != "prefix_ab" for m in args.modes)):
        # trace prompts are capped at --max-prompt; prefix_ab picks its
        # own (longer) geometry and clamps the share itself
        ap.error(f"--shared-prefix ({args.shared_prefix}) must be < "
                 f"--max-prompt ({args.max_prompt}): every prompt needs "
                 f"at least one non-shared token")
    if args.prefill_chunk and args.prefill_chunk % args.page_size:
        ap.error(f"--prefill-chunk ({args.prefill_chunk}) must be a "
                 f"multiple of --page-size ({args.page_size})")

    lt_tracer = None
    if args.check_invariants:
        # --check-invariants also turns on the runtime lock tracer
        # (analysis/concurrency.py's dynamic half): every serving lock
        # built from here on records acquisition order, and an
        # observed order inversion — two locks taken in both orders,
        # i.e. a latent deadlock the static cycle check may not see
        # across dynamic call paths — fails the bench after the modes
        # run. Enable BEFORE Bench construction: wrapping is decided
        # at lock construction time.
        from paddle_tpu.serving import locktrace
        lt_tracer = locktrace.enable()

    bench = Bench(args)
    trace = build_trace(args.requests, args.rate, args.max_prompt,
                        args.mnt_choices, args.seed,
                        shared_prefix=args.shared_prefix,
                        arrival=parse_arrival(args.arrival))
    bench.warmup([m for m in args.modes
                  if m not in ("prefix_ab", "ragged_ab", "spec_ab",
                               "fleet", "migration_ab", "cold_tier")])
    results = {}
    for mode in args.modes:
        results[mode] = getattr(bench, f"run_{mode}")(list(trace))
        print(json.dumps(results[mode]), flush=True)
    if "engine" in results and "batcher" in results:
        verdict = {
            "engine_beats_batcher_tok_s":
                results["engine"]["tok_s"] > results["batcher"]["tok_s"],
            "engine_beats_batcher_ttft_p99":
                results["engine"]["ttft_p99_ms"]
                < results["batcher"]["ttft_p99_ms"],
        }
        print(json.dumps(verdict), flush=True)
        results["verdict"] = verdict
    if lt_tracer is not None:
        rep = lt_tracer.report()
        results["lock_trace"] = rep
        print(json.dumps({"lock_trace": {
            "edges": rep["edges"], "inversions": rep["inversions"],
            "host_sync_held": rep["host_sync_held"]}}), flush=True)
        if rep["inversions"]:
            raise SystemExit(
                "serving_bench --check-invariants: lock-order "
                f"inversion(s) observed at runtime: {rep['inversions']}")
    return results


if __name__ == "__main__":
    main(sys.argv[1:])

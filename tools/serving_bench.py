"""Traffic-replay serving benchmark: sequential vs DynamicBatcher vs
the continuous-batching ServingEngine.

Replays one synthetic mixed-length request trace (Poisson arrivals,
mixed prompt lengths, mixed max_new_tokens) through three serving
strategies over the SAME model params:

  (a) sequential    — one `generate_paged` per request, in arrival
                      order (no batching at all);
  (b) batcher       — `inference.DynamicBatcher` whole-request ragged
                      batching: mixed-length prompts coalesce into one
                      paged decode, but every batch runs the GLOBAL
                      max_new_tokens and a request's tokens only
                      surface when the whole batch finishes;
  (c) engine        — `serving.ServingEngine` continuous batching:
                      per-step admission/retirement over the shared
                      page pool, tokens streamed as decoded.

Reported per mode: wall_s, useful tok/s (only each request's OWN
requested tokens count), time-to-first-token p50/p99 (ms), and mean
batch occupancy where defined. Acceptance (ISSUE r6): (c) beats (b) on
aggregate tok/s AND p99 TTFT on the CPU mesh.

``--shared-prefix N`` prepends one fixed N-token header to every prompt
(the common-system-prompt workload the r8 prefix cache targets) and adds
prefix-cache counters to the engine row. The ``prefix_ab`` mode emits
the ISSUE r8 acceptance numbers directly: cold-vs-warm TTFT on one
shared prefix, pages saved, and the max decode stall an in-flight stream
feels while a max-length prompt is admitted — chunked vs unchunked
prefill.

    JAX_PLATFORMS=cpu python tools/serving_bench.py --requests 32
    JAX_PLATFORMS=cpu python tools/serving_bench.py \
        --shared-prefix 24 --modes engine prefix_ab
"""
import argparse
import json
import os
import sys
import threading
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_trace(n, rate, max_prompt, mnt_choices, seed, shared_prefix=0):
    """[(arrival_s, prompt int32[?], max_new_tokens)] sorted by arrival.
    mnt_choices is a SMALL set so every mode compiles a bounded number
    of programs. shared_prefix > 0 prepends one fixed token header to
    EVERY prompt (the common-system-prompt serving shape the prefix
    cache exists for)."""
    rng = np.random.RandomState(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
    header = (rng.randint(0, 256, (shared_prefix,)).astype(np.int32)
              if shared_prefix else None)
    lo = min(shared_prefix + 2, max_prompt)
    trace = []
    for t in arrivals:
        plen = int(rng.randint(max(lo, 2), max_prompt + 1))
        prompt = rng.randint(0, 256, (plen,)).astype(np.int32)
        if header is not None:
            prompt[:shared_prefix] = header
        trace.append((float(t), prompt, int(rng.choice(mnt_choices))))
    return trace


def _bucket(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _pctl(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else float("nan")


def _report(name, wall, useful, ttfts, occupancy=None):
    out = {"mode": name, "wall_s": round(wall, 3),
           "useful_tokens": int(useful),
           "tok_s": round(useful / wall, 1),
           "ttft_p50_ms": round(_pctl(ttfts, 50) * 1e3, 1),
           "ttft_p99_ms": round(_pctl(ttfts, 99) * 1e3, 1)}
    if occupancy is not None:
        out["occupancy_mean"] = round(occupancy, 3)
    return out


class Bench:
    def __init__(self, args):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama as L
        self.jnp = jnp
        self.L = L
        self.args = args
        self.cfg = L.LlamaConfig(
            vocab_size=256, hidden_size=args.hidden,
            intermediate_size=2 * args.hidden,
            num_hidden_layers=args.layers,
            num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=args.max_prompt + max(args.mnt_choices),
            dtype=jnp.float32, use_flash_attention=False, remat=False)
        self.params = L.init_params(self.cfg, jax.random.PRNGKey(0))
        # the ENGINE's bucket policy, so every mode pads to the same
        # shapes as the engine under test (no silent drift)
        from paddle_tpu.serving.engine import _default_buckets
        self.buckets = _default_buckets(args.max_prompt)
        self.mnt_cap = max(args.mnt_choices)
        # one jitted ragged generate per (B, Tb, mnt): shared by (a)/(b)
        self._gen = jax.jit(
            partial(L.generate_paged, cfg=self.cfg, page_size=args.page_size),
            static_argnames=("max_new_tokens",))

    def _pad(self, prompts):
        lens = [len(p) for p in prompts]
        tb = _bucket(max(lens), self.buckets)
        out = np.zeros((len(prompts), tb), np.int32)
        for i, p in enumerate(prompts):
            out[i, :len(p)] = p
        return out, np.asarray(lens, np.int32)

    # ------------------------------------------------------------ modes ----
    def run_sequential(self, trace):
        jnp = self.jnp
        t0 = time.perf_counter()
        useful, ttfts = 0, []
        for arrival, prompt, mnt in trace:
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            padded, lens = self._pad([prompt])
            out = self._gen(self.params, jnp.asarray(padded),
                            jnp.asarray(lens), max_new_tokens=mnt)
            np.asarray(out)  # block
            ttfts.append(time.perf_counter() - t0 - arrival)
            useful += mnt
        return _report("sequential", time.perf_counter() - t0, useful,
                       ttfts)

    def run_batcher(self, trace):
        """Whole-request ragged batching: the r5 serving shape. Every
        batch decodes the GLOBAL mnt cap (the batcher cannot retire rows
        early), and a request's TTFT is its whole batch's completion."""
        from paddle_tpu.inference import DynamicBatcher
        jnp = self.jnp
        cap = self.mnt_cap

        def fn(batch, lengths):
            out = self._gen(self.params, jnp.asarray(batch),
                            jnp.asarray(lengths), max_new_tokens=cap)
            return np.asarray(out)

        bat = DynamicBatcher(fn, max_batch_size=self.args.max_batch,
                             max_delay_ms=self.args.batch_delay_ms,
                             seq_buckets=self.buckets)
        t0 = time.perf_counter()
        done_t, lock = {}, threading.Lock()
        futs = []
        for i, (arrival, prompt, mnt) in enumerate(trace):
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            fut = bat.submit(prompt)

            def _mark(f, i=i):
                with lock:
                    done_t[i] = time.perf_counter()
            fut.add_done_callback(_mark)
            futs.append(fut)
        for f in futs:
            f.result()
        wall = time.perf_counter() - t0
        bat.close()
        useful = sum(mnt for _, _, mnt in trace)
        ttfts = [done_t[i] - t0 - trace[i][0] for i in range(len(trace))]
        return _report("batcher", wall, useful, ttfts)

    def _mk_engine(self, **over):
        from paddle_tpu.serving import ServingEngine
        a = self.args
        kw = dict(max_batch=a.max_batch, page_size=a.page_size,
                  max_prompt_len=a.max_prompt,
                  max_new_tokens_cap=self.mnt_cap,
                  prompt_buckets=self.buckets,
                  decode_block_size=a.decode_block,
                  prefix_cache=not a.no_prefix_cache,
                  prefill_chunk=a.prefill_chunk or None,
                  admission_window=a.admission_window,
                  # None = env default; True = per-tick paged-KV
                  # invariant checking (violations raise inside the
                  # tick -> every handle errors -> main exits non-zero)
                  check_invariants=a.check_invariants or None)
        kw.update(over)
        return ServingEngine(self.params, self.cfg, **kw)

    def run_engine(self, trace):
        a = self.args
        eng = self._mk_engine()
        t0 = time.perf_counter()
        handles = []
        for arrival, prompt, mnt in trace:
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            handles.append(eng.submit(prompt, mnt))
        outs = [h.result(timeout=600) for h in handles]
        wall = time.perf_counter() - t0
        snap = eng.stats()
        if a.check_invariants:
            # final standalone audit on top of the per-tick checks —
            # the post-drain state (page leaks) is only visible here
            violations = eng.audit()
            if violations:
                eng.close()
                raise SystemExit(
                    "serving_bench --check-invariants: "
                    + "; ".join(str(v) for v in violations))
        eng.close()
        useful = sum(len(o) for o in outs)
        ttfts = [h.ttft_s for h in handles]
        occ = snap["histograms"]["batch_occupancy"]["mean"]
        out = _report("engine", wall, useful, ttfts, occupancy=occ)
        c = snap["counters"]
        if a.shared_prefix and not a.no_prefix_cache:
            denom = max(c["prefix_hits"] + c["prefix_misses"], 1)
            out["prefix_hit_rate"] = round(c["prefix_hits"] / denom, 3)
            out["prefix_hit_tokens"] = int(c["prefix_hit_tokens"])
            out["prefix_pages_saved"] = int(c["prefix_pages_saved"])
            out["prefix_hit_tokens_per_sec"] = round(
                c["prefix_hit_tokens"] / wall, 1)
        st = snap["histograms"]["decode_stall_s"]
        if st["count"]:
            out["decode_stall_max_ms"] = round(st["max"] * 1e3, 1)
        return out

    # -------------------------------------------- prefix / chunk A-Bs ----
    def _ab_geometry(self):
        """The A-B runs at prompt lengths where prefill COST (not fixed
        dispatch overhead) dominates — at the default tiny trace shapes
        a whole prefill costs ~2 ms against ~1 ms of per-call overhead
        and both effects drown. 128+ tokens puts prefill well clear of
        the noise floor on the CPU mesh."""
        from paddle_tpu.serving.engine import _default_buckets
        a = self.args
        ab_len = max(a.max_prompt, 256)
        if a.shared_prefix:
            # honor the user's shared FRACTION (their --shared-prefix is
            # sized for the --max-prompt trace), rescaled to ab_len — a
            # 24-of-256-token share would measure nothing
            shared = int(ab_len * a.shared_prefix / a.max_prompt)
        else:
            shared = 7 * ab_len // 8
        shared = min(shared, ab_len - 4)
        chunk = a.prefill_chunk or max(
            (ab_len // 8) // a.page_size, 1) * a.page_size
        return ab_len, shared, chunk, _default_buckets(ab_len)

    def run_prefix_ab(self, trace=None):
        """Controlled cold-vs-warm TTFT on one shared prefix, plus the
        max decode stall an in-flight stream feels while a max-length
        prompt is admitted — chunked vs unchunked. Emitted as one JSON
        row; the ISSUE r8 acceptance numbers."""
        a = self.args
        rng = np.random.RandomState(a.seed + 1)
        ab_len, shared, chunk, buckets = self._ab_geometry()
        header = rng.randint(0, 256, (shared,)).astype(np.int32)
        tail = ab_len - shared

        def mk_prompt():
            return np.concatenate(
                [header, rng.randint(0, 256, (tail,)).astype(np.int32)])

        mnt = min(self.mnt_cap, 8)
        eng = self._mk_engine(max_prompt_len=ab_len,
                              prompt_buckets=buckets)
        # compile the COLD-path shapes outside the timed submissions,
        # with token values that cannot seed the measured prefix chain
        warm_p = (mk_prompt() + 1) % 256
        eng.submit(warm_p, mnt).result(timeout=600)
        # compile the WARM-path shape (suffix bucket x attached-page
        # count) too: a second throwaway-header request hits the first
        # one's chain with exactly the measured geometry
        eng.submit(((mk_prompt() + 1) % 256), mnt).result(timeout=600)
        # median of 3 cold/warm PAIRS, each on a fresh header (cold
        # prefill time swings 2x with co-tenant CPU load; one sample
        # proves nothing)
        colds, warms = [], []
        for i in range(3):
            header[:] = rng.randint(0, 256, (shared,))
            h_cold = eng.submit(mk_prompt(), mnt)
            h_cold.result(timeout=600)
            h_warm = eng.submit(mk_prompt(), mnt)
            h_warm.result(timeout=600)
            colds.append(h_cold.ttft_s)
            warms.append(h_warm.ttft_s)
        snap = eng.stats()
        eng.close()
        c = snap["counters"]
        cold_s = float(np.median(colds))
        warm_s = float(np.median(warms))

        out = {
            "mode": "prefix_ab",
            "shared_prefix_tokens": int(shared),
            "ttft_cold_ms": round(cold_s * 1e3, 1),
            "ttft_warm_ms": round(warm_s * 1e3, 1),
            "warm_ttft_speedup": round(cold_s / max(warm_s, 1e-9), 2),
            "prefix_hit_tokens": int(c["prefix_hit_tokens"]),
            "prefix_pages_saved": int(c["prefix_pages_saved"]),
            "stall_unchunked_ms": self._admission_stall(None),
            "stall_chunked_ms": self._admission_stall(chunk),
        }
        out["prefill_chunk_tokens"] = int(chunk)
        out["stall_reduced"] = (out["stall_chunked_ms"]
                                < out["stall_unchunked_ms"])
        return out

    def _admission_stall(self, chunk):
        """Max per-tick stall (ms) — the engine's ``decode_stall_s``
        histogram: time between consecutive decode ticks while a stream
        is live, which is exactly where an admission's prefill work
        lands (the ISSUE r8 acceptance metric). One in-flight victim
        stream, one max-length intruder admitted mid-stream; median of
        3 fresh-engine repeats (any single gap swings with co-tenant
        CPU load). The victim's own decode-step cost is NOT in this
        metric — the stall clock runs only BETWEEN ticks."""
        rng = np.random.RandomState(self.args.seed + 2)
        ab_len, _, _, buckets = self._ab_geometry()
        mnt = min(self.mnt_cap, 24)
        victim_p = rng.randint(0, 256, (2,)).astype(np.int32)
        intruder_p = rng.randint(0, 256, (ab_len,)).astype(np.int32)
        stalls = []
        for _ in range(3):
            eng = self._mk_engine(prefill_chunk=chunk,
                                  prefix_cache=False, max_batch=2,
                                  max_prompt_len=ab_len,
                                  prompt_buckets=buckets,
                                  decode_block_size=1)
            # compile victim decode + intruder prefill shapes (the jit
            # cache is shared across engines, so only the first repeat
            # can ever pay a compile)
            eng.submit(intruder_p, 2).result(timeout=600)
            h = eng.submit(victim_p, mnt)
            it = iter(h)
            next(it)
            next(it)                   # victim is mid-decode
            h2 = eng.submit(intruder_p, 2)
            h.result(timeout=600)
            h2.result(timeout=600)
            snap = eng.stats()
            eng.close()
            stalls.append(snap["histograms"]["decode_stall_s"]["max"])
        return round(float(np.median(stalls)) * 1e3, 1)

    def warmup(self, modes):
        """Compile the selected modes' program shapes outside the timed
        runs (only theirs — the full grid is seconds of XLA compiles)."""
        warm = [(0.0, np.arange(1, 1 + ln, dtype=np.int32) % 200, mnt)
                for ln in self.buckets for mnt in self.args.mnt_choices]
        if "sequential" in modes:
            self.run_sequential(warm)
        if "batcher" in modes:
            # warm the (batch-bucket, seq-bucket) grid at the cap
            jnp = self.jnp
            bb = 1
            while True:
                for tb in self.buckets:
                    padded = np.ones((bb, tb), np.int32)
                    lens = np.full((bb,), tb, np.int32)
                    np.asarray(self._gen(self.params, jnp.asarray(padded),
                                         jnp.asarray(lens),
                                         max_new_tokens=self.mnt_cap))
                if bb >= self.args.max_batch:
                    break
                bb = min(bb * 2, self.args.max_batch)
        if "engine" in modes:
            # one prefill per prompt bucket + the decode step
            self.run_engine([(0.0, np.ones((b,), np.int32), 2)
                             for b in self.buckets])


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=96)
    ap.add_argument("--rate", type=float, default=500.0,
                    help="arrival rate, requests/sec (keep the system "
                         "LOADED: an underloaded trace measures the "
                         "arrival window, not serving capacity)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--max-prompt", type=int, default=32)
    ap.add_argument("--mnt-choices", type=int, nargs="+",
                    default=[4, 8, 16, 48])
    ap.add_argument("--batch-delay-ms", type=float, default=4.0)
    ap.add_argument("--decode-block", type=int, default=4,
                    help="fused greedy decode steps per engine tick")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--shared-prefix", type=int, default=0,
                    help="prepend one fixed N-token header to every "
                         "prompt (the common-system-prompt workload); "
                         "also enables the prefix_ab mode's default "
                         "prefix length and the engine row's "
                         "prefix-cache counters")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="engine prefill chunk tokens (multiple of "
                         "--page-size; 0 = whole-suffix prefill)")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable cross-request KV prefix reuse")
    ap.add_argument("--admission-window", type=int, default=0,
                    help="queued requests allowed to overtake a "
                         "non-fitting head (0 = strict FIFO)")
    ap.add_argument("--check-invariants", action="store_true",
                    help="run the paged-KV invariant checker "
                         "(analysis/kv_invariants.py) after every "
                         "engine tick + a final audit; any violation "
                         "exits non-zero")
    ap.add_argument("--modes", nargs="+",
                    default=["sequential", "batcher", "engine"],
                    help="any of: sequential batcher engine prefix_ab")
    args = ap.parse_args(argv)
    if (args.shared_prefix and args.shared_prefix >= args.max_prompt
            and any(m != "prefix_ab" for m in args.modes)):
        # trace prompts are capped at --max-prompt; prefix_ab picks its
        # own (longer) geometry and clamps the share itself
        ap.error(f"--shared-prefix ({args.shared_prefix}) must be < "
                 f"--max-prompt ({args.max_prompt}): every prompt needs "
                 f"at least one non-shared token")
    if args.prefill_chunk and args.prefill_chunk % args.page_size:
        ap.error(f"--prefill-chunk ({args.prefill_chunk}) must be a "
                 f"multiple of --page-size ({args.page_size})")

    bench = Bench(args)
    trace = build_trace(args.requests, args.rate, args.max_prompt,
                        args.mnt_choices, args.seed,
                        shared_prefix=args.shared_prefix)
    bench.warmup([m for m in args.modes if m != "prefix_ab"])
    results = {}
    for mode in args.modes:
        results[mode] = getattr(bench, f"run_{mode}")(list(trace))
        print(json.dumps(results[mode]), flush=True)
    if "engine" in results and "batcher" in results:
        verdict = {
            "engine_beats_batcher_tok_s":
                results["engine"]["tok_s"] > results["batcher"]["tok_s"],
            "engine_beats_batcher_ttft_p99":
                results["engine"]["ttft_p99_ms"]
                < results["batcher"]["ttft_p99_ms"],
        }
        print(json.dumps(verdict), flush=True)
        results["verdict"] = verdict
    return results


if __name__ == "__main__":
    main(sys.argv[1:])

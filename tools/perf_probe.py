"""One-variant-at-a-time perf probe for the llama bench config.

Slope-method timing: run N chained device-side iterations with a single
host sync, for two values of N; per-iter time = slope. This cancels the
(large, tunneled-TPU) host<->device sync overhead out of the estimate.

Usage: python tools/perf_probe.py <mode> [D L H KV B T F [remat]]
modes: step | fwd | grad | grad_dense | grad_nosm
"""
import sys
import time

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.parallel import init_hybrid_mesh


def slope_time(run_n, ns=(4, 12)):
    """run_n(n) must execute n chained iterations then sync once."""
    run_n(2)  # warmup/compile
    times = []
    for n in ns:
        t0 = time.perf_counter()
        run_n(n)
        times.append(time.perf_counter() - t0)
    return (times[1] - times[0]) / (ns[1] - ns[0]) * 1e3


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "step"
    hidden = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    layers = int(sys.argv[3]) if len(sys.argv) > 3 else 16
    heads = int(sys.argv[4]) if len(sys.argv) > 4 else 8
    kv = int(sys.argv[5]) if len(sys.argv) > 5 else 8
    B = int(sys.argv[6]) if len(sys.argv) > 6 else 4
    T = int(sys.argv[7]) if len(sys.argv) > 7 else 2048
    ffn = int(sys.argv[8]) if len(sys.argv) > 8 else 4 * hidden
    flags = set(sys.argv[9:])
    remat = "remat" in flags

    cfg = L.LlamaConfig(
        vocab_size=32000, hidden_size=hidden, intermediate_size=ffn,
        num_hidden_layers=layers, num_attention_heads=heads,
        num_key_value_heads=kv, max_position_embeddings=T,
        dtype=jnp.bfloat16, remat=remat, use_flash_attention=True,
        use_fused_norm_rope=False if "nofuse" in flags else "auto")
    hm = init_hybrid_mesh(dp=1, pp=1, tp=1, set_global=False)
    with hm.mesh:
        batch = L.make_batch(cfg, batch_size=B, seq_len=T, mesh=hm.mesh)
        if mode == "step":
            step, init = L.make_train_step(cfg, hm.mesh)
            state = init(jax.random.PRNGKey(0))
            st = [state]

            def run_n(n):
                l = None
                for _ in range(n):
                    s, l = step(st[0], batch)
                    st[0] = s
                float(l)
        else:
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            params = L.shard_params(params, cfg, hm.mesh)
            if mode == "fwd":
                @jax.jit
                def g(p, t):
                    lg = L.forward(p, t, cfg, hm.mesh)
                    # full-reduction feedback so successive calls chain
                    # device-side AND nothing can be dead-code-eliminated
                    # or narrowed (a single-element chain lets XLA slice
                    # the whole lm_head matmul down to one element)
                    s = lg.astype(jnp.float32).sum()
                    return (s * 0).astype(jnp.int32) + (s > 1e30).astype(
                        jnp.int32)

                def run_n(n):
                    d = jnp.int32(0)
                    for _ in range(n):
                        d = g(params, batch["tokens"] + d)
                    int(d)
            else:
                if mode == "grad":
                    lf = lambda p, b: L.loss_fn(p, b, cfg, hm.mesh)
                elif mode == "grad_dense":
                    cfg2 = L.LlamaConfig(
                        **{**cfg.__dict__, "use_flash_attention": False})
                    lf = lambda p, b: L.loss_fn(p, b, cfg2, hm.mesh)
                elif mode == "grad_nosm":
                    def lf(p, b):
                        lg = L.forward(p, b["tokens"], cfg, hm.mesh)
                        return (lg * lg).astype(jnp.float32).mean()
                else:
                    raise SystemExit(f"unknown mode {mode}")

                @jax.jit
                def g(p, b):
                    l, grads = jax.value_and_grad(lf)(p, b)
                    # fold every grad leaf into the chained scalar so the
                    # backward pass cannot be dead-code-eliminated
                    gs = sum(x.astype(jnp.float32).sum()
                             for x in jax.tree_util.tree_leaves(grads))
                    s = l + gs
                    return (s * 0).astype(jnp.int32) + (s > 1e30).astype(
                        jnp.int32)

                def run_n(n):
                    d = jnp.int32(0)
                    for _ in range(n):
                        d = g(params, {"tokens": batch["tokens"] + d,
                                       "labels": batch["labels"]})
                    int(d)
        ms = slope_time(run_n)

    D, L_, V = cfg.hidden_size, cfg.num_hidden_layers, cfg.vocab_size
    H, Hkv, Dh, F = (cfg.num_attention_heads, cfg.num_key_value_heads,
                     cfg.head_dim, cfg.intermediate_size)
    n_params = (V * D * 2
                + L_ * (D * H * Dh + 2 * D * Hkv * Dh + H * Dh * D + 3 * D * F))
    tokens = B * T
    mult = 2 if mode == "fwd" else 6
    flops = (mult * n_params + mult * L_ * D * T) * tokens
    mfu = flops / (ms / 1e3) / 197e12
    print(f"mode={mode} D={hidden} L={layers} B={B} T={T} F={ffn} "
          f"remat={remat} params={n_params/1e9:.3f}B ms={ms:.2f} MFU={mfu:.4f}")


if __name__ == "__main__":
    main()

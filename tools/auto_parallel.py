"""Auto-parallel planner CLI: search, rank, and trace-verify
(dp, tp, pp, V, M, schedule, zero, dtype) plans for a model + mesh +
HBM budget (analysis/planner.py — ROADMAP item 4).

    JAX_PLATFORMS=cpu python tools/auto_parallel.py \\
        --devices 4 --batch 64 --seq-len 64 --hbm-gb 0.25

enumerates the legal configuration space (illegal points pruned by the
same divisibility/schedule/zero rules the executors enforce, each
counted by reason), prices every point with the composed static cost
model (traced HBM peak, xla-cost-analysis step-time proxy normalized
by schedule efficiency, traced + analytic comms terms), prints the
ranked plan, and VERIFIES the winner: traces it at the full requested
batch and runs the complete registered pass stack plus the planner
contract (prediction-vs-trace deltas in the shared Finding schema;
non-zero exit when any pass errors or the prediction misses its
tolerance).

``--smoke`` is the CI entry (tests/test_auto_parallel_planner.py):
tiny config, 2x2 mesh, narrowed space — asserts a non-empty ranked
plan whose winner trace-verifies, in well under a minute.

Everything runs on virtual CPU devices — tracing is abstract and the
one reference compile per dtype is a tiny single-device step, so
planning a 4-device space costs ~20s and zero TPU time.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

DTYPE_ALIASES = {"bf16": "bfloat16", "bfloat16": "bfloat16",
                 "f32": "float32", "float32": "float32"}


def build_config(args):
    import dataclasses
    from paddle_tpu.models import llama as L
    cfg = (L.LlamaConfig.llama3_8b() if args.model == "llama3_8b"
           else L.LlamaConfig.tiny())
    over = {}
    if args.layers:
        over["num_hidden_layers"] = args.layers
    if args.hidden:
        over["hidden_size"] = args.hidden
    return dataclasses.replace(cfg, **over) if over else cfg


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", choices=["tiny", "llama3_8b"],
                    default="tiny")
    ap.add_argument("--layers", type=int, default=0,
                    help="override the model's layer count (e.g. to "
                         "open deeper pp factorizations)")
    ap.add_argument("--hidden", type=int, default=0)
    ap.add_argument("--devices", type=int, default=4,
                    help="mesh size the plan must fill (dp*tp*pp)")
    ap.add_argument("--batch", type=int, default=64,
                    help="global batch size the step must take")
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="per-device HBM budget; plans exceeding it "
                         "are excluded from the ranking (counted in "
                         "over_budget)")
    ap.add_argument("--dtypes", nargs="+", default=["bf16", "f32"],
                    choices=sorted(DTYPE_ALIASES))
    ap.add_argument("--zero", nargs="+", type=int, default=[0, 1, 3])
    ap.add_argument("--schedules", nargs="+", default=None,
                    help="pp schedules to search (default: every "
                         "entry of SCHEDULE_INFO)")
    ap.add_argument("--vpp", nargs="+", type=int, default=[1, 2])
    ap.add_argument("--microbatches", nargs="+", type=int, default=None)
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="planner-contract HBM tolerance")
    ap.add_argument("--no-verify", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="emit the full plan JSON on stdout")
    ap.add_argument("--out", default=None,
                    help="also write the plan JSON to this path")
    ap.add_argument("--smoke", action="store_true",
                    help="CI mode: tiny config, 2x2 mesh, narrowed "
                         "space; non-zero exit unless a non-empty "
                         "ranked plan verifies")
    args = ap.parse_args(argv)

    if args.smoke:
        # the CI space is defined ONCE (planner.SMOKE_KNOBS) and
        # shared with graph_lint --planner, so the two gates cannot
        # drift onto different spaces
        from paddle_tpu.analysis.planner import SMOKE_KNOBS
        args.model, args.layers, args.hidden = "tiny", 0, 0
        args.devices = SMOKE_KNOBS["devices"]
        args.batch = SMOKE_KNOBS["batch_size"]
        args.seq_len = SMOKE_KNOBS["seq_len"]
        args.dtypes = list(SMOKE_KNOBS["dtypes"])  # full names alias
        args.zero = list(SMOKE_KNOBS["zero_stages"])
        args.vpp = list(SMOKE_KNOBS["vpp_choices"])
        args.hbm_gb = (args.hbm_gb
                       or SMOKE_KNOBS["hbm_budget_bytes"] / 2**30)
        args.top = SMOKE_KNOBS["top"]

    # planning runs on virtual CPU devices — must happen before any
    # jax operation (tools/graph_lint.py does the same)
    from paddle_tpu.testing import force_host_cpu_devices
    force_host_cpu_devices(max(args.devices, 1))

    from paddle_tpu.analysis.planner import plan_auto_parallel

    cfg = build_config(args)
    budget = (int(args.hbm_gb * 2**30)
              if args.hbm_gb is not None else None)
    say = (lambda *_: None) if args.json else print
    t0 = time.time()
    out = plan_auto_parallel(
        cfg, args.devices, batch_size=args.batch,
        seq_len=args.seq_len, hbm_budget_bytes=budget, top=args.top,
        verify=not args.no_verify, tolerance=args.tolerance,
        dtypes=tuple(DTYPE_ALIASES[d] for d in args.dtypes),
        zero_stages=tuple(args.zero),
        schedules=(tuple(args.schedules) if args.schedules else None),
        vpp_choices=tuple(args.vpp),
        microbatch_choices=(tuple(args.microbatches)
                            if args.microbatches else None),
        progress=say)
    out["seconds"] = round(time.time() - t0, 2)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"\n{out['legal']} legal / {out['enumerated']} "
              f"enumerated points "
              f"({out['over_budget']} over budget) in "
              f"{out['seconds']}s")
        for reason, n in out["pruned"].items():
            print(f"  pruned {n:4d}  {reason}")
        print(f"\n rank  {'plan':34s} {'step*':>9s} {'peak MiB':>9s} "
              f"{'eff':>6s}  fits")
        for p in out["plans"]:
            c = p["cost"]
            print(f"  {p['rank']:3d}  {p['label']:34s} "
                  f"{c['step_time_proxy_s'] * 1e6:8.1f}u "
                  f"{c['hbm_peak_bytes'] / 2**20:9.2f} "
                  f"{c['efficiency']:6.3f}  {c['fits']}")
        ver = out.get("verification")
        if ver is not None:
            print(f"\nwinner verification: "
                  f"{'OK' if ver['ok'] else 'FAILED'}")
            for k, v in ver.get("deltas", {}).items():
                print(f"  {k}: {v}")
            for f_ in ver.get("report", {}).get("findings", []):
                if f_["severity"] != "info":
                    print(f"  [{f_['severity']}] {f_['pass']}: "
                          f"{f_['message']}")

    ok = bool(out["plans"])
    if not args.no_verify:
        ok = ok and bool(out.get("verification", {}).get("ok"))
    if args.smoke and not args.json:
        print(f"auto_parallel --smoke: "
              f"{'OK' if ok else 'FAIL'} "
              f"({len(out['plans'])} ranked plans)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""ResNet-50 training-step MFU on one chip — north-star metric #2.

BASELINE.md: "CINN-replacement (XLA) ResNet-50 MFU". The conv stack is
the real user Layer (models/resnet.py resnet50) traced into ONE jitted
XLA step via the same bind-params capture to_static/Engine use, with
AMP O1 auto_cast putting the convs on the MXU in bf16 and an SGD
momentum update fused into the step. (The Engine path compiles the
identical program; its slot-materialising first step runs EAGERLY,
which is minutes of per-op round trips over the tunneled TPU — the
functional form here skips that, nothing else differs.)

FLOP accounting: the compiled program's own XLA cost_analysis (no
remat, so HFU == MFU); falls back to the 2*4.09 GMAC torchvision
convention * 3 (fwd+bwd) if the backend hides cost analysis.

Run (TPU): python tools/resnet_bench.py

Profile mode — the measurement behind the conv rewrite passes
(analysis/rewrite_conv.py):

    python tools/resnet_bench.py --profile out.json [--mode infer]
        [--depth 50] [--image 224]

emits the per-region table (analysis/resnet_profile.py): every site
the rewrite passes match, slope-timed and XLA-cost-analyzed baseline
vs rewritten, plus the full-graph A/B. Batch comes from
RESNET_BENCH_B (keep it small on CPU).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

_PEAK_BF16 = {"v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
              "v4": 275e12, "v6e": 918e12}


def peak_flops() -> float:
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for k, v in _PEAK_BF16.items():
        if k in kind:
            return v
    return 197e12


def run_profile(path: str, mode: str, depth: int, image: int) -> None:
    from paddle_tpu.analysis.resnet_profile import profile_resnet

    B = int(os.environ.get("RESNET_BENCH_B", "8"))
    prof = profile_resnet(depth=depth, image=image, batch=B, mode=mode)
    with open(path, "w") as f:
        json.dump(prof, f, indent=1)
    hdr = (f"{'region':<34} {'rule':<20} {'n':>2} {'GF':>7} "
           f"{'MB/op':>8} {'MB/fus':>8} {'ms':>8} {'%step':>6} "
           f"{'MB(rw)':>8} {'ms(rw)':>8}")
    print(hdr)
    print("-" * len(hdr))
    for r in prof["regions"]:
        rw = r["rewritten"]
        print(f"{r['name']:<34} {r['rule']:<20} {r['count']:>2} "
              f"{r['flops'] / 1e9:>7.2f} {r['bytes'] / 1e6:>8.2f} "
              f"{r['fused']['bytes'] / 1e6:>8.2f} "
              f"{r['ms']:>8.3f} {str(r['pct_of_step']):>6} "
              f"{rw['bytes'] / 1e6:>8.2f} {rw['ms']:>8.3f}")
    t = prof["totals"]
    print(f"totals: per-op {t['baseline_per_op']['bytes'] / 1e6:.1f} MB, "
          f"region-fused {t['baseline_fused']['bytes'] / 1e6:.1f} MB, "
          f"rewritten {t['rewritten']['bytes'] / 1e6:.1f} MB -> "
          f"bytes_ratio per-op {t['bytes_ratio_per_op']}, fused "
          f"{t['bytes_ratio_fused']}; ms_ratio {t['ms_ratio']}")
    fg = prof["full_graph"]
    print(f"full-graph: {prof['step_ms']:.2f} -> "
          f"{prof['step_ms_rewritten']:.2f} ms, bytes_ratio "
          f"{fg['bytes_ratio']} ({fg['note']})")
    print(f"wrote {path}")


def main():
    import optax
    import paddle_tpu as pt
    from paddle_tpu.autograd import tape as _tape
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.resnet import resnet50
    from paddle_tpu.static.nn import _bind

    B = int(os.environ.get("RESNET_BENCH_B", "128"))
    pt.seed(0)
    model = resnet50(num_classes=1000)
    params = model.parameters()
    bufs = list(model.buffers())            # BN running stats

    def loss_arrays(parrs, barrs, x, y):
        with _bind(params, parrs), _bind(bufs, barrs), _tape.no_grad(), \
                pt.amp.auto_cast(True):
            out = model(Tensor(x))
            l = pt.nn.functional.cross_entropy(
                out.astype("float32"), Tensor(y)).mean()
            new_b = [b._data for b in bufs]
        return l.data, new_b

    opt = optax.sgd(0.1, momentum=0.9)

    def step(parrs, barrs, opt_state, x, y):
        (loss, new_b), grads = jax.value_and_grad(
            loss_arrays, has_aux=True)(parrs, barrs, x, y)
        updates, opt_state = opt.update(grads, opt_state, parrs)
        parrs = optax.apply_updates(parrs, updates)
        return parrs, new_b, opt_state, loss

    parrs = [p._data for p in params]
    barrs = [b._data for b in bufs]
    opt_state = opt.init(parrs)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, 3, 224, 224).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, (B,)).astype(np.int32))
    # compile ONCE ahead-of-time; the same executable serves warmup,
    # timing, and cost_analysis (calling the jit-wrapped fn AND
    # lower().compile() would build the program twice)
    comp = jax.jit(step, donate_argnums=(0, 1, 2)).lower(
        parrs, barrs, opt_state, x, y).compile()
    jstep = comp

    def run_n(n, parrs, barrs, opt_state):
        loss = None
        for _ in range(n):
            parrs, barrs, opt_state, loss = jstep(parrs, barrs,
                                                  opt_state, x, y)
        return parrs, barrs, opt_state, float(loss)  # one host sync

    parrs, barrs, opt_state, _ = run_n(2, parrs, barrs, opt_state)
    n0, n1 = 2, 10
    t = {}
    for n in (n0, n1):
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            parrs, barrs, opt_state, loss = run_n(n, parrs, barrs,
                                                  opt_state)
            best = min(best, time.perf_counter() - t0)
        t[n] = best
    dt = (t[n1] - t[n0]) / (n1 - n0)

    try:
        from paddle_tpu.analysis.hbm import xla_cost_analysis
        flops = float(xla_cost_analysis(comp)["flops"])
        source = "xla_cost_analysis"
    except Exception:
        flops = 3 * 2 * 4.089e9 * B
        source = "analytic_4.09GMAC"
    mfu = flops / dt / peak_flops()
    print(json.dumps({
        "metric": "resnet50_train_mfu_1chip",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak_bf16",
        "batch": B,
        "step_ms": round(dt * 1e3, 2),
        "images_per_sec": round(B / dt, 1),
        "flops_per_step": flops,
        "flop_source": source,
        "loss": loss,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile", metavar="OUT_JSON", default=None,
                    help="write the per-region rewrite profile and exit")
    ap.add_argument("--mode", choices=("infer", "train"),
                    default="infer")
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--image", type=int, default=224)
    args = ap.parse_args()
    if args.profile:
        run_profile(args.profile, args.mode, args.depth, args.image)
    else:
        main()

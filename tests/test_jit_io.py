"""to_static capture, save/load, StableHLO export, sharded checkpoint."""
import os

import numpy as np
import jax
import pytest

import paddle_tpu as pt
from paddle_tpu import nn


def test_to_static_function():
    @pt.jit.to_static
    def f(x, y):
        return pt.matmul(x, y) + 1.0

    a = pt.to_tensor(np.random.randn(3, 4).astype(np.float32))
    b = pt.to_tensor(np.random.randn(4, 5).astype(np.float32))
    out = f(a, b)
    ref = a.numpy() @ b.numpy() + 1.0
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_to_static_layer_training():
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    m2 = pt.jit.to_static(m)
    assert m2 is m
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    x = pt.to_tensor(np.random.randn(4, 8).astype(np.float32))
    y = pt.to_tensor(np.random.randn(4, 4).astype(np.float32))
    losses = []
    for _ in range(5):
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]  # grads flowed through the jit boundary


def test_to_static_lower_stablehlo():
    @pt.jit.to_static
    def f(x):
        return pt.exp(x)

    txt = f.lower(pt.to_tensor(np.ones((2, 2), np.float32)))
    assert "stablehlo" in txt or "exponential" in txt


def test_save_load_roundtrip(tmp_path):
    m = nn.Linear(4, 4)
    sd = m.state_dict()
    p = str(tmp_path / "model.pdparams")
    pt.save(sd, p)
    loaded = pt.load(p)
    m2 = nn.Linear(4, 4)
    m2.set_state_dict(loaded)
    np.testing.assert_array_equal(m.weight.numpy(), m2.weight.numpy())
    # nested structures + plain objects survive
    pt.save({"step": 7, "nested": {"w": m.weight}}, str(tmp_path / "x"))
    obj = pt.load(str(tmp_path / "x"))
    assert obj["step"] == 7
    np.testing.assert_array_equal(obj["nested"]["w"].numpy(),
                                  m.weight.numpy())


def test_jit_save_load_inference(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(tmp_path / "deploy")
    pt.jit.save(m, path, input_spec=[pt.jit.InputSpec([3, 4], "float32")])
    loaded = pt.jit.load(path)
    x = pt.to_tensor(np.random.randn(3, 4).astype(np.float32))
    np.testing.assert_allclose(loaded(x)[0].numpy() if isinstance(
        loaded(x), (list, tuple)) else loaded(x).numpy(),
        m(x).numpy(), rtol=1e-5)


def test_distributed_checkpoint_reshard(tmp_path):
    from paddle_tpu.distributed import checkpoint as dckpt
    from paddle_tpu.distributed import shard_tensor, ProcessMesh, Shard, Replicate
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
    w = pt.to_tensor(np.random.randn(8, 8).astype(np.float32))
    sharded = shard_tensor(w, mesh, [Shard(0), Shard(1)])
    path = str(tmp_path / "ckpt")
    dckpt.save_state_dict({"w": sharded}, path)
    # restore into a DIFFERENT layout (reshard-on-load)
    target = shard_tensor(pt.zeros([8, 8]), mesh, [Replicate(), Shard(0)])
    state = {"w": target}
    dckpt.load_state_dict(state, path)
    np.testing.assert_array_equal(state["w"].numpy(), w.numpy())


def test_jit_save_dynamic_batch_dim(tmp_path):
    """InputSpec([None, D]) exports a symbolic batch dim — the loaded
    program accepts ANY batch size (the serving path's requirement)."""
    import paddle_tpu as pt
    from paddle_tpu.jit import InputSpec
    m = pt.nn.Sequential(pt.nn.Linear(6, 3))
    pt.jit.save(m, str(tmp_path / "dyn"), input_spec=[InputSpec([None, 6])])
    loaded = pt.jit.load(str(tmp_path / "dyn"))
    w = np.asarray(m[0].weight.data)
    b = np.asarray(m[0].bias.data)
    for bs in (1, 2, 7):
        x = np.random.RandomState(bs).randn(bs, 6).astype(np.float32)
        out = loaded(x)
        np.testing.assert_allclose(np.asarray(out.data), x @ w + b,
                                   rtol=1e-4, atol=1e-5)


def test_jit_save_multi_input_shared_batch(tmp_path):
    """Two dynamic-batch inputs that combine in forward: their None dims
    must unify into ONE symbolic batch or the export cannot trace."""
    import paddle_tpu as pt
    from paddle_tpu.jit import InputSpec

    class TwoIn(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = pt.nn.Linear(6, 2)

        def forward(self, a, b):
            return self.fc(a + b)

    m = TwoIn()
    pt.jit.save(m, str(tmp_path / "two"),
                input_spec=[InputSpec([None, 6]), InputSpec([None, 6])])
    loaded = pt.jit.load(str(tmp_path / "two"))
    a = np.random.RandomState(0).randn(3, 6).astype(np.float32)
    b = np.random.RandomState(1).randn(3, 6).astype(np.float32)
    w = np.asarray(m.fc.weight.data)
    bias = np.asarray(m.fc.bias.data)
    np.testing.assert_allclose(np.asarray(loaded(a, b).data),
                               (a + b) @ w + bias, rtol=1e-4, atol=1e-5)
    # string dims are usable symbols too
    pt.jit.save(m, str(tmp_path / "twos"),
                input_spec=[InputSpec(["n", 6]), InputSpec(["n", 6])])
    loaded2 = pt.jit.load(str(tmp_path / "twos"))
    np.testing.assert_allclose(np.asarray(loaded2(a, b).data),
                               (a + b) @ w + bias, rtol=1e-4, atol=1e-5)

"""Multi-process fleet (paddle_tpu/serving/fleet/proc/): launcher,
RPC transport, crash supervision, KV-page migration.

Correctness bar (ISSUE r16): the process boundary must be INVISIBLE to
a request's math — every stream a worker process serves equals a
standalone in-process ``generate()`` token-for-token, including across
a SIGKILLed worker (crash detect -> hand-back -> re-dispatch, with
exactly-once emission) and across KV-page migration (prefill on A,
adopt on B, decode on B bitwise-equal).

All workers are forced ``JAX_PLATFORMS=cpu`` (WorkerSpec default) and
every test runs under a hard SIGALRM timeout so a hung worker fails
the test instead of wedging tier-1.
"""
import os
import signal
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.fleet.proc import (ProcServingFleet,
                                           TransportError,
                                           TransportTimeout,
                                           WorkerSpec, WorkerTransport,
                                           request_from_wire,
                                           request_to_wire)
from paddle_tpu.serving.prefix_cache import prefix_fingerprints
from paddle_tpu.serving.scheduler import Request, RequestHandle

# no pytest-timeout in the image: a hard SIGALRM per test is the
# wedge-proofing — a hung worker (or a deadlocked transport) raises
# here instead of stalling the whole tier-1 run
_HARD_TIMEOUT_S = 240


@pytest.fixture(autouse=True)
def _hard_timeout():
    def _boom(signum, frame):
        raise TimeoutError(
            f"fleet-proc test exceeded hard {_HARD_TIMEOUT_S}s limit")
    old = signal.signal(signal.SIGALRM, _boom)
    signal.alarm(_HARD_TIMEOUT_S)
    yield
    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


CFG_KW = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
              num_hidden_layers=2, num_attention_heads=4,
              num_key_value_heads=2, max_position_embeddings=128,
              dtype="float32", use_flash_attention=False, remat=False)
ENGINE_KW = dict(max_batch=4, page_size=4, max_prompt_len=16,
                 max_new_tokens_cap=16)
SPEC = WorkerSpec(cfg_kw=CFG_KW, params_seed=0, engine_kw=ENGINE_KW,
                  warm=False)
CFG = L.LlamaConfig(**{**CFG_KW, "dtype": jnp.float32})


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def ref_engine(params):
    eng = ServingEngine(params, CFG, **ENGINE_KW)
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def fleet():
    """ONE 2-worker fleet shared by the whole module (spawn + engine
    build is the expensive part); the kill test runs LAST in file
    order and consumes it."""
    f = ProcServingFleet(SPEC, replicas=2, policy="round_robin")
    yield f
    f.close()


# ---------------------------------------------------------------------------
# wire schema
# ---------------------------------------------------------------------------

def test_request_wire_roundtrip():
    """Request parameters survive the hop; deadlines travel as
    REMAINING seconds (monotonic clocks are per-process)."""
    req = Request([1, 2, 3], 4, eos_token_id=7,
                  deadline_s=time.monotonic() + 5.0,
                  temperature=0.5, top_p=0.9, top_k=3, seed=11)
    w = request_to_wire(req)
    assert w["rid"] == req.id and w["prompt"] == [1, 2, 3]
    assert 0.0 < w["deadline"] <= 5.0
    twin = request_from_wire(w)
    np.testing.assert_array_equal(twin.prompt, req.prompt)
    assert (twin.max_new_tokens, twin.eos_token_id, twin.temperature,
            twin.top_p, twin.top_k, twin.seed) == (4, 7, 0.5, 0.9, 3,
                                                   11)
    assert twin.deadline_s is not None
    # no deadline stays no deadline
    assert request_from_wire(
        request_to_wire(Request([1], 1))).deadline_s is None


# ---------------------------------------------------------------------------
# transport unit tests (no process needed: drive the demux directly)
# ---------------------------------------------------------------------------

def _shell_transport():
    """A WorkerTransport shell around the frame demux only."""
    t = object.__new__(WorkerTransport)
    t.name = "shell"
    t._lock = threading.Lock()
    t._waiters = {}
    t._fseq = {}
    t.frame_violations = 0
    t.ready = None
    t._ready_evt = threading.Event()
    t._fatal = None
    got = []
    t.on_frame = got.append
    return t, got


def test_frame_ordering_enforced():
    """Per-request fseq must count 0,1,2,...; an out-of-order frame is
    counted and DROPPED — it can never corrupt a caller's stream."""
    t, got = _shell_transport()
    t._feed(("tok", 1, 0, 10))
    t._feed(("tok", 1, 2, 12))          # gap: violation, dropped
    assert t.frame_violations == 1
    t._feed(("tok", 1, 1, 11))          # in-order resumes
    t._feed(("tok", 1, 1, 11))          # replay: violation, dropped
    assert t.frame_violations == 2
    t._feed(("done", 1, 2, "completed", ""))
    assert [m[0] for m in got] == ["tok", "tok", "done"]
    assert [m[3] for m in got if m[0] == "tok"] == [10, 11]
    # done must carry the final count too
    t._feed(("tok", 2, 0, 5))
    t._feed(("done", 2, 3, "completed", ""))    # wrong count: dropped
    assert t.frame_violations == 3
    assert sum(1 for m in got if m[0] == "done") == 1
    # independent requests keep independent sequences
    t._feed(("tok", 3, 0, 9))
    assert t.frame_violations == 3


def test_frame_reply_resolves_waiter():
    t, _ = _shell_transport()
    ev = threading.Event()
    slot = [ev, None, None]
    t._waiters[7] = slot
    t._feed(("reply", 7, True, {"x": 1}))
    assert ev.is_set() and slot[1] is True and slot[2] == {"x": 1}
    # a reply for a popped (timed-out) waiter is discarded quietly
    t._feed(("reply", 7, True, {"x": 2}))


# ---------------------------------------------------------------------------
# migration mechanics, in-process (engine.export_chain / adopt_chain)
# ---------------------------------------------------------------------------

HEADER = list(range(1, 9))              # 8 tokens = 2 full pages


def _chain_fp(tail):
    prompt = np.asarray(HEADER + tail, np.int32)
    return int(prefix_fingerprints(prompt, 4, max_depth=8)[-1])


def test_engine_export_adopt_bitwise(params, ref_engine):
    """The core migration invariant with no processes in the way:
    prefill on A, export the chain by fingerprint, adopt into B,
    decode on B == single-engine generate(), bitwise."""
    a = ServingEngine(params, CFG, **ENGINE_KW)
    b = ServingEngine(params, CFG, **ENGINE_KW)
    try:
        a.generate(HEADER + [50, 51, 52], 6)
        fp = _chain_fp([50, 51, 52])
        blob = a.export_chain(fp)
        assert blob is not None and blob["page_size"] == 4
        assert [len(t) for t in blob["tokens"]] == [4, 4]
        assert blob["k"].shape[2] == 2      # pages axis
        assert b.adopt_chain(blob) == {"matched_pages": 0,
                                       "adopted_pages": 2}
        # adoption is idempotent: the trie dedups, never double-allocs
        assert b.adopt_chain(blob) == {"matched_pages": 2,
                                       "adopted_pages": 0}
        out = b.generate(HEADER + [60, 61], 6)
        np.testing.assert_array_equal(
            out, ref_engine.generate(HEADER + [60, 61], 6))
        assert b.snapshot()["counters"]["prefix_hits"] >= 1
        # unknown fingerprints export nothing
        assert a.export_chain(987654321) is None
    finally:
        a.close()
        b.close()


def test_engine_export_after_defrag(params, ref_engine):
    """Export must follow the LIVE page ids: scatter the source's page
    table (evict an older chain out from under a newer one), compact
    with defragment(), THEN export — the adopted decode stays
    bitwise-equal because export reads node.page after remap."""
    a = ServingEngine(params, CFG, **ENGINE_KW)
    b = ServingEngine(params, CFG, **ENGINE_KW)
    try:
        other = list(range(100, 108))
        a.generate(other + [9, 8], 6)       # older chain: low pages
        a.generate(HEADER + [50, 51], 6)    # target chain: higher pages
        with a._tick_lock:                  # punch a hole under it
            a.prefix_cache.evict(2)
        moved = a.defragment()
        assert moved >= 1                   # pages actually remapped
        blob = a.export_chain(_chain_fp([50, 51]))
        assert blob is not None
        assert b.adopt_chain(blob)["adopted_pages"] == 2
        out = b.generate(HEADER + [77], 6)
        np.testing.assert_array_equal(
            out, ref_engine.generate(HEADER + [77], 6))
    finally:
        a.close()
        b.close()


def test_adopt_rejects_page_size_mismatch(params):
    a = ServingEngine(params, CFG, **ENGINE_KW)
    c = ServingEngine(params, CFG,
                      **{**ENGINE_KW, "page_size": 8})
    try:
        a.generate(HEADER + [50], 6)
        blob = a.export_chain(_chain_fp([50]))
        assert blob is not None
        with pytest.raises(ValueError, match="page-size mismatch"):
            c.adopt_chain(blob)
    finally:
        a.close()
        c.close()


# ---------------------------------------------------------------------------
# live fleet: parity, refusal, timeout, migration — then the kill
# ---------------------------------------------------------------------------

def test_proc_fleet_bitwise_parity(fleet, ref_engine):
    """Mixed requests over 2 worker processes: every stream equals the
    single in-process engine token-for-token (same weights by
    params_seed), and the merged scrape carries both workers."""
    rng = np.random.RandomState(0)
    specs = [(rng.randint(1, 256,
                          (int(rng.randint(2, 12)),)).tolist(),
              int(rng.randint(2, 10))) for _ in range(8)]
    handles = [fleet.submit(p, m) for p, m in specs]
    outs = [h.result(timeout=180) for h in handles]
    for (p, m), out in zip(specs, outs):
        np.testing.assert_array_equal(out, ref_engine.generate(p, m))
    snap = fleet.snapshot()
    served = {n: h["counters"]["completed"]
              for n, h in snap["replicas"].items() if "counters" in h}
    assert sum(served.values()) >= len(specs)
    assert all(v > 0 for v in served.values())   # round-robin spread
    text = fleet.expose()
    types = [ln for ln in text.splitlines() if ln.startswith("# TYPE")]
    assert len(types) == len(set(types))         # one TYPE per family
    assert 'replica="w0"' in text and 'replica="w1"' in text
    assert "paddle_serving_fleet_generation" in text


def test_oversized_prompt_refused_not_crashed(fleet):
    """A prompt beyond the worker's geometry is REFUSED over the
    transport (inject -> accepted:False -> router RuntimeError), and
    the worker stays alive."""
    r0 = fleet.replicas()[0]
    big = Request(list(range(1, 31)), 4)        # 30 > max_prompt_len 16
    assert r0.inject(big) is False
    with pytest.raises(RuntimeError, match="no serving replica"):
        fleet.submit(list(range(1, 31)), 4)
    assert r0.serving and r0.alive


def test_never_ack_worker_times_out(fleet):
    """A worker that never ACKs (SIGSTOPped) raises TransportTimeout
    instead of wedging the caller; after SIGCONT the same transport
    serves rpcs again (the late reply is discarded quietly)."""
    rep = fleet.replicas()[1]
    os.kill(rep.pid, signal.SIGSTOP)
    try:
        with pytest.raises(TransportTimeout):
            rep._rpc("ping", timeout=1.0)
    finally:
        os.kill(rep.pid, signal.SIGCONT)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            assert rep._rpc("ping", timeout=5.0)["pid"] == rep.pid
            break
        except TransportError:
            time.sleep(0.2)
    else:
        pytest.fail("worker did not recover after SIGCONT")


def test_unknown_op_is_an_error_not_a_hang(fleet):
    with pytest.raises(TransportError, match="unknown op"):
        fleet.replicas()[0]._rpc("no_such_op", timeout=10.0)


def test_kv_migration_between_workers(fleet, ref_engine):
    """Prefill on worker A -> migrate the chain's KV pages by trie
    fingerprint -> decode on worker B: B's stream is bitwise-equal to
    the single-engine run, and B's prefix cache scores real hits."""
    r0, r1 = fleet.replicas()[:2]
    header = list(range(1, 9))                  # 8 tokens = 2 pages
    warm = Request(header + [50, 51, 52], 6)
    assert r0.inject(warm)
    RequestHandle(warm).result(timeout=180)
    fps = prefix_fingerprints(np.asarray(header + [50, 51, 52],
                                         np.int32), 4, max_depth=8)
    before = (r1.snapshot_dict() or {}).get("counters", {})
    stats = fleet.migrate_chain(int(fps[-1]), r0.name, r1.name)
    assert stats == {"matched_pages": 0, "adopted_pages": 2}
    # replays are cheap no-ops (trie dedup), never double-alloc
    again = fleet.migrate_chain(int(fps[-1]), r0.name, r1.name)
    assert again == {"matched_pages": 2, "adopted_pages": 0}
    # an unknown fingerprint exports nothing
    assert fleet.migrate_chain(123456789, r0.name, r1.name) is None
    cont = Request(header + [60, 61], 6)
    assert r1.inject(cont)
    out = RequestHandle(cont).result(timeout=180)
    np.testing.assert_array_equal(
        out, ref_engine.generate(header + [60, 61], 6))
    after = (r1.snapshot_dict() or {}).get("counters", {})
    assert after.get("prefix_hits", 0) > before.get("prefix_hits", 0)


def test_sigkill_mid_stream_zero_drops_exactly_once(fleet, ref_engine):
    """THE crash contract, end to end: SIGKILL a worker while it is
    streaming; the launcher detects the death, hands every unfinished
    request back, and the router re-dispatches to the survivor —
    every handle completes, bitwise-equal to the single-engine run
    (exactly-once emission: the re-decoded prefix is deduped, so no
    token is ever delivered twice), with zero drops and a clean
    survivor sentinel. Runs LAST in file order: it consumes the
    module fleet."""
    rng = np.random.RandomState(3)
    specs = [(rng.randint(1, 256,
                          (int(rng.randint(2, 12)),)).tolist(), 12)
             for _ in range(10)]
    # warm the full program inventory in every worker first, so the
    # armed sentinels below prove the kill scenario compiles NOTHING
    # new on the survivor
    for rep in fleet.replicas():
        rep._rpc("warm_programs", timeout=180.0)
    fleet.arm_sentinels()
    handles = [fleet.submit(p, m) for p, m in specs]
    time.sleep(0.3)                     # let streams start
    victim = fleet.replicas()[0]
    survivor = fleet.replicas()[1]
    fleet.kill_hard(victim.name, timeout=60)
    outs = [h.result(timeout=180) for h in handles]
    for (p, m), out, h in zip(specs, outs, handles):
        assert h.status == "completed"
        np.testing.assert_array_equal(out, ref_engine.generate(p, m))
    snap = fleet.snapshot()
    assert snap["fleet"]["crashes"] == 1
    assert snap["router"]["redispatch_failed"] == 0
    assert victim.state == "gone"
    assert all(r.name != victim.name
               for r in fleet.router.replicas())
    s = survivor.sentinel_report()
    assert s is None or s.get("clean", True)
    # duplicate-emission pin: every completed handle has EXACTLY its
    # stream's tokens (a double delivery would show as length drift)
    for (p, m), out in zip(specs, outs):
        assert len(out) <= m

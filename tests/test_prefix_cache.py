"""Prefix cache + chunked prefill (ISSUE r8).

Correctness bar: greedy engine outputs stay BYTE-IDENTICAL to
standalone ``generate()`` whether a prompt's prefix was cached,
partially cached, or cold, and whether its suffix was prefilled whole
or in page-aligned chunks interleaved with decode. The enabling claim
— the chunk program (gathered prefix pages ++ in-graph chunk, bottom-
right causal flash) produces bitwise-identical KV and logits to the
whole-prompt program — is pinned at the model layer first, then
through the engine in every cache state.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.inference.paged_kv import PagePool
from paddle_tpu.models import llama as L
from paddle_tpu.serving import (COMPLETED, PrefixCache, Request,
                                Scheduler, ServingEngine)

CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


import functools


@functools.lru_cache(maxsize=None)
def _gen_jit(n):
    return jax.jit(lambda p, t: L.generate(p, t, CFG, max_new_tokens=n))


def _ref(params, prompt, n):
    out = _gen_jit(n)(params, jnp.asarray(prompt)[None])
    return np.asarray(out)[0, len(prompt):]


def _engine(params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_prompt_len", 16)
    kw.setdefault("max_new_tokens_cap", 16)
    return ServingEngine(params, CFG, **kw)


# ---------------------------------------------------------------------------
# model layer: the chunk program is bitwise-equal to the whole-prompt one
# ---------------------------------------------------------------------------

def test_chunked_prefill_bitwise_matches_whole_prompt(params):
    """Cold chunked prefill (two page-aligned chunks) must write the
    SAME KV bits and produce the SAME last-position logits as one
    whole-prompt serving_prefill — the exactness foundation everything
    engine-level rests on."""
    ps, n = 4, 11
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, CFG.vocab_size, (n,)).astype(np.int32)
    pools = L.init_serving_pages(CFG, 16, ps)
    table = np.zeros((8,), np.int32)
    table[:4] = [1, 2, 3, 4]

    pad = np.zeros((1, 16), np.int32)
    pad[0, :n] = prompt
    lg_full, kp_f, vp_f = L.serving_prefill(
        params, jnp.asarray(pad), jnp.int32(n), jnp.asarray(table),
        jnp.array(pools["k_pages"]), jnp.array(pools["v_pages"]), CFG)

    c0 = np.zeros((1, 8), np.int32)
    c0[0] = prompt[:8]
    _, kp_c, vp_c = L.serving_prefill_chunk(
        params, jnp.asarray(c0), jnp.int32(8), jnp.asarray(table),
        jnp.array(pools["k_pages"]), jnp.array(pools["v_pages"]), CFG,
        prefix_pages=0)
    c1 = np.zeros((1, 8), np.int32)
    c1[0, :3] = prompt[8:]
    lg_chunk, kp_c, vp_c = L.serving_prefill_chunk(
        params, jnp.asarray(c1), jnp.int32(3), jnp.asarray(table),
        kp_c, vp_c, CFG, prefix_pages=2)

    np.testing.assert_array_equal(np.asarray(lg_full),
                                  np.asarray(lg_chunk))
    # pages 1..3 hold the prompt's 11 valid positions (page 3 partially)
    np.testing.assert_array_equal(np.asarray(kp_f)[:, :, 1:4],
                                  np.asarray(kp_c)[:, :, 1:4])
    np.testing.assert_array_equal(np.asarray(vp_f)[:, :, 1:4],
                                  np.asarray(vp_c)[:, :, 1:4])


# ---------------------------------------------------------------------------
# engine: byte-identical outputs in every cache state
# ---------------------------------------------------------------------------

def test_warm_prefix_outputs_match_generate_and_save_pages(params):
    """Identical prompt twice: the second admission attaches cached
    pages (hit counters fire, fewer private pages allocated) and still
    produces generate()'s exact tokens."""
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, CFG.vocab_size, (12,)).astype(np.int32)
    want = _ref(params, prompt, 8)
    with _engine(params) as eng:
        out_cold = eng.submit(prompt, 8).result(timeout=300)
        snap0 = eng.stats()
        out_warm = eng.submit(prompt, 8).result(timeout=300)
        snap1 = eng.stats()
    np.testing.assert_array_equal(out_cold, want)
    np.testing.assert_array_equal(out_warm, want)
    c0, c1 = snap0["counters"], snap1["counters"]
    assert c0["prefix_misses"] == 1 and c0["prefix_hits"] == 0
    assert c1["prefix_hits"] == 1
    # attach cap: floor((12-1)/4) = 2 of the 3 cached full pages
    assert c1["prefix_hit_tokens"] == 8
    assert c1["prefix_pages_saved"] == 2
    assert snap0["gauges"]["prefix_cache"]["cached_pages"] == 3
    # close() returned every cached page to the pool
    assert eng.pool.used_pages == 0


def test_partial_prefix_and_extension_match_generate(params):
    """Prompts that diverge mid-prefix or extend past the cached chain
    attach only the matching page-aligned span — outputs stay exact."""
    rng = np.random.RandomState(2)
    base = rng.randint(0, CFG.vocab_size, (12,)).astype(np.int32)
    diverge = base.copy()[:10]
    diverge[6] = (diverge[6] + 1) % CFG.vocab_size   # breaks page 2
    extend = np.concatenate(
        [base, rng.randint(0, CFG.vocab_size, (4,)).astype(np.int32)])
    with _engine(params) as eng:
        outs = {}
        outs["base"] = eng.submit(base, 6).result(timeout=300)
        outs["diverge"] = eng.submit(diverge, 6).result(timeout=300)
        outs["extend"] = eng.submit(extend, 6).result(timeout=300)
        snap = eng.stats()
    np.testing.assert_array_equal(outs["base"], _ref(params, base, 6))
    np.testing.assert_array_equal(outs["diverge"],
                                  _ref(params, diverge, 6))
    np.testing.assert_array_equal(outs["extend"], _ref(params, extend, 6))
    # diverge matched page 1 only; extend matched base's whole chain
    assert snap["counters"]["prefix_hits"] == 2
    assert snap["counters"]["prefix_hit_tokens"] == 4 + 12


def test_chunked_prefill_engine_matches_generate(params):
    """Long prompts absorbed in page-aligned chunks (cold AND warm)
    produce generate()'s exact tokens; chunk counters fire."""
    rng = np.random.RandomState(3)
    long_p = rng.randint(0, CFG.vocab_size, (15,)).astype(np.int32)
    short_p = rng.randint(0, CFG.vocab_size, (3,)).astype(np.int32)
    with _engine(params, prefill_chunk=4) as eng:
        out_a = eng.submit(long_p, 8).result(timeout=300)
        out_b = eng.submit(short_p, 6).result(timeout=300)
        out_warm = eng.submit(long_p, 8).result(timeout=300)
        snap = eng.stats()
    np.testing.assert_array_equal(out_a, _ref(params, long_p, 8))
    np.testing.assert_array_equal(out_b, _ref(params, short_p, 6))
    np.testing.assert_array_equal(out_warm, _ref(params, long_p, 8))
    c = snap["counters"]
    # cold 15-token prompt: ceil(15/4) = 4 chunks; warm run attaches
    # floor(14/4)=3 pages and chunk-prefills the 3-token suffix
    assert c["prefill_chunks"] >= 5
    assert c["prefix_hits"] == 1 and c["prefix_hit_tokens"] == 12


def test_mid_stream_admission_during_chunked_prefill(params):
    """A request admitted while another's chunked prefill is in flight
    decodes correctly, and the prefilling one joins later — both exact.
    The chunk queue was genuinely populated (parked slots observed)."""
    rng = np.random.RandomState(4)
    long_p = rng.randint(0, CFG.vocab_size, (16,)).astype(np.int32)
    short_p = rng.randint(0, CFG.vocab_size, (2,)).astype(np.int32)
    with _engine(params, prefill_chunk=4, max_batch=2,
                 tick_interval_s=0.01) as eng:
        h_long = eng.submit(long_p, 10)
        h_short = eng.submit(short_p, 10)
        out_long = h_long.result(timeout=300)
        out_short = h_short.result(timeout=300)
        snap = eng.stats()
    np.testing.assert_array_equal(out_long, _ref(params, long_p, 10))
    np.testing.assert_array_equal(out_short, _ref(params, short_p, 10))
    assert snap["histograms"]["chunk_queue_depth"]["max"] >= 1
    assert snap["counters"]["prefill_chunks"] >= 4


def test_chunked_prefill_with_fused_decode_blocks(params):
    """prefill_chunk + decode_block_size>1 compose: the fused block
    program runs while a parked slot is mid-prefill (its writes must
    land on the trash page, not the pages being prefilled)."""
    rng = np.random.RandomState(9)
    long_p = rng.randint(0, CFG.vocab_size, (16,)).astype(np.int32)
    short_p = rng.randint(0, CFG.vocab_size, (3,)).astype(np.int32)
    with _engine(params, prefill_chunk=4, decode_block_size=3,
                 max_batch=2, tick_interval_s=0.01) as eng:
        h_short = eng.submit(short_p, 12)   # decoding first
        h_long = eng.submit(long_p, 8)      # chunk-prefills beside it
        out_short = h_short.result(timeout=300)
        out_long = h_long.result(timeout=300)
    np.testing.assert_array_equal(out_short, _ref(params, short_p, 12))
    np.testing.assert_array_equal(out_long, _ref(params, long_p, 8))


def test_close_drain_finishes_half_prefilled_request(params):
    """close(drain=True) racing a chunked prefill must still deliver
    the full, exact continuation."""
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, CFG.vocab_size, (16,)).astype(np.int32)
    eng = _engine(params, prefill_chunk=4, tick_interval_s=0.01)
    h = eng.submit(prompt, 6)
    eng.close()  # drain=True: the half-prefilled request completes
    assert h.status == COMPLETED
    np.testing.assert_array_equal(h.result(), _ref(params, prompt, 6))
    assert eng.pool.used_pages == 0


def test_eviction_under_page_pressure_keeps_serving(params):
    """A pool too small to keep every retired prefix cached must evict
    refcount-0 prefixes LRU-first and keep admitting — exactness and
    liveness under pressure."""
    rng = np.random.RandomState(6)
    specs = [(rng.randint(0, CFG.vocab_size, (12,)).astype(np.int32), 6)
             for _ in range(4)]
    # pages_per_slot = ceil((16+16-1)/4) = 8; 12 allocatable pages only
    with _engine(params, total_pages=13) as eng:
        outs = [eng.submit(p, m).result(timeout=300) for p, m in specs]
        snap = eng.stats()
    for (p, m), out in zip(specs, outs):
        np.testing.assert_array_equal(out, _ref(params, p, m))
    assert snap["gauges"]["prefix_cache"]["evictions"] > 0


def test_qwen2_moe_warm_prefix_matches_generate():
    from paddle_tpu.models import qwen2_moe as Q
    qcfg = Q.Qwen2MoeConfig.tiny(dtype=jnp.float32,
                                 use_flash_attention=False, remat=False)
    qparams = Q.init_params(qcfg, jax.random.PRNGKey(1))
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, qcfg.vocab_size, (7,)).astype(np.int32)
    ref = np.asarray(Q.generate(qparams, jnp.asarray(prompt)[None], qcfg,
                                max_new_tokens=5))[0, len(prompt):]
    with ServingEngine(qparams, qcfg, max_batch=2, page_size=2,
                       max_prompt_len=8, max_new_tokens_cap=8) as eng:
        np.testing.assert_array_equal(
            eng.submit(prompt, 5).result(timeout=300), ref)
        np.testing.assert_array_equal(
            eng.submit(prompt, 5).result(timeout=300), ref)
        assert eng.stats()["counters"]["prefix_hits"] == 1


# ---------------------------------------------------------------------------
# PrefixCache unit behaviour
# ---------------------------------------------------------------------------

def _toks(*xs):
    return np.asarray(xs, np.int32)


def test_prefix_cache_trie_acquire_insert_release():
    pool = PagePool(total_pages=16, page_size=2)
    pc = PrefixCache(pool)
    prompt = _toks(1, 2, 3, 4, 5)          # 2 full pages + 1 tail token
    assert pc.acquire(prompt) == []        # cold
    pages = pool.alloc(2)
    adopted, dup = pc.insert(prompt, [], pages)
    assert [nd.page for nd in adopted] == pages and dup == []
    assert pc.cached_pages == 2
    # same prompt: both pages match but the cap leaves >= 1 token
    got = pc.acquire(prompt)
    assert [nd.page for nd in got] == pages
    # exactly-page-sized prompt: cap attaches only the first page
    capped = pc.acquire(_toks(1, 2, 3, 4))
    assert len(capped) == 1
    pc.release(capped)
    # diverging second page stops the walk
    got2 = pc.acquire(_toks(1, 2, 9, 9, 7))
    assert len(got2) == 1
    pc.release(got2)
    pc.release(got)
    pc.release(adopted)       # drop the insert-time ownership: refs 0
    with pytest.raises(AssertionError):
        pc.release(adopted)   # refcount underflow is loud, not silent


def test_prefix_cache_attach_quantum_bounds_compile_shapes():
    """attach_quantum=q truncates attachment to multiples of q pages
    (bounding the chunk program's static prefix_pages value set); the
    trie still caches every full page."""
    pool = PagePool(total_pages=16, page_size=2)
    pc = PrefixCache(pool, attach_quantum=2)
    prompt = _toks(1, 2, 3, 4, 5, 6, 7)     # 3 full pages + 1 tail
    nodes = pc.insert(prompt, [], pool.alloc(3))[0]
    assert pc.cached_pages == 3             # caching is NOT quantized
    got = pc.acquire(prompt)                # match 3 -> attach 2
    assert len(got) == 2
    pc.release(got)
    pc.release(nodes)


def test_prefix_cache_insert_dedups_concurrent_identical_prompts():
    pool = PagePool(total_pages=16, page_size=2)
    pc = PrefixCache(pool)
    prompt = _toks(1, 2, 3, 4, 5)
    a = pool.alloc(2)
    pc.insert(prompt, [], a)
    b = pool.alloc(2)                       # the racing duplicate
    adopted, dup = pc.insert(prompt, [], b)
    assert adopted == [] and dup == b       # loser keeps its pages
    assert pc.cached_pages == 2


def test_prefix_cache_eviction_is_lru_and_leaf_only():
    pool = PagePool(total_pages=16, page_size=2)
    pc = PrefixCache(pool)
    # chain A: two pages (parent + leaf); chain B: one page, used later
    a = pc.insert(_toks(1, 2, 3, 4, 9), [], pool.alloc(2))[0]
    b = pc.insert(_toks(7, 8, 9), [], pool.alloc(1))[0]
    pc.release(a)
    pc.release(b)
    got = pc.acquire(_toks(7, 8, 5))        # touch B: A becomes LRU
    pc.release(got)
    free0 = pool.free_pages
    assert pc.evict(1) == 1                 # A's LEAF goes first ...
    assert pc.cached_pages == 2
    survivor = pc.acquire(_toks(1, 2, 5))   # ... its parent survives
    assert len(survivor) == 1
    pc.release(survivor)
    # pinned pages are never evicted
    pin = pc.acquire(_toks(7, 8, 5))
    assert pc.evict(10) == 1                # only A's parent evictable
    pc.release(pin)
    assert pc.evict(10) == 1                # now B goes too
    assert pc.cached_pages == 0
    assert pool.free_pages == free0 + 3


def test_prefix_cache_remap_follows_defrag_plan():
    pool = PagePool(total_pages=16, page_size=2)
    pc = PrefixCache(pool)
    nodes = pc.insert(_toks(1, 2, 3, 4, 5), [], [9, 12])[0]
    pc.remap({9: 1, 12: 2})
    assert [nd.page for nd in nodes] == [1, 2]


# ---------------------------------------------------------------------------
# hot-chain affinity summary (ISSUE r18 satellite): the fleet router's
# warmth signal must track the trie exactly — hit accounting correct
# across LRU eviction, and invariant under defrag remap
# ---------------------------------------------------------------------------

def test_affinity_summary_matches_prompt_fingerprints():
    from paddle_tpu.serving import prefix_fingerprints
    pool = PagePool(total_pages=16, page_size=2)
    pc = PrefixCache(pool)
    prompt = _toks(1, 2, 3, 4, 5)
    nodes = pc.insert(prompt, [], pool.alloc(2))[0]
    summ = pc.affinity_summary(max_depth=2)
    fps = prefix_fingerprints(prompt, page_size=2, max_depth=2)
    # the summary speaks the same hash: every prompt fingerprint
    # resolves, at the right depth
    assert len(fps) == 2 and set(fps) <= set(summ)
    assert summ[fps[0]]["depth"] == 1 and summ[fps[1]]["depth"] == 2
    # insert-time ownership is not a "hit"; acquire() is
    assert summ[fps[0]]["hits"] == 0
    got = pc.acquire(prompt)
    summ = pc.affinity_summary(max_depth=2)
    assert summ[fps[0]]["hits"] == 1 and summ[fps[1]]["hits"] == 1
    assert summ[fps[0]]["refs"] == 2            # insert ref + acquire
    # a non-pinning peek must NOT inflate the hotness signal
    pc.match_pages(prompt)
    assert pc.affinity_summary(2)[fps[0]]["hits"] == 1
    pc.release(got)
    pc.release(nodes)
    # depth cap bounds the walk: depth-1 summary has one entry
    assert len(pc.affinity_summary(max_depth=1)) == 1


def test_affinity_summary_drops_evicted_chains():
    """The affinity signal must never point at evicted KV: after LRU
    eviction the evicted chain's fingerprints vanish while the
    survivor's stats (hits included) are unchanged."""
    from paddle_tpu.serving import prefix_fingerprints
    pool = PagePool(total_pages=16, page_size=2)
    pc = PrefixCache(pool)
    p_a = _toks(1, 2, 3, 4, 9)
    p_b = _toks(7, 8, 9)
    a = pc.insert(p_a, [], pool.alloc(2))[0]
    b = pc.insert(p_b, [], pool.alloc(1))[0]
    pc.release(a)
    pc.release(b)
    got = pc.acquire(p_b)                   # B is hotter AND newer
    pc.release(got)
    fa = prefix_fingerprints(p_a, 2, 2)
    fb = prefix_fingerprints(p_b, 2, 2)
    summ = pc.affinity_summary(2)
    assert set(fa) <= set(summ) and set(fb) <= set(summ)
    assert pc.evict(2) == 2                 # chain A (LRU) fully gone
    summ = pc.affinity_summary(2)
    assert not (set(fa) & set(summ)), "evicted chain still advertised"
    assert summ[fb[0]]["hits"] == 1         # survivor stats intact


def test_affinity_summary_invariant_under_defrag_remap():
    """Fingerprints hash TOKENS, not page ids: a defrag remap moves
    every page and must not change the summary at all."""
    pool = PagePool(total_pages=16, page_size=2)
    pc = PrefixCache(pool)
    prompt = _toks(1, 2, 3, 4, 5)
    nodes = pc.insert(prompt, [], [9, 12])[0]
    got = pc.acquire(prompt)
    before = pc.affinity_summary(2)
    pc.remap({9: 1, 12: 2})
    after = pc.affinity_summary(2)
    assert before == after
    # and the remapped chain still resolves for new acquirers
    got2 = pc.acquire(prompt)
    assert [nd.page for nd in got2] == [1, 2]
    pc.release(got2)
    pc.release(got)
    pc.release(nodes)


# ---------------------------------------------------------------------------
# PagePool.free() guards (satellite): corruption is loud, not silent
# ---------------------------------------------------------------------------

def test_page_pool_free_guards():
    pool = PagePool(total_pages=8, page_size=2)
    pages = pool.alloc(3)
    with pytest.raises(ValueError, match="out of range"):
        pool.free([99])
    with pytest.raises(ValueError, match="out of range"):
        pool.free([-3])
    pool.free(pages[:1])
    with pytest.raises(ValueError, match="double free"):
        pool.free(pages[:1])
    with pytest.raises(ValueError, match="duplicate"):
        pool.free([pages[1], pages[1]])
    # rejected calls freed NOTHING: the two live pages are still live
    assert pool.used_pages == 2
    pool.free(pages[1:])                    # and a clean free still works
    assert pool.used_pages == 0
    pool.free([PagePool.TRASH])             # trash page stays a no-op


# ---------------------------------------------------------------------------
# serving_bench: the shared-prefix workload
# ---------------------------------------------------------------------------

def _load_bench():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "serving_bench.py")
    spec = importlib.util.spec_from_file_location("serving_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serving_bench_shared_prefix_smoke():
    """--shared-prefix replay emits nonzero prefix-cache counters on a
    micro trace (no perf assertions — those are the slow test's)."""
    sb = _load_bench()
    res = sb.main(["--requests", "6", "--rate", "100", "--max-batch", "2",
                   "--mnt-choices", "3", "--max-prompt", "16",
                   "--page-size", "4", "--shared-prefix", "12",
                   "--modes", "engine"])
    eng = res["engine"]
    assert eng["useful_tokens"] > 0
    assert eng["prefix_hit_rate"] > 0
    assert eng["prefix_pages_saved"] > 0


@pytest.mark.slow
def test_prefix_ab_acceptance():
    """ISSUE r8 acceptance on the CPU mesh: warm-prefix TTFT >= 2x
    better than cold, and the max per-tick decode stall under a
    long-prompt admission drops with chunked prefill. Best-of-4 with a
    settle pause: the margins are structural (~2.5x and ~3x measured)
    but this container's absolute latencies swing 2-3x with co-tenant
    load (one all-attempts miss observed right after a full-suite
    run)."""
    sb = _load_bench()
    wins_ttft = wins_stall = 0
    for attempt in range(4):
        if attempt:
            time.sleep(1.0)  # let a co-tenant load transient pass
        res = sb.main(["--requests", "4", "--modes", "prefix_ab"])
        ab = res["prefix_ab"]
        assert ab["prefix_hit_tokens"] > 0
        assert ab["prefix_pages_saved"] > 0
        wins_ttft += ab["warm_ttft_speedup"] >= 2.0
        wins_stall += ab["stall_reduced"]
        if wins_ttft and wins_stall:
            break
    assert wins_ttft >= 1, "warm-prefix TTFT never reached 2x vs cold"
    assert wins_stall >= 1, "chunked prefill never reduced the stall"


# ---------------------------------------------------------------------------
# bounded skip-ahead admission (satellite)
# ---------------------------------------------------------------------------

def test_admission_window_lets_small_requests_overtake():
    pool = PagePool(total_pages=9, page_size=4)
    sched = Scheduler(max_batch=3, pages_per_slot=8, pool=pool,
                      admission_window=2)
    blocker = Request(np.zeros((4,), np.int32), 16)   # 5 pages
    sched.submit(blocker)
    assert len(sched.admit()) == 1                    # 3 pages left
    big = Request(np.zeros((8,), np.int32), 25)       # 8 pages: stuck
    s1 = Request(np.zeros((2,), np.int32), 3)         # 1 page
    s2 = Request(np.zeros((2,), np.int32), 3)
    s3 = Request(np.zeros((2,), np.int32), 3)
    for r in (big, s1, s2, s3):
        assert sched.submit(r)
    # window=2: s1 and s2 overtake the stuck head (FIFO among the
    # fitting) — and that EXHAUSTS big's overtake budget
    a = sched.admit()
    assert [r.id for _, r in a] == [s1.id, s2.id]
    assert sched.queued() == 2                        # big, s3
    sched.retire(a[0][0], COMPLETED)
    # anti-starvation bound: s3 would fit, but big has already been
    # overtaken window=2 times — nothing more passes it
    assert sched.admit() == []
    # capacity frees -> big (always admissible as the head) goes first,
    # the budget resets for the new head, and s3 follows
    sched.retire(0, COMPLETED)
    sched.retire(a[1][0], COMPLETED)
    a3 = sched.admit()
    assert [r.id for _, r in a3] == [big.id]
    sched.retire(a3[0][0], COMPLETED)
    assert [r.id for _, r in sched.admit()] == [s3.id]


def test_fruitless_eviction_preserves_prefix_cache():
    """A candidate whose shortfall cannot be met even by evicting every
    reusable cached page must NOT drain the cache (that would destroy
    every later request's warm TTFT for nothing); once the shortfall IS
    satisfiable, eviction runs and admission proceeds."""
    pool = PagePool(total_pages=9, page_size=2)        # 8 allocatable
    pc = PrefixCache(pool)
    sched = Scheduler(max_batch=2, pages_per_slot=8, pool=pool,
                      prefix_cache=pc)
    holder = Request(np.zeros((2,), np.int32), 9)      # 5 pages
    assert sched.submit(holder) and len(sched.admit()) == 1
    nodes = pc.insert(_toks(1, 2, 3, 4, 5), [], pool.alloc(2))[0]
    pc.release(nodes)                                  # 2 reusable, 1 free
    big = Request(np.zeros((4,), np.int32), 5)         # needs 4 pages
    assert sched.submit(big)
    assert sched.admit() == []                         # 1+2 < 4: blocked
    assert pc.cached_pages == 2                        # cache UNTOUCHED
    sched.drop_queued(lambda r: r is big)
    ok = Request(np.zeros((2,), np.int32), 5)          # needs 3 pages
    assert sched.submit(ok)
    assert [r.id for _, r in sched.admit()] == [ok.id]  # evicts 2, fits
    assert pc.cached_pages == 0 and pc.evictions == 2


def test_admission_window_engine_end_to_end(params):
    """Through the engine: a head whose budget can't fit alongside the
    current resident does not convoy small requests behind it when
    admission_window is set — and everyone's tokens stay exact."""
    rng = np.random.RandomState(8)
    resident = rng.randint(0, CFG.vocab_size, (4,)).astype(np.int32)
    big = rng.randint(0, CFG.vocab_size, (16,)).astype(np.int32)
    small = rng.randint(0, CFG.vocab_size, (2,)).astype(np.int32)
    # pages_per_slot=8, 12 allocatable: resident (5) + big (8) cannot
    # coexist, resident (5) + small (2) can
    with _engine(params, max_batch=2, total_pages=13,
                 admission_window=1, prefix_cache=False,
                 tick_interval_s=0.01) as eng:
        h_res = eng.submit(resident, 16)
        it = iter(h_res)
        next(it)                       # resident holds 5 pages
        h_big = eng.submit(big, 16)    # needs 8: blocked
        h_small = eng.submit(small, 4)  # 2 pages: overtakes via window
        out_small = h_small.result(timeout=300)
        assert h_big.status != COMPLETED  # small really finished first
        out_res = h_res.result(timeout=300)
        out_big = h_big.result(timeout=300)
    np.testing.assert_array_equal(out_small, _ref(params, small, 4))
    np.testing.assert_array_equal(out_res, _ref(params, resident, 16))
    np.testing.assert_array_equal(out_big, _ref(params, big, 16))

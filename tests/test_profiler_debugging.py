"""Profiler (RecordEvent spans, scheduler windows, step timing) and AMP
debugging (tensor checker over the eager nan hook).

Mirrors the reference's test/legacy_test/test_profiler.py and
test_nan_inf / amp debugging tests.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import profiler as prof
from paddle_tpu.amp import debugging as dbg


def test_make_scheduler_windows():
    fn = prof.make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [fn(i) for i in range(5)]
    S = prof.ProfilerState
    assert states[0] == S.CLOSED
    assert states[1] == S.READY
    assert states[2] == S.RECORD
    assert states[3] == S.RECORD_AND_RETURN
    assert states[4] == S.CLOSED  # repeat exhausted


def test_record_event_and_host_stats():
    prof.reset_host_statistics()
    for _ in range(3):
        with prof.RecordEvent("my_span"):
            x = pt.ones([64, 64])
            (x @ x).numpy()
    st = prof.host_statistics()
    assert st["my_span"]["calls"] == 3
    assert st["my_span"]["total_ms"] > 0


def test_profiler_timer_only_summary(capsys):
    p = prof.Profiler(timer_only=True)
    p.start()
    for _ in range(4):
        pt.ones([8]).numpy()
        p.step()
    p.stop()
    out = p.summary()
    assert "steps: 4" in out


def test_check_numerics_counts_and_abort():
    t = pt.to_tensor(np.array([1.0, np.nan, np.inf, 0.0], np.float32))
    nan, inf, zero = dbg.check_numerics(t, debug_mode=dbg.DebugMode.CHECK_ALL)
    assert int(nan) == 1 and int(inf) == 1 and int(zero) == 1
    with pytest.raises(FloatingPointError):
        dbg.check_numerics(t, op_type="mul", var_name="x")


def test_tensor_checker_catches_nan_in_eager_op():
    cfg = dbg.TensorCheckerConfig(enable=True)
    with dbg.debug_guard(cfg):
        a = pt.to_tensor(np.array([1.0, 0.0], np.float32))
        b = pt.to_tensor(np.array([0.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError):
            _ = a / b  # 1/0 = inf
    # disabled again outside the guard
    c = (a / b).numpy()
    assert np.isinf(c).any()

"""Launcher integration (distributed/launch — reference
python/paddle/distributed/launch/main.py).

Spawns REAL subprocesses: a 2-process CPU job that goes through
init_parallel_env() -> jax.distributed (gloo collectives) and runs a
cross-process allgather, plus failure-propagation and log-capture
checks.
"""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(args, script_body, tmp_path, name="worker.py",
                timeout=180):
    script = tmp_path / name
    script.write_text(textwrap.dedent(script_body))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # children must not grab the session's TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("COORDINATOR_ADDRESS", None)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         *args, str(script)],
        capture_output=True, text=True, env=env, timeout=timeout)


def test_two_process_collective(tmp_path):
    res = _run_launch(["--nproc", "2", "--log_dir", str(tmp_path / "lg")],
                      """
        import jax
        jax.config.update("jax_platforms", "cpu")
        from paddle_tpu.distributed.env import init_parallel_env, get_rank
        init_parallel_env()
        assert jax.process_count() == 2, jax.process_count()
        import jax.numpy as jnp
        from jax.experimental.multihost_utils import process_allgather
        g = process_allgather(jnp.ones((2,)) * (get_rank() + 1))
        assert g.shape == (2, 2), g.shape
        assert float(g.sum()) == 6.0, g
        print("RANK_OK", get_rank())
        """, tmp_path)
    assert res.returncode == 0, res.stderr
    logs = ""
    for i in range(2):
        logs += (tmp_path / "lg" / f"workerlog.{i}").read_text()
    assert "RANK_OK 0" in logs and "RANK_OK 1" in logs


def test_failure_propagates_and_kills_peers(tmp_path):
    res = _run_launch(["--nproc", "2"], """
        import os, sys, time
        if os.environ["PROCESS_ID"] == "1":
            sys.exit(3)           # rank 1 dies immediately
        time.sleep(600)           # rank 0 would hang forever
        """, tmp_path, timeout=120)
    assert res.returncode == 3  # child's code becomes the job's code


def test_env_wiring_single_proc(tmp_path):
    res = _run_launch(["--nproc", "1", "--env", "MY_FLAG=7"], """
        import os
        assert os.environ["PADDLE_TRAINER_ID"] == "0"
        assert os.environ["PADDLE_TRAINERS_NUM"] == "1"
        assert os.environ["NUM_PROCESSES"] == "1"
        assert os.environ["MY_FLAG"] == "7"
        print("ENV_OK")
        """, tmp_path)
    assert res.returncode == 0, res.stderr
    assert "ENV_OK" in res.stdout


def test_elastic_restart_retries_and_succeeds(tmp_path):
    """Elastic: worker fails on attempt 0, succeeds on attempt 1 — the
    launcher restarts the whole job (reference elastic manager loop)."""
    res = _run_launch(["--nproc", "1", "--max_restarts", "2"], """
        import os, sys
        attempt = int(os.environ["PADDLE_RESTART_ATTEMPT"])
        if attempt == 0:
            sys.exit(7)     # first attempt dies
        print("RECOVERED on attempt", attempt)
        """, tmp_path)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "RECOVERED on attempt 1" in res.stdout
    assert "restarting" in res.stderr


def test_elastic_exhausts_restarts(tmp_path):
    res = _run_launch(["--nproc", "1", "--max_restarts", "1"], """
        import sys
        sys.exit(9)
        """, tmp_path)
    assert res.returncode == 9


def test_multinode_requires_master(tmp_path):
    script = tmp_path / "noop.py"
    script.write_text("print('hi')")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "2", "--node_rank", "0", "--nproc", "1",
         str(script)],
        capture_output=True, text=True, env=env, timeout=60)
    assert res.returncode != 0
    assert "--master" in res.stderr


def test_elastic_restart_resumes_from_checkpoint(tmp_path):
    """The full elastic loop (VERDICT r3 #7): a 2-proc job trains and
    checkpoints every step; rank 0 is killed mid-run on attempt 0; the
    launcher restarts the job (--max_restarts 1) and the script resumes
    from the newest checkpoint via PADDLE_RESTART_ATTEMPT +
    load_latest_checkpoint — it must NOT restart from step 0."""
    ck = tmp_path / "ckpt"
    res = _run_launch(
        ["--nproc", "2", "--max_restarts", "1",
         "--env", f"CKPT_DIR={ck}", "--env", f"MARK_DIR={tmp_path}"],
        """
        import os
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import paddle_tpu as pt
        from paddle_tpu.distributed.env import init_parallel_env, get_rank
        from paddle_tpu.distributed.checkpoint import (
            restart_attempt, save_checkpoint, load_latest_checkpoint)

        init_parallel_env()
        rank = get_rank()
        attempt = restart_attempt()
        root = os.environ["CKPT_DIR"]

        state = {"w": pt.to_tensor(jnp.zeros((4,), jnp.float32)),
                 "step": pt.to_tensor(jnp.zeros((), jnp.int32))}
        last = load_latest_checkpoint(state, root)
        start = last + 1
        if attempt == 0:
            assert start == 0, start
        else:
            # the restart must CONTINUE, not retrain from scratch
            assert start >= 3, f"resumed at {start}"
            assert float(state["w"].numpy().sum()) > 0

        for step in range(start, 6):
            state["w"] = state["w"] + 1.0          # "training"
            state["step"] = pt.to_tensor(jnp.asarray(step, jnp.int32))
            save_checkpoint(state, root, step)
            if attempt == 0 and step == 3 and rank == 0:
                os._exit(13)                        # simulated crash

        if rank == 0:
            with open(os.path.join(os.environ["MARK_DIR"],
                                   "done.txt"), "w") as f:
                f.write(f"attempt={attempt} start={start} "
                        f"w={float(state['w'].numpy()[0])}")
        print("TRAINED", rank, "from", start)
        """, tmp_path, timeout=300)
    assert res.returncode == 0, (res.stdout, res.stderr)
    marker = (tmp_path / "done.txt").read_text()
    assert "attempt=1" in marker, marker
    # resumed at >= step 4 (step 3's checkpoint was committed pre-crash)
    assert any(f"start={s}" in marker for s in (4, 5)), marker
    # w counts one increment per step across BOTH attempts: exactly 6
    assert "w=6.0" in marker, marker


def test_multinode_elastic_restart_resumes(tmp_path):
    """VERDICT r4 #5: TWO launchers (2 'nodes' x 2 procs) agree on
    restarts through the TCPStore rendezvous-generation counter. A
    worker on node 1 dies on attempt 0; BOTH launchers tear down,
    rejoin, respawn generation 1 against a fresh coordinator, and the
    job resumes from the newest checkpoint and finishes rc=0."""
    import socket as _socket

    def _free_port():
        with _socket.socket() as s:
            s.bind(("", 0))
            return s.getsockname()[1]

    master = f"127.0.0.1:{_free_port()}"
    ck = tmp_path / "ckpt"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import paddle_tpu as pt
        from paddle_tpu.distributed.env import init_parallel_env, get_rank
        from paddle_tpu.distributed.checkpoint import (
            restart_attempt, save_checkpoint, load_latest_checkpoint)

        init_parallel_env()
        rank = get_rank()
        assert jax.process_count() == 4, jax.process_count()
        attempt = restart_attempt()
        root = os.environ["CKPT_DIR"]

        state = {"w": pt.to_tensor(jnp.zeros((4,), jnp.float32)),
                 "step": pt.to_tensor(jnp.zeros((), jnp.int32))}
        start = load_latest_checkpoint(state, root) + 1
        if attempt > 0:
            assert start >= 3, f"resumed at {start}"

        for step in range(start, 6):
            state["w"] = state["w"] + 1.0
            state["step"] = pt.to_tensor(jnp.asarray(step, jnp.int32))
            save_checkpoint(state, root, step)
            if attempt == 0 and step == 3 and rank == 2:
                os._exit(13)            # node 1's worker dies

        if rank == 0:
            with open(os.path.join(os.environ["MARK_DIR"],
                                   "done.txt"), "w") as f:
                f.write(f"attempt={attempt} start={start} "
                        f"w={float(state['w'].numpy()[0])}")
        print("TRAINED", rank, "from", start)
    """))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("COORDINATOR_ADDRESS", None)
    env["CKPT_DIR"] = str(ck)
    env["MARK_DIR"] = str(tmp_path)
    launchers = [
        subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--node_rank", str(node),
             "--master", master, "--nproc", "2", "--max_restarts", "1",
             str(script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for node in (0, 1)]
    outs = [p.communicate(timeout=560) for p in launchers]
    rcs = [p.returncode for p in launchers]
    assert rcs == [0, 0], (rcs, outs[0][1][-2000:], outs[1][1][-2000:])
    marker = (tmp_path / "done.txt").read_text()
    assert "attempt=1" in marker, marker
    assert any(f"start={s}" in marker for s in (4, 5)), marker
    assert "w=6.0" in marker, marker

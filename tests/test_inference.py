"""Inference predictor over jit.save'd StableHLO artifacts.

Mirrors the reference's inference API tests (test/cpp/inference/api,
python predictor tests) minus TRT.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu import inference as infer
from paddle_tpu.jit import InputSpec


@pytest.fixture
def saved_model(tmp_path):
    net = pt.models.LeNet()
    net.eval()
    path = str(tmp_path / "lenet")
    pt.jit.save(net, path, input_spec=[InputSpec([1, 1, 28, 28], "float32")])
    x = np.random.RandomState(0).randn(1, 1, 28, 28).astype(np.float32)
    ref = np.asarray(net(pt.to_tensor(x)).numpy())
    return path, x, ref


def test_predictor_run_matches_eager(saved_model):
    path, x, ref = saved_model
    cfg = infer.Config(path)
    pred = infer.create_predictor(cfg)
    outs = pred.run([x])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-5)


def test_predictor_named_handles(saved_model):
    path, x, ref = saved_model
    pred = infer.create_predictor(infer.Config(path))
    names = pred.get_input_names()
    assert names == ["input_0"]
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_predictor_rejects_trt(saved_model):
    path, _, _ = saved_model
    cfg = infer.Config(path)
    with pytest.raises(NotImplementedError):
        cfg.enable_tensorrt_engine()


# ---------------------------------------------------------------------------
# dynamic batching (inference/serving.py)
# ---------------------------------------------------------------------------

def test_dynamic_batcher_coalesces_and_matches_single():
    import threading
    import jax
    import jax.numpy as jnp
    from paddle_tpu.inference.serving import DynamicBatcher

    w = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    fn = jax.jit(lambda x: x @ jnp.asarray(w))

    with DynamicBatcher(fn, max_batch_size=8, max_delay_ms=30) as b:
        xs = [np.random.RandomState(i).randn(8).astype(np.float32)
              for i in range(12)]
        futs = []
        # submit concurrently so the worker can coalesce
        threads = [threading.Thread(target=lambda x=x: futs.append(
            (x, b.submit(x)))) for x in xs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for x, f in futs:
            np.testing.assert_allclose(np.asarray(f.result()), x @ w,
                                       rtol=1e-5)
        stats = dict(b.stats)
    assert stats["requests"] == 12
    assert stats["batches"] < 12, stats  # some coalescing happened


def test_dynamic_batcher_shape_isolation_and_padding():
    from paddle_tpu.inference.serving import DynamicBatcher
    calls = []

    def fn(x):
        calls.append(x.shape)
        return x * 2

    with DynamicBatcher(fn, max_batch_size=4, max_delay_ms=0) as b:
        r1 = b.infer(np.ones((3,), np.float32))
        r2 = b.infer(np.ones((5,), np.float32))
    np.testing.assert_array_equal(r1, np.full((3,), 2, np.float32))
    np.testing.assert_array_equal(r2, np.full((5,), 2, np.float32))
    # each ran in its own (bucketed) batch; batch dims are bucket sizes
    assert all(s[0] in (1, 2, 4) for s in calls), calls
    assert {s[1:] for s in calls} == {(3,), (5,)}


def test_dynamic_batcher_tuple_outputs_and_errors():
    from paddle_tpu.inference.serving import DynamicBatcher

    def fn(x):
        if np.isnan(x).any():
            raise ValueError("nan batch")
        return x + 1, x.sum(axis=tuple(range(1, x.ndim)))

    with DynamicBatcher(fn, max_batch_size=2, max_delay_ms=0) as b:
        row, s = b.infer(np.ones((2, 2), np.float32))
        np.testing.assert_array_equal(row, np.full((2, 2), 2, np.float32))
        assert float(s) == 4.0
        f = b.submit(np.full((2, 2), np.nan, np.float32))
        with pytest.raises(ValueError, match="nan batch"):
            f.result()


# ---------------------------------------------------------------------------
# optimization passes (inference/passes.py)
# ---------------------------------------------------------------------------

def test_fold_batch_norms_resnet_matches_and_shrinks():
    import paddle_tpu as pt
    from paddle_tpu.inference import fold_batch_norms
    from paddle_tpu.vision.models import resnet18

    m = resnet18(num_classes=7)
    m.eval()
    # give BN stats non-trivial values so the fold actually does math
    rng = np.random.RandomState(0)
    for _, sub in m.named_sublayers(include_self=True):
        if type(sub).__name__.startswith("BatchNorm"):
            sub._mean.data = jnp.asarray(rng.randn(sub.num_features)
                                         .astype(np.float32) * 0.1)
            sub._variance.data = jnp.asarray(
                1.0 + rng.rand(sub.num_features).astype(np.float32))
    x = pt.to_tensor(rng.randn(2, 3, 32, 32).astype(np.float32))
    before = m(x).numpy()
    n = fold_batch_norms(m, [(1, 3, 32, 32)])
    assert n == 20, n  # every BN in resnet18 folds (incl. downsample)
    after = m(x).numpy()
    np.testing.assert_allclose(after, before, rtol=2e-4, atol=2e-5)
    # the folded model has no BatchNorm layers left
    assert not any(type(s).__name__.startswith("BatchNorm")
                   for _, s in m.named_sublayers())
    # exported ONNX no longer contains BatchNormalization nodes
    from paddle_tpu.jit import InputSpec
    from test_onnx_export import _op_types
    import tempfile, os
    out = pt.onnx.export(m, os.path.join(tempfile.mkdtemp(), "folded"),
                         input_spec=[InputSpec([1, 3, 32, 32])])
    ops = _op_types(open(out, "rb").read())
    assert "BatchNormalization" not in ops
    assert ops.count("Conv") == 20


def test_fold_batch_norms_respects_dataflow_fanout():
    import paddle_tpu as pt
    from paddle_tpu.inference import fold_batch_norms

    class FanOut(pt.nn.Layer):
        """conv output feeds BOTH the bn and a residual add — folding
        the bn would corrupt the second consumer."""
        def __init__(self):
            super().__init__()
            self.conv = pt.nn.Conv2D(3, 3, 1)
            self.bn = pt.nn.BatchNorm2D(3)

        def forward(self, x):
            h = self.conv(x)
            return self.bn(h) + h

    m = FanOut()
    m.eval()
    x = pt.to_tensor(np.random.RandomState(1)
                     .randn(1, 3, 4, 4).astype(np.float32))
    before = m(x).numpy()
    n = fold_batch_norms(m, [(1, 3, 4, 4)])
    assert n == 0  # correctly refused
    np.testing.assert_allclose(m(x).numpy(), before)


def test_fold_batch_norms_requires_eval():
    import paddle_tpu as pt
    from paddle_tpu.inference import fold_batch_norms
    m = pt.nn.Sequential(pt.nn.Conv2D(3, 4, 1), pt.nn.BatchNorm2D(4))
    with pytest.raises(ValueError, match="eval"):
        fold_batch_norms(m, [(1, 3, 4, 4)])


def test_fold_batch_norms_refuses_returned_intermediate():
    import paddle_tpu as pt
    from paddle_tpu.inference import fold_batch_norms

    class MultiOut(pt.nn.Layer):
        """conv output is RETURNED as well as normalised — folding
        would corrupt the returned features."""
        def __init__(self):
            super().__init__()
            self.conv = pt.nn.Conv2D(3, 3, 1)
            self.bn = pt.nn.BatchNorm2D(3)

        def forward(self, x):
            h = self.conv(x)
            return self.bn(h), h

    m = MultiOut()
    m.eval()
    x = pt.to_tensor(np.random.RandomState(2)
                     .randn(1, 3, 4, 4).astype(np.float32))
    b0, b1 = (o.numpy() for o in m(x))
    assert fold_batch_norms(m, [(1, 3, 4, 4)]) == 0
    a0, a1 = (o.numpy() for o in m(x))
    np.testing.assert_allclose(a0, b0)
    np.testing.assert_allclose(a1, b1)


def test_fold_batch_norms_refuses_reused_layers():
    import paddle_tpu as pt
    from paddle_tpu.inference import fold_batch_norms

    class Reuse(pt.nn.Layer):
        """the same conv+bn pair runs twice — folding once per EVENT
        would square the scale; folding at all corrupts the second
        call site when only one is bn-followed."""
        def __init__(self):
            super().__init__()
            self.conv = pt.nn.Conv2D(3, 3, 1)
            self.bn = pt.nn.BatchNorm2D(3)

        def forward(self, x):
            y = self.bn(self.conv(x))
            return self.bn(self.conv(y))

    m = Reuse()
    m.eval()
    x = pt.to_tensor(np.random.RandomState(3)
                     .randn(1, 3, 4, 4).astype(np.float32))
    before = m(x).numpy()
    assert fold_batch_norms(m, [(1, 3, 4, 4)]) == 0
    np.testing.assert_allclose(m(x).numpy(), before)


def test_fold_batch_norms_refuses_dict_and_kwarg_consumers():
    import paddle_tpu as pt
    from paddle_tpu.inference import fold_batch_norms

    class DictOut(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.conv = pt.nn.Conv2D(3, 3, 1)
            self.bn = pt.nn.BatchNorm2D(3)

        def forward(self, x):
            h = self.conv(x)
            return {"bn": self.bn(h), "raw": h}

    m = DictOut()
    m.eval()
    x = pt.to_tensor(np.random.RandomState(4)
                     .randn(1, 3, 4, 4).astype(np.float32))
    raw_before = m(x)["raw"].numpy()
    assert fold_batch_norms(m, [(1, 3, 4, 4)]) == 0
    np.testing.assert_allclose(m(x)["raw"].numpy(), raw_before)

    class KwargSkip(pt.nn.Layer):
        class Head(pt.nn.Layer):
            def forward(self, x, skip=None):
                return x + skip

        def __init__(self):
            super().__init__()
            self.conv = pt.nn.Conv2D(3, 3, 1)
            self.bn = pt.nn.BatchNorm2D(3)
            self.head = self.Head()

        def forward(self, x):
            h = self.conv(x)
            return self.head(self.bn(h), skip=h)

    k = KwargSkip()
    k.eval()
    before = k(x).numpy()
    assert fold_batch_norms(k, [(1, 3, 4, 4)]) == 0
    np.testing.assert_allclose(k(x).numpy(), before)


def test_remove_dropouts_pass():
    """reference: delete_dropout_op_pass — dropouts leave the artifact."""
    from paddle_tpu.inference import remove_dropouts
    m = pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.Dropout(0.5),
                         pt.nn.ReLU(), pt.nn.Dropout2D(0.1))
    assert remove_dropouts(m) == 2
    assert isinstance(m[1], pt.nn.Identity) and isinstance(m[3],
                                                           pt.nn.Identity)
    x = pt.to_tensor(np.ones((2, 4), np.float32))
    assert m(x).shape == [2, 8]


def test_fuse_linear_chains_pass():
    """reference: fc_fuse family — adjacent affine ops collapse, with
    dataflow verification (a consumed-elsewhere intermediate blocks)."""
    from paddle_tpu.inference import fuse_linear_chains
    from paddle_tpu.jit import InputSpec
    pt.seed(0)
    m = pt.nn.Sequential(pt.nn.Linear(4, 16), pt.nn.Linear(16, 8),
                         pt.nn.Linear(8, 2))  # chain of 3 -> 1 linear
    x = pt.to_tensor(np.random.RandomState(0).randn(3, 4).astype(np.float32))
    want = np.asarray(m(x).data)
    assert fuse_linear_chains(m, [InputSpec([1, 4])]) == 2
    lins = [l for l in m if isinstance(l, pt.nn.Linear)]
    assert len(lins) == 1 and tuple(lins[0].weight.shape) == (4, 2)
    np.testing.assert_allclose(np.asarray(m(x).data), want, atol=1e-4)

    class Branchy(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = pt.nn.Linear(4, 4)
            self.b = pt.nn.Linear(4, 4)

        def forward(self, x):
            h = self.a(x)
            return self.b(h) + h        # h consumed twice: no fuse

    bm = Branchy()
    assert fuse_linear_chains(bm, [InputSpec([1, 4])]) == 0

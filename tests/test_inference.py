"""Inference predictor over jit.save'd StableHLO artifacts.

Mirrors the reference's inference API tests (test/cpp/inference/api,
python predictor tests) minus TRT.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import inference as infer
from paddle_tpu.jit import InputSpec


@pytest.fixture
def saved_model(tmp_path):
    net = pt.models.LeNet()
    net.eval()
    path = str(tmp_path / "lenet")
    pt.jit.save(net, path, input_spec=[InputSpec([1, 1, 28, 28], "float32")])
    x = np.random.RandomState(0).randn(1, 1, 28, 28).astype(np.float32)
    ref = np.asarray(net(pt.to_tensor(x)).numpy())
    return path, x, ref


def test_predictor_run_matches_eager(saved_model):
    path, x, ref = saved_model
    cfg = infer.Config(path)
    pred = infer.create_predictor(cfg)
    outs = pred.run([x])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-5)


def test_predictor_named_handles(saved_model):
    path, x, ref = saved_model
    pred = infer.create_predictor(infer.Config(path))
    names = pred.get_input_names()
    assert names == ["input_0"]
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_predictor_rejects_trt(saved_model):
    path, _, _ = saved_model
    cfg = infer.Config(path)
    with pytest.raises(NotImplementedError):
        cfg.enable_tensorrt_engine()

"""Inference predictor over jit.save'd StableHLO artifacts.

Mirrors the reference's inference API tests (test/cpp/inference/api,
python predictor tests) minus TRT.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import inference as infer
from paddle_tpu.jit import InputSpec


@pytest.fixture
def saved_model(tmp_path):
    net = pt.models.LeNet()
    net.eval()
    path = str(tmp_path / "lenet")
    pt.jit.save(net, path, input_spec=[InputSpec([1, 1, 28, 28], "float32")])
    x = np.random.RandomState(0).randn(1, 1, 28, 28).astype(np.float32)
    ref = np.asarray(net(pt.to_tensor(x)).numpy())
    return path, x, ref


def test_predictor_run_matches_eager(saved_model):
    path, x, ref = saved_model
    cfg = infer.Config(path)
    pred = infer.create_predictor(cfg)
    outs = pred.run([x])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-5)


def test_predictor_named_handles(saved_model):
    path, x, ref = saved_model
    pred = infer.create_predictor(infer.Config(path))
    names = pred.get_input_names()
    assert names == ["input_0"]
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_predictor_rejects_trt(saved_model):
    path, _, _ = saved_model
    cfg = infer.Config(path)
    with pytest.raises(NotImplementedError):
        cfg.enable_tensorrt_engine()


# ---------------------------------------------------------------------------
# dynamic batching (inference/serving.py)
# ---------------------------------------------------------------------------

def test_dynamic_batcher_coalesces_and_matches_single():
    import threading
    import jax
    import jax.numpy as jnp
    from paddle_tpu.inference.serving import DynamicBatcher

    w = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    fn = jax.jit(lambda x: x @ jnp.asarray(w))

    with DynamicBatcher(fn, max_batch_size=8, max_delay_ms=30) as b:
        xs = [np.random.RandomState(i).randn(8).astype(np.float32)
              for i in range(12)]
        futs = []
        # submit concurrently so the worker can coalesce
        threads = [threading.Thread(target=lambda x=x: futs.append(
            (x, b.submit(x)))) for x in xs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for x, f in futs:
            np.testing.assert_allclose(np.asarray(f.result()), x @ w,
                                       rtol=1e-5)
        stats = dict(b.stats)
    assert stats["requests"] == 12
    assert stats["batches"] < 12, stats  # some coalescing happened


def test_dynamic_batcher_shape_isolation_and_padding():
    from paddle_tpu.inference.serving import DynamicBatcher
    calls = []

    def fn(x):
        calls.append(x.shape)
        return x * 2

    with DynamicBatcher(fn, max_batch_size=4, max_delay_ms=0) as b:
        r1 = b.infer(np.ones((3,), np.float32))
        r2 = b.infer(np.ones((5,), np.float32))
    np.testing.assert_array_equal(r1, np.full((3,), 2, np.float32))
    np.testing.assert_array_equal(r2, np.full((5,), 2, np.float32))
    # each ran in its own (bucketed) batch; batch dims are bucket sizes
    assert all(s[0] in (1, 2, 4) for s in calls), calls
    assert {s[1:] for s in calls} == {(3,), (5,)}


def test_dynamic_batcher_tuple_outputs_and_errors():
    from paddle_tpu.inference.serving import DynamicBatcher

    def fn(x):
        if np.isnan(x).any():
            raise ValueError("nan batch")
        return x + 1, x.sum(axis=tuple(range(1, x.ndim)))

    with DynamicBatcher(fn, max_batch_size=2, max_delay_ms=0) as b:
        row, s = b.infer(np.ones((2, 2), np.float32))
        np.testing.assert_array_equal(row, np.full((2, 2), 2, np.float32))
        assert float(s) == 4.0
        f = b.submit(np.full((2, 2), np.nan, np.float32))
        with pytest.raises(ValueError, match="nan batch"):
            f.result()

"""Rank-asymmetric 1F1B / ZB-H1 pipeline schedules
(parallel/pipeline_async.py).

Reference capabilities covered: pipeline_parallel.py:565 per-rank 1F1B
(warmup/steady/drain differ per rank — the fill/drain bubble is
1-(S-1)/(VM+S-1), not the lockstep (2S-1)/(M+2S-1)) and
pipeline_zero_bubble.py ZB-H1 (backward split into input-grad B and
deferred weight-grad W filling bubble slots).

Three pin families:
  * the schedule BUILDER: dependency-validated grids, closed-form
    spans (the analytic model measured efficiency is asserted
    against), O(S·V) M-independent saved-ring depths;
  * NUMERICS: loss+grads match the lockstep schedule (and plain
    single-stage autodiff) across a (pp, M, V) grid including M not
    divisible by pp, with f32 grad accumulation pinned structurally
    under bf16 activations;
  * MEASURED efficiency from the real traced train step >= the
    reference 1F1B numbers (0.889 at pp=2/M=8, 0.970 at M=32), and
    the dropped-W-deferral mutation trips the trip-count analysis.
"""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.parallel import init_hybrid_mesh
from paddle_tpu.parallel.pipeline_1f1b import (pipeline_train_1f1b,
                                               schedule_efficiency,
                                               schedule_ticks)
from paddle_tpu.parallel.pipeline_async import (IDLE, OP_W,
                                                build_schedule,
                                                pipeline_train_async)


def _cfg(pp, schedule="1f1b", vpp=1, M=8, layers=4, dtype=jnp.float32):
    return L.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=layers, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=32,
        dtype=dtype, remat=False, use_flash_attention=False,
        pp_stages=pp, num_microbatches=M, pp_schedule=schedule,
        vpp_chunks=vpp)


def _tree_close(a, b, rtol, atol):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol)


# ---------------------------------------------------------------------------
# schedule builder: validity, closed forms, memory bounds
# ---------------------------------------------------------------------------

def test_builder_v1_grid_matches_closed_forms():
    """The analytic model (schedule_ticks / schedule_efficiency) and
    the dependency-validated builder agree everywhere; 1f1b lands the
    reference per-rank bubble exactly, zb beats it."""
    for S in (2, 3, 4, 8):
        for M in (1, 2, 3, 5, 8, 16):
            sc = build_schedule(S, M, 1, "1f1b")
            assert sc.ticks == 2 * (M + S - 1)
            assert sc.ticks == schedule_ticks(S, M, 1, schedule="1f1b")
            assert sc.efficiency == pytest.approx(M / (M + S - 1))
            assert sc.efficiency == pytest.approx(
                schedule_efficiency(S, M, 1, schedule="1f1b"))
            zb = build_schedule(S, M, 1, "zb")
            assert zb.ticks == schedule_ticks(S, M, 1, schedule="zb")
            assert zb.efficiency == pytest.approx(
                schedule_efficiency(S, M, 1, schedule="zb"))
            if M >= S:   # steady-state regime: closed form 3M + S - 1
                assert zb.ticks == 3 * M + S - 1
                assert zb.efficiency > sc.efficiency
            # zb never falls below the 1F1B reference bound
            assert zb.efficiency >= M / (M + S - 1) - 1e-12


def test_builder_interleaved_matches_reference_bound():
    """V>1 (the reference's VPP round-robin order) lands the
    interleaved-1F1B analytic efficiency 1-(S-1)/(VM+S-1) exactly."""
    for S in (2, 4, 8):
        for V in (2, 4):
            for M in (S, 2 * S, 4 * S):
                sc = build_schedule(S, M, V, "1f1b")
                assert sc.ticks == 2 * (V * M + S - 1)
                assert sc.efficiency == pytest.approx(
                    V * M / (V * M + S - 1))
                assert sc.efficiency == pytest.approx(
                    schedule_efficiency(S, M, V, schedule="1f1b"))
                # interleaving strictly shrinks the bubble vs V=1
                assert sc.efficiency > M / (M + S - 1)


def test_builder_saved_rings_are_o_sv_and_m_independent():
    """The 1F1B property, proven per schedule by the interval
    allocator: saved-activation/cotangent ring depths are O(S·V) and
    DO NOT grow with M (GPipe's O(M) is exactly what this schedule
    exists to avoid; zb's W backlog is capped at S so deferral does
    not reintroduce it). The r19 RESIDUAL ring — what lets W skip the
    stage-forward replay — is re-pinned to the same discipline: depth
    exactly M-independent and bounded by the W backlog O(S)."""
    for S in (2, 4, 8):
        for V, var in ((1, "1f1b"), (1, "zb"), (2, "1f1b")):
            a = build_schedule(S, 2 * S, V, var)
            b = build_schedule(S, 8 * S, V, var)
            assert (a.depth_x, a.depth_c) == (b.depth_x, b.depth_c), \
                (S, V, var)
            assert b.depth_x <= 3 * S * V
            assert b.depth_c <= 2 * S * V
            assert a.depth_r == b.depth_r, (S, V, var)
            if var == "zb":
                assert 1 <= b.depth_r <= S + 1, (S, b.depth_r)
            else:
                assert b.depth_r == 0


def test_builder_rejections():
    with pytest.raises(ValueError, match="num_stages >= 2"):
        build_schedule(1, 4, 1, "1f1b")
    with pytest.raises(ValueError, match="variant"):
        build_schedule(2, 4, 1, "zigzag")
    with pytest.raises(ValueError, match="ZB-V"):
        build_schedule(2, 4, 2, "zb")
    with pytest.raises(ValueError, match="divisible"):
        build_schedule(2, 3, 2, "1f1b")
    with pytest.raises(ValueError, match="microbatches"):
        build_schedule(2, 0, 1, "1f1b")


def test_schedule_efficiency_lockstep_unchanged():
    """Back-compat: the lockstep model is untouched (same numbers the
    r5 ceiling table and existing tests pin)."""
    assert schedule_efficiency(2, 8) == pytest.approx(8 / 11)
    assert schedule_efficiency(4, 32) == pytest.approx(32 / 39)
    assert schedule_ticks(2, 8) == 11
    with pytest.raises(ValueError, match="schedule"):
        schedule_efficiency(2, 8, schedule="wat")


# ---------------------------------------------------------------------------
# numerics: match the lockstep schedule and single-stage autodiff
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pp,vpp,M,sched", [
    (2, 1, 5, "1f1b_async"),      # M not divisible by pp
    (2, 1, 5, "zb"),
    (4, 1, 8, "zb"),
    (2, 2, 4, "1f1b_async"),      # interleaved VPP
])
def test_async_matches_lockstep(pp, vpp, M, sched):
    """Loss and every grad must match the lockstep schedule — the
    existing 1F1B exactness pins transfer to the new schedules."""
    hm = init_hybrid_mesh(dp=1, pp=pp, tp=1, set_global=False)
    cfg_a, cfg_l = _cfg(pp, sched, vpp, M), _cfg(pp, "1f1b", vpp, M)
    params = L.init_params(cfg_a, jax.random.PRNGKey(0))
    with hm.mesh:
        batch = L.make_batch(cfg_a, batch_size=M, seq_len=16,
                             mesh=hm.mesh)
        loss_a, grads_a = jax.jit(
            lambda p, b: L.grads_1f1b(p, b, cfg_a, hm.mesh))(params,
                                                             batch)
        loss_l, grads_l = jax.jit(
            lambda p, b: L.grads_1f1b(p, b, cfg_l, hm.mesh))(params,
                                                             batch)
    np.testing.assert_allclose(loss_a, loss_l, rtol=1e-6, atol=1e-7)
    _tree_close(grads_a, grads_l, rtol=2e-5, atol=1e-6)


def test_async_matches_single_stage_autodiff():
    """Absolute correctness: the zb schedule against plain pp=1
    value_and_grad (embedding + head bracket included)."""
    pp, M = 2, 4
    hm = init_hybrid_mesh(dp=1, pp=pp, tp=1, set_global=False)
    cfg = _cfg(pp, "zb", 1, M)
    ref_cfg = _cfg(1, "gpipe", 1, 1)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    with hm.mesh:
        batch = L.make_batch(cfg, batch_size=M, seq_len=32,
                             mesh=hm.mesh)
        loss_p, grads_p = jax.jit(
            lambda p, b: L.grads_1f1b(p, b, cfg, hm.mesh))(params,
                                                           batch)
    hm1 = init_hybrid_mesh(dp=1, pp=1, tp=1, set_global=False)
    with hm1.mesh:
        loss_r, grads_r = jax.jit(
            lambda p, b: jax.value_and_grad(L.loss_fn)(
                p, b, ref_cfg, hm1.mesh))(params, batch)
    np.testing.assert_allclose(loss_p, loss_r, rtol=1e-5, atol=1e-6)
    _tree_close(grads_p, grads_r, rtol=2e-4, atol=1e-5)


def test_async_train_step_losses_equal_lockstep_steps():
    """make_train_step integration: three optimizer steps under the zb
    schedule produce the SAME loss trajectory as lockstep (same
    grads -> same adamw updates)."""
    losses = {}
    for sched in ("1f1b", "zb"):
        cfg = L.LlamaConfig.tiny(dtype=jnp.float32, remat=False,
                                 use_flash_attention=False, pp_stages=2,
                                 pp_schedule=sched, num_microbatches=4)
        hm = init_hybrid_mesh(dp=1, pp=2, tp=1, set_global=False)
        with hm.mesh:
            step, init = L.make_train_step(cfg, hm.mesh)
            state = init(jax.random.PRNGKey(0))
            batch = L.make_batch(cfg, batch_size=4, seq_len=16,
                                 mesh=hm.mesh)
            out = []
            for _ in range(3):
                state, loss = step(state, batch)
                out.append(float(loss))
        losses[sched] = out
    np.testing.assert_allclose(losses["zb"], losses["1f1b"], rtol=1e-5)
    assert losses["zb"][-1] < losses["zb"][0]


# ---------------------------------------------------------------------------
# composed dp/tp numerics (r19): the 4D north star rides the best
# schedules — every composed geometry must match the GSPMD lockstep
# schedule (and, transitively, plain autodiff) at the same tolerances
# as the dp=tp=1 grid above
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dp,tp,pp,sched,M,B,vpp", [
    (2, 1, 2, "zb", 4, 8, 1),           # dp composed into zb
    (2, 1, 2, "1f1b_async", 4, 8, 1),   # dp composed into 1f1b
    (1, 2, 2, "zb", 4, 4, 1),           # tp composed (manual colls)
    (1, 2, 2, "1f1b_async", 4, 4, 1),
    (2, 2, 2, "zb", 4, 8, 1),           # full 3D mesh
    (2, 1, 2, "zb", 5, 10, 1),          # dp with M not divisible by pp
    (1, 2, 2, "1f1b_async", 4, 4, 2),   # interleaved VPP under tp
])
def test_composed_matches_lockstep(dp, tp, pp, sched, M, B, vpp):
    """dp/tp composed into the async shard_map: loss and every grad
    match the lockstep (GSPMD) schedule at the dp=tp=1 grid's
    tolerances — the r19 acceptance pin."""
    hm = init_hybrid_mesh(dp=dp, pp=pp, tp=tp, set_global=False)
    cfg_a = _cfg(pp, sched, vpp, M)
    cfg_l = _cfg(pp, "1f1b", vpp, M)
    params = L.init_params(cfg_a, jax.random.PRNGKey(0))
    with hm.mesh:
        batch = L.make_batch(cfg_a, batch_size=B, seq_len=16,
                             mesh=hm.mesh)
        loss_a, grads_a = jax.jit(
            lambda p, b: L.grads_1f1b(p, b, cfg_a, hm.mesh))(params,
                                                             batch)
        loss_l, grads_l = jax.jit(
            lambda p, b: L.grads_1f1b(p, b, cfg_l, hm.mesh))(params,
                                                             batch)
    np.testing.assert_allclose(loss_a, loss_l, rtol=1e-6, atol=1e-7)
    _tree_close(grads_a, grads_l, rtol=2e-5, atol=1e-6)


def test_composed_3d_matches_single_stage_autodiff():
    """Absolute correctness of the full 3D composition: dp2 x tp2 x
    pp2 zb against plain pp=1 value_and_grad on a 1-device mesh
    (embedding + vocab-parallel head bracket included)."""
    dp, tp, pp, M = 2, 2, 2, 4
    hm = init_hybrid_mesh(dp=dp, pp=pp, tp=tp, set_global=False)
    cfg = _cfg(pp, "zb", 1, M)
    ref_cfg = _cfg(1, "gpipe", 1, 1)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    with hm.mesh:
        batch = L.make_batch(cfg, batch_size=2 * M, seq_len=32,
                             mesh=hm.mesh)
        loss_p, grads_p = jax.jit(
            lambda p, b: L.grads_1f1b(p, b, cfg, hm.mesh))(params,
                                                           batch)
    hm1 = init_hybrid_mesh(dp=1, pp=1, tp=1, set_global=False)
    with hm1.mesh:
        loss_r, grads_r = jax.jit(
            lambda p, b: jax.value_and_grad(L.loss_fn)(
                p, b, ref_cfg, hm1.mesh))(params, batch)
    np.testing.assert_allclose(loss_p, loss_r, rtol=1e-5, atol=1e-6)
    _tree_close(grads_p, grads_r, rtol=2e-4, atol=1e-5)


def test_async_rejects_cp_mesh_and_unsharded_dp_inputs():
    """The composition covers dp/tp/pp only — a live cp axis still
    rejects loudly; and an executor call with dp > 1 but replicated
    inputs (no x_spec) must refuse rather than over-count grads by
    the dp degree."""
    from paddle_tpu.parallel.pipeline_async import pipeline_train_async
    hm = init_hybrid_mesh(dp=1, pp=2, tp=1, cp=2, set_global=False)
    stage = lambda p, x: x @ p["w"]
    head = lambda hp, y, lbl: jnp.mean((y @ hp["wo"] - lbl) ** 2)
    d = 4
    sp = {"w": jnp.zeros((2, d, d))}
    hp = {"wo": jnp.zeros((d, d))}
    x = jnp.zeros((2, 2, d))
    with hm.mesh:
        with pytest.raises(NotImplementedError, match="cp"):
            pipeline_train_async(stage, head, sp, hp, x, x,
                                 num_stages=2, mesh=hm.mesh)
    hm2 = init_hybrid_mesh(dp=2, pp=2, tp=1, set_global=False)
    with hm2.mesh:
        with pytest.raises(ValueError, match="x_spec"):
            pipeline_train_async(stage, head, sp, hp, x, x,
                                 num_stages=2, mesh=hm2.mesh)


def test_bad_async_schedule_name_rejected():
    hm = init_hybrid_mesh(dp=1, pp=2, tp=1, set_global=False)
    cfg = _cfg(2, "zb_async")
    with pytest.raises(ValueError, match="pp_schedule"):
        L.make_train_step(cfg, hm.mesh)


# ---------------------------------------------------------------------------
# fp32 grad accumulation pin under bf16 activations
# ---------------------------------------------------------------------------

def test_fp32_grad_accum_pinned_under_bf16():
    """Structural dtype pin: in the traced schedule scan the grad
    accumulators ride the carry in f32 while the saved
    activation/cotangent rings stay bf16; returned grads are cast back
    to the bf16 param dtype."""
    from paddle_tpu.core.graph_trace import iter_jaxpr_eqns
    pp, M = 2, 4
    cfg = _cfg(pp, "zb", 1, M, dtype=jnp.bfloat16)
    hm = init_hybrid_mesh(dp=1, pp=pp, tp=1, set_global=False)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    with hm.mesh:
        batch = L.make_batch(cfg, batch_size=M, seq_len=16,
                             mesh=hm.mesh)
        jaxpr = jax.make_jaxpr(
            lambda p, b: L.grads_1f1b(p, b, cfg, hm.mesh))(params,
                                                           batch)
        grads = jax.jit(
            lambda p, b: L.grads_1f1b(p, b, cfg, hm.mesh))(params,
                                                           batch)[1]
    T = schedule_ticks(pp, M, 1, schedule="zb")
    sched_scans = [
        eqn for _path, eqn in iter_jaxpr_eqns(jaxpr)
        if eqn.primitive.name == "scan" and eqn.params["length"] == T]
    assert sched_scans, "schedule scan not found in the traced program"
    eqn = sched_scans[0]
    carry = eqn.invars[eqn.params["num_consts"]:
                       eqn.params["num_consts"] + eqn.params["num_carry"]]
    f32_acc = [v for v in carry
               if v.aval.dtype == jnp.float32 and v.aval.ndim >= 2]
    bf16_rings = [v for v in carry
                  if v.aval.dtype == jnp.bfloat16 and v.aval.ndim >= 3]
    assert len(f32_acc) >= 5, [v.aval for v in carry]   # gacc + ghead
    assert bf16_rings, [v.aval for v in carry]          # sx/sc rings
    for leaf, ref in zip(jax.tree_util.tree_leaves(grads),
                         jax.tree_util.tree_leaves(params)):
        assert leaf.dtype == ref.dtype


def test_fp32_grad_accum_pin_survives_dp_psum_in_carry():
    """The composed-dp program keeps the same structural discipline:
    f32 grad accumulators ride the schedule-scan carry, the dp
    reduction is ONE psum per accumulator leaf on the f32 values
    AFTER the scan (not per microbatch, not on the cast-back grads),
    and returned grads land back in the param dtype."""
    from paddle_tpu.core.graph_trace import iter_jaxpr_eqns
    pp, M, dp = 2, 4, 2
    cfg = _cfg(pp, "zb", 1, M, dtype=jnp.bfloat16)
    hm = init_hybrid_mesh(dp=dp, pp=pp, tp=1, set_global=False)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    with hm.mesh:
        batch = L.make_batch(cfg, batch_size=M * dp, seq_len=16,
                             mesh=hm.mesh)
        jaxpr = jax.make_jaxpr(
            lambda p, b: L.grads_1f1b(p, b, cfg, hm.mesh))(params,
                                                           batch)
        grads = jax.jit(
            lambda p, b: L.grads_1f1b(p, b, cfg, hm.mesh))(params,
                                                           batch)[1]
    T = schedule_ticks(pp, M, 1, schedule="zb")
    sched_scans = [
        eqn for _path, eqn in iter_jaxpr_eqns(jaxpr)
        if eqn.primitive.name == "scan" and eqn.params["length"] == T]
    assert sched_scans, "schedule scan not found in the traced program"
    eqn = sched_scans[0]
    carry = eqn.invars[eqn.params["num_consts"]:
                       eqn.params["num_consts"] + eqn.params["num_carry"]]
    f32_acc = [v for v in carry
               if v.aval.dtype == jnp.float32 and v.aval.ndim >= 2]
    assert len(f32_acc) >= 5, [v.aval for v in carry]
    # the folded dp psum: f32 multi-dim psums OUTSIDE the scan, one
    # per stage accumulator leaf (7 layer-param leaves) — none inside
    n_dp_psums = 0
    for path, e in iter_jaxpr_eqns(jaxpr):
        if e.primitive.name != "psum":
            continue
        axes = e.params.get("axes", ())
        in_scan = any(p[0] == "scan" for p in path)
        if "dp" in axes and not in_scan:
            assert all(o.aval.dtype == jnp.float32
                       for o in e.outvars), e
            n_dp_psums += sum(1 for o in e.outvars
                              if o.aval.ndim >= 2)
        assert not (("dp" in axes) and in_scan), \
            "dp grad psum leaked inside the schedule scan"
    assert n_dp_psums >= 7, n_dp_psums
    for leaf, ref in zip(jax.tree_util.tree_leaves(grads),
                         jax.tree_util.tree_leaves(params)):
        assert leaf.dtype == ref.dtype


# ---------------------------------------------------------------------------
# measured efficiency from the real traced program
# ---------------------------------------------------------------------------

def _measured(pp, M, sched_cfg, model):
    from paddle_tpu.analysis.collectives import scan_trip_counts
    cfg = L.LlamaConfig.tiny(dtype=jnp.float32, remat=False,
                             use_flash_attention=False, pp_stages=pp,
                             pp_schedule=sched_cfg, num_microbatches=M)
    hm = init_hybrid_mesh(dp=1, pp=pp, tp=1, set_global=False)
    with hm.mesh:
        step, init = L.make_train_step(cfg, hm.mesh)
        state = jax.eval_shape(lambda: init(jax.random.PRNGKey(0)))
        batch = {
            "tokens": jax.ShapeDtypeStruct((M, 8), jnp.int32),
            "labels": jax.ShapeDtypeStruct((M, 8), jnp.int32)}
        jaxpr = jax.make_jaxpr(lambda s, b: step(s, b))(state, batch)
    trips = scan_trip_counts(jaxpr)
    T = schedule_ticks(pp, M, 1, schedule=model)
    assert T in trips, (T, sorted(set(trips)))
    useful = {"1f1b": 2 * M, "zb": 3 * M}[model]
    return useful / T


def test_measured_efficiency_meets_reference_1f1b():
    """THE acceptance pin: measured (traced tick counts of the real
    train step) schedule efficiency >= the reference 1F1B numbers —
    0.889 at pp=2/M=8 and 0.970 at M=32 — and == the analytic model."""
    for M, floor in ((8, 0.889), (32, 0.970)):
        eff = _measured(2, M, "1f1b_async", "1f1b")
        assert eff == pytest.approx(M / (M + 1))       # = 0.8889/0.9697
        assert eff >= floor - 5e-4
        assert eff == pytest.approx(
            schedule_efficiency(2, M, schedule="1f1b"))


def test_measured_efficiency_zb_beats_1f1b():
    eff_zb = _measured(2, 8, "zb", "zb")
    assert eff_zb == pytest.approx(24 / 25)            # 0.96
    assert eff_zb > _measured(2, 8, "1f1b_async", "1f1b")
    assert eff_zb == pytest.approx(
        schedule_efficiency(2, 8, schedule="zb"))


# ---------------------------------------------------------------------------
# dropped W-deferral mutation: statically caught, concretely wrong
# ---------------------------------------------------------------------------

def test_dropped_w_deferral_trips_consistency_and_corrupts_grads():
    """Strip the deferred-W drain tail from a zb schedule: the traced
    scan loses ticks, so the collective/trip-count rule fires (the
    designated safety net), and the missing weight-grad contributions
    corrupt the stage grads concretely."""
    from paddle_tpu.analysis import (CollectiveConsistencyPass,
                                     GraphTarget, Severity)
    S, M = 2, 3
    sched = build_schedule(S, M, 1, "zb")
    # trailing ticks whose ops are only W/idle = the deferral tail
    tail = 0
    for t in range(sched.ticks - 1, -1, -1):
        if all(k in (IDLE, OP_W) for k in sched.kind[t]):
            tail += 1
        else:
            break
    assert tail >= 1
    cut = sched.ticks - tail
    mutated = dataclasses.replace(
        sched, ticks=cut,
        **{f: getattr(sched, f)[:cut]
           for f in ("kind", "chunk", "mb", "slot_x", "slot_c",
                     "slot_r", "inject", "emit", "store_up",
                     "store_dn")})

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    def head_fn(hp, y, lbl):
        return jnp.mean((y @ hp["wo"] - lbl) ** 2)

    d = 8
    sp = {"w": jax.random.normal(jax.random.PRNGKey(0), (S, d, d)) * .3}
    hp = {"wo": jax.random.normal(jax.random.PRNGKey(1), (d, d)) * .3}
    x = jax.random.normal(jax.random.PRNGKey(2), (M, 4, d))
    lbl = jax.random.normal(jax.random.PRNGKey(3), (M, 4, d))
    hm = init_hybrid_mesh(dp=1, pp=S, tp=1, set_global=False)

    def run(schedule):
        with hm.mesh:
            return pipeline_train_async(
                stage_fn, head_fn, sp, hp, x, lbl, num_stages=S,
                variant="zb", mesh=hm.mesh, _schedule=schedule)

    with hm.mesh:
        jaxpr = jax.make_jaxpr(lambda: run(mutated))()
    target = GraphTarget(
        name="toy.zb_mutated", jaxpr=jaxpr,
        meta={"expected_scan_trips": sched.ticks})
    errs = [f for f in CollectiveConsistencyPass().run(target)
            if f.severity == Severity.ERROR]
    assert errs and "trip count" in errs[0].message
    # and the grads really are wrong: W carried those contributions
    good = jax.jit(lambda: run(sched))()
    bad = jax.jit(lambda: run(mutated))()
    np.testing.assert_allclose(good[0], bad[0], rtol=1e-6)  # loss ok
    assert not np.allclose(np.asarray(good[1]["w"]),
                           np.asarray(bad[1]["w"]), rtol=1e-3)


# ---------------------------------------------------------------------------
# composed collectives are PRICED from the trace, and a dropped dp
# psum is statically caught + concretely wrong (r19 satellite)
# ---------------------------------------------------------------------------

def test_composed_collectives_priced_from_trace():
    """collective_cost_bytes must see the composed program's manual
    in-body collectives — the folded dp grad psum and the per-block tp
    all-reduces — not just the ppermute pairs: the composed traces
    carry strictly more explicit wire bytes than the dp=tp=1 trace of
    the same schedule, which is what lets the planner drop its
    analytic dp/tp terms for async points."""
    from paddle_tpu.analysis.collectives import collective_cost_bytes

    def traced(dp, tp, B):
        cfg = _cfg(2, "zb", 1, 4)
        hm = init_hybrid_mesh(dp=dp, pp=2, tp=tp, set_global=False)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        with hm.mesh:
            batch = L.make_batch(cfg, batch_size=B, seq_len=16,
                                 mesh=hm.mesh)
            return jax.make_jaxpr(
                lambda p, b: L.grads_1f1b(p, b, cfg, hm.mesh))(params,
                                                               batch)

    base = collective_cost_bytes(traced(1, 1, 4))
    with_dp = collective_cost_bytes(traced(2, 1, 8))
    with_tp = collective_cost_bytes(traced(1, 2, 4))
    assert base > 0                       # the ppermute pairs
    assert with_dp > base                 # + folded dp grad psum
    assert with_tp > base                 # + in-body tp all-reduces


def test_dropped_dp_psum_trips_consistency_and_corrupts_grads():
    """Seeded mutation: build the SAME composed-dp program with the
    folded dp gradient psum dropped — the collective signature
    diverges (collective-consistency stage-group compare fires, the
    designated safety net) and the stage grads are concretely wrong
    (each dp rank's partial accumulator escapes unreduced)."""
    from paddle_tpu.analysis import (CollectiveConsistencyPass,
                                     GraphTarget, Severity)
    from paddle_tpu.parallel.pipeline_async import pipeline_train_async
    S, M, dp = 2, 3, 2
    d = 8

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    def head_fn(hp, y, lbl):
        return jnp.mean((y @ hp["wo"] - lbl) ** 2)

    sp = {"w": jax.random.normal(jax.random.PRNGKey(0), (S, d, d)) * .3}
    hp = {"wo": jax.random.normal(jax.random.PRNGKey(1), (d, d)) * .3}
    x = jax.random.normal(jax.random.PRNGKey(2), (M, 2 * dp, d))
    lbl = jax.random.normal(jax.random.PRNGKey(3), (M, 2 * dp, d))
    hm = init_hybrid_mesh(dp=dp, pp=S, tp=1, set_global=False)

    def run(drop):
        with hm.mesh:
            return pipeline_train_async(
                stage_fn, head_fn, sp, hp, x, lbl, num_stages=S,
                variant="zb", mesh=hm.mesh,
                x_spec=jax.sharding.PartitionSpec(None, "dp", None),
                aux_specs=jax.sharding.PartitionSpec(None, "dp", None),
                _drop_dp_grad_psum=drop)

    with hm.mesh:
        targets = [
            GraphTarget(
                name=f"toy.zb_dp[{'dropped' if drop else 'ok'}]",
                jaxpr=jax.make_jaxpr(lambda drop=drop: run(drop))(),
                meta={"stage_group": "toy.zb_dp_psum",
                      "stage_count": 2,
                      "signature_include_loops": True})
            for drop in (False, True)]
    cc = CollectiveConsistencyPass()
    errs = [f for t in targets for f in cc.run(t)
            if f.severity == Severity.ERROR]
    assert errs and "collective" in errs[0].message
    # and the grads really are wrong without the fold-in psum
    good = jax.jit(lambda: run(False))()
    bad = jax.jit(lambda: run(True))()
    assert not np.allclose(np.asarray(good[1]["w"]),
                           np.asarray(bad[1]["w"]), rtol=1e-3)

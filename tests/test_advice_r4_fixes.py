"""Regression tests for the round-4 advisor findings (ADVICE.md r4):

1. Engine jitted step leaking tracers into model buffers — covered by
   test_auto_parallel_engine.py::test_engine_jitted_bn_buffers_*.
2. Segment _exec_cache unbounded + keyed by id(fn): fresh closures per
   call (static/nn.py cond/case/while) re-jitted every flush and pinned
   dead closures forever (jit/segments.py).
3. save_checkpoint keep-pruning: keep=0 pruned nothing, every process
   pruned concurrently, async saves left an extra stale checkpoint
   (distributed/checkpoint.py).
"""
import os

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit import segments as seg
from paddle_tpu.distributed import checkpoint as ckpt


def _record_one(rec, fn, x):
    with rec.active():
        out = rec.record("mul_test", fn, (Tensor(x),), {}, need_grad=False)
        rec.flush()
    return out


def test_segment_cache_hits_across_fresh_closures():
    """Same code + same closure values must share one executable even
    when the fn OBJECT is fresh each call (id(fn) keying never hit)."""
    rec = seg.SegmentRecorder()
    x = jnp.ones((4,))

    def make(scale):
        return lambda a: a * scale

    for _ in range(3):
        _record_one(rec, make(2.0), x)  # fresh closure, equal contents
    assert rec.stats["cache_hits"] == 2, rec.stats
    assert len(rec._exec_cache) == 1

    # different closure VALUES must not share (2.0 vs 3.0)
    _record_one(rec, make(3.0), x)
    assert len(rec._exec_cache) == 2


def test_segment_cache_bounded_lru():
    rec = seg.SegmentRecorder()
    old = seg._EXEC_CACHE_MAX
    seg._EXEC_CACHE_MAX = 4
    try:
        for n in range(1, 11):  # 10 distinct shapes -> 10 signatures
            _record_one(rec, lambda a: a * 2.0, jnp.ones((n,)))
        assert len(rec._exec_cache) <= 4
    finally:
        seg._EXEC_CACHE_MAX = old


def test_checkpoint_keep_zero_rejected():
    with pytest.raises(ValueError, match="keep"):
        ckpt.save_checkpoint({"a": np.zeros(2)}, "/tmp/_never", 0, keep=0)


def test_checkpoint_keep_prunes_older_only(tmp_path):
    root = str(tmp_path / "ck")
    for step in range(1, 5):
        state = {"w": Tensor(np.full((2,), float(step), np.float32))}
        ckpt.save_checkpoint(state, root, step, keep=2)
    steps = sorted(s for s, _ in ckpt.checkpoint_steps(root))
    assert steps == [3, 4], steps
    # the newest survives intact and restores
    state = {"w": Tensor(np.zeros((2,), np.float32))}
    assert ckpt.load_latest_checkpoint(state, root) == 4
    np.testing.assert_allclose(np.asarray(state["w"].data), 4.0)


def test_segment_cache_hits_for_cond_style_closures():
    """The advisor's cited workload: fn closes over a fresh LIST of
    stable Tensors + stable callables — must share one executable."""
    rec = seg.SegmentRecorder()
    state = Tensor(jnp.ones((4,)))

    def stable_branch(a):
        return a + 1.0

    def make_fn():
        captured = [state]  # fresh list per call, stable contents
        return lambda a: stable_branch(a * len(captured))

    x = jnp.ones((4,))
    for _ in range(3):
        _record_one(rec, make_fn(), x)
    assert rec.stats["cache_hits"] == 2, rec.stats

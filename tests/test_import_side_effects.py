"""Importing paddle_tpu must not initialise the JAX backend.

``paddle_tpu.testing.force_host_cpu_devices`` (used by conftest and the
driver's multi-chip dryrun) can only work if the package import graph has
no module-level jax array/op: backend init is lazy in JAX and the first
concrete computation pins the platform. Guard the whole class of failure
(a future module-level ``jnp.array(...)`` anywhere in the eager import
graph would silently grab the real TPU tunnel before tests can force CPU).
"""
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_import_does_not_init_backend():
    code = (
        "from paddle_tpu.testing import force_host_cpu_devices\n"
        "force_host_cpu_devices(4)\n"  # raises if backend already inited
        "print('OK')\n"
    )
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout

"""Domain APIs: fft, signal (stft/istft), distribution, geometric.

VERDICT round-2 flagged these modules as live-but-untested; these are
numeric checks against scipy-free closed forms and round-trip
identities (reference: python/paddle/fft.py, signal.py,
distribution/, geometric/).
"""
import numpy as np
import pytest

import paddle_tpu as pt


# ------------------------------------------------------------------ fft ----

def test_fft_roundtrip_and_parseval():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    X = pt.fft.fft(pt.to_tensor(x.astype(np.complex64)))
    back = pt.fft.ifft(X)
    np.testing.assert_allclose(np.asarray(back.numpy()).real, x,
                               atol=1e-4)
    # Parseval: sum|x|^2 == sum|X|^2 / N
    e_t = (x ** 2).sum()
    e_f = (np.abs(np.asarray(X.numpy())) ** 2).sum() / 16
    np.testing.assert_allclose(e_t, e_f, rtol=1e-4)


def test_rfft_matches_numpy():
    x = np.random.RandomState(1).randn(4, 32).astype(np.float32)
    got = pt.fft.rfft(pt.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, np.fft.rfft(x), rtol=1e-4,
                               atol=1e-4)


def test_fftfreq():
    np.testing.assert_allclose(pt.fft.fftfreq(8, d=0.5).numpy(),
                               np.fft.fftfreq(8, d=0.5), rtol=1e-6)


# --------------------------------------------------------------- signal ----

def test_frame_and_overlap_add_roundtrip():
    x = np.arange(32, dtype=np.float32)
    frames = pt.signal.frame(pt.to_tensor(x), frame_length=8,
                             hop_length=8)
    # non-overlapping frames reassemble exactly
    back = pt.signal.overlap_add(frames, hop_length=8)
    np.testing.assert_allclose(back.numpy(), x)


def test_stft_istft_roundtrip():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 512).astype(np.float32)
    spec = pt.signal.stft(pt.to_tensor(x), n_fft=64, hop_length=16)
    back = pt.signal.istft(spec, n_fft=64, hop_length=16)
    n = min(back.shape[-1], x.shape[-1])
    np.testing.assert_allclose(np.asarray(back.numpy())[..., 32:n - 32],
                               x[..., 32:n - 32], atol=1e-3)


# --------------------------------------------------------- distribution ----

def test_normal_log_prob_and_sampling_moments():
    d = pt.distribution.Normal(loc=1.0, scale=2.0)
    lp = float(d.log_prob(pt.to_tensor(np.float32(1.0))).numpy())
    np.testing.assert_allclose(lp, -np.log(2.0 * np.sqrt(2 * np.pi)),
                               rtol=1e-5)
    s = d.sample([20000])
    np.testing.assert_allclose(float(s.numpy().mean()), 1.0, atol=0.1)
    np.testing.assert_allclose(float(s.numpy().std()), 2.0, atol=0.1)


def test_kl_divergence_normal_closed_form():
    p = pt.distribution.Normal(loc=0.0, scale=1.0)
    q = pt.distribution.Normal(loc=1.0, scale=2.0)
    kl = float(pt.distribution.kl_divergence(p, q).numpy())
    want = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    np.testing.assert_allclose(kl, want, rtol=1e-5)


def test_categorical_and_bernoulli():
    c = pt.distribution.Categorical(
        probs=pt.to_tensor(np.array([0.2, 0.3, 0.5], np.float32)))
    s = c.sample([5000]).numpy()
    assert set(np.unique(s)) <= {0, 1, 2}
    frac2 = (s == 2).mean()
    assert 0.4 < frac2 < 0.6
    b = pt.distribution.Bernoulli(0.25)
    lp = float(b.log_prob(pt.to_tensor(np.float32(1.0))).numpy())
    np.testing.assert_allclose(lp, np.log(0.25), rtol=1e-5)


def test_gamma_beta_entropy_finite():
    for d in (pt.distribution.Gamma(2.0, 3.0),
              pt.distribution.Beta(2.0, 5.0),
              pt.distribution.Laplace(0.0, 1.0)):
        assert np.isfinite(float(np.asarray(d.entropy().numpy())))


# ------------------------------------------------------------ geometric ----

def test_segment_ops():
    data = pt.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]],
                                 np.float32))
    seg = pt.to_tensor(np.array([0, 0, 1], np.int32))
    s = pt.geometric.segment_sum(data, seg).numpy()
    np.testing.assert_allclose(s, [[4., 6.], [5., 6.]])
    m = pt.geometric.segment_mean(data, seg).numpy()
    np.testing.assert_allclose(m, [[2., 3.], [5., 6.]])
    mx = pt.geometric.segment_max(data, seg).numpy()
    np.testing.assert_allclose(mx, [[3., 4.], [5., 6.]])


def test_send_u_recv_message_passing():
    x = pt.to_tensor(np.array([[1.], [2.], [4.]], np.float32))
    src = pt.to_tensor(np.array([0, 1, 2], np.int32))
    dst = pt.to_tensor(np.array([1, 2, 0], np.int32))
    out = pt.geometric.send_u_recv(x, src, dst, reduce_op="sum").numpy()
    np.testing.assert_allclose(out, [[4.], [1.], [2.]])


def test_hfft_family_matches_numpy():
    rng = np.random.RandomState(5)
    x = (rng.randn(4, 6) + 1j * rng.randn(4, 6)).astype(np.complex64)
    got = pt.fft.hfft2(pt.to_tensor(x)).numpy()
    ref = np.fft.fftn(x, axes=(0,))
    ref = np.fft.hfft(ref, axis=1)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-3)
    r = rng.randn(4, 6).astype(np.float32)
    got_i = pt.fft.ihfft2(pt.to_tensor(r)).numpy()
    ref_i = np.fft.ifftn(np.fft.ihfft(r, axis=1), axes=(0,))
    np.testing.assert_allclose(got_i, ref_i, rtol=1e-4, atol=1e-4)
    gotn = pt.fft.hfftn(pt.to_tensor(x)).numpy()
    np.testing.assert_allclose(gotn, ref, rtol=1e-4, atol=1e-3)


def test_hfftn_s_shorter_than_ndim():
    # s=[n] transforms only the last len(s) axes (paddle semantics)
    rng = np.random.RandomState(6)
    x = (rng.randn(4, 6) + 1j * rng.randn(4, 6)).astype(np.complex64)
    got = pt.fft.hfftn(pt.to_tensor(x), s=[8]).numpy()
    ref = np.fft.hfft(x, n=8, axis=1)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-3)

"""Fused top-k/top-p sampling on the serving tick (ISSUE r16).

What this module pins, bottom up:

* the in-graph ``_fused_sample`` head degrades EXACTLY to greedy at
  temp=0 / top_k=1 / top_p→0 (argmax-equivalent masks), so every
  greedy bitwise pin in the suite survives by construction;
* SAMPLING requests ride the same fused programs as greedy ones —
  the fused block, the mixed tick's decode tail, the speculative
  verify — and the pre-r16 width-S single-step sampling program is
  GONE from the statically proven inventory;
* DETERMINISM: a fixed-seed sampled request emits one token stream
  whether it runs alone, packed with any neighbours, submitted in any
  order, under any decode_block size, or on a speculative engine
  (sampled acceptance) — the fold_in-by-token-index key discipline,
  the r16 determinism fix;
* ``warm_programs()`` still covers the whole (smaller) inventory, so
  the recompile sentinel stays clean under mixed sampled traffic.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.serving import ServingEngine

CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


def _engine(params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_prompt_len", 16)
    kw.setdefault("max_new_tokens_cap", 16)
    return ServingEngine(params, CFG, **kw)


def _ref(params, prompt, n):
    out = jax.jit(lambda p, t: L.generate(p, t, CFG, max_new_tokens=n)
                  )(params, jnp.asarray(prompt)[None])
    return np.asarray(out)[0, len(prompt):]


RNG = np.random.RandomState(3)
PROMPT = RNG.randint(0, CFG.vocab_size, (11,)).astype(np.int32)


def _sampled(params, *, neighbors=0, block=1, spec=False, order=0,
             n=8, **samp):
    """One fixed-seed sampled request's stream under a given batch
    composition; greedy neighbours verified exact on the side."""
    samp.setdefault("temperature", 0.9)
    samp.setdefault("top_p", 0.95)
    samp.setdefault("seed", 42)
    kw = dict(decode_block_size=block)
    if spec:
        kw.update(speculative=True, spec_k=3)
    nb_prompts = [RNG.randint(0, CFG.vocab_size, (7,)).astype(np.int32)
                  for _ in range(neighbors)]
    with _engine(params, **kw) as eng:
        handles, h_s = [], None
        for i in range(neighbors + 1):
            if i == order:
                h_s = eng.submit(PROMPT, n, **samp)
            else:
                p = nb_prompts[i if i < order else i - 1]
                handles.append((p, eng.submit(p, 6)))
        out = h_s.result(timeout=300)
        nb = [(p, h.result(timeout=300)) for p, h in handles]
    for p, o in nb:
        np.testing.assert_array_equal(o, _ref(params, p, 6))
    return out


def test_sampled_stream_is_batch_composition_invariant(params):
    """THE determinism pin (r16 fix): same seed -> same stream alone,
    packed with greedy neighbours, submitted first or last (slot
    permutation), and under either decode_block size — while every
    greedy neighbour stays bitwise-equal to generate()."""
    base = _sampled(params)
    assert len(base) == 8
    for kw in (dict(neighbors=3), dict(neighbors=3, order=2),
               dict(block=4), dict(neighbors=2, block=4, order=1)):
        np.testing.assert_array_equal(base, _sampled(params, **kw))


def test_sampled_stream_invariant_under_speculation(params):
    """Speculative engines verify drafts against the target's own
    SAMPLED token (spec_k no longer greedy-only): the emitted stream
    equals the plain engine's bitwise, whatever the drafter proposed
    and wherever acceptance landed."""
    base = _sampled(params)
    np.testing.assert_array_equal(base, _sampled(params, spec=True))
    np.testing.assert_array_equal(
        base, _sampled(params, spec=True, neighbors=2, order=1))


def test_top_k_one_and_top_p_zero_degrade_to_greedy(params):
    """Exactness hooks into the reference: top_k=1 (and top_p→0)
    force the fused sampler's mask down to the argmax token, so the
    sampled stream equals the GREEDY stream equals generate() —
    pinning the mask semantics, not just determinism."""
    greedy = _ref(params, PROMPT, 8)
    np.testing.assert_array_equal(
        greedy, _sampled(params, temperature=0.8, top_k=1, top_p=1.0))
    np.testing.assert_array_equal(
        greedy, _sampled(params, temperature=0.8, top_p=1e-9))
    # and through the fused block with greedy neighbours
    np.testing.assert_array_equal(
        greedy, _sampled(params, temperature=0.8, top_k=1,
                         neighbors=2, block=4))


def test_sampling_rides_the_fused_block(params):
    """A pure-decode tick mixing greedy and sampling slots runs the
    fused block (steps > ticks), not single steps — the program the
    width-S single-step tick used to own."""
    with _engine(params, decode_block_size=4,
                 prefix_cache=False) as eng:
        h_g = eng.submit(PROMPT, 12)
        h_s = eng.submit(PROMPT[:7], 12, temperature=0.7, seed=1)
        out_g = h_g.result(timeout=300)
        out_s = h_s.result(timeout=300)
        snap = eng.stats()
    np.testing.assert_array_equal(out_g, _ref(params, PROMPT, 12))
    assert len(out_s) == 12
    steps = snap["counters"]["decode_steps"]
    ticks = snap["histograms"]["decode_step_s"]["count"]
    assert steps > ticks, (
        f"sampling forced single steps: {steps} steps / {ticks} ticks")


def test_single_step_program_gone_from_inventory(params):
    """The static half of the acceptance: the engine's proven
    inventory (== analysis/recompile.py's enumeration) no longer
    contains the width-S single-step tick; width S is the fused block
    alone, and the per-bucket bound holds with sampling as data."""
    from paddle_tpu.analysis.recompile import (ServingGeometry,
                                               program_inventory)
    with _engine(params, decode_block_size=4) as eng:
        inv = eng.program_inventory
        S = eng.scheduler.max_batch
        assert inv == program_inventory(ServingGeometry.of_engine(eng))
    assert inv["programs_per_bucket"] <= 2
    progs = [p for ps in inv["widths"].values() for p in ps]
    assert "serving_tick[decode]" not in progs
    assert inv["widths"][str(S)] == ["serving_tick_block[k=4]"]


def test_warm_programs_sentinel_clean_under_sampled_traffic(params):
    """warm_programs() covers the whole r16 inventory (one compile per
    mixed-width tail variant + the block), and an armed sentinel stays
    clean through mixed greedy+sampled+chunked traffic — the runtime
    proof that sampling really is data."""
    from paddle_tpu.serving import engine as _em
    _em._JIT_CACHE.clear()
    with _engine(params, recompile_sentinel=True, prefill_chunk=4,
                 max_batch=2, decode_block_size=2) as eng:
        n = eng.warm_programs()
        # two tail variants per mixed width (decode_block=2) + block
        assert n == 2 * len(eng._w_grid) + 1
        eng.arm_sentinel()
        hs = [eng.submit(PROMPT, 6),
              eng.submit(PROMPT[:9], 6, temperature=0.9, seed=5),
              eng.submit(PROMPT[:5], 4, temperature=0.5, top_k=3,
                         seed=6)]
        for h in hs:
            h.result(timeout=300)
        rep = eng.sentinel.report()
    assert rep["clean"], rep["events"]


def test_host_key_data_matches_prngkey():
    """The engine builds each slot's raw threefry key HOST-side
    ([0, seed & 0xffffffff] on the Python int) to keep a jit dispatch
    + device sync off the admission path — pin it bit-identical to
    jax.random.PRNGKey under the default (x64-off) config, including
    PRNGKey's >32-bit truncation AND negative seeds (np.uint64(-1)
    would raise on NumPy 2 — the mask must run on the Python int)."""
    for s in (0, 7, 42, 2**31 - 1, 2**33 + 5, -1, -42):
        host = np.array([0, s & 0xffffffff], np.uint32)
        np.testing.assert_array_equal(
            host, np.asarray(jax.random.PRNGKey(s), np.uint32))


def test_negative_seed_serves(params):
    """A negative seed must not kill the engine worker (regression:
    the first r16 cut crashed in _park on NumPy 2)."""
    with _engine(params) as eng:
        out = eng.submit(PROMPT, 4, temperature=0.8,
                         seed=-1).result(timeout=300)
    assert len(out) == 4


def test_fused_sample_unit_masks():
    """Unit pins on ``_fused_sample``: greedy rows bitwise argmax;
    top_k=1 rows equal argmax regardless of temperature; top_p=1 /
    top_k=0 leave the distribution intact (every token reachable);
    draws depend only on (key, idx), not on neighbouring rows."""
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(4, 64).astype(np.float32))
    keys = jnp.asarray(
        np.stack([np.asarray(jax.random.PRNGKey(s)) for s in
                  (1, 2, 3, 4)]).astype(np.uint32))
    idx = jnp.asarray([0, 5, 9, 2], jnp.int32)
    zeros = jnp.zeros((4,), jnp.float32)
    ones = jnp.ones((4,), jnp.float32)
    zi = jnp.zeros((4,), jnp.int32)
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    out = np.asarray(L._fused_sample(logits, zeros, ones, zi, keys,
                                     idx))
    np.testing.assert_array_equal(out, greedy)
    out = np.asarray(L._fused_sample(logits, ones, ones,
                                     jnp.full((4,), 1, jnp.int32),
                                     keys, idx))
    np.testing.assert_array_equal(out, greedy)       # top_k=1
    # row independence: permuting OTHER rows does not change row 0
    a = np.asarray(L._fused_sample(logits, ones, ones, zi, keys, idx))
    perm = jnp.asarray([0, 3, 2, 1])
    b = np.asarray(L._fused_sample(logits[perm], ones, ones, zi,
                                   keys[perm], idx[perm]))
    assert a[0] == b[0]
    assert a[3] == b[1]

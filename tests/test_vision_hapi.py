"""Vision models + transforms + metrics + hapi Model.fit.

Mirrors the reference's test/legacy_test/test_vision_models.py,
test_transforms.py, test_metrics.py, and hapi test_model.py, scaled for CI.
"""
import os
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu.vision import transforms as T
from paddle_tpu.vision.datasets import FakeData
from paddle_tpu.metric import Accuracy, Precision, Recall, Auc
from paddle_tpu.hapi import Model


def test_resnet18_forward():
    net = pt.vision.models.resnet18(num_classes=10)
    x = pt.to_tensor(np.random.randn(2, 3, 32, 32).astype("float32"))
    out = net(x)
    assert tuple(out.shape) == (2, 10)


def test_resnet50_bottleneck_forward():
    net = pt.vision.models.resnet50(num_classes=7)
    x = pt.to_tensor(np.random.randn(1, 3, 64, 64).astype("float32"))
    out = net(x)
    assert tuple(out.shape) == (1, 7)


def test_resnext_grouped_conv():
    net = pt.vision.models.resnext50_32x4d(num_classes=4)
    x = pt.to_tensor(np.random.randn(1, 3, 32, 32).astype("float32"))
    assert tuple(net(x).shape) == (1, 4)


def test_transforms_pipeline():
    tf = T.Compose([
        T.Resize(40), T.CenterCrop(32), T.RandomHorizontalFlip(0.5),
        T.ToTensor(),
        T.Normalize(mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5]),
    ])
    img = (np.random.rand(48, 64, 3) * 255).astype(np.uint8)
    out = tf(img)
    assert out.shape == (3, 32, 32)
    assert out.dtype == np.float32
    assert -1.01 <= out.min() and out.max() <= 1.01


def test_resize_aspect_and_exact():
    img = np.zeros((40, 80, 3), np.uint8)
    assert T.Resize(20)(img).shape == (20, 40, 3)
    assert T.Resize((16, 24))(img).shape == (16, 24, 3)


def test_rotate_expand_keeps_whole_image():
    img = np.full((20, 40, 3), 200, np.uint8)
    out = T.rotate(img, 45, expand=True)
    # 45-deg bbox of a 40x20 canvas: ~ (40+20)/sqrt(2) ≈ 42.4 each side
    assert out.shape[0] > 40 and out.shape[1] > 40
    # all original mass is retained: fill is 0, content is 200
    assert (np.asarray(out, np.int64) > 0).sum() >= 20 * 40 * 3
    # non-expanding keeps the canvas and crops the corners
    crop = T.rotate(img, 45, expand=False)
    assert crop.shape == img.shape
    assert (np.asarray(crop, np.int64) > 0).sum() < 20 * 40 * 3


def test_rotate_90_expand_exact_transpose():
    # reference convention (functional.py:778): positive angle is
    # COUNTER-clockwise, i.e. np.rot90's default direction
    img = (np.arange(12 * 8 * 3) % 251).astype(np.uint8).reshape(12, 8, 3)
    out = T.rotate(img, 90, expand=True, interpolation="bilinear")
    assert out.shape == (8, 12, 3)
    np.testing.assert_array_equal(out, np.rot90(img))


def test_accuracy_metric():
    m = Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1], [0.1, 0.2, 0.7]])
    label = np.array([[1], [2], [2]])
    m.update(m.compute(pred, label))
    top1, top2 = m.accumulate()
    np.testing.assert_allclose(top1, 2 / 3, rtol=1e-6)
    np.testing.assert_allclose(top2, 2 / 3, rtol=1e-6)


def test_precision_recall_auc():
    p, r, a = Precision(), Recall(), Auc()
    preds = np.array([0.9, 0.8, 0.2, 0.6])
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    a.update(preds, labels)
    np.testing.assert_allclose(p.accumulate(), 2 / 3, rtol=1e-6)
    np.testing.assert_allclose(r.accumulate(), 2 / 3, rtol=1e-6)
    assert 0.0 <= a.accumulate() <= 1.0


def test_model_fit_evaluate_predict(tmp_path):
    train = FakeData(num_samples=64, image_shape=(1, 28, 28), num_classes=10)
    test = FakeData(num_samples=32, image_shape=(1, 28, 28), num_classes=10,
                    seed=1)
    net = pt.models.LeNet()
    model = Model(net)
    model.prepare(
        optimizer=pt.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters()),
        loss=pt.nn.CrossEntropyLoss(),
        metrics=Accuracy())
    model.fit(train, batch_size=16, epochs=1, verbose=0)
    logs = model.evaluate(test, batch_size=16, verbose=0)
    assert "loss" in logs and "acc" in logs
    preds = model.predict(test, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (32, 10)
    # save / load round-trip
    path = str(tmp_path / "ckpt" / "m")
    model.save(path)
    model2 = Model(pt.models.LeNet())
    model2.prepare(optimizer=pt.optimizer.Adam(
        learning_rate=1e-3, parameters=model2.network.parameters()),
        loss=pt.nn.CrossEntropyLoss())
    model2.load(path)
    w1 = model.network.state_dict()
    w2 = model2.network.state_dict()
    for k in w1:
        np.testing.assert_array_equal(np.asarray(w1[k].numpy()),
                                      np.asarray(w2[k].numpy()))


def test_model_fit_improves_on_learnable_data():
    """Two separable gaussian blobs: a few epochs must beat chance."""
    rng = np.random.RandomState(0)
    ys = rng.randint(0, 2, (128, 1)).astype(np.int64)
    xs = (rng.randn(128, 1, 8, 8) + ys[:, :, None, None]).astype(np.float32)

    class Arr(pt.io.Dataset):
        def __len__(self):
            return len(xs)

        def __getitem__(self, i):
            return xs[i], ys[i]

    net = pt.nn.Sequential(pt.nn.Flatten(), pt.nn.Linear(64, 2))
    model = Model(net)
    model.prepare(pt.optimizer.Adam(learning_rate=0.05,
                                    parameters=net.parameters()),
                  pt.nn.CrossEntropyLoss(), Accuracy())
    model.fit(Arr(), batch_size=32, epochs=5, verbose=0, shuffle=False)
    logs = model.evaluate(Arr(), batch_size=32, verbose=0)
    assert logs["acc"] > 0.8

"""TensorArray/SelectedRows (core/containers.py), the autotune cache
(ops/autotune.py), and the SOT graph-break fallback (jit full_graph).

Reference capabilities: LoDTensorArray + paddle.tensor.array_* ops,
phi/core/selected_rows.h, phi/kernels/autotune/, jit/sot fallback.
"""
import numpy as np
import pytest

import jax.numpy as jnp
import paddle_tpu as pt
from paddle_tpu.core.containers import (TensorArray, SelectedRows,
                                        create_array, array_write,
                                        array_read, array_length)


# ---------------------------------------------------------- TensorArray ----

def test_tensor_array_write_read_stack():
    arr = create_array()
    for i in range(4):
        array_write(pt.to_tensor(np.full((2,), float(i), np.float32)),
                    i, arr)
    assert int(array_length(arr).numpy()) == 4
    np.testing.assert_allclose(array_read(arr, 2).numpy(), 2.0)
    stacked = arr.stack()
    assert tuple(stacked.shape) == (4, 2)
    np.testing.assert_allclose(stacked.numpy()[:, 0], [0, 1, 2, 3])
    cat = arr.concat()
    assert tuple(cat.shape) == (8,)


def test_tensor_array_overwrite_and_bounds():
    arr = TensorArray()
    arr.write(0, pt.to_tensor(np.zeros(2, np.float32)))
    arr.write(0, pt.to_tensor(np.ones(2, np.float32)))
    np.testing.assert_allclose(arr.read(0).numpy(), 1.0)
    with pytest.raises(IndexError):
        arr.write(5, pt.to_tensor(np.ones(2, np.float32)))


def test_tensor_array_grad_flows_through_stack():
    xs = [pt.to_tensor(np.full((3,), float(i + 1), np.float32),
                       stop_gradient=False) for i in range(3)]
    arr = TensorArray(xs)
    loss = (arr.stack() * 2.0).sum()
    loss.backward()
    for x in xs:
        np.testing.assert_allclose(x.grad.numpy(), 2.0)


# ---------------------------------------------------------- SelectedRows ----

def test_selected_rows_roundtrip():
    rows = np.array([1, 4], np.int64)
    vals = np.arange(6, dtype=np.float32).reshape(2, 3)
    sr = SelectedRows(rows, vals, height=6)
    dense = sr.to_dense().numpy()
    assert dense.shape == (6, 3)
    np.testing.assert_allclose(dense[1], vals[0])
    np.testing.assert_allclose(dense[4], vals[1])
    assert np.all(dense[[0, 2, 3, 5]] == 0)
    back = SelectedRows.from_dense(dense)
    np.testing.assert_array_equal(np.asarray(back.rows.numpy()), rows)
    np.testing.assert_allclose(back.value.numpy(), vals)


def test_selected_rows_duplicate_rows_accumulate():
    sr = SelectedRows(np.array([2, 2], np.int64),
                      np.ones((2, 2), np.float32), height=4)
    np.testing.assert_allclose(sr.to_dense().numpy()[2], 2.0)


# -------------------------------------------------------------- autotune ----

def test_autotune_picks_faster_candidate_and_caches():
    import time
    from paddle_tpu.ops import autotune as at
    at.clear()
    calls = {"slow": 0, "fast": 0}

    def slow(x):
        calls["slow"] += 1
        time.sleep(0.02)
        return x * 2

    def fast(x):
        calls["fast"] += 1
        return x * 2

    x = jnp.ones((4,))
    for _ in range(5):
        out = at.autotune("k", [slow, fast], (x,), iters=2)
    np.testing.assert_allclose(np.asarray(out), 2.0)
    cache, stats = at.cache_info()
    assert cache["k"] == 1  # fast won
    # slow ran only during measurement, never after
    assert calls["slow"] <= 3 and calls["fast"] >= 7


def test_autotune_skips_failing_candidates():
    from paddle_tpu.ops import autotune as at
    at.clear()

    def broken(x):
        raise RuntimeError("no")

    def ok(x):
        return x + 1

    out = at.autotune("k2", [broken, ok], (jnp.zeros(2),), iters=1)
    np.testing.assert_allclose(np.asarray(out), 1.0)
    with pytest.raises(RuntimeError, match="all autotune"):
        at.autotune("k3", [broken], (jnp.zeros(2),), iters=1)


def test_autotune_key_includes_dtype_and_blocks():
    # shape-only keys collide across bf16/int8 callers of the same
    # geometry and across candidate block-shape sets
    from paddle_tpu.ops import autotune as at
    a16 = jnp.zeros((4, 8), jnp.bfloat16)
    a32 = jnp.zeros((4, 8), jnp.float32)
    keys = {at.make_key("op", (a16,), blocks=(128, 128)),
            at.make_key("op", (a32,), blocks=(128, 128)),
            at.make_key("op", (a16,), blocks=(256, 128))}
    assert len(keys) == 3


# ------------------------------------------- persistent winner store ----

def test_winner_store_disk_round_trip(tmp_path, monkeypatch):
    from paddle_tpu.ops import autotune as at
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_DIR", str(tmp_path))
    at.clear()
    at.record("fused_rms_norm", {"tile_n": 4},
              rows=64, d=32, dtype="float32")
    at.record("conv_epilogue", {"tm": 8, "tn": 128, "tk": 8},
              M=64, K=32, N=128, dtype="float32")
    # drop ALL in-process state — the next lookup must reload the file,
    # which is what a fresh benching->serving process pair does
    at.clear()
    assert at.lookup("fused_rms_norm", rows=64, d=32,
                     dtype="float32") == {"tile_n": 4}
    assert at.lookup("conv_epilogue", M=64, K=32, N=128,
                     dtype="float32") == {"tm": 8, "tn": 128, "tk": 8}
    # unswept geometry / kind / dtype -> None (caller keeps defaults)
    assert at.lookup("fused_rms_norm", rows=128, d=32,
                     dtype="float32") is None
    assert at.lookup("fused_rms_norm", rows=64, d=32,
                     dtype="bfloat16") is None
    assert at.lookup("never_swept", rows=1) is None
    at.clear()


def test_winner_store_corrupt_file_degrades_to_defaults(tmp_path,
                                                        monkeypatch):
    from paddle_tpu.ops import autotune as at
    from paddle_tpu.ops.pallas.fused_norm_rope import (_pick_row_tile,
                                                       _row_tile)
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_DIR", str(tmp_path))
    (tmp_path / "winners.json").write_text("{not json")
    at.clear()
    # corrupt store == empty store: lookups miss, entry points resolve
    # their static defaults, nothing raises
    assert at.lookup("fused_rms_norm", rows=64, d=32,
                     dtype="float32") is None
    assert _pick_row_tile(64, 32, jnp.float32, None) == _row_tile(64, 32)
    at.clear()


def test_winner_store_drives_entry_point_tiles(tmp_path, monkeypatch):
    import jax
    from paddle_tpu.ops import autotune as at
    from paddle_tpu.ops.pallas.fused_norm_rope import _pick_row_tile
    from paddle_tpu.ops.pallas.grouped_matmul import moe_mlp_dropless
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_DIR", str(tmp_path))
    at.clear()
    at.record("fused_rms_norm", {"tile_n": 4},
              rows=64, d=32, dtype="float32")
    assert _pick_row_tile(64, 32, jnp.float32, None) == 4
    # a recorded tile that does not divide the rows is ignored
    at.record("fused_rms_norm", {"tile_n": 5},
              rows=64, d=32, dtype="float32")
    assert _pick_row_tile(64, 32, jnp.float32, None) != 5
    # the 4th reader: a tiles-unspecified moe call resolves the swept
    # winner and matches the explicit-tiles call bitwise
    S, D, F, E, k = 32, 16, 32, 4, 2
    at.record("grouped_matmul", {"tile_m": 16, "tile_n": 32},
              S=S, D=D, F=F, E=E, k=k, dtype="float32")
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    x = jax.random.normal(ks[0], (S, D), jnp.float32)
    wg = jax.random.normal(ks[1], (E, D, F), jnp.float32) * 0.02
    wu = jax.random.normal(ks[2], (E, D, F), jnp.float32) * 0.02
    wd = jax.random.normal(ks[3], (E, F, D), jnp.float32) * 0.02
    logits = jax.random.normal(ks[4], (S, E), jnp.float32)
    cw, eids = jax.lax.top_k(jax.nn.softmax(logits), k)
    y_default = moe_mlp_dropless(x, eids, cw, wg, wu, wd)
    y_winner = moe_mlp_dropless(x, eids, cw, wg, wu, wd,
                                tile_m=16, tile_n=32)
    assert (np.asarray(y_default) == np.asarray(y_winner)).all()
    at.clear()


# ----------------------------------------------------------- SOT fallback ----

def test_to_static_full_graph_false_falls_back_on_graph_break():
    calls = []

    @pt.jit.to_static(full_graph=False)
    def f(x):
        calls.append(1)
        if float(x.sum().numpy()) > 0:  # concretizes a tracer -> break
            return x * 2
        return x

    x = pt.to_tensor(np.ones(3, np.float32))
    out = f(x)  # falls back to eager, still correct
    np.testing.assert_allclose(out.numpy(), 2.0)
    out2 = f(x)  # stays on the eager path
    np.testing.assert_allclose(out2.numpy(), 2.0)


def test_to_static_full_graph_true_raises_on_break():
    import jax

    @pt.jit.to_static(full_graph=True)
    def f(x):
        if float(x.sum().numpy()) > 0:
            return x * 2
        return x

    with pytest.raises(jax.errors.JAXTypeError):
        f(pt.to_tensor(np.ones(3, np.float32)))

"""TensorArray/SelectedRows (core/containers.py), the autotune cache
(ops/autotune.py), and the SOT graph-break fallback (jit full_graph).

Reference capabilities: LoDTensorArray + paddle.tensor.array_* ops,
phi/core/selected_rows.h, phi/kernels/autotune/, jit/sot fallback.
"""
import numpy as np
import pytest

import jax.numpy as jnp
import paddle_tpu as pt
from paddle_tpu.core.containers import (TensorArray, SelectedRows,
                                        create_array, array_write,
                                        array_read, array_length)


# ---------------------------------------------------------- TensorArray ----

def test_tensor_array_write_read_stack():
    arr = create_array()
    for i in range(4):
        array_write(pt.to_tensor(np.full((2,), float(i), np.float32)),
                    i, arr)
    assert int(array_length(arr).numpy()) == 4
    np.testing.assert_allclose(array_read(arr, 2).numpy(), 2.0)
    stacked = arr.stack()
    assert tuple(stacked.shape) == (4, 2)
    np.testing.assert_allclose(stacked.numpy()[:, 0], [0, 1, 2, 3])
    cat = arr.concat()
    assert tuple(cat.shape) == (8,)


def test_tensor_array_overwrite_and_bounds():
    arr = TensorArray()
    arr.write(0, pt.to_tensor(np.zeros(2, np.float32)))
    arr.write(0, pt.to_tensor(np.ones(2, np.float32)))
    np.testing.assert_allclose(arr.read(0).numpy(), 1.0)
    with pytest.raises(IndexError):
        arr.write(5, pt.to_tensor(np.ones(2, np.float32)))


def test_tensor_array_grad_flows_through_stack():
    xs = [pt.to_tensor(np.full((3,), float(i + 1), np.float32),
                       stop_gradient=False) for i in range(3)]
    arr = TensorArray(xs)
    loss = (arr.stack() * 2.0).sum()
    loss.backward()
    for x in xs:
        np.testing.assert_allclose(x.grad.numpy(), 2.0)


# ---------------------------------------------------------- SelectedRows ----

def test_selected_rows_roundtrip():
    rows = np.array([1, 4], np.int64)
    vals = np.arange(6, dtype=np.float32).reshape(2, 3)
    sr = SelectedRows(rows, vals, height=6)
    dense = sr.to_dense().numpy()
    assert dense.shape == (6, 3)
    np.testing.assert_allclose(dense[1], vals[0])
    np.testing.assert_allclose(dense[4], vals[1])
    assert np.all(dense[[0, 2, 3, 5]] == 0)
    back = SelectedRows.from_dense(dense)
    np.testing.assert_array_equal(np.asarray(back.rows.numpy()), rows)
    np.testing.assert_allclose(back.value.numpy(), vals)


def test_selected_rows_duplicate_rows_accumulate():
    sr = SelectedRows(np.array([2, 2], np.int64),
                      np.ones((2, 2), np.float32), height=4)
    np.testing.assert_allclose(sr.to_dense().numpy()[2], 2.0)


# -------------------------------------------------------------- autotune ----

def test_autotune_picks_faster_candidate_and_caches():
    import time
    from paddle_tpu.ops import autotune as at
    at.clear()
    calls = {"slow": 0, "fast": 0}

    def slow(x):
        calls["slow"] += 1
        time.sleep(0.02)
        return x * 2

    def fast(x):
        calls["fast"] += 1
        return x * 2

    x = jnp.ones((4,))
    for _ in range(5):
        out = at.autotune("k", [slow, fast], (x,), iters=2)
    np.testing.assert_allclose(np.asarray(out), 2.0)
    cache, stats = at.cache_info()
    assert cache["k"] == 1  # fast won
    # slow ran only during measurement, never after
    assert calls["slow"] <= 3 and calls["fast"] >= 7


def test_autotune_skips_failing_candidates():
    from paddle_tpu.ops import autotune as at
    at.clear()

    def broken(x):
        raise RuntimeError("no")

    def ok(x):
        return x + 1

    out = at.autotune("k2", [broken, ok], (jnp.zeros(2),), iters=1)
    np.testing.assert_allclose(np.asarray(out), 1.0)
    with pytest.raises(RuntimeError, match="all autotune"):
        at.autotune("k3", [broken], (jnp.zeros(2),), iters=1)


# ----------------------------------------------------------- SOT fallback ----

def test_to_static_full_graph_false_falls_back_on_graph_break():
    calls = []

    @pt.jit.to_static(full_graph=False)
    def f(x):
        calls.append(1)
        if float(x.sum().numpy()) > 0:  # concretizes a tracer -> break
            return x * 2
        return x

    x = pt.to_tensor(np.ones(3, np.float32))
    out = f(x)  # falls back to eager, still correct
    np.testing.assert_allclose(out.numpy(), 2.0)
    out2 = f(x)  # stays on the eager path
    np.testing.assert_allclose(out2.numpy(), 2.0)


def test_to_static_full_graph_true_raises_on_break():
    import jax

    @pt.jit.to_static(full_graph=True)
    def f(x):
        if float(x.sum().numpy()) > 0:
            return x * 2
        return x

    with pytest.raises(jax.errors.JAXTypeError):
        f(pt.to_tensor(np.ones(3, np.float32)))

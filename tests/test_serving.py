"""Continuous-batching serving engine (paddle_tpu/serving/).

Correctness bar (ISSUE r6): with greedy sampling, every request's
tokens must equal a standalone ``generate()`` run token-for-token,
regardless of what else shares the batch — admission order, slot
reuse, page placement and retirement of neighbours must all be
invisible to a request's math.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.inference.paged_kv import PagePool, apply_defrag
from paddle_tpu.models import llama as L
from paddle_tpu.serving import (CANCELLED, COMPLETED, QUEUED, Request,
                                Scheduler, ServingEngine, TIMED_OUT)

CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


import functools


@functools.lru_cache(maxsize=None)
def _gen_jit(n, eos):
    return jax.jit(lambda p, t: L.generate(p, t, CFG, max_new_tokens=n,
                                           eos_token_id=eos))


def _ref(params, prompt, n, eos=None):
    """Standalone generate() continuation (prompt stripped); jitted +
    memoized so repeated same-shape references trace once."""
    out = _gen_jit(n, eos)(params, jnp.asarray(prompt)[None])
    return np.asarray(out)[0, len(prompt):]


def _engine(params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_prompt_len", 16)
    kw.setdefault("max_new_tokens_cap", 16)
    return ServingEngine(params, CFG, **kw)


# ---------------------------------------------------------------------------
# greedy exactness under mixed continuous batching
# ---------------------------------------------------------------------------

def test_mixed_poisson_arrivals_match_generate_exactly(params):
    """Mixed-length prompts + mixed max_new_tokens, staggered Poisson
    arrivals, more requests than slots: every continuation must equal
    its standalone generate() run token-for-token."""
    rng = np.random.RandomState(0)
    lens, mnts = (3, 7, 12), (3, 8, 12)  # mixed, few distinct compiles
    specs = [(rng.randint(0, CFG.vocab_size,
                          (int(rng.choice(lens)),)).astype(np.int32),
              int(rng.choice(mnts))) for _ in range(10)]
    with _engine(params) as eng:
        handles = []
        for prompt, mnt in specs:
            handles.append(eng.submit(prompt, mnt))
            time.sleep(float(rng.exponential(0.003)))  # staggered admission
        outs = [h.result(timeout=300) for h in handles]
    for (prompt, mnt), out in zip(specs, outs):
        np.testing.assert_array_equal(out, _ref(params, prompt, mnt))
    snap = eng.stats()
    assert snap["counters"]["completed"] == len(specs)
    # continuous batching actually happened: fewer decode ticks than the
    # whole-request sum (slots were shared/refilled)
    total_steps = sum(m - 1 for _, m in specs)
    assert 0 < snap["counters"]["decode_steps"] < total_steps


def test_streaming_iterator_and_eos_retirement(params):
    prompt = np.asarray([5, 9, 2, 11], np.int32)
    full = _ref(params, prompt, 12)
    eos = int(full[3])  # force EOS at the 4th generated token
    with _engine(params) as eng:
        h = eng.submit(prompt, 12, eos_token_id=eos)
        streamed = list(h)  # consume the iterator as tokens arrive
    # engine retires AT the first EOS: its output is generate()'s
    # (EOS-latched) continuation truncated at the FIRST occurrence
    # (which may precede index 3 if the token repeats earlier)
    want = full[:int(np.argmax(full == eos)) + 1]
    np.testing.assert_array_equal(streamed, want)
    np.testing.assert_array_equal(h.result(), want)
    assert h.status == COMPLETED


# ---------------------------------------------------------------------------
# backpressure / rejection
# ---------------------------------------------------------------------------

def test_page_exhaustion_backpressure(params):
    """A pool that funds only ~1.5 worst-case slots must still serve
    every request — by queuing admissions until pages free up."""
    # pages_per_slot = ceil((16 + 16 - 1) / 4) = 8; give the pool 12
    with _engine(params, total_pages=13) as eng:
        occupied = []
        specs = [(np.arange(1, 9, dtype=np.int32) * (i + 1) % 100, 10)
                 for i in range(5)]
        handles = [eng.submit(p, m) for p, m in specs]
        outs = [h.result(timeout=300) for h in handles]
        occupied = eng.stats()["histograms"]["page_utilization"]["max"]
    for (p, m), out in zip(specs, outs):
        np.testing.assert_array_equal(out, _ref(params, p, m))
    assert occupied <= 1.0
    assert eng.stats()["counters"]["completed"] == 5


def test_never_fitting_request_rejected(params):
    with _engine(params, max_queue=2) as eng:
        with pytest.raises(RuntimeError, match="rejected"):
            eng.submit(np.zeros((17,), np.int32), 4)  # prompt > max bucket
        with pytest.raises(RuntimeError, match="rejected"):
            eng.submit(np.zeros((4,), np.int32), 4000)  # page budget
        assert eng.stats()["counters"]["rejected"] == 2


# ---------------------------------------------------------------------------
# cancellation / deadlines / drain
# ---------------------------------------------------------------------------

def test_cancel_mid_generation_frees_slot(params):
    prompt = np.asarray([3, 1, 4], np.int32)
    # paced ticks so the cancel deterministically lands mid-generation
    with _engine(params, max_batch=1, tick_interval_s=0.05) as eng:
        h = eng.submit(prompt, 16)
        it = iter(h)
        got = [next(it), next(it)]  # let it produce a couple of tokens
        h.cancel()
        rest = list(it)  # stream closes after the cancel sweeps
        assert h.status == CANCELLED
        # the produced prefix is still exact
        np.testing.assert_array_equal(
            got + rest, _ref(params, prompt, 16)[:len(got) + len(rest)])
        assert len(got) + len(rest) < 16
        # slot + pages came back: a follow-up request runs to completion
        p2 = np.asarray([7, 7], np.int32)
        np.testing.assert_array_equal(
            eng.submit(p2, 5).result(timeout=300), _ref(params, p2, 5))
    assert eng.pool.used_pages == 0


def test_deadline_timeout_retires(params):
    with _engine(params, max_batch=1) as eng:
        # a queued request whose deadline passes before admission
        h_run = eng.submit(np.asarray([1, 2, 3], np.int32), 16)
        h_q = eng.submit(np.asarray([4, 5], np.int32), 8, timeout=0.0)
        out = h_run.result(timeout=300)
        assert len(out) == 16
        assert h_q.result(timeout=300).size == 0  # nothing produced
        assert h_q.status == TIMED_OUT


def test_close_drains_all_pending(params):
    rng = np.random.RandomState(1)
    specs = [(rng.randint(0, CFG.vocab_size, (4,)).astype(np.int32),
              int(rng.randint(2, 8))) for _ in range(6)]
    eng = _engine(params, max_batch=2)
    handles = [eng.submit(p, m) for p, m in specs]
    eng.close()  # graceful drain: every accepted request finishes
    for (p, m), h in zip(specs, handles):
        assert h.status == COMPLETED
        np.testing.assert_array_equal(h.result(), _ref(params, p, m))
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(specs[0][0], 2)


def test_close_hand_back_returns_queued_requests(params):
    """The fleet drain protocol (ISSUE r18 satellite): a hand-back
    drain finishes IN-FLIGHT requests but returns queued-but-unadmitted
    ones STILL QUEUED — never finalized as failed — so a router can
    re-dispatch them (regression: a request queued mid-drain must
    survive the drain un-finalized and complete elsewhere)."""
    rng = np.random.RandomState(7)
    p_run = rng.randint(0, CFG.vocab_size, (4,)).astype(np.int32)
    p_q = [rng.randint(0, CFG.vocab_size, (5,)).astype(np.int32)
           for _ in range(2)]
    eng = _engine(params, max_batch=1)
    h_run = eng.submit(p_run, 12)
    it = iter(h_run)
    next(it)                        # h_run is admitted and decoding
    h_queued = [eng.submit(p, 8) for p in p_q]
    handed = eng.close(drain=True, hand_back=True)
    # in-flight finished on the draining engine, bitwise
    assert h_run.status == COMPLETED
    np.testing.assert_array_equal(h_run.result(), _ref(params, p_run, 12))
    # queued ones came back un-finalized, in order
    assert [r.id for r in handed] == [h.id for h in h_queued]
    for r, h in zip(handed, h_queued):
        assert r.state == QUEUED and not r.done.is_set()
        assert h.tokens_so_far == []
    # metrics recorded the hand-back; nothing was cancelled
    c = eng.stats()["counters"]
    assert c["handed_back"] == 2 and c["cancelled"] == 0
    # re-dispatch: the SAME Request objects into a fresh engine — the
    # ORIGINAL handles resolve there, bitwise
    eng2 = _engine(params)
    try:
        for r in handed:
            assert eng2.inject(r)
        for p, h in zip(p_q, h_queued):
            np.testing.assert_array_equal(h.result(timeout=300),
                                          _ref(params, p, 8))
            assert h.status == COMPLETED
    finally:
        assert eng2.close() == []   # plain drain hands nothing back
    # a hand-back without drain is contradictory
    eng3 = _engine(params)
    with pytest.raises(ValueError, match="hand_back"):
        eng3.close(drain=False, hand_back=True)
    eng3.close()


def test_close_without_drain_cancels(params):
    eng = _engine(params, max_batch=1)
    handles = [eng.submit(np.asarray([1, 2], np.int32), 16)
               for _ in range(3)]
    eng.close(drain=False)
    assert all(h.status == CANCELLED for h in handles)
    assert eng.pool.used_pages == 0


# ---------------------------------------------------------------------------
# defragmentation hook
# ---------------------------------------------------------------------------

def test_defragment_mid_generation_is_invisible(params):
    """Cancelling an EARLIER-admitted request leaves a low-index hole,
    so compaction must actually MOVE the later request's pages (a
    non-empty, chained plan) without changing its continuation."""
    rng = np.random.RandomState(2)
    p_a = rng.randint(0, CFG.vocab_size, (6,)).astype(np.int32)
    p_b = rng.randint(0, CFG.vocab_size, (9,)).astype(np.int32)
    with _engine(params, max_batch=2, tick_interval_s=0.03) as eng:
        h_a = eng.submit(p_a, 14)
        it_a = iter(h_a)
        next(it_a)            # A admitted: owns the LOW page indices
        h_b = eng.submit(p_b, 14)
        it_b = iter(h_b)
        next(it_b)            # B admitted after A: higher page indices
        h_a.cancel()          # frees A's low pages -> fragmentation
        list(it_a)            # wait for the cancel sweep
        moved = eng.defragment()
        assert moved > 0, "plan was empty: the fragmented path not hit"
        out_b = h_b.result(timeout=300)
        # a fresh request lands in the compacted region and still works
        p_c = rng.randint(0, CFG.vocab_size, (4,)).astype(np.int32)
        out_c = eng.submit(p_c, 8).result(timeout=300)
    np.testing.assert_array_equal(out_b, _ref(params, p_b, 14))
    np.testing.assert_array_equal(out_c, _ref(params, p_c, 8))
    assert h_a.status == CANCELLED


def test_page_pool_defrag_plan_and_apply():
    pool = PagePool(total_pages=9, page_size=2)
    a = pool.alloc(3)   # pages 8,7,6? free list is descending-built
    b = pool.alloc(2)
    pool.free(a)        # fragment: only b's pages live
    plan = pool.defrag_plan()
    assert plan == {4: 1, 5: 2}  # b's pages compact to the pool front
    # arrays: page p holds value p so moves are visible
    kp = jnp.arange(9, dtype=jnp.float32)[None, :, None, None] * \
        jnp.ones((2, 9, 2, 3))
    tables = jnp.asarray([b], jnp.int32)
    kp2, vp2, t2 = apply_defrag(plan, kp, kp, tables)
    pool.commit_defrag(plan)
    # every table entry still points at its page's (moved) contents
    for old, new in zip(b, np.asarray(t2)[0]):
        np.testing.assert_allclose(np.asarray(kp2[:, int(new)]),
                                   float(old))
    assert pool.used_pages == 2
    assert sorted(int(t) for t in np.asarray(t2)[0]) == [1, 2]
    # freed indices are allocatable again and distinct from live ones
    more = pool.alloc(6)
    assert set(more).isdisjoint(set(int(t) for t in np.asarray(t2)[0]))


# ---------------------------------------------------------------------------
# scheduler unit behaviour
# ---------------------------------------------------------------------------

def test_scheduler_fifo_and_page_budget():
    pool = PagePool(total_pages=9, page_size=4)
    sched = Scheduler(max_batch=2, pages_per_slot=4, pool=pool,
                      max_queue=3)
    big = Request(np.zeros((8,), np.int32), 9)      # 4 pages
    small = Request(np.zeros((2,), np.int32), 3)    # 1 page
    assert sched.submit(big) and sched.submit(small)
    admitted = sched.admit()
    assert [r.id for _, r in admitted] == [big.id, small.id]
    # a third is queued: slots full
    third = Request(np.zeros((2,), np.int32), 3)
    assert sched.submit(third)
    assert sched.admit() == []
    # strict FIFO under page pressure: big2 at the head blocks small2
    # from overtaking even though small2 would fit
    sched.retire(admitted[0][0], COMPLETED)
    big2 = Request(np.zeros((8,), np.int32), 9)
    assert sched.submit(big2)
    a2 = sched.admit()  # third (1 page) takes the slot: queued FIRST
    assert [r.id for _, r in a2] == [third.id]
    assert sched.admit() == []  # big2: no free slot
    # queue cap rejects
    assert sched.submit(Request(np.zeros((2,), np.int32), 2))
    assert sched.submit(Request(np.zeros((2,), np.int32), 2))
    assert not sched.submit(Request(np.zeros((2,), np.int32), 2))
    # never-fitting request rejected outright
    assert not sched.submit(Request(np.zeros((2,), np.int32), 4000))


def test_metrics_snapshot_shape(params):
    with _engine(params) as eng:
        eng.generate(np.asarray([1, 2, 3], np.int32), 4)
        snap = eng.stats()
    c, h = snap["counters"], snap["histograms"]
    assert c["submitted"] == c["completed"] == 1
    assert c["tokens_out"] == 4
    for name in ("queue_wait_s", "ttft_s", "decode_step_s",
                 "batch_occupancy", "page_utilization"):
        # lifetime (count/mean) AND windowed (window_*/percentiles)
        # stats are reported separately — see Histogram docstring
        assert set(h[name]) == {"count", "mean", "window_count",
                                "window_mean", "p50", "p99", "max"}
    assert h["ttft_s"]["count"] == 1
    assert 0 < h["batch_occupancy"]["max"] <= 1.0
    assert snap["gauges"]["free_pages"] == eng.pool.free_pages


def test_decode_block_mode_matches_single_step(params):
    """Multi-step (fused-block) greedy decode must emit the same tokens
    as tick-at-a-time decode — and as generate()."""
    rng = np.random.RandomState(4)
    specs = [(rng.randint(0, CFG.vocab_size, (n,)).astype(np.int32), m)
             for n, m in ((5, 9), (11, 3), (3, 12), (8, 7))]
    with _engine(params, decode_block_size=4) as eng:
        handles = [eng.submit(p, m) for p, m in specs]
        outs = [h.result(timeout=300) for h in handles]
    for (p, m), out in zip(specs, outs):
        np.testing.assert_array_equal(out, _ref(params, p, m))
    # block mode really ran fused: fewer jit calls than model steps
    snap = eng.stats()
    assert snap["counters"]["decode_steps"] >= \
        snap["histograms"]["decode_step_s"]["count"]


# ---------------------------------------------------------------------------
# serving_bench: the engine must beat whole-request batching (slow)
# ---------------------------------------------------------------------------

def _load_bench():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "serving_bench.py")
    spec = importlib.util.spec_from_file_location("serving_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serving_bench_smoke():
    """The replay tool runs end to end on a micro trace (no perf
    assertions — those live in the slow test below)."""
    sb = _load_bench()
    res = sb.main(["--requests", "6", "--rate", "100", "--max-batch", "2",
                   "--mnt-choices", "3", "6", "--max-prompt", "8",
                   "--modes", "engine"])
    assert res["engine"]["useful_tokens"] > 0


@pytest.mark.slow
def test_engine_beats_whole_request_batcher():
    """ISSUE r6 acceptance: under a loaded mixed-length trace on the
    CPU mesh, continuous batching beats the whole-request DynamicBatcher
    on aggregate tok/s AND p99 TTFT. Best-of-3 to shrug off co-tenant
    CPU noise (the margin is structural — ~40% measured — but this
    container's absolute throughput swings 2-3x between runs)."""
    sb = _load_bench()
    wins_tok, wins_ttft = 0, 0
    for _ in range(3):
        res = sb.main(["--modes", "batcher", "engine"])
        v = res["verdict"]
        wins_tok += v["engine_beats_batcher_tok_s"]
        wins_ttft += v["engine_beats_batcher_ttft_p99"]
        if wins_tok and wins_ttft:
            break
    assert wins_tok >= 1, "engine never beat the batcher on tok/s"
    assert wins_ttft >= 1, "engine never beat the batcher on p99 TTFT"


# ---------------------------------------------------------------------------
# qwen2-moe shares the drivers
# ---------------------------------------------------------------------------

def test_qwen2_moe_engine_matches_generate():
    from paddle_tpu.models import qwen2_moe as Q
    qcfg = Q.Qwen2MoeConfig.tiny(dtype=jnp.float32,
                                 use_flash_attention=False, remat=False)
    qparams = Q.init_params(qcfg, jax.random.PRNGKey(1))
    rng = np.random.RandomState(3)
    specs = [(rng.randint(0, qcfg.vocab_size, (n,)).astype(np.int32), m)
             for n, m in ((3, 5), (7, 3), (5, 6))]
    with ServingEngine(qparams, qcfg, max_batch=2, page_size=4,
                       max_prompt_len=8, max_new_tokens_cap=8) as eng:
        handles = [eng.submit(p, m) for p, m in specs]
        outs = [h.result(timeout=300) for h in handles]
    for (p, m), out in zip(specs, outs):
        ref = np.asarray(Q.generate(qparams, jnp.asarray(p)[None], qcfg,
                                    max_new_tokens=m))[0, len(p):]
        np.testing.assert_array_equal(out, ref)

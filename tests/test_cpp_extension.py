"""Out-of-tree custom C++ kernels via the XLA FFI
(utils/cpp_extension.py — reference: paddle.utils.cpp_extension +
paddle/phi/capi custom-kernel C API).

Compiles a REAL C++ kernel against jaxlib's shipped ffi.h, registers
it, and dispatches it as a framework op (eager + jit), including a
gradient surrogate via define_grad.
"""
import os
import textwrap

import numpy as np
import pytest

import jax
import paddle_tpu as pt
from paddle_tpu.core import native

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="no C++ toolchain")

KERNEL_CC = """
#include <cstdint>
#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error ScaledAddImpl(ffi::Buffer<ffi::F32> x,
                                ffi::Buffer<ffi::F32> y,
                                float alpha,
                                ffi::ResultBuffer<ffi::F32> out) {
  const float* xp = x.typed_data();
  const float* yp = y.typed_data();
  float* op = out->typed_data();
  const int64_t n = static_cast<int64_t>(x.element_count());
  for (int64_t i = 0; i < n; ++i) op[i] = xp[i] + alpha * yp[i];
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    ScaledAdd, ScaledAddImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Attr<float>("alpha")
        .Ret<ffi::Buffer<ffi::F32>>());
"""


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    from paddle_tpu.utils import cpp_extension
    d = tmp_path_factory.mktemp("customops")
    src = d / "my_ops.cc"
    src.write_text(KERNEL_CC)
    return cpp_extension.load(
        name="my_ops", sources=[str(src)],
        build_directory=str(d),
        functions={"scaled_add": dict(handler="ScaledAdd", n_args=2,
                                      attrs={"alpha": np.float32})})


@needs_native
def test_custom_kernel_eager(ext):
    x = pt.to_tensor(np.arange(8, dtype=np.float32))
    y = pt.to_tensor(np.ones(8, dtype=np.float32))
    out = ext.scaled_add(x, y, alpha=2.5)
    np.testing.assert_allclose(out.numpy(),
                               np.arange(8, dtype=np.float32) + 2.5)


@needs_native
def test_custom_kernel_under_jit(ext):
    import jax.numpy as jnp

    @jax.jit
    def f(a, b):
        return jax.ffi.ffi_call(
            "my_ops.scaled_add",
            jax.ShapeDtypeStruct(a.shape, a.dtype))(a, b,
                                                    alpha=np.float32(3.0))

    a = jnp.ones((4,), jnp.float32)
    out = f(a, a)
    np.testing.assert_allclose(np.asarray(out), 4.0 * np.ones(4))


@needs_native
def test_custom_kernel_registered_as_framework_op(ext):
    from paddle_tpu.ops.registry import OPS
    assert "my_ops.scaled_add" in OPS


@needs_native
def test_define_grad_surrogate(ext):
    from paddle_tpu.utils.cpp_extension import define_grad

    def surrogate(x, y, alpha=1.0):
        return x + alpha * y

    diff = define_grad(ext, "scaled_add", surrogate)
    x = pt.to_tensor(np.ones(4, np.float32), stop_gradient=False)
    y = pt.to_tensor(np.full(4, 2.0, np.float32), stop_gradient=False)
    out = diff(x, y, alpha=3.0)
    np.testing.assert_allclose(out.numpy(), 7.0 * np.ones(4))
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(4))
    np.testing.assert_allclose(y.grad.numpy(), 3.0 * np.ones(4))

"""Round-5 advisor findings, pinned.

- jit/segments._fn_cache_key must key default args (a factory's
  ``def f(x, y=s)`` capture) like closure cells — ADVICE r5 low.
- auto_parallel Engine pp must refuse models whose forward diverges
  from the definition-order unit list — ADVICE r5 medium.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.jit.segments import _fn_cache_key


# ---------------------------------------------------------------------------
# _fn_cache_key: default-arg capture
# ---------------------------------------------------------------------------

def _factory(s):
    def f(x, y=s):
        return x + y
    return f


def _kw_factory(s):
    def f(x, *, y=s):
        return x + y
    return f


def test_fn_cache_key_distinguishes_default_arg_capture():
    f1, f2 = _factory(1.0), _factory(2.0)
    assert f1.__code__ is f2.__code__ and not f1.__closure__
    assert _fn_cache_key(f1) != _fn_cache_key(f2)
    # equal captures still share a key (the whole point of the cache)
    assert _fn_cache_key(_factory(3.0)) == _fn_cache_key(_factory(3.0))


def test_fn_cache_key_distinguishes_kwonly_default_capture():
    f1, f2 = _kw_factory(1.0), _kw_factory(2.0)
    assert f1.__code__ is f2.__code__
    assert _fn_cache_key(f1) != _fn_cache_key(f2)
    assert _fn_cache_key(_kw_factory(3.0)) == _fn_cache_key(_kw_factory(3.0))


def test_fn_cache_key_unfreezable_default_falls_back_to_identity():
    class Mutable:
        pass

    f1 = _factory(Mutable())  # arbitrary object: must NOT key by value
    f2 = _factory(Mutable())
    assert _fn_cache_key(f1) == id(f1)
    assert _fn_cache_key(f1) != _fn_cache_key(f2)


def test_fn_cache_key_closures_still_keyed():
    def make(v):
        def g(x):
            return x * v
        return g

    assert _fn_cache_key(make(2.0)) == _fn_cache_key(make(2.0))
    assert _fn_cache_key(make(2.0)) != _fn_cache_key(make(3.0))


# ---------------------------------------------------------------------------
# Engine pp: definition-order vs forward-order guard
# ---------------------------------------------------------------------------

from paddle_tpu.distributed.auto_parallel import Engine, Strategy  # noqa


class _Block(pt.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = pt.nn.Linear(16, 16)

    def forward(self, x):
        return pt.nn.functional.relu(self.fc(x))


def _mse(pred, y):
    return ((pred - y) ** 2).mean()


def _x(bs=4):
    return np.random.RandomState(0).randn(bs, 16).astype(np.float32)


def _fit_one(model):
    opt = pt.optimizer.SGD(learning_rate=1e-2,
                           parameters=model.parameters())
    eng = Engine(model, loss=_mse, optimizer=opt,
                 strategy=Strategy(pp_degree=2, num_microbatches=2))
    return eng.fit([(_x(), np.zeros((4, 16), np.float32))], epochs=1)


def test_pp_guard_rejects_reversed_forward_order():
    class Reversed(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.blocks = pt.nn.Sequential(*[_Block() for _ in range(4)])

        def forward(self, x):
            for b in reversed(list(self.blocks)):
                x = b(x)
            return x

    with pytest.raises(ValueError, match="definition order"):
        _fit_one(Reversed())


def test_pp_guard_rejects_extra_math_between_units():
    class Residual(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.blocks = pt.nn.Sequential(*[_Block() for _ in range(4)])

        def forward(self, x):
            for b in self.blocks:
                x = b(x) + x  # glue the stage loop cannot reproduce
            return x

    with pytest.raises(ValueError, match="extra math between units"):
        _fit_one(Residual())


def test_pp_guard_rejects_postprocessed_output():
    class Post(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.blocks = pt.nn.Sequential(*[_Block() for _ in range(4)])

        def forward(self, x):
            for b in self.blocks:
                x = b(x)
            return x * 2.0  # outside the unit list

    with pytest.raises(ValueError, match="model output"):
        _fit_one(Post())


def test_pp_guard_rejects_unit_reuse():
    """A unit called TWICE shows up directly in the traced layer-event
    sequence (the shared ``trace_layer_graph`` machinery at unit
    granularity) — the sequence-mismatch raise, at prepare() time."""
    class Reuse(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.blocks = pt.nn.Sequential(*[_Block() for _ in range(4)])

        def forward(self, x):
            for b in self.blocks:
                x = b(x)
            return self.blocks[0](x)    # re-enters unit 0

    model = Reuse()
    eng = Engine(model, loss=_mse,
                 optimizer=pt.optimizer.SGD(
                     learning_rate=1e-2, parameters=model.parameters()),
                 strategy=Strategy(pp_degree=2, num_microbatches=2))
    with pytest.raises(ValueError, match="definition order"):
        eng.prepare(sample_input=_x())


def test_pp_guard_rejects_glue_before_first_unit():
    """Functional math BEFORE the first unit leaves the unit-to-unit
    identity chain intact — only the tracer's top-level op events see
    it (the new trace_layer_graph-based check; the old per-unit hook
    chain was blind here)."""
    class PreGlue(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.blocks = pt.nn.Sequential(*[_Block() for _ in range(4)])

        def forward(self, x):
            x = x * 2.0                 # outside every unit
            for b in self.blocks:
                x = b(x)
            return x

    model = PreGlue()
    eng = Engine(model, loss=_mse,
                 optimizer=pt.optimizer.SGD(
                     learning_rate=1e-2, parameters=model.parameters()),
                 strategy=Strategy(pp_degree=2, num_microbatches=2))
    with pytest.raises(ValueError, match="extra math between units"):
        eng.prepare(sample_input=_x())


def test_pp_guard_accepts_plain_chain_and_prepare_sample():
    model = pt.nn.Sequential(*[_Block() for _ in range(4)])
    opt = pt.optimizer.SGD(learning_rate=1e-2,
                           parameters=model.parameters())
    eng = Engine(model, loss=_mse, optimizer=opt,
                 strategy=Strategy(pp_degree=2, num_microbatches=2))
    eng.prepare(sample_input=_x())  # verification at prepare() time
    assert eng._pp_verified
    hist = eng.fit([(_x(), np.zeros((4, 16), np.float32))], epochs=1)
    assert np.isfinite(hist).all()

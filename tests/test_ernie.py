"""ERNIE-style encoder: pretrain heads + fine-tune (SURVEY §7 step 10).

Checks: padding-mask correctness (pad positions don't affect outputs),
MLM weight tying, fine-tune learnability, jit-ability.
"""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.models import (ErnieConfig, ErnieModel,
                               ErnieForSequenceClassification,
                               ErnieForPretraining)
from paddle_tpu.models.ernie import mlm_loss


def _cfg():
    return ErnieConfig.tiny(hidden_dropout_prob=0.0,
                            attention_probs_dropout_prob=0.0)


def test_padding_mask_isolates_pad_tokens():
    model = ErnieModel(_cfg())
    model.eval()
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 1000, (2, 10)).astype(np.int32)
    ids_padded = ids.copy()
    ids_padded[:, 7:] = 0  # pad_token_id
    seq_a, pooled_a = model(pt.to_tensor(ids_padded))
    # changing CONTENT of pad positions must not change non-pad outputs
    ids_garbage = ids_padded.copy()
    ids_garbage[:, 7:] = 999
    mask = (ids_padded != 0).astype(np.float32)
    seq_b, pooled_b = model(pt.to_tensor(ids_garbage),
                            attention_mask=pt.to_tensor(mask))
    np.testing.assert_allclose(np.asarray(seq_a.data)[:, :7],
                               np.asarray(seq_b.data)[:, :7], atol=1e-5)
    np.testing.assert_allclose(np.asarray(pooled_a.data),
                               np.asarray(pooled_b.data), atol=1e-5)


def test_pretraining_heads_and_weight_tying():
    cfg = _cfg()
    model = ErnieForPretraining(ErnieModel(cfg))
    model.eval()
    ids = np.random.RandomState(1).randint(1, 1000, (2, 8)).astype(np.int32)
    mlm_logits, nsp_logits = model(pt.to_tensor(ids))
    assert tuple(mlm_logits.shape) == (2, 8, cfg.vocab_size)
    assert tuple(nsp_logits.shape) == (2, 2)
    # MLM head reads the embedding matrix (tied): perturbing it moves logits
    labels = np.full((2, 8), -100)
    labels[0, 2] = 5
    loss = mlm_loss(mlm_logits, pt.to_tensor(labels))
    assert np.isfinite(float(loss))
    emb = model.ernie.embeddings.word_embeddings.weight
    # random perturbation (a constant shift would sit in LayerNorm's and
    # the zero-mean tied-projection's null space and change nothing)
    noise = np.random.RandomState(9).randn(*emb._data.shape) * 0.1
    emb._data = emb._data + jnp.asarray(noise, emb._data.dtype)
    mlm2, _ = model(pt.to_tensor(ids))
    assert np.abs(np.asarray(mlm2.data) - np.asarray(mlm_logits.data)).max() > 1e-3


def test_finetune_learns():
    cfg = _cfg()
    model = ErnieForSequenceClassification(ErnieModel(cfg), num_classes=2)
    model.train()
    rng = np.random.RandomState(2)
    # task: class = whether first token id is even
    ids = rng.randint(1, 1000, (32, 12)).astype(np.int32)
    y = (ids[:, 0] % 2).astype(np.int64)
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    first = last = None
    for _ in range(15):
        logits = model(pt.to_tensor(ids))
        loss = pt.nn.functional.cross_entropy(logits, pt.to_tensor(y)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first * 0.7, (first, last)


def test_pretraining_learns():
    # regression: the MLM head must stay on tape-tracked ops — raw jnp
    # on .data silently freezes training (caught by the e2e drive)
    cfg = _cfg()
    model = ErnieForPretraining(ErnieModel(cfg))
    model.train()
    rng = np.random.RandomState(4)
    ids = rng.randint(1, 1000, (8, 16)).astype(np.int32)
    labels = np.full((8, 16), -100)
    labels[:, 3] = ids[:, 3]
    masked = ids.copy()
    masked[:, 3] = 1
    opt = pt.optimizer.AdamW(learning_rate=2e-3,
                             parameters=model.parameters())
    first = last = None
    for _ in range(10):
        mlm, _ = model(pt.to_tensor(masked))
        loss = mlm_loss(mlm, pt.to_tensor(labels))
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first * 0.5, (first, last)


def test_ernie_jits():
    cfg = _cfg()
    model = ErnieModel(cfg)
    model.eval()
    ids = np.random.RandomState(3).randint(1, 1000, (2, 8)).astype(np.int32)

    from paddle_tpu import jit
    fn = jit.to_static(lambda t: model(t)[1])
    out = fn(pt.to_tensor(ids))
    ref = model(pt.to_tensor(ids))[1]
    np.testing.assert_allclose(np.asarray(out.data), np.asarray(ref.data),
                               atol=1e-5)

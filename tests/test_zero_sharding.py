"""ZeRO stage 1/2/3 layout + memory proofs (distributed/sharding.py and
llama make_train_step zero_stage).

Reference capability: fleet group-sharded stages
(dygraph_sharding_optimizer.py:48, group_sharded_stage2/3.py). The TPU
formulation is a layout; these tests prove the layout is real: shard
specs on the 8-device mesh, per-device bytes shrinking by the dp degree,
gradients reduce-scattered (not all-reduced to full) in the compiled
HLO, and numerics unchanged vs the replicated baseline.
"""
import re
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.models import llama as L
from paddle_tpu.parallel import init_hybrid_mesh


CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)


def _per_device_bytes(tree):
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "addressable_shards")]
    dev0 = leaves[0].addressable_shards[0].device
    total = 0
    for x in leaves:
        for sh in x.addressable_shards:
            if sh.device == dev0:
                total += sh.data.size * sh.data.dtype.itemsize
    return total


def _state(zero_stage, dp=8):
    hm = init_hybrid_mesh(dp=dp, pp=1, tp=1, set_global=False)
    with hm.mesh:
        step, init = L.make_train_step(CFG, hm.mesh,
                                       zero_stage=zero_stage)
        state = init(jax.random.PRNGKey(0))
        batch = L.make_batch(CFG, batch_size=8, seq_len=16, mesh=hm.mesh)
    return hm, step, state, batch


def test_zero1_opt_state_sharded_over_dp():
    hm, _, state, _ = _state(zero_stage=1)
    mu = state["opt"][0].mu  # adamw first moment, mirrors params
    lm_mu = mu["lm_head"]
    assert "dp" in jax.tree_util.tree_leaves(
        [lm_mu.sharding.spec])[0:] or "dp" in tuple(lm_mu.sharding.spec)
    # per-device bytes shrink ~8x vs replicated (scalars excluded)
    base = _per_device_bytes(_state(zero_stage=0)[2]["opt"])
    z1 = _per_device_bytes(state["opt"])
    assert z1 < base / 4, (z1, base)


def test_zero3_params_sharded_and_memory_shrinks():
    hm, _, state, _ = _state(zero_stage=3)
    specs = jax.tree_util.tree_map(
        lambda x: x.sharding.spec, state["params"])
    flat = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda s: isinstance(s, P))
    assert any("dp" in tuple(s) for s in flat if isinstance(s, P))
    base = _per_device_bytes(_state(zero_stage=0)[2]["params"])
    z3 = _per_device_bytes(state["params"])
    assert z3 < base / 4, (z3, base)


def test_zero2_grads_reduce_scattered_in_hlo():
    """Stage 2's claim: grads land in the dp-sharded layout via a
    scatter-style collective. GSPMD lowers reduce-scatter either as a
    literal reduce-scatter op (TPU) or as all-to-all + local add (the
    CPU SPMD partitioner); both prove the grads are never kept as a
    full replicated array at the optimizer update."""
    hm, step, state, batch = _state(zero_stage=2)
    with hm.mesh:
        compiled = jax.jit(step.__wrapped__, donate_argnums=(0,)).lower(
            state, batch).compile()
    hlo = compiled.as_text()
    assert ("reduce-scatter" in hlo) or ("all-to-all" in hlo), \
        "expected a scatter-style grad collective for ZeRO-2"
    # semantic check: the updated optimizer moments come out dp-sharded
    new_state, _ = step(state, batch)
    mu = new_state["opt"][0].mu["lm_head"]
    assert "dp" in tuple(mu.sharding.spec), mu.sharding


def _allgather_bytes(hlo):
    """Total bytes produced by all-gather instructions in an HLO text."""
    total = 0
    dt_bytes = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4}
    # HLO forms: %all-gather.2 = f32[64,256]{1,0} all-gather(...), and
    # the async pair on TPU: ... = (f32[..], f32[64,256]{..}) all-gather-start(
    # (count the result element, the second tuple member)
    for m in re.finditer(
            r"= (\w+)\[([0-9,]*)\]\S* all-gather\("
            r"|,\s*(\w+)\[([0-9,]*)\]\S*\) all-gather-start\(", hlo):
        dt = m.group(1) or m.group(3)
        dims = m.group(2) if m.group(2) is not None else m.group(4)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * dt_bytes.get(dt, 4)
    return total


def test_zero3_allgathers_params_at_use():
    """Stage 3's defining cost: dp-sharded parameters are all-gathered
    at their use sites (group_sharded_stage3.py's rebuild-on-forward).
    The compiled HLO must contain those gathers, and their total volume
    must be bounded — a sane placement gathers each param O(1) times
    per step (fwd + bwd/remat), not per-use-site."""
    hm0, step0, state0, batch = _state(zero_stage=0)
    hm3, step3, state3, _ = _state(zero_stage=3)

    def hlo_of(hm, step, state):
        with hm.mesh:
            return jax.jit(step.__wrapped__, donate_argnums=(0,)).lower(
                state, batch).compile().as_text()

    h0 = hlo_of(hm0, step0, state0)
    h3 = hlo_of(hm3, step3, state3)
    p_bytes = sum(x.size * x.dtype.itemsize for x in
                  jax.tree_util.tree_leaves(state0["params"]))
    b0 = _allgather_bytes(h0)
    b3 = _allgather_bytes(h3)
    # stage 3 must actually gather the params... (only a fraction of
    # p_bytes appears as explicit gathers: XLA keeps several params
    # SHARDED through their consumers — better than rebuilding — and
    # gathers under lax.scan count once statically)
    assert b3 > b0, (b0, b3)
    assert b3 >= p_bytes * 0.2, (b3, p_bytes)
    # ...but not explode: <= ~4x total param bytes per step (fwd + bwd
    # + remat re-gather + epsilon) — the silent failure this guards is
    # a per-use-site gather blowing the stage-3 memory/traffic win
    assert b3 <= 4 * p_bytes + b0, (b3, p_bytes, b0)


@pytest.mark.parametrize("stage", [1, 3])
def test_zero_numerics_match_replicated(stage):
    _, step0, state0, batch = _state(zero_stage=0)
    _, stepz, statez, _ = _state(zero_stage=stage)
    s0, l0 = step0(state0, batch)
    sz, lz = stepz(statez, batch)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(lz),
                               rtol=1e-5, atol=1e-6)
    p0 = jax.tree_util.tree_leaves(s0["params"])[0]
    pz = jax.tree_util.tree_leaves(sz["params"])[0]
    np.testing.assert_allclose(np.asarray(p0), np.asarray(pz),
                               rtol=1e-4, atol=1e-5)


def test_dp_shard_warns_instead_of_silent_noop():
    import paddle_tpu as pt
    from paddle_tpu.distributed.sharding import _dp_shard
    from paddle_tpu.parallel.mesh import init_hybrid_mesh as ihm
    ihm(dp=8, pp=1, tp=1, set_global=True)
    try:
        t = pt.to_tensor(np.zeros((7, 3), np.float32))  # 7 % 8 != 0
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            ok = _dp_shard(t)
        assert not ok
        assert any("replicated" in str(x.message) for x in w)
        with pytest.raises(ValueError, match="replicated"):
            _dp_shard(t, strict=True)
    finally:
        from paddle_tpu.parallel import mesh as _m
        _m._GLOBAL_MESH = None


def test_zero_spec_picks_first_free_divisible_dim():
    from paddle_tpu.distributed.sharding import zero_spec
    assert tuple(zero_spec(P(None, "tp"), (32, 64), 8)) == ("dp", "tp")
    assert tuple(zero_spec(P("tp"), (32, 64), 8)) == ("tp", "dp")
    assert zero_spec(P(), (7, 9), 8) is None
    assert zero_spec(P(), (), 8) is None
    # already dp-sharded arrays are DONE, not re-sharded on a second
    # dim (P('dp','dp') is invalid — the zero3 moments bug)
    assert zero_spec(P("dp", None), (32, 64), 8) is None


def test_zero3_moments_valid_at_small_dp():
    """Regression: zero3 at dp=2 used to stack a second 'dp' onto
    moments whose param spec already carried one (layer weights have a
    free dp-divisible dim left over) — an invalid PartitionSpec at
    init. The whole state must place cleanly and every spec use each
    axis at most once."""
    hm = init_hybrid_mesh(dp=2, pp=1, tp=1, set_global=False)
    with hm.mesh:
        _, init = L.make_train_step(CFG, hm.mesh, zero_stage=3)
        state = init(jax.random.PRNGKey(0))
    for leaf in jax.tree_util.tree_leaves(state):
        spec = tuple(leaf.sharding.spec)
        axes = [a for a in spec if a is not None]
        assert len(axes) == len(set(axes)), spec


def test_train_state_specs_match_placed_state():
    """The declared spec tree (what the sharding lint reads) and the
    actually placed state (what init_fn builds) are the same thing —
    leaf for leaf."""
    hm = init_hybrid_mesh(dp=8, pp=1, tp=1, set_global=False)
    with hm.mesh:
        _, init = L.make_train_step(CFG, hm.mesh, zero_stage=1)
        state = init(jax.random.PRNGKey(0))
    specs = L.train_state_specs(CFG, hm.mesh, zero_stage=1)
    flat_s = jax.tree_util.tree_leaves(state)
    flat_p = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda s: isinstance(s, P))
    assert len(flat_s) == len(flat_p)
    for leaf, spec in zip(flat_s, flat_p):
        assert tuple(leaf.sharding.spec) == tuple(spec), \
            (leaf.shape, leaf.sharding.spec, spec)

"""Static-analysis subsystem (ISSUE r9).

Detection is PROVEN, not assumed (the vacuous-pass lesson, ADVICE r5's
`test_export_int_scalar_const_dtype`): every lint pass and the paged-KV
invariant checker must (a) run clean on healthy flagship state and (b)
catch a deliberately seeded bug of the exact class it exists for —
f32-weight drift, host callbacks in decode loops, oversized host
pulls, diverging pipeline collectives, unbounded chunk-program sets,
corrupted refcounts, double-attached pages, stale defrag mappings,
non-TRASH dead-slot rows.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu._compat import shard_map
from paddle_tpu.analysis import (TRAIN_GEOMETRIES,
                                 CollectiveConsistencyPass,
                                 DonationAuditPass, DtypeDriftPass,
                                 GraphTarget, HbmPeakPass, HostSyncPass,
                                 KVInvariantError, RecompileHazardPass,
                                 ServingGeometry, Severity,
                                 ShardingLintPass, audit_defrag_plan,
                                 audit_serving_state,
                                 check_stage_consistency,
                                 collective_signature, engine_geometry,
                                 enumerate_chunk_programs,
                                 estimate_hbm_peak,
                                 flagship_train_objects,
                                 jit_donation_flags, pp_stage_targets,
                                 run_passes, scan_trip_counts,
                                 serving_targets, trace_graph,
                                 train_stage_targets, train_step_target,
                                 training_targets, xla_peak_bytes)
from paddle_tpu.inference.paged_kv import PagePool, apply_defrag
from paddle_tpu.models import llama as L
from paddle_tpu.serving import PrefixCache, ServingEngine

sds = jax.ShapeDtypeStruct
CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


def _errors(findings):
    return [f for f in findings if f.severity == Severity.ERROR]


# ---------------------------------------------------------------------------
# flagship graphs lint clean (the CLI's acceptance bar)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("model", ["llama", "qwen2_moe"])
def test_flagship_serving_graphs_lint_clean(model):
    targets = serving_targets(model)
    report = run_passes(
        [DtypeDriftPass(), HostSyncPass(), RecompileHazardPass()],
        targets)
    assert report.ran, "passes must actually run"
    assert report.ok, "\n".join(str(f) for f in report.errors)
    # the recompile pass PROVED a bound (info finding present), it did
    # not just fail to run
    assert any(f.pass_name == "recompile-hazard"
               and "proven bound" in f.message
               for f in report.findings)


def test_pp_stage_chunks_consistent():
    targets = pp_stage_targets()
    report = run_passes([CollectiveConsistencyPass()], targets)
    assert len(report.ran) == len(targets)
    assert report.ok


# ---------------------------------------------------------------------------
# dtype-drift: seeded mutations
# ---------------------------------------------------------------------------

def test_dtype_drift_catches_f32_weight_in_bf16_model():
    def bad(x, w):
        return (x @ w).astype(jnp.bfloat16)

    t = trace_graph("bad", bad,
                    (sds((4, 8), jnp.bfloat16), sds((8, 8), jnp.float32)),
                    compute_dtype=jnp.bfloat16)
    errs = _errors(DtypeDriftPass().run(t))
    assert errs and "dot_general" in errs[0].message

    def good(x, w):
        return x @ w

    t2 = trace_graph("good", good,
                     (sds((4, 8), jnp.bfloat16),
                      sds((8, 8), jnp.bfloat16)),
                     compute_dtype=jnp.bfloat16)
    assert not DtypeDriftPass().run(t2)


def test_dtype_drift_catches_f32_const_pollution():
    table = jnp.asarray(np.linspace(0, 1, 16, dtype=np.float32))

    def bad(x):
        return x * table      # f32 closure const forces the upcast

    t = trace_graph("bad", bad, (sds((4, 16), jnp.bfloat16),),
                    compute_dtype=jnp.bfloat16)
    errs = _errors(DtypeDriftPass().run(t))
    assert errs and "constant" in errs[0].message
    # the bf16-cast version of the same constant is clean
    table16 = table.astype(jnp.bfloat16)

    def good(x):
        return x * table16

    t2 = trace_graph("good", good, (sds((4, 16), jnp.bfloat16),),
                     compute_dtype=jnp.bfloat16)
    assert not DtypeDriftPass().run(t2)


def test_dtype_drift_scalar_eps_exempt_and_f64_flagged():
    def norm(x):
        # the idiomatic f32 island: explicit upcast, reduce, downcast
        xf = x.astype(jnp.float32)
        return (xf * lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True)
                               + 1e-5)).astype(x.dtype)

    t = trace_graph("norm", norm, (sds((4, 8), jnp.bfloat16),),
                    compute_dtype=jnp.bfloat16)
    assert not DtypeDriftPass().run(t)

    from jax.experimental import enable_x64
    with enable_x64():
        def f64fn(x):
            return x.astype(jnp.float64) * 2.0

        t2 = trace_graph("f64", f64fn, (sds((4,), jnp.float32),),
                         compute_dtype=jnp.bfloat16)
    errs = _errors(DtypeDriftPass().run(t2))
    assert errs and "float64" in errs[0].message


# ---------------------------------------------------------------------------
# host-sync: seeded mutations
# ---------------------------------------------------------------------------

def test_host_sync_catches_callback_in_decode_loop():
    def bad(x):
        def body(c, _):
            jax.debug.callback(lambda v: None, c)
            return c + 1, c

        return lax.scan(body, x, None, length=3)

    t = trace_graph("bad", bad, (sds((4,), jnp.float32),),
                    in_decode_loop=True)
    errs = _errors(HostSyncPass().run(t))
    assert errs and "callback" in errs[0].message
    assert errs[0].path and errs[0].path[0][0] == "scan"


def test_host_sync_catches_oversized_logits_pull():
    V = 256

    def bad_tick(x, w):
        return x @ w           # [S, V] f32 logits cross to the host

    t = trace_graph("bad", bad_tick,
                    (sds((4, 64), jnp.float32), sds((64, V), jnp.float32)),
                    slots=4, steps_per_call=1, in_decode_loop=True)
    errs = _errors(HostSyncPass().run(t))
    assert errs and "bytes/slot/step" in errs[0].message

    def good_tick(x, w):
        return jnp.argmax(x @ w, -1).astype(jnp.int32)  # [S] tokens

    t2 = trace_graph("good", good_tick,
                     (sds((4, 64), jnp.float32),
                      sds((64, V), jnp.float32)),
                     slots=4, steps_per_call=1, in_decode_loop=True)
    assert not HostSyncPass().run(t2)


def test_host_sync_prefill_exempt_from_pull_budget():
    """Prefill programs legitimately return logits once per prompt."""
    def prefill(x, w):
        return x @ w

    t = trace_graph("prefill", prefill,
                    (sds((1, 64), jnp.float32),
                     sds((64, 256), jnp.float32)),
                    slots=1, in_decode_loop=False)
    assert not HostSyncPass().run(t)


# ---------------------------------------------------------------------------
# collective-consistency: seeded mutations
# ---------------------------------------------------------------------------

def _two_device_mesh():
    devs = np.array(jax.devices()[:2])
    return Mesh(devs, ("x",))


def test_collective_divergence_caught():
    mesh = _two_device_mesh()

    def stage_a(x):
        return shard_map(lambda v: lax.psum(v, "x"), mesh=mesh,
                         in_specs=P("x"), out_specs=P())(x)

    def stage_b(x):
        return shard_map(
            lambda v: lax.ppermute(v, "x", [(0, 1), (1, 0)]),
            mesh=mesh, in_specs=P("x"), out_specs=P("x"))(x)

    x = jnp.ones((2, 4))
    ja = jax.make_jaxpr(stage_a)(x)
    jb = jax.make_jaxpr(stage_b)(x)
    assert collective_signature(ja) != collective_signature(jb)
    bad = check_stage_consistency([("s0", ja), ("s1", jb)])
    assert bad and bad[0][0] == "s1"
    assert not check_stage_consistency([("s0", ja), ("s1", ja)])


def test_collective_signature_counts_scan_trips():
    """Stages whose ring loops run different trip counts are NOT
    consistent even though the loop bodies match."""
    mesh = _two_device_mesh()

    def ring(x, hops):
        def inner(v):
            def body(c, _):
                return lax.ppermute(c, "x", [(0, 1), (1, 0)]), None

            out, _ = lax.scan(body, v, None, length=hops)
            return out

        return shard_map(inner, mesh=mesh, in_specs=P("x"),
                         out_specs=P("x"))(x)

    x = jnp.ones((2, 4))
    j3 = jax.make_jaxpr(lambda v: ring(v, 3))(x)
    j5 = jax.make_jaxpr(lambda v: ring(v, 5))(x)
    assert check_stage_consistency([("s0", j3), ("s1", j5)])


# ---------------------------------------------------------------------------
# recompile-hazard: proof + seeded hazard
# ---------------------------------------------------------------------------

def test_recompile_enumeration_matches_live_engine_geometry(params):
    """engine_geometry() (the static mirror) must agree with a real
    engine's extracted geometry — the proof is about the engine that
    actually runs, not a lookalike."""
    kw = dict(page_size=4, max_prompt_len=16, max_new_tokens_cap=16,
              prefill_chunk=8)
    with ServingEngine(params, CFG, max_batch=2, **kw) as eng:
        live = ServingGeometry.of_engine(eng)
    assert engine_geometry(max_batch=2, **kw) == live
    assert live.ragged and live.attach_quantum == 1


def test_recompile_pass_proves_flagship_bound_and_flags_hazard():
    """The ragged engine's program set is 1-2 per packed-width bucket
    BY CONSTRUCTION; the legacy bucketed model (still the oracle for
    the retained bucketed step fns) keeps flagging its hazard class,
    now with the offending value set spelled out."""
    from paddle_tpu.analysis import enumerate_tick_programs
    good = engine_geometry(page_size=4, max_prompt_len=16,
                           max_new_tokens_cap=16, prefill_chunk=8,
                           max_batch=4, decode_block=4)
    progs = enumerate_tick_programs(good)
    assert progs and all(len(v) <= 2 for v in progs.values())
    # both reachable widths are enumerated: S and S+budget
    assert set(progs) == {4, 12}
    t_good = trace_graph("geom", lambda x: x, (sds((1,), jnp.float32),),
                         meta={"geometry": good})
    found = RecompileHazardPass().run(t_good)
    assert not _errors(found)
    assert any("proven bound" in f.message for f in found)

    # seeded hazard through the LEGACY model: quantum 1 with a large
    # prompt/slot budget — the pre-r9 failure mode (attach grid off
    # the chunk grid); the error now carries the offending value set
    bad = ServingGeometry(page_size=8, pages_per_slot=40,
                          buckets=[32, 64, 128, 256],
                          attach_quantum=1, prefill_chunk=32)
    over = enumerate_chunk_programs(bad)
    assert any(len(v) > 16 for v in over.values())
    t = trace_graph("geom", lambda x: x, (sds((1,), jnp.float32),),
                    meta={"geometry": bad})
    errs = _errors(RecompileHazardPass().run(t))
    assert errs and "prefix_pages" in errs[0].message
    worst = max(over.values(), key=len)
    assert str(sorted(worst)) in errs[0].message  # offending set named


def test_engine_geometry_hazard_died_with_quantization(params):
    """The pre-r12 compile-storm geometry (tiny chunk against a big
    prompt budget — 38 programs where ≤16 was claimed) now compiles
    the SAME two programs as any other geometry: the ctor enumeration
    stays silent because the hazard is gone at the root, not because
    the check was dropped."""
    import warnings
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        eng = ServingEngine(params, CFG, max_batch=1, page_size=4,
                            max_prompt_len=128, max_new_tokens_cap=4,
                            prefill_chunk=4, check_invariants=False)
        geom = ServingGeometry.of_engine(eng)
        eng.close()
    assert not [x for x in w if "tick programs" in str(x.message)]
    from paddle_tpu.analysis import enumerate_tick_programs
    progs = enumerate_tick_programs(geom)
    assert all(len(v) <= 2 for v in progs.values())
    # the legacy dispatch model confirms this geometry WAS the hazard
    legacy = ServingGeometry(
        page_size=geom.page_size, pages_per_slot=geom.pages_per_slot,
        buckets=geom.buckets, attach_quantum=1, prefill_chunk=4)
    assert any(len(v) > 16
               for v in enumerate_chunk_programs(legacy).values())


def test_graph_lint_json_reports_serving_program_set(capsys):
    """graph_lint --json (and therefore --ci --json) carries the
    serving-suite program-set proof: per-width inventory plus the
    programs-per-bucket bound CI consumers gate on."""
    import importlib.util
    import json as _json
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "graph_lint.py")
    spec = importlib.util.spec_from_file_location("graph_lint", path)
    gl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gl)
    rc = gl.main(["--suite", "serving", "--json"])
    out = _json.loads(capsys.readouterr().out)
    assert rc == 0
    sp = out["serving_programs"]
    assert sp["programs_per_bucket"] <= 2
    assert sp["total"] >= 2
    assert all(len(progs) <= 2 for progs in sp["widths"].values())
    # r13: the observability block carries the SAME inventory dict the
    # runtime recompile sentinel reports as expected_programs — static
    # and runtime views share one schema
    sent = out["observability"]["sentinel"]
    assert sent["expected_programs"] == sp
    assert sent["metric"] == "paddle_serving_recompiles_total"
    # r15: the SPECULATIVE engine's inventory rides the same schema —
    # the static proof that the draft/verify tick programs keep the
    # per-bucket bound (exactly one verify program per mixed width)
    sps = out["serving_programs_spec"]
    assert sps["programs_per_bucket"] <= 2
    verify = [p for progs in sps["widths"].values() for p in progs
              if p.startswith("serving_tick[verify")]
    assert verify and all(len(progs) <= 2
                          for progs in sps["widths"].values())


def test_prefix_attach_is_exact(params):
    """r12: attach quantum is gone — the engine attaches EVERY cached
    full page (cap floor((n-1)/ps) only), whatever the chunk size."""
    with ServingEngine(params, CFG, max_batch=2, page_size=4,
                       max_prompt_len=16, max_new_tokens_cap=16,
                       prefill_chunk=8) as eng:
        assert eng.prefix_cache.attach_quantum == 1
        prompt = np.arange(1, 16, dtype=np.int32)      # 15 tokens
        eng.submit(prompt, 4).result(timeout=300)
        eng.submit(prompt, 4).result(timeout=300)
        c = eng.stats()["counters"]
    # floor(14/4) = 3 pages = 12 tokens attach — the r8-r11 quantum
    # (chunk grid: 2 pages) would have attached only 2
    assert c["prefix_pages_saved"] == 3
    assert c["prefix_hit_tokens"] == 12


# ---------------------------------------------------------------------------
# paged-KV invariant checker: healthy engine clean, mutations caught
# ---------------------------------------------------------------------------

def _eng(params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_prompt_len", 16)
    kw.setdefault("max_new_tokens_cap", 16)
    kw.setdefault("check_invariants", True)
    return ServingEngine(params, CFG, **kw)


def _ref(params, prompt, n):
    out = L.generate(params, jnp.asarray(prompt)[None], CFG,
                     max_new_tokens=n)
    return np.asarray(out)[0, len(prompt):]


def test_checker_clean_through_mixed_workload(params):
    rng = np.random.RandomState(0)
    with _eng(params, prefill_chunk=4) as eng:
        hs = [eng.submit(rng.randint(0, 256, (n,)).astype(np.int32), 5)
              for n in (12, 3, 15, 12, 7)]
        for h in hs:
            h.result(timeout=300)
        assert eng.audit() == []
    assert eng.pool.used_pages == 0


def test_checker_catches_refcount_corruption(params):
    prompt = np.arange(1, 13, dtype=np.int32)
    with _eng(params) as eng:
        eng.submit(prompt, 4).result(timeout=300)
        nodes = eng.prefix_cache.nodes()
        assert nodes
        nodes[0].refs += 1          # seeded bug: leaked reference
        bad = eng.audit()
        assert any(v.code == "refcount-drift" for v in bad)
        nodes[0].refs -= 1
        assert eng.audit() == []


def test_checker_catches_double_attached_page(params):
    """The page-aliasing bug class: one physical page in two live
    slots' rows without a backing trie refcount."""
    rng = np.random.RandomState(1)
    p1 = rng.randint(0, 256, (6,)).astype(np.int32)
    p2 = rng.randint(0, 256, (6,)).astype(np.int32)
    eng = _eng(params, check_invariants=False, tick_interval_s=0.01)
    try:
        h1 = eng.submit(p1, 12)
        h2 = eng.submit(p2, 12)
        it = iter(h1)
        next(it)                    # both slots live
        with eng._tick_lock:
            occ = eng.scheduler.occupied()
            if len(occ) == 2:
                (s1, r1), (s2, r2) = occ
                # double-attach: slot 2's first page aliased into
                # slot 1's row (classic mis-maintained page table)
                eng.scheduler.tables[s1, -1] = r2.pages[0]
                bad = audit_serving_state(eng.pool, eng.scheduler,
                                          eng.prefix_cache)
                assert any(v.code in ("share-uncached", "row-mismatch")
                           for v in bad)
                eng.scheduler.tables[s1, -1] = PagePool.TRASH
    finally:
        eng.close(drain=False)


def test_checker_catches_freelist_aliasing(params):
    prompt = np.arange(1, 9, dtype=np.int32)
    eng = _eng(params, check_invariants=False, tick_interval_s=0.01)
    try:
        h = eng.submit(prompt, 12)
        it = iter(h)
        next(it)
        with eng._tick_lock:
            occ = eng.scheduler.occupied()
            if occ:
                _, req = occ[0]
                page = req.pages[0]
                # seeded bug: a live page pushed back to the free list
                eng.pool._free.append(page)
                eng.pool._free_set.add(page)
                bad = audit_serving_state(eng.pool, eng.scheduler,
                                          eng.prefix_cache)
                assert any(v.code == "page-free-owned" for v in bad)
                eng.pool._free.remove(page)
                eng.pool._free_set.discard(page)
    finally:
        eng.close(drain=False)


def test_checker_catches_parked_row_leak(params):
    """A parked (mid chunked-prefill) slot whose scheduler row is not
    all-TRASH: the dead-slot contract the TPU pallas page loop depends
    on."""
    rng = np.random.RandomState(2)
    long_p = rng.randint(0, 256, (16,)).astype(np.int32)
    short_p = rng.randint(0, 256, (2,)).astype(np.int32)
    eng = _eng(params, prefill_chunk=4, max_batch=2,
               check_invariants=False, tick_interval_s=0.02)
    try:
        h_short = eng.submit(short_p, 24)
        it = iter(h_short)
        next(it)
        h_long = eng.submit(long_p, 4)
        seen = False
        for _ in range(400):
            time.sleep(0.002)
            with eng._tick_lock:
                parked = [(s, r) for s, r in eng.scheduler.occupied()
                          if r.table_row is not None]
                if parked:
                    seen = True
                    slot, req = parked[0]
                    # healthy parked state passes
                    assert audit_serving_state(
                        eng.pool, eng.scheduler,
                        eng.prefix_cache) == []
                    # seeded bug: one real entry leaks into the row
                    eng.scheduler.tables[slot, 0] = req.table_row[0]
                    bad = audit_serving_state(eng.pool, eng.scheduler,
                                              eng.prefix_cache)
                    assert any(v.code == "parked-row-live"
                               for v in bad)
                    eng.scheduler.tables[slot, 0] = PagePool.TRASH
                    break
            if h_long._req.done.is_set():
                break
        assert seen, "no parked slot observed — chunk too large?"
        h_long.result(timeout=300)
        h_short.result(timeout=300)
    finally:
        eng.close()


def test_defrag_plan_audit_catches_stale_mapping(params):
    prompt = np.arange(1, 13, dtype=np.int32)
    with _eng(params) as eng:
        eng.submit(prompt, 4).result(timeout=300)
        with eng._tick_lock:
            plan = eng.pool.defrag_plan()
            assert audit_defrag_plan(plan, eng.pool, eng.scheduler,
                                     eng.prefix_cache) == []
            # stale mapping: pretend a freed page is still being moved
            free_page = max(eng.pool.free_page_ids)
            stale = dict(plan)
            stale[free_page] = 1
            bad = audit_defrag_plan(stale, eng.pool, eng.scheduler,
                                    eng.prefix_cache)
            assert any(v.code == "defrag-stale-src" for v in bad)


def test_per_tick_checker_fails_engine_on_live_corruption(params):
    """Detection through the LIVE path: corrupt state under the tick
    lock and the next tick's audit kills the engine, surfacing
    KVInvariantError to every caller."""
    rng = np.random.RandomState(3)
    eng = _eng(params, tick_interval_s=0.01)
    try:
        eng.submit(rng.randint(0, 256, (9,)).astype(np.int32), 4) \
           .result(timeout=300)
        h = eng.submit(rng.randint(0, 256, (9,)).astype(np.int32), 24)
        it = iter(h)
        next(it)
        with eng._tick_lock:
            nodes = eng.prefix_cache.nodes()
            assert nodes
            nodes[0].refs += 3      # corruption the next tick must see
        with pytest.raises(KVInvariantError) as exc:
            h.result(timeout=300)
        # the raise names the engine geometry that produced it, so a
        # report from a dead engine is actionable without a repro
        assert "engine geometry:" in str(exc.value)
        assert "page_size=" in str(exc.value)
    finally:
        eng.close(drain=False)


# ---------------------------------------------------------------------------
# defrag while a chunk-prefill slot is parked (satellite)
# ---------------------------------------------------------------------------

def test_defrag_while_chunk_prefill_parked(params):
    """Defrag running while a slot is parked mid chunked-prefill must
    remap the dead-slot scheduler row (all-TRASH, trivially), the
    STASHED real row, and the prefix-cached pages consistently — the
    parked request then completes byte-exact and the checker stays
    green throughout."""
    rng = np.random.RandomState(4)
    churn = rng.randint(0, 256, (10,)).astype(np.int32)
    long_p = rng.randint(0, 256, (16,)).astype(np.int32)
    short_p = rng.randint(0, 256, (2,)).astype(np.int32)
    eng = _eng(params, prefill_chunk=4, max_batch=3,
               tick_interval_s=0.02)
    try:
        # all three admit together (3 free slots): churn takes the LOW
        # pages and retires after 2 tokens — while the long prompt is
        # still parked mid chunked-prefill — leaving a low hole that
        # gives defrag real work across: a live decode row (short), a
        # parked slot's STASHED row (long), and churn's now-cached
        # prefix pages in the trie
        h_churn = eng.submit(churn, 2)
        h_short = eng.submit(short_p, 30)
        h_long = eng.submit(long_p, 6)
        moved = None
        for _ in range(800):
            time.sleep(0.002)
            with eng._tick_lock:
                parked = [r for _, r in eng.scheduler.occupied()
                          if r.table_row is not None]
                fragmented = (h_churn._req.done.is_set()
                              and bool(eng.pool.defrag_plan()))
            if parked and fragmented:
                moved = eng.defragment()   # audits plan + result
                break
            if h_long._req.done.is_set():
                break
        assert moved is not None, \
            "never saw a parked slot + fragmentation window"
        assert moved > 0
        out_long = h_long.result(timeout=300)
        out_short = h_short.result(timeout=300)
        assert eng.audit() == []
    finally:
        eng.close()
    np.testing.assert_array_equal(out_long, _ref(params, long_p, 6))
    np.testing.assert_array_equal(out_short, _ref(params, short_p, 30))


# ---------------------------------------------------------------------------
# training-graph lint (ISSUE 5 tentpole): clean flagships + seeded defects
# ---------------------------------------------------------------------------

def _train_passes():
    return [ShardingLintPass(), DonationAuditPass(), HbmPeakPass(),
            CollectiveConsistencyPass()]


@pytest.fixture(scope="module")
def train_targets():
    """One traced target per geometry, shared across the mutation
    tests — tracing is the expensive part; each test gets a fresh META
    copy via _fresh() so seeded mutations cannot leak between tests."""
    return {g: train_step_target(g) for g in TRAIN_GEOMETRIES}


def _fresh(t):
    meta = {k: (list(v) if isinstance(v, list) else v)
            for k, v in t.meta.items()}
    return GraphTarget(name=t.name, jaxpr=t.jaxpr,
                       compute_dtype=t.compute_dtype, meta=meta)


def test_training_targets_cover_required_geometries_and_lint_clean():
    assert {"dp", "dp_mp", "pp_1f1b", "zero1"} <= set(TRAIN_GEOMETRIES)
    targets = training_targets()
    report = run_passes(_train_passes(), targets)
    assert len(report.ran) == 4 * len(targets)
    assert report.ok, "\n".join(str(f) for f in report.errors)
    # non-vacuous: the estimator actually reported, the donation audit
    # actually inventoried, on every train-step target
    steps = [t.name for t in targets if "train_step" in t.name]
    assert len(steps) == len(TRAIN_GEOMETRIES)
    for name in steps:
        assert any(f.pass_name == "hbm-peak" and f.graph == name
                   for f in report.findings)
        assert any(f.pass_name == "donation-audit" and f.graph == name
                   for f in report.findings)


def test_sharding_lint_catches_replicated_large_weight(train_targets):
    t = _fresh(train_targets["dp_mp"])
    i = t.meta["invar_labels"].index("[0]['params']['embed']")
    t.meta["in_specs"][i] = P()           # seeded: spec quietly lost
    errs = _errors(ShardingLintPass(replicated_bytes=16 * 1024).run(t))
    assert errs and "replicated" in errs[0].message
    # clean at the same threshold with the real spec
    assert not _errors(ShardingLintPass(replicated_bytes=16 * 1024)
                       .run(_fresh(train_targets["dp_mp"])))


def test_sharding_lint_catches_unknown_mesh_axis(train_targets):
    """The Engine-vs-llama axis-name class: 'mp' on a 'tp' mesh shards
    nothing while reading as if it did."""
    t = _fresh(train_targets["dp_mp"])
    i = t.meta["invar_labels"].index("[0]['params']['lm_head']")
    t.meta["in_specs"][i] = P(None, "mp")
    errs = _errors(ShardingLintPass().run(t))
    assert errs and "mp" in errs[0].message


def test_sharding_lint_catches_uncovered_opt_state(train_targets):
    t = _fresh(train_targets["zero1"])
    i = next(i for i, (c, sp) in enumerate(
        zip(t.meta["invar_classes"], t.meta["in_specs"]))
        if c == "opt" and "dp" in str(sp))
    t.meta["in_specs"][i] = P()           # seeded: ZeRO dim dropped
    errs = _errors(ShardingLintPass().run(t))
    assert errs and "zero_spec" in errs[0].message
    assert not _errors(ShardingLintPass().run(_fresh(train_targets["zero1"])))


def test_donation_audit_catches_undonated_opt_state(train_targets):
    t = _fresh(train_targets["dp"])
    i = next(i for i, (c, v) in enumerate(
        zip(t.meta["invar_classes"], t.jaxpr.jaxpr.invars))
        if c == "opt" and np.prod(v.aval.shape or (1,)) > 64)
    t.meta["donated_invars"][i] = False   # seeded: donation dropped
    errs = _errors(DonationAuditPass().run(t))
    assert errs and "NON-donated" in errs[0].message


def test_donation_audit_warns_on_unaliasable_donation():
    def f(a):
        return a.astype(jnp.bfloat16)     # no f32 output to alias onto

    t = trace_graph("bad", f, (sds((64, 64), jnp.float32),),
                    meta={"donated_invars": [True],
                          "invar_labels": ["a"],
                          "invar_classes": ["param"]})
    warns = [x for x in DonationAuditPass().run(t)
             if x.severity == Severity.WARNING]
    assert warns and "alias" in warns[0].message


def test_train_donation_flags_match_live_lowering():
    """The declared donation meta must equal what jax actually stamps
    into the step's lowering (tf.aliasing_output) — the
    engine_geometry-vs-live-engine lesson applied to donation."""
    target, step_fn, state, batch = flagship_train_objects()
    flags = jit_donation_flags(step_fn, state, batch)
    assert list(flags) == list(target.meta["donated_invars"])
    n_state = len(jax.tree_util.tree_leaves(state))
    assert sum(flags) == n_state          # whole state donated, batch not


def test_donation_flags_survive_unused_arg_pruning():
    """jit's default keep_unused=False drops unused flat args from the
    lowered @main; the parsed flags must still align with the CALLER's
    flat signature (a step with one dead state leaf used to shift every
    flag after it)."""
    def f(a, b, c):                       # b is dead
        return a * 2.0 + c

    j = jax.jit(f, donate_argnums=(0, 2))
    x = jax.ShapeDtypeStruct((4,), jnp.float32)
    import warnings
    with warnings.catch_warnings():
        # one output can alias only one donor; jax warns about the other
        warnings.simplefilter("ignore")
        flags = jit_donation_flags(j, x, x, x)
    assert len(flags) == 3                # full signature, not kept args
    assert flags[1] is False              # the dead arg is not donated
    assert flags[0] or flags[2]           # a real donor kept its flag
    # misaligned meta must be a loud lint error, not an IndexError
    closed = jax.make_jaxpr(f)(x, x, x)
    t = GraphTarget(name="pruned", jaxpr=closed,
                    meta={"donated_invars": [True]})
    errs = _errors(DonationAuditPass().run(t))
    assert errs and "misaligned" in errs[0].message


def test_collective_pass_catches_dropped_psum_in_dp_variant():
    mesh = _two_device_mesh()

    def with_psum(x):
        return shard_map(lambda v: lax.psum(v * 2, "x"), mesh=mesh,
                         in_specs=P("x"), out_specs=P())(x)

    def without_psum(x):                  # seeded: grad psum dropped
        return shard_map(lambda v: v * 2, mesh=mesh,
                         in_specs=P("x"), out_specs=P("x"))(x)

    x = jnp.ones((2, 4))
    group = {"stage_group": "llama.dp_grads", "stage_count": 2}
    ta = GraphTarget(name="dp0", jaxpr=jax.make_jaxpr(with_psum)(x),
                     meta=dict(group))
    tb = GraphTarget(name="dp1", jaxpr=jax.make_jaxpr(without_psum)(x),
                     meta=dict(group))
    report = run_passes([CollectiveConsistencyPass()], [ta, tb])
    assert not report.ok
    assert "psum" in str(report.errors[0])


def test_train_stage_chunks_consistent_and_trip_mismatch_caught():
    targets = train_stage_targets()
    report = run_passes([CollectiveConsistencyPass()], targets)
    assert len(report.ran) == len(targets) and report.ok
    # seeded: one chunk scans a different layer count (bad partition)
    cfg1 = L.LlamaConfig.tiny(use_flash_attention=False, remat=False)

    def chunk(n_layers):
        p = jax.eval_shape(lambda: jax.tree_util.tree_map(
            lambda a: jnp.zeros((n_layers,) + a.shape[1:], a.dtype),
            L.abstract_params(cfg1)["layers"]))
        x = sds((2, 8, cfg1.hidden_size), cfg1.dtype)
        return jax.make_jaxpr(
            lambda pp, h: L._scan_layers(pp, h, cfg1, None,
                                         remat=False))(p, x)

    group = {"stage_group": "bad.pp", "stage_count": 2,
             "signature_include_loops": True}
    ta = GraphTarget(name="c0", jaxpr=chunk(1), meta=dict(group))
    tb = GraphTarget(name="c1", jaxpr=chunk(2), meta=dict(group))
    report2 = run_passes([CollectiveConsistencyPass()], [ta, tb])
    assert not report2.ok


def test_1f1b_schedule_trip_count_checked_and_mutation_caught(train_targets):
    from paddle_tpu.parallel.pipeline_1f1b import schedule_ticks
    assert schedule_ticks(2, 4, 2) == 11
    t = _fresh(train_targets["pp_1f1b"])
    assert t.meta["expected_scan_trips"] == 11
    assert 11 in scan_trip_counts(t.jaxpr)   # the check is non-vacuous
    assert not _errors(CollectiveConsistencyPass().run(t))
    t.meta["expected_scan_trips"] = 13       # seeded: schedule desync
    errs = _errors(CollectiveConsistencyPass().run(t))
    assert errs and "trip count" in errs[0].message


@pytest.mark.parametrize("geom,model", [("pp2_zb", "zb"),
                                        ("pp4_async", "1f1b"),
                                        ("pp2_dp2_zb", "zb"),
                                        ("pp2_tp2_async", "1f1b")])
def test_async_schedule_trip_count_checked_and_mutation_caught(
        train_targets, geom, model):
    """The rank-asymmetric schedules are traced targets too: the
    schedule scan lives INSIDE the shard_map body and the trip-count
    rule still sees it (type-based jaxpr walk); a tick-arithmetic
    desync is caught exactly like the lockstep one."""
    from paddle_tpu.parallel.pipeline_1f1b import schedule_ticks
    g = TRAIN_GEOMETRIES[geom]
    T = schedule_ticks(g["pp"], g["microbatches"], g["vpp"],
                       schedule=model)
    t = _fresh(train_targets[geom])
    assert t.meta["expected_scan_trips"] == T
    assert T in scan_trip_counts(t.jaxpr)
    assert not _errors(CollectiveConsistencyPass().run(t))
    t.meta["expected_scan_trips"] = T + 1    # seeded: schedule desync
    errs = _errors(CollectiveConsistencyPass().run(t))
    assert errs and "trip count" in errs[0].message


def test_async_targets_per_pass_mutations(train_targets):
    """One seeded mutation per training pass on the rank-asymmetric
    targets — the shard_map program form must not blind any of them."""
    # sharding-lint: decorative axis name on a param spec
    t = _fresh(train_targets["pp4_async"])
    i = t.meta["invar_labels"].index("[0]['params']['lm_head']")
    t.meta["in_specs"][i] = P(None, "mp")
    errs = _errors(ShardingLintPass().run(t))
    assert errs and "mp" in errs[0].message
    # donation-audit: dropped donation on a large opt leaf
    t = _fresh(train_targets["pp2_zb"])
    i = next(i for i, (c, v) in enumerate(
        zip(t.meta["invar_classes"], t.jaxpr.jaxpr.invars))
        if c == "opt" and np.prod(v.aval.shape or (1,)) > 64)
    t.meta["donated_invars"][i] = False
    errs = _errors(DonationAuditPass().run(t))
    assert errs and "NON-donated" in errs[0].message
    # hbm-peak: the estimator walks the shard_map program and a budget
    # breach still fires
    t = _fresh(train_targets["pp4_async"])
    t.meta["hbm_budget_bytes"] = 1024
    errs = _errors(HbmPeakPass().run(t))
    assert errs and "budget" in errs[0].message
    # all three clean un-mutated
    for geom in ("pp2_zb", "pp4_async"):
        for p in (ShardingLintPass(), DonationAuditPass(),
                  CollectiveConsistencyPass()):
            assert not _errors(p.run(_fresh(train_targets[geom]))), \
                (geom, p.name)


def test_graph_lint_json_reports_schedule_inventory(capsys):
    """graph_lint --json carries the pipeline-schedule trip/phase
    inventory next to the serving program inventory — one diffable
    schema — and it agrees with the schedule builder's own counts."""
    import importlib.util
    import json as _json
    import os
    from paddle_tpu.analysis.training_graphs import schedule_inventory
    from paddle_tpu.parallel.pipeline_async import build_schedule
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "graph_lint.py")
    spec = importlib.util.spec_from_file_location("graph_lint", path)
    gl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gl)
    rc = gl.main(["--suite", "training", "--json"])
    out = _json.loads(capsys.readouterr().out)
    assert rc == 0
    inv = out["pipeline_schedules"]
    assert inv == schedule_inventory()
    assert inv["schema"] == "paddle_tpu.schedule_inventory/1"
    assert {"pp_1f1b", "pp2_zb", "pp4_async"} <= set(inv["geometries"])
    zb = inv["geometries"]["pp2_zb"]
    sched = build_schedule(2, 5, 1, "zb")
    assert zb["ticks"] == sched.ticks
    assert zb["phases"] == sched.op_counts()
    assert zb["phases"]["W"] == 2 * 5          # one W per stage per mb
    assert zb["efficiency"] == pytest.approx(sched.efficiency, abs=1e-6)


# ---------------------------------------------------------------------------
# HBM peak estimator: XLA accuracy pin + drift + budget mutations
# ---------------------------------------------------------------------------

def test_hbm_estimator_within_10pct_of_xla(tmp_path):
    """The acceptance pin: static estimate vs the compiled flagship
    llama train step's own accounting (memory_analysis — the
    cost_analysis introspection family), within ±10%."""
    target, step_fn, state, batch = flagship_train_objects()
    est = estimate_hbm_peak(target)
    # compile under the ambient matmul precision the conftest pins for
    # the whole suite ("highest") — the setting every numeric test
    # actually runs this step under; overriding to "default" here makes
    # the CPU backend pick a dot lowering with ~2MiB of extra temp
    # scratch the estimator (rightly) doesn't model. The compile goes
    # through a private EMPTY persistent-cache dir: the shared cache's
    # key ignores the matmul-precision context, so a stale entry
    # lowered under a different precision would silently substitute its
    # own buffer assignment for the fresh one this test measures
    # (disabling jax_enable_compilation_cache mid-process does not
    # reliably stop reads — measured).
    cache_dir = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", str(tmp_path))
        compiled = step_fn.lower(state, batch).compile()
    finally:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    xla = xla_peak_bytes(compiled)
    if xla is None:
        pytest.skip("backend exposes no memory_analysis")
    rel = abs(est.peak_bytes - xla) / xla
    assert rel <= 0.10, (est.peak_bytes, xla, rel)
    # the estimate is not a coincidence of ignoring donation: dropping
    # the donation model (old state held to the end) must visibly
    # drift the estimate out of tolerance
    target.meta["donated_invars"] = [False] * len(
        target.meta["donated_invars"])
    est_bad = estimate_hbm_peak(target)
    assert abs(est_bad.peak_bytes - xla) / xla > 0.10, \
        (est_bad.peak_bytes, xla)
    # top contributors are real values with real sizes
    assert est.top and all(b > 0 for b, _ in est.top)


def test_hbm_budget_breach_flagged(train_targets):
    t = _fresh(train_targets["dp"])
    t.meta["hbm_budget_bytes"] = 1 << 40
    assert not _errors(HbmPeakPass().run(t))
    t2 = _fresh(train_targets["dp"])
    t2.meta["hbm_budget_bytes"] = 1024
    errs = _errors(HbmPeakPass().run(t2))
    assert errs and "budget" in errs[0].message


# ---------------------------------------------------------------------------
# fixes the training lint surfaced
# ---------------------------------------------------------------------------

def test_gradscaler_unscale_is_one_host_sync_and_still_detects_inf():
    """amp.GradScaler.unscale_ used to pull one bool per PARAMETER per
    step (the host-sync pass's bug class); it now reduces once. The
    semantics must survive the rewrite: finite grads pass, a single inf
    grad flips found_inf and skips the optimizer step."""
    import paddle_tpu as pt
    from paddle_tpu.amp import GradScaler

    lin = pt.nn.Linear(4, 4)
    opt = pt.optimizer.SGD(learning_rate=0.1,
                           parameters=lin.parameters())
    scaler = GradScaler(init_loss_scaling=8.0)
    x = pt.to_tensor(np.ones((2, 4), np.float32))
    scaler.scale((lin(x) ** 2).mean()).backward()
    scaler.unscale_(opt)
    assert scaler._found_inf is False
    grads = [p._grad for p in opt._param_list if p._grad is not None]
    assert grads
    grads[0]._data = jnp.full_like(grads[0]._data, np.inf)
    scaler.unscale_(opt)
    assert scaler._found_inf is True
    w_before = np.asarray(lin.weight.data).copy()
    scaler.step(opt)                       # must SKIP the update
    np.testing.assert_array_equal(np.asarray(lin.weight.data), w_before)


def test_zero_spec_never_duplicates_axis():
    """Regression for the zero3-then-zero1 double placement: a spec
    already carrying the dp axis must not get it again on another dim
    (P('dp', 'dp') is not a valid sharding)."""
    from paddle_tpu.distributed.sharding import zero_spec
    assert zero_spec(P("dp", None), (32, 64), 2) is None
    assert zero_spec(P(None, "dp"), (32, 64), 2) is None
    assert tuple(zero_spec(P(None, "tp"), (32, 64), 2)) == ("dp", "tp")


def test_group_sharded_parallel_unknown_level_lists_valid_levels():
    import paddle_tpu as pt
    from paddle_tpu import distributed as dist
    m = pt.nn.Linear(4, 4)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    with pytest.raises(ValueError, match="p_g_os"):
        dist.group_sharded_parallel(m, opt, level="stage2")


# ---------------------------------------------------------------------------
# source lint
# ---------------------------------------------------------------------------

def test_source_lint_rules_and_noqa(tmp_path):
    from paddle_tpu.analysis.source_lint import lint_file
    f = tmp_path / "m.py"
    f.write_text(
        "import os\n"
        "import sys  # noqa: F401\n"
        "from typing import Optional\n"
        "x = None\n"
        "ok = x == None\n"
        "def g(a=[]):\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"
        "        pass\n"
        "    return os.sep\n")
    rules = sorted(r for r, _, _ in lint_file(f))
    assert rules == ["B006", "E711", "E722", "F401"]  # sys suppressed


def test_source_lint_unused_local_rule(tmp_path):
    """F841: plain never-read locals flag; closures, underscores,
    tuple unpacking, class attributes and noqa lines do not."""
    from paddle_tpu.analysis.source_lint import lint_file
    f = tmp_path / "m.py"
    f.write_text(
        "def f():\n"
        "    dead = 1\n"
        "    sup = 2  # noqa: F841\n"
        "    _scratch = 3\n"
        "    a, b = 4, 5\n"
        "    kept = 6\n"
        "    class C:\n"
        "        attr = 7\n"
        "    def inner():\n"
        "        return kept + C.attr\n"
        "    return inner()\n")
    hits = [(r, ln) for r, ln, _ in lint_file(f) if r == "F841"]
    assert hits == [("F841", 2)], hits


def test_repo_source_lint_clean():
    from paddle_tpu.analysis.source_lint import lint_tree
    import pathlib
    root = pathlib.Path(__file__).resolve().parent.parent
    findings = lint_tree(root)
    assert findings == [], "\n".join(map(str, findings))

"""Static-analysis subsystem (ISSUE r9).

Detection is PROVEN, not assumed (the vacuous-pass lesson, ADVICE r5's
`test_export_int_scalar_const_dtype`): every lint pass and the paged-KV
invariant checker must (a) run clean on healthy flagship state and (b)
catch a deliberately seeded bug of the exact class it exists for —
f32-weight drift, host callbacks in decode loops, oversized host
pulls, diverging pipeline collectives, unbounded chunk-program sets,
corrupted refcounts, double-attached pages, stale defrag mappings,
non-TRASH dead-slot rows.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu._compat import shard_map
from paddle_tpu.analysis import (CollectiveConsistencyPass,
                                 DtypeDriftPass, HostSyncPass,
                                 KVInvariantError, RecompileHazardPass,
                                 ServingGeometry, Severity,
                                 audit_defrag_plan, audit_serving_state,
                                 check_stage_consistency,
                                 collective_signature, engine_geometry,
                                 enumerate_chunk_programs,
                                 pp_stage_targets, run_passes,
                                 serving_targets, trace_graph)
from paddle_tpu.inference.paged_kv import PagePool, apply_defrag
from paddle_tpu.models import llama as L
from paddle_tpu.serving import PrefixCache, ServingEngine

sds = jax.ShapeDtypeStruct
CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


def _errors(findings):
    return [f for f in findings if f.severity == Severity.ERROR]


# ---------------------------------------------------------------------------
# flagship graphs lint clean (the CLI's acceptance bar)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("model", ["llama", "qwen2_moe"])
def test_flagship_serving_graphs_lint_clean(model):
    targets = serving_targets(model)
    report = run_passes(
        [DtypeDriftPass(), HostSyncPass(), RecompileHazardPass()],
        targets)
    assert report.ran, "passes must actually run"
    assert report.ok, "\n".join(str(f) for f in report.errors)
    # the recompile pass PROVED a bound (info finding present), it did
    # not just fail to run
    assert any(f.pass_name == "recompile-hazard"
               and "proven bound" in f.message
               for f in report.findings)


def test_pp_stage_chunks_consistent():
    targets = pp_stage_targets()
    report = run_passes([CollectiveConsistencyPass()], targets)
    assert len(report.ran) == len(targets)
    assert report.ok


# ---------------------------------------------------------------------------
# dtype-drift: seeded mutations
# ---------------------------------------------------------------------------

def test_dtype_drift_catches_f32_weight_in_bf16_model():
    def bad(x, w):
        return (x @ w).astype(jnp.bfloat16)

    t = trace_graph("bad", bad,
                    (sds((4, 8), jnp.bfloat16), sds((8, 8), jnp.float32)),
                    compute_dtype=jnp.bfloat16)
    errs = _errors(DtypeDriftPass().run(t))
    assert errs and "dot_general" in errs[0].message

    def good(x, w):
        return x @ w

    t2 = trace_graph("good", good,
                     (sds((4, 8), jnp.bfloat16),
                      sds((8, 8), jnp.bfloat16)),
                     compute_dtype=jnp.bfloat16)
    assert not DtypeDriftPass().run(t2)


def test_dtype_drift_catches_f32_const_pollution():
    table = jnp.asarray(np.linspace(0, 1, 16, dtype=np.float32))

    def bad(x):
        return x * table      # f32 closure const forces the upcast

    t = trace_graph("bad", bad, (sds((4, 16), jnp.bfloat16),),
                    compute_dtype=jnp.bfloat16)
    errs = _errors(DtypeDriftPass().run(t))
    assert errs and "constant" in errs[0].message
    # the bf16-cast version of the same constant is clean
    table16 = table.astype(jnp.bfloat16)

    def good(x):
        return x * table16

    t2 = trace_graph("good", good, (sds((4, 16), jnp.bfloat16),),
                     compute_dtype=jnp.bfloat16)
    assert not DtypeDriftPass().run(t2)


def test_dtype_drift_scalar_eps_exempt_and_f64_flagged():
    def norm(x):
        # the idiomatic f32 island: explicit upcast, reduce, downcast
        xf = x.astype(jnp.float32)
        return (xf * lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True)
                               + 1e-5)).astype(x.dtype)

    t = trace_graph("norm", norm, (sds((4, 8), jnp.bfloat16),),
                    compute_dtype=jnp.bfloat16)
    assert not DtypeDriftPass().run(t)

    from jax.experimental import enable_x64
    with enable_x64():
        def f64fn(x):
            return x.astype(jnp.float64) * 2.0

        t2 = trace_graph("f64", f64fn, (sds((4,), jnp.float32),),
                         compute_dtype=jnp.bfloat16)
    errs = _errors(DtypeDriftPass().run(t2))
    assert errs and "float64" in errs[0].message


# ---------------------------------------------------------------------------
# host-sync: seeded mutations
# ---------------------------------------------------------------------------

def test_host_sync_catches_callback_in_decode_loop():
    def bad(x):
        def body(c, _):
            jax.debug.callback(lambda v: None, c)
            return c + 1, c

        return lax.scan(body, x, None, length=3)

    t = trace_graph("bad", bad, (sds((4,), jnp.float32),),
                    in_decode_loop=True)
    errs = _errors(HostSyncPass().run(t))
    assert errs and "callback" in errs[0].message
    assert errs[0].path and errs[0].path[0][0] == "scan"


def test_host_sync_catches_oversized_logits_pull():
    V = 256

    def bad_tick(x, w):
        return x @ w           # [S, V] f32 logits cross to the host

    t = trace_graph("bad", bad_tick,
                    (sds((4, 64), jnp.float32), sds((64, V), jnp.float32)),
                    slots=4, steps_per_call=1, in_decode_loop=True)
    errs = _errors(HostSyncPass().run(t))
    assert errs and "bytes/slot/step" in errs[0].message

    def good_tick(x, w):
        return jnp.argmax(x @ w, -1).astype(jnp.int32)  # [S] tokens

    t2 = trace_graph("good", good_tick,
                     (sds((4, 64), jnp.float32),
                      sds((64, V), jnp.float32)),
                     slots=4, steps_per_call=1, in_decode_loop=True)
    assert not HostSyncPass().run(t2)


def test_host_sync_prefill_exempt_from_pull_budget():
    """Prefill programs legitimately return logits once per prompt."""
    def prefill(x, w):
        return x @ w

    t = trace_graph("prefill", prefill,
                    (sds((1, 64), jnp.float32),
                     sds((64, 256), jnp.float32)),
                    slots=1, in_decode_loop=False)
    assert not HostSyncPass().run(t)


# ---------------------------------------------------------------------------
# collective-consistency: seeded mutations
# ---------------------------------------------------------------------------

def _two_device_mesh():
    devs = np.array(jax.devices()[:2])
    return Mesh(devs, ("x",))


def test_collective_divergence_caught():
    mesh = _two_device_mesh()

    def stage_a(x):
        return shard_map(lambda v: lax.psum(v, "x"), mesh=mesh,
                         in_specs=P("x"), out_specs=P())(x)

    def stage_b(x):
        return shard_map(
            lambda v: lax.ppermute(v, "x", [(0, 1), (1, 0)]),
            mesh=mesh, in_specs=P("x"), out_specs=P("x"))(x)

    x = jnp.ones((2, 4))
    ja = jax.make_jaxpr(stage_a)(x)
    jb = jax.make_jaxpr(stage_b)(x)
    assert collective_signature(ja) != collective_signature(jb)
    bad = check_stage_consistency([("s0", ja), ("s1", jb)])
    assert bad and bad[0][0] == "s1"
    assert not check_stage_consistency([("s0", ja), ("s1", ja)])


def test_collective_signature_counts_scan_trips():
    """Stages whose ring loops run different trip counts are NOT
    consistent even though the loop bodies match."""
    mesh = _two_device_mesh()

    def ring(x, hops):
        def inner(v):
            def body(c, _):
                return lax.ppermute(c, "x", [(0, 1), (1, 0)]), None

            out, _ = lax.scan(body, v, None, length=hops)
            return out

        return shard_map(inner, mesh=mesh, in_specs=P("x"),
                         out_specs=P("x"))(x)

    x = jnp.ones((2, 4))
    j3 = jax.make_jaxpr(lambda v: ring(v, 3))(x)
    j5 = jax.make_jaxpr(lambda v: ring(v, 5))(x)
    assert check_stage_consistency([("s0", j3), ("s1", j5)])


# ---------------------------------------------------------------------------
# recompile-hazard: proof + seeded hazard
# ---------------------------------------------------------------------------

def test_recompile_enumeration_matches_live_engine_geometry(params):
    """engine_geometry() (the static mirror) must agree with a real
    engine's extracted geometry — the proof is about the engine that
    actually runs, not a lookalike."""
    kw = dict(page_size=4, max_prompt_len=16, max_new_tokens_cap=16,
              prefill_chunk=8)
    with ServingEngine(params, CFG, max_batch=2, **kw) as eng:
        live = ServingGeometry.of_engine(eng)
    assert engine_geometry(**kw) == live


def test_recompile_pass_proves_flagship_bound_and_flags_hazard():
    good = engine_geometry(page_size=4, max_prompt_len=16,
                           max_new_tokens_cap=16, prefill_chunk=8)
    progs = enumerate_chunk_programs(good)
    assert progs and all(len(v) <= 16 for v in progs.values())

    # seeded hazard: quantum 1 with a large prompt/slot budget — the
    # pre-r9 failure mode (attach grid off the chunk grid)
    bad = ServingGeometry(page_size=8, pages_per_slot=40,
                          buckets=[32, 64, 128, 256],
                          attach_quantum=1, prefill_chunk=32)
    over = enumerate_chunk_programs(bad)
    assert any(len(v) > 16 for v in over.values())
    t = trace_graph("geom", lambda x: x, (sds((1,), jnp.float32),),
                    meta={"geometry": bad})
    errs = _errors(RecompileHazardPass().run(t))
    assert errs and "prefix_pages" in errs[0].message


def test_engine_warns_on_unbounded_chunk_program_set(params):
    """A too-small chunk against a big prompt budget means one compile
    per chunk start inside serving ticks — the ctor must say so at
    construction, not stall under traffic."""
    import warnings
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        eng = ServingEngine(params, CFG, max_batch=1, page_size=4,
                            max_prompt_len=128, max_new_tokens_cap=4,
                            prefill_chunk=4, check_invariants=False)
        eng.close()
    assert any("chunk-prefill programs" in str(x.message) for x in w)
    # sane geometry: no warning
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        eng = ServingEngine(params, CFG, max_batch=1, page_size=4,
                            max_prompt_len=16, max_new_tokens_cap=4,
                            prefill_chunk=8, check_invariants=False)
        eng.close()
    assert not [x for x in w
                if "chunk-prefill programs" in str(x.message)]


def test_chunked_attach_quantum_sits_on_chunk_grid(params):
    """The r9 fix: with prefill_chunk=N the attach quantum is a
    multiple of N/page_size, so chunk starts stay on one grid."""
    with ServingEngine(params, CFG, max_batch=2, page_size=4,
                       max_prompt_len=16, max_new_tokens_cap=16,
                       prefill_chunk=8) as eng:
        q = eng.prefix_cache.attach_quantum
        assert q % (8 // 4) == 0


# ---------------------------------------------------------------------------
# paged-KV invariant checker: healthy engine clean, mutations caught
# ---------------------------------------------------------------------------

def _eng(params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_prompt_len", 16)
    kw.setdefault("max_new_tokens_cap", 16)
    kw.setdefault("check_invariants", True)
    return ServingEngine(params, CFG, **kw)


def _ref(params, prompt, n):
    out = L.generate(params, jnp.asarray(prompt)[None], CFG,
                     max_new_tokens=n)
    return np.asarray(out)[0, len(prompt):]


def test_checker_clean_through_mixed_workload(params):
    rng = np.random.RandomState(0)
    with _eng(params, prefill_chunk=4) as eng:
        hs = [eng.submit(rng.randint(0, 256, (n,)).astype(np.int32), 5)
              for n in (12, 3, 15, 12, 7)]
        for h in hs:
            h.result(timeout=300)
        assert eng.audit() == []
    assert eng.pool.used_pages == 0


def test_checker_catches_refcount_corruption(params):
    prompt = np.arange(1, 13, dtype=np.int32)
    with _eng(params) as eng:
        eng.submit(prompt, 4).result(timeout=300)
        nodes = eng.prefix_cache.nodes()
        assert nodes
        nodes[0].refs += 1          # seeded bug: leaked reference
        bad = eng.audit()
        assert any(v.code == "refcount-drift" for v in bad)
        nodes[0].refs -= 1
        assert eng.audit() == []


def test_checker_catches_double_attached_page(params):
    """The page-aliasing bug class: one physical page in two live
    slots' rows without a backing trie refcount."""
    rng = np.random.RandomState(1)
    p1 = rng.randint(0, 256, (6,)).astype(np.int32)
    p2 = rng.randint(0, 256, (6,)).astype(np.int32)
    eng = _eng(params, check_invariants=False, tick_interval_s=0.01)
    try:
        h1 = eng.submit(p1, 12)
        h2 = eng.submit(p2, 12)
        it = iter(h1)
        next(it)                    # both slots live
        with eng._tick_lock:
            occ = eng.scheduler.occupied()
            if len(occ) == 2:
                (s1, r1), (s2, r2) = occ
                # double-attach: slot 2's first page aliased into
                # slot 1's row (classic mis-maintained page table)
                eng.scheduler.tables[s1, -1] = r2.pages[0]
                bad = audit_serving_state(eng.pool, eng.scheduler,
                                          eng.prefix_cache)
                assert any(v.code in ("share-uncached", "row-mismatch")
                           for v in bad)
                eng.scheduler.tables[s1, -1] = PagePool.TRASH
    finally:
        eng.close(drain=False)


def test_checker_catches_freelist_aliasing(params):
    prompt = np.arange(1, 9, dtype=np.int32)
    eng = _eng(params, check_invariants=False, tick_interval_s=0.01)
    try:
        h = eng.submit(prompt, 12)
        it = iter(h)
        next(it)
        with eng._tick_lock:
            occ = eng.scheduler.occupied()
            if occ:
                _, req = occ[0]
                page = req.pages[0]
                # seeded bug: a live page pushed back to the free list
                eng.pool._free.append(page)
                eng.pool._free_set.add(page)
                bad = audit_serving_state(eng.pool, eng.scheduler,
                                          eng.prefix_cache)
                assert any(v.code == "page-free-owned" for v in bad)
                eng.pool._free.remove(page)
                eng.pool._free_set.discard(page)
    finally:
        eng.close(drain=False)


def test_checker_catches_parked_row_leak(params):
    """A parked (mid chunked-prefill) slot whose scheduler row is not
    all-TRASH: the dead-slot contract the TPU pallas page loop depends
    on."""
    rng = np.random.RandomState(2)
    long_p = rng.randint(0, 256, (16,)).astype(np.int32)
    short_p = rng.randint(0, 256, (2,)).astype(np.int32)
    eng = _eng(params, prefill_chunk=4, max_batch=2,
               check_invariants=False, tick_interval_s=0.02)
    try:
        h_short = eng.submit(short_p, 24)
        it = iter(h_short)
        next(it)
        h_long = eng.submit(long_p, 4)
        seen = False
        for _ in range(400):
            time.sleep(0.002)
            with eng._tick_lock:
                parked = [(s, r) for s, r in eng.scheduler.occupied()
                          if r.table_row is not None]
                if parked:
                    seen = True
                    slot, req = parked[0]
                    # healthy parked state passes
                    assert audit_serving_state(
                        eng.pool, eng.scheduler,
                        eng.prefix_cache) == []
                    # seeded bug: one real entry leaks into the row
                    eng.scheduler.tables[slot, 0] = req.table_row[0]
                    bad = audit_serving_state(eng.pool, eng.scheduler,
                                              eng.prefix_cache)
                    assert any(v.code == "parked-row-live"
                               for v in bad)
                    eng.scheduler.tables[slot, 0] = PagePool.TRASH
                    break
            if h_long._req.done.is_set():
                break
        assert seen, "no parked slot observed — chunk too large?"
        h_long.result(timeout=300)
        h_short.result(timeout=300)
    finally:
        eng.close()


def test_defrag_plan_audit_catches_stale_mapping(params):
    prompt = np.arange(1, 13, dtype=np.int32)
    with _eng(params) as eng:
        eng.submit(prompt, 4).result(timeout=300)
        with eng._tick_lock:
            plan = eng.pool.defrag_plan()
            assert audit_defrag_plan(plan, eng.pool, eng.scheduler,
                                     eng.prefix_cache) == []
            # stale mapping: pretend a freed page is still being moved
            free_page = max(eng.pool.free_page_ids)
            stale = dict(plan)
            stale[free_page] = 1
            bad = audit_defrag_plan(stale, eng.pool, eng.scheduler,
                                    eng.prefix_cache)
            assert any(v.code == "defrag-stale-src" for v in bad)


def test_per_tick_checker_fails_engine_on_live_corruption(params):
    """Detection through the LIVE path: corrupt state under the tick
    lock and the next tick's audit kills the engine, surfacing
    KVInvariantError to every caller."""
    rng = np.random.RandomState(3)
    eng = _eng(params, tick_interval_s=0.01)
    try:
        eng.submit(rng.randint(0, 256, (9,)).astype(np.int32), 4) \
           .result(timeout=300)
        h = eng.submit(rng.randint(0, 256, (9,)).astype(np.int32), 24)
        it = iter(h)
        next(it)
        with eng._tick_lock:
            nodes = eng.prefix_cache.nodes()
            assert nodes
            nodes[0].refs += 3      # corruption the next tick must see
        with pytest.raises(KVInvariantError):
            h.result(timeout=300)
    finally:
        eng.close(drain=False)


# ---------------------------------------------------------------------------
# defrag while a chunk-prefill slot is parked (satellite)
# ---------------------------------------------------------------------------

def test_defrag_while_chunk_prefill_parked(params):
    """Defrag running while a slot is parked mid chunked-prefill must
    remap the dead-slot scheduler row (all-TRASH, trivially), the
    STASHED real row, and the prefix-cached pages consistently — the
    parked request then completes byte-exact and the checker stays
    green throughout."""
    rng = np.random.RandomState(4)
    churn = rng.randint(0, 256, (10,)).astype(np.int32)
    long_p = rng.randint(0, 256, (16,)).astype(np.int32)
    short_p = rng.randint(0, 256, (2,)).astype(np.int32)
    eng = _eng(params, prefill_chunk=4, max_batch=3,
               tick_interval_s=0.02)
    try:
        # all three admit together (3 free slots): churn takes the LOW
        # pages and retires after 2 tokens — while the long prompt is
        # still parked mid chunked-prefill — leaving a low hole that
        # gives defrag real work across: a live decode row (short), a
        # parked slot's STASHED row (long), and churn's now-cached
        # prefix pages in the trie
        h_churn = eng.submit(churn, 2)
        h_short = eng.submit(short_p, 30)
        h_long = eng.submit(long_p, 6)
        moved = None
        for _ in range(800):
            time.sleep(0.002)
            with eng._tick_lock:
                parked = [r for _, r in eng.scheduler.occupied()
                          if r.table_row is not None]
                fragmented = (h_churn._req.done.is_set()
                              and bool(eng.pool.defrag_plan()))
            if parked and fragmented:
                moved = eng.defragment()   # audits plan + result
                break
            if h_long._req.done.is_set():
                break
        assert moved is not None, \
            "never saw a parked slot + fragmentation window"
        assert moved > 0
        out_long = h_long.result(timeout=300)
        out_short = h_short.result(timeout=300)
        assert eng.audit() == []
    finally:
        eng.close()
    np.testing.assert_array_equal(out_long, _ref(params, long_p, 6))
    np.testing.assert_array_equal(out_short, _ref(params, short_p, 30))


# ---------------------------------------------------------------------------
# source lint
# ---------------------------------------------------------------------------

def test_source_lint_rules_and_noqa(tmp_path):
    from paddle_tpu.analysis.source_lint import lint_file
    f = tmp_path / "m.py"
    f.write_text(
        "import os\n"
        "import sys  # noqa: F401\n"
        "from typing import Optional\n"
        "x = None\n"
        "ok = x == None\n"
        "def g(a=[]):\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"
        "        pass\n"
        "    return os.sep\n")
    rules = sorted(r for r, _, _ in lint_file(f))
    assert rules == ["B006", "E711", "E722", "F401"]  # sys suppressed


def test_repo_source_lint_clean():
    from paddle_tpu.analysis.source_lint import lint_tree
    import pathlib
    root = pathlib.Path(__file__).resolve().parent.parent
    findings = lint_tree(root)
    assert findings == [], "\n".join(map(str, findings))

"""MoE: gating, dispatch numerics, expert-parallel sharding, Qwen2-MoE.

Mirrors the reference's MoE coverage (moe_layer.py gates + dispatch) on the
8-device CPU mesh.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu.incubate.moe import functional as MF
from paddle_tpu.incubate.moe import MoELayer, NaiveGate, SwitchGate
from paddle_tpu.parallel import init_hybrid_mesh


def test_top_k_gating_shapes_and_norm():
    S, E, C = 16, 4, 8
    logits = jax.random.normal(jax.random.PRNGKey(0), (S, E))
    dispatch, combine, aux = MF.top_k_gating(logits, top_k=2, capacity=C)
    assert dispatch.shape == (S, E, C) and combine.shape == (S, E, C)
    # each token occupies at most top_k slots, one-hot
    per_token = dispatch.sum(axis=(1, 2))
    assert (per_token <= 2 + 1e-6).all()
    # combine weights sum to <= 1 (== 1 when nothing dropped)
    cw = combine.sum(axis=(1, 2))
    assert (cw <= 1 + 1e-5).all()
    # per-expert load never exceeds capacity
    load = dispatch.sum(axis=(0, 2))
    assert (load <= C + 1e-6).all()
    assert np.isfinite(float(aux))


def test_capacity_drops_overflow():
    # all tokens want expert 0; capacity 2 keeps exactly 2
    S, E = 8, 4
    logits = jnp.tile(jnp.array([[10.0, 0.0, 0.0, 0.0]]), (S, 1))
    dispatch, combine, _ = MF.top_k_gating(logits, top_k=1, capacity=2)
    assert float(dispatch[:, 0].sum()) == 2.0


def test_moe_ffn_matches_manual_expert_compute():
    """Dense-dispatch output == looping over experts by hand."""
    key = jax.random.PRNGKey(1)
    S, D, F, E = 8, 4, 8, 2
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (S, D))
    gate_w = jax.random.normal(ks[1], (D, E))
    w_gate = jax.random.normal(ks[2], (E, D, F)) * 0.1
    w_up = jax.random.normal(ks[3], (E, D, F)) * 0.1
    w_down = jax.random.normal(ks[4], (E, F, D)) * 0.1

    # top-1, capacity = S so nothing drops
    y, _ = MF.moe_ffn(x, gate_w, w_gate, w_up, w_down, top_k=1,
                      capacity_factor=float(E))

    probs = jax.nn.softmax(x @ gate_w, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    ref = np.zeros((S, D), np.float32)
    for s in range(S):
        e = int(idx[s])
        h = jax.nn.silu(x[s] @ w_gate[e]) * (x[s] @ w_up[e])
        ref[s] = np.asarray((h @ w_down[e]) * probs[s, e])
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)


def test_moe_layer_forward_and_aux():
    layer = MoELayer(d_model=8, num_expert=4, d_hidden=16,
                     gate={"type": "switch", "top_k": 1})
    x = jnp.ones((2, 6, 8), jnp.float32)
    y = layer(x)
    y = y.data if hasattr(y, "data") else y
    assert y.shape == (2, 6, 8)
    assert np.isfinite(float(layer.l_aux))


def test_moe_ffn_expert_parallel_matches_single_device():
    """ep-sharded dispatch == unsharded numerics (GSPMD all_to_all path)."""
    key = jax.random.PRNGKey(2)
    S, D, F, E = 16, 4, 8, 4
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (S, D))
    gate_w = jax.random.normal(ks[1], (D, E))
    w_gate = jax.random.normal(ks[2], (E, D, F)) * 0.1
    w_up = jax.random.normal(ks[3], (E, D, F)) * 0.1
    w_down = jax.random.normal(ks[4], (E, F, D)) * 0.1

    y_ref, _ = MF.moe_ffn(x, gate_w, w_gate, w_up, w_down, top_k=2)

    hm = init_hybrid_mesh(dp=2, ep=4, set_global=False)
    from jax.sharding import NamedSharding, PartitionSpec as P
    with hm.mesh:
        we = {k: jax.device_put(v, NamedSharding(hm.mesh, P("ep", None, None)))
              for k, v in {"g": w_gate, "u": w_up, "d": w_down}.items()}
        f = jax.jit(lambda x: MF.moe_ffn(
            x, gate_w, we["g"], we["u"], we["d"], top_k=2, ep_axis="ep")[0])
        y_ep = f(x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_qwen2_moe_train_step_decreases_loss():
    from paddle_tpu.models import qwen2_moe as Q
    cfg = Q.Qwen2MoeConfig.tiny(dtype=jnp.float32, remat=False,
                                use_flash_attention=False)
    hm = init_hybrid_mesh(dp=2, ep=2, tp=2, set_global=False)
    with hm.mesh:
        step, init = Q.make_train_step(cfg, hm.mesh)
        state = init(jax.random.PRNGKey(0))
        batch = Q.make_batch(cfg, batch_size=4, seq_len=16, mesh=hm.mesh)
        _, l0 = step(state, batch)
        state = _
        losses = [float(l0)]
        for _i in range(3):
            state, l = step(state, batch)
            losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_qwen2_moe_dropless_impl_trains():
    """cfg.moe_impl='dropless' routes the MoE FFN through the authored
    grouped-GEMM kernel; the train step must run and improve."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import qwen2_moe as Q
    from paddle_tpu.parallel import init_hybrid_mesh
    hm = init_hybrid_mesh(dp=1, pp=1, tp=1, set_global=False)
    cfg = Q.Qwen2MoeConfig.tiny(dtype=jnp.float32, remat=False,
                                use_flash_attention=False,
                                moe_impl="dropless")
    with hm.mesh:
        step, init = Q.make_train_step(cfg, hm.mesh)
        state = init(jax.random.PRNGKey(0))
        batch = Q.make_batch(cfg, batch_size=2, seq_len=16, mesh=hm.mesh)
        losses = []
        for _ in range(5):
            state, loss = step(state, batch)
            losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# r5: MoE decode (KV cache + generate)
# ---------------------------------------------------------------------------

def test_moe_generate_matches_stepwise_full_forward():
    """Greedy cached decode must equal re-running the FULL forward on
    the growing sequence each step. Precondition: the config routes
    without capacity drops (tiny's cf*top_k/E == 1 guarantees it) —
    decode always routes drop-free, while a TRAINING forward with a
    drop-inducing capacity_factor intentionally differs (drops are a
    training regularizer; see forward_with_cache)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.models import qwen2_moe as Q

    cfg = Q.Qwen2MoeConfig.tiny(dtype=jnp.float32, remat=False,
                                use_flash_attention=False)
    params = Q.init_params(cfg, jax.random.PRNGKey(0))
    B, T0, N = 2, 9, 6
    prompt = jax.random.randint(jax.random.PRNGKey(1), (B, T0), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    out = Q.generate(params, prompt, cfg, N, temperature=0.0)
    assert out.shape == (B, T0 + N)

    seq = prompt
    for _ in range(N):
        logits, _ = Q.forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_moe_generate_eos_latches():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.models import qwen2_moe as Q

    cfg = Q.Qwen2MoeConfig.tiny(dtype=jnp.float32, remat=False,
                                use_flash_attention=False)
    params = Q.init_params(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    out = np.asarray(Q.generate(params, prompt, cfg, 8,
                                temperature=0.0, eos_token_id=7))
    for row in out:
        hits = np.where(row[5:] == 7)[0]
        if hits.size:
            assert np.all(row[5 + hits[0]:] == 7), row

"""paddle.hub / paddle.batch / sysconfig / _C_ops shims.

Reference tests: test/legacy_test/test_hub.py, test_batch.py.
"""
import numpy as np
import pytest

import paddle_tpu as pt


def test_hub_local_roundtrip(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "def tiny_mlp(width=4):\n"
        "    'builds a tiny mlp'\n"
        "    import paddle_tpu as pt\n"
        "    return pt.nn.Linear(width, width)\n"
        "def _private():\n"
        "    pass\n")
    from paddle_tpu import hub
    assert hub.list(str(tmp_path)) == ["tiny_mlp"]
    assert "tiny mlp" in hub.help(str(tmp_path), "tiny_mlp")
    layer = hub.load(str(tmp_path), "tiny_mlp", width=6)
    assert layer.in_features == 6


def test_hub_remote_refuses():
    from paddle_tpu import hub
    with pytest.raises(NotImplementedError, match="egress"):
        hub.load("some/repo", "model", source="github")


def test_batch_reader():
    r = pt.batch(lambda: iter(range(7)), batch_size=3)
    assert [list(b) for b in r()] == [[0, 1, 2], [3, 4, 5], [6]]
    r2 = pt.batch(lambda: iter(range(7)), batch_size=3, drop_last=True)
    assert [list(b) for b in r2()] == [[0, 1, 2], [3, 4, 5]]
    with pytest.raises(ValueError):
        pt.batch(lambda: iter([]), batch_size=0)


def test_sysconfig_paths():
    from paddle_tpu import sysconfig
    import os
    assert os.path.isdir(sysconfig.get_include())
    assert sysconfig.get_lib().endswith("build")


def test_c_ops_shim_dispatches():
    from paddle_tpu import _C_ops
    x = pt.to_tensor(np.asarray([[1.0, 2.0]], np.float32))
    y = pt.to_tensor(np.asarray([[3.0], [4.0]], np.float32))
    out = _C_ops.matmul(x, y)
    np.testing.assert_allclose(np.asarray(out.data), [[11.0]])
    with pytest.raises(AttributeError):
        _C_ops.definitely_not_an_op

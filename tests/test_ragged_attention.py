"""Ragged paged-attention kernel + one-program serving tick (ISSUE r12).

Verification story, bottom up:

* the Pallas kernel (interpret mode off-TPU) is BITWISE-equal to the
  dense-gather reference on seeded ragged batches — mixed prefill and
  decode spans, empty slots, partial tail pages, post-defrag
  (scattered, non-monotone) page lists;
* the packed (work-proportional) formulation the engine's CPU ticks
  route through is bitwise-equal to the slot-major reference, padding
  rows exactly zero;
* the engine built on the tick keeps greedy outputs bitwise-equal to
  ``generate()`` in every cache state — cold, warm full-prefix hit,
  partial-prefix hit, chunked prefill, post-defrag;
* the paged-KV invariant checker stays clean through a ragged-tick
  bench-shaped run (mixed admissions, chunked prefill, prefix sharing,
  mid-stream defrag).

The slow tier pins the ragged_ab bench acceptance: one-program tick
latency at parity (or better) with the legacy bucketed path, with a
strictly smaller compiled-program set.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.ops.pallas.ragged_paged_attention import (
    ragged_paged_attention, ragged_paged_attention_packed)
from paddle_tpu.serving import ServingEngine

CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


import functools


@functools.lru_cache(maxsize=None)
def _gen_jit(n):
    return jax.jit(lambda p, t: L.generate(p, t, CFG, max_new_tokens=n))


def _ref(params, prompt, n):
    out = _gen_jit(n)(params, jnp.asarray(prompt)[None])
    return np.asarray(out)[0, len(prompt):]


# ---------------------------------------------------------------------------
# kernel vs dense-gather reference: bitwise on seeded ragged batches
# ---------------------------------------------------------------------------

def _ragged_case(seed, S=4, Tq=6, H=4, Hkv=2, Dh=8, ps=4, P=24, pps=5,
                 scatter_tables=False):
    """One seeded ragged batch: mixed prefill spans (q_len>1), decode
    steps (q_len=1), an empty slot (q_len=0), partial tail pages
    (kv_len % page_size != 0), TRASH entries past the covered range."""
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(S, Tq, H, Dh).astype(np.float32))
    kp = jnp.asarray(rng.randn(Hkv, P, ps, Dh).astype(np.float32))
    vp = jnp.asarray(rng.randn(Hkv, P, ps, Dh).astype(np.float32))
    kv_max = pps * ps
    q_len = np.zeros((S,), np.int32)
    kv_len = np.zeros((S,), np.int32)
    for s in range(S):
        kind = s % 3          # 0: prefill span, 1: decode, 2: empty
        if kind == 0:
            q_len[s] = rng.randint(2, Tq + 1)
            kv_len[s] = rng.randint(q_len[s], kv_max + 1)
        elif kind == 1:
            q_len[s] = 1
            kv_len[s] = rng.randint(1, kv_max + 1)
    if scatter_tables:
        # post-defrag shape: page ids scattered anywhere in the pool,
        # non-monotone per row (defrag remaps rows entry-by-entry)
        ids = rng.permutation(P - 1)[: S * pps] + 1
    else:
        ids = np.arange(1, S * pps + 1)
    tables = ids.reshape(S, pps).astype(np.int32)
    for s in range(S):
        covered = -(-int(kv_len[s]) // ps)
        tables[s, covered:] = 0              # TRASH past the span
    return (q, kp, vp, jnp.asarray(q_len), jnp.asarray(kv_len),
            jnp.asarray(tables))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_kernel_matches_reference_bitwise(seed):
    """Pallas kernel (interpret off-TPU) vs dense-gather reference:
    BITWISE on mixed prefill+decode batches with empty slots and
    partial tail pages."""
    case = _ragged_case(seed)
    out_k = ragged_paged_attention(*case, impl="pallas")
    out_r = ragged_paged_attention(*case, impl="dense")
    assert out_k.dtype == out_r.dtype
    np.testing.assert_array_equal(np.asarray(out_k), np.asarray(out_r))


def test_kernel_matches_reference_post_defrag_page_lists():
    """Scattered, non-monotone page tables (the shape defrag remaps
    produce) change nothing: the kernel walks the table, not an
    arithmetic page layout."""
    case = _ragged_case(7, scatter_tables=True)
    out_k = ragged_paged_attention(*case, impl="pallas")
    out_r = ragged_paged_attention(*case, impl="dense")
    np.testing.assert_array_equal(np.asarray(out_k), np.asarray(out_r))


def test_empty_batch_and_full_pages():
    """Degenerate geometries: every slot empty (all-zero output), and a
    span exactly filling its last page (no partial tail)."""
    q, kp, vp, _, _, tables = _ragged_case(3)
    zeros = jnp.zeros((4,), jnp.int32)
    out = ragged_paged_attention(q, kp, vp, zeros, zeros, tables,
                                 impl="pallas")
    assert not np.asarray(out).any()
    q_len = jnp.asarray([4, 1, 2, 1], jnp.int32)
    kv_len = jnp.asarray([8, 4, 20, 12], jnp.int32)   # all % ps == 0
    a = ragged_paged_attention(q, kp, vp, q_len, kv_len, tables,
                               impl="pallas")
    b = ragged_paged_attention(q, kp, vp, q_len, kv_len, tables,
                               impl="dense")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_packed_matches_slot_major_bitwise():
    """The work-proportional packed formulation (the engine's off-TPU
    tick path) against the slot-major reference: bitwise, with padding
    rows (slot sentinel S) exactly zero."""
    rng = np.random.RandomState(11)
    _, kp, vp, _, _, tables = _ragged_case(11, scatter_tables=True)
    S, Tq, H, Dh = 4, 3, 4, 8
    q_len = jnp.asarray([3, 1, 0, 2], jnp.int32)
    kv_len = jnp.asarray([9, 6, 0, 2], jnp.int32)
    # packed stream: slot 0's 3-token span, slot 1's decode token, one
    # padding token (sentinel S), slot 3's 2-token span
    tok_slot = jnp.asarray([0, 0, 0, 1, S, 3, 3], jnp.int32)
    tok_qoff = jnp.asarray([0, 1, 2, 0, 0, 0, 1], jnp.int32)
    qpk = jnp.asarray(rng.randn(7, H, Dh).astype(np.float32))
    out_p = ragged_paged_attention_packed(
        qpk, kp, vp, tok_slot, tok_qoff, q_len, kv_len, tables, tq=Tq,
        impl="packed")
    out_d = ragged_paged_attention_packed(
        qpk, kp, vp, tok_slot, tok_qoff, q_len, kv_len, tables, tq=Tq,
        impl="dense")
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_d))
    assert not np.asarray(out_p)[4].any()    # padding row is zero


def test_bottom_right_causal_prefill_equals_whole():
    """Chunked-prefill exactness at the kernel level: running a prompt
    as two ragged spans (KV written first, bottom-right causal) gives
    the SAME bits for the second span's rows as one whole-prompt span —
    the property the engine's chunked prefill rests on."""
    rng = np.random.RandomState(5)
    Hkv, Dh, ps, P, pps = 2, 8, 4, 10, 4
    H, n, split = 4, 10, 6
    kp0 = jnp.zeros((Hkv, P, ps, Dh), jnp.float32)
    vp0 = jnp.zeros((Hkv, P, ps, Dh), jnp.float32)
    k_new = rng.randn(n, Hkv, Dh).astype(np.float32)
    v_new = rng.randn(n, Hkv, Dh).astype(np.float32)
    q = rng.randn(n, H, Dh).astype(np.float32)
    table = np.zeros((1, pps), np.int32)
    table[0, : -(-n // ps)] = np.arange(1, -(-n // ps) + 1)
    tab = jnp.asarray(table)

    def write(kp, vp, lo, hi):
        pos = np.arange(lo, hi)
        pages = table[0, pos // ps]
        kp = kp.at[:, pages, pos % ps].set(
            np.moveaxis(k_new[lo:hi], 1, 0))
        vp = vp.at[:, pages, pos % ps].set(
            np.moveaxis(v_new[lo:hi], 1, 0))
        return kp, vp

    # whole prompt: one span of n rows
    kp, vp = write(kp0, vp0, 0, n)
    whole = ragged_paged_attention(
        jnp.asarray(q)[None], kp, vp, jnp.asarray([n], jnp.int32),
        jnp.asarray([n], jnp.int32), tab, impl="pallas")
    # two chunks: rows split.. attend over written prefix + own span
    kp, vp = write(kp0, vp0, 0, split)
    kp, vp = write(kp, vp, split, n)
    part = ragged_paged_attention(
        jnp.asarray(q[split:])[None], kp, vp,
        jnp.asarray([n - split], jnp.int32), jnp.asarray([n], jnp.int32),
        tab, impl="pallas")
    np.testing.assert_array_equal(np.asarray(whole)[0, split:],
                                  np.asarray(part)[0, : n - split])


# ---------------------------------------------------------------------------
# tiled flash-combine walk (r16): bitwise vs the tiled reference,
# ulp-at-row-scale contract vs the one-shot kernel, O(tile) scratch
# ---------------------------------------------------------------------------

from paddle_tpu.ops.pallas.ragged_paged_attention import (  # noqa: E402
    ONE_SHOT_VMEM_BUDGET, TILED_ULP_BOUND, default_kv_tile_pages,
    tiled_ulp_error, vmem_scratch_bytes)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("tile", [1, 3, 5])
def test_tiled_kernel_matches_tiled_reference_bitwise(seed, tile):
    """The tiled Pallas kernel (double-buffered DMA walk, interpret
    off-TPU) is BITWISE-equal to the tiled dense reference — the same
    ``_flash_tile`` math at two call sites, the one-shot kernel's own
    verification story replayed. tile=3 does not divide pps=5 (ragged
    last tile); tile=5 is the whole table in one tile."""
    case = _ragged_case(seed)
    out_k = ragged_paged_attention(*case, impl="pallas",
                                   kv_tile_pages=tile)
    out_r = ragged_paged_attention(*case, impl="dense",
                                   kv_tile_pages=tile)
    assert out_k.dtype == out_r.dtype
    np.testing.assert_array_equal(np.asarray(out_k), np.asarray(out_r))


def test_tiled_kernel_post_defrag_and_degenerate_slots():
    """Scattered page tables, kv_len=0 (dead slot -> exact zeros),
    kv_len=1 and single-page slots through the tiled walk."""
    case = _ragged_case(7, scatter_tables=True)
    a = ragged_paged_attention(*case, impl="pallas", kv_tile_pages=2)
    b = ragged_paged_attention(*case, impl="dense", kv_tile_pages=2)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    q, kp, vp, _, _, tables = _ragged_case(3)
    zeros = jnp.zeros((4,), jnp.int32)
    out = ragged_paged_attention(q, kp, vp, zeros, zeros, tables,
                                 impl="pallas", kv_tile_pages=2)
    assert not np.asarray(out).any()
    # kv_len 1 and single-page (kv_len <= page_size) slots
    q_len = jnp.asarray([1, 1, 1, 1], jnp.int32)
    kv_len = jnp.asarray([1, 4, 2, 3], jnp.int32)
    a = ragged_paged_attention(q, kp, vp, q_len, kv_len, tables,
                               impl="pallas", kv_tile_pages=2)
    b = ragged_paged_attention(q, kp, vp, q_len, kv_len, tables,
                               impl="dense", kv_tile_pages=2)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("seed", [0, 1, 2, 5])
@pytest.mark.parametrize("pps,ps", [(5, 4), (32, 8)])
def test_tiled_vs_oneshot_ulp_contract(seed, pps, ps):
    """The tiled walk's exactness contract vs the one-shot kernel
    (TILED_ULP_BOUND — ulp measured at the slot's output scale; a raw
    per-element ulp bound cannot survive the flash combine's
    reassociation at cancellation-small components, see the kernel
    module). Mixed prefill+decode spans, empty slots, partial tail
    pages, tiles that do not divide the live page count."""
    case = _ragged_case(seed, pps=pps, ps=ps)
    one = np.asarray(ragged_paged_attention(*case, impl="dense",
                                            kv_tile_pages=0))
    for tile in (1, 3, max(pps // 2, 1), pps):
        tiled = np.asarray(ragged_paged_attention(
            *case, impl="pallas", kv_tile_pages=tile))
        err = tiled_ulp_error(tiled, one)
        assert err <= TILED_ULP_BOUND, (seed, pps, ps, tile, err)


def test_tiled_scratch_independent_of_table_width():
    """The acceptance property in numbers, straight from the scratch
    shapes: one-shot K+V scratch grows with pages_per_slot; the tiled
    walk's does not — a 100k-token table pins the same VMEM as a 2k
    one — and the geometry auto-selection flips to tiled exactly at
    the budget knee."""
    ps, dh = 16, 128
    tiles = [vmem_scratch_bytes(pps, ps, dh, jnp.bfloat16,
                                kv_tile_pages=32)
             for pps in (128, 512, 6250)]
    assert len(set(tiles)) == 1
    shots = [vmem_scratch_bytes(pps, ps, dh, jnp.bfloat16)
             for pps in (128, 512, 6250)]
    assert shots == sorted(shots) and shots[0] < shots[-1]
    # knee: <= budget -> one-shot (0); past it -> a tile
    assert default_kv_tile_pages(128, ps, dh, jnp.bfloat16) == 0
    big = default_kv_tile_pages(6250, ps, dh, jnp.bfloat16)
    assert big > 0
    assert vmem_scratch_bytes(6250, ps, dh, jnp.bfloat16,
                              kv_tile_pages=big) \
        <= ONE_SHOT_VMEM_BUDGET
    # the knee itself sits at the budget boundary
    knee_pps = ONE_SHOT_VMEM_BUDGET // (2 * ps * dh * 2)
    assert default_kv_tile_pages(knee_pps, ps, dh, jnp.bfloat16) == 0
    assert default_kv_tile_pages(knee_pps + 1, ps, dh,
                                 jnp.bfloat16) > 0


# ---------------------------------------------------------------------------
# engine exactness: greedy == generate() in every cache state
# ---------------------------------------------------------------------------

def _engine(params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_prompt_len", 16)
    kw.setdefault("max_new_tokens_cap", 16)
    return ServingEngine(params, CFG, **kw)


def test_engine_matches_generate_cold_warm_partial(params):
    """The one-program tick keeps greedy outputs byte-identical to
    ``generate()`` whether the prompt's prefix was cold, fully cached
    (EXACT attach — any page count), or partially cached."""
    rng = np.random.RandomState(2)
    base = rng.randint(0, CFG.vocab_size, (13,)).astype(np.int32)
    partial = np.concatenate(
        [base[:9], rng.randint(0, CFG.vocab_size, (5,)).astype(np.int32)])
    with _engine(params) as eng:
        cold = eng.submit(base, 6).result(timeout=300)
        warm = eng.submit(base, 6).result(timeout=300)
        part = eng.submit(partial, 6).result(timeout=300)
        snap = eng.stats()
    np.testing.assert_array_equal(cold, _ref(params, base, 6))
    np.testing.assert_array_equal(warm, _ref(params, base, 6))
    np.testing.assert_array_equal(part, _ref(params, partial, 6))
    assert snap["counters"]["prefix_hits"] >= 2   # warm + partial

    # cache states actually differed: the warm run attached pages
    assert snap["counters"]["prefix_hit_tokens"] > 0


def test_engine_matches_generate_chunked_prefill(params):
    """Chunked prefill (prefill_chunk budget < prompt length) is purely
    a scheduling knob: outputs still match generate() bitwise, for
    aligned and unaligned chunk sizes."""
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, CFG.vocab_size, (n,)).astype(np.int32)
               for n in (15, 9, 13)]
    for chunk in (4, 5):
        with _engine(params, prefill_chunk=chunk) as eng:
            handles = [eng.submit(p, 5) for p in prompts]
            outs = [h.result(timeout=300) for h in handles]
        for p, out in zip(prompts, outs):
            np.testing.assert_array_equal(out, _ref(params, p, 5))


def test_engine_matches_generate_after_defrag(params):
    """Mid-stream defrag scatters every live page list; the ragged tick
    reads the remapped tables as data, so continuations stay bitwise
    equal to generate()."""
    rng = np.random.RandomState(6)
    p1 = rng.randint(0, CFG.vocab_size, (11,)).astype(np.int32)
    p2 = rng.randint(0, CFG.vocab_size, (7,)).astype(np.int32)
    with _engine(params, check_invariants=True) as eng:
        # stagger: retire a short request first so the pool fragments
        eng.submit(p2, 2).result(timeout=300)
        h1 = eng.submit(p1, 8)
        it = iter(h1)
        next(it)
        moved = eng.defragment()
        h2 = eng.submit(p2, 6)
        out1 = h1.result(timeout=300)
        out2 = h2.result(timeout=300)
        assert eng.audit() == []
    assert moved >= 0   # plan may be empty; the point is the remap path
    np.testing.assert_array_equal(out1, _ref(params, p1, 8))
    np.testing.assert_array_equal(out2, _ref(params, p2, 6))


def test_invariant_checker_clean_through_ragged_bench_run(params):
    """A bench-shaped mixed run — staggered admissions, shared
    prefixes, chunked prefill, mid-run defrag — with per-tick invariant
    checking ON: zero violations, every output exact."""
    rng = np.random.RandomState(8)
    header = rng.randint(0, CFG.vocab_size, (8,)).astype(np.int32)
    specs = []
    for i in range(8):
        tail = rng.randint(0, CFG.vocab_size,
                           (int(rng.randint(2, 8)),)).astype(np.int32)
        prompt = (np.concatenate([header, tail]) if i % 2
                  else tail)
        specs.append((prompt, int(rng.randint(2, 7))))
    with _engine(params, check_invariants=True, prefill_chunk=4,
                 max_batch=3) as eng:
        handles = []
        for i, (prompt, mnt) in enumerate(specs):
            handles.append(eng.submit(prompt, mnt))
            if i == 4:
                eng.defragment()
            time.sleep(0.002)
        outs = [h.result(timeout=300) for h in handles]
        assert eng.audit() == []
        snap = eng.stats()
    assert snap["counters"].get("invariant_violations", 0) == 0
    assert snap["counters"]["completed"] == len(specs)
    for (prompt, mnt), out in zip(specs, outs):
        np.testing.assert_array_equal(out, _ref(params, prompt, mnt))


def test_sampling_prefill_does_not_throttle_greedy_tail(params):
    """A parked SAMPLING request must not disable the fused greedy
    decode tail for in-flight greedy streams: mid-prefill spans sit
    the tail out on the trash page regardless of temperature, so only
    live decoders and COMPLETING spans gate it. Pins (a) greedy
    exactness with a sampling span sharing the tick — the tail>0 +
    sampling-span program path — and (b) that fused steps actually
    ran (steps > ticks would be equal if every tick were single-step)."""
    rng = np.random.RandomState(9)
    victim_p = rng.randint(0, CFG.vocab_size, (3,)).astype(np.int32)
    intruder_p = rng.randint(0, CFG.vocab_size, (16,)).astype(np.int32)
    with _engine(params, max_batch=2, decode_block_size=4,
                 prefill_chunk=3, prefix_cache=False) as eng:
        h_v = eng.submit(victim_p, 20)
        it = iter(h_v)
        next(it)                      # victim is mid-decode
        h_i = eng.submit(intruder_p, 4, temperature=0.7, seed=1)
        out_v = h_v.result(timeout=300)
        out_i = h_i.result(timeout=300)
        snap = eng.stats()
    np.testing.assert_array_equal(out_v, _ref(params, victim_p, 20))
    assert len(out_i) == 4            # sampling request completed
    steps = snap["counters"]["decode_steps"]
    ticks = snap["histograms"]["decode_step_s"]["count"]
    assert steps > ticks, (
        f"no fused tail/block ever ran: {steps} steps in {ticks} ticks")


# ---------------------------------------------------------------------------
# ragged_ab bench acceptance (slow tier)
# ---------------------------------------------------------------------------

def _load_bench():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "serving_bench.py")
    spec = importlib.util.spec_from_file_location("serving_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serving_bench_ragged_ab_smoke():
    """The A/B harness runs end to end on a micro trace and emits both
    arms (no perf assertions — those live in the slow test)."""
    sb = _load_bench()
    # max_prompt 16 / page 4: an attach-rich geometry (cached prefixes
    # up to 3 pages), where the legacy dispatch needs one chunk program
    # per static prefix_pages value
    res = sb.main(["--requests", "6", "--rate", "100", "--max-batch", "2",
                   "--mnt-choices", "3", "6", "--max-prompt", "16",
                   "--page-size", "4", "--modes", "ragged_ab"])
    ab = res["ragged_ab"]
    for arm in ("ragged", "bucketed"):
        assert ab[arm]["useful_tokens"] > 0
        assert ab[arm]["compiles"] > 0
    # the structural claim is static and deterministic: exact prefix
    # attach costs the ragged dispatch <=2 programs per width bucket,
    # the legacy dispatch one program per prefix_pages value
    ps = ab["program_set"]
    assert ps["ragged_worst_per_bucket"] <= 2
    assert ps["ragged_worst_per_bucket"] < ps["bucketed_worst_per_bucket"]
    assert ps["ragged"] < ps["bucketed"]


@pytest.mark.slow
def test_100k_token_page_table_serves_end_to_end(params):
    """The r16 acceptance scenario: a page table spanning ~100k tokens
    serves through the engine end-to-end, bitwise-equal to
    ``generate()`` — the geometry the one-shot walk cannot hold
    on-chip (its K+V scratch would be ~100 MB at serving dims; the
    auto-selection proves it flips to the tiled walk there), kept out
    of tier-1 for runtime.

    Three layers of evidence:
    * kernel: tiled == one-shot at kv_len = 100_000 under the
      ulp-at-row-scale contract (dense formulations — off-TPU there
      is no VMEM, the formulation is what's under test), and the
      tiled PALLAS walk (interpret) bitwise == the tiled reference at
      an 8k-token table (512 pages, 32 double-buffered tiles);
    * geometry: ``default_kv_tile_pages`` picks the tiled walk at the
      100k table and its scratch equals the 2k table's;
    * engine: a request decodes against the 100k-capacity table
      (pages_per_slot=6253) bitwise-equal to ``generate()``
      (attn_impl='dense' — the slot-major gather; the packed CPU
      formulation gathers per TOKEN and would thrash, which is
      exactly the work-scaling story docs/PERF.md records)."""
    # --- kernel at kv = 100_000 --------------------------------------
    rng = np.random.RandomState(0)
    Hkv, Dh, ps = 2, 16, 16
    pps = -(-100_000 // ps)                      # 6250 pages
    P = pps + 2
    q = jnp.asarray(rng.randn(1, 1, 4, Dh).astype(np.float32))
    kp = jnp.asarray(rng.randn(Hkv, P, ps, Dh).astype(np.float32))
    vp = jnp.asarray(rng.randn(Hkv, P, ps, Dh).astype(np.float32))
    ql = jnp.ones((1,), jnp.int32)
    kl = jnp.full((1,), 100_000, jnp.int32)
    tabs = jnp.asarray(1 + np.arange(pps, dtype=np.int32)[None])
    tile = default_kv_tile_pages(pps, ps, Dh, jnp.float32)
    assert tile > 0                              # past the VMEM knee
    assert vmem_scratch_bytes(pps, ps, Dh, jnp.float32,
                              kv_tile_pages=tile) == \
        vmem_scratch_bytes(128, ps, Dh, jnp.float32,
                           kv_tile_pages=tile)
    one = np.asarray(ragged_paged_attention(
        q, kp, vp, ql, kl, tabs, impl="dense", kv_tile_pages=0))
    tiled = np.asarray(ragged_paged_attention(
        q, kp, vp, ql, kl, tabs, impl="dense", kv_tile_pages=tile))
    assert tiled_ulp_error(tiled, one) <= TILED_ULP_BOUND
    # tiled PALLAS (interpret) at an 8k table: the real kernel's
    # double-buffered DMA walk, bitwise vs the tiled reference
    kl8 = jnp.full((1,), 8000, jnp.int32)
    a = ragged_paged_attention(q, kp[:, :514], vp[:, :514], ql, kl8,
                               tabs[:, :512], impl="pallas",
                               kv_tile_pages=16)
    b = ragged_paged_attention(q, kp[:, :514], vp[:, :514], ql, kl8,
                               tabs[:, :512], impl="dense",
                               kv_tile_pages=16)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # --- engine over the 100k-capacity table -------------------------
    prompt = np.random.RandomState(1).randint(
        0, CFG.vocab_size, (12,)).astype(np.int32)
    with ServingEngine(params, CFG, max_batch=1, page_size=ps,
                       max_prompt_len=32, max_new_tokens_cap=100_000,
                       attn_impl="dense", decode_block_size=8,
                       prefix_cache=False) as eng:
        assert eng.scheduler.pages_per_slot >= 6250
        out = eng.submit(prompt, 24).result(timeout=600)
        assert eng.audit() == []
    np.testing.assert_array_equal(out, _ref(params, prompt, 24))


@pytest.mark.slow
def test_ragged_ab_acceptance():
    """ISSUE r12 acceptance on the CPU mesh: the one-program tick's
    decode-tick latency is at parity (or better) with the legacy
    bucketed path, and the compiled-program set is strictly smaller.
    Measured at PRODUCTION matmul precision — the conftest-wide
    "highest" pin (for numeric tests) distorts the relative cost of
    the two attention formulations and is not what serves traffic.
    Best-of-4: the ratio is structural but this container's absolute
    latencies swing 2-3x with co-tenant load."""
    sb = _load_bench()
    jax.config.update("jax_default_matmul_precision", "default")
    try:
        wins = 0
        for attempt in range(4):
            if attempt:
                time.sleep(1.0)
            res = sb.main(["--modes", "ragged_ab"])
            ab = res["ragged_ab"]
            assert (ab["program_set"]["ragged"]
                    < ab["program_set"]["bucketed"])
            wins += ab["tick_latency_ratio"] <= 1.10
            if wins:
                break
        assert wins >= 1, (
            f"ragged tick latency never reached parity: {ab}")
    finally:
        jax.config.update("jax_default_matmul_precision", "highest")

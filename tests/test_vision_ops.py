"""paddle.vision.ops parity tests.

Mirrors reference tests: test/legacy_test/test_nms_op.py,
test_roi_align_op.py, test_deformable_conv_op.py, test_yolo_box_op.py,
test_box_coder_op.py, test_matrix_nms_op.py.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.vision import ops as V


def _iou(a, b):
    x1, y1 = max(a[0], b[0]), max(a[1], b[1])
    x2, y2 = min(a[2], b[2]), min(a[3], b[3])
    inter = max(x2 - x1, 0) * max(y2 - y1, 0)
    ua = ((a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1])
          - inter)
    return inter / max(ua, 1e-10)


def _nms_ref(boxes, scores, thresh):
    order = np.argsort(-scores)
    keep = []
    for i in order:
        if all(_iou(boxes[i], boxes[j]) <= thresh for j in keep):
            keep.append(i)
    return keep


def test_nms_matches_bruteforce():
    rng = np.random.RandomState(0)
    xy = rng.rand(40, 2) * 50
    wh = rng.rand(40, 2) * 20 + 1
    boxes = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
    scores = rng.rand(40).astype(np.float32)
    got = np.asarray(V.nms(pt.to_tensor(boxes), 0.4,
                           scores=pt.to_tensor(scores)).data)
    ref = _nms_ref(boxes, scores, 0.4)
    np.testing.assert_array_equal(got, ref)


def test_nms_categorical_and_topk():
    rng = np.random.RandomState(1)
    base = rng.rand(20, 2) * 10
    boxes = np.concatenate([base, base + 5], axis=1).astype(np.float32)
    scores = rng.rand(20).astype(np.float32)
    cats = (np.arange(20) % 3).astype(np.int32)
    got = np.asarray(V.nms(pt.to_tensor(boxes), 0.3,
                           scores=pt.to_tensor(scores),
                           category_idxs=pt.to_tensor(cats),
                           categories=[0, 1, 2], top_k=5).data)
    assert len(got) <= 5
    # same-category survivors must not overlap above threshold
    for i, gi in enumerate(got):
        for gj in got[:i]:
            if cats[gi] == cats[gj]:
                assert _iou(boxes[gi], boxes[gj]) <= 0.3 + 1e-6


def test_matrix_nms_runs_and_filters():
    rng = np.random.RandomState(2)
    b = rng.rand(1, 10, 2) * 20
    boxes = np.concatenate([b, b + 10], axis=2).astype(np.float32)
    scores = rng.rand(1, 3, 10).astype(np.float32)
    out, idx, num = V.matrix_nms(pt.to_tensor(boxes), pt.to_tensor(scores),
                                 score_threshold=0.3, post_threshold=0.1,
                                 return_index=True)
    out = np.asarray(out.data)
    assert out.shape[1] == 6  # [class, score, x1, y1, x2, y2]
    assert (out[:, 1] >= 0.1 - 1e-6).all()
    assert int(np.asarray(num.data)[0]) == out.shape[0]


def _matrix_nms_ref(boxes, scores, post_threshold, sigma, use_gaussian):
    """Sequential transcript of matrix_nms_kernel.cc NMSMatrix (:120-151):
    iou_max[i] = max overlap with higher-scored boxes; decay for box i =
    min over higher j of decay_score(iou(i,j), iou_max[j], sigma)."""
    order = list(np.argsort(-scores))
    iou_max, out = {}, {}
    for rank, i in enumerate(order):
        ious = [_iou(boxes[i], boxes[order[r]]) for r in range(rank)]
        iou_max[i] = max(ious, default=0.0)
        decay = 1.0
        for r, v in enumerate(ious):
            m = iou_max[order[r]]
            if use_gaussian:
                d = np.exp((m * m - v * v) * sigma)
            else:
                d = (1.0 - v) / (1.0 - m)
            decay = min(decay, d)
        ds = decay * scores[i]
        if ds > post_threshold:
            out[i] = ds
    return out


@pytest.mark.parametrize("use_gaussian", [False, True])
def test_matrix_nms_decay_matches_reference_formula(use_gaussian):
    # three heavily-overlapping boxes: suppression must be real, not a
    # near no-op (round-3 ADVICE: wrong compensation axis cancelled decay)
    boxes = np.asarray([[0, 0, 10, 10], [1, 1, 11, 11], [2, 0, 12, 10],
                        [30, 30, 40, 40], [0, 3, 10, 13]],
                       np.float32)[None]
    scores = np.asarray([0.9, 0.8, 0.7, 0.6, 0.5], np.float32)[None, None]
    sigma = 2.0
    out, idx, num = V.matrix_nms(
        pt.to_tensor(boxes), pt.to_tensor(scores), score_threshold=0.0,
        post_threshold=0.05, use_gaussian=use_gaussian,
        gaussian_sigma=sigma, background_label=-1, return_index=True)
    got = {int(i): float(s) for i, s in
           zip(np.asarray(idx.data), np.asarray(out.data)[:, 1])}
    want = _matrix_nms_ref(boxes[0], scores[0, 0], 0.05, sigma,
                           use_gaussian)
    assert set(got) == set(want)
    for i in got:
        np.testing.assert_allclose(got[i], want[i], rtol=1e-5)
    # the overlapped boxes really decayed
    assert got[1] < 0.8 * 0.7 and got[2] < 0.7 * 0.8


def test_roi_align_linear_field_exact():
    # bilinear sampling of a LINEAR field f(y,x)=y+x is exact, and the
    # mean over a bin's sample grid equals f at the bin center — so
    # out[i,j] must be yc(i) + xc(j) for interior RoIs (aligned=True)
    yy, xx = np.mgrid[0:8, 0:8].astype(np.float32)
    feat = (yy + xx)[None, None]
    rois = np.asarray([[1, 1, 7, 7]], np.float32)
    out = V.roi_align(pt.to_tensor(feat), pt.to_tensor(rois),
                      pt.to_tensor(np.asarray([1], np.int32)),
                      output_size=3, aligned=True)
    got = np.asarray(out.data)[0, 0]
    centers = np.asarray([1 + 2 * (i + 0.5) - 0.5 for i in range(3)])
    ref = centers[:, None] + centers[None, :]
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_roi_pool_max_semantics():
    feat = np.zeros((1, 1, 6, 6), np.float32)
    feat[0, 0, 1, 1] = 5.0
    feat[0, 0, 4, 4] = 7.0
    rois = np.asarray([[0, 0, 6, 6]], np.float32)
    out = V.roi_pool(pt.to_tensor(feat), pt.to_tensor(rois),
                     pt.to_tensor(np.asarray([1], np.int32)), output_size=2)
    got = np.asarray(out.data)[0, 0]
    assert got[0, 0] == 5.0 and got[1, 1] == 7.0


def test_psroi_pool_channel_routing():
    # channel c*4+i*2+j feeds output channel c at bin (i,j)
    feat = np.zeros((1, 8, 4, 4), np.float32)
    for t in range(8):
        feat[0, t] = t + 1
    rois = np.asarray([[0, 0, 4, 4]], np.float32)
    out = V.psroi_pool(pt.to_tensor(feat), pt.to_tensor(rois),
                       pt.to_tensor(np.asarray([1], np.int32)),
                       output_size=2)
    got = np.asarray(out.data)[0]      # [2, 2, 2]
    assert got.shape == (2, 2, 2)
    np.testing.assert_allclose(got[0].ravel(), [1, 2, 3, 4])
    np.testing.assert_allclose(got[1].ravel(), [5, 6, 7, 8])


def test_box_coder_roundtrip():
    rng = np.random.RandomState(3)
    priors = np.asarray([[0, 0, 10, 10], [5, 5, 20, 25]], np.float32)
    targets = np.abs(rng.rand(3, 4).astype(np.float32)) * 10
    targets[:, 2:] += targets[:, :2] + 1  # valid boxes
    enc = V.box_coder(pt.to_tensor(priors), None, pt.to_tensor(targets),
                      code_type="encode_center_size")
    assert tuple(enc.shape) == (3, 2, 4)
    # decode per prior column and compare against the original target
    for m in range(2):
        dec = V.box_coder(pt.to_tensor(priors[m:m + 1]), None,
                          pt.to_tensor(np.asarray(enc.data)[:, m]),
                          code_type="decode_center_size")
        np.testing.assert_allclose(np.asarray(dec.data), targets,
                                   rtol=1e-4, atol=1e-3)


def test_prior_box_shapes_and_range():
    feat = np.zeros((1, 8, 3, 3), np.float32)
    img = np.zeros((1, 3, 30, 30), np.float32)
    boxes, vars_ = V.prior_box(pt.to_tensor(feat), pt.to_tensor(img),
                               min_sizes=[4.0], aspect_ratios=[2.0],
                               clip=True)
    assert boxes.shape[:2] == [3, 3]
    b = np.asarray(boxes.data)
    assert (b >= 0).all() and (b <= 1).all()
    assert np.asarray(vars_.data).shape == b.shape


def test_yolo_box_decodes():
    rng = np.random.RandomState(4)
    B, na, C, H = 1, 2, 3, 4
    x = rng.randn(B, na * (5 + C), H, H).astype(np.float32)
    boxes, scores = V.yolo_box(pt.to_tensor(x),
                               pt.to_tensor(np.asarray([[64, 64]], np.int32)),
                               anchors=[10, 13, 16, 30], class_num=C,
                               conf_thresh=0.0, downsample_ratio=16)
    assert tuple(boxes.shape) == (B, na * H * H, 4)
    assert tuple(scores.shape) == (B, na * H * H, C)
    b = np.asarray(boxes.data)
    assert (b[..., 2] >= b[..., 0] - 1e-5).all()


def test_deform_conv2d_zero_offset_is_conv():
    rng = np.random.RandomState(5)
    x = rng.randn(1, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 9, 8, 8), np.float32)
    got = np.asarray(V.deform_conv2d(
        pt.to_tensor(x), pt.to_tensor(off), pt.to_tensor(w),
        padding=1).data)
    ref = np.asarray(pt.nn.functional.conv2d(
        pt.to_tensor(x), pt.to_tensor(w), padding=1).data)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_deform_conv2d_layer_and_mask():
    rng = np.random.RandomState(6)
    layer = V.DeformConv2D(3, 4, 3, padding=1)
    x = pt.to_tensor(rng.randn(2, 3, 6, 6).astype(np.float32))
    off = pt.to_tensor(rng.randn(2, 18, 6, 6).astype(np.float32) * 0.1)
    mask = pt.to_tensor(np.ones((2, 9, 6, 6), np.float32) * 0.5)
    out_nomask = layer(x, off)
    out_mask = layer(x, off, mask)
    assert tuple(out_nomask.shape) == (2, 4, 6, 6)
    # mask=0.5 halves the sampled contribution (pre-bias linearity)
    nb = np.asarray((out_nomask - layer.bias.reshape([1, -1, 1, 1])).data)
    mb = np.asarray((out_mask - layer.bias.reshape([1, -1, 1, 1])).data)
    np.testing.assert_allclose(mb, nb * 0.5, rtol=1e-4, atol=1e-4)


def test_distribute_fpn_proposals():
    rois = np.asarray([[0, 0, 10, 10],      # small -> low level
                       [0, 0, 500, 500],    # large -> high level
                       [0, 0, 60, 60]], np.float32)
    multi, restore = V.distribute_fpn_proposals(
        pt.to_tensor(rois), min_level=2, max_level=5, refer_level=4,
        refer_scale=224)
    assert len(multi) == 4
    total = sum(int(np.asarray(r.data).shape[0]) for r in multi)
    assert total == 3
    r = np.asarray(restore.data).ravel()
    assert sorted(r.tolist()) == [0, 1, 2]


def test_conv_norm_activation_block():
    blk = V.ConvNormActivation(3, 8, kernel_size=3, stride=2)
    x = pt.to_tensor(np.random.RandomState(7).randn(1, 3, 8, 8)
                     .astype(np.float32))
    assert tuple(blk(x).shape) == (1, 8, 4, 4)


def test_read_file_raises_with_guidance():
    with pytest.raises(NotImplementedError, match="host file IO"):
        V.read_file("x.jpg")

"""Sparse conv / batchnorm / attention vs dense references.

Mirrors reference tests: test/legacy_test/test_sparse_conv_op.py,
test_sparse_norm_op.py, test_sparse_attention_op.py.
"""
import numpy as np
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import sparse


def _random_coo(rng, shape, nnz, channels):
    """Random [N,D,H,W,C] sparse voxel tensor with unique sites."""
    n, d, h, w = shape
    sites = set()
    while len(sites) < nnz:
        sites.add((rng.randint(n), rng.randint(d), rng.randint(h),
                   rng.randint(w)))
    idx = np.asarray(sorted(sites), np.int32)                  # [nnz, 4]
    vals = rng.randn(nnz, channels).astype(np.float32)
    return idx, vals


def _densify(idx, vals, shape, channels):
    dense = np.zeros(shape + (channels,), np.float32)
    for row, (n, d, h, w) in enumerate(idx):
        dense[n, d, h, w] = vals[row]
    return dense


def _dense_conv3d(x, w, stride=1, padding=0):
    """Straightforward NDHWC conv for the check (float64 numpy)."""
    import itertools
    kd, kh, kw, cin, cout = w.shape
    N, D, H, W, _ = x.shape
    pad = np.zeros((N, D + 2 * padding, H + 2 * padding, W + 2 * padding,
                    cin), np.float64)
    pad[:, padding:padding + D, padding:padding + H,
        padding:padding + W] = x
    oD = (D + 2 * padding - kd) // stride + 1
    oH = (H + 2 * padding - kh) // stride + 1
    oW = (W + 2 * padding - kw) // stride + 1
    out = np.zeros((N, oD, oH, oW, cout), np.float64)
    for z, y, xx in itertools.product(range(oD), range(oH), range(oW)):
        patch = pad[:, z * stride:z * stride + kd,
                    y * stride:y * stride + kh,
                    xx * stride:xx * stride + kw]          # [N,kd,kh,kw,cin]
        out[:, z, y, xx] = np.einsum("nijkc,ijkco->no", patch, w)
    return out


def test_subm_conv3d_matches_masked_dense():
    rng = np.random.RandomState(0)
    shape, cin, cout = (2, 5, 5, 5), 3, 4
    idx, vals = _random_coo(rng, shape, nnz=20, channels=cin)
    sp = sparse.sparse_coo_tensor(idx.T, vals, shape + (cin,))
    conv = sparse.nn.SubmConv3D(cin, cout, kernel_size=3)
    out = conv(sp)
    # submanifold: same coords, values = dense conv at those sites
    np.testing.assert_array_equal(np.asarray(out.indices()), idx.T)
    dense_in = _densify(idx, vals, shape, cin)
    ref = _dense_conv3d(dense_in, np.asarray(conv.weight.data, np.float64),
                        stride=1, padding=1)
    ref = ref + np.asarray(conv.bias.data, np.float64)
    got = np.asarray(out.values())
    for row, (n, d, h, w) in enumerate(idx):
        np.testing.assert_allclose(got[row], ref[n, d, h, w], atol=1e-4)


def test_conv3d_matches_dense():
    rng = np.random.RandomState(1)
    shape, cin, cout = (1, 6, 6, 6), 2, 3
    idx, vals = _random_coo(rng, shape, nnz=12, channels=cin)
    sp = sparse.sparse_coo_tensor(idx.T, vals, shape + (cin,))
    conv = sparse.nn.Conv3D(cin, cout, kernel_size=2, stride=2, bias_attr=False)
    out = conv(sp)
    dense_in = _densify(idx, vals, shape, cin)
    ref = _dense_conv3d(dense_in, np.asarray(conv.weight.data, np.float64),
                        stride=2, padding=0)
    got = np.asarray(out.to_dense().data)
    assert got.shape == ref.shape
    # output sites produced by the sparse path must match dense values;
    # dense may have tiny values only where sparse emitted a site
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_sparse_batchnorm_relu():
    rng = np.random.RandomState(2)
    shape, c = (2, 4, 4, 4), 5
    idx, vals = _random_coo(rng, shape, nnz=30, channels=c)
    sp = sparse.sparse_coo_tensor(idx.T, vals, shape + (c,))
    bn = sparse.nn.BatchNorm(c)
    out = bn(sp)
    v = np.asarray(out.values())
    np.testing.assert_allclose(v.mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(v.std(0), 1.0, atol=1e-2)
    relu_out = sparse.nn.ReLU()(out)
    assert (np.asarray(relu_out.values()) >= 0).all()


def test_subm_conv3d_rejects_stride_dilation():
    import pytest
    rng = np.random.RandomState(4)
    shape, cin = (1, 4, 4, 4), 2
    idx, vals = _random_coo(rng, shape, nnz=5, channels=cin)
    sp = sparse.sparse_coo_tensor(idx.T, vals, shape + (cin,))
    conv = sparse.nn.SubmConv3D(cin, 3, kernel_size=3, stride=2)
    with pytest.raises(ValueError, match="stride"):
        conv(sp)


def test_sparse_attention_ragged_per_head():
    # per-head CSR patterns with DIFFERENT nnz must not cross-contaminate
    rng = np.random.RandomState(5)
    B, H, T, D = 1, 2, 4, 4
    q = rng.randn(B, H, T, D).astype(np.float32)
    # head 0: diagonal only (4 edges); head 1: full causal (10 edges)
    crows0 = np.arange(T + 1, dtype=np.int32)
    cols0 = np.arange(T, dtype=np.int32)
    crows1, cols1 = [0], []
    for t in range(T):
        cols1.extend(range(t + 1))
        crows1.append(len(cols1))
    # emulate a batched CSR object with ragged rows via a stub
    class _SP:
        pass
    class _Mask:
        _sp = _SP()
    nse = max(len(cols0), len(cols1))
    indptr = np.stack([np.pad(crows0, (0, 0)), np.asarray(crows1)])
    cols = np.stack([np.pad(cols0, (0, nse - len(cols0))),
                     np.asarray(cols1)])
    _Mask._sp.indptr = indptr
    _Mask._sp.indices = cols
    out = sparse.nn.functional.attention(
        pt.to_tensor(q), pt.to_tensor(q), pt.to_tensor(q), _Mask())
    got = np.asarray(out.data)
    # head 0 diagonal: output == v
    np.testing.assert_allclose(got[0, 0], q[0, 0], atol=1e-5)
    # head 1 causal: dense reference
    logits = (q[0, 1] @ q[0, 1].T) / np.sqrt(D)
    logits = np.where(np.tril(np.ones((T, T))) > 0, logits, -np.inf)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    np.testing.assert_allclose(got[0, 1], p @ q[0, 1], atol=1e-5)


def test_sparse_attention_matches_masked_dense():
    rng = np.random.RandomState(3)
    B, H, T, D = 1, 2, 8, 4
    q = rng.randn(B, H, T, D).astype(np.float32)
    k = rng.randn(B, H, T, D).astype(np.float32)
    v = rng.randn(B, H, T, D).astype(np.float32)
    # banded pattern (each row attends to itself and previous position)
    crows, cols = [0], []
    for t in range(T):
        row_cols = [max(t - 1, 0), t] if t else [0]
        cols.extend(sorted(set(row_cols)))
        crows.append(len(cols))
    mask = np.full((T, T), -np.inf, np.float64)
    for t in range(T):
        for c in cols[crows[t]:crows[t + 1]]:
            mask[t, c] = 0.0
    csr = sparse.sparse_csr_tensor(np.asarray(crows, np.int32),
                                   np.asarray(cols, np.int32),
                                   np.ones(len(cols), np.float32), (T, T))
    out = sparse.nn.functional.attention(
        pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v), csr)
    # dense reference with -inf masking
    logits = np.einsum("bhtd,bhsd->bhts", q.astype(np.float64),
                       k.astype(np.float64)) / np.sqrt(D) + mask
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhts,bhsd->bhtd", p, v.astype(np.float64))
    np.testing.assert_allclose(np.asarray(out.data), ref, atol=1e-4)

"""Weight-only int8 decode parity (ISSUE 2 tentpole).

quantization/decode.py quantize_for_decode + ops/fused/int8_matmul +
ops/pallas/int8_matmul, wired through generate / generate_paged / the
serving engine for llama and qwen2_moe.

What "correct" means here, in order of strictness:
  * the int8 primitive itself is EXACT vs its dequant-reference
    formulation, and the pallas kernel matches the jnp path;
  * every int8 decode path agrees with every other int8 decode path
    token-for-token (paged vs dense cache, engine vs generate) — the
    quantized params are just params, so the r6 exactness bar carries
    over unchanged;
  * int8 vs full-precision decode agrees approximately: bounded logit
    error and a high greedy token-match rate. On these TINY random
    models the logit gaps are near-uniform noise (std ~1.0 over vocab
    256), which is the WORST case for argmax stability — real trained
    models have peaked logits, so the match-rate floor asserted here is
    deliberately conservative while still catching a broken quantizer
    (which measures ~1/vocab ≈ 0.004).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.models import qwen2_moe as Q
from paddle_tpu.ops.fused.int8_matmul import (Int8Weight,
                                              int8_weight_matmul,
                                              quantize_weight_per_channel)
from paddle_tpu.ops.pallas.int8_matmul import int8_matmul_pallas
from paddle_tpu.quantization import (decode_weight_bytes,
                                     dequantize_for_decode,
                                     is_quantized_params,
                                     quantize_for_decode)

CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)
QCFG = Q.Qwen2MoeConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                             remat=False)


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def qparams(params):
    return quantize_for_decode(params, CFG)


@pytest.fixture(scope="module")
def moe_params():
    return Q.init_params(QCFG, jax.random.PRNGKey(0))


# ---------------------------------------------------------------------------
# the primitive
# ---------------------------------------------------------------------------

def test_quantize_roundtrip_error_bounded_by_half_scale():
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 64, 96),
                          jnp.float32) * 0.4
    q, s = quantize_weight_per_channel(w)
    assert q.dtype == jnp.int8 and s.shape == (3, 96)
    deq = q.astype(jnp.float32) * s[:, None, :]
    # round-to-nearest: per-channel error <= scale/2 (+ float eps)
    err = jnp.max(jnp.abs(deq - w), axis=-2)
    assert float(jnp.max(err - s / 2)) <= 1e-6
    # absmax channels hit +-127 exactly
    assert int(jnp.max(jnp.abs(q))) == 127


def test_int8_matmul_matches_dequant_reference():
    w = jax.random.normal(jax.random.PRNGKey(2), (48, 64), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(3), (5, 48), jnp.float32)
    q, s = quantize_weight_per_channel(w)
    ref = x @ (q.astype(jnp.float32) * s[None, :])
    np.testing.assert_allclose(int8_weight_matmul(x, q, s), ref,
                               rtol=1e-5, atol=1e-5)


def test_pallas_kernel_matches_jnp_path():
    # tileable shape (N % 128 == 0) so the kernel body actually runs
    w = jax.random.normal(jax.random.PRNGKey(4), (64, 256), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 64), jnp.float32)
    q, s = quantize_weight_per_channel(w)
    np.testing.assert_allclose(int8_matmul_pallas(x, q, s),
                               int8_weight_matmul(x, q, s),
                               rtol=1e-5, atol=1e-5)


def test_pallas_kernel_untileable_shape_falls_back():
    w = jax.random.normal(jax.random.PRNGKey(6), (30, 50), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(7), (3, 30), jnp.float32)
    q, s = quantize_weight_per_channel(w)
    np.testing.assert_allclose(int8_matmul_pallas(x, q, s),
                               int8_weight_matmul(x, q, s),
                               rtol=1e-5, atol=1e-5)


def test_int8_weight_scans_over_stacked_layers():
    W = jax.random.normal(jax.random.PRNGKey(8), (4, 16, 32), jnp.float32)
    iw = Int8Weight.quantize(W)
    x = jnp.ones((2, 16), jnp.float32)

    def body(c, lp):
        return c, lp.dequant_matmul(x)

    _, ys = jax.lax.scan(body, 0, iw)
    for i in range(4):
        np.testing.assert_allclose(
            ys[i], int8_weight_matmul(x, iw.q[i], iw.scale[i]),
            rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# quantize_for_decode structure
# ---------------------------------------------------------------------------

def test_quantized_tree_structure_and_bytes(params, qparams):
    lp = qparams["layers"]
    for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        assert isinstance(lp[k], Int8Weight), k
    assert isinstance(qparams["lm_head"], Int8Weight)
    # embed + norms stay dense
    assert not isinstance(qparams["embed"], Int8Weight)
    assert not isinstance(lp["attn_norm"], Int8Weight)
    assert is_quantized_params(qparams)
    assert not is_quantized_params(params)
    # weight stream: ~4x cut vs these f32 params (2x vs bf16)
    assert decode_weight_bytes(qparams) < 0.35 * decode_weight_bytes(params)
    # dequantized tree restores plain arrays
    deq = dequantize_for_decode(qparams, jnp.float32)
    assert not is_quantized_params(deq)
    np.testing.assert_allclose(
        np.asarray(deq["layers"]["wq"]), np.asarray(params["layers"]["wq"]),
        atol=float(jnp.max(qparams["layers"]["wq"].scale)) / 2 + 1e-6)


def test_double_quantization_rejected(qparams):
    with pytest.raises(ValueError, match="already"):
        quantize_for_decode(qparams, CFG)


# ---------------------------------------------------------------------------
# llama decode parity
# ---------------------------------------------------------------------------

def test_llama_int8_logit_error_and_token_match(params, qparams):
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0,
                                CFG.vocab_size)
    lg_fp, _ = L.forward_with_cache(params, prompt,
                                    L.init_kv_cache(CFG, 2, 8), 0, CFG)
    lg_q, _ = L.forward_with_cache(qparams, prompt,
                                   L.init_kv_cache(CFG, 2, 8), 0, CFG)
    err = float(jnp.max(jnp.abs(lg_fp - lg_q)))
    spread = float(jnp.std(lg_fp))
    assert err < 0.2 * max(spread, 1.0), (err, spread)  # measured ~0.07

    out_fp = L.generate(params, prompt, CFG, max_new_tokens=12)
    out_q = L.generate(qparams, prompt, CFG, max_new_tokens=12)
    match = float(np.mean(np.asarray(out_fp[:, 5:])
                          == np.asarray(out_q[:, 5:])))
    # measured 0.71 on this seed/model — near-uniform random logits are
    # the argmax worst case; a broken quantizer measures ~1/256
    assert match >= 0.5, match


def test_llama_paged_int8_matches_dense_int8_exactly(qparams):
    prompt = jax.random.randint(jax.random.PRNGKey(9), (2, 6), 0,
                                CFG.vocab_size)
    lens = jnp.asarray([6, 6], jnp.int32)
    paged = L.generate_paged(qparams, prompt, lens, CFG,
                             max_new_tokens=8, page_size=4)
    dense = L.generate(qparams, prompt, CFG, max_new_tokens=8)[:, 6:]
    np.testing.assert_array_equal(np.asarray(paged), np.asarray(dense))


# ---------------------------------------------------------------------------
# qwen2_moe decode parity
# ---------------------------------------------------------------------------

def test_qwen_int8_greedy_token_match(moe_params):
    qq = quantize_for_decode(moe_params, QCFG)
    exp = qq["layers"]["experts"]
    for k in ("w_gate", "w_up", "w_down"):
        assert isinstance(exp[k], Int8Weight)
        # per-(layer, expert, channel) scales
        assert exp[k].scale.ndim == 3
    # router deliberately NOT quantized (routing flips are catastrophic
    # vs logit wobble)
    assert not isinstance(qq["layers"]["router"], Int8Weight)

    prompt = jax.random.randint(jax.random.PRNGKey(10), (2, 5), 0,
                                QCFG.vocab_size)
    out_fp = Q.generate(moe_params, prompt, QCFG, max_new_tokens=10)
    out_q = Q.generate(qq, prompt, QCFG, max_new_tokens=10)
    match = float(np.mean(np.asarray(out_fp[:, 5:])
                          == np.asarray(out_q[:, 5:])))
    assert match >= 0.6, match  # measured 0.9


# ---------------------------------------------------------------------------
# serving engine path
# ---------------------------------------------------------------------------

def _drain(engine):
    engine.close()


def test_serving_engine_int8_matches_generate_int8(params, qparams):
    from paddle_tpu.serving import ServingEngine
    prompts = [[1, 2, 3], [7, 5], [11, 12, 13, 14]]
    refs = []
    gen = jax.jit(lambda p, t: L.generate(p, t, CFG, max_new_tokens=8))
    for pr in prompts:
        out = gen(qparams, jnp.asarray(pr)[None])
        refs.append(np.asarray(out)[0, len(pr):])

    eng = ServingEngine(params, CFG, quantization="int8", max_batch=4,
                        page_size=4, max_prompt_len=16,
                        max_new_tokens_cap=16)
    try:
        handles = [eng.submit(pr, max_new_tokens=8) for pr in prompts]
        for h, ref in zip(handles, refs):
            np.testing.assert_array_equal(np.asarray(h.result()), ref)
    finally:
        _drain(eng)


def test_serving_engine_int8_qwen(moe_params):
    from paddle_tpu.serving import ServingEngine
    qq = quantize_for_decode(moe_params, QCFG)
    prompt = [3, 1, 4]
    ref = np.asarray(Q.generate(qq, jnp.asarray(prompt)[None], QCFG,
                                max_new_tokens=6))[0, 3:]
    eng = ServingEngine(moe_params, QCFG, quantization="int8",
                        max_batch=2, page_size=4, max_prompt_len=8,
                        max_new_tokens_cap=8)
    try:
        np.testing.assert_array_equal(
            np.asarray(eng.generate(prompt, max_new_tokens=6)), ref)
    finally:
        _drain(eng)


def test_serving_engine_rejects_unknown_quantization(params):
    from paddle_tpu.serving import ServingEngine
    with pytest.raises(ValueError, match="quantization"):
        ServingEngine(params, CFG, quantization="int4", max_batch=2,
                      page_size=4, max_prompt_len=8, max_new_tokens_cap=8)


def test_serving_engine_accepts_prequantized_params(qparams):
    from paddle_tpu.serving import ServingEngine
    eng = ServingEngine(qparams, CFG, quantization="int8", max_batch=2,
                        page_size=4, max_prompt_len=8,
                        max_new_tokens_cap=8)
    try:
        out = eng.generate([1, 2], max_new_tokens=4)
        assert out.shape == (4,)
    finally:
        _drain(eng)

"""HLO-pattern proofs for the megatron TP layer path (distributed/mpu).

The mpu layers trust GSPMD to emit the collectives the reference
hand-codes (mp_ops.py: c_identity/allreduce, _c_softmax_with_cross_entropy
:414). These tests compile the LAYER forward (not a hand-built formula)
on the tp=8 mesh and assert on the partitioned HLO — the
test_zero_sharding technique:

  * ParallelCrossEntropy over a vocab-sharded lm_head must lower to the
    max-allreduce + sum-allreduce softmax pattern and must NEVER
    all-gather vocab-dim logits (the silent failure that destroys TP's
    memory savings).
  * RowParallelLinear with a tp-sharded contraction must all-reduce the
    partial products, not all-gather the full input.
  * RowSequenceParallelLinear must return the output to the
    sequence-sharded layout via a scatter-style collective.
"""
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.autograd import tape as _tape
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import mpu
from paddle_tpu.distributed.sequence_parallel import (
    RowSequenceParallelLinear, mark_sequence_parallel)
from paddle_tpu.jit import _bind_params
from paddle_tpu.parallel import init_hybrid_mesh
from paddle_tpu.parallel import mesh as _mesh_mod


@pytest.fixture
def tp_mesh():
    hm = init_hybrid_mesh(dp=1, pp=1, tp=8, set_global=True)
    try:
        yield hm
    finally:
        _mesh_mod._GLOBAL_MESH = None


def _compile_layer_fn(hm, params, fn, *example):
    """Jit-compile ``fn`` with the layers' (sharded) weights as traced
    inputs; returns partitioned HLO text."""

    def pure(warrs, *args):
        with _bind_params(params, warrs), _tape.no_grad():
            out = fn(*[Tensor(a) for a in args])
        return out.data if isinstance(out, Tensor) else out

    with hm.mesh:
        lowered = jax.jit(pure).lower([p.data for p in params], *example)
        return lowered.compile().as_text()


def _allgather_dim_hit(hlo, dim_size):
    """all-gather instructions whose result carries ``dim_size`` in any
    dim (shard sizes are dim_size/8, so a full-size hit means the
    sharded tensor was re-materialised)."""
    hits = []
    for m in re.finditer(r"all-gather[^\n]*", hlo):
        line = m.group(0)
        for s in re.findall(r"[a-z0-9]+\[([0-9,]+)\]", line):
            dims = [int(d) for d in s.split(",") if d]
            if dim_size in dims:
                hits.append(line)
    return hits


V = 1024  # vocab, sharded over tp=8 -> 128/shard


def test_parallel_ce_no_vocab_allgather(tp_mesh):
    col = mpu.ColumnParallelLinear(64, V, has_bias=False,
                                   gather_output=False)
    ce = mpu.ParallelCrossEntropy()

    def head_loss(x, labels):
        logits = col(x)
        return ce(logits, labels).mean()

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 64), jnp.float32)
    labels = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, V)
    hlo = _compile_layer_fn(tp_mesh, [col.weight], head_loss, x, labels)
    hits = _allgather_dim_hit(hlo, V)
    assert not hits, f"vocab logits all-gathered:\n" + "\n".join(hits[:3])
    # the softmax statistics must cross tp: all-reduce present
    assert "all-reduce" in hlo


def test_parallel_ce_backward_no_vocab_allgather(tp_mesh):
    col = mpu.ColumnParallelLinear(64, V, has_bias=False,
                                   gather_output=False)
    ce = mpu.ParallelCrossEntropy()
    labels = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, V)

    def pure(warrs, x):
        def loss(w, x):
            with _bind_params([col.weight], [w]), _tape.no_grad():
                return ce(col(Tensor(x)), Tensor(labels)).mean().data
        return jax.grad(loss)(warrs[0], x)

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 64), jnp.float32)
    with tp_mesh.mesh:
        hlo = jax.jit(pure).lower([col.weight.data], x).compile().as_text()
    hits = _allgather_dim_hit(hlo, V)
    assert not hits, "vocab logits all-gathered in bwd:\n" + "\n".join(
        hits[:3])


def test_row_parallel_allreduces_partials(tp_mesh):
    IN, OUT = 512, 64
    row = mpu.RowParallelLinear(IN, OUT, has_bias=False,
                                input_is_parallel=True)

    def fwd(x):
        x = mpu.split(x, axis=x.ndim - 1)  # tp-shard the contraction dim
        return row(x)

    x = jax.random.normal(jax.random.PRNGKey(0), (8, IN), jnp.float32)
    hlo = _compile_layer_fn(tp_mesh, [row.weight], fwd, x)
    # partial products must be summed across tp...
    assert ("all-reduce" in hlo) or ("reduce-scatter" in hlo), \
        "no cross-tp reduction of row-parallel partial products"
    # ...and the sharded input must not be re-gathered to full width
    hits = _allgather_dim_hit(hlo, IN)
    assert not hits, "row-parallel input all-gathered:\n" + "\n".join(
        hits[:3])


def test_row_sequence_parallel_scatter_output(tp_mesh):
    IN, OUT, B, T = 256, 128, 2, 64
    row = RowSequenceParallelLinear(IN, OUT, has_bias=False,
                                    input_is_parallel=True)

    def fwd(x):
        x = mpu.split(x, axis=x.ndim - 1)
        return row(x)

    x = jax.random.normal(jax.random.PRNGKey(0), (B, T, IN), jnp.float32)
    hlo = _compile_layer_fn(tp_mesh, [row.weight], fwd, x)
    # output returns to sequence-sharded layout: GSPMD fuses the partial
    # sum + seq split into reduce-scatter (TPU) or all-to-all+add (CPU
    # partitioner) — either proves no full [B, T, OUT] replication + slice
    assert ("reduce-scatter" in hlo) or ("all-to-all" in hlo) or \
        ("all-reduce" in hlo), "no collective on the SP output path"
    hits = _allgather_dim_hit(hlo, IN)
    assert not hits


def test_parallel_ce_numerics_match_dense(tp_mesh):
    # layer path == unsharded dense reference, on real values
    col = mpu.ColumnParallelLinear(32, 128, has_bias=False,
                                   gather_output=False)
    ce = mpu.ParallelCrossEntropy()
    x = np.random.RandomState(0).randn(4, 16, 32).astype(np.float32)
    labels = np.random.RandomState(1).randint(0, 128, (4, 16))
    out = ce(col(Tensor(jnp.asarray(x))), Tensor(jnp.asarray(labels)))
    w = np.asarray(col.weight.data)
    logits = x @ w
    lse = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) \
        + logits.max(-1)
    want = lse - np.take_along_axis(logits, labels[..., None], -1)[..., 0]
    np.testing.assert_allclose(np.asarray(out.data), want, rtol=2e-4,
                               atol=2e-4)

"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's fake-device testing pattern (SURVEY.md §4: the
custom_cpu plugin masquerading as a device, test/custom_runtime/): here the
fake devices are XLA host-platform devices, so multi-chip sharding code paths
(pjit/shard_map/collectives) execute for real without TPUs.

Tiers (VERDICT r5 Weak #7 — the suite must be runnable in one sitting):
  * ``pytest -m smoke``     — the <10-minute core: model math, decode,
    serving, ops, autograd (the modules listed in _SMOKE_MODULES).
  * ``pytest -m 'not slow'`` — tier-1, everything but the long benches.
  * ``pytest``               — tier-1 + tier-2 benchmarks.

XLA programs compile once per machine: a persistent compilation cache
(JAX_COMPILATION_CACHE_DIR, default ~/.cache/paddle_tpu/xla) makes
repeat runs skip recompiles — measured ~3x on a compile-heavy program,
and it is the difference between the full tier-1 suite fitting its time
budget or not on a cold container vs a warm one.
"""
import os

# force CPU: the session env pins JAX_PLATFORMS to the TPU tunnel, which
# must not be grabbed by the test suite (single-chip lock + slow compiles).
from paddle_tpu.testing import force_host_cpu_devices

force_host_cpu_devices(8)

import numpy as np
import pytest

import jax

# numeric tests compare against float64 numpy; use full-precision dots
# (production/bench keeps JAX's default TPU-friendly precision)
jax.config.update("jax_default_matmul_precision", "highest")

# every ServingEngine the suite builds runs the paged-KV invariant
# checker after every tick (analysis/kv_invariants.py): the engine
# tests in the smoke tier double as a continuous audit of page
# ownership / refcounts / dead-slot rows — a bookkeeping bug fails the
# suite at the tick that introduced it, not at some later token
# mismatch. (Tests that need it OFF pass check_invariants=False.)
os.environ.setdefault("PADDLE_TPU_SERVING_CHECK_INVARIANTS", "1")

# persistent XLA compile cache: repeat suite runs (and reruns of a
# single failing test) skip recompilation entirely
_cache_dir = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu", "xla"))
try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
except Exception:
    pass  # older jax without the flags: in-memory cache only


# the <10-minute core tier: every module here exercises a distinct
# subsystem's hot path (picked by measured module runtime, see
# docs/PERF.md "suite tiers" note)
_SMOKE_MODULES = {
    "test_ops", "test_autograd", "test_llama", "test_generate",
    "test_paged_kv", "test_int8_decode", "test_inference", "test_moe",
    "test_pallas_kernels", "test_distributed", "test_prefix_cache",
    "test_analysis", "test_rewrite", "test_ragged_attention",
    "test_observability", "test_pipeline_async", "test_speculative",
    "test_fused_sampling", "test_auto_parallel_planner", "test_fleet",
    "test_fleet_proc", "test_migration", "test_concurrency_lint",
    "test_kernel_audit",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tier-2 benchmarks (tier-1 runs -m 'not slow')")
    config.addinivalue_line(
        "markers",
        "smoke: <10-min core tier (one fast module per subsystem; "
        "run with -m smoke)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rpartition(".")[-1]
        if mod in _SMOKE_MODULES and "slow" not in item.keywords:
            item.add_marker(pytest.mark.smoke)


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as pt
    pt.seed(2024)
    np.random.seed(2024)
    yield

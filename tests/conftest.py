"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's fake-device testing pattern (SURVEY.md §4: the
custom_cpu plugin masquerading as a device, test/custom_runtime/): here the
fake devices are XLA host-platform devices, so multi-chip sharding code paths
(pjit/shard_map/collectives) execute for real without TPUs.
"""
# force CPU: the session env pins JAX_PLATFORMS to the TPU tunnel, which
# must not be grabbed by the test suite (single-chip lock + slow compiles).
from paddle_tpu.testing import force_host_cpu_devices

force_host_cpu_devices(8)

import numpy as np
import pytest

import jax

# numeric tests compare against float64 numpy; use full-precision dots
# (production/bench keeps JAX's default TPU-friendly precision)
jax.config.update("jax_default_matmul_precision", "highest")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tier-2 benchmarks (tier-1 runs -m 'not slow')")


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as pt
    pt.seed(2024)
    np.random.seed(2024)
    yield

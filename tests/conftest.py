"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's fake-device testing pattern (SURVEY.md §4: the
custom_cpu plugin masquerading as a device, test/custom_runtime/): here the
fake devices are XLA host-platform devices, so multi-chip sharding code paths
(pjit/shard_map/collectives) execute for real without TPUs.
"""
import os

# force CPU: the session env pins JAX_PLATFORMS to the TPU tunnel, which
# must not be grabbed by the test suite (single-chip lock + slow compiles).
# NOTE: the sandbox's sitecustomize pre-imports jax, so env vars are read
# too late — the platform must be set via jax.config before the (lazy)
# backend initialisation; XLA_FLAGS is still read at client creation.
import re

xla_flags = os.environ.get("XLA_FLAGS", "")
xla_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   xla_flags)  # the suite needs exactly 8 virtual devices
os.environ["XLA_FLAGS"] = (
    xla_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu" and len(jax.devices()) == 8

# numeric tests compare against float64 numpy; use full-precision dots
# (production/bench keeps JAX's default TPU-friendly precision)
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as pt
    pt.seed(2024)
    np.random.seed(2024)
    yield

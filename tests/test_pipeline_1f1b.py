"""1F1B / interleaved-VPP pipeline schedule (parallel/pipeline_1f1b.py).

Reference capabilities covered: pipeline_parallel.py:565
forward_backward_pipeline (1F1B numerics + O(S) activation memory) and
:1372 interleaved VPP round-robin partitioning.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.parallel import init_hybrid_mesh


def _cfg(pp, schedule="1f1b", vpp=1, M=8, layers=4):
    return L.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=layers, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=32,
        dtype=jnp.float32, remat=False, use_flash_attention=False,
        pp_stages=pp, num_microbatches=M, pp_schedule=schedule,
        vpp_chunks=vpp)


def _loss_and_grads(cfg, mesh, params, batch):
    if cfg.pp_stages > 1 and cfg.pp_schedule == "1f1b":
        return L.grads_1f1b(params, batch, cfg, mesh)
    return jax.value_and_grad(L.loss_fn)(params, batch, cfg, mesh)


def _tree_close(a, b, rtol, atol):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(x, y, rtol=rtol, atol=atol)


@pytest.mark.parametrize("pp,vpp,M", [(2, 1, 8), (4, 1, 8), (2, 2, 8)])
def test_1f1b_matches_single_stage(pp, vpp, M):
    """Loss and every grad from the explicit 1F1B schedule (incl. VPP)
    must match plain single-stage autodiff at M microbatches."""
    hm = init_hybrid_mesh(dp=1, pp=pp, tp=1, set_global=False)
    cfg = _cfg(pp, "1f1b", vpp, M)
    ref_cfg = _cfg(1, "gpipe", 1, 1)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    with hm.mesh:
        batch = L.make_batch(cfg, batch_size=M, seq_len=32, mesh=hm.mesh)
        loss_p, grads_p = jax.jit(
            lambda p, b: _loss_and_grads(cfg, hm.mesh, p, b))(params, batch)
    hm1 = init_hybrid_mesh(dp=1, pp=1, tp=1, set_global=False)
    with hm1.mesh:
        loss_r, grads_r = jax.jit(
            lambda p, b: _loss_and_grads(ref_cfg, hm1.mesh, p, b))(
            params, batch)
    np.testing.assert_allclose(loss_p, loss_r, rtol=1e-5, atol=1e-6)
    _tree_close(grads_p, grads_r, rtol=2e-4, atol=1e-5)


def test_1f1b_train_step_runs_and_loss_falls():
    hm = init_hybrid_mesh(dp=1, pp=2, tp=1, set_global=False)
    cfg = _cfg(2, "1f1b", 1, 4)
    with hm.mesh:
        step, init = L.make_train_step(cfg, hm.mesh)
        state = init(jax.random.PRNGKey(0))
        batch = L.make_batch(cfg, batch_size=4, seq_len=32, mesh=hm.mesh)
        losses = []
        for _ in range(5):
            state, loss = step(state, batch)
            losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_1f1b_uses_less_activation_memory_than_gpipe():
    """The schedule's reason to exist: per-stage live activations are
    O(S), not O(M). Compare XLA's compiled peak temp memory at M=16."""
    M, pp = 16, 2
    hm = init_hybrid_mesh(dp=1, pp=pp, tp=1, set_global=False)
    params = L.init_params(_cfg(pp), jax.random.PRNGKey(0))

    def peak_temp(cfg):
        with hm.mesh:
            batch = L.make_batch(cfg, batch_size=M, seq_len=32,
                                 mesh=hm.mesh)
            compiled = jax.jit(
                lambda p, b: _loss_and_grads(cfg, hm.mesh, p, b)).lower(
                params, batch).compile()
        ma = compiled.memory_analysis()
        assert ma is not None, "memory_analysis unavailable"
        return ma.temp_size_in_bytes

    t_1f1b = peak_temp(_cfg(pp, "1f1b", 1, M))
    t_gpipe = peak_temp(_cfg(pp, "gpipe", 1, M))
    assert t_1f1b < t_gpipe, (t_1f1b, t_gpipe)


def test_vpp_round_robin_chunk_layout():
    from paddle_tpu.parallel.pipeline_1f1b import split_chunks_round_robin
    layers = {"w": jnp.arange(8)[:, None] * jnp.ones((8, 3))}
    chunks = split_chunks_round_robin(layers, 8, num_stages=2,
                                      virtual_chunks=2)
    # chunk k holds contiguous layer block k; chunk index = v*S + s
    assert chunks["w"].shape == (4, 2, 3)
    np.testing.assert_array_equal(np.asarray(chunks["w"][1, :, 0]), [2, 3])


def test_bad_schedule_name_rejected():
    hm = init_hybrid_mesh(dp=1, pp=2, tp=1, set_global=False)
    cfg = _cfg(2, "zigzag")
    with pytest.raises(ValueError, match="pp_schedule"):
        L.make_train_step(cfg, hm.mesh)


def _scan_lengths(jaxpr, out):
    """Collect every lax.scan trip count in a (closed) jaxpr tree."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            out.add(int(eqn.params["length"]))
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                _scan_lengths(inner, out)
            if isinstance(v, (list, tuple)):
                for w in v:
                    inner = getattr(w, "jaxpr", None)
                    if inner is not None:
                        _scan_lengths(inner, out)
    return out


def test_schedule_efficiency_measured_from_traced_program():
    """VERDICT r3: pipeline efficiency must be MEASURED, not assumed.

    XLA's cost_analysis counts a while-loop body ONCE (trip counts are
    invisible to it), so the measurement is structural: the traced
    program's schedule scan must run exactly M + 2S - 1 ticks — every
    tick executes all S slots (the lockstep design) — making the
    measured efficiency M/ticks, which must equal the analytic
    schedule_efficiency. Also checks per-tick work scales with the
    microbatch size via cost_analysis (body-once semantics)."""
    from paddle_tpu.parallel.pipeline_1f1b import schedule_efficiency

    def program_of(M):
        cfg = L.LlamaConfig.tiny(dtype=jnp.float32,
                                 use_flash_attention=False, remat=False,
                                 pp_stages=2, pp_schedule="1f1b",
                                 num_microbatches=M)
        hm = init_hybrid_mesh(dp=1, pp=2, tp=1, set_global=False)
        with hm.mesh:
            step, init = L.make_train_step(cfg, hm.mesh)
            state = init(jax.random.PRNGKey(0))
            batch = L.make_batch(cfg, batch_size=8, seq_len=16,
                                 mesh=hm.mesh)
            from paddle_tpu.analysis.hbm import xla_cost_analysis
            jaxpr = jax.make_jaxpr(step.__wrapped__)(state, batch)
            flops = float(xla_cost_analysis(jax.jit(
                step.__wrapped__, donate_argnums=(0,)).lower(
                state, batch).compile())["flops"])
        return jaxpr, flops

    S = 2
    per_tick = {}
    for M in (2, 8):
        jaxpr, flops = program_of(M)
        lengths = _scan_lengths(jaxpr.jaxpr, set())
        ticks = M + 2 * S - 1
        # the schedule scan runs EXACTLY the predicted tick count —
        # fill/drain included; this IS the measured bubble
        assert ticks in lengths, (M, sorted(lengths))
        assert schedule_efficiency(S, M) == pytest.approx(M / ticks)
        per_tick[M] = flops
    # body-once flop accounting: per-tick work scales with the
    # microbatch size (8/M), confirming every tick computes all slots
    assert per_tick[2] / per_tick[8] == pytest.approx(4.0, rel=0.35), \
        per_tick


def test_schedule_efficiency_analytic_properties():
    from paddle_tpu.parallel.pipeline_1f1b import schedule_efficiency
    # S=1 still pays one drain tick (the loss head/bwd tail of the
    # lockstep schedule): M/(M+1)
    assert schedule_efficiency(1, 1) == pytest.approx(1 / 2)
    assert schedule_efficiency(2, 2) == pytest.approx(2 / 5)
    assert schedule_efficiency(4, 32) == pytest.approx(32 / 39)
    # VPP does not change the bubble in the traced form (documented)
    assert schedule_efficiency(2, 4, virtual_chunks=2) == \
        schedule_efficiency(2, 4)
    with pytest.raises(ValueError):
        schedule_efficiency(0, 4)


def test_schedule_efficiency_models_async_schedules():
    """The extended model (ISSUE 10): rank-asymmetric 1F1B lands the
    reference per-rank bubble M/(M+S-1) — 0.889 at pp=2/M=8, 0.970 at
    M=32 — interleaved V>1 is 1-(S-1)/(VM+S-1), and ZB-H1 W-deferral
    beats both (3M/(3M+S-1) in the M>=S regime)."""
    from paddle_tpu.parallel.pipeline_1f1b import (schedule_efficiency,
                                                   schedule_ticks)
    assert schedule_efficiency(2, 8, schedule="1f1b") == \
        pytest.approx(8 / 9)       # 0.889, the reference 1F1B number
    assert schedule_efficiency(2, 32, schedule="1f1b") == \
        pytest.approx(32 / 33)     # 0.970
    assert schedule_efficiency(2, 8, 2, schedule="1f1b") == \
        pytest.approx(16 / 17)     # interleaved V=2
    assert schedule_efficiency(2, 8, schedule="zb") == \
        pytest.approx(24 / 25)     # 0.96 > 0.889
    assert schedule_ticks(2, 8, schedule="1f1b") == 18
    assert schedule_ticks(2, 8, schedule="zb") == 25
    assert schedule_ticks(4, 8, schedule="1f1b") == 22
    for S, M in ((2, 8), (4, 16), (8, 32)):
        assert schedule_efficiency(S, M, schedule="zb") > \
            schedule_efficiency(S, M, schedule="1f1b") > \
            schedule_efficiency(S, M, schedule="lockstep")

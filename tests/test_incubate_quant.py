"""LBFGS, ASP n:m sparsity, and int8 PTQ deployment.

Mirrors reference tests: test/legacy_test/test_lbfgs_class.py (rosenbrock
/ quadratic convergence), test/asp/test_asp_pruning_*.py (mask validity +
density), test/quantization/test_ptq.py (observer->convert numerics).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.optimizer import LBFGS


class TinyMLP(nn.Layer):
    def __init__(self, din=8, dh=16, dout=4):
        super().__init__()
        self.fc1 = nn.Linear(din, dh)
        self.fc2 = nn.Linear(dh, dout)

    def forward(self, x):
        return self.fc2(pt.nn.functional.relu(self.fc1(x)))


# ---------------------------------------------------------------- LBFGS
def test_lbfgs_quadratic_converges():
    # min ||Ax - b||^2 — LBFGS should reach machine-precision optimum fast
    rng = np.random.RandomState(0)
    A = rng.randn(10, 6).astype(np.float32)
    b = rng.randn(10).astype(np.float32)
    x = pt.create_parameter([6], "float32")

    opt = LBFGS(parameters=[x], line_search_fn="strong_wolfe", max_iter=50)

    def closure():
        opt.clear_grad()
        r = pt.to_tensor(A) @ x - pt.to_tensor(b)
        loss = (r * r).sum()
        loss.backward()
        return loss

    for _ in range(5):
        opt.step(closure)
    x_star, *_ = np.linalg.lstsq(A, b, rcond=None)
    np.testing.assert_allclose(np.asarray(x.data), x_star, atol=1e-4)


def test_lbfgs_no_line_search_descends():
    w = pt.create_parameter([4], "float32")

    opt = LBFGS(parameters=[w], learning_rate=1.0, max_iter=10)

    def closure():
        opt.clear_grad()
        loss = ((w - 3.0) ** 2).sum()
        loss.backward()
        return loss

    first = float(opt.step(closure))
    for _ in range(3):
        last = float(opt.step(closure))
    assert last < first
    np.testing.assert_allclose(np.asarray(w.data), 3.0, atol=1e-3)


# ------------------------------------------------------------------ ASP
def test_asp_mask_and_prune():
    from paddle_tpu.incubate import asp

    w = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    mask = np.asarray(asp.get_mask_1d(w, 2, 4))
    assert asp.check_mask_1d(mask, 2, 4)
    # mask keeps exactly the 2 largest |w| per group of 4
    groups = (np.abs(w) * mask.reshape(w.shape)).reshape(8, 4, 4)
    kept_min = np.sort(groups, axis=-1)[..., -2]          # smallest kept
    dropped = (np.abs(w).reshape(8, 4, 4) * (1 - mask.reshape(8, 4, 4)))
    assert (dropped.max(-1) <= kept_min + 1e-6).all()

    model = TinyMLP()
    masks = asp.prune_model(model, n=2, m=4)
    assert set(masks) == {"fc1.weight", "fc2.weight"}
    for _, p in [("fc1", model.fc1.weight), ("fc2", model.fc2.weight)]:
        assert asp.check_sparsity(np.asarray(p.data), 2, 4)
        assert abs(asp.calculate_density(p) - 0.5) < 0.05


def test_asp_decorated_optimizer_keeps_sparsity():
    from paddle_tpu.incubate import asp

    model = TinyMLP()
    asp.prune_model(model, n=2, m=4)
    opt = asp.decorate(pt.optimizer.SGD(
        learning_rate=0.1, parameters=model.parameters()))
    x = pt.to_tensor(np.random.RandomState(2).randn(4, 8).astype(np.float32))
    for _ in range(3):
        loss = (model(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert asp.check_sparsity(np.asarray(model.fc1.weight.data), 2, 4)
    assert asp.check_sparsity(np.asarray(model.fc2.weight.data), 2, 4)


def test_asp_mask_2d_greedy():
    from paddle_tpu.incubate import asp

    w = np.random.RandomState(7).randn(8, 8).astype(np.float32)
    mask = np.asarray(asp.get_mask_2d_greedy(w, 2, 4))
    assert asp.check_mask_2d(mask, 2, 4)
    assert not asp.check_mask_2d(np.ones((8, 8)), 2, 4)
    model = TinyMLP(din=8, dh=16, dout=4)
    masks = asp.prune_model(model, n=2, m=4, mask_algo="mask_2d_greedy")
    # fc1 [8,16] divisible both dims -> 2D mask; fc2 [16,4] row dim 16 ok
    assert "fc1.weight" in masks
    assert asp.check_mask_2d(np.asarray(model.fc1.weight.data), 2, 4)


def test_lbfgs_state_dict_roundtrip_and_clip():
    from paddle_tpu.nn import ClipGradByNorm

    w = pt.create_parameter([4], "float32")
    opt = LBFGS(parameters=[w], learning_rate=1.0, max_iter=3,
                grad_clip=ClipGradByNorm(0.5))

    def closure():
        opt.clear_grad()
        loss = ((w - 2.0) ** 2).sum()
        loss.backward()
        return loss

    opt.step(closure)
    sd = opt.state_dict()
    assert "step_count" in sd and "n_iter" in sd  # base + lbfgs state
    w2 = pt.create_parameter([4], "float32")
    opt2 = LBFGS(parameters=[w2], learning_rate=1.0, max_iter=3)
    opt2.set_state_dict(sd)
    assert opt2._n_iter == opt._n_iter
    assert int(np.asarray(opt2._step_count.data)) == \
        int(np.asarray(opt._step_count.data))


def test_asp_excluded_layers():
    from paddle_tpu.incubate import asp

    model = TinyMLP()
    asp.set_excluded_layers(model, ["fc2"])
    masks = asp.prune_model(model, n=2, m=4)
    assert "fc1.weight" in masks and "fc2.weight" not in masks
    asp.reset_excluded_layers(model)


# ------------------------------------------------------------- int8 PTQ
def test_ptq_convert_int8_numerics():
    import jax.numpy as jnp
    from paddle_tpu.quantization import (
        PTQ, QuantConfig, AbsmaxObserver, ChannelWiseAbsmaxObserver,
        Int8Linear)

    model = TinyMLP(din=8, dh=32, dout=4)
    cfg = QuantConfig(activation=AbsmaxObserver,
                      weight=ChannelWiseAbsmaxObserver)
    ptq = PTQ(cfg)
    q = ptq.quantize(model)
    x = pt.to_tensor(np.random.RandomState(3).randn(16, 8).astype(np.float32))
    q(x)  # calibrate
    deployed = ptq.convert(q)
    assert isinstance(deployed.fc1, Int8Linear)
    assert deployed.fc1.qweight.data.dtype == jnp.int8
    # converted scales == the per-channel absmax the observer recorded
    np.testing.assert_allclose(
        np.asarray(deployed.fc1.scales.data),
        np.abs(np.asarray(model.fc1.weight.data)).max(0), rtol=1e-6)
    ref = np.asarray(model(x).data)
    got = np.asarray(deployed(x).data)
    # int8 weight-only: small relative error vs float model
    denom = max(np.abs(ref).max(), 1e-6)
    assert np.abs(got - ref).max() / denom < 0.05
    # int8 weights + scales survive a state_dict round trip
    sd = deployed.state_dict()
    assert any("qweight" in k for k in sd)
    fresh = ptq.convert(ptq.quantize(TinyMLP(din=8, dh=32, dout=4)))
    fresh(x)
    fresh.set_state_dict(sd)
    np.testing.assert_allclose(np.asarray(fresh(x).data), got, atol=1e-6)


def test_masked_multihead_attention_matches_reference_loop():
    """Decode-step fused attention (incubate.nn.functional
    masked_multihead_attention): per-row cache scatter + causal-masked
    softmax over the valid prefix, vs a numpy transcript."""
    from paddle_tpu.incubate.nn.functional import masked_multihead_attention

    rng = np.random.RandomState(0)
    B, H, S, D = 3, 2, 8, 4
    x = rng.randn(B, 3 * H * D).astype(np.float32)
    cache = rng.randn(2, B, H, S, D).astype(np.float32)
    lens = np.asarray([2, 5, 0], np.int32)   # new token's position per row
    bias = rng.randn(3, H, D).astype(np.float32)

    out, ck = masked_multihead_attention(
        x, cache_kv=cache.copy(), bias=bias, sequence_lengths=lens)
    out, ck = np.asarray(out), np.asarray(ck)

    qkv = x.reshape(B, 3, H, D) + bias[None]
    for b in range(B):
        p = int(lens[b])
        ref_k = cache[0, b].copy()
        ref_v = cache[1, b].copy()
        ref_k[:, p] = qkv[b, 1]
        ref_v[:, p] = qkv[b, 2]
        np.testing.assert_allclose(ck[0, b], ref_k, rtol=1e-5)
        np.testing.assert_allclose(ck[1, b], ref_v, rtol=1e-5)
        for h in range(H):
            s = ref_k[h, :p + 1] @ qkv[b, 0, h] / np.sqrt(D)
            w = np.exp(s - s.max()); w /= w.sum()
            ref_o = w @ ref_v[h, :p + 1]
            np.testing.assert_allclose(out[b, h * D:(h + 1) * D], ref_o,
                                       rtol=1e-4, atol=1e-5)


def test_masked_multihead_attention_validation():
    from paddle_tpu.incubate.nn.functional import masked_multihead_attention
    with pytest.raises(ValueError, match="cache_kv"):
        masked_multihead_attention(np.zeros((1, 24), np.float32))
    with pytest.raises(NotImplementedError, match="beam"):
        masked_multihead_attention(
            np.zeros((1, 24), np.float32),
            cache_kv=np.zeros((2, 1, 2, 4, 4), np.float32),
            beam_cache_offset=np.zeros((1, 1, 8)))


def test_masked_multihead_attention_mask_broadcast_and_guards():
    from paddle_tpu.incubate.nn.functional import masked_multihead_attention
    rng = np.random.RandomState(1)
    B, H, S, D = 3, 2, 4, 4
    x = rng.randn(B, 3 * H * D).astype(np.float32)
    cache = rng.randn(2, B, H, S, D).astype(np.float32)
    # shared-across-batch additive mask [1, 1, 1, S] must broadcast
    mask = np.zeros((1, 1, 1, S), np.float32)
    out0, _ = masked_multihead_attention(x, cache_kv=cache.copy(),
                                         sequence_lengths=np.full(B, 2))
    out1, _ = masked_multihead_attention(x, cache_kv=cache.copy(),
                                         sequence_lengths=np.full(B, 2),
                                         src_mask=mask)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(out1),
                               rtol=1e-6)
    # unsupported features raise instead of silently corrupting decode
    with pytest.raises(NotImplementedError, match="rope|rotary"):
        masked_multihead_attention(x, cache_kv=cache.copy(),
                                   rotary_tensor=np.zeros((B, 1, 1, S, D)))
    with pytest.raises(NotImplementedError, match="quant"):
        masked_multihead_attention(x, cache_kv=cache.copy(),
                                   qkv_out_scale=np.ones((3, H, D)))

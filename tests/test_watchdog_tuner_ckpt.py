"""Watchdog (distributed/watchdog.py), auto-tuner
(distributed/auto_tuner.py), and async checkpointing
(distributed/checkpoint.py async_save).

Reference capabilities: comm_task_manager.cc:43-59 (hang watchdog),
python/paddle/distributed/auto_tuner/ (config search),
save_state_dict.py async queue.
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.watchdog import Watchdog
from paddle_tpu.distributed.auto_tuner import (ModelDesc, search,
                                               estimate_memory, Candidate)


# ------------------------------------------------------------- watchdog ----

def test_watchdog_fires_on_stall_and_not_on_heartbeats():
    import io
    log = io.StringIO()
    fired = []
    wd = Watchdog(timeout=0.4, on_timeout=fired.append, log_stream=log)
    with wd:
        for _ in range(6):  # healthy loop: heartbeats keep it quiet
            time.sleep(0.1)
            wd.heartbeat(step=1)
        assert not wd.fired
        time.sleep(0.9)  # stall > timeout
    assert wd.fired and fired and fired[0]["last_step"] == 1
    assert "watchdog" in log.getvalue()
    assert "Thread" in log.getvalue() or "File" in log.getvalue()


def test_watchdog_stop_prevents_firing():
    fired = []
    wd = Watchdog(timeout=0.3, on_timeout=fired.append)
    wd.start()
    wd.stop()
    time.sleep(0.5)
    assert not fired


def test_watchdog_rejects_bad_timeout():
    with pytest.raises(ValueError):
        Watchdog(timeout=0)


# ------------------------------------------------------------ auto-tuner ----

LLAMA7B = ModelDesc(hidden=4096, layers=32, ffn=11008, vocab=32000,
                    heads=32, seq_len=2048, global_batch=32)


def test_search_prunes_infeasible_single_chip():
    # 7B on ONE 16 GiB chip cannot hold adamw state: nothing feasible
    res = search(1, LLAMA7B, hbm_bytes=16e9)
    assert res == []


def test_search_finds_sharded_configs_on_32_chips():
    res = search(32, LLAMA7B, hbm_bytes=16e9)
    assert res, "expected feasible configs on 32 chips"
    best = res[0]
    assert best.world == 32
    assert best.tp * best.pp * (best.dp if best.zero >= 3 else 1) > 1
    # every returned config satisfies the memory model
    assert all(c.mem_bytes <= 16e9 for c in res)


def test_memory_model_monotone_in_tp():
    m = LLAMA7B
    base = estimate_memory(m, Candidate(dp=1, tp=1, pp=1))
    tp8 = estimate_memory(m, Candidate(dp=1, tp=8, pp=1))
    assert tp8 < base / 4


def test_bubble_penalizes_small_microbatch_pp():
    from paddle_tpu.distributed.auto_tuner import estimate_step_cost
    m = LLAMA7B
    few = estimate_step_cost(m, Candidate(dp=1, tp=1, pp=8,
                                          microbatches=1))
    many = estimate_step_cost(m, Candidate(dp=1, tp=1, pp=8,
                                           microbatches=8))
    assert many < few


def test_measure_rerank_hook():
    m = ModelDesc(hidden=64, layers=4, ffn=128, vocab=256, heads=4,
                  global_batch=8, seq_len=64)
    calls = []

    def fake_measure(c):
        calls.append(c)
        return 1.0 if c.tp == 1 else 0.5  # pretend tp wins

    res = search(4, m, hbm_bytes=16e9, measure=fake_measure, top_k=3)
    assert calls, "measure hook not invoked"
    assert res[0].step_cost == min(c.step_cost for c in res[:3])


# ------------------------------------------------------- async checkpoint ----

def test_async_save_returns_fast_and_roundtrips(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from paddle_tpu.distributed.checkpoint import (save_state_dict,
                                                   load_state_dict)
    big = pt.to_tensor(np.random.randn(512, 512).astype(np.float32))
    state = {"w": big}
    path = str(tmp_path / "ck")
    ck = save_state_dict(state, path, async_save=True)
    assert hasattr(ck, "wait_until_finished")
    ck.wait_until_finished()
    target = {"w": pt.to_tensor(np.zeros((512, 512), np.float32))}
    load_state_dict(target, path)
    np.testing.assert_allclose(target["w"].numpy(), big.numpy())

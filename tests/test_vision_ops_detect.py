"""yolo_loss + generate_proposals (closing paddle.vision.ops).

Reference tests: test/legacy_test/test_yolov3_loss_op.py,
test_generate_proposals_v2_op.py.
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.vision import ops as V


def test_yolo_loss_basic_properties():
    rng = np.random.RandomState(0)
    B, na, C, H = 2, 3, 4, 8
    x = pt.to_tensor(rng.randn(B, na * (5 + C), H, H).astype(np.float32) * 0.1)
    gt_box = np.zeros((B, 5, 4), np.float32)
    gt_box[0, 0] = [0.5, 0.5, 0.3, 0.4]   # one real box in image 0
    gt_label = np.zeros((B, 5), np.int32)
    gt_label[0, 0] = 2
    loss = V.yolo_loss(x, pt.to_tensor(gt_box), pt.to_tensor(gt_label),
                       anchors=[10, 13, 16, 30, 33, 23],
                       anchor_mask=[0, 1, 2], class_num=C,
                       ignore_thresh=0.7, downsample_ratio=32)
    v = np.asarray(loss.data)
    assert v.shape == (B,)
    assert np.isfinite(v).all() and (v > 0).all()
    # the image with a gt box pays coordinate+class terms -> higher loss
    assert v[0] > v[1]


def test_yolo_loss_differentiable():
    rng = np.random.RandomState(1)
    B, na, C, H = 1, 3, 3, 4
    x = pt.to_tensor(rng.randn(B, na * (5 + C), H, H).astype(np.float32) * 0.1)
    x.stop_gradient = False
    gt_box = np.zeros((B, 2, 4), np.float32)
    gt_box[0, 0] = [0.4, 0.6, 0.2, 0.2]
    gt_label = np.zeros((B, 2), np.int32)
    loss = V.yolo_loss(x, pt.to_tensor(gt_box), pt.to_tensor(gt_label),
                       anchors=[10, 13, 16, 30, 33, 23],
                       anchor_mask=[0, 1, 2], class_num=C,
                       ignore_thresh=0.7, downsample_ratio=32)
    loss.sum().backward()
    g = np.asarray(x._grad.data)
    assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_yolo_loss_padding_invariant():
    # trailing all-zero gt padding (standard fixed-n_gt batching) must
    # not change the loss — regression for padded boxes clobbering the
    # (0, 0, 0) target slot
    rng = np.random.RandomState(7)
    B, na, C, H = 1, 3, 3, 8
    x = rng.randn(B, na * (5 + C), H, H).astype(np.float32) * 0.1
    gt1 = np.zeros((B, 1, 4), np.float32)
    gt1[0, 0] = [0.05, 0.05, 0.3, 0.4]   # center in cell (0, 0)
    lb1 = np.full((B, 1), 2, np.int32)
    gt2 = np.zeros((B, 6, 4), np.float32)
    gt2[0, 0] = gt1[0, 0]
    lb2 = np.zeros((B, 6), np.int32)
    lb2[0, 0] = 2
    kw = dict(anchors=[10, 13, 16, 30, 33, 23], anchor_mask=[0, 1, 2],
              class_num=C, ignore_thresh=0.7, downsample_ratio=32)
    l1 = float(np.asarray(V.yolo_loss(pt.to_tensor(x), pt.to_tensor(gt1),
                                      pt.to_tensor(lb1), **kw).data)[0])
    l2 = float(np.asarray(V.yolo_loss(pt.to_tensor(x), pt.to_tensor(gt2),
                                      pt.to_tensor(lb2), **kw).data)[0])
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_generate_proposals():
    rng = np.random.RandomState(2)
    B, A, H, W = 1, 3, 4, 4
    scores = rng.rand(B, A, H, W).astype(np.float32)
    deltas = rng.randn(B, 4 * A, H, W).astype(np.float32) * 0.1
    # simple anchor grid: 16x16 boxes at stride 16
    anchors = np.zeros((H, W, A, 4), np.float32)
    for y in range(H):
        for x in range(W):
            for a in range(A):
                size = 8 * (a + 1)
                cx, cy = x * 16 + 8, y * 16 + 8
                anchors[y, x, a] = [cx - size, cy - size, cx + size, cy + size]
    variances = np.ones_like(anchors)
    rois, rscores, num = V.generate_proposals(
        pt.to_tensor(scores), pt.to_tensor(deltas),
        pt.to_tensor(np.asarray([[64, 64]], np.float32)),
        pt.to_tensor(anchors), pt.to_tensor(variances),
        pre_nms_top_n=30, post_nms_top_n=10, nms_thresh=0.6,
        min_size=2.0, return_rois_num=True)
    r = np.asarray(rois.data)
    n = int(np.asarray(num.data)[0])
    assert r.shape == (n, 4) and 0 < n <= 10
    # clipped to image bounds, valid boxes
    assert (r[:, 0] >= 0).all() and (r[:, 2] <= 64).all()
    assert (r[:, 2] >= r[:, 0]).all() and (r[:, 3] >= r[:, 1]).all()
    s = np.asarray(rscores.data).ravel()
    assert (np.diff(s) <= 1e-6).all()  # score-descending

"""Dynamic-shape bucketing (jit/bucketing.py — the DimExpr/bucketed
lowering counterpart, dim_expr.h:168-177 / op_lowering_impl.h:61)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.jit.bucketing import bucketed, bucket_size, \
    BucketedFunction


def test_bucket_ladder():
    assert bucket_size(1) == 1
    assert bucket_size(3) == 4
    assert bucket_size(128) == 128
    assert bucket_size(129) == 256
    with pytest.raises(ValueError):
        bucket_size(10 ** 9)


def test_one_compile_per_bucket_many_sizes():
    traces = []

    @bucketed(axis=0)
    def f(x):
        traces.append(1)  # runs only when (re)tracing
        return x * 2.0

    for n in (3, 4, 2, 7, 8, 5, 6, 1):
        out = f(jnp.ones((n, 4)))
        assert out.shape == (n, 4)
        np.testing.assert_allclose(np.asarray(out), 2.0)
    # sizes 1..8 span buckets {1,2,4,8} -> at most 4 traces, not 8
    assert len(traces) <= 4, traces


def test_masking_with_valid_len():
    @bucketed(axis=0, with_length=True)
    def mean_rows(x, valid_len):
        mask = (jnp.arange(x.shape[0]) < valid_len)[:, None]
        return jnp.sum(x * mask) / (valid_len * x.shape[1])

    x = np.full((5, 2), 3.0, np.float32)
    out = mean_rows(x)  # padded to bucket 8; padding masked out
    np.testing.assert_allclose(float(out), 3.0, rtol=1e-6)


def test_multi_input_consistency_checked():
    @bucketed(axis=0)
    def f(a, b):
        return a + b

    with pytest.raises(ValueError, match="agree"):
        f(jnp.ones((3, 2)), jnp.ones((4, 2)))


def test_custom_buckets_and_pad_value():
    @bucketed(axis=0, buckets=(4, 16), pad_value=1.0, with_length=True)
    def prod_all(x, valid_len):
        del valid_len
        return jnp.prod(x)  # padding of 1.0 is the identity here

    out = prod_all(np.full((3,), 2.0, np.float32))
    np.testing.assert_allclose(float(out), 8.0)

"""Ring attention / Ulysses context parallelism on the 8-device CPU mesh.

The correctness bar: cp-sharded attention == single-device dense attention
(same bar the reference's collective tests use, test/collective/)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.parallel import (
    init_hybrid_mesh, context_parallel_attention, ring_attention)


def _qkv(key, B=2, T=32, H=4, Hkv=4, D=8):
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [True, False])
def test_cp_attention_matches_dense(impl, causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ref = flash_attention(q, k, v, causal=causal, impl="dense")
    hm = init_hybrid_mesh(dp=2, cp=4, set_global=False)
    with hm.mesh:
        out = jax.jit(lambda q, k, v: context_parallel_attention(
            q, k, v, hm.mesh, impl=impl, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_cp_attention_gqa():
    q, k, v = _qkv(jax.random.PRNGKey(1), H=8, Hkv=2)
    ref = flash_attention(q, k, v, causal=True, impl="dense")
    hm = init_hybrid_mesh(cp=4, tp=2, set_global=False)
    with hm.mesh:
        out = jax.jit(lambda q, k, v: context_parallel_attention(
            q, k, v, hm.mesh, impl="ring"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_differentiable():
    """Gradients flow through the ppermute ring (training usability)."""
    q, k, v = _qkv(jax.random.PRNGKey(2), B=1, T=16, H=2, Hkv=2, D=4)
    hm = init_hybrid_mesh(cp=4, set_global=False)

    def loss_cp(q, k, v):
        o = context_parallel_attention(q, k, v, hm.mesh, impl="ring")
        return (o ** 2).sum()

    def loss_ref(q, k, v):
        return (flash_attention(q, k, v, causal=True, impl="dense") ** 2).sum()

    with hm.mesh:
        g_cp = jax.jit(jax.grad(loss_cp, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_cp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_llama_forward_with_ring_attention_matches_dense():
    from paddle_tpu.models import llama as L
    cfg = L.LlamaConfig.tiny(dtype=jnp.float32, remat=False,
                             use_flash_attention=False)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    ref = L.forward(params, tokens, cfg)

    cfg_cp = L.LlamaConfig.tiny(dtype=jnp.float32, remat=False,
                                use_flash_attention=False,
                                context_parallel="ring")
    hm = init_hybrid_mesh(dp=2, cp=2, tp=2, set_global=False)
    with hm.mesh:
        params_cp = L.shard_params(params, cfg_cp, hm.mesh)
        out = jax.jit(lambda p, t: L.forward(p, t, cfg_cp, hm.mesh))(
            params_cp, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-4, atol=5e-4)


def test_llama_cp_train_step():
    from paddle_tpu.models import llama as L
    cfg = L.LlamaConfig.tiny(dtype=jnp.float32, remat=False,
                             use_flash_attention=False,
                             context_parallel="ring")
    hm = init_hybrid_mesh(dp=2, cp=2, tp=2, set_global=False)
    with hm.mesh:
        step, init = L.make_train_step(cfg, hm.mesh)
        state = init(jax.random.PRNGKey(0))
        batch = L.make_batch(cfg, batch_size=4, seq_len=32, mesh=hm.mesh)
        losses = []
        for _ in range(3):
            state, l = step(state, batch)
            losses.append(float(l))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# zigzag layout (causal load balance)
# ---------------------------------------------------------------------------

def _np_chunk_positions(r, R, Tl, layout):
    if layout == "zigzag":
        C = Tl // 2
        a = np.arange(C)
        return np.concatenate([r * C + a, (2 * R - 1 - r) * C + a])
    return r * Tl + np.arange(Tl)


@pytest.mark.parametrize("R,T", [(4, 64), (8, 64)])
def test_zigzag_balances_per_hop_unmasked_work(R, T):
    """The point of zigzag: at every ring hop, each rank's UNMASKED
    score area is identical — with contiguous sharding, the same hop
    gives some ranks a fully-masked (wasted) block and others a full
    one, so the synchronous hop runs at the worst rank's speed."""
    Tl = T // R
    for layout, want_balanced in [("zigzag", True), ("contiguous", False)]:
        per_hop_spread = []
        for s in range(R):  # hop index
            counts = []
            for r in range(R):
                qpos = _np_chunk_positions(r, R, Tl, layout)
                kpos = _np_chunk_positions((r - s) % R, R, Tl, layout)
                counts.append(int((qpos[:, None] >= kpos[None, :]).sum()))
            per_hop_spread.append(max(counts) - min(counts))
        if want_balanced:
            assert max(per_hop_spread) == 0, (layout, per_hop_spread)
        else:
            assert max(per_hop_spread) > 0, (layout, per_hop_spread)


def test_zigzag_covers_every_token_pair_once():
    from paddle_tpu.parallel.context_parallel import zigzag_global_perm
    R, T = 4, 32
    perm = zigzag_global_perm(T, R)
    assert sorted(perm.tolist()) == list(range(T))
    # local slots of rank r are perm[r*Tl:(r+1)*Tl] and must equal the
    # positions chunk_positions assigns
    Tl = T // R
    for r in range(R):
        np.testing.assert_array_equal(
            perm[r * Tl:(r + 1) * Tl],
            _np_chunk_positions(r, R, Tl, "zigzag"))


@pytest.mark.parametrize("causal", [True, False])
def test_zigzag_ring_matches_dense(causal):
    from paddle_tpu.parallel.context_parallel import zigzag_global_perm
    q, k, v = _qkv(jax.random.PRNGKey(3), T=32)
    ref = flash_attention(q, k, v, causal=causal, impl="dense")
    R = 4
    perm = zigzag_global_perm(32, R)
    inv = np.argsort(perm)
    hm = init_hybrid_mesh(dp=2, cp=R, set_global=False)
    with hm.mesh:
        out_z = jax.jit(lambda q, k, v: context_parallel_attention(
            q, k, v, hm.mesh, impl="zigzag", causal=causal))(
                q[:, perm], k[:, perm], v[:, perm])
    np.testing.assert_allclose(np.asarray(out_z[:, inv]), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_llama_zigzag_cp_matches_dense_forward():
    from paddle_tpu.models import llama as L
    from paddle_tpu.parallel.context_parallel import zigzag_global_perm
    cfg = L.LlamaConfig.tiny(dtype=jnp.float32, remat=False,
                             use_flash_attention=False)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    ref = L.forward(params, tokens, cfg)

    cfg_z = L.LlamaConfig.tiny(dtype=jnp.float32, remat=False,
                               use_flash_attention=False,
                               context_parallel="zigzag")
    hm = init_hybrid_mesh(dp=2, cp=4, set_global=False)
    perm = zigzag_global_perm(32, 4)
    inv = np.argsort(perm)
    with hm.mesh:
        params_z = L.shard_params(params, cfg_z, hm.mesh)
        out = jax.jit(lambda p, t: L.forward(p, t, cfg_z, hm.mesh))(
            params_z, tokens)
    # logits come back in zigzag order; unpermute to compare
    np.testing.assert_allclose(np.asarray(out)[:, inv], np.asarray(ref),
                               rtol=5e-4, atol=5e-4)


def test_llama_zigzag_loss_equals_contiguous_cp_loss():
    from paddle_tpu.models import llama as L
    base = dict(dtype=jnp.float32, remat=False, use_flash_attention=False)
    cfg_r = L.LlamaConfig.tiny(context_parallel="ring", **base)
    cfg_z = L.LlamaConfig.tiny(context_parallel="zigzag", **base)
    params = L.init_params(cfg_r, jax.random.PRNGKey(0))
    hm = init_hybrid_mesh(dp=2, cp=4, set_global=False)
    with hm.mesh:
        batch = L.make_batch(cfg_r, batch_size=2, seq_len=32, mesh=hm.mesh)
        p = L.shard_params(params, cfg_r, hm.mesh)
        lr = jax.jit(lambda p, b: L.loss_fn(p, b, cfg_r, hm.mesh))(p, batch)
        lz = jax.jit(lambda p, b: L.loss_fn(p, b, cfg_z, hm.mesh))(p, batch)
    np.testing.assert_allclose(float(lr), float(lz), rtol=2e-5)

"""Verified jaxpr rewrite passes (analysis/rewrite.py).

Mutation-test discipline, mirroring the lint passes: every rewrite has
a seeded graph it MUST fire on, mutated graphs it must NOT fire on
(wrong quantization scheme, non-exclusive intermediates, wrong
reduction), and an idempotence check (re-running the rewriter on
rewritten output is a no-op). The verifier itself is mutation-tested —
a deliberately wrong replacement must be rejected. Exactness pins:
greedy outputs through a ``ServingEngine(rewrites=True)`` are
byte-identical to the unrewritten engine, and a differentiated
(train-step-shaped) loss through ``rewrite_callable`` matches lockstep
numerics within the declared tolerance.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis.framework import (ExactnessContract,
                                           REWRITE_REGISTRY, Severity)
from paddle_tpu.analysis.rewrite import (DecodeTailFusePass,
                                         FusedRmsNormPass,
                                         Int8EpilogueFusePass,
                                         count_matches, rewrite_jaxpr,
                                         rewrite_callable,
                                         run_rewrite_suite,
                                         verify_rewrite)
from paddle_tpu.analysis.rewrite_conv import (ConvBnFoldPass,
                                              ConvNhwcLayoutPass,
                                              StemSpaceToDepthPass)
from paddle_tpu.models import llama as L


# ---------------------------------------------------------------------------
# seeded graphs
# ---------------------------------------------------------------------------

def _unfused_int8(x, q, scale):
    """The naive dequantize-then-matmul idiom the epilogue rewrite
    exists to eliminate."""
    w = (q.astype(jnp.float32) * scale[None, :]).astype(x.dtype)
    return jnp.matmul(x, w)


def _int8_args(m=4, k=16, n=8, dtype=jnp.bfloat16):
    r = np.random.RandomState(0)
    x = jnp.asarray(r.standard_normal((m, k)), dtype)
    q = jnp.asarray(r.randint(-127, 128, (k, n)), jnp.int8)
    s = jnp.asarray(np.abs(r.standard_normal(n)) * 0.02 + 1e-3,
                    jnp.float32)
    return x, q, s


def _rms(x, w, eps=1e-5):
    """The jnp rmsnorm formulation (models/llama.py rms_norm)."""
    return L.rms_norm(x, w, eps)


def _rms_args(rows=8, d=16, dtype=jnp.bfloat16):
    r = np.random.RandomState(1)
    x = jnp.asarray(r.standard_normal((rows, d)), dtype)
    w = jnp.asarray(r.standard_normal(d), jnp.float32)
    return x, w


# ---------------------------------------------------------------------------
# int8-epilogue-fuse: fire / no-fire / idempotence / contract
# ---------------------------------------------------------------------------

def test_int8_fires_on_seeded_unfused_graph():
    x, q, s = _int8_args()
    closed = jax.make_jaxpr(_unfused_int8)(x, q, s)
    res = rewrite_jaxpr(closed, retrace=True)
    assert res.fired.get("int8-epilogue-fuse") == 1
    assert res.idempotent is True
    out = verify_rewrite(res)
    assert out.ok, out
    assert out.sites == 1


def test_int8_rewritten_matches_fused_impl_exactly():
    # the replacement IS the hand-fused path: the rewriter reproduces
    # ops/fused/int8_matmul.int8_weight_matmul bit for bit
    from paddle_tpu.ops.fused.int8_matmul import int8_weight_matmul
    x, q, s = _int8_args()
    res = rewrite_jaxpr(jax.make_jaxpr(_unfused_int8)(x, q, s))
    (got,) = res.fn_flat(x, q, s)
    want = int8_weight_matmul(x, q, s, impl="jnp")
    assert np.asarray(got).tobytes() == np.asarray(want).tobytes()


def test_int8_must_not_fire_per_input_channel_scale():
    # a [in]-scale broadcast over the CONTRACTING dim is a different
    # quantization scheme — the epilogue cannot represent it. Square
    # weight so the 1-D shape check alone cannot distinguish.
    def per_input(x, q, scale):
        w = (q.astype(jnp.float32) * scale[:, None]).astype(x.dtype)
        return jnp.matmul(x, w)

    r = np.random.RandomState(0)
    x = jnp.asarray(r.standard_normal((4, 16)), jnp.bfloat16)
    q = jnp.asarray(r.randint(-127, 128, (16, 16)), jnp.int8)
    s = jnp.asarray(np.abs(r.standard_normal(16)) + 0.01, jnp.float32)
    fired = count_matches(jax.make_jaxpr(per_input)(x, q, s))
    assert not fired.get("int8-epilogue-fuse")


def test_int8_must_not_fire_when_dense_weight_escapes():
    # the dequantized weight is ALSO a graph output: deleting its
    # producer would break the other consumer (exclusivity)
    def leaky(x, q, scale):
        w = (q.astype(jnp.float32) * scale[None, :]).astype(x.dtype)
        return jnp.matmul(x, w), w

    x, q, s = _int8_args()
    fired = count_matches(jax.make_jaxpr(leaky)(x, q, s))
    assert not fired.get("int8-epilogue-fuse")


def test_int8_must_not_fire_on_non_int8_weight():
    x, q, s = _int8_args()
    q16 = q.astype(jnp.int16)
    fired = count_matches(jax.make_jaxpr(_unfused_int8)(x, q16, s))
    assert not fired.get("int8-epilogue-fuse")


def test_int8_must_not_fire_on_batched_dot():
    # 3-D stacked weights (layer-scanned): per-call-site 2-D only
    def batched(x, q, scale):
        w = (q.astype(jnp.float32) * scale[None, None, :]).astype(x.dtype)
        return jnp.einsum("bik,bkn->bin", x, w)

    r = np.random.RandomState(0)
    x = jnp.asarray(r.standard_normal((2, 4, 16)), jnp.bfloat16)
    q = jnp.asarray(r.randint(-127, 128, (2, 16, 8)), jnp.int8)
    s = jnp.asarray(np.abs(r.standard_normal(8)) + 0.01, jnp.float32)
    fired = count_matches(jax.make_jaxpr(batched)(x, q, s))
    assert not fired.get("int8-epilogue-fuse")


# ---------------------------------------------------------------------------
# fused-rmsnorm: fire / no-fire / idempotence / contract
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_rms_fires_on_both_spellings(dtype):
    x, w = _rms_args(dtype=dtype)
    res = rewrite_jaxpr(jax.make_jaxpr(_rms)(x, w), retrace=True)
    assert res.fired.get("fused-rmsnorm") == 1
    assert res.idempotent is True


def test_rms_within_declared_ulp_on_seeded_graph():
    # the kernel performs the same f32 reductions in the same
    # association; only compiler clustering (FMA contraction, reduction
    # tiling) across the fused body can round differently — the
    # declared contract is ulp<=4 (measured worst case over a
    # 420-config sweep; flagship shapes measure 2), and the verifier
    # enforces it per matched site
    x, w = _rms_args(dtype=jnp.bfloat16)
    res = rewrite_jaxpr(jax.make_jaxpr(_rms)(x, w))
    out = verify_rewrite(res)
    assert out.ok and out.mode == "ulp<=4", out


def test_rms_must_not_fire_wrong_denominator():
    # dividing the square-sum by anything but the normalized axis size
    # is not an rmsnorm
    def not_mean(x, w, eps=1e-5):
        xf = x.astype(jnp.float32)
        v = jnp.sum(xf * xf, axis=-1, keepdims=True) / (x.shape[-1] + 1)
        y = xf * jax.lax.rsqrt(v + eps)
        return (y * w.astype(jnp.float32)).astype(x.dtype)

    x, w = _rms_args()
    fired = count_matches(jax.make_jaxpr(not_mean)(x, w))
    assert not fired.get("fused-rmsnorm")


def test_rms_must_not_fire_on_cross_product():
    # mean(x*y) is not a square — the same-value constraint on the
    # mul's operands must hold
    def crossed(x, y, w, eps=1e-5):
        xf = x.astype(jnp.float32)
        yf = y.astype(jnp.float32)
        v = jnp.mean(xf * yf, axis=-1, keepdims=True)
        out = xf * jax.lax.rsqrt(v + eps)
        return (out * w.astype(jnp.float32)).astype(x.dtype)

    x, w = _rms_args()
    y = x + 1
    fired = count_matches(jax.make_jaxpr(crossed)(x, y, w))
    assert not fired.get("fused-rmsnorm")


def test_rms_must_not_fire_when_rstd_escapes():
    def leaky(x, w, eps=1e-5):
        xf = x.astype(jnp.float32)
        rstd = jax.lax.rsqrt(
            jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
        return (xf * rstd * w.astype(jnp.float32)).astype(x.dtype), rstd

    x, w = _rms_args()
    fired = count_matches(jax.make_jaxpr(leaky)(x, w))
    assert not fired.get("fused-rmsnorm")


def test_rms_fires_inside_scan_body():
    def scanned(x, w):
        def body(c, _):
            return _rms(c, w), None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    x, w = _rms_args(dtype=jnp.float32)
    closed = jax.make_jaxpr(scanned)(x, w)
    assert count_matches(closed).get("fused-rmsnorm") == 1
    res = rewrite_jaxpr(closed)
    (got,) = res.fn_flat(x, w)
    (want,) = [scanned(x, w)]
    np.testing.assert_allclose(np.asarray(got, np.float64),
                               np.asarray(want, np.float64),
                               rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# machinery: the verifier and the match gate are themselves tested
# ---------------------------------------------------------------------------

class _WrongEpsRms(FusedRmsNormPass):
    """Seeded defect: same pattern, numerically wrong replacement."""

    def build(self, statics):
        from paddle_tpu.ops.pallas.fused_norm_rope import fused_rms_norm
        return lambda x, w: fused_rms_norm(x, w, 0.25)  # wrong eps


class _WrongDtypeRms(FusedRmsNormPass):
    """Seeded defect: replacement changes the anchor's dtype."""

    def build(self, statics):
        inner = FusedRmsNormPass.build(self, statics)
        # f16, not f64: x64 is disabled suite-wide, a float64 astype
        # silently truncates back to f32 and would not change the aval
        return lambda x, w: inner(x, w).astype(jnp.float16)


def test_verifier_rejects_numerically_wrong_replacement():
    x, w = _rms_args(dtype=jnp.bfloat16)
    bad = _WrongEpsRms()
    res = rewrite_jaxpr(jax.make_jaxpr(_rms)(x, w), rules=[bad])
    assert res.fired.get("fused-rmsnorm") == 1
    out = verify_rewrite(res, rules=[bad])
    assert not out.ok
    assert "ulp" in out.mode


def test_aval_changing_replacement_cannot_match():
    x, w = _rms_args(dtype=jnp.bfloat16)
    fired = count_matches(jax.make_jaxpr(_rms)(x, w),
                          rules=[_WrongDtypeRms()])
    assert not fired.get("fused-rmsnorm")


def test_contracts_are_declared():
    # registry sanity: both concrete rewrites exist with the documented
    # contracts (ulp-pinned kernel substitution vs pinned-tolerance
    # reassociation)
    assert REWRITE_REGISTRY["fused-rmsnorm"] is FusedRmsNormPass
    assert REWRITE_REGISTRY["int8-epilogue-fuse"] is Int8EpilogueFusePass
    assert FusedRmsNormPass.contract.ulp == 4
    c = Int8EpilogueFusePass.contract
    assert not c.bitwise and c.rtol > 0 and c.atol > 0
    assert ExactnessContract(bitwise=True).describe() == "bitwise"
    assert ExactnessContract(ulp=1).describe() == "ulp<=1"


def test_suite_errors_when_expected_rewrite_missing():
    # the vacuous-pass guard: a target whose meta expects a rewrite
    # that cannot fire must produce an ERROR finding
    from paddle_tpu.analysis.framework import GraphTarget
    x, w = _rms_args()
    target = GraphTarget(name="seeded.no-int8",
                         jaxpr=jax.make_jaxpr(_rms)(x, w),
                         meta={"expect_rewrites": ("int8-epilogue-fuse",)})
    findings, _ = run_rewrite_suite(targets=[target], verify=False)
    errs = [f for f in findings if f.severity == Severity.ERROR]
    assert errs and "int8-epilogue-fuse" in errs[0].message


# ---------------------------------------------------------------------------
# flagship suite (what graph_lint --suite rewrite runs)
# ---------------------------------------------------------------------------

def test_flagship_rewrite_suite_clean():
    findings, table = run_rewrite_suite(models=("llama",))
    errs = [f for f in findings if f.severity == Severity.ERROR]
    assert not errs, [str(f) for f in errs]
    by_graph = {row["graph"]: row for row in table}
    int8 = by_graph["llama.serving_decode_step[int8-unfused]"]
    # every projection in the 2-layer step dequantizes unfused: q/k/v/o
    # + gate/up/down per layer land on the stacked per-layer weights
    # (scan body counts once) + lm_head
    assert int8["fired"]["int8-epilogue-fuse"] >= 2
    assert int8["fired"]["fused-rmsnorm"] >= 1
    assert int8["idempotent"] is True
    assert int8["verify"]["ok"] is True
    for row in table:
        assert row["verify"]["ok"], row
        assert row["idempotent"] is True, row


# ---------------------------------------------------------------------------
# exactness pins
# ---------------------------------------------------------------------------

def test_engine_rewrites_greedy_outputs_bitwise_equal():
    """ServingEngine(rewrites=True) greedy outputs are byte-identical
    to the unrewritten engine AND to generate()."""
    from paddle_tpu.serving.engine import ServingEngine

    cfg = L.LlamaConfig.tiny(dtype=jnp.float32,
                             use_flash_attention=False, remat=False)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 9, 12)]

    def run(**kw):
        with ServingEngine(params, cfg, max_batch=4, page_size=4,
                           max_prompt_len=16, max_new_tokens_cap=8,
                           **kw) as eng:
            hs = [eng.submit(p, 8) for p in prompts]
            return [tuple(np.asarray(h.result(timeout=300)).tolist())
                    for h in hs]

    base = run(rewrites=False)
    rewritten = run(rewrites=True)
    assert base == rewritten
    ref = [tuple(np.asarray(L.generate(
        params, p[None, :], cfg, max_new_tokens=8))[0, len(p):].tolist())
        for p in prompts]
    assert rewritten == ref


def test_rewritten_train_numerics_within_declared_tolerance():
    """A differentiated loss through rewrite_callable (fused-rmsnorm
    substituted, custom-VJP backward) matches the unrewritten lockstep
    numerics within the declared tolerance over 3 SGD steps."""
    cfg = L.LlamaConfig.tiny(dtype=jnp.float32,
                             use_flash_attention=False, remat=False)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                              cfg.vocab_size, dtype=jnp.int32)

    def loss_fn(params, tokens):
        logits = L.forward(params, tokens, cfg).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits[:, :-1])
        tgt = tokens[:, 1:]
        return -jnp.mean(jnp.take_along_axis(lp, tgt[..., None], -1))

    assert count_matches(
        jax.make_jaxpr(loss_fn)(params, toks)).get("fused-rmsnorm")

    vg_base = jax.jit(jax.value_and_grad(loss_fn))
    vg_rw = jax.jit(jax.value_and_grad(rewrite_callable(loss_fn)))

    def steps(vg, params, n=3, lr=0.1):
        losses = []
        for _ in range(n):
            loss, g = vg(params, toks)
            params = jax.tree_util.tree_map(
                lambda p, gg: p - lr * gg, params, g)
            losses.append(float(loss))
        return losses, params

    base_losses, base_params = steps(vg_base, params)
    rw_losses, rw_params = steps(vg_rw, params)
    # declared tolerance: the substituted kernel's backward is the
    # analytic rmsnorm VJP (same math, different association than jax
    # AD of the jnp formulation) — f32 lockstep agreement to ~1e-5
    np.testing.assert_allclose(rw_losses, base_losses, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(base_params),
                    jax.tree_util.tree_leaves(rw_params)):
        np.testing.assert_allclose(np.asarray(a, np.float64),
                                   np.asarray(b, np.float64),
                                   rtol=1e-3, atol=1e-5)


# ---------------------------------------------------------------------------
# conv passes (rewrite_conv.py): fire / no-fire / idempotence / contracts
# ---------------------------------------------------------------------------

def _conv(x, w, strides=(1, 1), padding=((1, 1), (1, 1))):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def _bn_infer(y, g, b, m, v, eps=1e-5, shape=(1, -1, 1, 1)):
    """The inference-BN eqn chain the fold pattern targets (what
    nn.BatchNorm2D traces to in eval mode)."""
    return ((y - m.reshape(shape)) * jax.lax.rsqrt(v.reshape(shape) + eps)
            * g.reshape(shape) + b.reshape(shape))


def _conv_bn_args(cout=4, cin=3, image=6, k=3):
    r = np.random.RandomState(2)
    x = jnp.asarray(r.standard_normal((2, cin, image, image)), jnp.float32)
    w = jnp.asarray(r.standard_normal((cout, cin, k, k)) * 0.1,
                    jnp.float32)
    g, b, m = (jnp.asarray(r.standard_normal(cout), jnp.float32)
               for _ in range(3))
    v = jnp.asarray(np.abs(r.standard_normal(cout)) + 0.5, jnp.float32)
    return x, w, g, b, m, v


def test_conv_bn_fold_fires_verifies_idempotent():
    rules = [ConvBnFoldPass()]
    for relu in (True, False):   # both anchor spellings
        def f(x, w, g, b, m, v):
            out = _bn_infer(_conv(x, w), g, b, m, v)
            return jax.nn.relu(out) if relu else out
        cj = jax.make_jaxpr(f)(*_conv_bn_args())
        res = rewrite_jaxpr(cj, rules=rules, retrace=True)
        assert res.fired.get("conv-bn-fold") == 1, relu
        assert res.idempotent, res.residual
        vo = verify_rewrite(res, rules=rules)
        assert vo.ok, vo


def test_conv_bn_fold_must_not_fire_when_conv_escapes():
    # the conv output is also a graph output — folding would change it
    def f(x, w, g, b, m, v):
        y = _conv(x, w)
        return jax.nn.relu(_bn_infer(y, g, b, m, v)), y
    assert not count_matches(jax.make_jaxpr(f)(*_conv_bn_args()),
                             rules=[ConvBnFoldPass()])


def test_conv_bn_fold_must_not_fire_wrong_axis_bn():
    # channels-LAST stats ([1,1,1,C]) on a channels-first conv: it
    # broadcasts (image == cout) but normalises the wrong axis
    def f(x, w, g, b, m, v):
        return _bn_infer(_conv(x, w), g, b, m, v, shape=(1, 1, 1, 4))
    assert not count_matches(jax.make_jaxpr(f)(*_conv_bn_args(image=4)),
                             rules=[ConvBnFoldPass()])


def test_conv_bn_fold_must_not_fire_on_batch_stats():
    # train-mode BN: the stats are reductions OF the conv output, which
    # therefore escapes the match — the no-fire is structural
    def f(x, w, g, b, m, v):
        y = _conv(x, w)
        return jax.nn.relu(_bn_infer(y, g, b, y.mean(axis=(0, 2, 3)),
                                     y.var(axis=(0, 2, 3))))
    assert not count_matches(jax.make_jaxpr(f)(*_conv_bn_args()),
                             rules=[ConvBnFoldPass()])


def _stem_args(cin=3, image=8):
    r = np.random.RandomState(3)
    x = jnp.asarray(r.standard_normal((1, cin, image, image)),
                    jnp.float32)
    w = jnp.asarray(r.standard_normal((4, cin, 7, 7)) * 0.1, jnp.float32)
    return x, w


def test_stem_s2d_fires_verifies_idempotent():
    def f(x, w):
        return _conv(x, w, strides=(2, 2), padding=((3, 3), (3, 3)))
    rules = [StemSpaceToDepthPass()]
    cj = jax.make_jaxpr(f)(*_stem_args())
    res = rewrite_jaxpr(cj, rules=rules, retrace=True)
    assert res.fired.get("stem-space-to-depth") == 1
    assert res.idempotent, res.residual
    assert verify_rewrite(res, rules=rules).ok


def test_stem_s2d_must_not_fire_off_stem_shapes():
    def f(x, w):
        return _conv(x, w, strides=(2, 2), padding=((3, 3), (3, 3)))
    rules = [StemSpaceToDepthPass()]
    # 4 input channels: not the RGB stem
    assert not count_matches(jax.make_jaxpr(f)(*_stem_args(cin=4)),
                             rules=rules)
    # odd image: the 2x2 phase split does not exist
    assert not count_matches(jax.make_jaxpr(f)(*_stem_args(image=7)),
                             rules=rules)


def test_layout_pass_fires_on_any_nchw_conv():
    rules = [ConvNhwcLayoutPass()]
    cj = jax.make_jaxpr(_conv)(*_conv_bn_args()[:2])
    res = rewrite_jaxpr(cj, rules=rules, retrace=True)
    assert res.fired.get("conv-nhwc-layout") == 1
    # the rewritten conv is NHWC — the NCHW pattern can never re-fire
    assert res.idempotent, res.residual
    assert verify_rewrite(res, rules=rules).ok


# ---------------------------------------------------------------------------
# decode-tail-fuse: fire / no-fire / exactness
# ---------------------------------------------------------------------------

def _tail_args(rows=6, d=16, vocab=32):
    r = np.random.RandomState(4)
    x = jnp.asarray(r.standard_normal((rows, d)), jnp.bfloat16)
    w = jnp.asarray(r.standard_normal(d), jnp.float32)
    idx = jnp.asarray([1, 4], jnp.int32)
    head = jnp.asarray(r.standard_normal((d, vocab)), jnp.bfloat16)
    return x, w, idx, head


def test_decode_tail_fires_and_is_exact_on_seeded_graph():
    def f(x, w, idx, head):
        h = L.rms_norm(x, w, 1e-5)
        return (h[idx] @ head).astype(jnp.float32)
    rules = [DecodeTailFusePass()]
    cj = jax.make_jaxpr(f)(*_tail_args())
    res = rewrite_jaxpr(cj, rules=rules, retrace=True)
    assert res.fired.get("decode-tail-fuse") == 1
    assert res.idempotent, res.residual
    vo = verify_rewrite(res, rules=rules)
    # dtype mirroring (dot in head.dtype, like the matched graph) makes
    # the substitution drift-free on the seeded sites — not just within
    # the 1e-3 pin
    assert vo.ok and vo.max_abs == 0.0, vo


def test_decode_tail_must_not_fire_when_rows_escape():
    def f(x, w, idx, head):
        h = L.rms_norm(x, w, 1e-5)
        rows = h[idx]
        return (rows @ head).astype(jnp.float32), rows
    assert not count_matches(jax.make_jaxpr(f)(*_tail_args()),
                             rules=[DecodeTailFusePass()])


def test_decode_tail_must_not_fire_on_column_gather():
    def f(x, w, idx, head):
        h = L.rms_norm(x, w, 1e-5)
        return (h[:, idx].T @ head).astype(jnp.float32)
    x, w, idx, _ = _tail_args()
    r = np.random.RandomState(5)
    head = jnp.asarray(r.standard_normal((x.shape[0], 8)), jnp.bfloat16)
    assert not count_matches(jax.make_jaxpr(f)(x, w, idx, head),
                             rules=[DecodeTailFusePass()])


def test_new_pass_contracts_pinned():
    # the measured pins documented in each pass docstring — a contract
    # loosened (or tightened past the measurement) without re-measuring
    # should fail here
    assert REWRITE_REGISTRY["conv-bn-fold"] is ConvBnFoldPass
    assert REWRITE_REGISTRY["stem-space-to-depth"] is StemSpaceToDepthPass
    assert REWRITE_REGISTRY["conv-nhwc-layout"] is ConvNhwcLayoutPass
    assert REWRITE_REGISTRY["decode-tail-fuse"] is DecodeTailFusePass
    c = ConvBnFoldPass.contract
    assert (c.rtol, c.atol) == (5e-2, 1e-3) and not c.bitwise
    for cls in (StemSpaceToDepthPass, ConvNhwcLayoutPass):
        assert (cls.contract.rtol, cls.contract.atol) == (5e-2, 2e-2)
    c = DecodeTailFusePass.contract
    assert (c.rtol, c.atol) == (1e-3, 1e-3)
    # the tail swallows the rms core, so it must outrank the plain
    # substitution — and the fold must outrank stem/layout
    assert DecodeTailFusePass.priority < FusedRmsNormPass.priority
    assert (ConvBnFoldPass.priority < StemSpaceToDepthPass.priority
            < ConvNhwcLayoutPass.priority)


# ---------------------------------------------------------------------------
# source_lint host-sync rules (the satellite's own mutation tests)
# ---------------------------------------------------------------------------

def test_source_lint_host_sync_rules_fire():
    from paddle_tpu.analysis.source_lint import lint_file
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n\n\n"
        "def f(x):\n"
        "    y = jax.device_get(x)\n"
        "    x.block_until_ready()\n"
        "    return y, float(jnp.max(x)), bool(jnp.isfinite(x).all())\n"
    )
    rules = sorted(r for r, _, _ in lint_file("fake.py", src=src,
                                              host_sync_scope=True))
    assert rules == ["PT001", "PT002", "PT003", "PT003"]
    # tools/tests scope: the same source is clean
    assert not [r for r, _, _ in lint_file("fake.py", src=src,
                                           host_sync_scope=False)
                if r.startswith("PT")]


def test_source_lint_host_sync_noqa_suppresses():
    from paddle_tpu.analysis.source_lint import lint_file
    src = (
        "import jax.numpy as jnp\n\n\n"
        "def sync():\n"
        "    jnp.zeros(()).block_until_ready()  # noqa: PT002 — api\n"
        "    return float(jnp.zeros(()))  # noqa: PT003\n"
    )
    assert not [r for r, _, _ in lint_file("fake.py", src=src,
                                           host_sync_scope=True)
                if r.startswith("PT")]


def test_source_lint_pt004_table_width_vmem_scratch():
    """PT004 (r16): a Pallas kernel allocating VMEM scratch that
    scales with pages_per_slot flags — the CI guard that the
    long-context ceiling cannot silently regress — while noqa'd
    (explicitly one-shot) and O(tile) shapes stay clean, and the rule
    only runs in pallas scope."""
    from paddle_tpu.analysis.source_lint import lint_file
    bad = (
        "from jax.experimental.pallas import tpu as pltpu\n\n\n"
        "def shapes(pps, page_size, dh, tile, dt):\n"
        "    return [pltpu.VMEM((pps, page_size, dh), dt),\n"
        "            pltpu.VMEM((2, tile, page_size, dh), dt)]\n"
    )
    hits = [r for r, _, _ in lint_file("fake.py", src=bad,
                                       pallas_scope=True)
            if r == "PT004"]
    assert hits == ["PT004"]        # the O(tile) shape did not flag
    assert not [r for r, _, _ in lint_file("fake.py", src=bad)
                if r == "PT004"]    # non-pallas scope: rule off
    ok = (
        "from jax.experimental.pallas import tpu as pltpu\n\n\n"
        "def shapes(pps, page_size, dh, dt):\n"
        "    return pltpu.VMEM((pps, page_size, dh), dt)"
        "  # noqa: PT004 — one-shot by design\n"
    )
    assert not [r for r, _, _ in lint_file("fake.py", src=ok,
                                           pallas_scope=True)
                if r == "PT004"]


def test_source_lint_pt005_serving_host_sync():
    """PT005 (ISSUE 13 satellite): host-sync idioms inside the serving
    hot paths flag — `.item()` and the bare single-arg `np.asarray`
    device-pull shape — while dtype'd container conversions, noqa'd
    sanctioned pull sites, and non-serving scope stay clean."""
    from paddle_tpu.analysis.source_lint import lint_file
    src = (
        "import numpy as np\n\n\n"
        "def tick(toks_d, host_list):\n"
        "    n = toks_d.sum().item()\n"
        "    toks = np.asarray(toks_d)\n"
        "    also = np.array(toks_d)\n"
        "    ok = np.asarray(host_list, np.int32)\n"
        "    ok2 = np.array(host_list, np.int32)\n"
        "    return n, toks, also, ok, ok2\n"
    )
    hits = [r for r, _, _ in lint_file("fake.py", src=src,
                                       serving_scope=True)
            if r == "PT005"]
    assert hits == ["PT005"] * 3  # dtype'd conversions did not flag
    assert not [r for r, _, _ in lint_file("fake.py", src=src)
                if r == "PT005"]       # non-serving scope: rule off
    noqa = (
        "import numpy as np\n\n\n"
        "def tick(toks_d):\n"
        "    return np.asarray(toks_d)"
        "  # noqa: PT005 - the sanctioned pull\n"
    )
    assert not [r for r, _, _ in lint_file("fake.py", src=noqa,
                                           serving_scope=True)
                if r == "PT005"]
    # the live serving tree staying clean (engine read-backs noqa'd
    # with justifications) is covered by
    # test_library_tree_is_clean_of_host_syncs below


def test_source_lint_conservative_on_locals():
    # coercions of locals it cannot prove jax-rooted do not flag
    from paddle_tpu.analysis.source_lint import lint_file
    src = (
        "import numpy as np\n\n\n"
        "def f(diff, eps):\n"
        "    return float(np.max(diff)), float(eps), bool(diff.any())\n"
    )
    assert not [r for r, _, _ in lint_file("fake.py", src=src,
                                           host_sync_scope=True)
                if r.startswith("PT")]


def test_library_tree_is_clean_of_host_syncs():
    import os
    from paddle_tpu.analysis.source_lint import lint_tree
    root = os.path.join(os.path.dirname(__file__), "..")
    hits = [h for h in lint_tree(root) if h[1].startswith("PT")]
    assert not hits, hits

"""Router-driven KV migration + host-memory cold tier (ISSUE r17).

Three layers under test, all riding the bitwise contracts:

* **router-driven handoff** — on a prefill/decode split fleet, a
  prefill worker's chain-completion event triggers an automatic
  chunked transfer to the rendezvous-chosen decode worker, and the
  session's next turn routes there warm (``routed_migrated``);
* **decode-overlapped chunked transfer** — export/adopt streamed in
  bounded page chunks between ticks; equals the synchronous
  whole-blob path bitwise, survives a defrag on the source MID
  transfer, and dies cleanly (abort + cold-start re-prefill fallback,
  ``migration_failed`` counted) when the source is SIGKILLed;
* **host-memory cold tier** — refcount-0 chains evicted under
  pressure page out to bounded host RAM; a prefix re-hit re-adopts
  the pages instead of recomputing prefill, bitwise-equal.

All workers are forced ``JAX_PLATFORMS=cpu`` (WorkerSpec default) and
every test runs under a hard SIGALRM timeout so a hung worker fails
the test instead of wedging tier-1.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.fleet import ServingFleet
from paddle_tpu.serving.fleet.proc import (ProcServingFleet,
                                           TransportError,
                                           TransportTimeout, WorkerSpec)
from paddle_tpu.serving.prefix_cache import prefix_fingerprints

_HARD_TIMEOUT_S = 240


@pytest.fixture(autouse=True)
def _hard_timeout():
    def _boom(signum, frame):
        raise TimeoutError(
            f"migration test exceeded hard {_HARD_TIMEOUT_S}s limit")
    old = signal.signal(signal.SIGALRM, _boom)
    signal.alarm(_HARD_TIMEOUT_S)
    yield
    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


CFG_KW = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
              num_hidden_layers=2, num_attention_heads=4,
              num_key_value_heads=2, max_position_embeddings=128,
              dtype="float32", use_flash_attention=False, remat=False)
ENGINE_KW = dict(max_batch=4, page_size=4, max_prompt_len=16,
                 max_new_tokens_cap=16)
SPEC = WorkerSpec(cfg_kw=CFG_KW, params_seed=0, engine_kw=ENGINE_KW,
                  warm=False)
CFG = L.LlamaConfig(**{**CFG_KW, "dtype": jnp.float32})

HEADER = list(range(1, 9))              # 8 tokens = 2 full pages


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def ref_engine(params):
    eng = ServingEngine(params, CFG, **ENGINE_KW)
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def split_fleet():
    """ONE prefill/decode split fleet shared by the auto-migration
    tests (spawn + engine build is the expensive part). auto_migrate
    defaults ON because both pools are present."""
    f = ProcServingFleet(SPEC, replicas=2, roles=["prefill", "decode"],
                         prefill_len_ratio=1.0, health_ttl_s=0.123)
    yield f
    f.close()


def _wait(pred, timeout_s=60.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# layer 1: router-driven handoff
# ---------------------------------------------------------------------------

def test_auto_migrate_routes_next_turn_warm(split_fleet, ref_engine):
    """The full policy loop with NO caller involvement: turn 1
    (prefill-classed) lands on the prefill worker, its
    chain-completion event fires the chunked handoff to the decode
    worker, and turn 2 (decode-classed) routes there via the router's
    migration table and scores a warm prefix hit — the decoded stream
    bitwise-equal to a single-engine ``generate()``."""
    fleet = split_fleet
    assert fleet.auto_migrate
    # satellite: health_ttl_s= plumbs through to the router's
    # summary-cache TTL (staleness tuning knob)
    assert fleet.router.summary_ttl_s == 0.123

    prompt = np.array(HEADER, np.int32)
    out1 = split_fleet.submit(prompt, 4).result(timeout=180)
    np.testing.assert_array_equal(out1, ref_engine.generate(HEADER, 4))
    _wait(lambda: fleet.counters["migrations"] >= 1,
          what="auto-migration")
    assert fleet.counters["migration_failed"] == 0

    # turn 2: 8-token prompt with mnt=12 is decode-classed
    # (plen < 1.0*mnt) -> decode pool -> the adopting worker
    out2 = fleet.submit(prompt, 12).result(timeout=180)
    np.testing.assert_array_equal(out2, ref_engine.generate(HEADER, 12))
    assert fleet.router.counters["routed_migrated"] >= 1
    dec = next(r for r in fleet.replicas() if r.role == "decode")
    snap = dec.snapshot_dict()
    assert snap["counters"]["prefix_hits"] >= 1


def test_auto_migrated_chain_re_adopt_is_noop(split_fleet):
    """Exactly-once: re-running the handoff the policy already did is
    a trie-dedup no-op (full match, zero adoptions, no double-alloc —
    the per-tick invariant audits would catch a leak)."""
    fleet = split_fleet
    assert fleet.counters["migrations"] >= 1
    fp = int(prefix_fingerprints(np.asarray(HEADER, np.int32), 4,
                                 max_depth=8)[-1])
    src = next(r for r in fleet.replicas() if r.role == "prefill")
    dst = next(r for r in fleet.replicas() if r.role == "decode")
    again = fleet.migrate_chain(fp, src.name, dst.name)
    assert again is not None and again["adopted_pages"] == 0
    assert again["matched_pages"] >= 1


# ---------------------------------------------------------------------------
# layer 2: chunked transfer — equivalence, defrag-during, source death
# ---------------------------------------------------------------------------

def test_chunked_equals_whole_blob_with_defrag_mid_transfer(
        params, ref_engine):
    """The chunked protocol == the synchronous whole-blob path,
    bitwise — including when the SOURCE defragments (pages move)
    between chunk reads: chunks re-read each node's page at gather
    time, and export pins stop FREE, not MOVE."""
    src = ServingEngine(params, CFG, **ENGINE_KW)
    via_blob = ServingEngine(params, CFG, **ENGINE_KW)
    via_chunks = ServingEngine(params, CFG, **ENGINE_KW)
    try:
        warm = HEADER + [50, 51, 52]
        src.submit(np.asarray(warm, np.int32), 4).result(timeout=180)
        fp = int(prefix_fingerprints(np.asarray(warm, np.int32), 4,
                                     max_depth=8)[-1])

        blob = src.export_chain(fp)
        assert blob is not None
        via_blob.adopt_chain(blob)

        hdr = src.export_chain_begin(fp)
        assert hdr is not None and hdr["tokens"] == blob["tokens"]
        st = via_chunks.adopt_chain_begin(
            {"page_size": hdr["page_size"], "tokens": hdr["tokens"]})
        # fragment the source mid-transfer: pages may MOVE under the
        # open export — the per-chunk page re-read keeps it correct
        src.defragment()
        total = len(hdr["tokens"])          # per-page token tuples
        for i in range(st["matched_pages"], total):
            ch = src.export_chain_chunk(hdr["xid"], i, 1)
            via_chunks.adopt_chain_chunk(st["aid"], ch["start"],
                                         ch["k"], ch["v"])
        stats = via_chunks.adopt_chain_commit(st["aid"])
        src.export_chain_end(hdr["xid"])
        assert stats["adopted_pages"] == total

        cont = HEADER + [60, 61]
        ref = ref_engine.generate(cont, 6)
        for eng in (via_blob, via_chunks):
            out = eng.submit(np.asarray(cont, np.int32),
                             6).result(timeout=180)
            np.testing.assert_array_equal(out, ref)
            assert eng.audit() == []
        assert src.audit() == []
    finally:
        src.close()
        via_blob.close()
        via_chunks.close()


def test_sigkill_source_mid_transfer_cold_start_fallback(ref_engine):
    """Exactly-once when the source dies MID chunked transfer: the
    in-flight adopt aborts cleanly on the destination (audit stays
    green), the policy counts ``migration_failed``, and the session's
    next turn still completes on a survivor via cold-start re-prefill
    — zero drops, bitwise-equal output."""
    fleet = ProcServingFleet(SPEC, replicas=2, policy="round_robin")
    try:
        prompt = np.array(HEADER, np.int32)
        fleet.submit(prompt, 4).result(timeout=180)
        fp = int(prefix_fingerprints(prompt, 4, max_depth=8)[-1])
        src = next(r for r in fleet.replicas()
                   if (r.snapshot_dict() or {}).get(
                       "counters", {}).get("completed"))
        dst = next(r for r in fleet.replicas() if r is not src)

        hdr = src.export_chain_begin(fp)
        assert hdr is not None
        st = dst.adopt_chain_begin(
            {"page_size": hdr["page_size"], "tokens": hdr["tokens"]})
        ch = src.export_chain_chunk(hdr["xid"], st["matched_pages"], 1)
        dst.adopt_chain_chunk(st["aid"], ch["start"], ch["k"], ch["v"])
        src.kill_process()          # SIGKILL, mid-transfer
        with pytest.raises((TransportError, TransportTimeout)):
            src.export_chain_chunk(hdr["xid"], st["matched_pages"] + 1,
                                   1)
        dst.adopt_chain_abort(st["aid"])    # frees the staged pages

        # the policy path against the dead source counts the failure
        # instead of raising (exactly-once: nothing was committed)
        fleet._do_migrate(fp, {"fps": [fp]}, src, dst)
        assert fleet.counters["migration_failed"] == 1
        assert fleet.counters["migrations"] == 0

        # session turn 2: cold-start re-prefill on the survivor
        _wait(lambda: not src.alive, what="crash detection")
        out = fleet.submit(prompt, 12).result(timeout=180)
        np.testing.assert_array_equal(out,
                                      ref_engine.generate(HEADER, 12))
        snap = dst.snapshot_dict()
        assert snap["counters"]["completed"] >= 1
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# layer 3: host-memory cold tier
# ---------------------------------------------------------------------------

def test_cold_tier_spill_rewarm_bitwise(params, ref_engine):
    """Chains evicted under device-page pressure spill to host RAM; a
    prefix re-hit re-adopts the pages (``cold_hits``) instead of
    recomputing prefill, and the decoded stream is bitwise-equal to
    the original. Pool sized (8 pages vs 3-page chains + a 6-page
    slot) so later admissions MUST fully evict the first chain."""
    eng = ServingEngine(params, CFG, max_batch=1, page_size=4,
                        max_prompt_len=16, max_new_tokens_cap=8,
                        total_pages=8, cold_tier_bytes=1 << 20)
    try:
        p1 = list(range(1, 13))             # 3 pages, 2 attachable
        p2 = list(range(101, 113))
        p3 = list(range(201, 213))
        out1 = eng.submit(np.asarray(p1, np.int32),
                          4).result(timeout=180)
        np.testing.assert_array_equal(out1, ref_engine.generate(p1, 4))
        for p in (p2, p3):
            eng.submit(np.asarray(p, np.int32), 4).result(timeout=180)
        c = eng.snapshot()["counters"]
        assert c["cold_spills"] >= 3        # p1's chain paged out

        out1b = eng.submit(np.asarray(p1, np.int32),
                           4).result(timeout=180)
        np.testing.assert_array_equal(out1b, out1)
        snap = eng.snapshot()
        c = snap["counters"]
        assert c["cold_hits"] == 1
        # the attach bound: 2 of the 3 spilled pages are re-adoptable
        # ((n-1)//page_size — at least one token must be computed)
        assert c["cold_hit_pages"] == 2
        assert c["prefix_hits"] >= 1        # admission matched them
        assert snap["gauges"]["cold_tier"]["bytes"] > 0
        assert eng.audit() == []
    finally:
        eng.close()


def test_cold_tier_bounded_lru(params):
    """The tier is BOUNDED host RAM: a budget too small for one page
    refuses the spill outright; a small budget LRU-drops the oldest
    entries rather than growing."""
    from paddle_tpu.serving.prefix_cache import ColdTier
    tier = ColdTier(64)                     # bytes: far below one page
    k = np.zeros((2, 2, 1, 4, 8), np.float32)
    assert not tier.put(1, (1, 2, 3, 4), k, k)
    assert tier.stats()["entries"] == 0
    one = 2 * k.nbytes
    tier2 = ColdTier(2 * one)               # room for exactly two
    for fp in (1, 2, 3):
        assert tier2.put(fp, (fp,), k, k)
    st = tier2.stats()
    assert st["entries"] == 2 and st["drops"] == 1
    assert tier2.get(1) is None             # oldest was dropped
    assert tier2.get(3) is not None


def test_inprocess_fleet_health_ttl_and_auto_migrate_default(params):
    """The in-process fleet mirrors the proc knobs: health_ttl_s=
    reaches the router, and auto_migrate defaults ON exactly when
    both a prefill and a decode pool exist."""
    f = ServingFleet(lambda: ServingEngine(params, CFG, **ENGINE_KW),
                     replicas=1, health_ttl_s=0.077)
    try:
        assert f.router.summary_ttl_s == 0.077
        assert not f.auto_migrate        # no pools -> policy off
    finally:
        f.close()


# ---------------------------------------------------------------------------
# bench pins (slow tier): the measured acceptance numbers
# ---------------------------------------------------------------------------

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "serving_bench.py"), *argv],
        cwd=_REPO, env=env, capture_output=True, text=True,
        timeout=1200)
    assert out.returncode == 0, out.stderr[-4000:]
    rows = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    return rows[-1]


@pytest.mark.slow
def test_bench_migration_ab_overlap_bound():
    """serving_bench --modes migration_ab: migrations happen, nothing
    drops, and — the overlap pin — no worker's tick loop stalls
    longer than the chunk bound while pages stream (2.5 s is generous
    for a 1-page gather/scatter on a contended CPU host; a
    whole-blob synchronous transfer under load would hold the tick
    lock for the full chain)."""
    row = _run_bench("--modes", "migration_ab", "--layers", "2",
                     "--hidden", "64", "--page-size", "4",
                     "--max-prompt", "24", "--mnt-choices", "4", "16",
                     "--fleet-groups", "4", "--fleet-group-size", "3",
                     "--fleet-header", "12", "--rate", "50",
                     "--seed", "0")
    assert row["mode"] == "migration_ab"
    assert row["migrations_happened"]
    assert row["zero_drops_both"]
    dis = row["disaggregated_migrate"]
    assert dis["migration_failed"] == 0
    assert dis["routed_migrated"] >= 1
    assert dis["decode_prefix_hit_rate"] > 0
    for name, stall in dis["max_tick_stall_s"].items():
        assert stall <= 2.5, (name, stall)


@pytest.mark.slow
def test_bench_cold_tier_rehit_beats_cold_prefill():
    """serving_bench --modes cold_tier: re-hits land (every revisit
    re-adopts from host RAM instead of re-prefilling), outputs are
    bitwise-equal between arms, and the adopt path itself is cheap —
    p50 host→device re-adopt well under the cold revisit turn it
    replaces. The ABSOLUTE revisit-TTFT comparison is reported in the
    JSON (``rehit_beats_cold_prefill``) but NOT pinned: on this
    CPU-geometry box the margin (~4ms at layers=4/hidden=256) is
    inside co-tenant noise, so the strict win is an on-TPU number;
    here we pin that the re-hit is at worst marginally slower."""
    row = _run_bench("--modes", "cold_tier", "--layers", "4",
                     "--hidden", "256", "--page-size", "8",
                     "--max-prompt", "64", "--mnt-choices", "4",
                     "--fleet-groups", "6", "--fleet-header", "48",
                     "--seed", "0")
    assert row["mode"] == "cold_tier"
    assert row["bitwise_equal"]
    on, off = row["cold_tier_on"], row["cold_tier_off"]
    assert on["cold_hits"] > 0
    assert off["cold_hits"] == 0
    # the mechanism pin: one re-adopt is much cheaper than the cold
    # revisit turn it replaces (full header re-prefill)
    assert on["cold_adopt_s"]["p50"] * 1e3 < off["revisit_ttft_p50_ms"], (
        on["cold_adopt_s"], off["revisit_ttft_p50_ms"])
    # the TTFT pin, noise-tolerant: warm-from-host must not LOSE to
    # cold prefill by more than scheduling jitter
    assert on["revisit_ttft_p50_ms"] <= off["revisit_ttft_p50_ms"] * 1.6, (
        on["revisit_ttft_p50_ms"], off["revisit_ttft_p50_ms"])

"""Speculative decoding on the one-program tick (ISSUE r15).

Verification story, mirroring the int8/ragged playbooks:

* the DRAFTER is exactness-irrelevant by construction — the engine's
  greedy output is pinned bitwise-equal to the non-speculative engine
  AND to ``generate()`` under the self-drafting n-gram proposer, an
  ORACLE drafter (every draft accepted) and an ANTI-oracle (every
  draft rejected), across every cache state: cold, warm-prefix,
  chunked prefill, post-defrag;
* hard neighbors share the tick: a speculating slot with a
  chunked-prefill span and a parked SAMPLING request (the PR 7
  regression class), and ``close(drain=True)`` lands mid-verify;
* the acceptance-aware scheduler degrades a hostile-drafter slot to
  plain decode (probes only) and the program set stays within the
  statically proven ≤2-per-width-bucket inventory — pinned against the
  live engine and kept compile-clean under an armed recompile
  sentinel after ``warm_programs()``;
* the spec_ab bench emits the acceptance numbers; the slow tier pins
  the ISSUE bar: ≥1.8x fewer target-model launches per emitted token
  at acceptance ≥0.7 on the self-drafting repetitive workload.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.serving import NGramDrafter, ServingEngine
from paddle_tpu.serving.speculative import AcceptancePolicy

CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


import functools


@functools.lru_cache(maxsize=None)
def _gen_jit(n):
    return jax.jit(lambda p, t: L.generate(p, t, CFG, max_new_tokens=n))


def _ref(params, prompt, n):
    out = _gen_jit(n)(params, jnp.asarray(prompt)[None])
    return np.asarray(out)[0, len(prompt):]


def _engine(params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_prompt_len", 16)
    kw.setdefault("max_new_tokens_cap", 32)
    kw.setdefault("speculative", "ngram")
    kw.setdefault("spec_k", 3)
    return ServingEngine(params, CFG, **kw)


def _repetitive(seed, n=13):
    rng = np.random.RandomState(seed)
    pat = rng.randint(0, CFG.vocab_size, (4,)).astype(np.int32)
    return np.tile(pat, -(-n // 4))[:n]


class OracleDrafter:
    """Drafts the TRUE greedy continuation (looked up from a reference
    run): every draft accepted — the deterministic full-accept path."""

    def __init__(self, full_seq):
        self.full = np.asarray(full_seq, np.int32)

    def propose(self, history, k):
        h = np.asarray(history, np.int32).reshape(-1)
        return self.full[h.size: h.size + k]


class AntiOracleDrafter(OracleDrafter):
    """Every draft WRONG by construction (true token + 1 mod V): the
    deterministic zero-accept / rollback-every-tick path."""

    def propose(self, history, k):
        d = super().propose(history, k)
        return (d + 1) % CFG.vocab_size


# ---------------------------------------------------------------------------
# drafter + policy units
# ---------------------------------------------------------------------------

def test_ngram_drafter_prefers_full_continuations():
    d = NGramDrafter(max_ngram=3, min_ngram=1)
    # period-4 history: the suffix trigram recurs one period back with
    # a full continuation available
    h = np.tile([5, 9, 2, 7], 4)
    out = d.propose(h, 3)
    np.testing.assert_array_equal(out, [5, 9, 2])
    # period-1 run: the most recent [8] match sits at the edge with a
    # short continuation; an earlier match yields the full k
    h = np.asarray([1, 2, 8, 8, 8, 8, 8])
    np.testing.assert_array_equal(d.propose(h, 4), [8, 8, 8, 8])


def test_ngram_drafter_no_match_is_empty():
    d = NGramDrafter()
    assert d.propose(np.arange(10, dtype=np.int32), 4).size == 0
    assert d.propose(np.asarray([3]), 4).size == 0      # too short
    assert d.propose(np.tile([1, 2], 4), 0).size == 0   # k = 0


def test_acceptance_policy_degrades_and_probes():
    class S:
        spec_rate = 1.0
        spec_probe = 0

    pol = AcceptancePolicy(4, probe_every=8)
    s = S()
    assert pol.budget(s, remaining=100) == 4     # optimistic start
    for _ in range(12):
        pol.update(s, drafted=4, accepted=0)
    assert s.spec_rate < pol.floor
    budgets = [pol.budget(s, remaining=100) for _ in range(16)]
    assert budgets.count(0) == 14 and budgets.count(1) == 2  # probes
    # recovery: accepted drafts pull the EWMA back up
    for _ in range(12):
        pol.update(s, drafted=1, accepted=1)
    assert pol.budget(s, remaining=100) >= 1
    # the remaining-budget cap wins near the end of a request
    s.spec_rate = 1.0
    assert pol.budget(s, remaining=2) == 2
    assert pol.budget(s, remaining=0) == 0


# ---------------------------------------------------------------------------
# engine exactness: spec == plain engine == generate() in every state
# ---------------------------------------------------------------------------

def test_spec_matches_plain_engine_and_generate_cold_warm_partial(params):
    """The ISSUE acceptance pin: greedy speculative output bitwise-
    equal to the non-speculative engine and generate() — cold, fully
    warm (prefix attach), partially warm — with speculation actually
    engaging (drafted AND accepted tokens non-zero)."""
    base = _repetitive(2, 13)
    partial = np.concatenate([base[:9], _repetitive(11, 5)[:4]])
    outs = {}
    for spec in (False, True):
        with _engine(params, speculative="ngram" if spec else None) \
                as eng:
            outs[spec] = [
                eng.submit(base, 8).result(timeout=300),     # cold
                eng.submit(base, 8).result(timeout=300),     # warm
                eng.submit(partial, 8).result(timeout=300),  # partial
            ]
            snap = eng.stats()
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(outs[True][0], _ref(params, base, 8))
    np.testing.assert_array_equal(outs[True][2],
                                  _ref(params, partial, 8))
    c = snap["counters"]
    assert c["draft_tokens"] > 0 and c["draft_accepted"] > 0
    assert c["spec_ticks"] > 0


def test_spec_matches_generate_chunked_prefill(params):
    """Chunked prefill interleaved with speculation: prefill spans and
    verify spans share the packed batch; outputs stay exact for
    aligned and unaligned chunk sizes."""
    prompts = [_repetitive(s, n) for s, n in ((2, 15), (5, 9), (7, 13))]
    for chunk in (4, 5):
        with _engine(params, prefill_chunk=chunk) as eng:
            handles = [eng.submit(p, 6) for p in prompts]
            outs = [h.result(timeout=300) for h in handles]
        for p, out in zip(prompts, outs):
            np.testing.assert_array_equal(out, _ref(params, p, 6))


def test_spec_matches_generate_after_defrag(params):
    """Mid-stream defrag scatters the speculating slot's page list;
    verify spans read the remapped tables as data — continuations stay
    bitwise-equal and the invariant checker stays clean."""
    p1 = _repetitive(2, 11)
    p2 = _repetitive(5, 7)
    with _engine(params, check_invariants=True) as eng:
        eng.submit(p2, 2).result(timeout=300)
        h1 = eng.submit(p1, 10)
        it = iter(h1)
        next(it)
        moved = eng.defragment()
        h2 = eng.submit(p2, 6)
        out1 = h1.result(timeout=300)
        out2 = h2.result(timeout=300)
        assert eng.audit() == []
    assert moved >= 0
    np.testing.assert_array_equal(out1, _ref(params, p1, 10))
    np.testing.assert_array_equal(out2, _ref(params, p2, 6))


def test_oracle_drafter_full_accept_path(params):
    """A drafter proposing the true continuation: every draft accepted
    (acceptance 1.0), launches collapse toward (mnt-1)/(1+k), output
    still exact — the deterministic upper bound of the mechanism."""
    prompt = _repetitive(2, 13)
    mnt = 25
    full = np.concatenate([prompt, _ref(params, prompt, mnt)])
    with _engine(params, speculative=OracleDrafter(full), spec_k=3,
                 max_new_tokens_cap=32) as eng:
        out = eng.submit(prompt, mnt).result(timeout=300)
        c = eng.stats()["counters"]
    np.testing.assert_array_equal(out, full[len(prompt):])
    assert c["draft_accepted"] == c["draft_tokens"] > 0
    # 24 post-prefill tokens at k=3: six 4-token verify launches beats
    # 24 plain launches by 4x; leave slack for the final short tick
    assert c["decode_steps"] <= 8


def test_anti_oracle_rejects_all_and_degrades(params):
    """Every draft wrong: acceptance 0, EVERY verify rolls back its
    whole draft (rejected == drafted), output still bitwise-exact, and
    the acceptance policy degrades the slot to plain decode (drafted
    tokens stop well short of one per emitted token)."""
    prompt = _repetitive(2, 13)
    mnt = 30
    full = np.concatenate([prompt, _ref(params, prompt, mnt)])
    with _engine(params, speculative=AntiOracleDrafter(full), spec_k=3,
                 max_new_tokens_cap=32) as eng:
        out = eng.submit(prompt, mnt).result(timeout=300)
        c = eng.stats()["counters"]
    np.testing.assert_array_equal(out, full[len(prompt):])
    assert c["draft_accepted"] == 0
    assert c["draft_rejected"] == c["draft_tokens"] > 0
    # degraded: EWMA falls below the floor after ~4 rejected verifies,
    # then only periodic probes draft — nowhere near one draft/token
    assert c["spec_ticks"] < mnt // 2


def test_spec_with_chunked_prefill_and_parked_sampling_neighbor(params):
    """The PR 7 regression class, speculative edition: a speculating
    greedy stream must stay exact (and keep speculating) while a
    SAMPLING request chunk-prefills in the same ticks, and the
    sampling request itself completes."""
    victim = _repetitive(2, 14)
    intruder = np.arange(1, 17, dtype=np.int32)
    with _engine(params, max_batch=3, prefill_chunk=3,
                 check_invariants=True) as eng:
        h_v = eng.submit(victim, 20)
        it = iter(h_v)
        next(it)                    # victim is mid-decode
        h_s = eng.submit(intruder, 4, temperature=0.7, seed=1)
        h_g = eng.submit(intruder, 5)
        out_v = h_v.result(timeout=300)
        out_s = h_s.result(timeout=300)
        out_g = h_g.result(timeout=300)
        assert eng.audit() == []
        c = eng.stats()["counters"]
    np.testing.assert_array_equal(out_v, _ref(params, victim, 20))
    np.testing.assert_array_equal(out_g, _ref(params, intruder, 5))
    assert len(out_s) == 4          # sampling neighbor completed
    assert c["spec_ticks"] > 0      # speculation ran alongside


def test_close_drain_mid_verify(params):
    """close(drain=True) while a request is mid-speculation finishes
    it exactly; drain=False cancels cleanly and the pool ends
    balanced."""
    prompt = _repetitive(2, 13)
    eng = _engine(params, check_invariants=True,
                  max_new_tokens_cap=64)
    h = eng.submit(prompt, 40)
    it = iter(h)
    next(it)                        # speculation in flight
    eng.close(drain=True)
    np.testing.assert_array_equal(h.result(timeout=60),
                                  _ref(params, prompt, 40))
    eng2 = _engine(params, max_new_tokens_cap=64)
    h2 = eng2.submit(prompt, 40)
    it2 = iter(h2)
    next(it2)
    eng2.close(drain=False)
    assert h2.status in ("cancelled",)
    assert eng2.pool.free_pages == eng2.pool.total_pages - 1  # - trash


# ---------------------------------------------------------------------------
# static proof + runtime sentinel
# ---------------------------------------------------------------------------

def test_spec_program_inventory_matches_live_engine(params):
    """The engine's width grid and program inventory equal the static
    enumeration (analysis/recompile.py) — the ≤2-programs-per-bucket
    invariant survives speculation, with exactly ONE verify program
    per mixed width."""
    from paddle_tpu.analysis.recompile import (ServingGeometry,
                                               program_inventory,
                                               tick_width_grid)
    with _engine(params, spec_k=3) as eng:
        geom = ServingGeometry.of_engine(eng)
        inv = eng.program_inventory
        grid = list(eng._w_grid)
        S = eng.scheduler.max_batch
    assert geom.spec_k == 3
    assert grid == tick_width_grid(geom)
    assert inv == program_inventory(geom)
    assert inv["programs_per_bucket"] <= 2
    for width, progs in inv["widths"].items():
        if int(width) == S:
            # r16: the fused block ALONE — the width-S single-step
            # sampling tick is gone (sampling rides the block as data)
            assert len(progs) == 1
            assert progs[0].startswith("serving_tick_block")
        else:
            assert progs == ["serving_tick[verify,spec_k=3]"]


def test_warm_programs_keeps_sentinel_clean(params):
    """warm_programs() covers the whole speculative inventory, so an
    armed recompile sentinel stays clean through mixed speculative
    traffic — the runtime half of the static proof. Fresh jit objects
    (cleared step-fn cache) so the warmup compiles really fire."""
    from paddle_tpu.serving import engine as _em
    _em._JIT_CACHE.clear()
    with _engine(params, recompile_sentinel=True, prefill_chunk=4,
                 max_batch=2) as eng:
        n = eng.warm_programs()
        # r16: one verify compile per mixed width + the fused block
        # (the single-step sampling tick no longer exists to warm)
        assert n == len(eng._w_grid) + 1
        rep0 = eng.sentinel.report()
        assert rep0["warmup_compiles"] >= 1
        eng.arm_sentinel()
        hs = [eng.submit(_repetitive(s, n), 6)
              for s, n in ((2, 13), (5, 9), (7, 15))]
        for h in hs:
            h.result(timeout=300)
        rep = eng.sentinel.report()
    assert rep["clean"], rep["events"]


def test_spec_metrics_and_spans_exposed(params, tmp_path):
    """Acceptance counters ride expose() and the draft/verify/rollback
    spans land in the exported Perfetto trace (the observability half
    of the ISSUE acceptance)."""
    import json
    prompt = _repetitive(2, 13)
    full = np.concatenate([prompt, _ref(params, prompt, 20)])
    # anti-oracle guarantees at least one rollback span
    with _engine(params, speculative=AntiOracleDrafter(full),
                 trace=True) as eng:
        eng.submit(prompt, 20).result(timeout=300)
        text = eng.expose()
        path = eng.export_trace(str(tmp_path / "spec.json"))
        hist = eng.stats()["histograms"]["spec_accept_rate"]
    for metric in ("paddle_serving_draft_tokens_total",
                   "paddle_serving_draft_accepted_total",
                   "paddle_serving_draft_rejected_total",
                   "paddle_serving_spec_ticks_total"):
        assert metric in text
    assert hist["count"] > 0
    events = json.load(open(path))["traceEvents"]
    names = {e.get("name") for e in events}
    assert "spec.verify" in names and "spec.rollback" in names
    assert "serving.draft" in names


# ---------------------------------------------------------------------------
# spec_ab bench: smoke + the pinned acceptance bar (slow)
# ---------------------------------------------------------------------------

def _load_bench():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "serving_bench.py")
    spec = importlib.util.spec_from_file_location("serving_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serving_bench_spec_ab_smoke():
    """The A/B harness runs end to end on a short horizon: both arms
    emit launch counts, outputs bitwise-equal across arms, speculation
    strictly reduces launches (the bar itself is the slow test)."""
    sb = _load_bench()
    res = sb.main(["--modes", "spec_ab", "--spec-mnt", "48"])
    ab = res["spec_ab"]
    assert ab["bitwise_equal"]
    assert ab["plain"]["tokens"] == ab["spec"]["tokens"] > 0
    assert (ab["spec"]["target_launches"]
            < ab["plain"]["target_launches"])
    assert ab["launch_reduction"] > 1.0


@pytest.mark.slow
def test_spec_ab_acceptance():
    """ISSUE r15 acceptance: ≥1.8x reduction in target-model launches
    per emitted token at acceptance ≥0.7 on the self-drafting
    repetitive workload — deterministic (seeded weights, seeded
    prompts, greedy decode), so pinned directly."""
    sb = _load_bench()
    res = sb.main(["--modes", "spec_ab", "--check-invariants"])
    ab = res["spec_ab"]
    assert ab["bitwise_equal"]
    assert ab["acceptance"] >= 0.7, ab
    assert ab["launch_reduction"] >= 1.8, ab
    assert ab["meets_bar"]
    assert ab["plain"]["sentinel_clean"] and ab["spec"]["sentinel_clean"]


# ---------------------------------------------------------------------------
# qwen2_moe: the second step-fn family serves speculatively too
# ---------------------------------------------------------------------------

def test_qwen2_moe_spec_matches_generate():
    from paddle_tpu.models import qwen2_moe as Q
    qcfg = Q.Qwen2MoeConfig.tiny(use_flash_attention=False, remat=False)
    qparams = Q.init_params(qcfg, jax.random.PRNGKey(0))
    prompt = _repetitive(2, 11)
    ref = np.asarray(jax.jit(
        lambda p, t: Q.generate(p, t, qcfg, max_new_tokens=8)
    )(qparams, jnp.asarray(prompt)[None]))[0, len(prompt):]
    with ServingEngine(qparams, qcfg, max_batch=2, page_size=4,
                       max_prompt_len=16, max_new_tokens_cap=16,
                       speculative="ngram", spec_k=3) as eng:
        out = eng.submit(prompt, 8).result(timeout=300)
        c = eng.stats()["counters"]
    np.testing.assert_array_equal(out, ref)
    assert c["spec_ticks"] > 0

"""Behavioral checks for the API-parity batch: distributions, extended
nn/functional layers, transforms, distributed facade, static compat,
audio IO, geometric sampling, incubate re-exports.

(Name-presence is covered by tools/api_parity.py; these tests assert
numerics for a representative slice of each namespace.)
"""
import os

import numpy as np
import pytest

import paddle_tpu as pt


# ----------------------------------------------------------- distribution
def test_distribution_moments_and_logprob():
    D = pt.distribution
    po = D.Poisson(4.0)
    s = np.asarray(po.sample([4000]).data)
    assert abs(s.mean() - 4.0) < 0.3
    # poisson pmf at k=2, rate 4: 4^2 e^-4 / 2!
    lp = float(np.asarray(po.log_prob(pt.to_tensor(2.0)).data))
    assert abs(np.exp(lp) - (16 * np.exp(-4) / 2)) < 1e-4

    mvn = D.MultivariateNormal(
        np.zeros(2, np.float32),
        covariance_matrix=np.asarray([[2.0, 0.5], [0.5, 1.0]], np.float32))
    samp = np.asarray(mvn.rsample([20000]).data)
    assert np.allclose(np.cov(samp.T), [[2, 0.5], [0.5, 1]], atol=0.2)

    ind = D.Independent(D.Normal(np.zeros((3, 4), np.float32),
                                 np.ones((3, 4), np.float32)), 1)
    lp = np.asarray(ind.log_prob(
        pt.to_tensor(np.zeros((3, 4), np.float32))).data)
    assert lp.shape == (3,)
    np.testing.assert_allclose(lp, 4 * -0.5 * np.log(2 * np.pi), rtol=1e-5)

    lkj = D.LKJCholesky(3, 2.0)
    L = np.asarray(lkj.sample([8]).data)
    corr = L @ L.transpose(0, 2, 1)
    assert np.allclose(np.diagonal(corr, axis1=1, axis2=2), 1, atol=1e-5)

    td = D.TransformedDistribution(
        D.Normal(0.0, 1.0), [pt.distribution.ExpTransform()]) \
        if hasattr(pt.distribution, "ExpTransform") else None


def test_distribution_binomial_geometric_chi2_student():
    D = pt.distribution
    assert abs(np.asarray(D.Binomial(10, 0.3).sample([4000]).data).mean()
               - 3.0) < 0.3
    assert abs(np.asarray(D.Geometric(0.25).sample([4000]).data).mean()
               - 3.0) < 0.4
    assert abs(np.asarray(D.Chi2(3.0).sample([4000]).data).mean()
               - 3.0) < 0.4
    st = D.StudentT(6.0, 1.0, 2.0)
    lp = float(np.asarray(st.log_prob(pt.to_tensor(1.0)).data))
    from scipy.stats import t as _t
    assert abs(lp - _t(6.0, 1.0, 2.0).logpdf(1.0)) < 1e-4


# ----------------------------------------------------- extended functional
def test_extended_losses_numerics():
    F = pt.nn.functional
    x = pt.to_tensor(np.asarray([[2.0, -1.0, 0.5]], np.float32))
    y = pt.to_tensor(np.asarray([0], np.int64))
    l = float(np.asarray(F.multi_margin_loss(x, y).data))
    # margins: max(0, 1-2+(-1))=0, max(0, 1-2+0.5)=0  -> 0 loss... compute
    assert l >= 0
    # gaussian nll at perfect prediction = 0.5*log(var)
    g = float(np.asarray(F.gaussian_nll_loss(
        pt.to_tensor(np.asarray([1.0])), pt.to_tensor(np.asarray([1.0])),
        pt.to_tensor(np.asarray([2.0]))).data))
    np.testing.assert_allclose(g, 0.5 * np.log(2.0), rtol=1e-5)
    # soft margin: log(1+exp(-1*1))
    sm = float(np.asarray(F.soft_margin_loss(
        pt.to_tensor(np.asarray([1.0])), pt.to_tensor(np.asarray([1.0]))).data))
    np.testing.assert_allclose(sm, np.log1p(np.exp(-1.0)), rtol=1e-5)


def test_rnnt_loss_two_frame():
    # tiny lattice with hand-checkable paths: T=2, U=1, V=2 (blank=0)
    F = pt.nn.functional
    logits = np.zeros((1, 2, 2, 2), np.float32)  # uniform: log 0.5 each
    loss = float(np.asarray(F.rnnt_loss(
        pt.to_tensor(logits), pt.to_tensor(np.asarray([[1]], np.int64)),
        pt.to_tensor(np.asarray([2])), pt.to_tensor(np.asarray([1])),
        fastemit_lambda=0.0, reduction="none").data).ravel()[0])
    # paths: (emit@t0, blank, blank) ... enumerate: alignments of length
    # T+U=3 with 1 label: C(2,1)=2 paths, each prob (1/2)^3
    np.testing.assert_allclose(np.exp(-loss), 2 * 0.5 ** 3, rtol=1e-4)


def test_grid_sample_and_affine_grid_roundtrip():
    F = pt.nn.functional
    img = pt.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    theta = pt.to_tensor(np.asarray([[[1, 0, 0], [0, 1, 0]]], np.float32))
    grid = F.affine_grid(theta, [1, 1, 4, 4])
    out = F.grid_sample(img, grid)
    np.testing.assert_allclose(np.asarray(out.data), np.asarray(img.data),
                               atol=1e-5)


def test_max_unpool_roundtrip():
    F = pt.nn.functional
    x = pt.to_tensor(np.random.RandomState(0).rand(1, 2, 4, 4)
                     .astype(np.float32))
    pooled, mask = F.max_pool2d(x, 2, return_mask=True)
    up = F.max_unpool2d(pooled, mask, 2)
    # unpooled peaks match pooled values at max positions; sum preserved
    np.testing.assert_allclose(np.asarray(up.data).sum(),
                               np.asarray(pooled.data).sum(), rtol=1e-6)
    assert tuple(up.shape) == (1, 2, 4, 4)


def test_sequence_mask_and_temporal_shift():
    F = pt.nn.functional
    m = np.asarray(F.sequence_mask(
        pt.to_tensor(np.asarray([1, 3])), maxlen=4).data)
    np.testing.assert_array_equal(m, [[1, 0, 0, 0], [1, 1, 1, 0]])
    x = pt.to_tensor(np.random.RandomState(1).randn(4, 8, 2, 2)
                     .astype(np.float32))
    ts = F.temporal_shift(x, seg_num=2, shift_ratio=0.25)
    assert tuple(ts.shape) == (4, 8, 2, 2)


# ------------------------------------------------------------ extended nn
def test_extended_layers_smoke():
    nn = pt.nn
    x = pt.to_tensor(np.random.RandomState(0).randn(2, 8).astype(np.float32))
    asl = nn.AdaptiveLogSoftmaxWithLoss(8, 12, cutoffs=[3, 6])
    out, loss = asl(x, pt.to_tensor(np.asarray([0, 11])))
    lp = np.asarray(asl.log_prob(x).data)
    assert np.allclose(np.exp(lp).sum(-1), 1.0, atol=1e-4)
    hs = nn.HSigmoidLoss(8, 6)
    hl = hs(x, pt.to_tensor(np.asarray([0, 5])))
    assert np.isfinite(np.asarray(hl.data)).all()
    sn = nn.SpectralNorm((6, 3), power_iters=8)
    w = pt.to_tensor(np.random.RandomState(1).randn(6, 3).astype(np.float32))
    sv = np.linalg.svd(np.asarray(sn(w).data))[1][0]
    assert abs(sv - 1.0) < 0.05
    img = pt.to_tensor(np.random.RandomState(2).randn(1, 4, 4, 4)
                       .astype(np.float32))
    assert tuple(nn.PixelUnshuffle(2)(img).shape) == (1, 16, 2, 2)
    assert tuple(nn.ChannelShuffle(2)(img).shape) == (1, 4, 4, 4)
    assert tuple(nn.ZeroPad2D(1)(img).shape) == (1, 4, 6, 6)
    ld = nn.LayerDict({"a": nn.Linear(2, 2)})
    ld["b"] = nn.Linear(2, 3)
    assert set(ld.keys()) == {"a", "b"} and len(ld.parameters()) == 4


def test_birnn_and_unflatten():
    nn = pt.nn
    bi = nn.BiRNN(nn.GRUCell(4, 5), nn.GRUCell(4, 5))
    o, _ = bi(pt.to_tensor(np.zeros((2, 6, 4), np.float32)))
    assert tuple(o.shape) == (2, 6, 10)
    u = nn.Unflatten(1, (2, 3))
    assert tuple(u(pt.to_tensor(np.zeros((4, 6), np.float32))).shape) == \
        (4, 2, 3)


# ------------------------------------------------------------- transforms
def test_transforms_batch():
    from paddle_tpu.vision import transforms as T
    img = (np.random.RandomState(0).rand(12, 12, 3) * 255).astype(np.uint8)
    assert T.affine(img, angle=0).shape == img.shape
    assert T.pad(img, 2).shape == (16, 16, 3)
    g = T.Grayscale(3)(img)
    assert (g[..., 0] == g[..., 1]).all()
    out = T.Compose([T.RandomResizedCrop(8), T.RandomErasing(prob=1.0)])(img)
    assert out.shape == (8, 8, 3)
    # hue shift by 1.0 is identity (mod 1); by 0 is identity
    np.testing.assert_allclose(T.adjust_hue(img, 0.0), img, atol=2)


# ---------------------------------------------------- distributed / static
def test_distributed_facade_extras():
    d = pt.distributed
    t = pt.to_tensor(np.ones((4,), np.float32))
    d.send(t)
    r = d.recv()
    assert np.asarray(r.data).sum() == 4
    out = d.reduce_scatter(None, [t, pt.to_tensor(
        np.full((4,), 3.0, np.float32))])
    np.testing.assert_allclose(np.asarray(out.data), 4.0)
    assert d.is_available() and d.get_backend().startswith("xla:")
    lin = d.split(None, (8, 12), operation="linear", axis=1)
    assert type(lin).__name__ == "ColumnParallelLinear"
    emb = d.split(None, (100, 16), operation="embedding")
    assert type(emb).__name__ == "VocabParallelEmbedding"


def test_static_compat():
    st = pt.static
    x = pt.to_tensor(np.asarray([[0.2, 0.8], [0.9, 0.1]], np.float32))
    y = pt.to_tensor(np.asarray([1, 1], np.int64))
    acc = float(np.asarray(st.accuracy(x, y).data))
    assert abs(acc - 0.5) < 1e-6
    auc = float(np.asarray(st.auc(x, pt.to_tensor(
        np.asarray([1, 0], np.int64))).data))
    assert abs(auc - 1.0) < 1e-6  # positive scored higher
    assert len(st.cpu_places()) >= 1
    w = pt.create_parameter([3], "float32")
    ema = st.ExponentialMovingAverage(0.9)
    ema.update([w])
    orig = np.asarray(w.data).copy()
    w._data = w._data + 10.0
    ema.update()
    with ema.apply():
        assert np.asarray(w.data).mean() < orig.mean() + 10.0
    assert np.allclose(np.asarray(w.data), orig + 10.0)


# ------------------------------------------------------------------ audio
def test_audio_wav_roundtrip(tmp_path):
    from paddle_tpu import audio
    sr = 8000
    t = np.linspace(0, 1, sr, endpoint=False)
    wave = (0.5 * np.sin(2 * np.pi * 440 * t)).astype(np.float32)[None, :]
    path = str(tmp_path / "tone.wav")
    audio.save(path, pt.to_tensor(wave), sr)
    info = audio.info(path)
    assert info.sample_rate == sr and info.num_channels == 1
    loaded, sr2 = audio.load(path)
    assert sr2 == sr
    np.testing.assert_allclose(np.asarray(loaded.data), wave, atol=1e-3)


# -------------------------------------------------------------- geometric
def test_geometric_sampling_and_reindex():
    from paddle_tpu import geometric as G
    # CSC graph: node 0 has neighbors {1, 2, 3}; node 1 has {0}
    row = np.asarray([1, 2, 3, 0], np.int64)
    colptr = np.asarray([0, 3, 4], np.int64)
    nb, cnt = G.sample_neighbors(pt.to_tensor(row), pt.to_tensor(colptr),
                                 pt.to_tensor(np.asarray([0])),
                                 sample_size=2)
    assert int(np.asarray(cnt.data)[0]) == 2
    assert set(np.asarray(nb.data)) <= {1, 2, 3}
    out, nodes = G.reindex_graph(pt.to_tensor(np.asarray([5, 9])),
                                 pt.to_tensor(np.asarray([9, 7, 5, 7])),
                                 None)
    np.testing.assert_array_equal(np.asarray(out.data), [1, 2, 0, 2])
    np.testing.assert_array_equal(np.asarray(nodes.data), [5, 9, 7])
    uv = G.send_uv(pt.to_tensor(np.asarray([[1.0], [2.0]], np.float32)),
                   pt.to_tensor(np.asarray([[10.0], [20.0]], np.float32)),
                   pt.to_tensor(np.asarray([0, 1])),
                   pt.to_tensor(np.asarray([1, 0])), "add")
    np.testing.assert_allclose(np.asarray(uv.data), [[21.0], [12.0]])


# ------------------------------------------------------------- incubate
def test_incubate_exports():
    inc = pt.incubate
    x = pt.to_tensor(np.random.RandomState(0).randn(2, 4, 4)
                     .astype(np.float32))
    sm = np.asarray(inc.softmax_mask_fuse_upper_triangle(x).data)
    # causal: first row attends only to position 0
    np.testing.assert_allclose(sm[:, 0, 0], 1.0, rtol=1e-5)
    assert abs(sm[0, 2, :3].sum() - 1.0) < 1e-5
    w = pt.create_parameter([4], "float32")
    ma = inc.ModelAverage(parameters=[w])
    ma.step()
    w._data = w._data + 2.0
    ma.step()
    before = np.asarray(w.data).copy()
    ma.apply()
    assert np.allclose(np.asarray(w.data), before - 1.0)
    ma.restore()
    assert np.allclose(np.asarray(w.data), before)
    assert float(np.asarray(inc.identity_loss(
        pt.to_tensor(np.asarray([2.0, 4.0])), "mean").data)) == 3.0


# ---------------------------------------------------------------- inplace
def test_functional_inplace_activations():
    F = pt.nn.functional
    x = pt.to_tensor(np.asarray([-1.0, 2.0], np.float32))
    assert F.relu_(x) is x
    np.testing.assert_allclose(np.asarray(x.data), [0.0, 2.0])
    y = pt.to_tensor(np.asarray([0.5, -0.5], np.float32))
    F.tanh_(y)
    np.testing.assert_allclose(np.asarray(y.data), np.tanh([0.5, -0.5]),
                               rtol=1e-6)


# ------------------------------------------------- review-fix regressions
def test_adaptive_and_fractional_pools_return_mask():
    F = pt.nn.functional
    x = pt.to_tensor(np.random.RandomState(3).rand(1, 1, 4, 4, 4)
                     .astype(np.float32))
    out, mask = F.adaptive_max_pool3d(x, 2, return_mask=True)
    assert tuple(out.shape) == (1, 1, 2, 2, 2)
    assert tuple(mask.shape) == (1, 1, 2, 2, 2)
    # indices point at the max values
    flat = np.asarray(x.data).reshape(1, 1, -1)
    picked = np.take_along_axis(flat, np.asarray(mask.data).reshape(1, 1, -1),
                                axis=-1)
    np.testing.assert_allclose(picked.reshape(-1),
                               np.asarray(out.data).reshape(-1))
    x2 = pt.to_tensor(np.random.RandomState(4).rand(1, 2, 8, 8)
                      .astype(np.float32))
    p2, m2 = F.fractional_max_pool2d(x2, 4, random_u=0.3, return_mask=True)
    flat2 = np.asarray(x2.data).reshape(1, 2, -1)
    picked2 = np.take_along_axis(flat2, np.asarray(m2.data).reshape(1, 2, -1),
                                 axis=-1)
    np.testing.assert_allclose(picked2.reshape(-1),
                               np.asarray(p2.data).reshape(-1))


def test_householder_batched_and_ormqr_full_q():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 4, 3).astype(np.float32)
    tau = rng.rand(2, 3).astype(np.float32)
    q = np.asarray(pt.linalg.householder_product(
        pt.to_tensor(x), pt.to_tensor(tau)).data)
    assert q.shape == (2, 4, 3)
    other = rng.randn(4, 2).astype(np.float32)
    out = np.asarray(pt.linalg.ormqr(
        pt.to_tensor(x[0]), pt.to_tensor(tau[0]),
        pt.to_tensor(other)).data)
    assert out.shape == (4, 2)  # full m x m Q applied


def test_random_affine_scalar_shear():
    from paddle_tpu.vision import transforms as T
    img = (np.random.RandomState(6).rand(8, 8, 3) * 255).astype(np.uint8)
    out = T.RandomAffine(10, shear=5)(img)
    assert out.shape == img.shape


def test_geometric_sampler_respects_seed():
    from paddle_tpu import geometric as G
    row = np.arange(50, dtype=np.int64)
    colptr = np.asarray([0, 50], np.int64)
    pt.seed(123)
    a = np.asarray(G.sample_neighbors(pt.to_tensor(row),
                                      pt.to_tensor(colptr),
                                      pt.to_tensor(np.asarray([0])),
                                      sample_size=5)[0].data)
    pt.seed(123)
    b = np.asarray(G.sample_neighbors(pt.to_tensor(row),
                                      pt.to_tensor(colptr),
                                      pt.to_tensor(np.asarray([0])),
                                      sample_size=5)[0].data)
    np.testing.assert_array_equal(a, b)


def test_scatter_object_list_and_flops():
    d = pt.distributed
    out = []
    d.scatter_object_list(out, [{"a": 1}, {"b": 2}])
    # world size 1: the single rank receives the whole list
    assert out == [{"a": 1}, {"b": 2}]
    assert pt.flops(pt.nn.Linear(4, 8), [2, 4]) == 2 * 4 * 8 * 2

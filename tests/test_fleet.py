"""Serving fleet (paddle_tpu/serving/fleet/): replicas behind the
prefix-affinity router, drain-on-failure, aggregated observability.

Correctness bar (ISSUE r18): routing and re-dispatch must be INVISIBLE
to a request's math — every greedy continuation equals a standalone
``generate()`` run token-for-token whatever replica (or sequence of
replicas, across a drain) served it. The kill-one-replica test pins
the zero-drop drain contract end to end.
"""
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.fleet import (DRAINING, GONE, JOINING, SERVING,
                                      FleetRouter, Replica, ServingFleet)

CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


import functools


@functools.lru_cache(maxsize=None)
def _gen_jit(n):
    return jax.jit(lambda p, t: L.generate(p, t, CFG, max_new_tokens=n))


def _ref(params, prompt, n):
    out = _gen_jit(n)(params, jnp.asarray(prompt)[None])
    return np.asarray(out)[0, len(prompt):]


def _factory(params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_prompt_len", 16)
    kw.setdefault("max_new_tokens_cap", 16)

    def make():
        return ServingEngine(params, CFG, **kw)

    return make


def _fleet(params, n=2, **fkw):
    ekw = fkw.pop("engine_kw", {})
    return ServingFleet(_factory(params, **ekw), replicas=n, **fkw)


# ---------------------------------------------------------------------------
# smoke: bitwise parity vs a single engine / generate()
# ---------------------------------------------------------------------------

def test_fleet_bitwise_matches_generate(params):
    """2 replicas, mixed requests spread by round-robin: every stream
    equals its standalone generate() run token-for-token (the CI fleet
    smoke gate — routing must be invisible to the math)."""
    rng = np.random.RandomState(0)
    specs = [(rng.randint(0, CFG.vocab_size,
                          (int(rng.randint(2, 12)),)).astype(np.int32),
              int(rng.randint(2, 10))) for _ in range(8)]
    with _fleet(params, n=2, policy="round_robin") as fleet:
        handles = [fleet.submit(p, m) for p, m in specs]
        outs = [h.result(timeout=300) for h in handles]
        snap = fleet.snapshot()
    for (p, m), out in zip(specs, outs):
        np.testing.assert_array_equal(out, _ref(params, p, m))
    served = {name: h["counters"]["completed"]
              for name, h in snap["replicas"].items()}
    assert sum(served.values()) == len(specs)
    # round-robin really spread the work across both replicas
    assert all(v > 0 for v in served.values()), served


def test_fleet_lifecycle_and_generations(params):
    with _fleet(params, n=2) as fleet:
        reps = fleet.replicas()
        assert [r.state for r in reps] == [SERVING, SERVING]
        assert fleet.generation == 2            # one bump per join
        r2 = fleet.join(role="decode")
        assert fleet.generation == 3 and r2.state == SERVING
        assert r2.role == "decode"
        fleet.drain(r2.name)
        assert r2.state == GONE and fleet.generation == 4
        # GONE replicas still answer health() for postmortems
        h = r2.health()
        assert h["state"] == GONE and not h["alive"]
        # the router no longer selects it
        assert r2.name not in [r.name for r in fleet.router._candidates()]
    assert all(r.state == GONE for r in fleet.replicas())


# ---------------------------------------------------------------------------
# prefix-affinity routing
# ---------------------------------------------------------------------------

def test_affinity_keeps_session_on_one_replica(params):
    """Requests sharing a prompt header route to the replica whose trie
    is warm: one cold prefill per session, every follow-up a hit —
    while round-robin on the same workload scatters them cold."""
    rng = np.random.RandomState(1)
    # 3 sessions over 2 replicas: an ODD session count, so round-robin
    # cannot accidentally stay session-aligned (4 sessions x 2 replicas
    # would rotate back onto the same replica every turn)
    headers = [rng.randint(0, CFG.vocab_size, (8,)).astype(np.int32)
               for _ in range(3)]

    def run(policy):
        with _fleet(params, n=2, policy=policy) as fleet:
            for turn in range(4):
                hs = []
                for head in headers:
                    tail = rng.randint(0, CFG.vocab_size,
                                       (4,)).astype(np.int32)
                    hs.append(fleet.submit(
                        np.concatenate([head, tail]), 3))
                for h in hs:        # multi-turn: next turn after replies
                    h.result(timeout=300)
                # outlive the router's summary/load TTL cache: the next
                # turn must see a FRESH affinity summary (real session
                # turn gaps dwarf the 50ms TTL; this tiny model's don't)
                time.sleep(2.5 * fleet.router.summary_ttl_s)
            snap = fleet.snapshot()
        hits = sum(h["counters"]["prefix_hits"]
                   for h in snap["replicas"].values())
        misses = sum(h["counters"]["prefix_misses"]
                     for h in snap["replicas"].values())
        return hits, misses, snap

    hits, misses, snap = run("affinity")
    # 3 sessions x 4 turns: exactly one cold prefill per session
    assert misses == len(headers), (hits, misses)
    assert hits == 3 * len(headers)
    rr_hits, rr_misses, _ = run("round_robin")
    assert rr_misses > misses, (misses, rr_misses)
    # the router actually used the affinity/hash paths, not fallback
    routed = snap["router"]
    assert routed["routed_affinity"] > 0
    assert routed["routed_affinity"] + routed["routed_hash"] \
        + routed["routed_fallback"] == 12


def test_consistent_hash_fallback_groups_unseen_prefixes(params):
    """Before a chain is cached anywhere, requests sharing a header
    must STILL agree on a replica (rendezvous hash on the first-page
    fingerprint) — racing session starts must not build N cold
    tries."""
    with _fleet(params, n=3) as fleet:
        router = fleet.router
        rng = np.random.RandomState(2)
        head = rng.randint(0, CFG.vocab_size, (8,)).astype(np.int32)
        picks = set()
        for _ in range(5):
            tail = rng.randint(0, CFG.vocab_size, (4,)).astype(np.int32)
            from paddle_tpu.serving import Request
            req = Request(np.concatenate([head, tail]), 2)
            order = router._pick(req, router._candidates())
            picks.add(order[0].name)
        assert len(picks) == 1, picks


def test_role_pools_route_prefill_vs_decode(params):
    """Disaggregation as routing policy: prompt-dominated requests land
    on the prefill-tagged replica, decode-dominated on the decode one."""
    with _fleet(params, n=2, roles=["prefill", "decode"],
                policy="least_loaded") as fleet:
        reps = {r.role: r for r in fleet.replicas()}
        rng = np.random.RandomState(3)
        long_prompt = rng.randint(0, CFG.vocab_size,
                                  (14,)).astype(np.int32)
        short_prompt = rng.randint(0, CFG.vocab_size,
                                   (2,)).astype(np.int32)
        fleet.submit(long_prompt, 2).result(timeout=300)
        fleet.submit(short_prompt, 12).result(timeout=300)
        c_pre = reps["prefill"].engine.snapshot()["counters"]
        c_dec = reps["decode"].engine.snapshot()["counters"]
    assert c_pre["completed"] == 1 and c_dec["completed"] == 1
    assert c_pre["tokens_out"] == 2     # the long-prompt short-decode
    assert c_dec["tokens_out"] == 12


# ---------------------------------------------------------------------------
# drain / kill / re-dispatch
# ---------------------------------------------------------------------------

def test_drain_redispatches_queued_and_drops_nothing(params):
    """Drain a replica while it holds running AND queued requests:
    in-flight finish on the drained replica, queued re-dispatch to the
    survivor, every handle resolves bitwise-correct."""
    rng = np.random.RandomState(4)
    specs = [(rng.randint(0, CFG.vocab_size, (4,)).astype(np.int32), 12)
             for _ in range(10)]
    with _fleet(params, n=2, policy="round_robin",
                engine_kw=dict(max_batch=2)) as fleet:
        handles = [fleet.submit(p, m) for p, m in specs]
        victim = fleet.replicas()[0]
        handed = fleet.drain(victim.name)
        outs = [h.result(timeout=300) for h in handles]
        snap = fleet.snapshot()
    for (p, m), out in zip(specs, outs):
        np.testing.assert_array_equal(out, _ref(params, p, m))
    assert victim.state == GONE
    # with 5 requests round-robined onto a 2-slot replica, some were
    # still queued at drain time and went through re-dispatch
    assert len(handed) > 0
    assert snap["router"]["redispatched"] == len(handed)
    assert snap["router"]["redispatch_failed"] == 0
    assert snap["fleet"]["handed_back"] == len(handed)


def test_redispatch_is_exactly_once_per_request(params):
    """A request whose second home also drains is failed, not bounced
    around a shrinking fleet (dedup by request id)."""
    rng = np.random.RandomState(5)
    with _fleet(params, n=2, policy="round_robin",
                engine_kw=dict(max_batch=1)) as fleet:
        specs = [(rng.randint(0, CFG.vocab_size,
                              (3,)).astype(np.int32), 14)
                 for _ in range(8)]
        handles = [fleet.submit(p, m) for p, m in specs]
        names = [r.name for r in fleet.replicas()]
        fleet.drain(names[0])       # queued -> re-dispatched to names[1]
        fleet.drain(names[1])       # re-dispatch AGAIN -> must fail them
        resolved = 0
        for h in handles:
            try:
                h.result(timeout=300)
                resolved += 1
            except RuntimeError as e:
                assert "re-dispatch" in str(e)
        snap = fleet.snapshot()
    # nothing hangs: every handle resolved (completed or failed loudly)
    assert resolved + snap["router"]["redispatch_failed"] == len(specs)
    assert snap["router"]["redispatch_failed"] > 0


@pytest.mark.slow
def test_kill_one_replica_end_to_end(params):
    """The ISSUE r18 acceptance scenario, in-process: 3 replicas under
    flood, one killed mid-traffic (drain-on-failure), submissions
    continuing throughout — zero drops, every stream bitwise-correct,
    clean recompile sentinels on the survivors."""
    rng = np.random.RandomState(6)
    specs = [(rng.randint(0, CFG.vocab_size,
                          (int(rng.randint(2, 12)),)).astype(np.int32),
              int(rng.randint(4, 14))) for _ in range(30)]
    fleet = _fleet(params, n=3)
    fleet.arm_sentinels()
    handles = []
    killed = {}

    def _submit_all():
        for i, (p, m) in enumerate(specs):
            if i == len(specs) // 2:
                victim = fleet.replicas(SERVING)[0]
                handed = fleet.kill(victim.name)
                killed["name"] = victim.name
                killed["handed"] = len(handed)
            handles.append(fleet.submit(p, m))
            time.sleep(0.002)

    _submit_all()
    outs = [h.result(timeout=300) for h in handles]
    snap = fleet.snapshot()
    sentinels = {r.name: r.sentinel_report() for r in fleet.replicas()}
    fleet.close()
    # zero drops, bitwise parity across the kill
    for (p, m), out in zip(specs, outs):
        np.testing.assert_array_equal(out, _ref(params, p, m))
    assert "name" in killed
    assert snap["replicas"][killed["name"]]["state"] == GONE
    assert snap["router"]["redispatch_failed"] == 0
    assert snap["fleet"]["kills"] == 1
    # survivors' sentinels stayed clean (no post-warmup compiles: the
    # fleet's shared step fns were warmed before arming)
    for name, rep in sentinels.items():
        if name != killed["name"] and rep is not None:
            assert rep["clean"], (name, rep)


# ---------------------------------------------------------------------------
# aggregated observability
# ---------------------------------------------------------------------------

def test_fleet_expose_single_scrape(params):
    with _fleet(params, n=2) as fleet:
        fleet.generate(np.asarray([1, 2, 3], np.int32), 4)
        text = fleet.expose()
        view = fleet.flight_view()
    lines = text.splitlines()
    # one TYPE line per family, even with 2 replicas sampling each
    types = [ln for ln in lines if ln.startswith("# TYPE")]
    assert len(types) == len(set(types))
    # per-replica labels present on engine families
    assert any('replica="r0"' in ln and "_submitted_total" in ln
               for ln in lines)
    assert any('replica="r1"' in ln and "_submitted_total" in ln
               for ln in lines)
    # fleet-level gauges ride the same scrape
    assert any(ln.startswith("paddle_serving_fleet_generation ")
               for ln in lines)
    # flight view: every replica reports lifecycle + recent ticks
    assert set(view) == {"r0", "r1"}
    assert all("ticks" in v and v["state"] == SERVING
               for v in view.values())


def test_arrival_schedule_is_seeded_and_replayable():
    """--arrival seed:K (ROADMAP item 5 first slice): the schedule
    (inter-arrival gaps, prompt lengths, mnt draws) replays
    bit-identical from its own seed, independent of the content seed."""
    import importlib
    sb = importlib.import_module("tools.serving_bench")
    assert sb.parse_arrival("seed:17") == 17
    assert sb.parse_arrival(None) is None
    with pytest.raises(ValueError):
        sb.parse_arrival("bogus")
    t1 = sb.build_trace(16, 100.0, 24, [4, 8], seed=0, arrival=17)
    t2 = sb.build_trace(16, 100.0, 24, [4, 8], seed=1, arrival=17)
    t3 = sb.build_trace(16, 100.0, 24, [4, 8], seed=0, arrival=18)
    # same schedule whatever the content seed ...
    assert [(a, len(p), m) for a, p, m in t1] == \
        [(a, len(p), m) for a, p, m in t2]
    # ... with content still governed by --seed
    assert any(not np.array_equal(p1, p2)
               for (_, p1, _), (_, p2, _) in zip(t1, t2))
    # a different schedule seed draws a different schedule
    assert [a for a, _, _ in t1] != [a for a, _, _ in t3]
    # session traces replay the same way (group interleave included)
    s1 = sb.build_session_trace(3, 4, 100.0, 8, 2, 6, [4], seed=0,
                                arrival=5)
    s2 = sb.build_session_trace(3, 4, 100.0, 8, 2, 6, [4], seed=9,
                                arrival=5)
    assert [(a, g, len(p), m) for a, g, p, m in s1] == \
        [(a, g, len(p), m) for a, g, p, m in s2]


def test_arrival_heavy_tailed_laws():
    """--arrival lognormal:K[:s] / pareto:K[:a] (ISSUE r16 satellite):
    heavy-tailed gaps + lengths with the SAME replay contract as
    seed:K — the spec string reproduces the schedule bitwise whatever
    the content seed — and visibly heavier tails than the exponential
    default at the same offered rate."""
    import importlib
    sb = importlib.import_module("tools.serving_bench")
    spec = sb.parse_arrival("lognormal:7")
    assert isinstance(spec, sb.ArrivalSpec)
    assert (spec.kind, spec.seed, spec.param) == ("lognormal", 7, 1.5)
    par = sb.parse_arrival("pareto:7:2.5")
    assert (par.kind, par.seed, par.param) == ("pareto", 7, 2.5)
    with pytest.raises(ValueError):         # Lomax needs a finite mean
        sb.parse_arrival("pareto:7:0.9")
    with pytest.raises(ValueError):
        sb.parse_arrival("lognormal:7:0")
    with pytest.raises(ValueError):
        sb.parse_arrival("weibull:7")
    # replay contract: same spec string -> same schedule, any --seed
    for s in ("lognormal:7", "pareto:7:2.5"):
        t1 = sb.build_trace(24, 100.0, 24, [4, 8],
                            seed=0, arrival=sb.parse_arrival(s))
        t2 = sb.build_trace(24, 100.0, 24, [4, 8],
                            seed=1, arrival=sb.parse_arrival(s))
        assert [(a, len(p), m) for a, p, m in t1] == \
            [(a, len(p), m) for a, p, m in t2]
        assert any(not np.array_equal(p1, p2)
                   for (_, p1, _), (_, p2, _) in zip(t1, t2))
        # lengths stay inside the geometry the engine is built for
        assert all(2 <= len(p) <= 24 and m in (4, 8)
                   for _, p, m in t1)
    # the tails are actually heavier: max/median inter-arrival gap far
    # above the exponential baseline at the same mean rate
    def max_over_median_gap(arrival):
        t = sb.build_trace(400, 100.0, 24, [4], seed=0,
                           arrival=arrival)
        gaps = np.diff([a for a, _, _ in t])
        return float(gaps.max() / np.median(gaps))
    base = max_over_median_gap(17)          # seed:17 -> exponential
    heavy = max_over_median_gap(sb.parse_arrival("lognormal:17"))
    assert heavy > 2.0 * base
    # session traces accept the spec too (fleet modes)
    s1 = sb.build_session_trace(3, 4, 100.0, 8, 2, 6, [4], seed=0,
                                arrival=sb.parse_arrival("pareto:5"))
    s2 = sb.build_session_trace(3, 4, 100.0, 8, 2, 6, [4], seed=9,
                                arrival=sb.parse_arrival("pareto:5"))
    assert [(a, g, len(p), m) for a, g, p, m in s1] == \
        [(a, g, len(p), m) for a, g, p, m in s2]


@pytest.mark.slow
def test_serving_bench_fleet_kill_replica():
    """End-to-end through tools/serving_bench.py --replicas 2: the
    fleet mode's JSON carries the acceptance signals — affinity
    hit-rate at the session ceiling and above forced round-robin, and
    the kill-one-replica scenario completing every accepted request
    with zero drops and clean survivor sentinels."""
    from tools.serving_bench import main
    res = main(["--replicas", "2", "--requests", "48",
                "--fleet-groups", "6", "--fleet-group-size", "10",
                "--arrival", "seed:3", "--layers", "2",
                "--hidden", "32"])
    row = res["fleet"]
    # hit rate: exactly one cold prefill per session (the ceiling for
    # this workload) and measurably above forced round-robin
    ceiling = 1 - 1 / 10
    assert row["hit_rate_affinity"] == pytest.approx(ceiling, abs=1e-6)
    assert row["affinity_beats_round_robin"]
    assert row["hit_rate_round_robin"] < row["hit_rate_affinity"]
    for arm in ("single", "affinity", "round_robin"):
        assert row["sessions"][arm]["drops"] == 0
    # kill-one-replica: zero drops, everything completed, survivors'
    # sentinels clean
    k = row["kill"]
    assert k["zero_drops"] and k["drops"] == 0
    assert k["completed"] == 48
    assert k["sentinel_clean_survivors"]
    assert k["redispatch_failed"] == 0


def test_replica_health_feeds_router_load(params):
    with _fleet(params, n=2) as fleet:
        rep = fleet.replicas()[0]
        h = rep.health()
        assert h["alive"] and h["state"] == SERVING
        assert "gauges" in h and "free_pages" in h["gauges"]
        assert rep.load() < float("inf")
        # a draining replica is never a routing candidate
        rep.state = DRAINING
        assert rep.load() == float("inf")
        assert not rep.serving
        rep.state = SERVING

"""Partial-graph capture (jit/segments.py): to_static(full_graph=False)
must keep compiled segments around a graph break instead of the round-3
wholesale eager fallback.

Reference: SOT subgraph splitting (python/paddle/jit/sot/translate.py:99).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.jit import segments


def t(x):
    return pt.to_tensor(np.asarray(x, np.float32))


def _chain(x, n):
    for i in range(n):
        x = pt.tanh(x * 1.01 + 0.01)
    return x


def test_break_splits_into_two_segments():
    calls = []

    @pt.jit.to_static(full_graph=False)
    def f(x):
        h = _chain(x, 5)                     # segment 1: 10 ops
        if float(h.mean()) > 0:              # GRAPH BREAK (concretise)
            h = h + 1.0
        return _chain(h, 5)                  # segment 2

    x = t([0.5, 1.0])
    out = f(x)
    assert f._segmented and not f._fell_back
    stats = f.graph_break_stats
    # >= 80% of tensor ops ran inside compiled segments (VERDICT r3 bar);
    # here the break itself is pure python so ALL ops are recorded
    total = stats["ops_recorded"] + stats["ops_eager"]
    assert stats["ops_recorded"] / total >= 0.8, stats
    assert stats["segments"] >= 2, stats

    # numerics match plain eager
    ref = _chain(_chain(x, 5) + 1.0, 5)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)


def test_segment_executables_are_reused():
    @pt.jit.to_static(full_graph=False)
    def f(x):
        h = _chain(x, 3)
        if float(h.sum()) > -1e9:
            h = h * 2.0
        return h

    x = t([0.1, 0.2])
    f(x)
    s1 = dict(f.graph_break_stats)
    f(x)
    f(x)
    s3 = f.graph_break_stats
    assert s3["cache_hits"] >= s3["segments"] - s1["segments"], (s1, s3)
    # and repeated calls stay correct
    np.testing.assert_allclose(f(x).numpy(),
                               (np.tanh(np.tanh(np.tanh(
                                   np.asarray([0.1, 0.2], np.float32)
                                   * 1.01 + 0.01) * 1.01 + 0.01)
                                   * 1.01 + 0.01) * 2.0), rtol=1e-5)


def test_both_branches_of_break_work():
    @pt.jit.to_static(full_graph=False)
    def f(x):
        h = x * 3.0
        if float(h.sum()) > 0:
            return h + 100.0
        return h - 100.0

    np.testing.assert_allclose(f(t([1.0])).numpy(), [103.0])
    np.testing.assert_allclose(f(t([-1.0])).numpy(), [-103.0])


def test_full_graph_true_still_raises():
    @pt.jit.to_static(full_graph=True)
    def f(x):
        if float(x.sum()) > 0:
            return x + 1
        return x

    with pytest.raises(Exception):
        f(t([1.0]))


def test_grad_path_trains_correctly():
    # r4: training fell back to wholesale eager; r5: grad-wanted ops
    # record into tape-aware segments — either way the grads must be
    # exactly d(2x^2)/dx
    @pt.jit.to_static(full_graph=False)
    def f(x):
        h = x * x
        if float(h.sum()) > 0:
            h = h * 2.0
        return h

    x = pt.to_tensor(np.asarray([3.0], np.float32), stop_gradient=False)
    y = f(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0])  # d(2x^2)/dx


def test_traceable_function_never_segments():
    @pt.jit.to_static(full_graph=False)
    def f(x):
        return _chain(x, 4)

    out = f(t([0.3]))
    assert not f._segmented and not f._fell_back
    assert out.shape == [1]


def test_shape_metadata_does_not_flush():
    # reading .shape/.ndim between ops must not end the segment
    @pt.jit.to_static(full_graph=False)
    def f(x):
        h = x * 2.0
        assert h.shape == [2]      # metadata only
        h = h.reshape([2, 1])
        if float(h.sum()) > 0:
            h = h + 1
        return h

    f(t([1.0, 2.0]))
    stats = f.graph_break_stats
    assert stats["segments"] >= 1
    assert stats["ops_recorded"] >= 2


# ---------------------------------------------------------------------------
# r5: training THROUGH graph breaks (tape-aware segments, VERDICT r4 #4)
# ---------------------------------------------------------------------------

def test_training_records_segments_and_matches_eager_grads():
    """Grad-wanted ops record into compiled segments; each flush is ONE
    GradNode (backward = jax.vjp of the segment). Reference: SOT compiles
    training subgraphs (jit/sot/translate.py:99)."""
    def body(x, w):
        h = _chain(x * w, 4)
        if float(h.sum()) > -1e9:            # GRAPH BREAK
            h = h * 2.0
        return _chain(h, 4)

    f = pt.jit.to_static(body, full_graph=False)
    x = t([0.5, 1.0])
    w = pt.to_tensor(np.asarray([1.5], np.float32), stop_gradient=False)
    out = f(x, w)
    out.sum().backward()
    stats = f.graph_break_stats
    total = stats["ops_recorded"] + stats["ops_eager"]
    assert stats["ops_recorded"] / total >= 0.8, stats
    assert stats["grad_segments"] >= 2, stats

    # eager reference: same math, no segmenting
    w2 = pt.to_tensor(np.asarray([1.5], np.float32), stop_gradient=False)
    ref = body(x, w2)
    ref.sum().backward()
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)
    np.testing.assert_allclose(w.grad.numpy(), w2.grad.numpy(), rtol=1e-5)


def test_training_through_break_loss_falls():
    """A Layer with a data-dependent break actually TRAINS segmented."""
    class Net(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = pt.nn.Linear(8, 16)
            self.fc2 = pt.nn.Linear(16, 8)

        def forward(self, x):
            h = pt.tanh(self.fc1(x))
            if float(h.mean()) > -1e9:       # GRAPH BREAK
                h = h * 1.0
            return self.fc2(h)

    pt.seed(0)
    net = pt.jit.to_static(Net(), full_graph=False)
    opt = pt.optimizer.AdamW(learning_rate=5e-3,
                             parameters=net.parameters())
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randn(16, 8).astype(np.float32))
    y = pt.to_tensor(np.tanh(rng.randn(16, 8)).astype(np.float32))
    losses = []
    for _ in range(12):
        out = net(x)
        loss = ((out - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, losses
    stats = net.forward.graph_break_stats
    total = stats["ops_recorded"] + stats["ops_eager"]
    assert stats["ops_recorded"] / total >= 0.8, stats
    assert stats["grad_segments"] > 0, stats
    # steady state reuses the compiled grad segments
    assert stats["cache_hits"] > 0, stats


def test_segment_create_graph_raises_clearly():
    def body(x, w):
        h = x * w
        if float(h.sum()) > -1e9:
            h = h * 2.0
        return h

    f = pt.jit.to_static(body, full_graph=False)
    x = t([1.0])
    w = pt.to_tensor(np.asarray([2.0], np.float32), stop_gradient=False)
    out = f(x, w)
    with pytest.raises((NotImplementedError, RuntimeError)):
        g = pt.autograd.grad(out.sum(), [w], create_graph=True)
        g[0].sum().backward()

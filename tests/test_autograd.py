"""Autograd tape tests (the reference's check_grad pattern,
test/legacy_test/op_test.py:3114: analytic grads vs numeric/known refs)."""
import numpy as np
import pytest

import paddle_tpu as pt


def t(x, sg=False):
    return pt.to_tensor(np.asarray(x, np.float32), stop_gradient=sg)


def numeric_grad(fn, x, eps=1e-3):
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[idx] += eps
        xm[idx] -= eps
        g[idx] = (fn(xp) - fn(xm)) / (2 * eps)
        it.iternext()
    return g


@pytest.mark.parametrize("opname,fn,lo,hi", [
    ("exp", lambda x: np.exp(x).sum(), -1, 1),
    ("tanh", lambda x: np.tanh(x).sum(), -1, 1),
    ("sqrt", lambda x: np.sqrt(x).sum(), 0.5, 2),
    ("log", lambda x: np.log(x).sum(), 0.5, 2),
    ("sigmoid", lambda x: (1 / (1 + np.exp(-x))).sum(), -1, 1),
])
def test_unary_grads(opname, fn, lo, hi):
    x = np.random.RandomState(0).uniform(lo, hi, (3, 4))
    xt = t(x)
    y = getattr(pt, opname)(xt).sum()
    y.backward()
    ng = numeric_grad(fn, x)
    np.testing.assert_allclose(xt.grad.numpy(), ng, rtol=1e-2, atol=1e-3)


def test_matmul_grad():
    rng = np.random.RandomState(1)
    a, b = rng.randn(3, 4), rng.randn(4, 5)
    at, bt = t(a), t(b)
    out = pt.matmul(at, bt).sum()
    out.backward()
    np.testing.assert_allclose(at.grad.numpy(),
                               np.ones((3, 5)) @ b.T, rtol=1e-5)
    np.testing.assert_allclose(bt.grad.numpy(),
                               a.T @ np.ones((3, 5)), rtol=1e-5)


def test_grad_accumulation():
    x = t([1.0, 2.0])
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient():
    x = t([1.0, 2.0])
    y = t([3.0, 4.0], sg=True)
    (x * y).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 4.0])
    assert y.grad is None


def test_detach():
    x = t([1.0, 2.0])
    y = (x * 2).detach()
    assert y.stop_gradient
    z = (x * 2) + y
    z.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_no_grad():
    x = t([1.0])
    with pt.no_grad():
        y = x * 2
    assert y._node is None and y.stop_gradient


def test_retain_graph():
    x = t([1.0, 2.0])
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 8.0])
    with pytest.raises(RuntimeError):
        y.backward()


def test_second_backward_raises():
    x = t([1.0])
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_branching_graph():
    x = t([2.0])
    a = x * 3
    b = x * 5
    (a + b).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])


def test_deep_chain():
    x = t([1.5])
    y = x
    for _ in range(50):
        y = y * 1.01
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.01 ** 50], rtol=1e-4)


def test_functional_grad_api():
    x = t([1.0, 2.0])
    y = t([3.0, 4.0])
    out = (x * y).sum()
    gx, gy = pt.grad(out, [x, y])
    np.testing.assert_allclose(gx.numpy(), [3.0, 4.0])
    np.testing.assert_allclose(gy.numpy(), [1.0, 2.0])
    assert x.grad is None  # .grad not polluted


def test_grad_hooks():
    x = t([1.0, 2.0])
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    x.register_hook(hook)
    (x * 3).sum().backward()
    np.testing.assert_allclose(seen[0], [3.0, 3.0])
    np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])


def test_multi_output_op_grad():
    x = t(np.random.RandomState(2).randn(4, 6))
    parts = pt.split(x, 2, axis=1)
    (parts[0].sum() * 2 + parts[1].sum() * 3).backward()
    expect = np.concatenate([np.full((4, 3), 2.0), np.full((4, 3), 3.0)], 1)
    np.testing.assert_allclose(x.grad.numpy(), expect)


def test_backward_nonscalar_with_grad():
    x = t([[1.0, 2.0], [3.0, 4.0]])
    y = x * 2
    y.backward(pt.to_tensor([[1.0, 0.0], [0.0, 1.0]]))
    np.testing.assert_allclose(x.grad.numpy(), [[2.0, 0.0], [0.0, 2.0]])


def test_pylayer():
    class Double(pt.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x, factor):
            ctx.save_for_backward(x)
            ctx.factor = factor
            return x * factor

        @staticmethod
        def backward(ctx, gy):
            (x,) = ctx.saved_tensor()
            return gy * ctx.factor

    x = t([1.0, 2.0])
    out = Double.apply(x, 3.0)
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])


def test_broadcast_grad():
    x = t(np.ones((3, 4)))
    b = t(np.ones((4,)))
    (x + b).sum().backward()
    np.testing.assert_allclose(b.grad.numpy(), [3.0] * 4)


def test_getitem_grad():
    x = t(np.arange(6.0).reshape(2, 3))
    y = x[0, :2].sum()
    y.backward()
    expect = np.zeros((2, 3))
    expect[0, :2] = 1
    np.testing.assert_allclose(x.grad.numpy(), expect)

"""Flagship model + hybrid parallelism tests on the 8-device CPU mesh.

Mirrors the reference's hybrid-strategy integration tests
(test/auto_parallel/hybrid_strategy/semi_auto_llama.py — dp/mp/pp Llama on
multi-GPU): here the mesh is virtual, the parallelism is real.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.parallel import init_hybrid_mesh
from paddle_tpu.models import llama as L


def _cfg(**kw):
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("use_flash_attention", False)
    kw.setdefault("remat", False)
    return L.LlamaConfig.tiny(**kw)


def test_forward_shapes():
    cfg = _cfg()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = L.forward(params, toks, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_fused_norm_rope_path_matches_unfused():
    # the bench path runs the pallas fused rmsnorm/rope between GEMMs
    # (interpret mode here); it must agree with the jnp formulation
    cfg_f = _cfg(use_fused_norm_rope=True)
    cfg_u = _cfg(use_fused_norm_rope=False)
    params = L.init_params(cfg_f, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                              cfg_f.vocab_size)

    def loss(p, cfg):
        lg = L.forward(p, toks, cfg)
        return (lg.astype(jnp.float32) ** 2).mean()

    lf, gf = jax.value_and_grad(loss)(params, cfg_f)
    lu, gu = jax.value_and_grad(loss)(params, cfg_u)
    np.testing.assert_allclose(float(lf), float(lu), rtol=2e-5)
    flat_f = jax.tree_util.tree_leaves(gf)
    flat_u = jax.tree_util.tree_leaves(gu)
    for a, b in zip(flat_f, flat_u):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_pipeline_matches_single_stage():
    """forward_pipelined (pp=2, 2 microbatches) == forward (pp=1)."""
    hm = init_hybrid_mesh(dp=2, pp=2, tp=2, set_global=False)
    cfg1 = _cfg()
    cfg2 = _cfg(pp_stages=2, num_microbatches=2)
    params = L.init_params(cfg1, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg1.vocab_size)
    ref = L.forward(params, toks, cfg1)
    with hm.mesh:
        sharded = L.shard_params(params, cfg2, hm.mesh)
        out = jax.jit(lambda p, t: L.forward_pipelined(p, t, cfg2, hm.mesh))(
            sharded, toks)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-4)


def test_train_step_4d_loss_decreases():
    hm = init_hybrid_mesh(dp=2, pp=2, tp=2, set_global=False)
    cfg = _cfg(pp_stages=2, num_microbatches=2)
    with hm.mesh:
        step, init = L.make_train_step(cfg, hm.mesh)
        state = init(jax.random.PRNGKey(0))
        batch = L.make_batch(cfg, batch_size=4, seq_len=16, mesh=hm.mesh)
        losses = []
        for _ in range(5):
            state, loss = step(state, batch)
            losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert int(state["step"]) == 5


def test_gqa_attention_matches_mha_expansion():
    cfg = _cfg()
    B, T, H, Dh = 2, 8, cfg.num_attention_heads, cfg.head_dim
    Hkv = cfg.num_key_value_heads
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (B, T, H, Dh))
    k = jax.random.normal(k2, (B, T, Hkv, Dh))
    v = jax.random.normal(k3, (B, T, Hkv, Dh))
    out = L.attention(q, k, v, cfg)
    # manual expansion
    kk = jnp.repeat(k, H // Hkv, axis=2)
    vv = jnp.repeat(v, H // Hkv, axis=2)
    ref = L.attention(q, kk, vv, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_flash_attention_fallback_matches_dense():
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    cfg = _cfg()
    B, T, H, Dh = 2, 16, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, T, H, Dh)) for kk in ks)
    out = flash_attention(q, k, v, causal=True)
    ref = L.attention(q, k, v, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_lenet_train_step():
    import paddle_tpu as pt
    from paddle_tpu.models import LeNet
    m = LeNet()
    opt = pt.optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
    x = pt.to_tensor(np.random.randn(8, 1, 28, 28).astype(np.float32))
    y = pt.to_tensor(np.random.randint(0, 10, (8,)))
    losses = []
    for _ in range(5):
        logits = m(x)
        loss = pt.nn.functional.cross_entropy(logits, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]

"""Higher-order autograd: create_graph double backward + functional
jacobian/hessian/jvp/vjp (autograd/tape.py, autograd/functional.py).

Reference capability: paddle.grad(create_graph=True) (GeneralGrad,
paddle/fluid/eager/backward.cc) and python/paddle/autograd functional
transforms.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import autograd as AG


def test_double_backward_cubic():
    x = pt.to_tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
    y = (x * x * x).sum()  # y = sum(x^3)
    (g1,) = AG.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(g1.numpy(), 3 * np.array([4.0, 9.0]),
                               rtol=1e-6)
    g1sum = g1.sum()
    (g2,) = AG.grad(g1sum, [x])
    np.testing.assert_allclose(g2.numpy(), 6 * np.array([2.0, 3.0]),
                               rtol=1e-6)


def test_double_backward_via_backward():
    x = pt.to_tensor(np.array(1.5, np.float32), stop_gradient=False)
    y = x * x * x * x  # x^4
    AG.backward(y, create_graph=True)
    g1 = x.grad  # 4x^3, carries graph
    x.clear_grad()
    AG.backward(g1.sum())
    # d(4x^3)/dx = 12 x^2
    np.testing.assert_allclose(x.grad.numpy(), 12 * 1.5 ** 2, rtol=1e-6)


def test_third_order():
    x = pt.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = x * x * x * x          # x^4
    (g1,) = AG.grad(y, [x], create_graph=True)     # 4x^3
    (g2,) = AG.grad(g1, [x], create_graph=True)    # 12x^2
    (g3,) = AG.grad(g2, [x])                       # 24x
    np.testing.assert_allclose(g3.numpy(), 48.0, rtol=1e-6)


def test_mixed_partials():
    x = pt.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = pt.to_tensor(np.array(5.0, np.float32), stop_gradient=False)
    z = x * x * y
    (gx,) = AG.grad(z, [x], create_graph=True)     # 2xy
    (gxy,) = AG.grad(gx, [y])                      # d(2xy)/dy = 2x
    np.testing.assert_allclose(gxy.numpy(), 4.0, rtol=1e-6)


def test_jacobian_matches_closed_form():
    def f(x):
        return x * x * 3.0

    x = pt.to_tensor(np.array([1.0, 2.0, -1.0], np.float32))
    J = AG.jacobian(f, x)
    np.testing.assert_allclose(J.numpy(),
                               np.diag(6 * np.array([1.0, 2.0, -1.0])),
                               rtol=1e-6)


def test_jacobian_numeric_check():
    def f(x):
        return pt.tanh(x).sum() * pt.exp(x * 0.1).sum()

    x0 = np.array([0.3, -0.7, 1.2], np.float32)
    J = AG.jacobian(f, pt.to_tensor(x0)).numpy()
    eps = 1e-3
    for i in range(3):
        xp, xm = x0.copy(), x0.copy()
        xp[i] += eps
        xm[i] -= eps
        fd = (float(f(pt.to_tensor(xp)).numpy())
              - float(f(pt.to_tensor(xm)).numpy())) / (2 * eps)
        np.testing.assert_allclose(J[i], fd, rtol=2e-3, atol=2e-3)


def test_hessian_symmetric_and_correct():
    def f(x):
        return (x[0] ** 2) * x[1] + x[1] ** 3

    x0 = np.array([1.0, 2.0], np.float32)
    H = AG.hessian(f, pt.to_tensor(x0)).numpy()
    want = np.array([[2 * 2.0, 2 * 1.0], [2 * 1.0, 6 * 2.0]])
    np.testing.assert_allclose(H, want, rtol=1e-5)
    np.testing.assert_allclose(H, H.T, rtol=1e-6)


def test_jvp_vjp_consistency():
    def f(x):
        return x * x

    x = pt.to_tensor(np.array([1.0, 4.0], np.float32))
    v = pt.to_tensor(np.array([1.0, 0.5], np.float32))
    out, tangent = AG.jvp(f, x, v)
    np.testing.assert_allclose(tangent.numpy(), [2.0, 4.0], rtol=1e-6)
    out2, grads = AG.vjp(f, x, v)
    np.testing.assert_allclose(grads.numpy(), [2.0, 4.0], rtol=1e-6)
    np.testing.assert_allclose(out.numpy(), out2.numpy())


def test_create_graph_through_pylayer_raises():
    class Double(AG.PyLayer):
        @staticmethod
        def forward(ctx, x):
            return x * 2

        @staticmethod
        def backward(ctx, g):
            return g * 2

    x = pt.to_tensor(np.array(3.0, np.float32), stop_gradient=False)
    y = Double.apply(x)
    with pytest.raises(NotImplementedError, match="PyLayer|forward closure"):
        AG.grad(y, [x], create_graph=True)

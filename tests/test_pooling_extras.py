"""ceil_mode / return_mask / pad edge cases (validated against torch CPU,
mirroring the reference's OpTest numeric-vs-reference pattern,
test/legacy_test/op_test.py check_output)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


@pytest.fixture
def x():
    rng = np.random.RandomState(7)
    return rng.randn(2, 3, 7, 7).astype(np.float32)


def test_max_pool2d_ceil_mode(x):
    ref = TF.max_pool2d(torch.tensor(x), 3, 2, padding=0, ceil_mode=True)
    out = F.max_pool2d(pt.to_tensor(x), 3, 2, padding=0, ceil_mode=True)
    np.testing.assert_allclose(ref.numpy(), out.numpy())


def test_avg_pool2d_ceil_exclusive(x):
    ref = TF.avg_pool2d(torch.tensor(x), 3, 2, padding=1, ceil_mode=True,
                        count_include_pad=False)
    out = F.avg_pool2d(pt.to_tensor(x), 3, 2, padding=1, ceil_mode=True,
                       exclusive=True)
    np.testing.assert_allclose(ref.numpy(), out.numpy(), rtol=1e-6)


def test_max_pool2d_return_mask(x):
    for k, s, p in [(2, 2, 0), (3, 2, 1), (3, 1, 1)]:
        ref, refidx = TF.max_pool2d(torch.tensor(x), k, s, padding=p,
                                    return_indices=True)
        out, mask = F.max_pool2d(pt.to_tensor(x), k, s, padding=p,
                                 return_mask=True)
        np.testing.assert_allclose(ref.numpy(), out.numpy())
        np.testing.assert_array_equal(refidx.numpy(), mask.numpy())


def test_max_pool1d_return_mask(x):
    ref, refidx = TF.max_pool1d(torch.tensor(x[:, :, 0]), 2, 2,
                                return_indices=True)
    out, mask = F.max_pool1d(pt.to_tensor(x[:, :, 0]), 2, 2,
                             return_mask=True)
    np.testing.assert_allclose(ref.numpy(), out.numpy())
    np.testing.assert_array_equal(refidx.numpy(), mask.numpy())


def test_pad_validation():
    z = pt.to_tensor(np.zeros((2, 3), "float32"))
    with pytest.raises(ValueError):
        pt.pad(z, [1, 2, 3])
    with pytest.raises(ValueError):
        pt.pad(z, [1, 1, 1, 1, 1, 1])  # 3 pairs on 2-D input
    assert pt.pad(z, [1, 2]).shape == [2, 6]


def test_pad_from_left_axis():
    z = pt.to_tensor(np.zeros((2, 3), "float32"))
    assert pt.pad(z, [1, 1, 0, 0], pad_from_left_axis=True).shape == [4, 3]
    assert pt.pad(z, [1, 1, 0, 0], pad_from_left_axis=False).shape == [2, 5]

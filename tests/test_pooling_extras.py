"""ceil_mode / return_mask / pad edge cases (validated against torch CPU,
mirroring the reference's OpTest numeric-vs-reference pattern,
test/legacy_test/op_test.py check_output)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


@pytest.fixture
def x():
    rng = np.random.RandomState(7)
    return rng.randn(2, 3, 7, 7).astype(np.float32)


def test_max_pool2d_ceil_mode(x):
    ref = TF.max_pool2d(torch.tensor(x), 3, 2, padding=0, ceil_mode=True)
    out = F.max_pool2d(pt.to_tensor(x), 3, 2, padding=0, ceil_mode=True)
    np.testing.assert_allclose(ref.numpy(), out.numpy())


def test_avg_pool2d_ceil_exclusive(x):
    ref = TF.avg_pool2d(torch.tensor(x), 3, 2, padding=1, ceil_mode=True,
                        count_include_pad=False)
    out = F.avg_pool2d(pt.to_tensor(x), 3, 2, padding=1, ceil_mode=True,
                       exclusive=True)
    np.testing.assert_allclose(ref.numpy(), out.numpy(), rtol=1e-6)


def test_max_pool2d_return_mask(x):
    for k, s, p in [(2, 2, 0), (3, 2, 1), (3, 1, 1)]:
        ref, refidx = TF.max_pool2d(torch.tensor(x), k, s, padding=p,
                                    return_indices=True)
        out, mask = F.max_pool2d(pt.to_tensor(x), k, s, padding=p,
                                 return_mask=True)
        np.testing.assert_allclose(ref.numpy(), out.numpy())
        np.testing.assert_array_equal(refidx.numpy(), mask.numpy())


def test_max_pool1d_return_mask(x):
    ref, refidx = TF.max_pool1d(torch.tensor(x[:, :, 0]), 2, 2,
                                return_indices=True)
    out, mask = F.max_pool1d(pt.to_tensor(x[:, :, 0]), 2, 2,
                             return_mask=True)
    np.testing.assert_allclose(ref.numpy(), out.numpy())
    np.testing.assert_array_equal(refidx.numpy(), mask.numpy())


def test_max_pool2d_return_mask_string_padding(x):
    # VALID == explicit 0 padding; indices must match the explicit path
    ref, refidx = TF.max_pool2d(torch.tensor(x), 3, 2, padding=0,
                                return_indices=True)
    out, mask = F.max_pool2d(pt.to_tensor(x), 3, 2, padding="VALID",
                             return_mask=True)
    np.testing.assert_allclose(ref.numpy(), out.numpy())
    np.testing.assert_array_equal(refidx.numpy(), mask.numpy())
    # SAME: just consistency — mask indices must point at the max values
    out_s, mask_s = F.max_pool2d(pt.to_tensor(x), 2, 2, padding="SAME",
                                 return_mask=True)
    flat = x.reshape(2, 3, -1)
    picked = np.take_along_axis(flat, mask_s.numpy().reshape(2, 3, -1),
                                axis=2).reshape(out_s.shape)
    np.testing.assert_allclose(picked, out_s.numpy())


def test_max_pool2d_return_mask_nhwc(x):
    xh = np.transpose(x, (0, 2, 3, 1)).copy()
    ref, refidx = TF.max_pool2d(torch.tensor(x), 2, 2, return_indices=True)
    out, mask = F.max_pool2d(pt.to_tensor(xh), 2, 2, return_mask=True,
                             data_format="NHWC")
    np.testing.assert_allclose(np.transpose(ref.numpy(), (0, 2, 3, 1)),
                               out.numpy())
    np.testing.assert_array_equal(np.transpose(refidx.numpy(), (0, 2, 3, 1)),
                                  mask.numpy())


def test_pixel_unshuffle_nhwc_inverts_shuffle():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 8, 6, 4).astype(np.float32)  # NHWC, c=4, r=2
    shuf = F.pixel_shuffle(pt.to_tensor(x), 2, data_format="NHWC")
    back = F.pixel_unshuffle(shuf, 2, data_format="NHWC")
    np.testing.assert_allclose(back.numpy(), x)
    # and unshuffle matches the NCHW formulation through transposes
    un = F.pixel_unshuffle(pt.to_tensor(x), 2, data_format="NHWC")
    un_ref = F.pixel_unshuffle(
        pt.to_tensor(np.transpose(x, (0, 3, 1, 2)).copy()), 2)
    assert un.shape == [2, 4, 3, 16]
    assert un_ref.shape == [2, 16, 4, 3]


def test_spectral_norm_layer():
    import paddle_tpu.nn as nn
    rng = np.random.RandomState(0)
    w = rng.randn(6, 4).astype(np.float32)
    sn = nn.SpectralNorm([6, 4], dim=0, power_iters=20)
    out = sn(pt.to_tensor(w))
    # after enough power iterations the top singular value is ~1
    s = np.linalg.svd(out.numpy(), compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=1e-4)
    # direction preserved: out is w / sigma
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    np.testing.assert_allclose(out.numpy(), w / sigma, rtol=1e-3)


def test_spectral_norm_grad_flows_to_weight():
    import paddle_tpu.nn as nn
    rng = np.random.RandomState(1)
    w = pt.to_tensor(rng.randn(4, 3).astype(np.float32),
                     stop_gradient=False)
    sn = nn.SpectralNorm([4, 3], dim=0, power_iters=8)
    y = sn(w)
    y.sum().backward()
    assert w.grad is not None
    assert np.isfinite(w.grad.numpy()).all()
    assert np.abs(w.grad.numpy()).max() > 0


def test_pad_validation():
    z = pt.to_tensor(np.zeros((2, 3), "float32"))
    with pytest.raises(ValueError):
        pt.pad(z, [1, 2, 3])
    with pytest.raises(ValueError):
        pt.pad(z, [1, 1, 1, 1, 1, 1])  # 3 pairs on 2-D input
    assert pt.pad(z, [1, 2]).shape == [2, 6]


def test_pad_from_left_axis():
    z = pt.to_tensor(np.zeros((2, 3), "float32"))
    assert pt.pad(z, [1, 1, 0, 0], pad_from_left_axis=True).shape == [4, 3]
    assert pt.pad(z, [1, 1, 0, 0], pad_from_left_axis=False).shape == [2, 5]

"""KV-cache decode path (models/llama.py forward_with_cache/generate,
inference.GenerationPredictor).

Reference capability: fused decode attention + generation
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu,
masked_multihead_attention_kernel.cu behind paddle.inference).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L


CFG = L.LlamaConfig.tiny(dtype=jnp.float32, use_flash_attention=False,
                         remat=False)


@pytest.fixture(scope="module")
def params():
    return L.init_params(CFG, jax.random.PRNGKey(0))


def test_prefill_logits_match_full_forward(params):
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                              CFG.vocab_size)
    cache = L.init_kv_cache(CFG, 2, 16)
    logits, cache2 = L.forward_with_cache(params, toks, cache, 0, CFG)
    full = L.forward(params, toks, CFG)[:, -1].astype(jnp.float32)
    np.testing.assert_allclose(logits, full, rtol=2e-4, atol=2e-4)
    # cache holds the prompt K/V
    assert not np.allclose(np.asarray(cache2["k"][:, :, :12]), 0)
    assert np.allclose(np.asarray(cache2["k"][:, :, 12:]), 0)


def test_decode_step_matches_full_forward(params):
    """Incremental decode at position T must equal the last-position
    logits of a full forward over the T+1 tokens."""
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0,
                              CFG.vocab_size)
    cache = L.init_kv_cache(CFG, 2, 16)
    _, cache = L.forward_with_cache(params, toks[:, :8], cache, 0, CFG)
    step_logits, _ = L.forward_with_cache(
        params, toks[:, 8:9], cache, jnp.int32(8), CFG)
    full = L.forward(params, toks, CFG)[:, -1].astype(jnp.float32)
    np.testing.assert_allclose(step_logits, full, rtol=2e-4, atol=2e-4)


def test_greedy_generate_matches_stepwise_full_forward(params):
    """The whole point: cached greedy decode == argmax chain of full
    (uncached) forwards."""
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0,
                                CFG.vocab_size)
    out = L.generate(params, prompt, CFG, max_new_tokens=6)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(out[:, :5], prompt)

    seq = prompt
    for _ in range(6):
        logits = L.forward(params, seq, CFG)[:, -1]
        nxt = jnp.argmax(logits.astype(jnp.float32), axis=-1)
        seq = jnp.concatenate([seq, nxt[:, None].astype(jnp.int32)], 1)
    np.testing.assert_array_equal(out, seq)


def test_generate_eos_padding(params):
    prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 4), 0,
                                CFG.vocab_size)
    out = L.generate(params, prompt, CFG, max_new_tokens=8)
    eos = int(out[0, 4])  # force EOS = the first generated token
    out2 = L.generate(params, prompt, CFG, max_new_tokens=8,
                      eos_token_id=eos)
    assert np.all(np.asarray(out2[0, 4:]) == eos)


def test_sampling_valid_and_greedy_limit(params):
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 4), 0,
                                CFG.vocab_size)
    out = L.generate(params, prompt, CFG, max_new_tokens=5,
                     temperature=0.8, top_p=0.9, top_k=16,
                     key=jax.random.PRNGKey(7))
    a = np.asarray(out[:, 4:])
    assert a.min() >= 0 and a.max() < CFG.vocab_size
    # temperature 0 through the sampling path == greedy
    g1 = L.generate(params, prompt, CFG, max_new_tokens=5, temperature=0.0)
    g2 = L.generate(params, prompt, CFG, max_new_tokens=5)
    np.testing.assert_array_equal(g1, g2)


def test_top_p_zero_degrades_to_greedy():
    """top_p=0 must keep the top token, not disable filtering."""
    logits = jnp.array([[1.0, 2.0, 3.0, 0.5]])
    for seed in range(8):
        tok = L.sample_logits(logits, jax.random.PRNGKey(seed),
                              temperature=1.0, top_p=0.0)
        assert int(tok[0]) == 2


def test_generation_predictor(params):
    from paddle_tpu.inference import GenerationPredictor
    pred = GenerationPredictor(params, CFG, max_len=32)
    prompt = np.array([[1, 2, 3]], np.int32)
    out = pred.generate(prompt, max_new_tokens=4)
    ref = L.generate(params, jnp.asarray(prompt), CFG, max_new_tokens=4)
    np.testing.assert_array_equal(out, np.asarray(ref))
    with pytest.raises(ValueError, match="max_len"):
        pred.generate(np.zeros((1, 30), np.int32), max_new_tokens=4)

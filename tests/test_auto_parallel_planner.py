"""Auto-parallel planner (ISSUE 13, ROADMAP item 4): search, rank,
trace-verify.

The CI wiring the issue asks for: ``tools/auto_parallel.py --smoke``
runs as a subprocess (the real CLI entry, own 2x2 virtual mesh) and
its JSON is asserted — non-empty ranked plan, >= 20 legal
configurations, winner trace-verified under the planner contract.
Everything else is in-process: enumeration legality is pinned against
the schedule builder (the no-drift contract), and the contract pass is
MUTATION-tested — a corrupted HBM prediction and a corrupted tick
count must each fail verification (the vacuous-pass lesson: detection
is proven, not assumed). The xla_cost_analysis/xla_peak_bytes
normalizer coverage (satellite) lives here too: finite counters for a
compiled train step on the CPU backend, graceful degradation (empty
dict / None, never a crash) when a backend omits the introspection.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis import (PlanPoint, Severity,
                                 enumerate_plan_points,
                                 estimate_hbm_peak, verify_plan,
                                 xla_cost_analysis, xla_peak_bytes)
from paddle_tpu.analysis.planner import (point_config,
                                         reference_step_costs)
from paddle_tpu.analysis.training_graphs import build_train_target
from paddle_tpu.models import llama as L
from paddle_tpu.parallel.pipeline_1f1b import schedule_ticks
from paddle_tpu.parallel.pipeline_async import (SCHEDULE_INFO,
                                                build_schedule,
                                                schedule_legality)

REPO = os.path.join(os.path.dirname(__file__), "..")
CFG = L.LlamaConfig.tiny()


# ---------------------------------------------------------------------------
# the CLI smoke: one subprocess run, several assertions on its JSON
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def smoke_plan():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "auto_parallel.py"),
         "--smoke", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=420)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    return json.loads(proc.stdout)


def test_smoke_ranks_nonempty_plan(smoke_plan):
    out = smoke_plan
    assert out["schema"] == "paddle_tpu.auto_parallel_plan/1"
    assert out["legal"] >= 20, "flagship smoke space collapsed"
    assert out["priced"] >= 20
    assert out["plans"], "ranked plan is empty"
    # ranking is by the step-time proxy among fitting plans
    times = [p["cost"]["step_time_proxy_s"] for p in out["plans"]]
    assert times == sorted(times)
    assert all(p["cost"]["fits"] for p in out["plans"])
    # the pruned space is auditable: the current dp=tp=1 restriction
    # on the async schedules must show up as a counted reason
    assert any("1f1b_async" in r for r in out["pruned"]), out["pruned"]


def test_smoke_winner_trace_verifies(smoke_plan):
    ver = smoke_plan["verification"]
    assert ver["ok"], ver
    d = ver["deltas"]
    # predicted HBM peak within the contract tolerance of the traced
    # HbmPeakPass estimate (the acceptance bar is ±15%)
    assert abs(d["hbm_rel_delta"]) <= ver["tolerance"] <= 0.15
    assert d["traced_hbm_peak_bytes"] > 0
    # deltas ride the shared Finding JSON schema
    findings = ver["report"]["findings"]
    assert any(f["pass"] == "planner-contract" for f in findings)
    assert {"pass", "severity", "graph", "message"} <= set(findings[0])
    # zero sharding/donation findings at error severity on the winner
    assert not [f for f in findings
                if f["severity"] == "error"
                and f["pass"] in ("sharding-lint", "donation-audit")]


# ---------------------------------------------------------------------------
# enumeration: legality matches the executors (no-drift contract)
# ---------------------------------------------------------------------------

def test_enumeration_points_are_legal():
    points, pruned = enumerate_plan_points(4, CFG, batch_size=16)
    assert len(points) >= 20
    for p in points:
        assert p.dp * p.tp * p.pp == 4
        assert CFG.num_hidden_layers % (p.pp * p.vpp) == 0
        assert 16 % p.microbatches == 0
        assert (16 // p.microbatches) % p.dp == 0
        if p.zero_stage >= 1:
            assert p.dp > 1
        if p.pp > 1:
            assert schedule_legality(
                p.schedule, num_stages=p.pp,
                num_microbatches=p.microbatches,
                virtual_chunks=p.vpp, dp=p.dp, tp=p.tp) is None
        else:
            assert (p.schedule, p.vpp, p.microbatches) == ("none", 1, 1)
    # the known-illegal classes are counted, not silently skipped
    assert pruned.get("zero-needs-dp>1")
    assert any(r.startswith("schedule[") for r in pruned)


def test_schedule_legality_matches_builder():
    """The queryable table and the builder must agree point for point —
    a constraint added to one without the other fails here."""
    for S in (2, 3, 4):
        for M in (1, 2, 4, 5, 8):
            for V in (1, 2):
                for name in ("1f1b_async", "zb"):
                    variant = SCHEDULE_INFO[name].executor
                    reason = schedule_legality(
                        name, num_stages=S, num_microbatches=M,
                        virtual_chunks=V)
                    try:
                        build_schedule(S, M, V, variant)
                        built = True
                    except ValueError:
                        built = False
                    assert built == (reason is None), (
                        f"{name} S={S} M={M} V={V}: builder "
                        f"{'accepts' if built else 'rejects'} but "
                        f"legality says {reason!r}")


def test_schedule_legality_dp_tp_restriction():
    assert schedule_legality("1f1b_async", num_stages=2,
                             num_microbatches=4, dp=2) is not None
    assert schedule_legality("zb", num_stages=2,
                             num_microbatches=4, tp=2) is not None
    assert schedule_legality("1f1b", num_stages=2,
                             num_microbatches=4, dp=2, tp=2) is None


# ---------------------------------------------------------------------------
# the planner contract is a real check: corrupted predictions fail
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pp_point_and_target():
    pt = PlanPoint(dp=1, tp=1, pp=2, vpp=1, microbatches=4,
                   schedule="1f1b", zero_stage=0, dtype="bfloat16")
    tgt = build_train_target(
        pt.geometry(), f"planner.winner[{pt.label()}]",
        batch_size=8, seq_len=8, cfg=point_config(CFG, pt))
    return pt, tgt


def _verify_with(pt, tgt, prediction):
    cache = {(pt, 8, 8): tgt}
    return verify_plan(pt, CFG, batch_size=8, seq_len=8,
                       hbm_budget_bytes=None, prediction=prediction,
                       trace_cache=cache)


def test_contract_accepts_honest_prediction(pp_point_and_target):
    pt, tgt = pp_point_and_target
    peak = estimate_hbm_peak(tgt).peak_bytes
    ticks = schedule_ticks(2, 4, 1, schedule="lockstep")
    ver = _verify_with(pt, tgt, {"hbm_peak_bytes": peak,
                                 "ticks": ticks})
    assert ver["ok"], ver["report"]
    assert ver["deltas"]["hbm_rel_delta"] == 0.0
    assert ver["deltas"]["predicted_ticks"] == ticks


def test_contract_catches_bad_hbm_prediction(pp_point_and_target):
    pt, tgt = pp_point_and_target
    peak = estimate_hbm_peak(tgt).peak_bytes
    ver = _verify_with(pt, tgt, {"hbm_peak_bytes": 2 * peak})
    assert not ver["ok"]
    errs = [f for f in ver["report"]["findings"]
            if f["severity"] == Severity.ERROR
            and f["pass"] == "planner-contract"]
    assert errs and "untrustworthy" in errs[0]["message"]


def test_contract_catches_bad_tick_prediction(pp_point_and_target):
    pt, tgt = pp_point_and_target
    peak = estimate_hbm_peak(tgt).peak_bytes
    ticks = schedule_ticks(2, 4, 1, schedule="lockstep")
    ver = _verify_with(pt, tgt, {"hbm_peak_bytes": peak,
                                 "ticks": ticks + 3})
    assert not ver["ok"]
    assert any("not the schedule that runs" in f["message"]
               for f in ver["report"]["findings"])


# ---------------------------------------------------------------------------
# xla_cost_analysis / xla_peak_bytes coverage (satellite)
# ---------------------------------------------------------------------------

def test_xla_cost_analysis_finite_for_jitted_train_step():
    """The CPU backend exposes the counters the step-time proxy reads:
    finite positive flops/bytes for a compiled tiny train step."""
    ref = reference_step_costs(CFG, "bfloat16", seq_len=8)
    assert ref["source"] == "xla_cost_analysis"
    assert np.isfinite(ref["flops_per_row"]) and ref["flops_per_row"] > 0
    assert np.isfinite(ref["bytes_per_row"]) and ref["bytes_per_row"] > 0


def test_xla_cost_analysis_normalizes_versions_and_degrades():
    """List-of-dicts (current jax), plain dict (older), None, and a
    raising backend all normalize without version branches — and
    without crashing (the degrade-to-None satellite)."""
    class ListCA:
        def cost_analysis(self):
            return [{"flops": 7.0}]

    class DictCA:
        def cost_analysis(self):
            return {"flops": 7.0}

    class NoneCA:
        def cost_analysis(self):
            return None

    class RaisingCA:
        def cost_analysis(self):
            raise NotImplementedError("backend omits cost analysis")

    assert xla_cost_analysis(ListCA()) == {"flops": 7.0}
    assert xla_cost_analysis(DictCA()) == {"flops": 7.0}
    assert xla_cost_analysis(NoneCA()) == {}
    assert xla_cost_analysis(RaisingCA()) == {}
    assert xla_cost_analysis(object()) == {}  # no method at all


def test_xla_peak_bytes_real_and_degraded():
    c = jax.jit(lambda x: (x @ x).sum()).lower(
        jnp.zeros((64, 64), jnp.float32)).compile()
    pb = xla_peak_bytes(c)
    assert pb is None or pb > 0  # CPU exposes it on current jax

    class NoMA:
        def memory_analysis(self):
            raise NotImplementedError

    class PartialMA:
        # backend returns an object missing the size fields
        def memory_analysis(self):
            return object()

    assert xla_peak_bytes(NoMA()) is None
    assert xla_peak_bytes(PartialMA()) is None
    assert xla_peak_bytes(object()) is None


def test_reference_costs_analytic_fallback():
    """A dtype whose compile path dies degrades to the closed-form
    transformer estimate instead of crashing the whole plan."""
    import paddle_tpu.analysis.planner as P
    from paddle_tpu.analysis import hbm as H
    real = H.xla_cost_analysis
    try:
        H.xla_cost_analysis = lambda compiled: {}
        ref = P.reference_step_costs(CFG, "bfloat16", seq_len=8)
    finally:
        H.xla_cost_analysis = real
    assert ref["source"] == "analytic-fallback"
    assert np.isfinite(ref["flops_per_row"]) and ref["flops_per_row"] > 0
    assert np.isfinite(ref["bytes_per_row"]) and ref["bytes_per_row"] > 0

"""Auto-parallel planner (ISSUE 13, ROADMAP item 4): search, rank,
trace-verify.

The CI wiring the issue asks for: ``tools/auto_parallel.py --smoke``
runs as a subprocess (the real CLI entry, own 2x2 virtual mesh) and
its JSON is asserted — non-empty ranked plan, >= 20 legal
configurations, winner trace-verified under the planner contract.
Everything else is in-process: enumeration legality is pinned against
the schedule builder (the no-drift contract), and the contract pass is
MUTATION-tested — a corrupted HBM prediction and a corrupted tick
count must each fail verification (the vacuous-pass lesson: detection
is proven, not assumed). The xla_cost_analysis/xla_peak_bytes
normalizer coverage (satellite) lives here too: finite counters for a
compiled train step on the CPU backend, graceful degradation (empty
dict / None, never a crash) when a backend omits the introspection.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis import (PlanPoint, Severity,
                                 enumerate_plan_points,
                                 estimate_hbm_peak, verify_plan,
                                 xla_cost_analysis, xla_peak_bytes)
from paddle_tpu.analysis.planner import (point_config,
                                         reference_step_costs)
from paddle_tpu.analysis.training_graphs import build_train_target
from paddle_tpu.models import llama as L
from paddle_tpu.parallel.pipeline_1f1b import schedule_ticks
from paddle_tpu.parallel.pipeline_async import (SCHEDULE_INFO,
                                                build_schedule,
                                                schedule_legality)

REPO = os.path.join(os.path.dirname(__file__), "..")
CFG = L.LlamaConfig.tiny()


# ---------------------------------------------------------------------------
# the CLI smoke: one subprocess run, several assertions on its JSON
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def smoke_plan():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "auto_parallel.py"),
         "--smoke", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=420)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    return json.loads(proc.stdout)


def test_smoke_ranks_nonempty_plan(smoke_plan):
    out = smoke_plan
    assert out["schema"] == "paddle_tpu.auto_parallel_plan/1"
    # r19 lifted the dp=tp=1 restriction on the async schedules, so
    # the same smoke space grew from 32 legal points to >= 50 (58 at
    # the r19 flagship run) — and NO pruned reason may mention the
    # old mesh-axis restriction anymore
    assert out["legal"] >= 50, "composed smoke space collapsed"
    assert out["priced"] >= 50
    assert out["plans"], "ranked plan is empty"
    # ranking is by the step-time proxy among fitting plans
    times = [p["cost"]["step_time_proxy_s"] for p in out["plans"]]
    assert times == sorted(times)
    assert all(p["cost"]["fits"] for p in out["plans"])
    assert not any("non-pp mesh axis" in r or "dp=" in r
                   for r in out["pruned"]), out["pruned"]


def test_smoke_winner_trace_verifies(smoke_plan):
    ver = smoke_plan["verification"]
    assert ver["ok"], ver
    d = ver["deltas"]
    # predicted HBM peak within the contract tolerance of the traced
    # HbmPeakPass estimate (the acceptance bar is ±15%)
    assert abs(d["hbm_rel_delta"]) <= ver["tolerance"] <= 0.15
    assert d["traced_hbm_peak_bytes"] > 0
    # deltas ride the shared Finding JSON schema
    findings = ver["report"]["findings"]
    assert any(f["pass"] == "planner-contract" for f in findings)
    assert {"pass", "severity", "graph", "message"} <= set(findings[0])
    # zero sharding/donation findings at error severity on the winner
    assert not [f for f in findings
                if f["severity"] == "error"
                and f["pass"] in ("sharding-lint", "donation-audit")]


# ---------------------------------------------------------------------------
# enumeration: legality matches the executors (no-drift contract)
# ---------------------------------------------------------------------------

def test_enumeration_points_are_legal():
    points, pruned = enumerate_plan_points(4, CFG, batch_size=16)
    assert len(points) >= 20
    for p in points:
        assert p.dp * p.tp * p.pp == 4
        assert CFG.num_hidden_layers % (p.pp * p.vpp) == 0
        assert 16 % p.microbatches == 0
        assert (16 // p.microbatches) % p.dp == 0
        if p.zero_stage >= 1:
            assert p.dp > 1
        if p.pp > 1:
            assert schedule_legality(
                p.schedule, num_stages=p.pp,
                num_microbatches=p.microbatches,
                virtual_chunks=p.vpp, dp=p.dp, tp=p.tp) is None
        else:
            assert (p.schedule, p.vpp, p.microbatches) == ("none", 1, 1)
    # the known-illegal classes are counted, not silently skipped
    assert pruned.get("zero-needs-dp>1")
    assert any(r.startswith("schedule[") for r in pruned)


def test_schedule_legality_matches_builder():
    """The queryable table and the builder must agree point for point —
    a constraint added to one without the other fails here."""
    for S in (2, 3, 4):
        for M in (1, 2, 4, 5, 8):
            for V in (1, 2):
                for name in ("1f1b_async", "zb"):
                    variant = SCHEDULE_INFO[name].executor
                    reason = schedule_legality(
                        name, num_stages=S, num_microbatches=M,
                        virtual_chunks=V)
                    try:
                        build_schedule(S, M, V, variant)
                        built = True
                    except ValueError:
                        built = False
                    assert built == (reason is None), (
                        f"{name} S={S} M={M} V={V}: builder "
                        f"{'accepts' if built else 'rejects'} but "
                        f"legality says {reason!r}")


def test_schedule_legality_composed_dp_tp_legal():
    """r19: the async schedules compose dp/tp — the legality table
    must accept every (dp, tp) for every schedule (the executors run
    them; model-level divisibility is the mesh-level prune)."""
    for name in SCHEDULE_INFO:
        if SCHEDULE_INFO[name].min_stages > 1:
            assert schedule_legality(name, num_stages=2,
                                     num_microbatches=4, dp=2,
                                     tp=2) is None, name
        assert not SCHEDULE_INFO[name].requires_dp1_tp1, name


def test_enumeration_composes_async_points_at_devices_8():
    """The acceptance pin: the widened search space contains composed
    (dp·tp > 1) async-schedule points at the flagship devices=8 run —
    the 4D north star can now ride the best schedules."""
    points, pruned = enumerate_plan_points(8, CFG, batch_size=64)
    composed = [p for p in points
                if p.dp * p.tp > 1 and p.pp > 1
                and SCHEDULE_INFO[p.schedule].executor is not None]
    assert composed, "no composed async points enumerated"
    # both axes individually and the full 3D mesh appear
    assert any(p.dp > 1 and p.schedule == "zb" for p in composed)
    assert any(p.tp > 1 and p.schedule == "1f1b_async"
               for p in composed)
    assert any(p.dp > 1 and p.tp > 1 for p in composed)
    # the zb work factor the planner prices reflects the residual-ring
    # recompute cut (r14's 5/4 -> r19's 4.5/4)
    assert SCHEDULE_INFO["zb"].work_units_per_mb_stage == 4.5


def test_composed_async_point_prices_and_verifies():
    """A composed (dp>1) zb point prices with its in-body collectives
    TRACED (collective_bytes > 0 — the folded dp grad psum and the
    ppermute pairs; the analytic dp term is skipped for async points
    so nothing double-counts), carries the 4.5/4 residual-ring work
    factor, and trace-VERIFIES through the full registered pass stack
    under the planner contract — the r19 acceptance loop in one
    point."""
    from paddle_tpu.analysis.planner import price_plan_point
    pt = PlanPoint(dp=2, tp=1, pp=2, vpp=1, microbatches=4,
                   schedule="zb", zero_stage=0, dtype="bfloat16")
    ref = {"bfloat16": reference_step_costs(CFG, "bfloat16",
                                            seq_len=8)}
    cache = {}
    cost = price_plan_point(pt, CFG, batch_size=8, seq_len=8,
                            hbm_budget_bytes=None, ref_costs=ref,
                            trace_cache=cache)
    assert cost.collective_bytes > 0
    assert cost.work_multiplier == pytest.approx(4.5 / 4)
    ver = verify_plan(pt, CFG, batch_size=8, seq_len=8,
                      hbm_budget_bytes=None,
                      prediction=dict(cost.to_dict(),
                                      point=pt.to_dict()),
                      trace_cache=cache)
    assert ver["ok"], ver["report"]
    assert abs(ver["deltas"]["hbm_rel_delta"]) <= ver["tolerance"]


# ---------------------------------------------------------------------------
# the planner contract is a real check: corrupted predictions fail
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pp_point_and_target():
    pt = PlanPoint(dp=1, tp=1, pp=2, vpp=1, microbatches=4,
                   schedule="1f1b", zero_stage=0, dtype="bfloat16")
    tgt = build_train_target(
        pt.geometry(), f"planner.winner[{pt.label()}]",
        batch_size=8, seq_len=8, cfg=point_config(CFG, pt))
    return pt, tgt


def _verify_with(pt, tgt, prediction):
    cache = {(pt, 8, 8): tgt}
    return verify_plan(pt, CFG, batch_size=8, seq_len=8,
                       hbm_budget_bytes=None, prediction=prediction,
                       trace_cache=cache)


def test_contract_accepts_honest_prediction(pp_point_and_target):
    pt, tgt = pp_point_and_target
    peak = estimate_hbm_peak(tgt).peak_bytes
    ticks = schedule_ticks(2, 4, 1, schedule="lockstep")
    ver = _verify_with(pt, tgt, {"hbm_peak_bytes": peak,
                                 "ticks": ticks})
    assert ver["ok"], ver["report"]
    assert ver["deltas"]["hbm_rel_delta"] == 0.0
    assert ver["deltas"]["predicted_ticks"] == ticks


def test_contract_catches_bad_hbm_prediction(pp_point_and_target):
    pt, tgt = pp_point_and_target
    peak = estimate_hbm_peak(tgt).peak_bytes
    ver = _verify_with(pt, tgt, {"hbm_peak_bytes": 2 * peak})
    assert not ver["ok"]
    errs = [f for f in ver["report"]["findings"]
            if f["severity"] == Severity.ERROR
            and f["pass"] == "planner-contract"]
    assert errs and "untrustworthy" in errs[0]["message"]


def test_contract_catches_bad_tick_prediction(pp_point_and_target):
    pt, tgt = pp_point_and_target
    peak = estimate_hbm_peak(tgt).peak_bytes
    ticks = schedule_ticks(2, 4, 1, schedule="lockstep")
    ver = _verify_with(pt, tgt, {"hbm_peak_bytes": peak,
                                 "ticks": ticks + 3})
    assert not ver["ok"]
    assert any("not the schedule that runs" in f["message"]
               for f in ver["report"]["findings"])


# ---------------------------------------------------------------------------
# xla_cost_analysis / xla_peak_bytes coverage (satellite)
# ---------------------------------------------------------------------------

def test_xla_cost_analysis_finite_for_jitted_train_step():
    """The CPU backend exposes the counters the step-time proxy reads:
    finite positive flops/bytes for a compiled tiny train step."""
    ref = reference_step_costs(CFG, "bfloat16", seq_len=8)
    assert ref["source"] == "xla_cost_analysis"
    assert np.isfinite(ref["flops_per_row"]) and ref["flops_per_row"] > 0
    assert np.isfinite(ref["bytes_per_row"]) and ref["bytes_per_row"] > 0


def test_xla_cost_analysis_normalizes_versions_and_degrades():
    """List-of-dicts (current jax), plain dict (older), None, and a
    raising backend all normalize without version branches — and
    without crashing (the degrade-to-None satellite)."""
    class ListCA:
        def cost_analysis(self):
            return [{"flops": 7.0}]

    class DictCA:
        def cost_analysis(self):
            return {"flops": 7.0}

    class NoneCA:
        def cost_analysis(self):
            return None

    class RaisingCA:
        def cost_analysis(self):
            raise NotImplementedError("backend omits cost analysis")

    assert xla_cost_analysis(ListCA()) == {"flops": 7.0}
    assert xla_cost_analysis(DictCA()) == {"flops": 7.0}
    assert xla_cost_analysis(NoneCA()) == {}
    assert xla_cost_analysis(RaisingCA()) == {}
    assert xla_cost_analysis(object()) == {}  # no method at all


def test_xla_peak_bytes_real_and_degraded():
    c = jax.jit(lambda x: (x @ x).sum()).lower(
        jnp.zeros((64, 64), jnp.float32)).compile()
    pb = xla_peak_bytes(c)
    assert pb is None or pb > 0  # CPU exposes it on current jax

    class NoMA:
        def memory_analysis(self):
            raise NotImplementedError

    class PartialMA:
        # backend returns an object missing the size fields
        def memory_analysis(self):
            return object()

    assert xla_peak_bytes(NoMA()) is None
    assert xla_peak_bytes(PartialMA()) is None
    assert xla_peak_bytes(object()) is None


def test_reference_costs_analytic_fallback():
    """A dtype whose compile path dies degrades to the closed-form
    transformer estimate instead of crashing the whole plan."""
    import paddle_tpu.analysis.planner as P
    from paddle_tpu.analysis import hbm as H
    real = H.xla_cost_analysis
    try:
        H.xla_cost_analysis = lambda compiled: {}
        ref = P.reference_step_costs(CFG, "bfloat16", seq_len=8)
    finally:
        H.xla_cost_analysis = real
    assert ref["source"] == "analytic-fallback"
    assert np.isfinite(ref["flops_per_row"]) and ref["flops_per_row"] > 0
    assert np.isfinite(ref["bytes_per_row"]) and ref["bytes_per_row"] > 0
